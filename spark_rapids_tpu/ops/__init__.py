"""Expression IR + kernels (reference: the 221 GpuOverrides.expr rules and
their Gpu* implementations, SURVEY.md §2.3/Appendix A).

Every expression implements three coordinated evaluation paths:

* ``eval_cpu``  — Spark-exact semantics over HostColumns (numpy). This is the
  CPU fallback substrate AND the test oracle (the reference compares against
  CPU Spark; we compare against this path).
* ``prep``      — a host-side pass over a DeviceTable that mirrors the
  string-dictionary dataflow: computes each node's output dictionary and
  emits per-batch auxiliary device inputs (dictionary remaps, per-entry
  hashes/lengths, literal codes). Aux arrays are padded to buckets so
  compiled programs are reused across batches.
* ``eval_dev``  — traced JAX evaluation; the whole tree is fused into a
  single jitted XLA computation per (schema, expr, bucket) by the compile
  cache (the cuDF-AST analog: SURVEY.md §2.9 ast.*).
"""

from spark_rapids_tpu.ops.expr import (  # noqa: F401
    Expression,
    BoundReference,
    Literal,
    Alias,
    AttributeReference,
    col,
    lit,
    bind,
    evaluate_cpu,
    compile_project,
)
