"""Query-service subsystem tests: concurrent scheduler + WFQ, admission
control (queue depth, memory pressure), lifecycle (cancellation,
deadlines), the plan-fingerprint result cache (hits, eviction,
invalidation on catalog mutation and table writes), semaphore metrics,
event-log service fields, the concurrent chaos slice, and the
`tools loadtest` CLI smoke."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.errors import (
    QueryCancelledError,
    QueryRejectedError,
    QueryTimeoutError,
    SemaphoreTimeoutError,
)
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.service import QueryService
from spark_rapids_tpu.service.query import QueryState

pytestmark = pytest.mark.service

#: every kernel dispatch sleeps 50ms — makes queries deterministically
#: slow (seconds across a multi-batch plan) so lifecycle races are
#: controllable without wall-clock guessing
_SLOW_FAULT = {"spark.rapids.test.faults": "dispatch.kernel:slow:1.0"}


@pytest.fixture(autouse=True)
def _clean_fault_state():
    from spark_rapids_tpu.runtime.faults import CIRCUIT_BREAKER, FAULTS
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()
    yield
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()


def _data(n=240):
    return {"k": np.array(["a", "b", "c", "d"] * (n // 4), dtype=object),
            "v": np.arange(n, dtype=np.int64)}


def _slow_query(svc, num_batches=24):
    """Multi-batch agg: with the slow-dispatch fault armed, each batch
    costs several 50ms sleeps, and the cancellation boundary runs
    between batches."""
    df = svc.session.create_dataframe(_data(), num_batches=num_batches)
    return (df.filter(col("v") >= lit(0))
            .group_by("k").agg(F.sum("v").alias("sv")))


def _fast_query(svc, tag=0):
    # one source DataFrame per session: the fingerprint keys source
    # tables by IDENTITY, so repeated submissions must share the table
    # (like the loadtest harness's shared `tables` dict)
    df = getattr(svc.session, "_test_src_df", None)
    if df is None:
        df = svc.session._test_src_df = svc.session.create_dataframe(
            _data())
    return (df.filter(col("v") > lit(tag))
            .group_by("k").agg(F.count("v").alias("c")))


def _wait_state(handle, state, timeout=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if handle.state == state or handle.done:
            return handle.state
        time.sleep(0.005)
    return handle.state


# ---------------------------------------------------------------------------
# lifecycle: cancellation + deadlines
# ---------------------------------------------------------------------------


def test_running_query_cancels_between_batches():
    with QueryService(dict(_SLOW_FAULT)) as svc:
        h = svc.submit(_slow_query(svc), tenant="a")
        assert _wait_state(h, QueryState.RUNNING) == QueryState.RUNNING
        t_cancel = time.monotonic()
        assert h.cancel()
        assert h.wait(timeout=30)
        assert h.state == QueryState.CANCELLED
        # cooperative: the interrupt landed at a batch boundary, not
        # after the full (many-seconds) plan drained
        assert time.monotonic() - t_cancel < 10.0
        with pytest.raises(QueryCancelledError):
            h.result(timeout=1)
        assert h.scope.checks > 0
        assert svc.counters["cancelled"] == 1


def test_queued_query_cancels_without_running():
    with QueryService(dict(_SLOW_FAULT), max_concurrent=1) as svc:
        blocker = svc.submit(_slow_query(svc))
        queued = svc.submit(_fast_query(svc))
        assert queued.cancel()
        assert queued.wait(timeout=10)
        assert queued.state == QueryState.CANCELLED
        assert queued.start_t is None  # never ran
        assert svc.counters["cancelled"] == 1
        blocker.cancel()


def test_running_deadline_times_out():
    with QueryService(dict(_SLOW_FAULT)) as svc:
        h = svc.submit(_slow_query(svc), timeout_ms=300)
        assert h.wait(timeout=30)
        assert h.state == QueryState.TIMED_OUT
        with pytest.raises(QueryTimeoutError):
            h.result(timeout=1)
        assert svc.counters["timed_out"] == 1


def test_queued_deadline_times_out_without_running():
    with QueryService(dict(_SLOW_FAULT), max_concurrent=1) as svc:
        blocker = svc.submit(_slow_query(svc))
        queued = svc.submit(_fast_query(svc), timeout_ms=100)
        t0 = time.monotonic()
        assert queued.wait(timeout=10)
        # the dedicated sweeper expires it ON TIME even though the only
        # worker is busy — not seconds later when the worker frees
        assert time.monotonic() - t0 < 2.0
        assert blocker.state == QueryState.RUNNING
        assert queued.state == QueryState.TIMED_OUT
        assert queued.start_t is None
        blocker.cancel()


def test_default_timeout_conf_applies():
    conf = dict(_SLOW_FAULT)
    conf["spark.rapids.service.defaultTimeoutMs"] = "250"
    with QueryService(conf) as svc:
        h = svc.submit(_slow_query(svc))
        assert h.wait(timeout=30)
        assert h.state == QueryState.TIMED_OUT


# ---------------------------------------------------------------------------
# admission: queue depth + memory pressure
# ---------------------------------------------------------------------------


def test_queue_full_rejection_with_retry_after():
    conf = dict(_SLOW_FAULT)
    conf["spark.rapids.service.queueDepth"] = "1"
    with QueryService(conf, max_concurrent=1) as svc:
        running = svc.submit(_slow_query(svc))
        _wait_state(running, QueryState.RUNNING)
        queued = svc.submit(_fast_query(svc))
        with pytest.raises(QueryRejectedError) as ei:
            svc.submit(_fast_query(svc, tag=1))
        assert ei.value.retry_after_ms >= 50
        assert svc.counters["rejected"] == 1
        running.cancel()
        queued.cancel()


def test_memory_pressure_holds_admission():
    conf = dict(_SLOW_FAULT)
    conf["spark.rapids.service.admission.maxDeviceBytes"] = "1"
    with QueryService(conf, max_concurrent=2) as svc:
        svc._memory_probe = lambda: 10 ** 12  # far over the high water
        h1 = svc.submit(_slow_query(svc))
        _wait_state(h1, QueryState.RUNNING)
        h2 = svc.submit(_fast_query(svc))
        time.sleep(0.4)
        # the gate held h2 back even though a worker was free...
        assert h2.state == QueryState.QUEUED
        assert svc.stats()["heldForMemory"] > 0
        # ...but forward progress wins once nothing is running
        h1.cancel()
        assert h2.wait(timeout=30)
        assert h2.state == QueryState.FINISHED
        assert h2.start_t >= h1.end_t


def test_unknown_pool_rejected_and_bad_specs_raise():
    from spark_rapids_tpu.errors import ColumnarProcessingError
    from spark_rapids_tpu.service.scheduler import (
        parse_pools,
        parse_tenant_weights,
    )
    with QueryService({}) as svc:
        with pytest.raises(ColumnarProcessingError, match="unknown"):
            svc.submit(_fast_query(svc), pool="nope")
    assert parse_pools("a;b:weight=2") == {"a": 1.0, "b": 2.0}
    with pytest.raises(ColumnarProcessingError):
        parse_pools("a;a")
    with pytest.raises(ColumnarProcessingError):
        parse_pools("a:weight=0")
    with pytest.raises(ColumnarProcessingError):
        parse_pools("")
    with pytest.raises(ColumnarProcessingError, match="not a number"):
        parse_pools("a:weight=high")
    assert parse_tenant_weights("x=2, y=0.5") == {"x": 2.0, "y": 0.5}
    with pytest.raises(ColumnarProcessingError):
        parse_tenant_weights("x")
    with pytest.raises(ColumnarProcessingError, match="not a number"):
        parse_tenant_weights("x=fast")


# ---------------------------------------------------------------------------
# weighted fair queueing
# ---------------------------------------------------------------------------


def test_wfq_prefers_underweighted_tenant():
    """With heavy weight >> light weight, every queued heavy query runs
    before the 2nd light one once the light tenant has been charged."""
    conf = dict(_SLOW_FAULT)
    conf["spark.rapids.service.tenantWeights"] = "heavy=1000,light=1"
    conf["spark.rapids.service.resultCache.enabled"] = "false"
    with QueryService(conf, max_concurrent=1) as svc:
        blocker = svc.submit(_slow_query(svc), tenant="warm")
        light = [svc.submit(_fast_query(svc, tag=i), tenant="light")
                 for i in range(3)]
        heavy = [svc.submit(_fast_query(svc, tag=10 + i), tenant="heavy")
                 for i in range(3)]
        blocker.cancel()
        for h in light + heavy:
            assert h.wait(timeout=60)
            assert h.state == QueryState.FINISHED, h.error
        # first pick ties at clock 0 (FIFO by id -> light[0]); after the
        # light tenant is charged, all heavy queries cut ahead
        assert max(h.end_t for h in heavy) < max(h.end_t
                                                 for h in light[1:])


def test_wfq_clocks_are_weight_normalized_exactly_once():
    """_charge_locked adds elapsed/weight; the pick must compare those
    clocks RAW (dividing by the weight again would hand a weight-W
    tenant a W^2 share)."""
    from collections import deque

    from spark_rapids_tpu.service.query import QueryHandle
    conf = {"spark.rapids.service.tenantWeights": "a=2,b=1"}
    with QueryService(conf, max_concurrent=1) as svc:
        ha = QueryHandle(tenant="a", pool="default", tag=None,
                         sql_text=None, plan=None, deadline=None)
        hb = QueryHandle(tenant="b", pool="default", tag=None,
                         sql_text=None, plan=None, deadline=None)
        with svc._cond:  # workers can't race the pick while held
            svc._queues[("default", "a")] = deque([ha])
            svc._queues[("default", "b")] = deque([hb])
            svc._queued_per_pool["default"] = 2
            # a served 2.0s at weight 2 -> clock 1.0; b served 0.9s at
            # weight 1 -> clock 0.9: b is BEHIND its fair share
            svc._tenant_clock[("default", "a")] = 1.0
            svc._tenant_clock[("default", "b")] = 0.9
            picked = svc._pick_locked()
            # drain the other so shutdown doesn't cancel a fake handle
            svc._pick_locked()
        assert picked is hb


def test_wfq_returning_tenant_cannot_spend_idle_credit():
    """A tenant idle for a long stretch re-joins at the pool's ACTIVE
    minimum clock — idle time banks no credit, so a returning burst
    cannot monopolize workers (classic WFQ virtual-time lift)."""
    from collections import deque

    from spark_rapids_tpu.service.query import QueryHandle

    def _handle(tenant):
        return QueryHandle(tenant=tenant, pool="default", tag=None,
                           sql_text=None, plan=None, deadline=None)

    with QueryService({}, max_concurrent=1) as svc:
        with svc._cond:
            # veteran A has been served 60s; B ran 1s long ago and idled
            svc._tenant_clock[("default", "a")] = 60.0
            svc._tenant_clock[("default", "b")] = 1.0
            # A has work queued when B's burst arrives
            svc._queues[("default", "a")] = deque([_handle("a")])
            svc._queued_per_pool["default"] = 1
            svc._activate_locked("default", "b")
            # B lifted to A's clock: no 59s of exclusive service
            assert svc._tenant_clock[("default", "b")] == 60.0
            # and empty-queue state is pruned, not accumulated forever
            svc._pick_locked()
            assert ("default", "a") not in svc._queues


# ---------------------------------------------------------------------------
# result cache
# ---------------------------------------------------------------------------


def test_result_cache_hit_is_bit_identical():
    import scale_test
    with QueryService({}) as svc:
        h1 = svc.submit(_fast_query(svc), tenant="a")
        t1 = h1.result(timeout=60)
        h2 = svc.submit(_fast_query(svc), tenant="b")
        t2 = h2.result(timeout=60)
        assert not h1.cache_hit and h2.cache_hit
        assert scale_test.tables_differ(t1, t2) is None
        stats = svc.result_cache.stats()
        assert stats["hits"] == 1 and stats["misses"] == 1


def test_result_cache_lru_eviction():
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.service.result_cache import ResultCache
    t = HostTable.from_pydict(
        {"v": np.arange(100, dtype=np.int64)})
    cache = ResultCache(max_bytes=int(t.nbytes() * 2.5))
    assert cache.put("a", t) and cache.put("b", t) and cache.put("c", t)
    assert cache.evictions == 1 and cache.entry_count == 2
    assert cache.get("a") is None          # the LRU victim
    assert cache.get("c") is not None
    assert not cache.put("huge", HostTable.from_pydict(
        {"v": np.arange(100000, dtype=np.int64)}))  # oversized: skipped


def test_cache_invalidated_on_temp_view_mutation():
    with QueryService({}) as svc:
        s = svc.session
        s.create_dataframe(_data()).create_or_replace_temp_view("t")
        sql = "SELECT k, COUNT(v) AS c FROM t GROUP BY k"
        h1 = svc.submit(sql)
        r1 = h1.result(timeout=60)
        assert svc.submit(sql).result(timeout=60).num_rows == r1.num_rows
        # redefine the view over different data -> epoch bump -> miss
        d = _data()
        d["k"] = np.array(["x"] * len(d["k"]), dtype=object)
        s.create_dataframe(d).create_or_replace_temp_view("t")
        h3 = svc.submit(sql)
        r3 = h3.result(timeout=60)
        assert not h3.cache_hit
        assert r3.num_rows == 1  # one group now; stale entry not served
        # resubmitting the PRE-mutation plan (same fingerprint as the
        # cached entry) must also miss: its entry predates the epoch
        # bump and is dropped on lookup, never served
        h4 = svc.submit(h1.plan)
        assert h4.result(timeout=60).num_rows == r1.num_rows
        assert not h4.cache_hit
        assert svc.result_cache.invalidations >= 1


def test_cache_invalidated_on_write(tmp_path):
    with QueryService({}) as svc:
        h1 = svc.submit(_fast_query(svc))
        h1.result(timeout=60)
        h2 = svc.submit(_fast_query(svc))
        h2.result(timeout=60)
        assert h2.cache_hit
        # a WriteFiles plan through the SAME session's execute bumps
        # the invalidation epoch: contents under written paths changed
        svc.session.create_dataframe(_data()).write_parquet(
            str(tmp_path / "out"))
        h3 = svc.submit(_fast_query(svc))
        h3.result(timeout=60)
        assert not h3.cache_hit


def test_delta_commit_bumps_invalidation_epoch(tmp_path):
    """A Delta commit bumps ITS table's epoch — scoped, so an unrelated
    table's cached results keep serving — while the global epoch (the
    catalog-wide invalidation hammer) stays put."""
    from spark_rapids_tpu.delta.log import DeltaLog
    from spark_rapids_tpu.plan.fingerprint import (
        delta_table_id,
        table_epoch,
    )
    from spark_rapids_tpu.service.result_cache import invalidation_epoch
    tid = delta_table_id(str(tmp_path))
    other = delta_table_id(str(tmp_path) + "-other")
    global_before = invalidation_epoch()
    before, other_before = table_epoch(tid), table_epoch(other)
    DeltaLog(str(tmp_path)).commit([], 0, op_name="WRITE")
    assert table_epoch(tid) == before + 1
    assert table_epoch(other) == other_before
    assert invalidation_epoch() == global_before


def test_uncacheable_plans_never_cache():
    from spark_rapids_tpu.service.result_cache import fingerprint
    from spark_rapids_tpu.plan import nodes as P
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession()
    df = s.create_dataframe(_data())
    node = P.WriteFiles(df.plan, "parquet", "/tmp/x", None, {})
    assert fingerprint(node, s.conf) is None  # side effects never cache
    # structurally identical plans from DIFFERENT builder calls match
    a = fingerprint(_fast_query_plan(s, df), s.conf)
    b = fingerprint(_fast_query_plan(s, df), s.conf)
    assert a is not None and a == b
    # a result-affecting conf change changes the key
    c = fingerprint(_fast_query_plan(s, df),
                    s.conf.set("spark.sql.ansi.enabled", "true"))
    assert c != a


def _fast_query_plan(s, df):
    return (df.filter(col("v") > lit(0))
            .group_by("k").agg(F.count("v").alias("c"))).plan


def test_cancellation_wrapped_exec_still_pickles_for_lore():
    """LORE dumps of a service-executed plan must survive the third
    (cancellation) wrapper layer like the fault/observation ones."""
    import pickle

    from spark_rapids_tpu.execs.base import TpuExec
    from spark_rapids_tpu.lore import _iter_tree, _strip_for_pickle
    with QueryService({}) as svc:
        svc.submit(_fast_query(svc, tag=5)).result(timeout=60)
        ex = svc.session._last_executable  # mirror: last completed
    assert ex is not None
    execs = [e for e in _iter_tree(ex) if isinstance(e, TpuExec)]
    assert execs
    for e in execs:
        assert "_cancel_installed" in e.__dict__  # wrapper was live
        pickle.dumps(_strip_for_pickle(e))


# ---------------------------------------------------------------------------
# semaphore: typed timeout + metrics scope (two-thread contention)
# ---------------------------------------------------------------------------


def test_semaphore_contention_routes_metrics_and_typed_timeout():
    from spark_rapids_tpu.obs.metrics import metric_scope
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
    scope = metric_scope("semaphore")
    before = dict(scope)
    sem = TpuSemaphore(1)
    sem.acquire_if_necessary()
    errs = []

    def blocked():
        try:
            sem.acquire_if_necessary(timeout=0.05)
        except SemaphoreTimeoutError as e:
            errs.append(e)
            sem.release_if_held()  # no-op: never acquired

    t = threading.Thread(target=blocked)
    t.start()
    t.join(10)
    assert len(errs) == 1
    assert isinstance(errs[0], TimeoutError)  # stays a TimeoutError too
    assert sem.timeout_count == 1

    def second():
        sem.acquire_if_necessary(timeout=10)
        sem.release_if_held()

    t2 = threading.Thread(target=second)
    t2.start()
    time.sleep(0.1)
    sem.release_if_held()
    t2.join(10)
    after = dict(scope)
    assert after.get("acquireTimeouts", 0) - before.get(
        "acquireTimeouts", 0) == 1
    assert after.get("acquires", 0) - before.get("acquires", 0) >= 2
    assert after.get("acquireWaitTime", 0.0) > before.get(
        "acquireWaitTime", 0.0)


# ---------------------------------------------------------------------------
# event log: service fields
# ---------------------------------------------------------------------------


def test_event_log_records_service_fields(tmp_path):
    conf = {"spark.rapids.sql.eventLog.enabled": "true",
            "spark.rapids.sql.eventLog.dir": str(tmp_path)}
    with QueryService(conf) as svc:
        h1 = svc.submit(_fast_query(svc), tenant="alice", tag="q")
        h1.result(timeout=60)
        h2 = svc.submit(_fast_query(svc), tenant="bob", tag="q")
        h2.result(timeout=60)
    rec1, rec2 = h1.event_record, h2.event_record
    assert rec1["tenant"] == "alice" and rec1["pool"] == "default"
    assert rec1["cacheHit"] is False and rec1["queueWaitS"] >= 0
    assert rec2["tenant"] == "bob" and rec2["cacheHit"] is True
    # both the execution and the cache-hit serve landed in the log
    files = [f for f in os.listdir(tmp_path) if f.endswith(".jsonl")]
    assert len(files) == 1
    lines = open(tmp_path / files[0]).read().strip().splitlines()
    assert len(lines) == 2
    hits = [json.loads(ln)["cacheHit"] for ln in lines]
    assert sorted(hits) == [False, True]


# ---------------------------------------------------------------------------
# concurrent execution: identity + chaos slice
# ---------------------------------------------------------------------------


def test_concurrent_results_bit_identical_and_faster_than_serial():
    """The tier-1-sized loadtest: 2 tenants x 2 golden queries at
    concurrency 4 through the service, every result bit-identical to
    serial execution and the aggregate wall below the serial sum."""
    from spark_rapids_tpu.tools.loadtest import run_loadtest
    report = run_loadtest(sf=0.005, queries=["q1", "q3"], concurrency=4,
                          tenants=2)
    assert report["ok"], (report["mismatches"], report["failures"])
    assert report["allIdentical"]
    assert report["submissions"] == 4
    assert report["belowSerialSum"], (report["wallClockS"],
                                      report["serialSumS"])
    assert report["latencyP95S"] >= report["latencyP50S"]
    assert 0.0 <= report["cacheHitRate"] <= 1.0


@pytest.mark.chaos
def test_concurrent_chaos_slice_bit_identical():
    """scale_test --concurrency 4 --chaos --seed 7 slice: recovery and
    the concurrent scheduler together, results bit-identical to
    fault-free serial execution, lifecycle counters sane."""
    from spark_rapids_tpu.lint.golden import _load_scale_test
    st = _load_scale_test()
    report = st.run_chaos(sf=0.01, seed=7, queries=["q1", "q3", "q7"],
                          concurrency=4)
    assert report["ok"]
    assert all(e["identical"] for e in report["queries"].values())
    assert sum(report["fault_fires"].values()) > 0  # not vacuous
    svc = report["service"]
    assert svc["finished"] == 3
    assert svc["cancelled"] == svc["timed_out"] == svc["rejected"] == 0
    for field, per_query_bound in st.CHAOS_BOUNDS.items():
        assert report["recovery"].get(field, 0) <= per_query_bound * 3


# ---------------------------------------------------------------------------
# tools loadtest CLI smoke
# ---------------------------------------------------------------------------


@pytest.mark.slow  # a fresh-process jax import just to re-prove the
# arg wiring: run_loadtest's logic is covered in-process above, and
# the tools CLI surface is covered by the telemetry/warmup CLI smokes
def test_tools_loadtest_cli_smoke():
    """q1 at concurrency 2 through the real CLI -> JSON report."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "loadtest",
         "--sf", "0.002", "--queries", "q1", "--concurrency", "2",
         "--tenants", "2", "--json"],
        capture_output=True, text=True, env=env, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["ok"] and report["allIdentical"]
    assert report["concurrency"] == 2 and report["submissions"] == 2
    for key in ("wallClockS", "serialSumS", "latencyP50S", "latencyP95S",
                "queueWaitP50S", "cacheHitRate", "throughputQps"):
        assert key in report
