"""PINNED Spark-semantics golden vectors.

The r1 oracle was circular: the TPU path was compared only against this
repo's own CPU implementations, so a shared misunderstanding of Spark
semantics passed both paths (VERDICT r1 'weak' #3). These vectors pin the
EXPECTED outputs independently — each is hand-derived from documented
Apache Spark behavior (function docs, SQL reference, Java/Scala conversion
semantics Spark inherits) and committed as literals. test_golden.py runs
every vector through BOTH the CPU path and the TPU overrides path and
compares each against the pinned expectation, not against each other.

No Spark/JVM exists in this environment, so these are transcription-
verified rather than machine-generated; regenerating with real Spark
(scripts commented at the bottom) is the follow-up when a JVM is available.

Format: (name, columns, rows, build_expr, expected_column)
  columns: {name: type_str}; rows: list of tuples (None = null)
  build_expr: fn(F, col, lit) -> Expression evaluated as one projection
  expected: list of expected python values (None = null)
"""

import datetime as dt

from spark_rapids_tpu import types as T

D = dt.date

TYPES = {"int": T.INT, "long": T.LONG, "double": T.DOUBLE, "float": T.FLOAT,
         "string": T.STRING, "bool": T.BOOLEAN, "date": T.DATE,
         "short": T.SHORT, "byte": T.BYTE}

VECTORS = [
    # -- arithmetic: Java semantics Spark inherits (non-ANSI) ---------------
    ("int_add_overflow_wraps", {"a": "int", "b": "int"},
     [(2147483647, 1), (-2147483648, -1), (5, 7)],
     lambda F, col, lit: col("a") + col("b"),
     [-2147483648, 2147483647, 12]),

    ("long_mul_overflow_wraps", {"a": "long", "b": "long"},
     [(4611686018427387904, 2), (3, 4)],
     lambda F, col, lit: col("a") * col("b"),
     [-9223372036854775808, 12]),

    ("divide_double_by_zero_is_null", {"a": "double", "b": "double"},
     [(1.0, 0.0), (7.0, 2.0), (None, 2.0), (0.0, 0.0)],
     lambda F, col, lit: col("a") / col("b"),
     [None, 3.5, None, None]),

    ("integral_divide_truncates", {"a": "int", "b": "int"},
     [(7, 2), (-7, 2), (7, -2), (1, 0)],
     lambda F, col, lit: _intdiv(col("a"), col("b")),
     [3, -3, -3, None]),

    ("remainder_java_sign", {"a": "int", "b": "int"},
     [(-7, 3), (7, -3), (7, 3), (7, 0)],
     lambda F, col, lit: col("a") % col("b"),
     [-1, 1, 1, None]),

    ("pmod_nonnegative", {"a": "int", "b": "int"},
     [(-7, 3), (7, -3), (7, 3), (7, 0)],
     lambda F, col, lit: _pmod(col("a"), col("b")),
     [2, -2, 1, None]),

    ("abs_minint_wraps", {"a": "int"},
     [(-2147483648,), (-5,), (5,)],
     lambda F, col, lit: F.abs(col("a")),
     [-2147483648, 5, 5]),

    ("unary_minus_minint_wraps", {"a": "int"},
     [(-2147483648,), (3,)],
     lambda F, col, lit: -col("a"),
     [-2147483648, -3]),

    # -- rounding -----------------------------------------------------------
    ("round_half_up", {"a": "double"},
     [(2.5,), (3.5,), (-2.5,), (2.4,), (-2.6,)],
     lambda F, col, lit: F.round(col("a")),
     [3.0, 4.0, -3.0, 2.0, -3.0]),

    ("bround_half_even", {"a": "double"},
     [(2.5,), (3.5,), (-2.5,), (2.4,)],
     lambda F, col, lit: F.bround(col("a")),
     [2.0, 4.0, -2.0, 2.0]),

    ("floor_ceil", {"a": "double"},
     [(-0.1,), (0.1,), (-1.5,)],
     lambda F, col, lit: F.ceil(col("a")) * lit(1000) + F.floor(col("a")),
     [-1, 1000, -1002]),

    # -- string functions (1-based positions, null propagation) -------------
    ("substring_positive", {"s": "string"},
     [("Spark SQL",), ("ab",), (None,)],
     lambda F, col, lit: F.substring(col("s"), 5, 1),
     ["k", "", None]),

    ("substring_negative_start", {"s": "string"},
     [("Spark SQL",), ("ab",)],
     lambda F, col, lit: F.substring(col("s"), -3, 3),
     ["SQL", "ab"]),

    ("substring_pos_zero_acts_like_one", {"s": "string"},
     [("Spark",)],
     lambda F, col, lit: F.substring(col("s"), 0, 2),
     ["Sp"]),

    ("length_of_empty_and_null", {"s": "string"},
     [("",), ("abc",), (None,)],
     lambda F, col, lit: F.length(col("s")),
     [0, 3, None]),

    ("concat_null_propagates", {"a": "string", "b": "string"},
     [("x", "y"), (None, "y"), ("x", None)],
     lambda F, col, lit: F.concat(col("a"), col("b")),
     ["xy", None, None]),

    ("instr_one_based_zero_missing", {"s": "string"},
     [("SparkSQL",), ("abc",), (None,)],
     lambda F, col, lit: F.instr(col("s"), "SQL"),
     [6, 0, None]),

    ("upper_lower_ascii", {"s": "string"},
     [("MixEd123",)],
     lambda F, col, lit: F.concat(F.upper(col("s")), F.lower(col("s"))),
     ["MIXED123mixed123"]),

    ("trim_spaces_only", {"s": "string"},
     [("  a b  ",), ("\tx",)],
     lambda F, col, lit: F.trim(col("s")),
     ["a b", "\tx"]),  # Spark trim removes ASCII space 0x20 only

    ("repeat_and_reverse", {"s": "string"},
     [("ab",), ("",)],
     lambda F, col, lit: F.concat(F.repeat(col("s"), 2), F.reverse(col("s"))),
     ["ababba", ""]),

    ("startswith_endswith_contains", {"s": "string"},
     [("Spark",), ("park",), (None,)],
     lambda F, col, lit: (F.startswith(col("s"), "Sp")
                          & F.contains(col("s"), "ar")
                          & F.endswith(col("s"), "rk")),
     [True, False, None]),

    # -- casts (Java/Scala conversion semantics) -----------------------------
    ("cast_string_to_int_hive_truncation", {"s": "string"},
     # UTF8String.toInt (Hive LazyLong compat): trailing .digits TRUNCATE;
     # exponents and garbage are null (reference: CastOpSuite hand-picked)
     [(" 42 ",), ("4.5",), ("321.123",), ("-.2",), (".3",), ("+1.2",),
      ("1e4",), ("abc",), ("-0",), (".",), (None,)],
     lambda F, col, lit: col("s").cast(T.INT),
     [42, 4, 321, 0, 0, 1, None, None, 0, None, None]),

    ("cast_string_to_double", {"s": "string"},
     [("4.5",), (" 1e3 ",), ("abc",), ("-0.0",)],
     lambda F, col, lit: col("s").cast(T.DOUBLE),
     [4.5, 1000.0, None, -0.0]),

    ("cast_double_to_int_truncates_saturates", {"a": "double"},
     [(3.9,), (-3.9,), (float("nan"),), (1e20,), (-1e20,)],
     lambda F, col, lit: col("a").cast(T.INT),
     [3, -3, 0, 2147483647, -2147483648]),

    ("cast_bool_string_roundtrip", {"s": "string"},
     [("true",), ("false",), ("1",), ("0",), ("maybe",)],
     lambda F, col, lit: col("s").cast(T.BOOLEAN),
     [True, False, True, False, None]),

    ("cast_int_to_string", {"a": "int"},
     [(-42,), (0,), (2147483647,)],
     lambda F, col, lit: col("a").cast(T.STRING),
     ["-42", "0", "2147483647"]),

    ("cast_double_to_string_java_format", {"a": "double"},
     [(1.0,), (0.5,), (1e7,), (12345678.0,), (0.001,), (0.0001,),
      (float("nan"),), (float("inf",),)],
     lambda F, col, lit: col("a").cast(T.STRING),
     ["1.0", "0.5", "1.0E7", "1.2345678E7", "0.001", "1.0E-4",
      "NaN", "Infinity"]),

    ("cast_bool_to_string", {"a": "bool"},
     [(True,), (False,), (None,)],
     lambda F, col, lit: col("a").cast(T.STRING),
     ["true", "false", None]),

    ("cast_date_to_string_iso", {"d": "date"},
     [(D(2015, 3, 18),), (D(1969, 12, 31),)],
     lambda F, col, lit: col("d").cast(T.STRING),
     ["2015-03-18", "1969-12-31"]),

    ("cast_string_to_date_formats", {"s": "string"},
     [("2015-03-18",), ("2015-03",), ("2015",), ("2015-03-18T12:03:17",),
      ("2015-02-29",), ("not-a-date",), ("2015-3-8",)],
     lambda F, col, lit: col("s").cast(T.DATE),
     [D(2015, 3, 18), D(2015, 3, 1), D(2015, 1, 1), D(2015, 3, 18),
      None, None, D(2015, 3, 8)]),

    ("cast_string_to_long_overflow_null", {"s": "string"},
     [("9223372036854775807",), ("9223372036854775808",),
      ("-9223372036854775808",)],
     lambda F, col, lit: col("s").cast(T.LONG),
     [9223372036854775807, None, -9223372036854775808]),

    ("cast_float_specials", {"s": "string"},
     [("Infinity",), ("-infinity",), ("NaN",), ("1.5f",), ("2.5d",)],
     lambda F, col, lit: col("s").cast(T.DOUBLE),
     [float("inf"), float("-inf"), float("nan"), 1.5, 2.5]),

    # -- datetime (proleptic Gregorian, epoch days) --------------------------
    ("year_month_day_pre_epoch", {"d": "date"},
     [(D(1969, 12, 31),), (D(1970, 1, 1),), (D(2000, 2, 29),)],
     lambda F, col, lit: (F.year(col("d")) * lit(10000)
                          + F.month(col("d")) * lit(100)
                          + F.dayofmonth(col("d"))),
     [19691231, 19700101, 20000229]),

    ("date_add_sub", {"d": "date"},
     [(D(2015, 9, 30),), (D(2016, 2, 28),)],
     lambda F, col, lit: F.date_add(col("d"), 1),
     [D(2015, 10, 1), D(2016, 2, 29)]),

    ("datediff_order", {"a": "date", "b": "date"},
     [(D(2009, 7, 31), D(2009, 7, 30)), (D(2009, 7, 30), D(2009, 7, 31))],
     lambda F, col, lit: F.datediff(col("a"), col("b")),
     [1, -1]),

    ("dayofweek_sunday_is_one", {"d": "date"},
     [(D(2009, 7, 30),), (D(2024, 1, 7),)],  # Thursday, Sunday
     lambda F, col, lit: F.dayofweek(col("d")),
     [5, 1]),

    ("weekday_monday_is_zero", {"d": "date"},
     [(D(2024, 1, 8),), (D(2024, 1, 7),)],  # Monday, Sunday
     lambda F, col, lit: F.weekday(col("d")),
     [0, 6]),

    ("last_day_of_month", {"d": "date"},
     [(D(2009, 1, 12),), (D(2016, 2, 10),)],
     lambda F, col, lit: F.last_day(col("d")),
     [D(2009, 1, 31), D(2016, 2, 29)]),

    ("add_months_clamps_day", {"d": "date"},
     [(D(2016, 8, 31),), (D(2015, 1, 30),)],
     lambda F, col, lit: F.add_months(col("d"), 1),
     [D(2016, 9, 30), D(2015, 2, 28)]),

    # -- comparisons / null logic -------------------------------------------
    ("three_valued_and_or", {"a": "bool", "b": "bool"},
     [(True, None), (False, None), (None, None)],
     lambda F, col, lit: (col("a") & col("b")),
     [None, False, None]),

    ("or_with_null", {"a": "bool", "b": "bool"},
     [(True, None), (False, None)],
     lambda F, col, lit: (col("a") | col("b")),
     [True, None]),

    ("equality_null_yields_null", {"a": "int", "b": "int"},
     [(1, 1), (1, None), (None, None)],
     lambda F, col, lit: col("a") == col("b"),
     [True, None, None]),

    ("nan_comparisons", {"a": "double"},
     [(float("nan"),), (1.0,)],
     # Spark: NaN == NaN is TRUE and NaN > anything (total order semantics)
     lambda F, col, lit: col("a") == col("a"),
     [True, True]),

    ("negative_zero_equals_zero", {"a": "double", "b": "double"},
     [(-0.0, 0.0)],
     lambda F, col, lit: col("a") == col("b"),
     [True]),

    # -- conditional ----------------------------------------------------------
    ("coalesce_first_non_null", {"a": "int", "b": "int"},
     [(None, 2), (1, 2), (None, None)],
     lambda F, col, lit: F.coalesce(col("a"), col("b"), lit(9)),
     [2, 1, 9]),

    ("if_null_condition_is_false", {"c": "bool", "a": "int", "b": "int"},
     [(None, 1, 2), (True, 1, 2), (False, 1, 2)],
     lambda F, col, lit: F.if_(col("c"), col("a"), col("b")),
     [2, 1, 2]),

    ("greatest_skips_nulls_least", {"a": "int", "b": "int"},
     [(3, None), (None, None), (3, 7)],
     lambda F, col, lit: F.greatest(col("a"), col("b")),
     [3, None, 7]),
]


def _intdiv(a, b):
    from spark_rapids_tpu.ops.arithmetic import IntegralDivide
    return IntegralDivide(a, b)


def _pmod(a, b):
    from spark_rapids_tpu.ops.arithmetic import Pmod
    return Pmod(a, b)


# Regeneration with real Apache Spark (when a JVM is available):
#   spark = SparkSession.builder.getOrCreate()
#   for each vector: spark.createDataFrame(rows, schema).select(expr(sql))
#   .collect() and compare/update the pinned `expected` literals.
