"""Profiler / tracing subsystem.

Reference (SURVEY.md §5): (a) NVTX ranges everywhere
(``NvtxWithMetrics.scala``) for Nsight timelines; (b) the built-in async
profiler — ``profiler.scala`` ProfilerOnExecutor/OnDriver: JNI CUPTI
trace collection to a ProfileWriter, with driver-coordinated enable
windows keyed by job/time ranges (``spark.rapids.profile.*`` confs).

TPU mapping: XLA's profiler (Xprof) plays CUPTI's role —
``jax.profiler.start_trace/stop_trace`` writes a TensorBoard/Xprof trace
directory; ``jax.profiler.TraceAnnotation`` is the NVTX-range analog and
shows engine operators on the device timeline. Enable windows: every
query, or a query-index range (``spark.rapids.profile.queryRanges`` e.g.
"2-5,8" — RangeConfMatcher semantics)."""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional, Set

from spark_rapids_tpu.conf import RapidsConf, bool_conf, str_conf
from spark_rapids_tpu.lockorder import ordered_lock

PROFILE_ENABLED = bool_conf(
    "spark.rapids.profile.enabled", False,
    "Collect XLA (Xprof) device traces for queries (profiler.scala "
    "analog).")

PROFILE_PATH = str_conf(
    "spark.rapids.profile.pathPrefix", "/tmp/rapids_tpu_profile",
    "Directory prefix for collected trace sessions.")

PROFILE_QUERY_RANGES = str_conf(
    "spark.rapids.profile.queryRanges", "",
    "Query-index ranges to profile, e.g. \"0-2,5\" (empty = all queries "
    "when profiling is enabled). RangeConfMatcher syntax.")


def parse_ranges(spec: str) -> Optional[Set[int]]:
    """\"1-3,8\" -> {1,2,3,8}; empty/blank -> None (match all)
    (RangeConfMatcher.scala analog).

    Malformed specs raise a ValueError NAMING the conf key — the
    profiler parses at conf-read time (TpuProfiler.__init__), so a typo
    fails the session's first execute with an actionable message
    instead of a bare int() traceback at the first profiled query."""
    key = PROFILE_QUERY_RANGES.key

    def _bound(text: str, part: str) -> int:
        text = text.strip()
        try:
            v = int(text)
        except ValueError:
            raise ValueError(
                f"{key}: range entry {part!r} has non-integer bound "
                f"{text!r} (expected e.g. \"0-2,5\")") from None
        if v < 0:
            raise ValueError(
                f"{key}: range entry {part!r} has negative bound {v}")
        return v

    spec = (spec or "").strip()
    if not spec:
        return None
    out: Set[int] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo_s, _, hi_s = part.partition("-")
            if not lo_s.strip() or not hi_s.strip():
                raise ValueError(
                    f"{key}: range entry {part!r} is missing a bound "
                    f"(expected \"<lo>-<hi>\")")
            lo, hi = _bound(lo_s, part), _bound(hi_s, part)
            if lo > hi:
                raise ValueError(
                    f"{key}: range entry {part!r} is reversed "
                    f"({lo} > {hi})")
            out.update(range(lo, hi + 1))
        else:
            out.add(_bound(part, part))
    return out


class TpuProfiler:
    """Per-session profiler driver (ProfilerOnExecutor analog)."""

    def __init__(self, conf: RapidsConf):
        self.enabled = bool(conf.get_entry(PROFILE_ENABLED))
        self.path_prefix = str(conf.get_entry(PROFILE_PATH))
        # conf-read-time validation: a malformed queryRanges spec fails
        # HERE with the conf key named, not at the first profiled query
        self.ranges = parse_ranges(str(conf.get_entry(PROFILE_QUERY_RANGES)))
        self._query_index = 0
        self._lock = ordered_lock("profiler")
        self._active = 0
        self.sessions_written = 0

    def should_profile(self, query_index: int) -> bool:
        return self.enabled and (self.ranges is None
                                 or query_index in self.ranges)

    @contextlib.contextmanager
    def profile_query(self):
        """Wrap one query execution in a trace session; traces land under
        <prefix>/query_<N>/.

        Only TOP-LEVEL queries advance the query index: a nested query
        (cached-relation materialization inside an outer execute) rides
        the outer trace session and must NOT burn a ``queryRanges``
        slot, or every index after it would drift off the user's spec.
        XLA allows one trace session per process anyway, so nested (and
        concurrent) queries yield None."""
        with self._lock:
            nested = self._active > 0
            self._active += 1
            if not nested:
                idx = self._query_index
                self._query_index += 1
        try:
            if nested or not self.should_profile(idx):
                yield None
                return
            import jax
            path = os.path.join(self.path_prefix, f"query_{idx}")
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            try:
                yield path
            finally:
                jax.profiler.stop_trace()
                self.sessions_written += 1
        finally:
            with self._lock:
                self._active -= 1


def op_range(name: str, cat: str = "op"):
    """Operator range on BOTH timelines (NvtxWithMetrics analog): always
    a jax.profiler.TraceAnnotation (device/Xprof timeline, zero-cost
    when no trace session is active) and, while the host span tracer is
    collecting, a host span too — so the same range shows up in the
    Xprof trace and the exported Chrome host timeline."""
    import jax
    from spark_rapids_tpu.obs.spans import TRACER
    ann = jax.profiler.TraceAnnotation(name)
    if not TRACER.enabled:
        return ann
    return _CombinedRange(ann, name, cat)


class _CombinedRange:
    __slots__ = ("ann", "name", "cat", "_span")

    def __init__(self, ann, name, cat):
        self.ann = ann
        self.name = name
        self.cat = cat
        self._span = None

    def __enter__(self):
        from spark_rapids_tpu.obs.spans import TRACER
        self._span = TRACER.begin(self.name, self.cat)
        self.ann.__enter__()
        return self

    def __exit__(self, *exc):
        from spark_rapids_tpu.obs.spans import TRACER
        self.ann.__exit__(*exc)
        TRACER.end(self._span)
        return False
