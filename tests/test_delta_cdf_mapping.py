"""Delta Change Data Feed + column mapping (reference:
delta_lake_*_test.py CDF suites and the column-mapping shims; VERDICT r4
listed both as the connector's remaining gaps).

CDF: DML on a table with delta.enableChangeDataFeed=true writes cdc
files under _change_data/ with _change_type, and table_changes() reads
row-level changes per commit version (deriving insert/delete rows from
plain add/remove commits that carry no cdc actions).

Column mapping: rename_column() upgrades the table to
columnMapping.mode=name (physical names pinned in field metadata,
protocol 2/5) and renames WITHOUT touching any data file; scans, DML
and writers resolve logical->physical from then on.
"""

import json
import os

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit


def _mk(session, path, n=60):
    data = {"id": np.arange(n, dtype=np.int64),
            "v": (np.arange(n) % 7).astype(np.float64)}
    session.create_dataframe(data).write_delta(path)
    return session.delta_table(path)


def _changes(dt, start, end=None):
    df = dt.table_changes(start, end)
    names = [n for n, _ in df.schema()] if hasattr(df, "schema") else None
    rows = df.collect()
    return names, rows


# -- CDF ---------------------------------------------------------------------

def test_cdf_delete_and_update(session, tmp_path):
    path = str(tmp_path / "t")
    dt = _mk(session, path)
    dt.set_properties({"delta.enableChangeDataFeed": "true"})
    v_del = dt.delete(col("id") < lit(5))
    assert v_del["num_affected_rows"] == 5
    dt.update(col("id") == lit(10), {"v": lit(99.0)})
    ver = dt.version()

    # cdc actions present in both DML commits
    log_dir = os.path.join(path, "_delta_log")
    acts = []
    for v in (ver - 1, ver):
        with open(os.path.join(log_dir, f"{v:020d}.json")) as f:
            acts.append([json.loads(x) for x in f if x.strip()])
    assert any("cdc" in a for a in acts[0])
    assert any("cdc" in a for a in acts[1])

    changes = dt.table_changes(ver - 1).collect()
    by_type = {}
    for r in changes:
        by_type.setdefault(r[-2], []).append(r)
    assert len(by_type["delete"]) == 5
    assert sorted(r[0] for r in by_type["delete"]) == [0, 1, 2, 3, 4]
    assert len(by_type["update_preimage"]) == 1
    assert len(by_type["update_postimage"]) == 1
    assert by_type["update_postimage"][0][1] == 99.0
    # _commit_version distinguishes the two commits
    assert {r[-1] for r in by_type["delete"]} == {ver - 1}
    assert {r[-1] for r in by_type["update_postimage"]} == {ver}


def test_cdf_derives_inserts_from_plain_writes(session, tmp_path):
    """A plain append after enablement carries adds only (no cdc
    actions) — table_changes derives insert rows from the data files."""
    path = str(tmp_path / "t")
    dt = _mk(session, path, n=10)
    dt.set_properties({"delta.enableChangeDataFeed": "true"})
    session.create_dataframe({
        "id": np.arange(100, 110, dtype=np.int64),
        "v": np.full(10, 0.5)}).write_delta(path, mode="append")
    ver = dt.version()
    changes = dt.table_changes(ver, ver).collect()
    assert len(changes) == 10
    assert all(r[-2] == "insert" and r[-1] == ver for r in changes)


def test_cdf_range_before_enablement_raises(session, tmp_path):
    """ADVICE r5 (medium): versions predating
    delta.enableChangeDataFeed carry no recorded change data — deriving
    them from add/remove actions turned a deletion-vector partial
    DELETE into a full-file delete (survivors included). The reader now
    errors for any range touching a pre-enablement version."""
    from spark_rapids_tpu.errors import ColumnarProcessingError
    path = str(tmp_path / "t")
    dt = _mk(session, path)                       # v0: CREATE
    dt.delete(col("id") < lit(5))                 # v1: DV partial DELETE
    dt.set_properties({"delta.enableChangeDataFeed": "true"})  # v2
    for start, end in [(0, None), (1, 1), (0, 2), (1, None)]:
        with pytest.raises(ColumnarProcessingError,
                           match="enableChangeDataFeed"):
            dt.table_changes(start, end)
    # from the enabling version onward the feed reads fine
    dt.delete(col("id") < lit(10))                # v3: cdc commit
    changes = dt.table_changes(2).collect()
    assert sorted(r[0] for r in changes) == [5, 6, 7, 8, 9]
    assert all(r[-2] == "delete" for r in changes)


def test_cdf_merge_emits_all_change_types(session, tmp_path):
    path = str(tmp_path / "t")
    dt = _mk(session, path, n=20)
    dt.set_properties({"delta.enableChangeDataFeed": "true"})
    src = session.create_dataframe({
        "id": np.array([5, 99], dtype=np.int64),
        "v": np.array([50.0, 990.0])})
    dt.merge(src, on=["id"]).when_matched_update(
        set={"v": "v"}).when_not_matched_insert().execute()
    ver = dt.version()
    changes = dt.table_changes(ver, ver).collect()
    types = sorted(set(r[-2] for r in changes))
    assert types == ["insert", "update_postimage", "update_preimage"]
    post = [r for r in changes if r[-2] == "update_postimage"]
    assert post[0][0] == 5 and post[0][1] == 50.0
    ins = [r for r in changes if r[-2] == "insert"]
    assert ins[0][0] == 99


def test_vacuum_keeps_cdc_files(session, tmp_path):
    path = str(tmp_path / "t")
    dt = _mk(session, path)
    dt.set_properties({"delta.enableChangeDataFeed": "true"})
    dt.delete(col("id") < lit(3))
    v_delete = dt.version()
    dt.optimize()
    dt.vacuum()
    cdc_dir = os.path.join(path, "_change_data")
    assert os.path.isdir(cdc_dir) and os.listdir(cdc_dir)
    # change feed still reads after vacuum
    assert dt.table_changes(v_delete, v_delete).count() == 3


# -- column mapping ----------------------------------------------------------

def test_rename_column_without_rewrite(session, cpu_session, tmp_path):
    path = str(tmp_path / "t")
    dt = _mk(session, path, n=40)
    files_before = sorted(
        f for f in os.listdir(path) if f.endswith(".parquet"))
    dt.rename_column("v", "value")
    files_after = sorted(
        f for f in os.listdir(path) if f.endswith(".parquet"))
    assert files_before == files_after  # NO data file rewritten

    got = sorted(session.read_delta(path).collect())
    want = sorted(cpu_session.read_delta(path).collect())
    assert got == want and len(got) == 40
    names = [n for n, _ in session.read_delta(path).schema]
    assert names == ["id", "value"]
    # the log records mode=name + physical names + protocol 2/5
    snap = dt.log.snapshot()
    assert snap.metadata.column_mapping_mode() == "name"
    assert snap.metadata.physical_names()["value"] == "v"


def test_mapped_table_append_and_dml(session, tmp_path):
    """After the mapping upgrade, appends write PHYSICAL column names
    and DML keeps working end to end."""
    path = str(tmp_path / "t")
    dt = _mk(session, path, n=20)
    dt.rename_column("v", "value")
    session.create_dataframe({
        "id": np.arange(100, 110, dtype=np.int64),
        "value": np.full(10, 7.5)}).write_delta(path, mode="append")
    assert session.read_delta(path).count() == 30

    # the appended file stores the PHYSICAL name 'v'
    import pyarrow.parquet as pq
    snap = dt.log.snapshot()
    newest = max(snap.files, key=lambda a: a.modification_time)
    cols = pq.ParquetFile(
        os.path.join(path, newest.path)).schema_arrow.names
    assert "v" in cols and "value" not in cols

    dt.update(col("id") >= lit(100), {"value": lit(1.25)})
    got = sorted(session.read_delta(path)
                 .filter(col("id") >= lit(100)).collect())
    assert all(r[1] == 1.25 for r in got) and len(got) == 10
    dt.delete(col("id") >= lit(100))
    assert session.read_delta(path).count() == 20


def test_mapped_table_cdf_roundtrip(session, tmp_path):
    """Column mapping + CDF together: cdc files carry physical names,
    table_changes surfaces logical ones."""
    path = str(tmp_path / "t")
    dt = _mk(session, path, n=15)
    dt.rename_column("v", "value")
    dt.set_properties({"delta.enableChangeDataFeed": "true"})
    dt.delete(col("id") == lit(3))
    changes = dt.table_changes(dt.version(), dt.version()).collect()
    assert len(changes) == 1
    assert changes[0][0] == 3 and changes[0][-2] == "delete"


def test_rename_errors(session, tmp_path):
    from spark_rapids_tpu.errors import ColumnarProcessingError
    path = str(tmp_path / "t")
    dt = _mk(session, path, n=5)
    with pytest.raises(ColumnarProcessingError):
        dt.rename_column("nope", "x")
    with pytest.raises(ColumnarProcessingError):
        dt.rename_column("v", "id")


def test_merge_schema_append_preserves_mapping_and_cdf(session, tmp_path):
    """Code-review r5: a mergeSchema append on a mapped/CDF table must
    not wipe columnMapping state or delta.enableChangeDataFeed from the
    evolved Metadata action."""
    path = str(tmp_path / "t")
    dt = _mk(session, path, n=10)
    dt.rename_column("v", "value")
    dt.set_properties({"delta.enableChangeDataFeed": "true"})
    session.create_dataframe({
        "id": np.arange(100, 105, dtype=np.int64),
        "value": np.full(5, 1.0),
        "extra": np.arange(5, dtype=np.int64)}).write_delta(
            path, mode="append", merge_schema=True)
    snap = dt.log.snapshot()
    assert snap.metadata.column_mapping_mode() == "name"
    assert snap.metadata.physical_names()["value"] == "v"
    assert snap.metadata.cdf_enabled()
    # renamed column still reads from OLD files after the evolution
    got = sorted(session.read_delta(path).collect())
    assert len(got) == 15
    old = [r for r in got if r[0] < 100]
    assert all(r[1] is not None for r in old)   # not null-filled
    assert all(r[2] is None for r in old)       # evolution null-fills extra


def test_merge_schema_assigns_mapping_to_new_fields(session, tmp_path):
    """ADVICE r5 (low): on a mapped table, a mergeSchema append must
    give NEW fields their own columnMapping.physicalName/id and bump
    maxColumnId — and write the data file under the physical name so
    the new column reads back (not null-filled)."""
    from spark_rapids_tpu.delta.log import schema_fields_from_json
    path = str(tmp_path / "t")
    dt = _mk(session, path, n=10)
    dt.rename_column("v", "value")       # upgrades to mapping mode=name
    session.create_dataframe({
        "id": np.arange(100, 105, dtype=np.int64),
        "value": np.full(5, 1.0),
        "extra": np.arange(5, dtype=np.int64)}).write_delta(
            path, mode="append", merge_schema=True)
    m = dt.log.snapshot().metadata
    fields = {f["name"]: f
              for f in schema_fields_from_json(m.schema_json)}
    md = fields["extra"].get("metadata") or {}
    pn = md.get("delta.columnMapping.physicalName")
    fid = md.get("delta.columnMapping.id")
    assert pn and pn != "extra" and pn.startswith("col-")
    old_ids = [(fields[n].get("metadata") or {})
               .get("delta.columnMapping.id", 0) for n in ("id", "value")]
    assert fid and fid > max(old_ids)
    assert int(m.configuration["delta.columnMapping.maxColumnId"]) >= fid
    # the new column's values read back from the physical name
    got = sorted(session.read_delta(path).collect())
    new = [r for r in got if r[0] >= 100]
    assert [r[2] for r in new] == [0, 1, 2, 3, 4]


def test_rename_partition_column_rejected(session, tmp_path):
    from spark_rapids_tpu.errors import ColumnarProcessingError
    path = str(tmp_path / "t")
    session.create_dataframe({
        "id": np.arange(20, dtype=np.int64),
        "p": (np.arange(20) % 3).astype(np.int64)}).write_delta(
            path, partition_by=["p"])
    dt = session.delta_table(path)
    with pytest.raises(ColumnarProcessingError):
        dt.rename_column("p", "q")


def test_cdf_partitioned_mixed_commit_kinds(session, tmp_path):
    """Code-review r5: cdc-derived and add-derived change tables concat
    positionally — both branches must emit SCHEMA column order even when
    a partition column is not last."""
    path = str(tmp_path / "t")
    session.create_dataframe({
        "p": (np.arange(12) % 2).astype(np.int64),
        "id": np.arange(12, dtype=np.int64),
        "v": np.arange(12, dtype=np.float64)}).write_delta(
            path, partition_by=["p"])
    dt = session.delta_table(path)
    dt.set_properties({"delta.enableChangeDataFeed": "true"})
    v_enabled = dt.version()
    dt.delete(col("id") == lit(3))               # cdc commit
    session.create_dataframe({
        "p": np.array([0], dtype=np.int64),
        "id": np.array([100], dtype=np.int64),
        "v": np.array([5.5])}).write_delta(
            path, mode="append", partition_by=["p"])  # add commit
    changes = dt.table_changes(v_enabled).collect()
    by_type = {}
    for r in changes:
        by_type.setdefault(r[-2], []).append(r)
    assert len(by_type["insert"]) == 1
    assert len(by_type["delete"]) == 1
    # the deleted row's values are coherent (id=3 came from partition 1)
    d = by_type["delete"][0]
    names = [n for n, _ in dt.to_df().schema]
    row = dict(zip(names, d))
    assert row["id"] == 3 and row["p"] == 1 and row["v"] == 3.0
