"""Canonical structural plan fingerprinting (shared by both caches).

One implementation, two variants:

* **full** (``strip_literals=False``) — every non-child attribute of
  every plan node and expression folds in, INCLUDING literal values.
  This is the result-cache key (service/result_cache.py): two plans
  differing in any literal compute different tables and must never
  collide.
* **template** (``strip_literals=True``) — ``Literal`` expression
  nodes contribute only their dtype and null-ness, so distinct-literal
  variants of one query template (``price > 5`` vs ``price > 6``)
  share a fingerprint. This is the executable-cache grouping key
  (plan/executable_cache.py): kernels are keyed structurally
  (``Expression.key``), so template-mates share every compiled program
  whose key is literal-value-free (string-literal predicates, joins,
  aggregates, all shape-dependent kernels); numeric literal values
  trace as XLA constants and keep per-value programs for the
  expressions that contain them.

The two keys diverge EXACTLY on literal values (pinned by
tests/test_serving_latency.py): any other difference changes both.

Correctness over hit rate, everywhere: anything the walk cannot PROVE
structurally stable (a UDF closure, an unknown object with an
address-y repr) raises :class:`Unfingerprintable` and the caller
treats the plan as uncacheable — a miss, never a wrong hit.

The warehouse invalidation epoch lives here too (it versions the
state BOTH caches key against): every catalog mutation, WriteFiles
execution, or Delta/Iceberg commit bumps it; cache entries remember
the epoch they were filled under and stale entries drop on lookup.
"""

from __future__ import annotations

import hashlib
import os
import threading
import weakref
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Invalidation epochs
# ---------------------------------------------------------------------------
#
# Two granularities version the warehouse state the caches key against:
#
# * the GLOBAL epoch — catalog-wide changes (temp-view/table
#   registration, WriteFiles to arbitrary paths) where the affected
#   table set is unknowable; a bump stales EVERY entry;
# * PER-TABLE epochs — a Delta commit names exactly the table it
#   changed (:func:`delta_table_id`), so only entries whose plans READ
#   that table (:func:`plan_table_ids`) go stale, and a hot cache over
#   an unrelated table survives the commit.
#
# Cache entries snapshot the vector they were filled under
# (:func:`epoch_snapshot`) and drop on lookup when any component moved
# (:func:`epochs_current`). Listeners (:func:`register_epoch_listener`)
# observe every bump — the materialized-view registry's refresh trigger
# (streaming/mv.py) rides this hook instead of losing its state.

_EPOCH_LOCK = threading.Lock()
_EPOCH = [0]
_EPOCH_REASON = [""]
_TABLE_EPOCHS: Dict[str, int] = {}
_EPOCH_LISTENERS: List[Callable] = []

#: the global component's key inside an epoch-snapshot dict (never a
#: valid table id — table ids always carry a "<kind>:" prefix)
GLOBAL_EPOCH_KEY = ""


def invalidation_epoch() -> int:
    with _EPOCH_LOCK:
        return _EPOCH[0]


def table_epoch(table_id: str) -> int:
    """Current epoch of one table identity (0 until its first bump)."""
    with _EPOCH_LOCK:
        return _TABLE_EPOCHS.get(table_id, 0)


def _notify_listeners(table_id: Optional[str], epoch: int,
                      reason: str) -> None:
    # outside _EPOCH_LOCK: listeners run arbitrary user code (the MV
    # registry marks views stale) and must never deadlock a concurrent
    # epoch read; snapshot under the lock, call without it
    with _EPOCH_LOCK:
        listeners = list(_EPOCH_LISTENERS)
    for fn in listeners:
        try:
            fn(table_id, epoch, reason)
        except Exception:
            pass  # a broken listener must not fail the commit path


def register_epoch_listener(fn: Callable) -> None:
    """Subscribe ``fn(table_id_or_None, new_epoch, reason)`` to every
    epoch bump (``table_id`` is None for global bumps). THE hook for
    maintenance that must react to commits without being dropped by
    them (incremental MV refresh)."""
    with _EPOCH_LOCK:
        if fn not in _EPOCH_LISTENERS:
            _EPOCH_LISTENERS.append(fn)


def unregister_epoch_listener(fn: Callable) -> None:
    with _EPOCH_LOCK:
        try:
            _EPOCH_LISTENERS.remove(fn)
        except ValueError:
            pass


def bump_invalidation_epoch(reason: str = "") -> int:
    """Catalog-wide state changed (temp-view or table registration,
    WriteFiles, schema mutation): every currently cached result — and
    every cached executable whose scans may now read different bytes —
    is stale. Called by the session's write detection and the SQL
    catalog's mutators; Delta commits use the table-scoped
    :func:`bump_table_epoch` instead."""
    with _EPOCH_LOCK:
        _EPOCH[0] += 1
        _EPOCH_REASON[0] = reason
        new = _EPOCH[0]
    _notify_listeners(None, new, reason)
    return new


def bump_table_epoch(table_id: str, reason: str = "") -> int:
    """ONE table's state changed (a Delta commit): entries whose plans
    read ``table_id`` are stale; everything else keeps serving. The
    global epoch does not move."""
    with _EPOCH_LOCK:
        _TABLE_EPOCHS[table_id] = _TABLE_EPOCHS.get(table_id, 0) + 1
        new = _TABLE_EPOCHS[table_id]
    _notify_listeners(table_id, new, reason)
    return new


def delta_table_id(table_path: str) -> str:
    """Canonical epoch identity of a Delta table (path-normalized so
    the commit path and the scan walk agree on relative paths)."""
    return "delta:" + os.path.abspath(table_path)


def plan_table_ids(plan) -> frozenset:
    """The epoch-scoped table identities a plan reads: every node
    carrying a ``table_path`` (DeltaScanNode and the other
    log-backed scans). File scans and in-memory tables key structurally
    through the fingerprint itself, so only the global epoch governs
    them."""
    ids = set()
    stack = [plan]
    while stack:
        n = stack.pop()
        tp = getattr(n, "table_path", None)
        if isinstance(tp, str) and tp:
            ids.add(delta_table_id(tp))
        stack.extend(getattr(n, "children", ()))
    return frozenset(ids)


def epoch_snapshot(table_ids: Iterable[str] = ()) -> Dict[str, int]:
    """One atomic view of the global epoch plus the named tables'
    epochs — what a cache entry remembers it was filled under."""
    with _EPOCH_LOCK:
        snap = {GLOBAL_EPOCH_KEY: _EPOCH[0]}
        for t in table_ids:
            snap[t] = _TABLE_EPOCHS.get(t, 0)
    return snap


def epochs_current(snap: Dict[str, int]) -> bool:
    """Is a remembered epoch snapshot still the live state? False as
    soon as ANY component (global or per-table) moved."""
    with _EPOCH_LOCK:
        for k, v in snap.items():
            cur = _EPOCH[0] if k == GLOBAL_EPOCH_KEY \
                else _TABLE_EPOCHS.get(k, 0)
            if cur != v:
                return False
    return True


# ---------------------------------------------------------------------------
# Plan fingerprinting
# ---------------------------------------------------------------------------


class Unfingerprintable(Exception):
    """Internal: the plan holds state the fingerprinter cannot prove
    structurally stable. The query runs uncached."""


#: lazily resolved (datetime, np, T, HostTable, Expression, PlanNode,
#: Literal) — module-level import would pull the whole plan layer at
#: package import; resolving on first fingerprint keeps the module
#: importable standalone while the hot path pays one tuple unpack
_FP_TYPES = None


#: conf key prefixes that cannot change a query's RESULT — observability
#: and service knobs are excluded from the result-cache fingerprint so
#: flipping the event log on does not cold the cache. Everything else
#: folds in.
RESULT_NEUTRAL_PREFIXES = (
    "spark.rapids.sql.eventLog.",
    "spark.rapids.trace.",
    "spark.rapids.profile.",
    "spark.rapids.sql.metrics.level",
    "spark.rapids.sql.lore.",
    "spark.rapids.sql.explain",
    "spark.rapids.sql.planVerify.mode",
    "spark.rapids.service.",
    "spark.rapids.streaming.",
    # the lock witness wraps lock ACQUISITION bookkeeping only — query
    # results are byte-identical with it armed
    "spark.rapids.lint.",
    # fetch mechanics only — the root transition's flag is re-set per
    # query, results and the converted tree are byte-identical
    "spark.rapids.sql.asyncResultFetch",
    "spark.rapids.sql.executableCache.",
)

#: conf key prefixes that cannot change the CONVERTED EXECUTABLE. A
#: strict subset of the result-neutral set: lore dump ids rewrite the
#: tree (_TeeChild wrappers) and planVerify.mode decides whether the
#: tree was proven, so both fold into the executable-cache key even
#: though they cannot change results.
EXECUTABLE_NEUTRAL_PREFIXES = (
    "spark.rapids.sql.eventLog.",
    "spark.rapids.trace.",
    "spark.rapids.profile.",
    "spark.rapids.sql.metrics.level",
    "spark.rapids.sql.explain",
    "spark.rapids.service.",
    "spark.rapids.streaming.",
    "spark.rapids.lint.",
    "spark.rapids.sql.asyncResultFetch",
    "spark.rapids.sql.executableCache.",
)

#: identity tokens for in-memory source tables: a HostTable object IS
#: its data (tables are immutable after construction), so identity is a
#: sound cache key — and the weak keying means a collected table can
#: never alias a new one's token
_TABLE_TOKENS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_TABLE_TOKEN_LOCK = threading.Lock()
_TABLE_TOKEN_SEQ = [0]


def _table_token(table) -> str:
    with _TABLE_TOKEN_LOCK:
        tok = _TABLE_TOKENS.get(table)
        if tok is None:
            _TABLE_TOKEN_SEQ[0] += 1
            tok = f"tbl#{_TABLE_TOKEN_SEQ[0]}"
            _TABLE_TOKENS[table] = tok
        return tok


def _resolve_types():
    global _FP_TYPES
    if _FP_TYPES is None:
        import datetime

        import numpy as np

        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.columnar import HostTable
        from spark_rapids_tpu.ops.expr import Expression, Literal
        from spark_rapids_tpu.plan.nodes import PlanNode
        _FP_TYPES = (datetime, np, T, HostTable, Expression, PlanNode,
                     Literal)
    return _FP_TYPES


def _fp_value(obj, depth: int = 0, strip_literals: bool = False) -> str:
    """One value's canonical token. Raises Unfingerprintable for
    anything that cannot be proven stable."""
    # deferred-but-cached: fingerprinting runs on the service's submit
    # hot path, once per attribute of every plan node — resolve the
    # type anchors once per process, not per call
    datetime, np, T, HostTable, Expression, PlanNode, Literal = \
        _resolve_types()

    if depth > 64:
        raise Unfingerprintable("plan too deep to fingerprint")
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return f"{type(obj).__name__}:{obj!r}"
    if isinstance(obj, (datetime.date, datetime.datetime)):
        return f"dt:{obj.isoformat()}"
    if isinstance(obj, T.DataType):
        return f"type:{obj}"
    if isinstance(obj, HostTable):
        return _fp_value_table(obj)
    if isinstance(obj, (Expression, PlanNode)) or \
            type(obj).__module__.startswith("spark_rapids_tpu."):
        # generic structural walk over instance state — plan nodes,
        # expressions, and plain engine data holders (SortOrder,
        # WindowSpec, ...). Unlike .key() (which drops string literal
        # VALUES because the compile cache doesn't need them) or
        # __repr__ (which some subclasses leave at the children-only
        # default), this captures EVERY non-child attribute, so two
        # nodes differing in any parameter can never collide; state the
        # walk cannot prove stable (closures, device arrays) raises
        # Unfingerprintable and the plan just never caches
        return _fp_node(obj, depth + 1, strip_literals)
    if isinstance(obj, np.generic):
        return f"np:{obj.dtype}:{obj!r}"
    if isinstance(obj, np.ndarray):
        if obj.dtype == object:
            raise Unfingerprintable("object ndarray in plan state")
        return (f"nd:{obj.dtype}:{obj.shape}:"
                f"{hashlib.sha1(np.ascontiguousarray(obj).tobytes()).hexdigest()}")
    if isinstance(obj, dict):
        items = sorted((str(k), _fp_value(v, depth + 1, strip_literals))
                       for k, v in obj.items())
        return "dict{" + ",".join(f"{k}={v}" for k, v in items) + "}"
    if isinstance(obj, (list, tuple)):
        return ("seq[" +
                ",".join(_fp_value(v, depth + 1, strip_literals)
                         for v in obj) + "]")
    if isinstance(obj, (set, frozenset)):
        return ("set{" +
                ",".join(sorted(_fp_value(v, depth + 1, strip_literals)
                                for v in obj)) +
                "}")
    raise Unfingerprintable(
        f"{type(obj).__name__} in plan state is not fingerprintable")


def _fp_value_table(table) -> str:
    return f"table:{_table_token(table)}"


#: per-node attributes that never affect results (caches, back-refs;
#: the session conf folds into the fingerprint separately)
_SKIP_ATTRS = {"_session", "_table", "conf", "_conf"}


def _fp_node(node, depth: int = 0, strip_literals: bool = False) -> str:
    """Canonical token of one plan node or expression: class name +
    every non-child attribute's token (sorted by name) + children in
    order. With ``strip_literals``, a ``Literal`` contributes only its
    dtype and null-ness — the one place the template and full
    fingerprints are allowed to differ."""
    Literal = _resolve_types()[6]
    if strip_literals and isinstance(node, Literal):
        return (f"(Literal;dtype=type:{node.data_type};"
                f"null={node.value is None})[]")
    parts = [type(node).__name__]
    try:
        state = vars(node)
    except TypeError:  # __slots__ object; nothing generic to prove
        raise Unfingerprintable(
            f"{type(node).__name__} has no inspectable state")
    for name in sorted(state):
        if name in _SKIP_ATTRS or name == "children":
            continue
        value = state[name]
        if callable(value) and not isinstance(value, type):
            raise Unfingerprintable(
                f"{type(node).__name__}.{name} holds a callable")
        parts.append(
            f"{name}={_fp_value(value, depth + 1, strip_literals)}")
    kids = ",".join(_fp_node(c, depth + 1, strip_literals)
                    for c in getattr(node, "children", ()))
    return "(" + ";".join(parts) + ")[" + kids + "]"


def fingerprint(plan, conf, *, strip_literals: bool = False,
                neutral_prefixes: Tuple[str, ...] = RESULT_NEUTRAL_PREFIXES,
                ) -> Optional[str]:
    """Canonical fingerprint of (bound plan, result-affecting conf), or
    None when the plan is uncacheable (side-effecting WriteFiles nodes,
    UDF closures, unfingerprintable state)."""
    from spark_rapids_tpu.plan.nodes import WriteFiles

    stack = [plan]
    while stack:
        n = stack.pop()
        if isinstance(n, WriteFiles):
            return None  # side effects never cache
        stack.extend(getattr(n, "children", ()))
    try:
        plan_tok = _fp_node(plan, 0, strip_literals)
    except Unfingerprintable:
        return None
    conf_items = sorted(
        (k, str(v)) for k, v in conf.to_dict().items()
        if not any(k.startswith(p) or k == p.rstrip(".")
                   for p in neutral_prefixes))
    h = hashlib.sha1()
    h.update(plan_tok.encode())
    h.update(repr(conf_items).encode())
    # mesh identity (parallel/mesh.py): shape/axes/device ids of the
    # ACTIVE mesh fold in beyond the spark.rapids.mesh.* conf keys
    # above — a backend whose device set changed (reinit after device
    # loss) must not serve plans cached against the old placement
    from spark_rapids_tpu.parallel.mesh import MESH
    h.update(MESH.identity_token().encode())
    # host topology token (runtime/cluster.py): the cluster's declared/
    # lost/excluded host set folds in beyond the spark.rapids.cluster.*
    # conf keys — a plan cached while host h1 was lost (its scans
    # re-landed on survivors) must not serve the full-strength topology
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    h.update(CLUSTER.identity_token().encode())
    # Pallas kernel demotions are runtime state the conf cannot see
    # (the kernels.* conf keys fold in above): a cached tree traced
    # with a kernel embedded must never serve a query after that
    # primitive demoted to HLO, and vice versa
    from spark_rapids_tpu import kernels
    h.update(kernels.demotion_token().encode())
    return h.hexdigest()


def template_fingerprint(plan, conf) -> Optional[str]:
    """THE template key: literal-stripped, executable-neutral-conf
    fingerprint — what the executable cache groups by and the poison
    quarantine strikes against. One definition so the scheduler's
    strike ledger and explain()'s quarantine flag can never key on
    different fingerprints."""
    return fingerprint(plan, conf, strip_literals=True,
                       neutral_prefixes=EXECUTABLE_NEUTRAL_PREFIXES)


def plan_fingerprints(plan, conf) -> Tuple[Optional[str], Optional[str]]:
    """(template_fp, full_fp) for the executable cache: the template is
    literal-stripped and conf-reduced to executable-affecting keys; the
    full print distinguishes literal variants within the template.
    (None, None) for uncacheable plans."""
    template = template_fingerprint(plan, conf)
    if template is None:
        return None, None
    full = fingerprint(plan, conf, strip_literals=False,
                       neutral_prefixes=EXECUTABLE_NEUTRAL_PREFIXES)
    return template, full
