"""External-source provider SPI + Hive UDF tests
(reference: ExternalSource.scala, hiveUDFs.scala — SURVEY.md §2.8)."""

import numpy as np
import pytest

from spark_rapids_tpu import sources
from spark_rapids_tpu import types as T
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.hive_udf import (
    hive_udf,
    register_hive_udf,
    unregister_hive_udf,
)
from spark_rapids_tpu.ops.expr import col


# -- provider SPI ------------------------------------------------------------

def test_builtin_formats_registered():
    fmts = sources.supported_formats()
    for f in ("parquet", "orc", "csv", "json", "avro", "delta",
              "iceberg", "hive"):
        assert f in fmts, f


def test_reader_surface_parquet(session, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    pq.write_table(pa.table({"a": [1, 2, 3]}), tmp_path / "t.parquet")
    df = session.read.format("parquet").load(str(tmp_path / "t.parquet"))
    assert [r[0] for r in df.collect()] == [1, 2, 3]
    # convenience form
    df2 = session.read.parquet(str(tmp_path / "t.parquet"))
    assert df2.count() == 3


def test_reader_routes_delta_through_spi(session, tmp_path):
    d = session.create_dataframe({"x": np.arange(5, dtype=np.int64)})
    d.write_delta(str(tmp_path / "dt"))
    got = session.read.format("delta").load(str(tmp_path / "dt")).collect()
    assert sorted(r[0] for r in got) == [0, 1, 2, 3, 4]


def test_unknown_format_lists_available(session):
    with pytest.raises(ColumnarProcessingError, match="no available source"):
        session.read_format("kudu", "/nope")


def test_graceful_absence_when_module_missing():
    class P(sources.ExternalSourceProvider):
        name = "ghost"
        formats = ("ghost",)
        required_modules = ("module_that_does_not_exist_xyz",)

    sources.register_provider(P())
    try:
        assert sources.provider_for("ghost") is None
        assert "ghost" not in sources.supported_formats()
    finally:
        sources._PROVIDERS.pop("ghost", None)


def test_custom_provider_end_to_end(session):
    """A third-party connector plugs in with one register call."""
    class MemScanProvider(sources.ExternalSourceProvider):
        name = "mem"
        formats = ("mem",)

        def create_scan_node(self, paths, conf, **options):
            from spark_rapids_tpu.columnar import HostTable
            from spark_rapids_tpu.plan.nodes import LocalScan
            t = HostTable.from_pydict(
                {"p": np.array([len(p) for p in paths], dtype=np.int64)})
            return LocalScan([t])

    sources.register_provider(MemScanProvider())
    try:
        df = session.read.format("mem").load("abc", "de")
        assert sorted(r[0] for r in df.collect()) == [2, 3]
    finally:
        sources._PROVIDERS.pop("mem", None)


def test_capability_checked(session):
    class WOnly(sources.ExternalSourceProvider):
        name = "wonly"
        formats = ("wonly",)
        capabilities = frozenset({"write"})

    sources.register_provider(WOnly())
    try:
        with pytest.raises(ColumnarProcessingError, match="does not support"):
            session.read_format("wonly", "/x")
    finally:
        sources._PROVIDERS.pop("wonly", None)


# -- hive UDFs ---------------------------------------------------------------

def _strings_df(s):
    return s.create_dataframe(
        {"s": np.array(["a", "Bc", None, "dEf"], dtype=object),
         "n": np.array([1, 2, 3, 4], dtype=np.int64)})


def test_hive_simple_udf(session, cpu_session):
    register_hive_udf("t_upper",
                      lambda v: v.upper() if v is not None else None,
                      "string")
    try:
        def q(s):
            return _strings_df(s).select(
                "n", hive_udf("t_upper")(col("s")).alias("u"))
        got = sorted(q(session).collect())
        want = sorted(q(cpu_session).collect())
        assert got == want
        assert got[0][1] == "A" and got[2][1] is None
    finally:
        unregister_hive_udf("t_upper")


def test_hive_simple_udf_multi_arg(session):
    register_hive_udf("t_addmul", lambda a, b: a * 10 + b, "long")
    try:
        df = _strings_df(session).select(
            hive_udf("t_addmul")(col("n"), col("n")).alias("r"))
        assert sorted(r[0] for r in df.collect()) == [11, 22, 33, 44]
    finally:
        unregister_hive_udf("t_addmul")


def test_hive_generic_udf(session, cpu_session):
    register_hive_udf("t_len",
                      lambda s: s.str.len().astype("float").fillna(-1.0),
                      "double", generic=True)
    try:
        def q(s):
            return _strings_df(s).select(
                hive_udf("t_len")(col("s")).alias("l"))
        got = sorted(q(session).collect())
        assert got == sorted(q(cpu_session).collect())
        assert got == [[-1.0], [1.0], [2.0], [3.0]] or \
            [r[0] for r in got] == [-1.0, 1.0, 2.0, 3.0]
    finally:
        unregister_hive_udf("t_len")


def test_hive_udf_kill_switch_reports_fallback(session):
    from spark_rapids_tpu.session import TpuSession
    register_hive_udf("t_neg", lambda v: -v, "long")
    try:
        s = TpuSession(
            {"spark.rapids.sql.expression.HiveSimpleUDF": "false"})
        df_expr = hive_udf("t_neg")(col("n")).alias("m")
        d = _strings_df(s).select("n", df_expr)
        plan = d.explain()
        assert "HiveSimpleUDF" in plan and "disabled by conf" in plan
        # fallback still computes correct results on the CPU path
        assert sorted(r[1] for r in d.collect()) == [-4, -3, -2, -1]
    finally:
        unregister_hive_udf("t_neg")


def test_hive_udf_unregistered_name_raises():
    with pytest.raises(ColumnarProcessingError, match="not registered"):
        hive_udf("nope")
