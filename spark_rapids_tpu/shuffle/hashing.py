"""Spark-exact Murmur3 (x86_32) on device.

Reference: the jni Hash kernels (SURVEY.md §2.9 — "murmur3/xxhash64/hiveHash
Spark-exact") and GpuHashPartitioningBase.scala ("murmur3-compatible").
Spark's algorithm is Murmur3_x86_32 with seed 42, hashed column-by-column
with each column's hash seeding the next:

  int/short/byte/bool/date -> hashInt(v)
  long/timestamp           -> hashLong(v)
  float                    -> hashInt(floatToIntBits(f)), -0.0 -> 0.0
  double                   -> hashLong(doubleToLongBits(d)), -0.0 -> 0.0
  string                   -> hashUnsafeBytes(utf8): full 4-byte words get a
                              mix round, then EACH tail byte (sign-extended)
                              gets its own full mix round — Spark's
                              non-standard tail, kept bit-exact.
  null                     -> hash unchanged (seed passes through)

Device mapping: all arithmetic in uint32 lanes on the VPU. String bytes live
in a host-built (dict_size x padded_len) uint8 matrix uploaded once per
dictionary; rows gather their byte row by dictionary code so per-row seeds
work. A numpy mirror of the same algorithm validates the device kernels.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T

C1 = 0xCC9E2D51
C2 = 0x1B873593
SPARK_SEED = 42


# -- device (jnp, uint32) ---------------------------------------------------

def _rotl(x, r):
    return (x << r) | (x >> (32 - r))


def _mix_k1(k1):
    k1 = (k1 * C1).astype(jnp.uint32)
    k1 = _rotl(k1, 15)
    return (k1 * C2).astype(jnp.uint32)


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13)
    return (h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)).astype(jnp.uint32)


def _fmix(h1, length):
    h1 = h1 ^ length.astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 16)
    h1 = (h1 * jnp.uint32(0x85EBCA6B)).astype(jnp.uint32)
    h1 = h1 ^ (h1 >> 13)
    h1 = (h1 * jnp.uint32(0xC2B2AE35)).astype(jnp.uint32)
    return h1 ^ (h1 >> 16)


def _hash_int(v_u32, seed_u32):
    return _fmix(_mix_h1(seed_u32, _mix_k1(v_u32)), jnp.full_like(seed_u32, 4))


def _hash_long(v_i64, seed_u32):
    low = (v_i64 & 0xFFFFFFFF).astype(jnp.uint32)
    high = ((v_i64 >> 32) & 0xFFFFFFFF).astype(jnp.uint32)
    h1 = _mix_h1(seed_u32, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, jnp.full_like(seed_u32, 8))


def _float_bits(data):
    data = jnp.where(data == 0.0, jnp.zeros_like(data), data)  # -0.0 -> 0.0
    if data.dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(data, jnp.int32)
    return jax.lax.bitcast_convert_type(data, jnp.int64)


def _hash_string_bytes(byte_rows, lengths, seed_u32):
    """murmur3 over per-row byte sequences.

    byte_rows: (n, L) uint8 with L a static multiple of 4 (zero-padded);
    lengths:   (n,) int32 actual byte lengths;
    Spark tail semantics: bytes beyond the last aligned word are hashed one
    by one as SIGN-EXTENDED ints, each with a full mix round."""
    n, L = byte_rows.shape
    h1 = seed_u32
    aligned = (lengths // 4) * 4
    for w in range(L // 4):
        base = w * 4
        b = byte_rows[:, base:base + 4].astype(jnp.uint32)
        word = b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16) | (b[:, 3] << 24)
        h1_next = _mix_h1(h1, _mix_k1(word))
        h1 = jnp.where(base + 4 <= aligned, h1_next, h1)
    for i in range(3):  # tail is at most 3 bytes
        pos = jnp.clip(aligned + i, 0, L - 1)
        byte = jnp.take_along_axis(byte_rows, pos[:, None], axis=1)[:, 0]
        signed = byte.astype(jnp.int8).astype(jnp.int32)
        h1_next = _mix_h1(h1, _mix_k1(signed.astype(jnp.uint32)))
        h1 = jnp.where(aligned + i < lengths, h1_next, h1)
    return _fmix(h1, lengths.astype(jnp.uint32))


def murmur3_hash_device(cols: List[Tuple[object, object, T.DataType]],
                        seed: int = SPARK_SEED,
                        string_bytes: Optional[dict] = None):
    """Row hash over multiple columns (inside jit).

    cols: list of (data, validity, DataType); for STRING columns data is the
    code array and string_bytes[i] = (byte_matrix, length_vector) built from
    the dictionary (host prep, uploaded as aux).
    Returns int32 hashes (Spark's hash() value)."""
    n = cols[0][0].shape[0]
    h = jnp.full(n, seed, dtype=jnp.uint32)
    for i, (data, validity, dt) in enumerate(cols):
        if isinstance(dt, T.StringType):
            byte_matrix, len_vec = string_bytes[i]
            codes = jnp.clip(data, 0, byte_matrix.shape[0] - 1)
            rows = byte_matrix[codes]
            lengths = len_vec[codes]
            nh = _hash_string_bytes(rows, lengths, h)
        elif T.is_dec128(dt):
            # ENGINE convention (diverges from Spark's byte-array hash of
            # p>18 decimals, which is row-variable-length): hash the two
            # limbs as two longs — both engine paths agree, and partition
            # ASSIGNMENT never changes query results
            nh = _hash_long(data[:, 1], _hash_long(data[:, 0], h))
        elif isinstance(dt, (T.LongType, T.TimestampType)) or \
                (isinstance(dt, T.DecimalType)):
            nh = _hash_long(data.astype(jnp.int64), h)
        elif isinstance(dt, T.DoubleType):
            nh = _hash_long(_float_bits(data), h)
        elif isinstance(dt, T.FloatType):
            nh = _hash_int(_float_bits(data).astype(jnp.uint32), h)
        elif isinstance(dt, T.BooleanType):
            nh = _hash_int(data.astype(jnp.uint32), h)
        else:  # byte/short/int/date: int widening
            nh = _hash_int(data.astype(jnp.int32).astype(jnp.uint32), h)
        h = jnp.where(validity, nh, h)  # null: seed passes through
    return h.astype(jnp.int32)


def string_dict_bytes(dictionary: np.ndarray, max_bytes: int = 1 << 16
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Host prep: encode a string dictionary as a (d, L) uint8 matrix +
    lengths, L padded to a multiple of 4."""
    if dictionary is None or len(dictionary) == 0:
        return np.zeros((1, 4), dtype=np.uint8), np.zeros(1, dtype=np.int32)
    encoded = [s.encode("utf-8") if s is not None else b"" for s in dictionary]
    lens = np.array([len(b) for b in encoded], dtype=np.int32)
    # power-of-two width so varying max-string-lengths share compiled traces
    L = 4
    while L < int(lens.max()):
        L <<= 1
    if L > max_bytes:
        raise ValueError(f"string too long for device hash: {lens.max()} bytes")
    mat = np.zeros((len(encoded), L), dtype=np.uint8)
    for i, b in enumerate(encoded):
        mat[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
    return mat, lens


# -- numpy mirror (validation + host-side hashing) --------------------------

def _np_u32(x):
    return np.uint32(int(x) & 0xFFFFFFFF)


def _np_mix_k1(k1):
    k1 = np.uint32((int(k1) * C1) & 0xFFFFFFFF)
    k1 = np.uint32(((int(k1) << 15) | (int(k1) >> 17)) & 0xFFFFFFFF)
    return np.uint32((int(k1) * C2) & 0xFFFFFFFF)


def _np_mix_h1(h1, k1):
    h1 = np.uint32(int(h1) ^ int(k1))
    h1 = np.uint32(((int(h1) << 13) | (int(h1) >> 19)) & 0xFFFFFFFF)
    return np.uint32((int(h1) * 5 + 0xE6546B64) & 0xFFFFFFFF)


def _np_fmix(h1, length):
    h1 = int(h1) ^ length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & 0xFFFFFFFF
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & 0xFFFFFFFF
    h1 ^= h1 >> 16
    return np.uint32(h1)


def _np_hash_int(v, seed):
    return _np_fmix(_np_mix_h1(seed, _np_mix_k1(_np_u32(v))), 4)


def _np_hash_long(v, seed):
    v = int(np.int64(v))
    low = _np_u32(v)
    high = _np_u32((v >> 32))
    h1 = _np_mix_h1(seed, _np_mix_k1(low))
    h1 = _np_mix_h1(h1, _np_mix_k1(high))
    return _np_fmix(h1, 8)


def _dec128_twos_complement_bytes(v: int) -> bytes:
    """java.math.BigInteger.toByteArray(): minimal-length big-endian
    two's complement."""
    if v == 0:
        return b"\x00"
    # BigInteger.bitLength() is the MINIMAL two's-complement length
    # excluding the sign bit: for negatives that is (~v).bit_length()
    # (e.g. -128 encodes as one byte 0x80, not 0xff80)
    bitlen = (~v).bit_length() if v < 0 else v.bit_length()
    length = bitlen // 8 + 1
    return v.to_bytes(length, byteorder="big", signed=True)


def _np_hash_bytes(b: bytes, seed):
    h1 = np.uint32(seed)
    aligned = len(b) - len(b) % 4
    for i in range(0, aligned, 4):
        word = int.from_bytes(b[i:i + 4], "little")
        h1 = _np_mix_h1(h1, _np_mix_k1(np.uint32(word)))
    for i in range(aligned, len(b)):
        byte = b[i] - 256 if b[i] >= 128 else b[i]  # signed
        h1 = _np_mix_h1(h1, _np_mix_k1(_np_u32(byte)))
    return _np_fmix(h1, len(b))


def murmur3_hash_host(values: List[Tuple[object, bool, T.DataType]],
                      seed: int = SPARK_SEED) -> int:
    """One ROW's hash on host (oracle for tests / CPU partitioner path)."""
    h = np.uint32(seed)
    for v, valid, dt in values:
        if not valid:
            continue
        if isinstance(dt, T.StringType):
            h = _np_hash_bytes(str(v).encode("utf-8"), h)
        elif T.is_dec128(dt):
            # Spark-exact: murmur3 over the unscaled BigInteger's
            # minimal big-endian two's-complement bytes
            # (HashExpression.scala decimal precision > 18 case). The
            # DEVICE partitioner hashes the two limbs as longs instead
            # (row-variable byte lengths don't map to static shapes);
            # partition assignment never changes results, and the
            # user-visible hash() expression over dec128 falls back to
            # THIS Spark-exact path
            h = _np_hash_bytes(_dec128_twos_complement_bytes(int(v)), h)
        elif isinstance(dt, (T.LongType, T.TimestampType, T.DecimalType)):
            h = _np_hash_long(v, h)
        elif isinstance(dt, T.DoubleType):
            d = 0.0 if v == 0.0 else float(v)
            h = _np_hash_long(np.float64(d).view(np.int64), h)
        elif isinstance(dt, T.FloatType):
            f = 0.0 if v == 0.0 else float(v)
            h = _np_hash_int(np.float32(f).view(np.int32), h)
        elif isinstance(dt, T.BooleanType):
            h = _np_hash_int(1 if v else 0, h)
        else:
            h = _np_hash_int(int(v), h)
    return int(np.int32(h))
