"""Incrementally-maintained materialized views.

Register a plan as an MV and the registry keeps a maintained HostTable
current against the Delta tables the plan reads. Table-scoped
invalidation epochs (plan/fingerprint.bump_table_epoch, fired from
DeltaLog.commit) are the trigger: a commit to a base table marks the
view stale, and the NEXT read refreshes it by *delta recomputation* —
running the plan over the table's CDF rows since the view's last epoch —
instead of recomputing from scratch:

* ``append``  — (Project|Filter)* over one Delta scan, insert-only delta:
  run the chain over just the change rows and append.
* ``reaggregate`` — Aggregate over such a chain with plain-column keys:
  find the group keys the delta touches, recompute ONLY those groups
  against the new snapshot (a filtered run of the original plan), and
  splice them over the maintained rows. Per-group accumulation order is
  the scan order either way, so the incremental result is bit-identical
  to a full recompute at the same epoch.
* ``full``    — everything else (joins, renamed keys, non-insert deltas
  when appending, too many touched groups): recompute at the target
  version. The chosen strategy and any fallback reason surface in
  ``explain()``.

MV maintenance deliberately does NOT touch the service result cache —
the epoch API is the only coupling (lint rule RL-MV-EPOCH enforces it).
"""

from __future__ import annotations

import copy
import threading
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.columnar.table import HostTable
from spark_rapids_tpu.conf import (
    STREAMING_MV_INCREMENTAL,
    STREAMING_MV_MAX_TOUCHED_GROUPS,
)
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.plan.fingerprint import (
    plan_table_ids,
    register_epoch_listener,
    unregister_epoch_listener,
)
from spark_rapids_tpu.streaming.metrics import STREAM_METRICS
from spark_rapids_tpu.lockorder import ordered_lock

__all__ = ["MaterializedView", "MaterializedViewRegistry"]


def _clone_with_children(node, children: tuple):
    """Shallow-copy a plan node onto replacement children. Sound because
    every replacement child preserves the original child's output
    schema, so bound expressions (ordinals) stay valid."""
    new = copy.copy(node)
    new.children = tuple(children)
    return new


def _rebuild_chain(chain: List, leaf):
    """Re-root a (Project|Filter)* chain (outermost first) onto ``leaf``."""
    node = leaf
    for op in reversed(chain):
        node = _clone_with_children(op, (node,))
    return node


class MaterializedView:
    """One registered view; refreshed under its own lock."""

    def __init__(self, name: str, plan, session):
        from spark_rapids_tpu.delta.table import DeltaScanNode
        from spark_rapids_tpu.plan import nodes as P

        self.name = name
        self.plan = plan
        self.session = session
        self.table_ids = plan_table_ids(plan)
        if not self.table_ids:
            raise ColumnarProcessingError(
                f"materialized view {name!r} reads no Delta table; "
                "register a plan with at least one Delta scan")
        self._refresh_lock = ordered_lock("streaming.mv.refresh")
        self._stale = threading.Event()
        self._stale.set()
        self.table: Optional[HostTable] = None
        #: per-base-table Delta version the maintained table reflects
        self.versions: Dict[str, int] = {}
        self.refreshes = 0
        self.incremental_refreshes = 0
        self.full_recomputes = 0
        self.last_refresh_mode = "none"
        self.fallback_reason: Optional[str] = None

        # -- strategy detection (by plan shape) ---------------------------
        self.strategy = "full"
        self._chain: List = []
        self._scan = None
        self._agg = None
        node, chain = plan, []
        while isinstance(node, (P.Project, P.Filter)):
            chain.append(node)
            node = node.children[0]
        if isinstance(node, DeltaScanNode):
            self.strategy, self._chain, self._scan = "append", chain, node
        elif isinstance(node, P.Aggregate) and not chain:
            agg, inner, chain2 = node, node.children[0], []
            while isinstance(inner, (P.Project, P.Filter)):
                chain2.append(inner)
                inner = inner.children[0]
            from spark_rapids_tpu.ops.expr import BoundReference
            keys_ok = bool(agg.grouping) and all(
                isinstance(g, BoundReference) for g in agg.grouping)
            if isinstance(inner, DeltaScanNode) and keys_ok:
                self.strategy = "reaggregate"
                self._chain, self._scan, self._agg = chain2, inner, agg
            else:
                self.fallback_reason = (
                    "aggregate keys are not plain columns" if
                    isinstance(inner, DeltaScanNode)
                    else "aggregate input is not a Delta scan chain")
        else:
            self.fallback_reason = "plan shape outside the incremental whitelist"

    # -- epoch bookkeeping ---------------------------------------------------
    @property
    def stale(self) -> bool:
        return self._stale.is_set()

    def mark_stale(self) -> None:
        self._stale.set()

    def epoch(self) -> int:
        """The maintained table's epoch: the newest base-table version it
        reflects (single-table views have exactly one)."""
        return max(self.versions.values()) if self.versions else -1

    def _base_paths(self) -> List[str]:
        import os
        return [tid[len("delta:"):] if tid.startswith("delta:") else tid
                for tid in sorted(self.table_ids)] if self._scan is None \
            else [os.path.abspath(self._scan.table_path)]

    # -- refresh -------------------------------------------------------------
    def refresh(self) -> str:
        """Bring the maintained table to the base tables' current
        versions; returns the refresh mode used (``"noop"`` when already
        current)."""
        from spark_rapids_tpu.delta.log import DeltaLog
        with self._refresh_lock:
            targets = {p: DeltaLog(p).latest_version()
                       for p in self._base_paths()}
            if (self.table is not None
                    and targets == self.versions and not self.stale):
                return "noop"
            self._stale.clear()
            if targets == self.versions and self.table is not None:
                return "noop"
            mode = self._refresh_locked(targets)
            self.versions = targets
            self.refreshes += 1
            STREAM_METRICS.add("mvRefreshes", 1)
            if mode.startswith("incremental"):
                self.incremental_refreshes += 1
                STREAM_METRICS.add("mvIncrementalRefreshes", 1)
            else:
                self.full_recomputes += 1
                STREAM_METRICS.add("mvFullRecomputes", 1)
            self.last_refresh_mode = mode
            return mode

    def _refresh_locked(self, targets: Dict[str, int]) -> str:
        incremental_on = STREAMING_MV_INCREMENTAL.get(self.session.conf)
        if (self.table is None or self.strategy == "full"
                or not incremental_on):
            if self.table is not None and self.strategy != "full" \
                    and not incremental_on:
                self.fallback_reason = \
                    "spark.rapids.streaming.mv.incremental.enabled=false"
            return self._full_recompute(targets)
        base = self._base_paths()[0]
        lo, hi = self.versions.get(base, -1) + 1, targets[base]
        try:
            changes = self._collect_changes(base, lo, hi)
        except ColumnarProcessingError as e:
            self.fallback_reason = f"CDF unavailable: {e}"
            return self._full_recompute(targets)
        if self.strategy == "append":
            return self._refresh_append(changes, targets)
        return self._refresh_reaggregate(changes, targets)

    def _collect_changes(self, base: str, lo: int, hi: int) -> HostTable:
        from spark_rapids_tpu.delta.commands import DeltaTable
        df = DeltaTable(self.session, base).table_changes(lo, hi)
        return self.session.execute(df.plan)

    def _project_to_scan_schema(self, changes: HostTable) -> HostTable:
        names = [n for n, _ in self._scan.output_schema()]
        return HostTable(names, [changes.column(n) for n in names])

    def _run(self, plan) -> HostTable:
        return self.session.execute(plan)

    def _full_recompute(self, targets: Dict[str, int]) -> str:
        self.session.stage_stream_delta("mvRefreshes")
        self.session.stage_stream_delta("mvFullRecomputes")
        self.table = self._run(self._pinned_plan(targets))
        return "full-recompute"

    def _pinned_plan(self, targets: Dict[str, int]):
        """The registered plan with every Delta scan replaced by a fresh
        scan pinned at the target version (also the bit-identity oracle:
        pin at ``self.versions`` to recompute the CURRENT epoch)."""
        import os

        from spark_rapids_tpu.delta.table import DeltaScanNode

        def rebuild(node):
            if isinstance(node, DeltaScanNode):
                return DeltaScanNode(
                    node.table_path, node.conf,
                    version_as_of=targets[os.path.abspath(node.table_path)],
                    columns=node.columns)
            kids = tuple(rebuild(c) for c in getattr(node, "children", ()))
            return _clone_with_children(node, kids) if kids else node

        return rebuild(self.plan)

    def recompute_at_epoch(self) -> HostTable:
        """From-scratch recompute at the maintained epoch (does not touch
        the maintained table) — the tests' bit-identity oracle."""
        with self._refresh_lock:
            return self._run(self._pinned_plan(dict(self.versions)))

    # -- append strategy -----------------------------------------------------
    def _refresh_append(self, changes: HostTable,
                        targets: Dict[str, int]) -> str:
        from spark_rapids_tpu.plan import nodes as P
        kinds = set(changes.column("_change_type").to_pylist())
        if kinds - {"insert"}:
            self.fallback_reason = \
                f"non-insert changes for append view: {sorted(kinds)}"
            return self._full_recompute(targets)
        if changes.num_rows:
            self.session.stage_stream_delta("mvRefreshes")
            self.session.stage_stream_delta("mvIncrementalRefreshes")
            leaf = P.LocalScan([self._project_to_scan_schema(changes)])
            delta = self._run(_rebuild_chain(self._chain, leaf))
            self.table = HostTable.concat([self.table, delta])
        return "incremental-append"

    # -- reaggregate strategy ------------------------------------------------
    def _key_source_columns(self) -> List[str]:
        child_schema = self._agg.children[0].output_schema()
        return [child_schema[g.ordinal][0] for g in self._agg.grouping]

    def _touched_keys(self, changes: HostTable) -> Set[Tuple]:
        """Distinct group-key tuples the delta touches, AFTER the chain
        below the aggregate (its filters decide group membership; a
        deleted row's key still lands here because its values evaluate
        the same predicates they passed when inserted)."""
        from spark_rapids_tpu.plan import nodes as P
        if not changes.num_rows:
            return set()
        leaf = P.LocalScan([self._project_to_scan_schema(changes)])
        filtered = self._run(_rebuild_chain(self._chain, leaf))
        cols = [filtered.column(n).to_pylist()
                for n in self._key_source_columns()]
        return set(zip(*cols)) if cols else set()

    def _refresh_reaggregate(self, changes: HostTable,
                             targets: Dict[str, int]) -> str:
        from spark_rapids_tpu.ops.expr import col, lit
        from spark_rapids_tpu.plan import nodes as P
        touched = self._touched_keys(changes)
        if not touched:
            return "incremental-reaggregate"
        max_groups = STREAMING_MV_MAX_TOUCHED_GROUPS.get(self.session.conf)
        if len(touched) > max_groups:
            self.fallback_reason = (
                f"{len(touched)} touched groups > "
                f"spark.rapids.streaming.mv.maxTouchedGroups={max_groups}")
            return self._full_recompute(targets)
        # recompute ONLY the touched groups against the new snapshot:
        # scan@target -> chain -> keep touched keys -> original aggregate
        base = self._base_paths()[0]
        key_cols = self._key_source_columns()
        pred = None
        for tup in sorted(touched, key=repr):
            conj = None
            for c, v in zip(key_cols, tup):
                term = col(c) == lit(v)
                conj = term if conj is None else (conj & term)
            pred = conj if pred is None else (pred | conj)
        pinned = self._pinned_plan({base: targets[base]})
        # pinned is Aggregate over chain over fresh scan; splice the
        # touched-keys filter between aggregate and its input
        agg_in = pinned.children[0]
        self.session.stage_stream_delta("mvRefreshes")
        self.session.stage_stream_delta("mvIncrementalRefreshes")
        recomputed = self._run(_clone_with_children(
            pinned, (P.Filter(agg_in, pred),)))
        self._splice_groups(touched, recomputed)
        return "incremental-reaggregate"

    def _splice_groups(self, touched: Set[Tuple],
                       recomputed: HostTable) -> None:
        """Replace maintained rows whose key is touched with the freshly
        recomputed groups (order: surviving rows keep their order,
        recomputed groups append — MV equality is row-set equality)."""
        key_names = list(self._agg.grouping_names)
        maintained = self.table
        key_lists = [maintained.column(n).to_pylist() for n in key_names]
        keep = [i for i, tup in enumerate(zip(*key_lists))
                if tup not in touched]
        kept = HostTable(maintained.names,
                         [c.take(keep) if hasattr(c, "take")
                          else _take_column(c, keep)
                          for c in maintained.columns])
        self.table = HostTable.concat([kept, recomputed]) \
            if recomputed.num_rows else kept

    # -- serving -------------------------------------------------------------
    def read(self) -> HostTable:
        """Serve the view (refreshing first if stale) THROUGH the session
        so the serve lands in the event log with the view's epoch
        (schema v11 ``mvEpoch``)."""
        from spark_rapids_tpu.plan import nodes as P
        if self.stale or self.table is None:
            self.refresh()
        with self._refresh_lock:
            table, epoch = self.table, self.epoch()
        self.session.next_query_mv_epoch = epoch
        self.session.next_query_tag = f"mv:{self.name}@v{epoch}"
        return self.session.execute(P.LocalScan([table]))

    def explain(self) -> str:
        lines = [
            f"MaterializedView[{self.name}]",
            f"  strategy={self.strategy}"
            + (f" (fallback: {self.fallback_reason})"
               if self.strategy == "full" and self.fallback_reason else ""),
            f"  epoch=v{self.epoch()} stale={self.stale}",
            f"  refreshes={self.refreshes} "
            f"(incremental={self.incremental_refreshes}, "
            f"full={self.full_recomputes})",
            f"  lastRefresh={self.last_refresh_mode}"
            + (f" (fallback: {self.fallback_reason})"
               if self.last_refresh_mode == "full-recompute"
               and self.fallback_reason else ""),
        ]
        return "\n".join(lines)

    def describe(self) -> dict:
        return {
            "name": self.name,
            "kind": "materialized-view",
            "strategy": self.strategy,
            "epoch": self.epoch(),
            "stale": self.stale,
            "refreshes": self.refreshes,
            "incrementalRefreshes": self.incremental_refreshes,
            "fullRecomputes": self.full_recomputes,
            "rows": self.table.num_rows if self.table is not None else 0,
        }


def _take_column(col_, idx: List[int]):
    """Row-subset of a HostColumn by index list (no HostColumn.take)."""
    from spark_rapids_tpu.columnar.column import HostColumn
    vals = col_.to_pylist()
    return HostColumn.from_pylist([vals[i] for i in idx], col_.dtype)


class MaterializedViewRegistry:
    """Named MVs over one session, wired to the table-scoped epoch bus."""

    def __init__(self, session):
        self.session = session
        self._lock = ordered_lock("streaming.mv.registry")
        self._views: Dict[str, MaterializedView] = {}
        register_epoch_listener(self._on_epoch)
        self._closed = False

    def _on_epoch(self, table_id: Optional[str], epoch: int,
                  reason: str) -> None:
        # fired from inside DeltaLog.commit — only MARK here; the
        # refresh itself runs on the next read (or explicit refresh())
        with self._lock:
            views = list(self._views.values())
        for v in views:
            if table_id is None or table_id in v.table_ids:
                v.mark_stale()

    def register(self, name: str, df, refresh: bool = True) \
            -> MaterializedView:
        plan = df.plan if hasattr(df, "plan") else df
        mv = MaterializedView(name, plan, self.session)
        with self._lock:
            if self._closed:
                raise ColumnarProcessingError("MV registry is closed")
            if name in self._views:
                raise ColumnarProcessingError(
                    f"materialized view {name!r} already registered")
            self._views[name] = mv
        if refresh:
            mv.refresh()
        return mv

    def get(self, name: str) -> MaterializedView:
        with self._lock:
            mv = self._views.get(name)
        if mv is None:
            raise ColumnarProcessingError(
                f"no materialized view named {name!r}")
        return mv

    def drop(self, name: str) -> None:
        with self._lock:
            self._views.pop(name, None)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._views)

    def describe(self) -> List[dict]:
        with self._lock:
            views = list(self._views.values())
        return [v.describe() for v in views]

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._views.clear()
        unregister_epoch_listener(self._on_epoch)
