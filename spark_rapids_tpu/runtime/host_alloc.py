"""Host memory arbiter + pinned staging pool.

Reference (SURVEY.md §2.5): ``HostAlloc.scala`` (349 LoC) — a host-memory
arbiter with a configured limit; allocations past the limit first try to
free host memory (spilling the host tier to disk), then block briefly for
other tasks to release, then surface a CPU retry-OOM that the retry
framework handles like a device OOM. ``PinnedMemoryPool`` — fixed-size
pool of transfer staging buffers.

TPU mapping: identical arbiter semantics over Python buffers. The pinned
pool hands out reusable bytearrays for H2D/D2H staging (conf
``spark.rapids.memory.pinnedPool.size``); when exhausted, callers fall
back to unpooled allocation, exactly the reference's behavior."""

from __future__ import annotations

import threading
from typing import Optional

from spark_rapids_tpu.errors import ColumnarProcessingError, CpuRetryOOM
from spark_rapids_tpu.lockorder import ordered_condition, ordered_lock


class HostAllocation:
    """Grant handle; release returns the bytes to the arbiter. Usable as a
    context manager."""

    def __init__(self, arbiter: "HostMemoryArbiter", nbytes: int):
        self.arbiter = arbiter
        self.nbytes = nbytes
        self._released = False

    def release(self):
        if not self._released:
            self._released = True
            self.arbiter._release(self.nbytes)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class HostMemoryArbiter:
    """Process-wide host-memory budget (HostAlloc analog)."""

    _instance: Optional["HostMemoryArbiter"] = None
    _instance_lock = ordered_lock("host_alloc.instance")

    def __init__(self, limit_bytes: int):
        self.limit_bytes = limit_bytes
        self._used = 0
        self._cv = ordered_condition("host_alloc.cv")
        self.alloc_count = 0
        self.blocked_count = 0
        self.spill_triggered_count = 0

    @classmethod
    def get(cls) -> "HostMemoryArbiter":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = HostMemoryArbiter(4 << 30)
            return cls._instance

    @classmethod
    def reset(cls, limit_bytes: int) -> "HostMemoryArbiter":
        with cls._instance_lock:
            cls._instance = HostMemoryArbiter(limit_bytes)
            return cls._instance

    @property
    def used_bytes(self) -> int:
        with self._cv:
            return self._used

    def _release(self, nbytes: int):
        with self._cv:
            self._used -= nbytes
            self._cv.notify_all()

    def _try_free_host_memory(self) -> int:
        """Demote the spill framework's host tier to disk (the arbiter's
        'free some host memory' hook — HostAlloc's spill integration)."""
        from spark_rapids_tpu.runtime.spill import BufferCatalog
        self.spill_triggered_count += 1
        return BufferCatalog.get().spill_host_to_disk()

    def alloc(self, nbytes: int, timeout_s: float = 10.0) -> HostAllocation:
        """Grant ``nbytes`` of host budget. Oversized single requests are
        granted anyway (a single allocation larger than the pool must not
        deadlock — reference behavior); contended requests spill the host
        tier, then wait, then raise CpuRetryOOM."""
        if nbytes < 0:
            raise ColumnarProcessingError("negative host allocation")
        with self._cv:
            self.alloc_count += 1
            if nbytes >= self.limit_bytes:
                # whole-pool+ request: grant standalone (tracked, may push
                # used over limit; concurrent allocs will block until free)
                self._used += nbytes
                return HostAllocation(self, nbytes)
            if self._used + nbytes <= self.limit_bytes:
                self._used += nbytes
                return HostAllocation(self, nbytes)
        # over budget: try to free spillable host memory first
        self._try_free_host_memory()
        with self._cv:
            if self._used + nbytes <= self.limit_bytes:
                self._used += nbytes
                return HostAllocation(self, nbytes)
            self.blocked_count += 1
            ok = self._cv.wait_for(
                lambda: self._used + nbytes <= self.limit_bytes,
                timeout=timeout_s)
            if not ok:
                raise CpuRetryOOM(
                    f"host memory exhausted: want {nbytes}B, "
                    f"{self._used}/{self.limit_bytes}B in use")
            self._used += nbytes
            return HostAllocation(self, nbytes)


class PinnedMemoryPool:
    """Staging-buffer pool for H2D/D2H transfers (PinnedMemoryPool
    analog). Fixed total size; buffers are reusable bytearrays. When the
    pool is exhausted or a request exceeds the buffer size, returns None
    and the caller allocates unpooled (the reference's fallback)."""

    _instance: Optional["PinnedMemoryPool"] = None
    _instance_lock = ordered_lock("pinned_pool.instance")

    def __init__(self, total_bytes: int, buffer_bytes: int = 8 << 20):
        self.buffer_bytes = buffer_bytes
        n = max(total_bytes // buffer_bytes, 0)
        self._free = [bytearray(buffer_bytes) for _ in range(n)]
        self._lock = ordered_lock("pinned_pool")
        self.total_buffers = n
        self.hits = 0
        self.misses = 0

    @classmethod
    def initialize(cls, total_bytes: int,
                   buffer_bytes: int = 8 << 20) -> Optional["PinnedMemoryPool"]:
        with cls._instance_lock:
            if total_bytes <= 0:
                cls._instance = None  # unpooled mode; drop any old pool
            else:
                cls._instance = PinnedMemoryPool(total_bytes, buffer_bytes)
            return cls._instance

    @classmethod
    def get(cls) -> Optional["PinnedMemoryPool"]:
        return cls._instance

    def acquire(self, nbytes: int) -> Optional[bytearray]:
        if nbytes > self.buffer_bytes:
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            if not self._free:
                self.misses += 1
                return None
            self.hits += 1
            return self._free.pop()

    def release(self, buf: bytearray):
        with self._lock:
            if len(self._free) >= self.total_buffers:
                raise ColumnarProcessingError(
                    "double release of pinned buffer")
            self._free.append(buf)
