"""Plan layer: the CPU-side physical plan (the "Spark plan" analog) that the
overrides engine rewrites into TPU execs, plus the DataFrame builder API.

Every node carries a Spark-exact CPU execution path (``execute_cpu``) — this
is simultaneously the per-operator fallback substrate and the test oracle,
playing the role CPU Spark plays for the reference (SURVEY.md §4)."""

from spark_rapids_tpu.plan.nodes import (  # noqa: F401
    PlanNode,
    LocalScan,
    RangeNode,
    Project,
    Filter,
    Aggregate,
    Sort,
    SortOrder,
    Limit,
    Union,
    Join,
    Exchange,
    Expand,
)
from spark_rapids_tpu.plan.dataframe import (  # noqa: F401
    DataFrame,
    range_df,
    from_pydict,
    from_pandas,
    from_host_table,
)
