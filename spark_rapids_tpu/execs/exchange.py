"""Shuffle exchange exec.

Reference (SURVEY.md §3.4): GpuShuffleExchangeExecBase — device partition
split (GpuPartitioning.sliceInternalOnGpuAndClose), serialized write through
the shuffle manager, then the read side's GpuShuffleCoalesceExec concats a
reduce partition's serialized tables ON HOST to the target size before one
device upload (GpuShuffleCoalesceExec.scala:43-229).

The exec yields batches per reduce partition: oversized partitions split
at the batch target; with adaptive coalescing enabled, adjacent
undersized partitions share output batches (so batch count can be far
below the partition count)."""

from __future__ import annotations

from time import perf_counter
from typing import List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceTable, HostTable
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.errors import ColumnarProcessingError, MapOutputLostError
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.runtime.faults import RECOVERY
from spark_rapids_tpu.ops.expr import Expression
from spark_rapids_tpu.shuffle.manager import get_shuffle_manager
from spark_rapids_tpu.shuffle.partitioning import (
    HashPartitioner,
    Partitioner,
    RangePartitioner,
    RoundRobinPartitioner,
    SinglePartitioner,
    split_by_partition,
)


def _pad_capacity(table: DeviceTable, new_cap: int) -> DeviceTable:
    """Extend every column with dead tail rows to ``new_cap`` (flat
    columns only — the ICI exchange's equal-shard requirement for
    non-pow2 partition counts)."""
    import jax.numpy as jnp

    extra = new_cap - table.capacity

    def pad(arr):
        # zeros of a bool dtype are False, so validity/live tails are dead
        tail = jnp.zeros((extra,) + arr.shape[1:], dtype=arr.dtype)
        return jnp.concatenate([arr, tail])

    cols = [c.with_arrays(pad(c.data), pad(c.validity))
            for c in table.columns]
    live = pad(table.live) if table.live is not None else None
    return DeviceTable(table.names, cols, table.nrows_dev, new_cap,
                       live=live)


def ici_requested(conf: RapidsConf) -> bool:
    """Did the session ask for collective shuffles — either the legacy
    ``spark.rapids.shuffle.mode=ICI`` or mesh-native execution
    (``spark.rapids.mesh.enabled``)?"""
    from spark_rapids_tpu.conf import SHUFFLE_MANAGER_MODE
    from spark_rapids_tpu.parallel.mesh import MESH_ENABLED
    return (str(conf.get_entry(SHUFFLE_MANAGER_MODE)).upper() == "ICI"
            or bool(conf.get_entry(MESH_ENABLED)))


def collective_applicable(mode: str, num_partitions: int) -> bool:
    """Whether an exchange of this shape has a collective form AT ALL.
    A single output partition is a gather, not an all-to-all — taking
    the host path there is not a demotion, so it neither counts toward
    hostShuffleFallbacks nor earns a fallback note in explain()."""
    return mode != "single" and num_partitions > 1


def ici_demotion_reason(conf: RapidsConf, mode: str, num_partitions: int,
                        schema) -> Optional[str]:
    """Why an ICI-requested exchange takes the host-file shuffle, or
    None when the collective path will run. STATIC facts only (mode,
    partition count, device count, column dtypes), so the overrides
    tagger surfaces the same reason in explain() that the exec acts on
    at execution (the demotion analog for shuffles: the exchange still
    runs on device, just through the host path). Callers gate on
    ``collective_applicable`` first — shapes with no collective form
    are not demotions."""
    import jax
    from spark_rapids_tpu.parallel.mesh import MESH, suppression_reason
    sup = suppression_reason()
    if sup is not None:
        # the degradation ladder suppressed mesh landing for THIS
        # attempt (partial device loss, retry failed): the collective
        # demotes with the ladder's reason so hostShuffleFallbacks and
        # explain() surface WHY the exchange took the host path
        return sup
    if mode != "hash":
        return (f"{mode} partitioning has no deterministic per-row "
                f"device target; host shuffle computes it row-by-row")
    # ONE atomic snapshot: separate enabled/ndev reads racing a
    # concurrent reconfiguration could see enabled=True then ndev=0
    ndev = MESH.effective_ndev()
    if ndev is None:
        ndev = len(jax.devices())
    if num_partitions > ndev:
        return (f"partition count {num_partitions} exceeds the "
                f"{ndev}-device mesh")
    nested = [n for n, dt in schema
              if isinstance(dt, (T.ArrayType, T.StructType, T.MapType))]
    if nested:
        return (f"nested-type columns ({', '.join(nested[:3])}) have no "
                f"collective-exchangeable device layout")
    return None


def make_partitioner(mode: str, keys: Sequence[Expression],
                     num_partitions: int) -> Partitioner:
    mode = mode.lower()
    if mode == "hash":
        if not keys:
            raise ColumnarProcessingError("hash partitioning requires keys")
        return HashPartitioner(keys, num_partitions)
    if mode == "range":
        return RangePartitioner(keys, num_partitions)
    if mode == "roundrobin":
        return RoundRobinPartitioner(num_partitions)
    if mode == "single":
        return SinglePartitioner()
    raise ColumnarProcessingError(f"unknown partitioning {mode}")


class TpuShuffleExchangeExec(TpuExec):
    def __init__(self, child: TpuExec, mode: str, num_partitions: int,
                 keys: Sequence[Expression], conf: RapidsConf,
                 target_batch_bytes: int = 1 << 30):
        super().__init__()
        self.children = (child,)
        self.mode = mode
        self.num_partitions = 1 if mode == "single" else num_partitions
        self.keys = list(keys)
        self.conf = conf
        self.target_batch_bytes = target_batch_bytes
        #: why an ICI-requested exchange demoted to the host shuffle
        #: (None while on the collective path or when never requested)
        self.ici_fallback_reason: Optional[str] = None

    def output_schema(self):
        return self.children[0].output_schema()

    def describe(self):
        extra = (f", hostShuffleFallback={self.ici_fallback_reason!r}"
                 if self.ici_fallback_reason else "")
        return f"TpuShuffleExchange[{self.mode}, n={self.num_partitions}{extra}]"

    def _aqe_coalesce_enabled(self) -> bool:
        from spark_rapids_tpu.conf import AQE_COALESCE_PARTITIONS
        return bool(self.conf.get_entry(AQE_COALESCE_PARTITIONS))

    def _ici_eligible(self) -> bool:
        """The collective path runs when the session asked for it (ICI
        shuffle mode or mesh-native execution), the partitioning is
        hash, and every partition maps onto one mesh device (SURVEY
        §2.6: 'partitions on one slice -> collective, else host
        shuffle'). Supports EVERY non-nested column type — decimal128's
        two-limb layout rides the collective as a trailing dim — and
        non-pow2 partition counts pad the row capacity up to a multiple
        of the mesh size (_pad_capacity). A requested-but-demoted
        exchange counts hostShuffleFallbacks with the reason surfaced
        in explain() (overrides._tag_exchange notes the same static
        reason this check acts on)."""
        if not ici_requested(self.conf):
            return False
        if not collective_applicable(self.mode, self.num_partitions):
            return False
        reason = ici_demotion_reason(self.conf, self.mode,
                                     self.num_partitions,
                                     self.output_schema())
        if reason is not None:
            from spark_rapids_tpu.parallel.mesh import MESH_SCOPE
            self.ici_fallback_reason = reason
            self.add_metric("hostShuffleFallbacks", 1)
            MESH_SCOPE.add("hostShuffleFallbacks", 1)
            return False
        return True

    #: masked batches share the input buffers, but every downstream
    #: kernel still runs at full input capacity PER partition — beyond
    #: this many partitions the host shuffle's compacted batches win
    LOCAL_SPLIT_MAX_PARTITIONS = 32

    def _local_split_eligible(self) -> bool:
        from spark_rapids_tpu.conf import (
            SHUFFLE_LOCAL_DEVICE_SPLIT,
            SHUFFLE_MANAGER_MODE,
        )
        from spark_rapids_tpu.execs.base import MASKED_ENABLED
        mode = str(self.conf.get_entry(SHUFFLE_MANAGER_MODE)).upper()
        # the device split wins over host-shuffle coalescing when both
        # apply: its per-partition masked VIEWS cost no serialization and
        # downstream group-blind consumers mask-union undersized views
        # back together (columnar/table.merge_split_views) — the same
        # sliver-batch problem AQE coalescing solves, without the stats
        return (mode == "MULTITHREADED"
                and bool(self.conf.get_entry(SHUFFLE_LOCAL_DEVICE_SPLIT))
                and MASKED_ENABLED.get()  # masked-batch kill switch
                and self.mode in ("hash", "roundrobin", "single")
                and self.num_partitions <= self.LOCAL_SPLIT_MAX_PARTITIONS)

    produces_masked = True

    def execute(self):
        # base-contract note: execute() must yield PREFIX batches; the
        # masked local split therefore lives in execute_masked() and
        # mask-unaware callers get compacted tables via the base wrapper
        if self._ici_eligible():
            yield from self._execute_ici()
            return
        if self._local_split_eligible():
            for b in self._execute_local_device_split():
                yield b.compacted()
            return
        yield from self._execute_host_shuffle()

    def execute_masked(self):
        if self._ici_eligible():
            yield from self._execute_ici()
            return
        if self._local_split_eligible():
            yield from self._execute_local_device_split()
            return
        yield from self._execute_host_shuffle()

    def _execute_local_device_split(self):
        """Single-process repartition entirely ON DEVICE: one partition-id
        kernel over the coalesced input, then one MASKED batch per
        partition sharing the input buffers — liveness masks instead of
        per-partition compaction scatters (columnar/table.py
        DeviceTable.live). The reference always round-trips the shuffle
        manager because its executors are separate processes; a
        single-chip engine has no wire to cross."""
        import jax.numpy as jnp

        from spark_rapids_tpu.columnar.table import concat_device
        from spark_rapids_tpu.dispatch import tpu_jit
        from spark_rapids_tpu.ops.expr import shared_traces
        from spark_rapids_tpu.runtime.retry import retry_block

        t0 = perf_counter()
        batches = list(self.children[0].execute_masked())
        if not batches:
            return
        table = retry_block(lambda: concat_device(batches)) \
            if len(batches) > 1 else batches[0]
        parter = make_partitioner(self.mode, self.keys, self.num_partitions)
        nparts = self.num_partitions
        pids = parter.partition_ids(table)
        traces = shared_traces(("localsplit", nparts))
        tkey = (table.capacity, table.live is not None)
        fn = traces.get(tkey)
        if fn is None:
            cap = table.capacity

            def masks(pids, nrows, live_in):
                if live_in is not None:
                    live = live_in
                else:
                    live = jnp.arange(cap, dtype=jnp.int32) < nrows
                outs = []
                for p in range(nparts):
                    m = live & (pids == p)
                    outs.append((m, jnp.sum(m.astype(jnp.int32))))
                return outs

            fn = tpu_jit(masks)
            traces[tkey] = fn
        outs = fn(pids, table.nrows_dev, table.live)
        self.add_metric("localSplitParts", nparts)
        self.add_metric("localSplitTime", perf_counter() - t0)
        from spark_rapids_tpu.columnar.table import mark_shared_view
        split_group = object()  # one token per split: its masks are disjoint
        for mask, cnt in outs:
            out = DeviceTable(table.names, table.columns, cnt,
                              table.capacity, live=mask)
            # coalesce streams capacity-sharing views (and may mask-union
            # same-group views back together for group-blind consumers)
            mark_shared_view(out, split_group)
            yield out

    def _execute_ici(self):
        """ONE all-to-all collective over the device mesh instead of the
        host-file shuffle: coalesce input, evaluate key columns, exchange
        every column's rows to its murmur3 partition's device, emit one
        front-compacted batch per partition (parallel/exchange.py).
        Input shards stay DEVICE-RESIDENT end to end — the only host
        traffic is the per-shard live-count fetch, which doubles as the
        AQE map-output statistic (skew/coalesce decisions see the real
        shard distribution instead of the host path's file sizes)."""
        from spark_rapids_tpu.columnar import DeviceColumn, bucket_for
        from spark_rapids_tpu.columnar.table import concat_device
        from spark_rapids_tpu.ops.expr import compile_project
        from spark_rapids_tpu.parallel.exchange import (
            MeshExchange,
            interned_dict_bytes,
        )
        from spark_rapids_tpu.parallel.mesh import MESH, MESH_SCOPE
        from spark_rapids_tpu.runtime.retry import retry_block

        t0 = perf_counter()
        batches = list(self.children[0].execute())
        if not batches:
            return
        table = retry_block(lambda: concat_device(batches)) \
            if len(batches) > 1 else batches[0]
        ndev = self.num_partitions
        if table.capacity % ndev != 0:
            # non-pow2 partition counts (or tiny tables): pad the row
            # capacity up to a multiple of ndev with dead rows — every
            # column extends with zero/False tails, so the collective's
            # equal per-device shards always exist
            table = _pad_capacity(table, -(-table.capacity // ndev) * ndev)

        key_cols = compile_project(self.keys, table)
        mesh, axis = MESH.exchange_mesh(ndev)
        string_bytes = {}
        for i, c in enumerate(key_cols):
            if isinstance(c.dtype, T.StringType):
                # replicated byte matrix, interned by dictionary
                # identity: repeated exchanges over one dictionary pay
                # the replication upload once
                string_bytes[i] = interned_dict_bytes(c.dictionary, mesh)

        ex = MeshExchange.get(
            mesh,
            tuple(str(c.dtype) for c in table.columns),
            tuple(range(len(key_cols))),
            tuple(c.dtype for c in key_cols),
            tuple(sorted((i, v[0].shape) for i, v in string_bytes.items())),
            table.capacity, axis_name=axis)
        out_d, out_v, counts = ex.run(
            [c.data for c in table.columns],
            [c.validity for c in table.columns],
            [c.data for c in key_cols],
            [c.validity for c in key_cols],
            table.row_mask(),
            string_bytes)
        self.add_metric("iciExchangeTime", perf_counter() - t0)
        self.add_metric("iciPartitions", ndev)
        # exchanged payload bytes (static shapes: no device sync)
        ici_bytes = sum(a.nbytes for a in out_d) + \
            sum(a.nbytes for a in out_v)
        self.add_metric("iciBytes", ici_bytes)
        MESH_SCOPE.add("iciExchanges", 1)
        MESH_SCOPE.add("iciBytes", ici_bytes)

        # AQE exchange statistics from the MEASURED per-shard live
        # counts (MapOutputStatistics analog): rows x packed row bytes
        # approximates per-partition output size, driving the same
        # skew metric the host shuffle records from file sizes
        row_bytes = max(self._packed_row_bytes_for(table), 1)
        live = sorted(int(c) * row_bytes for c in counts if int(c) > 0)
        if live:
            from spark_rapids_tpu.conf import AQE_SKEW_FACTOR
            median = live[len(live) // 2]
            factor = float(self.conf.get_entry(AQE_SKEW_FACTOR))
            skewed = sum(1 for b in live if b > factor * max(median, 1))
            self.add_metric("mapOutputBytesMax", live[-1])
            self.add_metric("mapOutputBytesMedian", median)
            if skewed:
                self.add_metric("skewedPartitions", skewed)

        shard = len(out_d[0]) // ndev if out_d else 0
        for p in range(ndev):
            n = int(counts[p])
            if n == 0:
                continue
            k = min(bucket_for(max(n, 1)), shard)
            cols = []
            for c, d, v in zip(table.columns, out_d, out_v):
                sl = slice(p * shard, p * shard + k)
                cols.append(DeviceColumn(c.dtype, d[sl], v[sl],
                                         dictionary=c.dictionary,
                                         dict_sorted=c.dict_sorted))
            yield DeviceTable(table.names, cols, n, k)

    @staticmethod
    def _packed_row_bytes_for(table: DeviceTable) -> int:
        """Approximate serialized bytes per row of ``table`` (column
        data words + validity) for the AQE map-output statistic."""
        total = 0
        for c in table.columns:
            itemsize = getattr(c.data.dtype, "itemsize", 4)
            if getattr(c.data, "ndim", 1) == 2:
                itemsize *= c.data.shape[1]
            total += itemsize + 1
        return total

    def _shuffle_manager(self):
        """MULTITHREADED -> file-backed manager; P2P -> cached blocks
        served through the client/server transport (UCX-mode analog). Both
        expose the same write/read handle interface."""
        from spark_rapids_tpu.conf import SHUFFLE_MANAGER_MODE
        mode = str(self.conf.get_entry(SHUFFLE_MANAGER_MODE)).upper()
        if mode == "P2P":
            from spark_rapids_tpu.shuffle.p2p import get_p2p_env
            return get_p2p_env(self.conf)
        return get_shuffle_manager(self.conf)

    def _execute_host_shuffle(self, prefetched=None):
        manager = self._shuffle_manager()
        partitioner = make_partitioner(self.mode, self.keys, self.num_partitions)
        handle = manager.new_shuffle(self.num_partitions)
        try:
            t0 = perf_counter()
            batches = (iter(prefetched) if prefetched is not None
                       else self.children[0].execute())
            if isinstance(partitioner, RangePartitioner):
                # range bounds must sample the WHOLE input, not the first
                # batch (Spark samples per-partition across the input)
                batches = list(batches)
                partitioner.compute_bounds_multi(batches)
            from spark_rapids_tpu.runtime.retry import retry_block
            for batch in batches:
                parts = split_by_partition(batch, partitioner)
                # host-memory pressure (CpuRetryOOM from the arbiter)
                # retries through the same framework as device OOM
                retry_block(lambda p=parts: handle.write_partitions(p))
            self.add_metric("shuffleWriteTime", perf_counter() - t0)
            self.add_metric("shuffleBytesWritten", handle.bytes_written)

            reader = manager.reader(handle)

            def read_one_partition(p: int) -> List[HostTable]:
                """Buffer one reduce partition (the recovery unit: nothing
                is emitted downstream until the partition read succeeded,
                so a recompute-and-retry never double-counts rows). A lost
                map output re-runs the missing upstream partitions from
                the RETAINED PLAN LINEAGE (self.children[0]) instead of
                failing the query."""
                for attempt in range(3):
                    bytes_before = reader.bytes_read
                    try:
                        return list(reader.read_partition(p))
                    except MapOutputLostError as e:
                        # a failed attempt's partial reads must not count
                        # toward shuffleBytesRead (the retry re-reads them)
                        reader.bytes_read = bytes_before
                        if attempt == 2:
                            raise
                        self._recompute_maps(handle, partitioner, e.map_ids)

            t0 = perf_counter()
            # AQE partition coalescing (reference: AQE
            # CoalesceShufflePartitions / ShufflePartitionsUtil): with the
            # conf enabled, ADJACENT undersized reduce partitions share
            # output batches, so a 200-partition shuffle of a small dataset
            # emits a handful of full batches instead of 200 slivers. NOTE:
            # a flush can land mid-partition, so batches are NOT
            # partition-aligned in this mode (keyed co-location still holds
            # per ROW, just not per batch). The within-partition target-
            # size split (GpuShuffleCoalesce) applies in both modes.
            coalesce_parts = self._aqe_coalesce_enabled()
            # measured map-output stats (AQE MapOutputStatistics analog):
            # per-partition byte sizes drive the skew metric and make the
            # coalescing decision observable
            part_bytes = [0] * self.num_partitions
            pending: List[HostTable] = []
            pending_bytes = 0
            nonempty_parts = 0
            emitted = 0
            for p in range(self.num_partitions):
                saw_rows = False
                for t in read_one_partition(p):
                    saw_rows = True
                    pending.append(t)
                    nb = t.nbytes()
                    part_bytes[p] += nb
                    pending_bytes += nb
                    if pending_bytes >= self.target_batch_bytes:
                        yield self._upload(pending)
                        emitted += 1
                        pending, pending_bytes = [], 0
                nonempty_parts += saw_rows
                if pending and not coalesce_parts:
                    yield self._upload(pending)
                    emitted += 1
                    pending, pending_bytes = [], 0
            if pending:
                yield self._upload(pending)
                emitted += 1
            if coalesce_parts and nonempty_parts > emitted:
                self.add_metric("aqeCoalescedPartitions",
                                nonempty_parts - emitted)
            live = sorted(b for b in part_bytes if b > 0)
            if live:
                from spark_rapids_tpu.conf import AQE_SKEW_FACTOR
                median = live[len(live) // 2]
                factor = float(self.conf.get_entry(AQE_SKEW_FACTOR))
                skewed = sum(1 for b in live if b > factor * max(median, 1))
                self.add_metric("mapOutputBytesMax", live[-1])
                self.add_metric("mapOutputBytesMedian", median)
                if skewed:
                    # oversized partitions already split into target-size
                    # batches above (OptimizeSkewedJoin's split, from
                    # MEASURED sizes); surface how many were skewed
                    self.add_metric("skewedPartitions", skewed)
            self.add_metric("shuffleReadTime", perf_counter() - t0)
            self.add_metric("shuffleBytesRead", reader.bytes_read)
        finally:
            manager.remove_shuffle(handle)

    def _recompute_maps(self, handle, partitioner, map_ids) -> None:
        """Lost-map-output recovery: re-run the child plan (map output i
        is batch i — partitioning is deterministic, so the recomputed
        blocks are byte-identical to the lost ones) and rewrite the
        missing maps through the manager's write handle. ``map_ids`` None
        means the loss scope is unknown: recompute every map once."""
        wanted = None if map_ids is None else set(map_ids)
        already = getattr(handle, "_recomputed_maps", set())
        # a second loss report for maps we already rewrote means the
        # rewrite itself is unreadable — recomputing again cannot
        # converge, so let the MapOutputLostError surface on the next try
        if wanted is None:
            if getattr(handle, "_recomputed_all", False):
                return
            handle._recomputed_all = True
        elif wanted <= already:
            return
        from spark_rapids_tpu.runtime.retry import retry_block
        total_maps = len(handle.map_outputs)
        rewritten = 0
        for i, batch in enumerate(self.children[0].execute()):
            if i >= total_maps:
                break
            if wanted is not None:
                if wanted <= already:
                    break  # everything lost is rewritten: stop re-running
                if i not in wanted:
                    continue
            parts = split_by_partition(batch, partitioner)
            # host-memory pressure retries like the original write path
            retry_block(lambda i=i, p=parts: handle.rewrite_map(i, p))
            already = already | {i}
            rewritten += 1
        handle._recomputed_maps = already
        RECOVERY.bump("recomputed_maps", rewritten)
        self.add_metric("recomputedMapOutputs", rewritten)

    @staticmethod
    def _upload(tables: List[HostTable]) -> DeviceTable:
        from spark_rapids_tpu.runtime.retry import retry_block
        host = tables[0] if len(tables) == 1 else HostTable.concat(tables)
        # shuffle re-landings are device landings like scans: a budget
        # squeeze (arbiter RetryOOM) spills and replays here instead
        # of failing the query with an unhandled OOM
        return retry_block(lambda: DeviceTable.from_host(host))
