"""File IO: scans and writers.

Reference surface: SURVEY.md §2.4 — GpuParquetScan/GpuOrcScan/GpuCSVScan/
GpuJsonScan three-mode readers (PERFILE / COALESCING / MULTITHREADED),
GpuParquetFileFormat/GpuOrcFileFormat writers, GpuFileFormatDataWriter
dynamic partitioning.

TPU design: decode happens on the host via Arrow (the TPU has no
general-purpose byte-wrangling path worth using for format decode; the
bandwidth win comes from batching decoded columns into large device uploads),
with the reference's prefetch/coalescing iterator architecture kept: the
MULTITHREADED mode overlaps decode of file k+1..k+N with device compute on
file k, and COALESCING stitches many small files into one large host buffer
so each H2D transfer and each downstream XLA program runs at full batch size.
"""

from spark_rapids_tpu.io.arrow_convert import (
    arrow_to_host_table,
    host_table_to_arrow,
    arrow_schema_to_spark,
)
from spark_rapids_tpu.io.committer import WriteJob, read_manifest
from spark_rapids_tpu.io.common import FileScanNode, ReaderMode
from spark_rapids_tpu.io.parquet import ParquetScanNode, write_parquet
from spark_rapids_tpu.io.orc import OrcScanNode, write_orc
from spark_rapids_tpu.io.csv import CsvScanNode, write_csv
from spark_rapids_tpu.io.json import JsonScanNode, write_json
from spark_rapids_tpu.io.hive_text import HiveTextScanNode, write_hive_text

from spark_rapids_tpu.overrides.rules import register_file_scan as _register

for _cls in (ParquetScanNode, OrcScanNode, CsvScanNode, JsonScanNode,
             HiveTextScanNode):
    _register(_cls)
del _register, _cls

__all__ = [
    "arrow_to_host_table",
    "host_table_to_arrow",
    "arrow_schema_to_spark",
    "FileScanNode",
    "ReaderMode",
    "ParquetScanNode",
    "OrcScanNode",
    "CsvScanNode",
    "JsonScanNode",
    "write_parquet",
    "write_orc",
    "write_csv",
    "write_json",
    "WriteJob",
    "read_manifest",
]
