"""Determinism + dead-code rules.

* RL-NONDETERMINISM — no wall-clock or unseeded randomness in kernel
  modules (results must replay bit-identically; LORE depends on it).
* RL-DEAD-LAMBDA — a lambda bound to a name that is never referenced
  again is dead code.
"""

from __future__ import annotations

import ast
from typing import List

from spark_rapids_tpu.lint.diagnostics import Diagnostic, make
from spark_rapids_tpu.lint.rules.common import _attr_chain

#: np.random attributes that construct SEEDED generators (allowed in
#: kernels); everything else on np.random is process-global state
_SEEDED_RANDOM_OK = {"default_rng", "Generator", "SeedSequence",
                     "BitGenerator", "PCG64", "Philox"}


def _check_nondeterminism(rel: str, tree: ast.AST,
                          diags: List[Diagnostic]):
    in_kernel = rel.startswith(("spark_rapids_tpu/execs/",
                                "spark_rapids_tpu/ops/"))
    if not in_kernel:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        bad = None
        if chain in ("time.time", "datetime.now", "datetime.datetime.now",
                     "date.today", "datetime.date.today",
                     "datetime.utcnow", "datetime.datetime.utcnow"):
            bad = f"{chain}() (wall clock)"
        else:
            parts = chain.split(".")
            if len(parts) >= 2 and parts[-2] == "random" and \
                    parts[0] in ("np", "numpy") and \
                    parts[-1] not in _SEEDED_RANDOM_OK:
                bad = f"{chain}() (process-global RNG state)"
            elif chain.startswith("random.") and len(parts) == 2:
                bad = f"{chain}() (unseeded stdlib RNG)"
        if bad:
            diags.append(make(
                "RL-NONDETERMINISM", f"{rel}:{node.lineno}",
                f"{bad} in a kernel module — results must replay "
                "bit-identically (seeded default_rng only)"))


def _check_dead_lambdas(rel: str, tree: ast.AST,
                        diags: List[Diagnostic]):
    lambda_defs = {}
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Lambda):
            name = node.targets[0].id
            lambda_defs.setdefault(name, node.lineno)
        elif isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load):
            used.add(node.id)
    for name, lineno in sorted(lambda_defs.items(), key=lambda kv: kv[1]):
        if name not in used:
            diags.append(make(
                "RL-DEAD-LAMBDA", f"{rel}:{lineno}",
                f"lambda bound to {name!r} is never used — dead code"))
