"""P2P shuffle transport: bounce buffers, transactions, windowed transfers.

Reference (SURVEY.md §2.6): the UCX transport stack —
``shuffle-plugin/.../ucx/UCX.scala`` (worker/listener/ActiveMessages),
``UCXShuffleTransport.scala`` (bounce-buffer pools, inflight limits),
``sql-plugin/.../shuffle/RapidsShuffleTransport.scala`` (transport-agnostic
layer), ``WindowedBlockIterator.scala:179`` (fixed-size windows over block
ranges), ``BounceBufferManager.scala``.

TPU mapping: there is no RDMA/NVLink between TPU executor hosts; the p2p
fast path's analog is a direct host-to-host wire (TCP over DCN) that
bypasses the shuffle-file + external-fetch hop, with the same protocol
shape the reference uses: driver-heartbeat peer discovery, a metadata
round trip, then windowed data transfers through a bounded bounce-buffer
pool so a fetch never buffers more than ``num_buffers * buffer_size``
regardless of shuffle size. An in-process transport implements the same
interface for protocol tests (the analog of the reference's mocked-jucx
suites, ``RapidsShuffleTestHelper.scala``)."""

from __future__ import annotations

import socket
import struct
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from spark_rapids_tpu.errors import (
    ColumnarProcessingError,
    ShuffleFetchError,
    ShuffleTransportError,
)
from spark_rapids_tpu.runtime.faults import fault_point

# message types (ActiveMessage ids in the reference's UCX.scala)
MSG_METADATA_REQ = 1
MSG_METADATA_RESP = 2
MSG_TRANSFER_REQ = 3
MSG_DATA_WINDOW = 4
MSG_TRANSFER_DONE = 5
MSG_ERROR = 6

TX_SUCCESS = "SUCCESS"
TX_ERROR = "ERROR"
TX_CANCELLED = "CANCELLED"


@dataclass
class Transaction:
    """Completion handle for one request/transfer (Transaction analog,
    UCXTransaction.scala)."""

    status: str = TX_SUCCESS
    error_message: Optional[str] = None
    bytes_transferred: int = 0
    payload: Optional[bytes] = None


#: sentinel distinguishing "no timeout passed" (use the pool default) from
#: an explicit timeout=None (wait forever)
_USE_DEFAULT = object()


class BounceBufferManager:
    """Bounded pool of fixed-size reusable buffers (BounceBufferManager
    analog). acquire() blocks until a buffer frees; the pool caps how much
    memory an in-flight fetch pipeline can hold.

    ``default_timeout`` (seconds; plumbed from
    spark.rapids.shuffle.p2p.bounceAcquireTimeoutMs by the p2p env) bounds
    how long an acquire with no explicit timeout waits — a peer dying
    while holding buffers must surface as a retryable ShuffleFetchError,
    not a hang."""

    def __init__(self, buffer_size: int, num_buffers: int,
                 default_timeout: Optional[float] = None):
        if buffer_size <= 0 or num_buffers <= 0:
            raise ColumnarProcessingError("bounce pool must be non-empty")
        self.buffer_size = buffer_size
        self.num_buffers = num_buffers
        self.default_timeout = default_timeout
        self._free: List[bytearray] = [bytearray(buffer_size)
                                       for _ in range(num_buffers)]
        self._cv = threading.Condition()
        self.acquire_count = 0
        self.high_water = 0

    def acquire(self, timeout=_USE_DEFAULT) -> bytearray:
        if timeout is _USE_DEFAULT:
            timeout = self.default_timeout
        with self._cv:
            if not self._cv.wait_for(lambda: self._free, timeout=timeout):
                raise ShuffleFetchError(
                    f"timed out after {timeout}s waiting for a bounce "
                    "buffer (peer holding buffers may be dead)")
            buf = self._free.pop()
            self.acquire_count += 1
            in_use = self.num_buffers - len(self._free)
            self.high_water = max(self.high_water, in_use)
            return buf

    def release(self, buf: bytearray):
        with self._cv:
            if len(self._free) >= self.num_buffers:
                raise ColumnarProcessingError("double release of bounce buffer")
            self._free.append(buf)
            self._cv.notify()

    @property
    def available(self) -> int:
        with self._cv:
            return len(self._free)


@dataclass(frozen=True)
class BlockRange:
    """One requested block (a serialized shuffle blob) addressed by id."""

    block_id: Tuple[int, int, int]  # (shuffle_id, map_id, partition_id)
    length: int


@dataclass(frozen=True)
class WindowSlice:
    """A window-sized piece of one block (WindowedBlockIterator element)."""

    block_index: int
    block_offset: int
    length: int


def windowed_slices(blocks: List[BlockRange],
                    window_size: int) -> List[List[WindowSlice]]:
    """Split a block list into windows of at most ``window_size`` bytes;
    blocks larger than a window span multiple windows, and small blocks
    share one (WindowedBlockIterator.scala:179). Each window maps onto one
    bounce buffer on both ends."""
    if window_size <= 0:
        raise ColumnarProcessingError("window_size must be positive")
    windows: List[List[WindowSlice]] = []
    cur: List[WindowSlice] = []
    cur_bytes = 0
    for bi, blk in enumerate(blocks):
        off = 0
        remaining = blk.length
        while remaining > 0:
            take = min(remaining, window_size - cur_bytes)
            cur.append(WindowSlice(bi, off, take))
            off += take
            remaining -= take
            cur_bytes += take
            if cur_bytes == window_size:
                windows.append(cur)
                cur, cur_bytes = [], 0
    if cur:
        windows.append(cur)
    return windows


class Connection:
    """One logical peer connection: a synchronous request channel plus a
    windowed data-stream channel (ClientConnection analog)."""

    def request(self, msg_type: int, payload: bytes) -> Transaction:
        raise NotImplementedError

    def stream(self, msg_type: int, payload: bytes,
               on_window: Callable[[memoryview], None]) -> Transaction:
        """Send a request whose response is a stream of data windows;
        ``on_window`` runs for each arriving window (inside a bounce
        buffer), and the returned transaction completes at DONE/ERROR."""
        raise NotImplementedError


class Transport:
    """Factory for peer connections + owner of the bounce pools
    (RapidsShuffleTransport analog)."""

    def __init__(self, recv_pool: BounceBufferManager):
        self.recv_pool = recv_pool

    def connect(self, peer: "PeerInfo") -> Connection:
        raise NotImplementedError

    def shutdown(self):
        pass


@dataclass(frozen=True)
class PeerInfo:
    """What the driver's heartbeat manager hands out per executor."""

    executor_id: str
    host: str = ""
    port: int = 0


# ---------------------------------------------------------------------------
# In-process transport: direct calls into a peer server object. The protocol
# tests (RapidsShuffleClientSuite analog) run against this, as the
# reference's run against mocked jucx.
# ---------------------------------------------------------------------------

class InProcessTransport(Transport):
    _registry: Dict[str, "object"] = {}
    _registry_lock = threading.Lock()

    def __init__(self, recv_pool: BounceBufferManager):
        super().__init__(recv_pool)

    @classmethod
    def register_server(cls, executor_id: str, server: "object"):
        with cls._registry_lock:
            cls._registry[executor_id] = server

    @classmethod
    def unregister_server(cls, executor_id: str):
        with cls._registry_lock:
            cls._registry.pop(executor_id, None)

    def connect(self, peer: PeerInfo) -> Connection:
        with self._registry_lock:
            server = self._registry.get(peer.executor_id)
        if server is None:
            raise ColumnarProcessingError(
                f"no in-process server for executor {peer.executor_id}")
        return _InProcessConnection(server, self.recv_pool)


class _InProcessConnection(Connection):
    def __init__(self, server, recv_pool: BounceBufferManager):
        self.server = server
        self.recv_pool = recv_pool

    def request(self, msg_type: int, payload: bytes) -> Transaction:
        try:
            fault_point("shuffle.transport.request")
            resp_type, resp = self.server.handle_request(msg_type, payload)
        except Exception as e:  # transport surfaces handler faults as tx errors
            return Transaction(status=TX_ERROR, error_message=str(e))
        if resp_type == MSG_ERROR:
            return Transaction(status=TX_ERROR,
                               error_message=resp.decode("utf-8", "replace"))
        return Transaction(payload=resp, bytes_transferred=len(resp))

    def stream(self, msg_type: int, payload: bytes,
               on_window: Callable[[memoryview], None]) -> Transaction:
        from spark_rapids_tpu.runtime.faults import FAULTS
        total = 0
        try:
            for window in self.server.handle_stream(msg_type, payload):
                if FAULTS.armed:
                    # disconnect/slow raise or stall here; corrupt
                    # damages the window copy before reassembly
                    window = fault_point("shuffle.transport.stream",
                                         data=bytes(window))
                buf = self.recv_pool.acquire()
                try:
                    n = len(window)
                    if n > len(buf):
                        raise ColumnarProcessingError(
                            f"window {n}B exceeds bounce buffer {len(buf)}B")
                    buf[:n] = window
                    total += n
                    on_window(memoryview(buf)[:n])
                finally:
                    self.recv_pool.release(buf)
        except Exception as e:
            return Transaction(status=TX_ERROR, error_message=str(e),
                               bytes_transferred=total)
        return Transaction(bytes_transferred=total)


# ---------------------------------------------------------------------------
# TCP transport: length-prefixed frames over sockets — the DCN wire. Frame:
# u32 msg_type | u64 length | payload.
# ---------------------------------------------------------------------------

_FRAME_HDR = struct.Struct("<IQ")


def _send_frame(sock: socket.socket, msg_type: int, payload) -> None:
    sock.sendall(_FRAME_HDR.pack(msg_type, len(payload)))
    if len(payload):
        sock.sendall(payload)


def _recv_exact(sock: socket.socket, n: int, buf: Optional[bytearray] = None):
    out = buf if buf is not None else bytearray(n)
    view = memoryview(out)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:n], n - got)
        if r == 0:
            raise ColumnarProcessingError("peer closed connection mid-frame")
        got += r
    return out


def _recv_frame_header(sock: socket.socket) -> Tuple[int, int]:
    hdr = _recv_exact(sock, _FRAME_HDR.size)
    return _FRAME_HDR.unpack(bytes(hdr))


class TcpShuffleServerListener:
    """Accept loop for a peer server: each connection gets a handler thread
    (UCX listener analog). ``server`` must expose handle_request /
    handle_stream like the in-process one."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0):
        self.server = server
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._closing = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="shuffle-server-accept", daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             name="shuffle-server-conn", daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while True:
                try:
                    msg_type, length = _recv_frame_header(conn)
                except ColumnarProcessingError:
                    return  # peer hung up between requests
                payload = bytes(_recv_exact(conn, length)) if length else b""
                if msg_type == MSG_TRANSFER_REQ:
                    try:
                        for window in self.server.handle_stream(
                                msg_type, payload):
                            _send_frame(conn, MSG_DATA_WINDOW, window)
                        _send_frame(conn, MSG_TRANSFER_DONE, b"")
                    except Exception as e:
                        _send_frame(conn, MSG_ERROR, str(e).encode())
                else:
                    try:
                        resp_type, resp = self.server.handle_request(
                            msg_type, payload)
                        _send_frame(conn, resp_type, resp)
                    except Exception as e:
                        _send_frame(conn, MSG_ERROR, str(e).encode())
        finally:
            conn.close()

    def close(self):
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass


class TcpTransport(Transport):
    """``connect_timeout`` comes from spark.rapids.shuffle.fetch
    .connectTimeoutMs; a timed-out connect raises a retryable
    ShuffleTransportError so the fetch-retry loop counts it against the
    peer instead of the query dying on socket.timeout."""

    def __init__(self, recv_pool: BounceBufferManager,
                 connect_timeout: float = 30.0):
        super().__init__(recv_pool)
        self.connect_timeout = connect_timeout

    def connect(self, peer: PeerInfo) -> Connection:
        try:
            sock = socket.create_connection((peer.host, peer.port),
                                            timeout=self.connect_timeout)
        except OSError as e:
            raise ShuffleTransportError(
                f"cannot connect to shuffle peer {peer.executor_id} at "
                f"{peer.host}:{peer.port}: {e}") from e
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return _TcpConnection(sock, self.recv_pool)


class _TcpConnection(Connection):
    """``broken`` marks a connection whose wire state is undefined — a
    transport fault (socket error) or protocol desync (unexpected frame,
    oversized window, partially-read stream). The env evicts broken
    connections so the next fetch reconnects instead of parsing mid-stream
    bytes as frame headers (ADVICE r2). A clean MSG_ERROR response at a
    frame boundary does NOT break the connection."""

    def __init__(self, sock: socket.socket, recv_pool: BounceBufferManager):
        self.sock = sock
        self.recv_pool = recv_pool
        self.broken = False
        self._lock = threading.Lock()  # one request at a time per connection

    def _fault(self, e) -> Transaction:
        self.broken = True
        self.close()
        return Transaction(status=TX_ERROR, error_message=str(e))

    def request(self, msg_type: int, payload: bytes) -> Transaction:
        with self._lock:
            try:
                fault_point("shuffle.transport.request")
                _send_frame(self.sock, msg_type, payload)
                resp_type, length = _recv_frame_header(self.sock)
                resp = bytes(_recv_exact(self.sock, length)) if length else b""
            except (OSError, ColumnarProcessingError) as e:
                return self._fault(e)
        if resp_type == MSG_ERROR:
            return Transaction(status=TX_ERROR,
                               error_message=resp.decode("utf-8", "replace"))
        return Transaction(payload=resp, bytes_transferred=len(resp))

    def stream(self, msg_type: int, payload: bytes,
               on_window: Callable[[memoryview], None]) -> Transaction:
        from spark_rapids_tpu.runtime.faults import FAULTS
        total = 0
        with self._lock:
            try:
                _send_frame(self.sock, msg_type, payload)
                while True:
                    resp_type, length = _recv_frame_header(self.sock)
                    if resp_type == MSG_TRANSFER_DONE:
                        return Transaction(bytes_transferred=total)
                    if resp_type == MSG_ERROR:
                        msg = bytes(_recv_exact(self.sock, length)).decode(
                            "utf-8", "replace") if length else "server error"
                        return Transaction(status=TX_ERROR, error_message=msg,
                                           bytes_transferred=total)
                    if resp_type != MSG_DATA_WINDOW:
                        raise ColumnarProcessingError(
                            f"unexpected frame type {resp_type} in stream")
                    buf = self.recv_pool.acquire()
                    try:
                        if length > len(buf):
                            raise ColumnarProcessingError(
                                f"window {length}B exceeds bounce buffer "
                                f"{len(buf)}B")
                        # receive directly into the bounce buffer
                        view = memoryview(buf)[:length]
                        got = 0
                        while got < length:
                            r = self.sock.recv_into(view[got:], length - got)
                            if r == 0:
                                raise ColumnarProcessingError(
                                    "peer closed mid-window")
                            got += r
                        total += length
                        if FAULTS.armed:
                            view = memoryview(fault_point(
                                "shuffle.transport.stream",
                                data=bytes(view)))
                        on_window(view)
                    finally:
                        self.recv_pool.release(buf)
            except (OSError, ColumnarProcessingError) as e:
                tx = self._fault(e)
                tx.bytes_transferred = total
                return tx

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass
