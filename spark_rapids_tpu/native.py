"""Native (C++) runtime components, loaded via ctypes with a pure-Python
fallback when the toolchain or prebuilt library is unavailable.

The compute path is JAX/XLA; these are the HOST runtime hot spots the
reference also keeps native (cuDF/JNI): currently the order-preserving
string dictionary encoder (native/strcodec.cpp). The shared library builds
lazily with g++ on first use and is cached next to the source; every
caller must tolerate ``None`` (fallback to numpy)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")

_lock = threading.Lock()
_libs: dict = {}


def _load_lib(stem: str, configure) -> Optional[ctypes.CDLL]:
    """Build native/<stem>.cpp into lib<stem>.so (if stale) and load it.
    ``configure(lib)`` declares the ctypes signatures. Returns None (and
    remembers the failure) when the toolchain or build is unavailable."""
    if stem in _libs:
        return _libs[stem]
    with _lock:
        if stem in _libs:
            return _libs[stem]
        src = os.path.join(_NATIVE_DIR, f"{stem}.cpp")
        so = os.path.join(_NATIVE_DIR, f"lib{stem}.so")
        try:
            if not os.path.exists(so) or (
                    os.path.exists(src)
                    and os.path.getmtime(src) > os.path.getmtime(so)):
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                     src, "-o", so],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(so)
            configure(lib)
            _libs[stem] = lib
        except Exception:
            _libs[stem] = None
    return _libs[stem]


def _configure_strcodec(lib):
    lib.encode_sorted_dict_u32.restype = ctypes.c_int64
    lib.encode_sorted_dict_u32.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p]


def _load() -> Optional[ctypes.CDLL]:
    return _load_lib("strcodec", _configure_strcodec)


def native_available() -> bool:
    return _load() is not None


def _sort_keys_native(keys: np.ndarray):
    """Sort an object array of DISTINCT strings by code-point order with
    the native codec (numpy UTF-32 conversion + C++ index sort); None when
    the library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    k = len(keys)
    u = keys.astype(str).astype("U")
    width = max(u.dtype.itemsize // 4, 1)
    chars = np.ascontiguousarray(u).view(np.uint32).reshape(k, width)
    codes = np.empty(k, dtype=np.int32)
    dict_row = np.empty(k, dtype=np.int64)
    ndict = lib.encode_sorted_dict_u32(
        chars.ctypes.data_as(ctypes.c_void_p), k, width,
        codes.ctypes.data_as(ctypes.c_void_p),
        dict_row.ctypes.data_as(ctypes.c_void_p))
    if ndict != k:
        # numpy 'U' padding cannot represent trailing NULs: distinct keys
        # like "a" and "a\x00" collapse to one row — fall back to the
        # python comparator which distinguishes them
        return None
    return codes  # rank of each key in sorted order (keys are distinct)


#: above this many distinct keys, Python-object argsort comparisons lose
#: to the native UTF-32 index sort
_NATIVE_SORT_MIN_KEYS = 4096


def encode_sorted_dict(values: np.ndarray):
    """Order-preserving dictionary encode of an object array of str:
    hash-dedupe at C-dict speed, then rank the DISTINCT keys — natively
    (UTF-32 code-point sort) at high cardinality, via numpy otherwise.
    Returns (codes int32, dictionary object array); 5-6x the old
    np.unique-over-objects path at typical cardinalities."""
    n = len(values)
    if n == 0:
        return (np.zeros(0, dtype=np.int32), np.array([], dtype=object))
    table: dict = {}
    setd = table.setdefault
    raw = np.fromiter((setd(s, len(table)) for s in values),
                      dtype=np.int32, count=n)
    keys = np.fromiter(table.keys(), dtype=object, count=len(table))
    k = len(keys)
    rank = None
    if k >= _NATIVE_SORT_MIN_KEYS:
        rank = _sort_keys_native(keys)
    if rank is None:
        order = np.argsort(keys)
        rank = np.empty(k, dtype=np.int32)
        rank[order] = np.arange(k, dtype=np.int32)
    codes = rank[raw]
    dictionary = np.empty(k, dtype=object)
    dictionary[rank] = keys
    return codes, dictionary


# ---------------------------------------------------------------------------
# LZ4 block codec (native/lz4codec.cpp) — shuffle wire compression.
# Reference analog: nvcomp BatchedLZ4Compressor (TableCompressionCodec.scala).
# ---------------------------------------------------------------------------

def _configure_lz4(lib):
    lib.lz4_compress_bound.restype = ctypes.c_int64
    lib.lz4_compress_bound.argtypes = [ctypes.c_int64]
    lib.lz4_compress.restype = ctypes.c_int64
    lib.lz4_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]
    lib.lz4_decompress.restype = ctypes.c_int64
    lib.lz4_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]


def lz4_available() -> bool:
    return _load_lib("lz4codec", _configure_lz4) is not None


def lz4_compress(data: bytes) -> Optional[bytes]:
    """Compress to a raw LZ4 block; None if the native lib is unavailable.
    The caller must track the uncompressed size (the block format does not)."""
    lib = _load_lib("lz4codec", _configure_lz4)
    if lib is None:
        return None
    n = len(data)
    out = ctypes.create_string_buffer(lib.lz4_compress_bound(n))
    written = lib.lz4_compress(data, n, out, len(out))
    if written < 0:
        raise RuntimeError("lz4_compress failed")
    return out.raw[:written]


def lz4_decompress(data: bytes, out_size: int) -> Optional[bytes]:
    """Decompress a raw LZ4 block of known uncompressed size; None if the
    native lib is unavailable; raises on corrupt input."""
    lib = _load_lib("lz4codec", _configure_lz4)
    if lib is None:
        return None
    out = ctypes.create_string_buffer(out_size) if out_size else b""
    written = lib.lz4_decompress(data, len(data), out, out_size)
    if written != out_size:
        raise RuntimeError(
            f"lz4_decompress: expected {out_size} bytes, got {written}")
    return bytes(out.raw[:out_size]) if out_size else b""
