"""WindowGroupLimit (reference: GpuWindowGroupLimitExec / Spark 3.5
InsertWindowGroupLimit) + supported-ops doc generation + conf-tuned
constants."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.functions import dense_rank, rank, row_number
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.ops.window import Window as W
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def tpu():
    return TpuSession()


@pytest.fixture(scope="module")
def cpu():
    return TpuSession({"spark.rapids.sql.enabled": "false"})


def _data(n=4000, seed=0):
    rng = np.random.default_rng(seed)
    return {"k": rng.integers(0, 40, n).astype(np.int64),
            "v": rng.random(n)}


@pytest.mark.parametrize("fn_maker,kind", [
    (row_number, "rownumber"), (rank, "rank"), (dense_rank, "denserank")])
def test_group_limit_inserted_and_exact(tpu, cpu, fn_maker, kind):
    data = _data()
    q = lambda s: sorted(
        s.create_dataframe(data)
        .with_windows(r=fn_maker().over(
            W.partition_by("k").order_by("v")))
        .filter(col("r") <= lit(3)).collect(), key=repr)
    a, b = q(tpu), q(cpu)
    assert len(a) == len(b)
    assert all(repr(x) == repr(y) for x, y in zip(a, b))
    assert "TpuWindowGroupLimit" in tpu.last_metrics()


def test_group_limit_less_than_and_equal(tpu, cpu):
    data = _data(seed=1)
    for cond in (lambda c: c < lit(4), lambda c: c == lit(1)):
        q = lambda s: sorted(
            s.create_dataframe(data)
            .with_windows(r=row_number().over(
                W.partition_by("k").order_by("v")))
            .filter(cond(col("r"))).collect(), key=repr)
        assert q(tpu) == q(cpu)


def test_group_limit_not_inserted_for_aggregate_window(tpu):
    """A non-ranking window filter must not trigger the rewrite."""
    data = _data(seed=2)
    df = (tpu.create_dataframe(data)
          .with_windows(s=F.sum(col("v")).over(
              W.partition_by("k").order_by("v")))
          .filter(col("s") <= lit(1.0)))
    _ = df.collect()
    assert "TpuWindowGroupLimit" not in tpu.last_metrics()


def test_group_limit_plan_not_mutated_across_runs(tpu):
    """The rewrite builds a new tree: re-collecting the same DataFrame
    must not stack group-limit layers."""
    data = _data(seed=3)
    df = (tpu.create_dataframe(data)
          .with_windows(r=row_number().over(
              W.partition_by("k").order_by("v")))
          .filter(col("r") <= lit(2)))
    a = sorted(df.collect(), key=repr)
    b = sorted(df.collect(), key=repr)
    assert a == b
    assert tpu.last_metrics().count("TpuWindowGroupLimit") == 1


# -- generated supported-ops doc + conf-driven tuning ------------------------

def test_supported_ops_doc_generates():
    from spark_rapids_tpu.overrides.docs import generate_supported_ops
    doc = generate_supported_ops()
    assert "## Execs" in doc and "## Expressions" in doc
    rows = [l for l in doc.splitlines() if l.startswith("| ")]
    assert len(rows) > 150  # exec + expression matrix breadth
    assert any(l.startswith("| Join ") for l in rows)
    assert any(l.startswith("| Cast ") for l in rows)
    # nested columns: scans support MAP/STRUCT, plain execs do not
    scan_row = next(l for l in rows if l.startswith("| LocalScan "))
    assert scan_row.count(" S ") >= 12
    filt_row = next(l for l in rows if l.startswith("| Filter "))
    assert " NS " in filt_row  # nested columns tag fallback at filters


def test_sequence_multiplier_conf_applies():
    from spark_rapids_tpu.errors import AnsiViolation
    s = TpuSession({"spark.rapids.tpu.sequence.elementMultiplier": "1"})
    data = {"a": np.full(100, 1, dtype=np.int64),
            "b": np.full(100, 50, dtype=np.int64)}
    with pytest.raises(AnsiViolation):
        s.create_dataframe(data).select(
            F.sequence(col("a"), col("b")).alias("s")).collect()
    big = TpuSession({"spark.rapids.tpu.sequence.elementMultiplier": "64"})
    got = big.create_dataframe(data).select(
        F.sequence(col("a"), col("b")).alias("s")).collect()
    assert len(got) == 100 and got[0][0] == list(range(1, 51))


def test_group_limit_not_inserted_with_unsafe_sibling(tpu, cpu):
    """A sibling window column over a different spec blocks the rewrite
    (review finding: pruning would corrupt the sibling's values)."""
    data = _data(seed=4)
    q = lambda s: sorted(
        s.create_dataframe(data)
        .with_windows(
            r=row_number().over(W.partition_by("k").order_by("v")),
            t=F.count(col("v")).over(W.partition_by("k")))
        .filter(col("r") <= lit(2)).collect(), key=repr)
    a, b = q(tpu), q(cpu)
    assert a == b
    assert "TpuWindowGroupLimit" not in tpu.last_metrics()
