"""Broadcast exchange + conditioned nested-loop joins (reference analog:
GpuBroadcastExchangeExec / GpuBroadcastNestedLoopJoinExec)."""

import pytest

from spark_rapids_tpu.ops.expr import col, lit

from tests.asserts import assert_tpu_and_cpu_are_equal
from tests.data_gen import DoubleGen, IntGen, StringGen, gen_table


def _dfs(sess, n_left=300, n_right=40, nb=3, seed=53):
    from spark_rapids_tpu.plan import from_host_table
    lg = {"a": IntGen(min_val=0, max_val=60), "lv": DoubleGen(corner_prob=0.0)}
    rg = {"b": IntGen(min_val=0, max_val=60), "rv": IntGen(min_val=0, max_val=60)}
    left = from_host_table(gen_table(lg, n_left, seed), sess, nb)
    right = from_host_table(gen_table(rg, n_right, seed + 1), sess, 1)
    return left, right


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "leftsemi", "leftanti"])
def test_nlj_condition_join_types(session, cpu_session, how):
    def build(s):
        left, right = _dfs(s)
        return left.join(right, on=col("a") < col("rv"), how=how)
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_nlj_range_band_condition(session, cpu_session):
    """Band join: a BETWEEN b-5 AND b+5 — the classic NLJ workload."""
    def build(s):
        left, right = _dfs(s)
        cond = (col("a") >= col("b") - lit(5)) & (col("a") <= col("b") + lit(5))
        return left.join(right, on=cond, how="inner")
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_nlj_condition_with_nulls(session, cpu_session):
    def build(s):
        from spark_rapids_tpu.plan import from_host_table
        lg = {"a": IntGen(min_val=0, max_val=20, null_prob=0.3)}
        rg = {"b": IntGen(min_val=0, max_val=20, null_prob=0.3)}
        left = from_host_table(gen_table(lg, 120, 5), s, 2)
        right = from_host_table(gen_table(rg, 30, 6), s, 1)
        return left.join(right, on=col("a") == col("b") + lit(1), how="full")
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_nlj_runs_on_device(session):
    from tests.asserts import assert_runs_on_tpu
    def build(s):
        left, right = _dfs(s)
        return left.join(right, on=col("a") < col("rv"), how="left")
    assert_runs_on_tpu(build, session)




def _collect_execs(root, cls):
    found = []

    def walk(e):
        if isinstance(e, cls):
            found.append(e)
        for c in getattr(e, "children", ()):
            walk(c)
        for attr in ("source", "tpu_exec", "cpu_node"):
            nxt = getattr(e, attr, None)
            if nxt is not None:
                walk(nxt)

    walk(root)
    return found


def test_broadcast_exchange_selected_for_small_build(session):
    """Small build sides (LocalScan size estimate) go through the broadcast
    exchange; the table materializes once and is reused."""
    from spark_rapids_tpu.overrides import apply_overrides
    from spark_rapids_tpu.execs.broadcast import TpuBroadcastExchangeExec

    from spark_rapids_tpu.plan import from_host_table
    l2 = {"k": IntGen(min_val=0, max_val=9), "x": IntGen()}
    r2 = {"k": IntGen(min_val=0, max_val=9), "y": IntGen()}
    left = from_host_table(gen_table(l2, 200, 1), session, 1)
    right = from_host_table(gen_table(r2, 50, 2), session, 1)
    j = left.join(right, on="k", how="inner")
    executable, _ = apply_overrides(j.plan, session.conf)

    found = _collect_execs(executable, TpuBroadcastExchangeExec)
    assert len(found) == 1, "build side should broadcast"
    list(executable.execute_cpu())
    assert found[0]._cached is not None
    cached = found[0]._cached
    list(executable.execute_cpu())
    assert found[0]._cached is cached  # reused, not rebuilt


def test_broadcast_disabled_by_threshold(session):
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.overrides import apply_overrides
    from spark_rapids_tpu.execs.broadcast import TpuBroadcastExchangeExec
    from spark_rapids_tpu.plan import from_host_table

    off = TpuSession({"spark.rapids.sql.broadcastSizeBytes": 0})
    l2 = {"k": IntGen(min_val=0, max_val=9)}
    left = from_host_table(gen_table(l2, 100, 1), off, 1)
    right = from_host_table(gen_table(l2, 20, 2), off, 1)
    executable, _ = apply_overrides(
        left.join(right, on="k", how="inner").plan, off.conf)

    found = _collect_execs(executable, TpuBroadcastExchangeExec)
    assert not found
