"""TPU sort (reference: GpuSortExec.scala / SortUtils.scala — SURVEY.md
§2.3; out-of-core spill variant comes with the memory runtime).

Multi-operand ``lax.sort`` does the lexicographic work directly on the MXU-
adjacent sort network. Each sort key is transformed into ascending operands:
descending order negates/complements the key; nulls-first/last becomes an
explicit leading flag operand; padding rows always sort last. A row-index
payload yields the permutation used to gather every output column."""

from __future__ import annotations

from typing import List, Sequence

import jax
from spark_rapids_tpu.dispatch import tpu_jit
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceTable
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.ops.expr import (
    DevVal,
    EvalCtx,
    NodePrep,
    PrepCtx,
    _prep_trace_key,
    _walk_eval,
    _walk_prep,
)
from spark_rapids_tpu.plan.nodes import SortOrder


def _directional(data, validity, ascending: bool, nulls_first: bool, capacity: int):
    """Make (null_flag, *key_operands) for an ascending lax.sort realizing
    the requested direction and null placement. Keys decompose into
    native <=32-bit order-isomorphic operands (ops/ordering.py) — i64/f64
    sort ~1.6x faster than emulated 64-bit compares, and f64 gets -0.0/NaN
    canonicalization (Spark NormalizeFloatingNumbers / NaN-last) for free."""
    from spark_rapids_tpu.ops.ordering import (
        comparable_operands,
        descending_operands,
        zero_invalid,
    )
    ops = comparable_operands(zero_invalid(data, validity))
    if not ascending:
        ops = descending_operands(ops)
    # null flag sorts ahead of the key: 0 sorts first, so invalid rows get 0
    # when nulls_first else 1.
    nf = jnp.where(validity, 1 if nulls_first else 0, 0 if nulls_first else 1)
    return [nf] + ops


class TpuSortExec(TpuExec):
    def __init__(self, child: TpuExec, orders: Sequence[SortOrder]):
        super().__init__()
        self.children = (child,)
        self.orders = list(orders)

    def output_schema(self):
        return self.children[0].output_schema()

    #: set by the overrides conversion from
    #: spark.rapids.sql.sort.outOfCoreThresholdBytes
    ooc_threshold_bytes = 1 << 30

    def execute(self):
        """Multi-batch inputs accumulate as SPILLABLE batches (bounded HBM
        while upstream streams; reference: GpuSortExec pending pool,
        GpuSortExec.scala:281). Small totals concat on device and sort
        once; totals above the out-of-core threshold take the spilled-run
        range merge (``sorted_run_stream``) so peak HBM stays one output
        range — the GpuSortExec.scala:281 merge-of-spilled-runs analog."""
        from spark_rapids_tpu.runtime.retry import retry_block
        from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch

        it = self.children[0].execute_masked()
        first = next(it, None)
        if first is None:
            return
        second = next(it, None)
        if second is None:
            yield retry_block(lambda: self._sort(first))
            return

        from itertools import chain
        from spark_rapids_tpu.columnar.table import concat_device
        from spark_rapids_tpu.runtime.memory import MEMORY
        catalog = BufferCatalog.get()
        pending = []
        total = 0
        # spill-aware threshold: a multi-batch sort past the device
        # budget's chunk share goes out of core even when the conf
        # threshold is higher — the spilled-run range merge keeps peak
        # HBM at one output range
        threshold = min(self.ooc_threshold_bytes,
                        MEMORY.scan_chunk_bytes())
        all_batches = chain([first, second], it)
        try:
            for batch in all_batches:
                pending.append(SpillableBatch(batch, catalog))
                total += batch.device_nbytes()
                self.add_metric("sortInputBatches", 1)
                if total > threshold:
                    # switch to out-of-core: drain the rest as host runs
                    batches = [sb for sb in pending]
                    pending = []
                    self.add_metric("sortOutOfCore", 1)
                    yield from self._ooc_stream(batches, all_batches,
                                                catalog)
                    return

            def merge_and_sort():
                tables = [sb.get() for sb in pending]
                return self._sort(concat_device(tables))

            yield retry_block(merge_and_sort)
        finally:
            for sb in pending:
                sb.release()

    @classmethod
    def for_orders(cls, orders):
        """Standalone sorter over ``orders`` (used by range merging and
        the window streaming path — no child exec)."""
        ex = cls.__new__(cls)
        ex.orders = list(orders)
        ex.metrics = {}
        return ex

    def _ooc_stream(self, spillables, rest_iter, catalog):
        from spark_rapids_tpu.runtime.retry import retry_block
        runs = []
        try:
            while spillables:
                sb = spillables.pop()
                try:
                    with sb.pinned_batch() as dt:
                        runs.append(retry_block(
                            lambda d=dt: self._sort(d)).to_host())
                finally:
                    sb.release()
            for batch in rest_iter:
                runs.append(retry_block(
                    lambda b=batch: self._sort(b)).to_host())
                self.add_metric("sortInputBatches", 1)
        finally:
            for sb in spillables:  # error mid-loop: drop the rest
                sb.release()
        yield from sorted_run_stream(runs, self.orders)

    def _pos_dep(self) -> bool:
        from spark_rapids_tpu.ops.expr import has_position_dependent
        return any(has_position_dependent(o.expr) for o in self.orders)

    def _sort(self, table: DeviceTable) -> DeviceTable:
        from spark_rapids_tpu.ops.expr import shared_traces
        if table.live is not None and self._pos_dep():
            table = table.compacted()  # slot ids must match prefix form
        self._traces = shared_traces(
            ("sort",
             tuple((o.expr.key(), o.ascending, o.resolved_nulls_first())
                   for o in self.orders),
             table.schema_key()[0]))
        pctx = PrepCtx(table)
        key_preps: List[List[NodePrep]] = []
        for o in self.orders:
            preps: List[NodePrep] = []
            _walk_prep(o.expr, pctx, preps)
            key_preps.append(preps)
        from spark_rapids_tpu.dispatch import prep_aux
        cols = tuple(DevVal(c.data, c.validity) for c in table.columns)
        aux = prep_aux(pctx)
        capacity = table.capacity

        from spark_rapids_tpu import kernels
        has_mask = table.live is not None
        tkey = (capacity, has_mask, kernels.trace_token(),
                tuple(_prep_trace_key(p) for p in key_preps))
        fn = self._traces.get(tkey)
        if fn is None:
            orders = self.orders

            def run(cols, aux, nrows, live_in):
                from spark_rapids_tpu.ops.ordering import lex_sort
                # masked input: dead rows park last via the liveness
                # operand, so the sort doubles as the deferred compaction
                if live_in is not None:
                    live = live_in
                else:
                    live = jnp.arange(capacity, dtype=jnp.int32) < nrows
                operands = [(~live).astype(jnp.int32)]  # padding last
                for o, preps in zip(orders, key_preps):
                    ctx = EvalCtx(cols, aux, nrows, capacity, live=live_in)
                    ctx._prep_iter = iter(preps)
                    kv = _walk_eval(o.expr, ctx)
                    operands.extend(_directional(kv.data, kv.validity, o.ascending,
                                                 o.resolved_nulls_first(), capacity))
                payload = jnp.arange(capacity, dtype=jnp.int32)
                res = lex_sort(operands, payload)
                perm = res[-1]
                return [(d[perm], v[perm]) for d, v in cols]

            fn = tpu_jit(run)
            self._traces[tkey] = fn

        outs = fn(cols, aux, table.nrows_dev, table.live)
        new_cols = [c.with_arrays(d, v) for c, (d, v) in zip(table.columns, outs)]
        return DeviceTable(table.names, new_cols, table.nrows_dev, capacity)

    def _topk(self, table: DeviceTable, k: int) -> DeviceTable:
        """Top-k rows by sort order at a k-sized capacity: sort ONLY the
        key operands + a row-index payload, then gather the k winning rows
        of every column. The reference's per-batch top-k sorts then slices
        (GpuTakeOrderedAndProjectExec), but on TPU a full-width gather at
        input capacity costs ~10-30ms per 64-bit column (PERF.md) — this
        does O(k) gather work instead and emits a small-capacity batch,
        which also shrinks every downstream kernel."""
        from spark_rapids_tpu.columnar import bucket_for
        from spark_rapids_tpu.ops.expr import shared_traces
        if table.live is not None and self._pos_dep():
            table = table.compacted()  # slot ids must match prefix form
        capacity = table.capacity
        kcap = min(bucket_for(max(k, 1)), capacity)
        self._traces = shared_traces(
            ("topk", kcap,
             tuple((o.expr.key(), o.ascending, o.resolved_nulls_first())
                   for o in self.orders),
             table.schema_key()[0]))
        pctx = PrepCtx(table)
        key_preps: List[List[NodePrep]] = []
        for o in self.orders:
            preps: List[NodePrep] = []
            _walk_prep(o.expr, pctx, preps)
            key_preps.append(preps)
        from spark_rapids_tpu.dispatch import prep_aux
        cols = tuple(DevVal(c.data, c.validity) for c in table.columns)
        aux = prep_aux(pctx)
        from spark_rapids_tpu import kernels
        has_mask = table.live is not None
        tkey = (capacity, has_mask, k, kernels.trace_token(),
                tuple(_prep_trace_key(p) for p in key_preps))
        fn = self._traces.get(tkey)
        if fn is None:
            orders = self.orders

            def run(cols, aux, nrows, live_in):
                if live_in is not None:
                    live = live_in
                    n_live = jnp.sum(live.astype(jnp.int32))
                else:
                    live = jnp.arange(capacity, dtype=jnp.int32) < nrows
                    n_live = nrows
                operands = [(~live).astype(jnp.int32)]  # dead rows last
                for o, preps in zip(orders, key_preps):
                    ctx = EvalCtx(cols, aux, nrows, capacity, live=live_in)
                    ctx._prep_iter = iter(preps)
                    kv = _walk_eval(o.expr, ctx)
                    operands.extend(_directional(
                        kv.data, kv.validity, o.ascending,
                        o.resolved_nulls_first(), capacity))
                from spark_rapids_tpu.ops.ordering import lex_sort
                payload = jnp.arange(capacity, dtype=jnp.int32)
                res = lex_sort(operands, payload)
                idx = res[-1][:kcap]
                n_out = jnp.minimum(n_live, jnp.asarray(k, jnp.int32))
                out_live = jnp.arange(kcap, dtype=jnp.int32) < n_out
                outs = []
                for d, v in cols:
                    outs.append((d[idx], v[idx] & out_live))
                return outs, n_out

            fn = tpu_jit(run)
            self._traces[tkey] = fn
        outs, n_out = fn(cols, aux, table.nrows_dev, table.live)
        new_cols = [c.with_arrays(d, v)
                    for c, (d, v) in zip(table.columns, outs)]
        return DeviceTable(table.names, new_cols, n_out, kcap)

    def describe(self):
        return f"TpuSort[{len(self.orders)} keys]"


class TpuTakeOrderedAndProjectExec(TpuExec):
    """ORDER BY + LIMIT n (+ projection): per-batch device top-k via the
    sort kernel, keep only k rows per batch, then one final k*batches
    merge-sort — the reference's GpuTakeOrderedAndProjectExec shape
    (never materializes the full sorted input)."""

    def __init__(self, child: TpuExec, orders, limit: int,
                 project=None, project_names=None):
        super().__init__()
        self.children = (child,)
        self.orders = list(orders)
        self.limit = int(limit)
        self.project = list(project) if project is not None else None
        self.project_names = list(project_names) if project_names else None
        self._sorter = TpuSortExec(child, orders)  # reuse the sort kernel

    def output_schema(self):
        if self.project is None:
            return self.children[0].output_schema()
        return [(n, e.data_type)
                for n, e in zip(self.project_names, self.project)]

    def describe(self):
        return f"TpuTakeOrderedAndProject[limit={self.limit}]"

    def execute(self):
        from spark_rapids_tpu.columnar import bucket_for
        from spark_rapids_tpu.columnar.table import concat_device
        from spark_rapids_tpu.ops.expr import compile_project
        from spark_rapids_tpu.runtime.retry import retry_block

        k = self.limit
        tops = []
        for batch in self.children[0].execute_masked():
            tops.append(retry_block(lambda b=batch: self._sorter._topk(b, k)))

        if not tops:
            return
        merged = tops[0] if len(tops) == 1 else retry_block(
            lambda: concat_device(tops))
        if len(tops) == 1:
            final = merged  # a single _topk batch is already sorted
        else:
            final = retry_block(lambda: self._sorter._sort(merged))
        from spark_rapids_tpu.dispatch import device_scalar
        nrows = jnp.minimum(final.nrows_dev, device_scalar(k))
        out = DeviceTable(final.names, final.columns, nrows, final.capacity)
        if self.project is not None:
            cols = compile_project(self.project, out)
            out = DeviceTable(self.project_names, cols, out.nrows_dev,
                              out.capacity)
        yield out


def sorted_run_stream(runs, orders, target_rows: int = None):
    """Merge HOST-resident sorted runs into a stream of globally ordered
    DEVICE batches without materializing the whole table on device — the
    reference's merge of spilled sorted runs (GpuSortExec.scala:281),
    re-shaped for the TPU: instead of a pointer-chasing k-way merge, the
    FIRST sort key's value space splits into quantile ranges; each range
    gathers its slice from every run (host slicing is O(log n) per run —
    runs are sorted), uploads, and one device sort orders the range. Peak
    HBM = one range. Rows with EQUAL first keys always land in the same
    output batch (bounds are cut points), which also makes the stream
    safe for RANGE-frame window peers (execs/window.py streaming).

    ``runs``: list of HostTable, each fully sorted by ``orders``."""
    import numpy as np
    from spark_rapids_tpu.columnar import DeviceTable, HostTable
    from spark_rapids_tpu.runtime.retry import retry_block

    o0 = orders[0]
    asc = o0.ascending
    nulls_first = o0.resolved_nulls_first()

    # first-key host values + per-run null spans (contiguous by sortedness)
    keys = []
    spans = []
    for run in runs:
        kc = o0.expr.eval_cpu(run)
        n = run.num_rows
        nn = int(kc.validity.sum())
        if nulls_first:
            null_lo, null_hi, lo, hi = 0, n - nn, n - nn, n
        else:
            null_lo, null_hi, lo, hi = nn, n, 0, nn
        vals = kc.data[lo:hi]
        keys.append(vals if asc else vals[::-1])  # ascending view
        spans.append((null_lo, null_hi, lo, hi))

    total = sum(k.shape[0] for k in keys)
    if target_rows is None:
        target_rows = max((r.num_rows for r in runs), default=1)
    nparts = max(1, -(-total // max(target_rows, 1)))
    if total:
        allvals = np.sort(np.concatenate([np.asarray(k) for k in keys]))
        bounds = []
        for i in range(1, nparts):
            b = allvals[(total * i) // nparts]
            if not bounds or b != bounds[-1]:
                bounds.append(b)
    else:
        bounds = []

    def run_slices(part_idx, lo_b, hi_b):
        """HostTable slices of every run for value range [lo_b, hi_b)."""
        parts = []
        for run, k, (null_lo, null_hi, lo, hi) in zip(runs, keys, spans):
            a = 0 if lo_b is None else int(np.searchsorted(k, lo_b, "left"))
            b = k.shape[0] if hi_b is None else int(
                np.searchsorted(k, hi_b, "left"))
            if b <= a:
                continue
            if asc:
                parts.append(run.slice(lo + a, b - a))
            else:
                # ascending view was reversed: map back from the end
                parts.append(run.slice(hi - b, b - a))
        return parts

    ranges = [(bounds[i - 1] if i else None,
               bounds[i] if i < len(bounds) else None)
              for i in range(len(bounds) + 1)]
    if not asc:
        ranges = ranges[::-1]  # larger keys first in the output order

    def null_parts():
        out = []
        for run, (null_lo, null_hi, lo, hi) in zip(runs, spans):
            if null_hi > null_lo:
                out.append(run.slice(null_lo, null_hi - null_lo))
        return out

    emitted_sorter = _RangeSorter(orders)
    if nulls_first:
        np_parts = null_parts()
        if np_parts:
            yield retry_block(lambda p=np_parts: emitted_sorter(p))
    for lo_b, hi_b in ranges:
        parts = run_slices(0, lo_b, hi_b)
        if parts:
            yield retry_block(lambda p=parts: emitted_sorter(p))
    if not nulls_first:
        np_parts = null_parts()
        if np_parts:
            yield retry_block(lambda p=np_parts: emitted_sorter(p))


class _RangeSorter:
    """Upload + device-sort one range's host slices."""

    def __init__(self, orders):
        self._exec = TpuSortExec.for_orders(orders)

    def __call__(self, host_parts):
        from spark_rapids_tpu.columnar import DeviceTable, HostTable
        host = host_parts[0] if len(host_parts) == 1 else \
            HostTable.concat(host_parts)
        return self._exec._sort(DeviceTable.from_host(host))
