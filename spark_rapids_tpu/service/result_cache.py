"""Plan-fingerprint result cache.

Reference: Spark's ``CACHE TABLE`` / the reference plugin's
``GpuInMemoryTableScanExec`` cache the INPUT of a query; a serving
layer wants to cache the OUTPUT — the same SQL (or DSL plan) from
another tenant should not re-run q1 over an unchanged warehouse. The
cache keys on a CANONICAL STRUCTURAL FINGERPRINT of the submitted plan
(expression trees hash by their structural ``repr``; source tables by
identity token; file scans by path list) with the result-affecting conf
keys folded in, so two structurally identical queries hit regardless of
which tenant built them.

Correctness over hit rate, everywhere:

* anything the fingerprinter cannot PROVE structurally stable (a UDF
  closure, an unknown object with an address-y repr) marks the plan
  uncacheable — a miss, never a wrong hit;
* every catalog mutation bumps the process-wide invalidation epoch
  (:func:`bump_invalidation_epoch`) and every Delta commit bumps its
  TABLE's epoch (:func:`bump_table_epoch`); entries remember the epoch
  vector (global + the tables their plan read) they were filled under
  and a stale entry is evicted on lookup, never served — while a
  commit to an unrelated table leaves hot entries serving;
* the LRU is bounded by ``spark.rapids.service.resultCache.maxBytes``
  of ``HostTable.nbytes()``.

Hit/miss/evict/invalidation counters live in the unified metric
registry's ``resultCache`` scope.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

from spark_rapids_tpu.obs.metrics import metric_scope, register_metric
from spark_rapids_tpu.plan.fingerprint import (  # noqa: F401  (re-exports:
    # the fingerprint machinery moved to plan/fingerprint.py so the
    # executable cache keys off the SAME implementation; historical
    # import sites — delta/log.py, sql/catalog.py, session.py, tests —
    # keep resolving through this module)
    GLOBAL_EPOCH_KEY,
    RESULT_NEUTRAL_PREFIXES as _RESULT_NEUTRAL_PREFIXES,
    Unfingerprintable,
    bump_invalidation_epoch,
    bump_table_epoch,
    delta_table_id,
    epoch_snapshot,
    epochs_current,
    fingerprint,
    invalidation_epoch,
    plan_table_ids,
    register_epoch_listener,
    table_epoch,
    unregister_epoch_listener,
)
from spark_rapids_tpu.lockorder import ordered_lock

register_metric("resultCacheHits", "count", "ESSENTIAL",
                "service queries served from the plan-fingerprint cache")
register_metric("resultCacheMisses", "count", "ESSENTIAL",
                "service queries that executed (fingerprint absent, "
                "stale, or plan uncacheable)")
register_metric("resultCacheEvictions", "count", "ESSENTIAL",
                "entries evicted by the LRU byte bound")
register_metric("resultCacheInvalidations", "count", "ESSENTIAL",
                "stale entries dropped on lookup after an epoch bump")
register_metric("resultCacheBytes", "bytes", "MODERATE",
                "bytes currently held by the result cache")


# ---------------------------------------------------------------------------
# The LRU cache
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = ("table", "nbytes", "epochs", "event_record")

    def __init__(self, table, nbytes: int, epochs: dict, event_record):
        self.table = table
        self.nbytes = nbytes
        #: the epoch VECTOR the result was computed under: the global
        #: epoch keyed by GLOBAL_EPOCH_KEY plus one component per table
        #: the plan read — staleness is "any component moved", so a
        #: commit to an unrelated table leaves this entry serving
        self.epochs = epochs
        self.event_record = event_record

    @property
    def epoch(self) -> int:
        """The global component (back-compat for introspection)."""
        return self.epochs.get(GLOBAL_EPOCH_KEY, 0)


class ResultCache:
    """LRU HostTable cache bounded by bytes. Thread-safe; entries filled
    under an older invalidation epoch are dropped on lookup."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = ordered_lock("service.result_cache")
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        self._metrics = metric_scope("resultCache")
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    def _account_miss(self):
        self.misses += 1
        self._metrics.add("resultCacheMisses", 1)

    def get(self, key: Optional[str]):
        """The cached (table, event_record) for ``key``, or None. A None
        key (uncacheable plan) counts a miss."""
        if key is None:
            with self._lock:
                self._account_miss()
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is not None and not epochs_current(e.epochs):
                del self._entries[key]
                self._bytes -= e.nbytes
                self._metrics.add("resultCacheBytes", -e.nbytes)
                self.invalidations += 1
                self._metrics.add("resultCacheInvalidations", 1)
                e = None
            if e is None:
                self._account_miss()
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            self._metrics.add("resultCacheHits", 1)
            return e

    def put(self, key: Optional[str], table, event_record=None,
            epoch: Optional[int] = None,
            epochs: Optional[dict] = None) -> bool:
        """Insert a result. ``epochs`` is the epoch VECTOR the result
        was COMPUTED under (global + per-table components, captured by
        the caller before execution via ``epoch_snapshot``) — a write
        that landed mid-execution then stales the entry on its first
        lookup instead of the entry masquerading as post-write state.
        ``epoch`` (global-only) is the legacy spelling; both default to
        the current state for callers with no execution window.
        Oversized results (> max_bytes) are not cached. Returns
        whether stored."""
        if key is None or table is None:
            return False
        nbytes = int(table.nbytes())
        if nbytes > self.max_bytes:
            return False
        if epochs is None:
            epochs = epoch_snapshot() if epoch is None \
                else {GLOBAL_EPOCH_KEY: int(epoch)}
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                self._metrics.add("resultCacheBytes", -old.nbytes)
            while self._bytes + nbytes > self.max_bytes and self._entries:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                self._metrics.add("resultCacheBytes", -victim.nbytes)
                self.evictions += 1
                self._metrics.add("resultCacheEvictions", 1)
            self._entries[key] = _Entry(table, nbytes, epochs, event_record)
            self._bytes += nbytes
            self._metrics.add("resultCacheBytes", nbytes)
        return True

    def clear(self) -> None:
        with self._lock:
            self._metrics.add("resultCacheBytes", -self._bytes)
            self._entries.clear()
            self._bytes = 0

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "invalidations": self.invalidations,
                    "entries": len(self._entries), "bytes": self._bytes}
