"""DataFrame builder API over the plan layer.

The reference integrates into Spark SQL transparently; standalone, this
PySpark-flavored DataFrame API is the user surface that builds CPU plans
which the overrides engine then rewrites onto the TPU (session.py)."""

from __future__ import annotations

from typing import Optional, Sequence

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.ops.expr import (
    Alias,
    AttributeReference,
    Expression,
    col,
    lit,
    output_name,
)
from spark_rapids_tpu.plan import nodes as P


class DataFrame:
    def __init__(self, plan: P.PlanNode, session=None):
        self.plan = plan
        self.session = session

    # -- transformations ----------------------------------------------------
    def _wrap(self, plan: P.PlanNode) -> "DataFrame":
        return DataFrame(plan, self.session)

    def select(self, *exprs) -> "DataFrame":
        from spark_rapids_tpu.ops.collections import Explode
        exprs = [col(e) if isinstance(e, str) else e for e in exprs]

        # Spark rule: a generator (explode/posexplode) in the select list
        # plans as Generate(child) + Project; at most one generator
        gens = [(i, e) for i, e in enumerate(exprs)
                if isinstance(e, Explode)
                or (isinstance(e, Alias) and isinstance(e.children[0], Explode))]
        if gens:
            if len(gens) > 1:
                raise ValueError("only one generator per select (Spark rule)")
            i, e = gens[0]
            gen = e.children[0] if isinstance(e, Alias) else e
            if gen.pos:
                names = ["pos", output_name(e, "col")]
            else:
                names = [output_name(e, "col")]

            # requiredChildOutput: only columns the surrounding select
            # references pass through the Generate
            refs = set()

            def _walk_refs(x):
                if isinstance(x, AttributeReference):
                    refs.add(x.col_name)
                for ch in x.children:
                    _walk_refs(ch)

            for j, other in enumerate(exprs):
                if j != i:
                    _walk_refs(other)
            g = P.Generate(self.plan, gen.children[0], gen.pos, gen.outer,
                           names, required=sorted(refs))
            out = [col(n) if isinstance(n, str) else n
                   for n in ([*exprs[:i]]
                             + [col(n2) for n2 in names]
                             + [*exprs[i + 1:]])]
            return DataFrame(g, self.session)._wrap(P.Project(g, out))

        # scalar pandas UDFs in the select list plan as ArrowEvalPython +
        # Project (the reference splits PythonUDF out of projects the same
        # way — GpuArrowEvalPythonExec)
        from spark_rapids_tpu.plan.pandas_udf import (
            PandasUDFExpr,
            extract_scalar_udfs,
        )
        def _contains_udf(e):
            return isinstance(e, PandasUDFExpr) or any(
                _contains_udf(c) for c in e.children)

        if any(_contains_udf(e) for e in exprs):
            names = [output_name(e, f"col{i}") for i, e in enumerate(exprs)]
            plan, rewritten = extract_scalar_udfs(self.plan, exprs, names)
            return self._wrap(P.Project(plan, rewritten))
        return self._wrap(P.Project(self.plan, exprs))

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """fn(iterator of pandas DataFrames) -> iterator of pandas
        DataFrames (Spark mapInPandas; GpuMapInPandasExec analog)."""
        from spark_rapids_tpu.plan.pandas_udf import MapInPandas
        return self._wrap(MapInPandas(self.plan, fn, schema))

    mapInPandas = map_in_pandas

    def map_in_arrow(self, fn, schema) -> "DataFrame":
        """fn(iterator of pyarrow RecordBatches) -> iterator of pyarrow
        RecordBatches (Spark mapInArrow; GpuMapInArrowExec analog)."""
        from spark_rapids_tpu.plan.pandas_udf import MapInArrow
        return self._wrap(MapInArrow(self.plan, fn, schema))

    mapInArrow = map_in_arrow

    def with_column(self, name: str, expr: Expression) -> "DataFrame":
        existing = [col(n) for n, _ in self.plan.output_schema() if n != name]
        return self.select(*existing, expr.alias(name))

    def filter(self, condition: Expression) -> "DataFrame":
        return self._wrap(P.Filter(self.plan, condition))

    where = filter

    def group_by(self, *keys) -> "GroupedData":
        keys = [col(k) if isinstance(k, str) else k for k in keys]
        return GroupedData(self, keys)

    groupBy = group_by

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def sort(self, *orders, ascending: bool = True) -> "DataFrame":
        sos = []
        for o in orders:
            if isinstance(o, str):
                o = col(o)
            if isinstance(o, P.SortOrder):
                sos.append(o)
            else:
                sos.append(P.SortOrder(o, ascending))
        return self._wrap(P.Sort(self.plan, sos))

    order_by = sort
    orderBy = sort

    def limit(self, n: int) -> "DataFrame":
        if isinstance(self.plan, P.Sort):
            # ORDER BY + LIMIT plans as TakeOrderedAndProject (per-batch
            # top-k, no full sorted materialization — Spark's planner rule)
            return self._wrap(P.TakeOrderedAndProject(
                self.plan.children[0], self.plan.orders, n))
        # LIMIT without ordering = CollectLimit (Spark's planner shape)
        return self._wrap(P.CollectLimit(self.plan, n))

    def sample(self, fraction: float, seed: int = 0) -> "DataFrame":
        return self._wrap(P.Sample(self.plan, fraction, seed))

    def cache(self) -> "DataFrame":
        return self._wrap(P.CachedRelation(self.plan, self.session))

    def union(self, other: "DataFrame") -> "DataFrame":
        return self._wrap(P.Union([self.plan, other.plan]))

    def join(self, other: "DataFrame", on=None, how: str = "inner") -> "DataFrame":
        if on is None:
            return self._wrap(P.Join(self.plan, other.plan, "cross", [], []))
        if isinstance(on, Expression):
            # arbitrary condition over both sides -> nested-loop join
            return self._wrap(P.Join(self.plan, other.plan, how, [], [],
                                     condition=on))
        if isinstance(on, str):
            on = [on]
        if isinstance(on, (list, tuple)) and on and isinstance(on[0], str):
            lk = [col(k) for k in on]
            rk = [col(k) for k in on]
            return self._wrap(P.Join(self.plan, other.plan, how, lk, rk))
        raise ValueError(
            "join `on` must be a column name, list of names, or a condition "
            "Expression")

    def stack(self, n: int, *exprs, names=None) -> "DataFrame":
        """stack(n, e1..ek): n output rows per input row with k/n columns
        (reference: GpuGenerateExec Stack). TPU rewrite: a UNION of n
        projections — fully static shapes, no generator kernel (row order
        across generated rows is unspecified, as in Spark)."""
        exprs = [col(e) if isinstance(e, str) else e for e in exprs]
        if n <= 0 or len(exprs) % n != 0:
            raise ValueError("stack(n, ...) needs a multiple of n exprs")
        width = len(exprs) // n
        if names is None:
            names = [f"col{i}" for i in range(width)]
        parts = []
        for r in range(n):
            row = [exprs[r * width + j].alias(names[j])
                   for j in range(width)]
            parts.append(self.select(*row).plan)
        return self._wrap(parts[0] if len(parts) == 1 else P.Union(parts))

    def replicate_rows(self, n_expr) -> "DataFrame":
        """replicate_rows(n): each row repeated n times (reference:
        GpuReplicateRows). TPU rewrite: explode(sequence(1, n)) and drop
        the sequence column — rides the existing Generate machinery."""
        from spark_rapids_tpu.functions import sequence
        n_expr = col(n_expr) if isinstance(n_expr, str) else n_expr
        from spark_rapids_tpu.ops.collections import Explode
        keep = [c for c, _ in self.plan.output_schema()]
        # rows with n <= 0 are DROPPED (GpuReplicateRows semantics);
        # filtering first also pins the sequence direction to ascending
        filtered = self.filter(n_expr > lit(0))
        seq = sequence(lit(1), n_expr, lit(1))
        exploded = filtered.select(*keep, Explode(seq).alias("__rep"))
        return exploded.select(*keep)

    def with_windows(self, **named_exprs) -> "DataFrame":
        """Append window-function columns:
        df.with_windows(rn=F.row_number().over(W.partition_by("k").order_by("v")))

        GROUPED_AGG pandas UDFs applied .over(spec) plan separately as a
        WindowInPandas node (GpuWindowInPandasExec analog)."""
        from spark_rapids_tpu.plan.pandas_udf import (
            WindowedPandasUDF,
            WindowInPandas,
        )
        builtin = [(n, e) for n, e in named_exprs.items()
                   if not isinstance(e, WindowedPandasUDF)]
        pandas_udfs = []
        for n, e in named_exprs.items():
            if isinstance(e, WindowedPandasUDF):
                args = []
                for a in e.udf.children:
                    if not isinstance(a, AttributeReference):
                        raise ValueError(
                            "window pandas UDF args must be plain columns")
                    args.append(a.col_name)
                for k in (list(e.spec.partition_exprs)
                          + [o.expr for o in e.spec.orders]):
                    if not isinstance(k, AttributeReference):
                        raise ValueError(
                            "window pandas UDF partition/order keys must "
                            f"be plain columns, got {k}")
                pandas_udfs.append((n, e.udf.fn, e.udf.data_type, args,
                                    e.spec))
        out = self
        if builtin:
            out = out._wrap(P.WindowNode(out.plan, builtin))
        if pandas_udfs:
            out = out._wrap(WindowInPandas(out.plan, pandas_udfs))
        return out

    def repartition(self, num_partitions: int, *keys) -> "DataFrame":
        keys = [col(k) if isinstance(k, str) else k for k in keys]
        mode = "hash" if keys else "roundrobin"
        return self._wrap(P.Exchange(self.plan, mode, num_partitions, keys))

    def create_or_replace_temp_view(self, name: str) -> None:
        """Register this DataFrame's plan as a temp view resolvable from
        session.sql() / session.table() (requires a session)."""
        if self.session is None:
            raise ValueError(
                "create_or_replace_temp_view requires a session-attached "
                "DataFrame")
        self.session.catalog.create_or_replace_temp_view(name, self)

    createOrReplaceTempView = create_or_replace_temp_view

    # -- actions ------------------------------------------------------------
    @property
    def schema(self):
        return self.plan.output_schema()

    @property
    def columns(self):
        return [n for n, _ in self.plan.output_schema()]

    def collect_table(self) -> HostTable:
        if self.session is not None:
            # SQL-origin DataFrames carry their text; hand it to the
            # session so the query event log records it
            sql_text = getattr(self, "sql_text", None)
            if sql_text is not None:
                self.session.next_query_sql = sql_text
            return self.session.execute(self.plan)
        return self.plan.collect_cpu()

    def collect(self):
        t = self.collect_table()
        cols = [c.to_pylist() for c in t.columns]
        return [tuple(c[i] for c in cols) for i in range(t.num_rows)]

    def to_pandas(self):
        return self.collect_table().to_pandas()

    def to_device_arrays(self):
        """Zero-copy device export (ColumnarRdd analog): {name: jax
        arrays} + row count, no host round trip. See to_device_arrays()."""
        return to_device_arrays(self)

    def to_pydict(self):
        return self.collect_table().to_pydict()

    def count(self) -> int:
        return self.collect_table().num_rows

    def explain(self) -> str:
        if self.session is not None:
            out = self.session.explain(self.plan)
        else:
            out = self.plan.tree_string()
        # SQL-origin plans (session.sql) carry their text so the explain
        # output ties fallback reasons back to the query
        sql_text = getattr(self, "sql_text", None)
        if sql_text:
            one_line = " ".join(sql_text.split())
            return f"-- SQL: {one_line}\n{out}"
        return out

    # -- writers (reference: GpuDataWritingCommandExec + format writers) ----
    def _write(self, fmt: str, path: str, partition_by, options):
        """Plan a WriteFiles command: the CHILD runs through the overrides
        engine (device when convertible), the write commits atomically
        (staging dir + rename + _SUCCESS), and the stats row returns."""
        node = P.WriteFiles(self.plan, fmt, path, partition_by, options)
        if self.session is not None:
            return self.session.execute(node)
        return node.collect_cpu()

    def write_parquet(self, path: str, partition_by=None, **options):
        return self._write("parquet", path, partition_by, options)

    def write_orc(self, path: str, partition_by=None, **options):
        return self._write("orc", path, partition_by, options)

    def write_csv(self, path: str, partition_by=None, **options):
        return self._write("csv", path, partition_by, options)

    def write_json(self, path: str, partition_by=None, **options):
        return self._write("json", path, partition_by, options)

    def write_hive_text(self, path: str, partition_by=None, **options):
        return self._write("hive_text", path, partition_by, options)

    def write_delta(self, path: str, mode: str = "error",
                    partition_by=None, merge_schema: bool = False) -> int:
        """Write as a Delta table; returns the committed version
        (reference: delta-lake module write path). ``merge_schema``
        allows adding columns (Spark mergeSchema)."""
        from spark_rapids_tpu.delta import write_delta
        return write_delta(self.plan, self.session, path, mode=mode,
                           partition_by=partition_by,
                           merge_schema=merge_schema)


class GroupedData:
    def __init__(self, df: DataFrame, keys: Sequence[Expression]):
        self.df = df
        self.keys = keys

    def _key_names(self, what: str):
        names = []
        for k in self.keys:
            if not isinstance(k, AttributeReference):
                raise ValueError(
                    f"{what} requires plain column-name grouping keys")
            names.append(k.col_name)
        return names

    def pivot(self, pivot_col: str, values) -> "PivotedData":
        """df.group_by(k).pivot(c, [v1, v2]).agg(...) — the reference's
        GpuPivotFirst surface. The TPU rewrite turns each (pivot value,
        aggregate) pair into a conditionally-masked aggregate
        (agg(when(c == v, x))) — the same rewrite Spark applies before
        PivotFirst, with no new device kernel."""
        return PivotedData(self, pivot_col, list(values))

    def agg(self, *aggs) -> DataFrame:
        from spark_rapids_tpu.plan.pandas_udf import (
            AggregateInPandas,
            PandasUDFExpr,
        )

        def _udf_of(e):
            inner = e.children[0] if isinstance(e, Alias) else e
            return inner if isinstance(inner, PandasUDFExpr) else None

        udfs = [_udf_of(e) for e in aggs]
        if any(u is not None for u in udfs):
            if not all(u is not None and u.kind == "grouped_agg"
                       for u in udfs):
                raise ValueError(
                    "pandas grouped-agg UDFs cannot mix with built-in "
                    "aggregates in one agg() (Spark restriction)")
            keys = self._key_names("agg with pandas UDFs")
            entries = []
            for e, u in zip(aggs, udfs):
                out = output_name(e, u.udf_name)
                args = []
                for a in u.children:
                    if not isinstance(a, AttributeReference):
                        raise ValueError(
                            "pandas grouped-agg UDF args must be plain "
                            "columns")
                    args.append(a.col_name)
                entries.append((out, u.fn, u.data_type, args))
            return self.df._wrap(
                AggregateInPandas(self.df.plan, keys, entries))
        return self.df._wrap(P.Aggregate(self.df.plan, self.keys, list(aggs)))

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """fn(pandas DataFrame of one group) -> pandas DataFrame
        (Spark applyInPandas; GpuFlatMapGroupsInPandasExec analog)."""
        from spark_rapids_tpu.plan.pandas_udf import FlatMapGroupsInPandas
        keys = self._key_names("apply_in_pandas")
        return self.df._wrap(
            FlatMapGroupsInPandas(self.df.plan, keys, fn, schema))

    applyInPandas = apply_in_pandas

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        """df1.group_by(k).cogroup(df2.group_by(k)) — Spark cogroup
        (GpuFlatMapCoGroupsInPandasExec analog)."""
        return CoGroupedData(self, other)


class CoGroupedData:
    """Pair of grouped DataFrames awaiting apply_in_pandas (pyspark's
    PandasCogroupedOps)."""

    def __init__(self, left: GroupedData, right: GroupedData):
        self.left = left
        self.right = right

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """fn(left pandas DataFrame, right pandas DataFrame of one
        cogrouped key) -> pandas DataFrame."""
        from spark_rapids_tpu.plan.pandas_udf import FlatMapCoGroupsInPandas
        lk = self.left._key_names("cogroup")
        rk = self.right._key_names("cogroup")
        return self.left.df._wrap(FlatMapCoGroupsInPandas(
            self.left.df.plan, self.right.df.plan, lk, rk, fn, schema))

    applyInPandas = apply_in_pandas


def from_pydict(data, dtypes=None, session=None, num_batches: int = 1) -> DataFrame:
    table = HostTable.from_pydict(data, dtypes)
    return from_host_table(table, session, num_batches)


def from_pandas(df, session=None, num_batches: int = 1) -> DataFrame:
    return from_host_table(HostTable.from_pandas(df), session, num_batches)


def from_host_table(table: HostTable, session=None, num_batches: int = 1) -> DataFrame:
    if num_batches <= 1 or table.num_rows == 0:
        batches = [table]
    else:
        per = -(-table.num_rows // num_batches)
        batches = [table.slice(i * per, min(per, table.num_rows - i * per))
                   for i in range(num_batches) if i * per < table.num_rows]
    return DataFrame(P.LocalScan(batches), session)


def range_df(start: int, end: Optional[int] = None, step: int = 1, session=None) -> DataFrame:
    if end is None:
        start, end = 0, start
    return DataFrame(P.RangeNode(start, end, step), session)


def to_device_arrays(df: "DataFrame"):
    """ColumnarRdd analog (reference: sql-plugin-api ColumnarRdd.scala:54
    — zero-copy GPU-table export for ML/XGBoost): execute the plan on
    device and hand back the raw jax arrays WITHOUT a host round trip:
    {name: (data, validity)} per column, plus the live row count. String
    columns export as (codes, validity, dictionary)."""
    from spark_rapids_tpu.overrides.rules import apply_overrides
    from spark_rapids_tpu.execs.base import DeviceToHost
    from spark_rapids_tpu.runtime.retry import retry_block
    if df.session is None:
        # session-less DataFrame: CPU plan, one upload at the end
        # (retry_block: a device-budget squeeze spills and replays)
        from spark_rapids_tpu.columnar import DeviceTable, HostTable
        host = HostTable.concat(list(df.plan.execute_cpu()))
        t = retry_block(lambda: DeviceTable.from_host(host))
        out = {}
        for name, c in zip(t.names, t.columns):
            out[name] = ((c.data, c.validity, c.dictionary)
                         if c.dictionary is not None
                         else (c.data, c.validity))
        return out, t.num_rows
    executable, _ = apply_overrides(df.plan, df.session.conf)
    if isinstance(executable, DeviceToHost):
        exec_dev = executable.tpu_exec
        batches = list(exec_dev.execute())
    else:
        # fully-fallen-back plan: upload the host result once
        from spark_rapids_tpu.columnar import DeviceTable, HostTable
        host = HostTable.concat(list(executable.execute_cpu()))
        batches = [retry_block(lambda: DeviceTable.from_host(host))]
    if len(batches) != 1:
        from spark_rapids_tpu.columnar.table import concat_device
        batches = [concat_device(batches)]
    t = batches[0]
    out = {}
    for name, c in zip(t.names, t.columns):
        if c.dictionary is not None:
            out[name] = (c.data, c.validity, c.dictionary)
        else:
            out[name] = (c.data, c.validity)
    return out, t.num_rows


class PivotedData:
    """group_by(...).pivot(col, values) — expands to masked aggregates."""

    def __init__(self, grouped: GroupedData, pivot_col: str, values):
        self.grouped = grouped
        self.pivot_col = pivot_col
        self.values = values

    def agg(self, *aggs) -> DataFrame:
        from spark_rapids_tpu.ops import aggregates as _agg
        from spark_rapids_tpu.ops.conditional import CaseWhen
        from spark_rapids_tpu.ops.expr import col as _col, lit as _lit
        from spark_rapids_tpu.ops.expr import Alias, output_name

        out = []
        for pv in self.values:
            for i, a in enumerate(aggs):
                name = output_name(a, f"agg{i}")
                fn = a.children[0] if isinstance(a, Alias) else a
                if not isinstance(fn, _agg.AggregateFunction):
                    raise ValueError(f"pivot agg must be an aggregate: {a!r}")
                if fn.child is None:  # count(*): count matching rows
                    masked = _agg.Count(CaseWhen(
                        _col(self.pivot_col) == _lit(pv), _lit(1)))
                else:
                    # with_children preserves extra ctor params
                    # (Percentile.percentage etc.)
                    masked = fn.with_children([CaseWhen(
                        _col(self.pivot_col) == _lit(pv), fn.child)])
                label = (f"{pv}" if len(aggs) == 1 else f"{pv}_{name}")
                out.append(Alias(masked, label))
        return self.grouped.agg(*out)
