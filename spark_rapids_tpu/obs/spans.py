"""Thread-aware host-side span tracer + exec-boundary instrumentation.

Reference (SURVEY.md §5): NVTX ranges (``NvtxWithMetrics.scala``) put
operator ranges on the DEVICE timeline; nothing in the reference shows
where HOST wall time goes — which on the tunneled TPU is where queries
actually live (transfers, shuffle IO, serialization, spill). This
tracer records host spans (enter/exit wall times, thread, parent,
query/op attribution) and exports Chrome trace-event JSON, so a host
timeline loads in Perfetto/chrome://tracing NEXT TO the Xprof device
trace the profiler collects.

Two layers:

* :class:`SpanTracer` / the process-wide :data:`TRACER` — collection is
  enabled per query by the session (``spark.rapids.trace.enabled``, or
  implicitly while the event log needs attribution). Disabled cost is
  one attribute read per site.
* :func:`install_observation` — the per-query exec-boundary wrapper
  (the ``install_fault_boundaries`` threading pattern from PR 3): every
  device exec's ``execute``/``execute_masked`` and the ``DeviceToHost``
  root get (a) a span per batch pull when tracing, and (b) the
  ESSENTIAL ``opTime``/``numOutputRows``/``numOutputBatches`` metrics
  ALWAYS — row counts that only exist on device are deferred and
  resolved in ONE batched fetch by :func:`finalize_observation`, never
  a per-batch sync.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.conf import bool_conf, str_conf
from spark_rapids_tpu.lockorder import ordered_lock

TRACE_ENABLED = bool_conf(
    "spark.rapids.trace.enabled", False,
    "Collect host-side spans for every query and export a Chrome "
    "trace-event JSON per query under spark.rapids.trace.dir — load it "
    "in Perfetto next to the Xprof device trace.")

TRACE_DIR = str_conf(
    "spark.rapids.trace.dir", "/tmp/rapids_tpu_trace",
    "Directory for exported Chrome trace JSON files (one "
    "query_<N>.trace.json per traced query).")

#: hard cap on buffered spans per query (a runaway batch loop must
#: degrade the trace, not the process); dropped spans are counted
_MAX_SPANS = 200_000


class Span:
    __slots__ = ("sid", "name", "cat", "t0", "t1", "tid", "tname",
                 "parent", "args", "ctx")

    def __init__(self, sid, name, cat, t0, tid, tname, parent, args,
                 ctx=None):
        self.sid = sid
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.t1 = None
        self.tid = tid
        self.tname = tname
        self.parent = parent
        self.args = args
        self.ctx = ctx

    @property
    def dur(self) -> float:
        return (self.t1 - self.t0) if self.t1 is not None else 0.0


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _LiveSpan:
    __slots__ = ("tracer", "span")

    def __init__(self, tracer, span):
        self.tracer = tracer
        self.span = span

    def __enter__(self):
        return self.span

    def __exit__(self, *exc):
        self.tracer.end(self.span)
        return False


class _QueryCtx:
    """One query's span buffer, owned by the thread that called
    ``begin_query``. Per-thread span stacks live ON the context (keyed by
    thread id) so helper-thread stacks die with the query instead of
    leaking stale parents into the next query on that thread."""

    __slots__ = ("query_id", "owner_tid", "spans", "dropped", "t0",
                 "stacks", "closed")

    def __init__(self, query_id: int, owner_tid: int):
        self.query_id = query_id
        self.owner_tid = owner_tid
        self.spans: List[Span] = []
        self.dropped = 0
        self.t0 = time.perf_counter()
        self.stacks: Dict[int, list] = {}
        self.closed = False


#: sentinel bound to a thread's ctx slot while it runs an UNOBSERVED
#: query — blocks the single-active-context adoption below
_ADOPT_BLOCKED = object()


class SpanTracer:
    """Process-wide span collector, safe for CONCURRENT queries: each
    ``begin_query`` opens a :class:`_QueryCtx` bound to the calling
    thread (the query service executes every query on its own worker
    thread), and spans recorded on that thread land in that context.
    A thread with no bound context (a shuffle/IO pool helper) adopts the
    single active context when exactly one query is in flight — under
    concurrency its spans are dropped rather than misattributed.
    ``enabled`` is True while ANY query collects; record sites keep
    their one-attribute-read disabled cost."""

    def __init__(self):
        self.enabled = False
        self._lock = ordered_lock("obs.spans")
        self._ctxs: Dict[int, _QueryCtx] = {}  # owner tid -> ctx
        self._next_id = 0
        self._tls = threading.local()
        self._unobserved = 0  # in-flight queries with NO envelope

    # -- context resolution -------------------------------------------------
    def _ctx(self) -> Optional[_QueryCtx]:
        ctx = getattr(self._tls, "ctx", None)
        if ctx is _ADOPT_BLOCKED:
            # this thread runs an UNOBSERVED query concurrently with an
            # observed one: its spans belong to neither active ctx
            return None
        if ctx is not None and not ctx.closed:
            return ctx
        # helper thread: adopt the only active query, but ONLY while no
        # unobserved query is in flight anywhere — an unobserved
        # query's shuffle/IO pool work is indistinguishable from the
        # observed query's here, and misattribution is worse than a
        # dropped helper span
        with self._lock:
            if len(self._ctxs) == 1 and not self._unobserved:
                return next(iter(self._ctxs.values()))
        return None

    def begin_unobserved_query(self) -> None:
        """Mark this thread as executing a query WITHOUT an observation
        envelope (event log and tracing off for its session): neither
        its own spans nor its helper-pool work may be adopted into some
        other session's concurrently active query context."""
        self._tls.ctx = _ADOPT_BLOCKED
        with self._lock:
            self._unobserved += 1

    def end_unobserved_query(self) -> None:
        if getattr(self._tls, "ctx", None) is _ADOPT_BLOCKED:
            self._tls.ctx = None
            with self._lock:
                self._unobserved -= 1

    def _stack(self, ctx: _QueryCtx) -> list:
        return ctx.stacks.setdefault(threading.get_ident(), [])

    # -- compat / introspection --------------------------------------------
    @property
    def _spans(self) -> List[Span]:
        """All in-flight spans across active contexts (tests/debug)."""
        with self._lock:
            return [s for c in self._ctxs.values() for s in c.spans]

    @property
    def main_tid(self) -> Optional[int]:
        """Owner thread of the CURRENT thread's query context."""
        ctx = self._ctx()
        return ctx.owner_tid if ctx is not None else None

    @property
    def query_id(self) -> Optional[int]:
        ctx = self._ctx()
        return ctx.query_id if ctx is not None else None

    @property
    def dropped(self) -> int:
        ctx = self._ctx()
        return ctx.dropped if ctx is not None else 0

    # -- collection --------------------------------------------------------
    def begin_query(self, query_id: int) -> _QueryCtx:
        tid = threading.get_ident()
        ctx = _QueryCtx(query_id, tid)
        with self._lock:
            self._ctxs[tid] = ctx
            self.enabled = True
        self._tls.ctx = ctx
        return ctx

    def end_query(self) -> List[Span]:
        """Stop collecting THIS thread's query and return its finished
        spans."""
        tid = threading.get_ident()
        with self._lock:
            ctx = self._ctxs.pop(tid, None)
            self.enabled = bool(self._ctxs)
        self._tls.ctx = None
        if ctx is None:
            return []
        ctx.closed = True
        return [s for s in ctx.spans if s.t1 is not None]

    def begin(self, name: str, cat: str = "op", **args) -> Optional[Span]:
        if not self.enabled:
            return None
        ctx = self._ctx()
        if ctx is None:
            return None
        st = self._stack(ctx)
        parent = st[-1].sid if st else None
        tid = threading.get_ident()
        with self._lock:
            if ctx.closed:
                return None
            if len(ctx.spans) >= _MAX_SPANS:
                ctx.dropped += 1
                return None
            self._next_id += 1
            sp = Span(self._next_id, name, cat, time.perf_counter(), tid,
                      threading.current_thread().name, parent, args or None,
                      ctx)
            ctx.spans.append(sp)
        st.append(sp)
        return sp

    def end(self, span: Optional[Span]) -> None:
        if span is None or span.t1 is not None:
            return  # idempotent: an error path may re-end a closed span
        span.t1 = time.perf_counter()
        ctx = span.ctx
        st = ctx.stacks.get(span.tid) if ctx is not None else None
        if not st:
            return
        if st[-1] is span:
            st.pop()
        elif span in st:        # exception unwound past nested spans
            while st and st[-1] is not span:
                st.pop().t1 = span.t1
            if st:
                st.pop()

    def span(self, name: str, cat: str = "op", **args):
        """Context manager; zero-allocation no-op when disabled."""
        if not self.enabled:
            return _NOOP
        return _LiveSpan(self, self.begin(name, cat, **args))

    # -- cross-host trace propagation ---------------------------------------
    def add_remote_spans(self, source: str, payload, anchor_t0: float,
                         cap: int = 256) -> int:
        """Merge span summaries shipped back by a cluster EXECUTOR into
        this thread's active query context (runtime/cluster.py scan
        replies). Each payload entry is ``{name, cat, t0, dur[, args]}``
        with ``t0`` relative to the executor's scan start; spans land on
        a synthetic per-source thread row (``executor-<host>``) so the
        Chrome trace shows one lane per executor host next to the
        driver's lanes. The executor clock is a DIFFERENT perf_counter
        domain — ``anchor_t0`` (the driver's dispatch-send time) anchors
        the remote window, so remote spans are positioned relative to
        the dispatch, exact in duration, approximate in offset by the
        one-way wire latency. Returns the number of spans merged."""
        if not self.enabled or not payload:
            return 0
        ctx = self._ctx()
        if ctx is None:
            return 0
        # stable synthetic tid per source, far above real thread idents'
        # typical range and deterministic across runs of one process
        tid = 0x52000000 + (hash(str(source)) & 0xFFFFF)
        tname = f"executor-{source}"
        merged = 0
        with self._lock:
            if ctx.closed:
                return 0
            for p in payload[:max(0, int(cap))]:
                if len(ctx.spans) >= _MAX_SPANS:
                    ctx.dropped += 1
                    continue
                try:
                    t0 = anchor_t0 + float(p["t0"])
                    dur = max(0.0, float(p["dur"]))
                    name = str(p["name"])
                except (KeyError, TypeError, ValueError):
                    continue  # a malformed entry degrades the trace only
                self._next_id += 1
                sp = Span(self._next_id, name, str(p.get("cat", "remote")),
                          t0, tid, tname, None, p.get("args") or None, ctx)
                sp.t1 = t0 + dur
                ctx.spans.append(sp)
                merged += 1
        return merged


TRACER = SpanTracer()


def span(name: str, cat: str = "op", **args):
    return TRACER.span(name, cat, **args)


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------


def to_chrome_trace(spans: List[Span], query_id=None) -> dict:
    """Chrome trace-event JSON (the ``traceEvents`` array form) — loads
    in Perfetto / chrome://tracing. Timestamps are microseconds on the
    perf_counter clock; complete events (``ph: "X"``) carry durations."""
    events = []
    threads = {}
    for s in spans:
        threads.setdefault(s.tid, s.tname)
        ev = {"name": s.name, "cat": s.cat, "ph": "X",
              "ts": round(s.t0 * 1e6, 3), "dur": round(s.dur * 1e6, 3),
              "pid": 1, "tid": s.tid}
        if s.args:
            ev["args"] = dict(s.args)
        events.append(ev)
    for tid, tname in sorted(threads.items()):
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": tid, "args": {"name": tname}})
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    if query_id is not None:
        trace["otherData"] = {"query": query_id}
    return trace


def write_chrome_trace(path: str, spans: List[Span], query_id=None) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(spans, query_id), f)
    return path


# ---------------------------------------------------------------------------
# Span aggregation (the event record's span summary)
# ---------------------------------------------------------------------------


def union_seconds(intervals) -> float:
    """Total length covered by at least one [t0, t1) interval."""
    total = 0.0
    end = None
    for t0, t1 in sorted(intervals):
        if end is None or t0 > end:
            total += t1 - t0
            end = t1
        elif t1 > end:
            total += t1 - end
            end = t1
    return total


def summarize_spans(spans: List[Span], exec_tid: Optional[int],
                    wall_s: float) -> dict:
    """Per-query span summary: category totals (union per category, so
    nesting never double-counts), attribution of the query wall to
    NAMED spans on the thread that EXECUTED the query (the thread that
    opened the query context — the process main thread for direct
    ``session.execute`` calls, a service worker thread for scheduled
    queries), and helper-thread totals."""
    by_cat: Dict[str, list] = {}
    main_intervals = []
    worker: Dict[str, list] = {}
    for s in spans:
        by_cat.setdefault(s.cat, []).append((s.t0, s.t1))
        if s.tid == exec_tid:
            if s.cat != "query":
                main_intervals.append((s.t0, s.t1))
        else:
            worker.setdefault(s.cat, []).append((s.t0, s.t1))
    attributed = min(union_seconds(main_intervals), wall_s)
    return {
        "byCategoryS": {c: round(union_seconds(iv), 6)
                        for c, iv in sorted(by_cat.items())},
        "workerByCategoryS": {c: round(union_seconds(iv), 6)
                              for c, iv in sorted(worker.items())},
        "attributedS": round(attributed, 6),
        "untrackedS": round(max(wall_s - attributed, 0.0), 6),
        "spanCount": len(spans),
    }


# ---------------------------------------------------------------------------
# Exec-boundary instrumentation
# ---------------------------------------------------------------------------


def _observed(fn, e, name: str, count_output: bool):
    """Wrap one execute/execute_masked with per-pull spans + metrics.
    The per-instance ``_obs_depth`` guard keeps the two protocol layers
    of one exec (execute() delegating to execute_masked() or vice
    versa, both instance-wrapped) from double-counting a batch."""

    def wrapped(*args, **kwargs):
        it = fn(*args, **kwargs)
        while True:
            if e._obs_depth:
                # inner protocol layer of the SAME exec: pass through
                try:
                    batch = next(it)
                except StopIteration:
                    return
                yield batch
                continue
            e._obs_depth = 1
            t0 = time.perf_counter()
            sp = TRACER.begin(name, "exec") if TRACER.enabled else None
            stop = False
            try:
                try:
                    batch = next(it)
                except StopIteration:
                    stop = True
            finally:
                TRACER.end(sp)
                e._obs_depth = 0
                e.metrics.add("opTime", time.perf_counter() - t0)
            if stop:
                if count_output:
                    # presence contract: an exec that ran to exhaustion
                    # always reports its output counts, even when zero
                    e.metrics.add("numOutputBatches", 0)
                    e.metrics.add("numOutputRows", 0)
                return
            if count_output:
                e.metrics.add("numOutputBatches", 1)
                nh = getattr(batch, "_nrows_host", None)
                if nh is not None:
                    e.metrics.add("numOutputRows", int(nh))
                else:
                    nd = getattr(batch, "nrows_dev", None)
                    if nd is not None:
                        # defer: nrows_dev is a tiny standalone device
                        # scalar — holding it pins ~4 bytes, not the
                        # table; finalize_observation fetches ALL
                        # pending counts in one host round trip
                        e._obs_pending_rows.append(nd)
                    else:
                        e.metrics.add("numOutputRows",
                                      int(getattr(batch, "num_rows", 0)))
            yield batch

    return wrapped


def install_observation(executable) -> None:
    """Wrap every device exec (and the DeviceToHost root) in the
    converted tree with the observation boundary. Installed per query by
    the session AFTER install_fault_boundaries, so spans/metrics see the
    fault-injected failures too. Idempotent per instance."""
    from spark_rapids_tpu.execs.base import DeviceToHost, TpuExec
    from spark_rapids_tpu.lore import _iter_tree
    for e in _iter_tree(executable):
        if getattr(e, "_obs_installed", False):
            continue
        if isinstance(e, TpuExec):
            e._obs_installed = True
            e._obs_depth = 0
            e._obs_pending_rows = []
            name = type(e).__name__
            e.execute = _observed(e.execute, e, name, count_output=True)
            e.execute_masked = _observed(e.execute_masked, e, name,
                                         count_output=True)
        elif isinstance(e, DeviceToHost):
            # DeviceToHost counts its own output rows on host (they are
            # free there) — the wrapper only adds opTime + the span
            e._obs_installed = True
            e._obs_depth = 0
            e._obs_pending_rows = []
            e.execute_cpu = _observed(e.execute_cpu, e, "DeviceToHost",
                                      count_output=False)


def finalize_observation(executable) -> None:
    """Resolve every deferred device row count in the tree with ONE
    batched host fetch (a single tunnel round trip however many execs
    deferred), folding the sums into each exec's ``numOutputRows``.
    Called lazily — by the event-log writer, ``session.last_metrics``
    and the metrics audit — so a query nobody inspects never pays the
    sync."""
    from spark_rapids_tpu.lore import _iter_tree
    owners = []
    scalars = []
    for e in _iter_tree(executable):
        pend = getattr(e, "_obs_pending_rows", None)
        if pend:
            owners.append((e, len(pend)))
            scalars.extend(pend)
            e._obs_pending_rows = []
    if not scalars:
        return
    from spark_rapids_tpu.dispatch import host_fetch
    fetched = host_fetch(scalars)
    i = 0
    for e, n in owners:
        total = sum(int(v) for v in fetched[i:i + n])
        i += n
        e.metrics.add("numOutputRows", total)
