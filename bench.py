"""Benchmark: TPC-H q1 pipeline through the full engine on the TPU vs the
pandas CPU baseline (the "Spark CPU" proxy — BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline: speedup vs CPU divided by the 3x target from BASELINE.md
(>= 1.0 means the target is met).

The timed run measures the steady state: the table is device-resident after
the warmup collect (scan device cache — GpuInMemoryTableScanExec analog,
spark.rapids.tpu.scan.deviceCache), matching the repeated-query pattern the
reference benchmarks (NDS runs queries against loaded tables). ``detail``
also reports the cold time (fresh upload included) for honesty. See PERF.md
for the full time breakdown."""

import json
import sys
import time


def main():
    import spark_rapids_tpu  # noqa: F401
    from spark_rapids_tpu.models.tpch import (
        lineitem_table,
        q1_dataframe,
        q1_pandas,
        q1_sql,
    )
    from spark_rapids_tpu.session import TpuSession

    argv = [a for a in sys.argv[1:]]
    use_sql = "--sql" in argv
    if use_sql:
        argv.remove("--sql")
    no_eventlog = "--no-eventlog" in argv
    if no_eventlog:
        argv.remove("--no-eventlog")
    require_tpu = "--require-tpu" in argv
    if require_tpu:
        argv.remove("--require-tpu")
    # the resolved backend is recorded in the artifact AND gateable
    # (tools.require_tpu_backend: the shared BENCH_r06-lesson gate)
    if require_tpu:
        from spark_rapids_tpu.tools import require_tpu_backend
        backend = require_tpu_backend()
    else:
        import jax
        backend = jax.default_backend()
    eventlog_dir = "/tmp/rapids_tpu_eventlog/bench"
    if "--eventlog-dir" in argv:
        i = argv.index("--eventlog-dir")
        if i + 1 >= len(argv):
            sys.exit("usage: bench.py [rows] [--sql] [--no-eventlog] "
                     "[--eventlog-dir DIR]")
        eventlog_dir = argv[i + 1]
        del argv[i:i + 2]
    rows = int(argv[0]) if argv else 4_000_000
    table = lineitem_table(rows, seed=0)

    # event logs on by default: every bench run leaves a
    # machine-readable artifact `python -m spark_rapids_tpu.tools`
    # can profile/compare (disable with --no-eventlog to measure the
    # observability-off steady state)
    conf = {}
    if not no_eventlog:
        conf = {"spark.rapids.sql.eventLog.enabled": "true",
                "spark.rapids.sql.eventLog.dir": eventlog_dir}
    session = TpuSession(conf)
    q1_build = q1_sql if use_sql else q1_dataframe

    # cold: compile + upload + first run
    session.next_query_tag = "q1_cold"
    t0 = time.perf_counter()
    _ = q1_build(session, table).collect_table()
    cold_s = time.perf_counter() - t0

    # warm (steady state): compiled, table device-resident. >=3 trials
    # with min AND median so tunnel-latency variance is distinguishable
    # from real regressions (VERDICT r4 weak #8)
    warms = []
    for _i in range(3):
        session.next_query_tag = "q1"
        t0 = time.perf_counter()
        tpu_result = q1_build(session, table).collect_table()
        warms.append(time.perf_counter() - t0)
    warms.sort()
    tpu_s = warms[0]
    tpu_med_s = warms[len(warms) // 2]

    # CPU baseline (pandas proxy for Spark CPU)
    _ = q1_pandas(table)  # warmup caches
    t0 = time.perf_counter()
    cpu_result = q1_pandas(table)
    cpu_s = time.perf_counter() - t0

    # sanity: same group count and close sums
    assert tpu_result.num_rows == len(cpu_result), \
        f"group mismatch {tpu_result.num_rows} vs {len(cpu_result)}"
    tpu_sum = sorted(tpu_result.to_pydict()["sum_qty"])
    cpu_sum = sorted(cpu_result["sum_qty"].tolist())
    for a, b in zip(tpu_sum, cpu_sum):
        assert abs(a - b) <= 1e-6 * max(1.0, abs(b)), f"sum_qty mismatch {a} vs {b}"

    # q3-style multi-join (broadcast-heavy plan shape): secondary detail
    from spark_rapids_tpu.models.tpch import q3_dataframe, q3_pandas, q3_tables
    cust, orders, li = q3_tables(rows // 4, seed=1)
    session.next_query_tag = "q3_cold"
    _ = q3_dataframe(session, cust, orders, li).collect_table()  # warm
    session.next_query_tag = "q3"
    t0 = time.perf_counter()
    q3_res = q3_dataframe(session, cust, orders, li).collect_table()
    q3_tpu_s = time.perf_counter() - t0
    q3_dispatches = getattr(session, "last_dispatches", None)
    _ = q3_pandas(cust, orders, li)
    t0 = time.perf_counter()
    q3_ref = q3_pandas(cust, orders, li)
    q3_cpu_s = time.perf_counter() - t0
    # validate before reporting a speedup from it
    got = q3_res.to_pydict()
    assert got["l_orderkey"] == [int(x) for x in q3_ref.l_orderkey], \
        "q3 key mismatch vs pandas"
    for a, b in zip(got["revenue"], q3_ref.revenue):
        assert abs(a - b) <= 1e-6 * max(1.0, abs(b)), f"q3 revenue {a} vs {b}"

    speedup = cpu_s / tpu_s if tpu_s > 0 else 0.0
    print(json.dumps({
        "metric": "tpch_q1_speedup_vs_cpu",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup / 3.0, 3),
        "backend": backend,
        "detail": {"rows": rows, "tpu_s": round(tpu_s, 4),
                   "tpu_med_s": round(tpu_med_s, 4),
                   "tpu_cold_s": round(cold_s, 4), "cpu_s": round(cpu_s, 4),
                   "q3_join_speedup": round(q3_cpu_s / max(q3_tpu_s, 1e-9), 3),
                   "q3_tpu_s": round(q3_tpu_s, 4),
                   "q3_cpu_s": round(q3_cpu_s, 4),
                   "q3_dispatches": q3_dispatches},
    }))


if __name__ == "__main__":
    main()
