"""Physical-plan verifier (reference: Catalyst plan integrity validation +
the spark-rapids assert-on-fallback test hook).

Walks a CONVERTED plan — the mixed TpuExec / transition / CPU-PlanNode
tree ``apply_overrides`` produces, including AQE-deferred build nodes —
and asserts the cross-layer invariants the tagging layer promises but
nothing previously checked.  Every violation is a structured
``Diagnostic`` with a plan path (``Join.left.Project``) and a stable rule
id (see diagnostics.RULES, PV-*)."""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.lint.diagnostics import Diagnostic, make

# ---------------------------------------------------------------------------
# tree walking over the heterogeneous converted plan
# ---------------------------------------------------------------------------


def _label(node) -> str:
    name = type(node).__name__
    if name.startswith("Tpu"):
        name = name[3:]
    for suffix in ("Exec", "Node"):
        if name.endswith(suffix) and len(name) > len(suffix):
            name = name[: -len(suffix)]
    return name


def _edges(node) -> List[Tuple[str, object]]:
    """(edge_label, child) pairs; edge_label '' means a plain descent."""
    from spark_rapids_tpu.execs.base import (
        DeviceToHost,
        HostToDevice,
        InputAdapter,
    )
    if isinstance(node, DeviceToHost):
        return [("", node.tpu_exec)]
    if isinstance(node, HostToDevice):
        return [("", node.cpu_node)]
    if isinstance(node, InputAdapter):
        return [("", node.source)]
    scan_node = getattr(node, "scan_node", None)
    if scan_node is not None:
        return [("scan", scan_node)]
    children = list(getattr(node, "children", ()) or ())
    if len(children) == 2:
        return [("left", children[0]), ("right", children[1])]
    if len(children) <= 1:
        return [("", c) for c in children]
    return [(f"child{i}", c) for i, c in enumerate(children)]


def iter_nodes(root) -> Iterable[Tuple[str, object]]:
    """Yield (plan_path, node) in pre-order; shared subtrees visit once."""
    seen = set()

    def rec(node, path):
        if id(node) in seen:
            return
        seen.add(id(node))
        yield path, node
        for edge, child in _edges(node):
            sub = f"{path}.{edge}.{_label(child)}" if edge \
                else f"{path}.{_label(child)}"
            yield from rec(child, sub)

    yield from rec(root, _label(root))


def _schema_of(node):
    try:
        return node.output_schema()
    except Exception as exc:  # malformed schema IS the finding
        return exc


# ---------------------------------------------------------------------------
# expression extraction (per node: what binds against which child schema)
# ---------------------------------------------------------------------------


def _window_exprs(window_cols):
    out = []
    for name, w in window_cols:
        fn = getattr(w, "function", None)
        spec = getattr(w, "spec", None)
        if fn is not None:
            for c in getattr(fn, "children", ()):
                out.append((f"window {name} input", c))
        if spec is not None:
            for p in getattr(spec, "partition_exprs", ()):
                out.append((f"window {name} partition key", p))
            for o in getattr(spec, "orders", ()):
                out.append((f"window {name} order key", o.expr))
    return out


def node_expr_bindings(node):
    """[(context, expression, binding_schema_or_None)] for every
    expression a node evaluates.  ``binding_schema`` is what its
    BoundReferences must resolve against (None = not checkable)."""
    from spark_rapids_tpu.execs import basic as XB
    from spark_rapids_tpu.execs import exchange as XX
    from spark_rapids_tpu.execs import sort as XS
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.execs.broadcast import TpuNestedLoopJoinExec
    from spark_rapids_tpu.execs.generate import TpuGenerateExec
    from spark_rapids_tpu.execs.join import TpuJoinExec
    from spark_rapids_tpu.execs.window import (
        TpuWindowExec,
        TpuWindowGroupLimitExec,
    )
    from spark_rapids_tpu.plan import nodes as P

    def child_schema(i=0):
        s = _schema_of(node.children[i])
        return s if isinstance(s, list) else None

    out = []
    if isinstance(node, (XB.TpuProjectExec,)):
        cs = child_schema()
        for e in node.exprs:
            out.append(("project expression", e, cs))
    elif isinstance(node, P.Project):
        cs = child_schema()
        for e in node.exprs:
            out.append(("project expression", e, cs))
    elif isinstance(node, (XB.TpuFilterExec, P.Filter)):
        out.append(("filter condition", node.condition, child_schema()))
    elif isinstance(node, (XB.TpuExpandExec, P.Expand)):
        cs = child_schema()
        for proj in node.projections:
            for e in proj:
                out.append(("expand projection", e, cs))
    elif isinstance(node, (XS.TpuSortExec, P.Sort)):
        cs = child_schema()
        for o in node.orders:
            out.append(("sort key", o.expr, cs))
    elif isinstance(node, (XS.TpuTakeOrderedAndProjectExec,
                           P.TakeOrderedAndProject)):
        cs = child_schema()
        for o in node.orders:
            out.append(("sort key", o.expr, cs))
        if node.project is not None:
            for e in node.project:
                out.append(("projection", e, cs))
    elif isinstance(node, TpuHashAggregateExec):
        cs = child_schema()
        for g in node.grouping:
            out.append(("grouping key", g, cs))
        for name, fn in node.agg_specs:
            child = getattr(fn, "child", None)
            if child is not None:
                out.append((f"aggregate {name} input", child, cs))
        for f in node.filters:
            out.append(("fused filter", f, cs))
    elif isinstance(node, P.Aggregate):
        cs = child_schema()
        for g in node.grouping:
            out.append(("grouping key", g, cs))
        for name, fn in node.agg_specs:
            child = getattr(fn, "child", None)
            if child is not None:
                out.append((f"aggregate {name} input", child, cs))
    elif isinstance(node, TpuJoinExec):
        ls, rs = node._left_schema, node._right_schema
        for k in node.left_keys:
            out.append(("left join key", k, ls))
        for k in node.right_keys:
            out.append(("right join key", k, rs))
        if node.condition is not None:
            out.append(("join condition", node.condition, ls + rs))
    elif isinstance(node, P.Join):
        ls = _schema_of(node.children[0])
        rs = _schema_of(node.children[1])
        ls = ls if isinstance(ls, list) else None
        rs = rs if isinstance(rs, list) else None
        for k in node.left_keys:
            out.append(("left join key", k, ls))
        for k in node.right_keys:
            out.append(("right join key", k, rs))
        if node.condition is not None:
            both = (ls + rs) if (ls is not None and rs is not None) else None
            out.append(("join condition", node.condition, both))
    elif isinstance(node, TpuNestedLoopJoinExec):
        if node.condition is not None:
            both = list(node._left_schema) + list(node._right_schema)
            out.append(("join condition", node.condition, both or None))
    elif isinstance(node, (XX.TpuShuffleExchangeExec, P.Exchange)):
        cs = child_schema()
        for k in node.keys:
            out.append(("partition key", k, cs))
    elif isinstance(node, (TpuGenerateExec, P.Generate)):
        out.append(("generator input", node.gen_child, child_schema()))
    elif isinstance(node, (TpuWindowExec, P.WindowNode)):
        cs = child_schema()
        for ctx, e in _window_exprs(node.window_cols):
            out.append((ctx, e, cs))
    elif isinstance(node, (TpuWindowGroupLimitExec, P.WindowGroupLimit)):
        cs = child_schema()
        for e in node.partition_exprs:
            out.append(("group-limit partition key", e, cs))
        for o in node.orders:
            out.append(("group-limit order key", o.expr, cs))
    return out


def _walk_expr(e):
    yield e
    for c in getattr(e, "children", ()):
        yield from _walk_expr(c)
    body = getattr(e, "_rebound", None)
    if body is not None:
        yield from _walk_expr(body)


# ---------------------------------------------------------------------------
# per-rule checks
# ---------------------------------------------------------------------------

#: exec/plan classes whose output schema must equal their child's exactly
_PASS_THROUGH = {
    "TpuFilterExec", "TpuLimitExec", "TpuCoalesceExec", "TpuSortExec",
    "TpuShuffleExchangeExec", "TpuBroadcastExchangeExec",
    "TpuAdaptiveBuildExec", "TpuWindowGroupLimitExec", "TpuSampleExec",
    "TpuMeshRelandExec",
    "Filter", "Sort", "Limit", "CollectLimit", "Exchange", "Sample",
    "WindowGroupLimit", "CachedRelation",
}


def _check_schema(path, node, diags):
    schema = _schema_of(node)
    if not isinstance(schema, list):
        diags.append(make("PV-SCHEMA", path,
                          f"output_schema() failed: {schema!r}"))
        return None
    for entry in schema:
        if (not isinstance(entry, tuple) or len(entry) != 2
                or not isinstance(entry[0], str) or not entry[0]
                or not isinstance(entry[1], T.DataType)):
            diags.append(make("PV-SCHEMA", path,
                              f"malformed schema entry {entry!r}"))
            return schema
    children = [c for _, c in _edges(node)]
    if type(node).__name__ in _PASS_THROUGH and children:
        cs = _schema_of(children[0])
        if isinstance(cs, list) and schema != cs:
            diags.append(make(
                "PV-SCHEMA", path,
                f"pass-through node output schema {_fmt_schema(schema)} "
                f"!= child schema {_fmt_schema(cs)}"))
    if type(node).__name__ in ("TpuUnionExec", "Union") and children:
        want = [dt for _, dt in schema]
        for i, c in enumerate(children):
            cs = _schema_of(c)
            if isinstance(cs, list) and [dt for _, dt in cs] != want:
                diags.append(make(
                    "PV-SCHEMA", path,
                    f"union child {i} types {_fmt_schema(cs)} != "
                    f"{_fmt_schema(schema)}"))
    return schema


def _fmt_schema(schema) -> str:
    return "[" + ", ".join(f"{n}:{dt.simple_string()}"
                           for n, dt in schema) + "]"


def _check_transitions(path, node, diags):
    from spark_rapids_tpu.execs.base import (
        DeviceToHost,
        HostToDevice,
        InputAdapter,
        TpuExec,
    )
    from spark_rapids_tpu.plan.nodes import PlanNode
    if isinstance(node, DeviceToHost):
        if not isinstance(node.tpu_exec, TpuExec):
            diags.append(make(
                "PV-TRANSITION", path,
                f"DeviceToHost wraps {_label(node.tpu_exec)}, which is "
                "not a device exec"))
        return
    if isinstance(node, HostToDevice):
        if not isinstance(node.cpu_node, PlanNode) or \
                isinstance(node.cpu_node, TpuExec):
            diags.append(make(
                "PV-TRANSITION", path,
                f"HostToDevice wraps {_label(node.cpu_node)}, which is "
                "not a host plan node"))
        return
    if isinstance(node, InputAdapter):
        if not isinstance(node.source, DeviceToHost):
            diags.append(make(
                "PV-TRANSITION", path,
                f"InputAdapter sources {_label(node.source)} instead of "
                "a DeviceToHost transition"))
        return
    if isinstance(node, TpuExec):
        for edge, child in _edges(node):
            if edge == "scan":
                continue  # file scans upload internally (sanctioned)
            if not isinstance(child, TpuExec):
                diags.append(make(
                    "PV-TRANSITION", path,
                    f"device exec consumes host node {_label(child)} "
                    "without a HostToDevice transition"))
    elif isinstance(node, PlanNode):
        for _, child in _edges(node):
            if isinstance(child, (TpuExec, DeviceToHost)):
                diags.append(make(
                    "PV-TRANSITION", path,
                    f"host node consumes device exec {_label(child)} "
                    "without an InputAdapter(DeviceToHost) transition"))


_VALID_PARTITIONING = ("hash", "range", "roundrobin", "single")


def _check_exchange(path, node, diags):
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.plan.nodes import Exchange
    if not isinstance(node, (TpuShuffleExchangeExec, Exchange)):
        return
    mode = getattr(node, "mode", None) or getattr(node, "partitioning", None)
    mode = str(mode).lower()
    n = node.num_partitions
    if mode not in _VALID_PARTITIONING:
        diags.append(make("PV-EXCHANGE", path,
                          f"unknown partitioning mode {mode!r}"))
        return
    if not isinstance(n, int) or n < 1:
        diags.append(make("PV-EXCHANGE", path,
                          f"invalid partition count {n!r}"))
    if mode == "single" and isinstance(node, TpuShuffleExchangeExec) \
            and n != 1:
        diags.append(make("PV-EXCHANGE", path,
                          f"single partitioning with {n} partitions"))
    if mode in ("hash", "range") and not node.keys:
        diags.append(make("PV-EXCHANGE", path,
                          f"{mode} partitioning requires keys"))
    cs = _schema_of(node.children[0]) if getattr(node, "children", ()) \
        else None
    if isinstance(cs, list):
        from spark_rapids_tpu.ops.expr import BoundReference
        for k in node.keys:
            for e in _walk_expr(k):
                if isinstance(e, BoundReference) and \
                        not (0 <= e.ordinal < len(cs)):
                    diags.append(make(
                        "PV-EXCHANGE", path,
                        f"partition key references ordinal {e.ordinal} "
                        f"outside the child's {len(cs)}-column output"))


def _iter_outer_refs(e):
    """BoundReferences that bind against the node's CHILD schema —
    stop at lambda boundaries: a higher-order function's LambdaFunction
    child (and its _rebound body) lives in element space with its own
    synthetic ordinals."""
    from spark_rapids_tpu.ops.nested import LambdaFunction, NamedLambdaVariable
    if isinstance(e, (LambdaFunction, NamedLambdaVariable)):
        return
    yield e
    for c in getattr(e, "children", ()):
        yield from _iter_outer_refs(c)


def _check_boundrefs(path, node, diags):
    from spark_rapids_tpu.ops.expr import BoundReference
    for ctx, expr, schema in node_expr_bindings(node):
        if schema is None:
            continue
        for e in _iter_outer_refs(expr):
            if not isinstance(e, BoundReference):
                continue
            if not (0 <= e.ordinal < len(schema)):
                diags.append(make(
                    "PV-BOUNDREF", path,
                    f"{ctx}: ordinal {e.ordinal} outside the child's "
                    f"{len(schema)}-column schema"))
            elif e.data_type != schema[e.ordinal][1]:
                diags.append(make(
                    "PV-BOUNDREF", path,
                    f"{ctx}: ordinal {e.ordinal} typed "
                    f"{e.data_type.simple_string()} but child column "
                    f"{schema[e.ordinal][0]} is "
                    f"{schema[e.ordinal][1].simple_string()}"))


def _in_lambda_body(expr, node_e) -> bool:
    body = getattr(expr, "_rebound", None)
    if body is None:
        return False
    return any(e is node_e for e in _walk_expr(body))


def _check_typesig(path, node, on_device, conf, diags):
    if not on_device:
        return
    from spark_rapids_tpu.overrides.rules import check_expr
    for ctx, expr, _ in node_expr_bindings(node):
        reasons: List[str] = []
        try:
            check_expr(expr, conf, reasons)
        except Exception as exc:
            reasons = [f"check_expr failed: {exc!r}"]
        for r in reasons:
            diags.append(make(
                "PV-TYPESIG", path,
                f"{ctx}: {r} (expression ran on device anyway)"))


def _iter_types(dt):
    yield dt
    if isinstance(dt, T.ArrayType):
        yield from _iter_types(dt.element_type)
    elif isinstance(dt, T.StructType):
        for f in dt.fields:
            yield from _iter_types(f.data_type)
    elif isinstance(dt, T.MapType):
        yield from _iter_types(dt.key_type)
        yield from _iter_types(dt.value_type)


def _check_decimals(path, node, diags):
    from spark_rapids_tpu.ops.decimal import DecimalBinary
    schema = _schema_of(node)
    if isinstance(schema, list):
        for name, dt in schema:
            for t in _iter_types(dt):
                if isinstance(t, T.DecimalType) and not (
                        0 < t.precision <= T.DecimalType.MAX_PRECISION
                        and 0 <= t.scale <= t.precision):
                    diags.append(make(
                        "PV-DECIMAL", path,
                        f"column {name} has invalid decimal "
                        f"({t.precision},{t.scale})"))
    for ctx, expr, _ in node_expr_bindings(node):
        for e in _walk_expr(expr):
            try:
                dt = e.data_type
            except Exception:
                continue
            for t in _iter_types(dt):
                if isinstance(t, T.DecimalType) and not (
                        0 < t.precision <= T.DecimalType.MAX_PRECISION
                        and 0 <= t.scale <= t.precision):
                    diags.append(make(
                        "PV-DECIMAL", path,
                        f"{ctx}: {type(e).__name__} produces invalid "
                        f"decimal ({t.precision},{t.scale})"))
            if isinstance(e, DecimalBinary):
                try:
                    want = e._result_type(e._ltype, e._rtype)
                except Exception:
                    continue
                if isinstance(dt, T.DecimalType) and (
                        dt.precision != want.precision
                        or dt.scale != want.scale):
                    diags.append(make(
                        "PV-DECIMAL", path,
                        f"{ctx}: {type(e).__name__} declares "
                        f"decimal({dt.precision},{dt.scale}) but the "
                        f"Spark promotion rule gives "
                        f"decimal({want.precision},{want.scale})"))


def _check_nullability(path, node, diags):
    import inspect

    from spark_rapids_tpu.ops.expr import Alias, Expression
    for ctx, expr, _ in node_expr_bindings(node):
        for e in _walk_expr(expr):
            try:
                e_nullable = e.nullable
                kids_nullable = any(c.nullable for c in
                                    getattr(e, "children", ()))
            except Exception:
                continue
            if isinstance(e, Alias):
                child = e.children[0]
                try:
                    if e_nullable != child.nullable:
                        diags.append(make(
                            "PV-NULLABLE", path,
                            f"{ctx}: Alias nullability {e_nullable} != "
                            f"child nullability {child.nullable}"))
                except Exception:
                    pass
                continue
            if not e_nullable and kids_nullable:
                cls_attr = inspect.getattr_static(type(e), "nullable", None)
                if not isinstance(cls_attr, property):
                    # a plain `nullable = False` class attribute shadows
                    # the derived property — the exact footgun this rule
                    # exists for; a property override is a deliberate
                    # null-suppressing op (IsNull, Count, Coalesce...)
                    diags.append(make(
                        "PV-NULLABLE", path,
                        f"{ctx}: {type(e).__name__} claims non-nullable "
                        "over nullable inputs without overriding the "
                        "nullable property"))


def _check_aggregate(path, node, diags):
    from spark_rapids_tpu.execs.aggregate import (
        DEVICE_SUPPORTED_AGGS,
        TpuHashAggregateExec,
    )
    from spark_rapids_tpu.ops import aggregates as agg
    from spark_rapids_tpu.plan.nodes import Aggregate
    if not isinstance(node, (TpuHashAggregateExec, Aggregate)):
        return
    names = getattr(node, "grouping_names", None)
    if names is not None and len(names) != len(node.grouping):
        diags.append(make(
            "PV-AGG", path,
            f"{len(names)} grouping names for {len(node.grouping)} "
            "grouping keys"))
    for name, fn in node.agg_specs:
        if not isinstance(fn, agg.AggregateFunction):
            diags.append(make(
                "PV-AGG", path,
                f"aggregate spec {name} is {type(fn).__name__}, not an "
                "AggregateFunction"))
        elif isinstance(node, TpuHashAggregateExec) and \
                not isinstance(fn, DEVICE_SUPPORTED_AGGS):
            diags.append(make(
                "PV-AGG", path,
                f"aggregate {name} ({type(fn).__name__}) is not device-"
                "supported but sits in a device aggregate exec"))


_SUPPORTED_JOIN_TYPES = {"inner", "cross", "left", "leftouter", "right",
                         "rightouter", "full", "fullouter", "outer",
                         "leftsemi", "leftanti"}


def _check_join(path, node, diags):
    from spark_rapids_tpu.execs.join import TpuJoinExec
    from spark_rapids_tpu.plan.nodes import Join
    if not isinstance(node, (TpuJoinExec, Join)):
        return
    jt = node.join_type.lower().replace("_", "")
    if jt not in _SUPPORTED_JOIN_TYPES:
        diags.append(make("PV-JOIN", path,
                          f"unsupported join type {node.join_type!r}"))
    if len(node.left_keys) != len(node.right_keys):
        diags.append(make(
            "PV-JOIN", path,
            f"key arity mismatch: {len(node.left_keys)} left vs "
            f"{len(node.right_keys)} right"))
        return
    if isinstance(node, TpuJoinExec):
        # the converter promotes mismatched key types with Casts; a
        # surviving mismatch means the device kernel compares raw buffers
        # of different types
        for i, (lk, rk) in enumerate(zip(node.left_keys, node.right_keys)):
            try:
                lt, rt = lk.data_type, rk.data_type
            except Exception:
                continue
            if lt != rt:
                diags.append(make(
                    "PV-JOIN", path,
                    f"device join key {i} types diverge: "
                    f"{lt.simple_string()} vs {rt.simple_string()}"))


# ---------------------------------------------------------------------------
# fallback bookkeeping (PlanMeta side)
# ---------------------------------------------------------------------------


def verify_meta(meta, diags: List[Diagnostic]) -> None:
    from spark_rapids_tpu.overrides.rules import _EXEC_RULES
    explain_txt = meta.explain(only_fallback=False)

    def rec(m, path):
        if m.reasons:
            for r in m.reasons:
                if not str(r).strip():
                    diags.append(make(
                        "PV-FALLBACK", path,
                        "fallback carries an empty reason"))
                elif str(r) not in explain_txt:
                    diags.append(make(
                        "PV-FALLBACK", path,
                        f"fallback reason {r!r} does not surface in "
                        "explain()"))
        elif type(m.node) not in _EXEC_RULES:
            diags.append(make(
                "PV-FALLBACK", path,
                f"{_label(m.node)} has no exec rule yet carries no "
                "fallback reason (tagging skipped?)"))
        kids = m.children
        for i, c in enumerate(kids):
            if len(kids) == 2:
                edge = "left" if i == 0 else "right"
                rec(c, f"{path}.{edge}.{_label(c.node)}")
            else:
                rec(c, f"{path}.{_label(c.node)}")

    rec(meta, _label(meta.node))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def verify_converted(executable, meta=None, conf=None) -> List[Diagnostic]:
    """Verify a converted plan (and, when given, its tagged PlanMeta)."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.execs.base import HostToDevice, TpuExec
    conf = conf if conf is not None else RapidsConf()
    diags: List[Diagnostic] = []
    for path, node in iter_nodes(executable):
        on_device = isinstance(node, TpuExec) and \
            not isinstance(node, HostToDevice)
        _check_schema(path, node, diags)
        _check_transitions(path, node, diags)
        _check_exchange(path, node, diags)
        _check_boundrefs(path, node, diags)
        _check_typesig(path, node, on_device, conf, diags)
        _check_decimals(path, node, diags)
        _check_nullability(path, node, diags)
        _check_aggregate(path, node, diags)
        _check_join(path, node, diags)
    if meta is not None:
        verify_meta(meta, diags)
    return diags


def verify_plan(plan, conf=None) -> List[Diagnostic]:
    """Tag + convert a logical plan, then verify the converted tree."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.overrides import apply_overrides
    conf = conf if conf is not None else RapidsConf()
    executable, meta = apply_overrides(plan, conf)
    return verify_converted(executable, meta, conf)
