"""Tier-1 chaos smoke: one small SEEDED fault-injection run on every PR.

The full corpus chaos run is ``python scale_test.py --chaos`` (all of
q1-q22 under the randomized-but-seeded schedule); this marker-gated
slice keeps the recovery machinery — fetch retry, transport reconnect,
corrupt-frame refetch, kernel-crash replay/demotion — exercised in the
tier-1 gate without the full corpus cost."""

import pytest

from spark_rapids_tpu.runtime.faults import CIRCUIT_BREAKER, FAULTS


@pytest.fixture(autouse=True)
def _clean_fault_state():
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()
    yield
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()


@pytest.mark.chaos
@pytest.mark.slow  # strict subset of test_service.py's concurrent
# chaos slice: same seed/sf/queries, minus the scheduler — tier-1
# keeps the superset and the full corpus runs keep this one
def test_seeded_chaos_slice_bit_identical():
    from spark_rapids_tpu.lint.golden import _load_scale_test
    st = _load_scale_test()
    # q7 exercises the P2P shuffle wire (fetch/transport/corrupt faults);
    # q1/q3 cover agg + join under exec/dispatch crash injection
    report = st.run_chaos(sf=0.01, seed=7, queries=["q1", "q3", "q7"])
    assert report["ok"]
    assert all(e["identical"] for e in report["queries"].values())
    # the schedule must actually have injected something (a silent no-op
    # chaos run would pass vacuously)
    fires = report["queries"]["q7"]["fault_fires"]
    assert sum(fires.values()) > 0


@pytest.mark.chaos
def test_chaos_with_deterministic_crash_demotes_and_matches():
    """A chaos slice where one op crashes deterministically: the circuit
    breaker must demote it and results must STILL be bit-identical."""
    import numpy as np
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col, lit
    from spark_rapids_tpu.session import TpuSession
    from tests.asserts import assert_tpu_and_cpu_are_equal

    data = {"k": (np.arange(300) % 7).astype(np.int64),
            "v": np.arange(300, dtype=np.float64)}

    def build(s):
        return (s.create_dataframe(dict(data))
                .filter(col("v") > lit(10.0))
                .group_by("k")
                .agg(F.sum("v").alias("s"), F.count("v").alias("c")))

    chaotic = TpuSession({
        "spark.rapids.test.faults": "exec.execute@Aggregate:crash:999",
        "spark.rapids.sql.runtimeFallback.maxFailures": "2",
    })
    cpu = TpuSession({"spark.rapids.sql.enabled": "false"})
    assert_tpu_and_cpu_are_equal(build, chaotic, cpu)
    assert "Aggregate" in CIRCUIT_BREAKER.demoted_ops()
