"""Dynamic partition pruning + bloom-filter join filtering
(reference: dpp_test.py, GpuFileSourceScanExec DynamicPruningExpression;
SURVEY §2.9 BloomFilter / InjectRuntimeFilter)."""

import os

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def tpu():
    return TpuSession()


@pytest.fixture(scope="module")
def cpu():
    return TpuSession({"spark.rapids.sql.enabled": "false"})


def _write_partitioned(tmp_path, n_parts=8, rows=500, seed=0):
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(seed)
    root = str(tmp_path / "data")
    for r in range(n_parts):
        t = pa.table({"v": rng.random(rows),
                      "k": rng.integers(0, 50, rows)})
        os.makedirs(f"{root}/region={r}", exist_ok=True)
        pq.write_table(t, f"{root}/region={r}/part-0.parquet")
    return root


def _scan_metrics(session):
    """dpp metrics of every pruning-armed scan EXEC in the last plan."""
    out = []

    def walk(e):
        if getattr(e, "_dynamic_prunes", None):
            out.append(e.metrics)
        for c in getattr(e, "children", ()):
            walk(c)
        for attr in ("scan_node", "cpu_node", "tpu_exec", "source"):
            n = getattr(e, attr, None)
            if n is not None:
                walk(n)

    walk(session._last_executable)
    return out


def test_dpp_prunes_files_inner_join(tmp_path, tpu, cpu):
    root = _write_partitioned(tmp_path)
    dim = {"region": np.array([1, 6], dtype=np.int64),
           "name": np.array(["a", "b"], dtype=object)}
    q = lambda s: sorted(
        s.read_parquet(root)
        .join(s.create_dataframe(dim), on="region", how="inner")
        .group_by("name").agg(F.count().alias("c")).collect())
    assert q(tpu) == q(cpu)
    m = _scan_metrics(tpu)
    assert m and m[0]["dppPrunedFiles"] == 6 and m[0]["dppScannedFiles"] == 2


def test_dpp_through_projection(tmp_path, tpu, cpu):
    root = _write_partitioned(tmp_path, n_parts=5)
    dim = {"region": np.array([0], dtype=np.int64)}
    q = lambda s: sorted(
        s.read_parquet(root)
        .select(col("region"), (col("v") * lit(2.0)).alias("v2"))
        .join(s.create_dataframe(dim), on="region", how="leftsemi")
        .agg(F.count().alias("c")).collect())
    assert q(tpu) == q(cpu)
    m = _scan_metrics(tpu)
    assert m and m[0]["dppPrunedFiles"] == 4


def test_dpp_not_installed_for_outer_join(tmp_path, tpu):
    root = _write_partitioned(tmp_path, n_parts=4)
    dim = {"region": np.array([2], dtype=np.int64)}
    df = (tpu.read_parquet(root)
          .join(tpu.create_dataframe(dim), on="region", how="left"))
    got = df.collect_table()
    assert got.num_rows == 4 * 500  # every probe row kept
    assert not _scan_metrics(tpu)  # no pruning armed on an outer join


def test_dpp_disabled_by_conf(tmp_path):
    root = _write_partitioned(tmp_path, n_parts=4)
    s = TpuSession({"spark.rapids.sql.dpp.enabled": "false"})
    dim = {"region": np.array([2], dtype=np.int64)}
    _ = (s.read_parquet(root)
         .join(s.create_dataframe(dim), on="region", how="inner")
         .collect())
    assert not _scan_metrics(s)


def test_dpp_prune_to_zero_files(tmp_path, tpu, cpu):
    root = _write_partitioned(tmp_path, n_parts=3)
    dim = {"region": np.array([99], dtype=np.int64)}
    q = lambda s: (s.read_parquet(root)
                   .join(s.create_dataframe(dim), on="region", how="inner")
                   .collect())
    assert q(tpu) == q(cpu) == []


# -- bloom -------------------------------------------------------------------

def test_bloom_no_false_negatives_and_oracle_match(tpu, cpu):
    rng = np.random.default_rng(3)
    fact = {"k": rng.integers(0, 50000, 20000).astype(np.int64)}
    keys = rng.choice(50000, 300, replace=False).astype(np.int64)
    bloom = F.build_bloom_filter(tpu.create_dataframe({"k": keys}), "k")
    q = lambda s: sorted(
        s.create_dataframe(fact)
        .filter(F.might_contain(bloom, col("k"))).collect())
    got, want = q(tpu), q(cpu)
    assert got == want
    truth = set(fact["k"][np.isin(fact["k"], keys)].tolist())
    assert truth <= {r[0] for r in got}  # no false negatives


def test_bloom_prefilter_preserves_join_result(tpu, cpu):
    """Probe pre-filtering with might_contain must not change the join's
    result (the InjectRuntimeFilter invariant)."""
    rng = np.random.default_rng(4)
    fact = {"k": rng.integers(0, 10000, 30000).astype(np.int64),
            "v": rng.random(30000)}
    keys = np.sort(rng.choice(10000, 200, replace=False).astype(np.int64))
    dim = {"k": keys, "w": np.arange(200, dtype=np.int64)}
    bloom = F.build_bloom_filter(tpu.create_dataframe(dim), "k")

    def q(s, prefilter):
        df = s.create_dataframe(fact)
        if prefilter:
            df = df.filter(F.might_contain(bloom, col("k")))
        return sorted(df.join(s.create_dataframe(dim), on="k", how="inner")
                      .group_by("w").agg(F.count().alias("c")).collect())

    base = q(cpu, False)
    assert q(tpu, True) == base
    assert q(tpu, False) == base


def test_bloom_null_propagation(tpu, cpu):
    from spark_rapids_tpu import types as T
    vals = [1, None, 7, 99999]
    keys = np.array([1, 7], dtype=np.int64)
    bloom = F.build_bloom_filter(tpu.create_dataframe({"k": keys}), "k")
    for s in (tpu, cpu):
        got = s.create_dataframe({"k": vals}, dtypes={"k": T.LONG}).select(
            F.might_contain(bloom, col("k")).alias("m")).collect()
        assert got[0][0] is True and got[1][0] is None and got[2][0] is True


def test_dpp_does_not_leak_across_queries(tmp_path, tpu, cpu):
    """A pruning filter installed for one query must not affect other
    queries over the SAME shared scan (review finding: filters used to
    accumulate on the logical plan node)."""
    root = _write_partitioned(tmp_path, n_parts=6)
    base = tpu.read_parquet(root)
    dim = {"region": np.array([1], dtype=np.int64)}
    _ = base.join(tpu.create_dataframe(dim), on="region",
                  how="inner").collect()
    assert _scan_metrics(tpu) and _scan_metrics(tpu)[0][
        "dppScannedFiles"] == 1
    # plain scan over the same DataFrame: ALL partitions
    full = base.agg(F.count().alias("c")).collect()
    assert full[0][0] == 6 * 500
    # and re-running the join does not stack duplicate providers
    _ = base.join(tpu.create_dataframe(dim), on="region",
                  how="inner").collect()
    m = _scan_metrics(tpu)
    assert m and m[0]["dppScannedFiles"] == 1
