"""Device-constant interning + dispatch accounting.

Measured on the tunneled TPU (PERF.md): kernel dispatches PIPELINE — eight
chained dispatches plus one result fetch cost the same ~0.09s as one — but
every host->device transfer in the warm path is a fresh ~0.1-3s stall (a
tiny 4-byte scalar upload costs ~0.15s, and an upload interleaved between
dispatches forces a pipeline flush costing seconds). The reference never
faces this: cudaMemcpyAsync on PCIe is microseconds, so it re-uploads
per-kernel scratch freely (e.g. JCudfSerialization headers).

The TPU-first rule is therefore: NOTHING transfers host->device on a warm
query. Every per-query host-side constant — expression aux arrays
(dictionary codes, literal tables, remap vectors), aggregate size/stride
vectors, row-count scalars — is interned here by CONTENT, so a repeated
query shape reuses the device-resident copy and the warm path performs
zero uploads.

``count_dispatch`` feeds the per-query ``dispatches`` metric (VERDICT r3:
the dispatch count must be observable)."""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_LOCK = threading.Lock()

#: spark.sql.ansi.enabled, set per-query by the session (same pattern as
#: the masked-batch and retry contextvars)
import contextvars
ANSI_MODE = contextvars.ContextVar("rapids_ansi_mode", default=False)

#: content-keyed device copies of host constant arrays
_CONST_CACHE: Dict[tuple, jax.Array] = {}
#: interned device scalars keyed by (dtype, value)
_SCALAR_CACHE: Dict[tuple, jax.Array] = {}

#: evict the const cache above this many entries (scans are cached on their
#: host tables, not here; these are small aux/remap arrays)
_CONST_CACHE_CAP = 8192


def _content_key(arr: np.ndarray) -> tuple:
    if arr.dtype == object:
        # object arrays (string dictionaries) hash by element repr
        h = hashlib.sha1("\x00".join(map(repr, arr.ravel().tolist()))
                         .encode()).digest()
        return (str(arr.dtype), arr.shape, h)
    b = np.ascontiguousarray(arr).tobytes()
    if len(b) <= 128:
        return (str(arr.dtype), arr.shape, b)
    return (str(arr.dtype), arr.shape, hashlib.sha1(b).digest())


def device_const(arr) -> jax.Array:
    """Device copy of a host constant array, interned by content. Safe to
    call inside a jit trace (the cached concrete array is captured as a
    trace constant — uploaded once at compile, never per call)."""
    if isinstance(arr, jax.Array):
        return arr
    arr = np.asarray(arr)
    key = _content_key(arr)
    with _LOCK:
        d = _CONST_CACHE.get(key)
    if d is None:
        d = jnp.asarray(arr)
        with _LOCK:
            if len(_CONST_CACHE) >= _CONST_CACHE_CAP:
                _CONST_CACHE.clear()
            _CONST_CACHE[key] = d
    return d


def device_scalar(value, dtype=np.int32) -> jax.Array:
    """Interned 0-d device scalar (the DeviceTable row-count pattern:
    ``jnp.asarray(np.int32(n))`` per table was a ~0.15s upload EACH)."""
    dt = np.dtype(dtype)
    key = (dt.str, value)
    with _LOCK:
        d = _SCALAR_CACHE.get(key)
    if d is None:
        d = jnp.asarray(np.asarray(value, dtype=dt))
        with _LOCK:
            if len(_SCALAR_CACHE) >= _CONST_CACHE_CAP:
                _SCALAR_CACHE.clear()
            _SCALAR_CACHE[key] = d
    return d


def prep_aux(pctx) -> tuple:
    """Upload a PrepCtx's aux arrays: content-interned for deterministic
    slots, plain per-call upload for nondeterministic ones (rand streams —
    interning those would pin every batch's values on device forever)."""
    intern = getattr(pctx, "aux_intern", None) or [True] * len(pctx.aux_arrays)
    return tuple(device_const(a) if keep else jnp.asarray(a)
                 for a, keep in zip(pctx.aux_arrays, intern))


def clear_device_constants() -> int:
    """Drop interned device constants (device OOM recovery hook)."""
    with _LOCK:
        n = len(_CONST_CACHE) + len(_SCALAR_CACHE)
        _CONST_CACHE.clear()
        _SCALAR_CACHE.clear()
    return n


# -- sanctioned host synchronization ----------------------------------------


class _ThreadCounter(threading.local):
    """Per-thread counter: queries execute whole on one thread (direct
    calls on the caller's thread, service queries on their worker), so
    thread-locality makes the per-query dispatch/sync counts correct
    under CONCURRENT queries — a shared slot would cross-contaminate
    every in-flight query's count on reset."""

    def __init__(self):
        self.n = 0


_HOST_FETCHES = _ThreadCounter()


def host_fetch(value):
    """THE sanctioned device->host synchronization point for exec/op hot
    paths (the repo lint's RL-HOST-SYNC rule rejects raw
    ``jax.device_get`` / ``block_until_ready`` in execs/ and ops/).

    Every call is a deliberate ~0.1s pipeline stall on the tunneled TPU,
    so funneling them here keeps them countable (``host_fetch_count``)
    and greppable in review. Returns the fetched value as host data
    (numpy array or python scalar for 0-d inputs)."""
    _HOST_FETCHES.n += 1
    fetched = jax.device_get(value)
    return fetched


def host_fetch_count() -> int:
    return _HOST_FETCHES.n


# -- dispatch accounting ----------------------------------------------------

_DISPATCHES = _ThreadCounter()


def count_dispatch(n: int = 1) -> None:
    """Record ``n`` device kernel dispatches (on this thread — see
    _ThreadCounter). No-op inside a jit trace (an inlined sub-kernel is
    not a dispatch)."""
    _DISPATCHES.n += n


def dispatch_count() -> int:
    return _DISPATCHES.n


def reset_dispatch_count() -> int:
    old = _DISPATCHES.n
    _DISPATCHES.n = 0
    return old


try:
    from jax._src.core import trace_state_clean as _trace_state_clean
except ImportError:  # pragma: no cover - jax internals moved
    def _trace_state_clean() -> bool:
        return True


def tracing() -> bool:
    """Are we inside a jax trace right now?"""
    return not _trace_state_clean()


#: per-kernel wall timings when SRT_PROFILE_DISPATCH=1 (each dispatch is
#: force-synced via a scalar fetch, so entries ~= kernel compute + one RTT)
DISPATCH_PROFILE: list = []


def _sync_result(res):
    from spark_rapids_tpu.shims import get_shim
    leaves = get_shim().tree_leaves(res)
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            jax.device_get(jnp.ravel(leaf)[:1])
            return


def tpu_jit(fn, **kwargs):
    """jax.jit that records a dispatch per (non-traced) call — when an
    exec kernel runs inside a whole-plan fused trace (execs/fused.py) it
    inlines into the outer program and is NOT a dispatch."""
    import os
    jf = jax.jit(fn, **kwargs)
    name = getattr(fn, "__qualname__", getattr(fn, "__name__", "kernel"))
    profile = bool(os.environ.get("SRT_PROFILE_DISPATCH"))

    from spark_rapids_tpu.obs.spans import TRACER
    from spark_rapids_tpu.runtime.faults import fault_point

    def call(*args, **kw):
        if not _trace_state_clean():
            return jf(*args, **kw)
        fault_point("dispatch.kernel", op=name)
        count_dispatch()
        # host span per dispatch (async: covers enqueue, not device
        # compute — Xprof owns the device timeline); one attribute read
        # when the tracer is idle
        sp = TRACER.begin(name, "dispatch") if TRACER.enabled else None
        try:
            if not profile:
                return jf(*args, **kw)
            import time
            t0 = time.perf_counter()
            res = jf(*args, **kw)
            _sync_result(res)
            DISPATCH_PROFILE.append((name, time.perf_counter() - t0))
            return res
        finally:
            TRACER.end(sp)

    call.__wrapped__ = jf
    return call
