"""Placement layer: WHERE query data lives and how it is drained.

The deliberate driver/placement split (ROADMAP item 1): ``TpuSession``
keeps the DRIVER half — SQL front end, catalog, planning, overrides/AQE
conversion, verification, the executable/result caches and the
observability envelope — while this layer owns everything about device
PLACEMENT and execution residency:

* realizing the mesh config (``spark.rapids.mesh.*`` ->
  :class:`~spark_rapids_tpu.parallel.mesh.MeshRuntime`) BEFORE planning,
  so the plan fingerprint and the executable-cache generation see the
  mesh the query will execute under (shard dispatch then happens in the
  scan execs, which land each shard per-device);
* the device semaphore: fully-fallen-back plans must not consume a
  device-concurrency slot, so residency gating keys off whether the
  converted tree holds any device exec;
* the speculative drain (operator sizing validated by the collect's
  packed fetch, with blocklist-and-replay on failure) and the
  conf-driven tuning constants it pushes into the kernel layers;
* async result-fetch resolution: enqueued ``PendingHostTable`` batches
  complete their d2h round trip AFTER the semaphore released.

On a multi-host deployment this layer is what a per-host executor would
implement; single-process it is the seam the mesh runtime, the
semaphore and the drain hang off.
"""

from __future__ import annotations

from typing import List


def uses_device(executable) -> bool:
    """Does a converted plan contain any device exec? (Transitions wrap
    TpuExec trees in DeviceToHost; CPU nodes may hold them via
    InputAdapter.)"""
    from spark_rapids_tpu.execs.base import DeviceToHost, InputAdapter, TpuExec
    if isinstance(executable, (DeviceToHost, TpuExec)):
        return True
    if isinstance(executable, InputAdapter):
        return uses_device(executable.source)
    for c in getattr(executable, "children", ()):
        if uses_device(c):
            return True
    return False


class PlacementLayer:
    """One session's placement half (stateless between queries: the
    conf is re-read per call so ``session.set_conf`` takes effect like
    every other per-query knob)."""

    def __init__(self, session):
        self._session = session

    @property
    def _conf(self):
        return self._session.conf

    # -- mesh ----------------------------------------------------------------
    def prepare(self) -> None:
        """Realize the placement config for the coming query. Called by
        the driver BEFORE fingerprinting/planning: the mesh runtime must
        reflect this query's ``spark.rapids.mesh.*`` conf when the plan
        fingerprint folds the mesh identity token and the executable
        cache stamps its generation."""
        from spark_rapids_tpu.parallel.mesh import MESH
        MESH.configure(self._conf)
        # the host topology above the mesh (runtime/cluster.py): the
        # fingerprint folds its identity token right next to the mesh's
        from spark_rapids_tpu.runtime.cluster import CLUSTER
        CLUSTER.configure(self._conf)

    # -- drain ---------------------------------------------------------------
    def drain(self, executable) -> List:
        """Drain the converted plan under a speculation context
        (speculative operator sizing, validated by the collect's packed
        fetch). A failed speculation blocklists the failing sites
        process-wide and replays once — the replay takes the exact
        sync-per-operator path there, so a repeated query shape never
        replays twice (runtime/speculation.py).

        The device semaphore is held around each DRAIN only: with async
        result fetch the root transition yields enqueued
        PendingHostTable batches, and their d2h round trips complete
        AFTER the semaphore releases — the device slot frees as soon as
        the last kernel is in flight. Resolution stays INSIDE the
        speculation attempt so a flag failure riding the packed buffer
        still replays."""
        from spark_rapids_tpu.conf import (
            JOIN_DIRECT_TABLE_MULT,
            MASKED_BATCHES,
            SPECULATIVE_SIZING,
        )
        from spark_rapids_tpu.execs.base import MASKED_ENABLED
        from spark_rapids_tpu.execs.join import DIRECT_TABLE_MULT
        from spark_rapids_tpu.runtime import (
            TpuSemaphore,
            acquired,
            speculation as spec,
        )

        conf = self._conf
        # the semaphore gates DEVICE residency: fully-fallen-back plans
        # must not consume a device-concurrency slot
        sem = None
        if uses_device(executable):
            sem = TpuSemaphore.initialize(conf.concurrent_tpu_tasks)

        self.apply_tuning_confs()
        from spark_rapids_tpu import kernels as K
        from spark_rapids_tpu.conf import ANSI_ENABLED
        from spark_rapids_tpu.dispatch import ANSI_MODE
        tok_m = MASKED_ENABLED.set(bool(conf.get_entry(MASKED_BATCHES)))
        tok_d = DIRECT_TABLE_MULT.set(
            conf.get_entry(JOIN_DIRECT_TABLE_MULT))
        tok_a = ANSI_MODE.set(bool(conf.get_entry(ANSI_ENABLED)))
        # Pallas kernel enablement rides a contextvar like the masked/
        # direct-join knobs: ops and execs hold no conf handle, and the
        # resolved set folds into their trace keys (kernels.trace_token)
        tok_k = K.KERNELS_ENABLED.set(K.resolve_enabled(conf))

        def drain_once():
            with acquired(sem):
                batches = list(executable.execute_cpu())
            return self.resolve_pending(executable, batches)

        try:
            if not conf.get_entry(SPECULATIVE_SIZING):
                return drain_once()
            # each failed attempt blocklists its sites, so every replay
            # makes strict progress (a site never fails twice); the cap
            # guards a pathological plan by dropping to the exact path
            for _attempt in range(8):
                tok = spec.activate()
                try:
                    batches = drain_once()
                    spec.current().validate_remaining()
                    if _attempt and hasattr(executable, "metrics"):
                        # replays re-execute operators, double-counting
                        # their metrics; record how many times so the
                        # numbers can be interpreted (ADVICE r3)
                        executable.metrics["speculationReplays"] = _attempt
                    return batches
                except spec.SpeculationFailed as sf:
                    spec.blocklist(sf.sites)
                finally:
                    spec.deactivate(tok)
            return drain_once()
        finally:
            MASKED_ENABLED.reset(tok_m)
            DIRECT_TABLE_MULT.reset(tok_d)
            ANSI_MODE.reset(tok_a)
            K.KERNELS_ENABLED.reset(tok_k)

    def resolve_pending(self, executable, batches) -> List:
        """Complete enqueued async downloads — the device semaphore is
        already released; only the tunnel round trip remains. Records
        resultFetchTime plus the root transition's deferred output-row
        count (plain HostTable batches pass through untouched)."""
        from spark_rapids_tpu.columnar.table import PendingHostTable
        if not any(isinstance(b, PendingHostTable) for b in batches):
            return batches
        import time as _time
        t0 = _time.perf_counter()
        out = []
        rows = 0
        for b in batches:
            if isinstance(b, PendingHostTable):
                b = b.resolve()
                rows += b.num_rows
            out.append(b)
        if hasattr(executable, "add_metric"):
            executable.add_metric("resultFetchTime",
                                  _time.perf_counter() - t0)
            executable.add_metric("numOutputRows", rows)
        return out

    def apply_tuning_confs(self) -> None:
        """Push registry-tunable constants into the modules that consume
        them (RapidsConf -> class attrs; execs/expressions hold no conf
        handle — same pattern as the retry/masked contextvars)."""
        from spark_rapids_tpu import conf as C
        from spark_rapids_tpu.columnar.table import DeviceTable
        from spark_rapids_tpu.execs import broadcast as B
        from spark_rapids_tpu.ops.collections import Sequence
        get = self._conf.get_entry
        from spark_rapids_tpu.columnar import column as CCol
        CCol.set_bucket_policy(str(get(C.SHAPE_BUCKETS)),
                               int(get(C.SHAPE_BUCKETS_MIN)))
        Sequence.SEQ_ELEMENT_MULT = int(get(C.SEQUENCE_ELEMENT_MULT))
        DeviceTable.EMBED_NROWS_CAP = int(get(C.COLLECT_EMBED_ROWS_CAP))
        DeviceTable.EMBED_MAX_BYTES = int(get(C.COLLECT_EMBED_MAX_BYTES))
        B.PAIR_BUDGET = int(get(C.NLJ_PAIR_BUDGET))
        from spark_rapids_tpu.ops import segsum as SS
        SS.BLOCK = int(get(C.SEGSUM_BLOCK_ROWS))
        SS.MAX_PARTIALS = int(get(C.SEGSUM_MAX_PARTIALS))
        SS.MATMUL_MAX_SEGMENTS = int(get(C.SEGSUM_MATMUL_MAX_SEGMENTS))
        SS.SPLIT_MAX_ABS = float(get(C.SPLIT_SUM_MAX_ABS))
        # mesh fault-domain tunables (the gather-integrity boundary and
        # the ICI exchange hold no conf handle, like every other exec)
        from spark_rapids_tpu.parallel import mesh as PM
        PM.MAX_SHARD_RETRIES = int(get(PM.MESH_MAX_SHARD_RETRIES))
        PM.GATHER_VERIFY = bool(get(PM.MESH_GATHER_VERIFY))
