"""Service survivability (PR 7): device-loss recovery + cache
invalidation, the CPU-only latch, the worker watchdog (hard wall limit,
respawn), poison-query quarantine, DEGRADED-mode load shedding, and the
satellite fixes (spill disk-file cleanup, locked stats snapshots,
semaphore-timeout cleanup). Seeded and small — this slice rides tier-1.
"""

import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.errors import (
    DeviceLostError,
    HardTimeoutError,
    QueryQuarantinedError,
    QueryRejectedError,
    SemaphoreTimeoutError,
)
from spark_rapids_tpu.runtime import faults as FMOD
from spark_rapids_tpu.runtime.faults import CIRCUIT_BREAKER, FAULTS
from spark_rapids_tpu.runtime.health import HEALTH, QUARANTINE
from spark_rapids_tpu.service import QueryService
from spark_rapids_tpu.session import TpuSession

pytestmark = pytest.mark.survivability


@pytest.fixture(autouse=True)
def _clean_survivability_state():
    """The health monitor, quarantine ledger and fault registry are
    process-wide; a latched CPU-only mode or leftover strikes would
    poison every later test."""
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()
    HEALTH.reset()
    QUARANTINE.reset()
    yield
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()
    HEALTH.reset()
    QUARANTINE.reset()


def _data(n=200):
    return {"k": np.array(["a", "b", "c", "d"] * (n // 4), dtype=object),
            "v": np.arange(n, dtype=np.int64)}


def _agg(df):
    return df.group_by("k").agg(F.sum("v").alias("s"))


def _expected():
    return {"a": sum(range(0, 200, 4)), "b": sum(range(1, 200, 4)),
            "c": sum(range(2, 200, 4)), "d": sum(range(3, 200, 4))}


def _check_result(table):
    got = dict(zip(np.asarray(table.columns[0].data).tolist(),
                   np.asarray(table.columns[1].data).tolist()))
    assert got == _expected()


# ---------------------------------------------------------------------------
# device-loss recovery
# ---------------------------------------------------------------------------


def test_device_loss_recovery_invalidates_caches():
    """THE acceptance proof: after a device loss the plan->executable
    and kernel-trace caches are invalidated — the post-recovery repeat
    query RE-TRACES (a stale cached program would have been served
    otherwise), and the run after that re-warms (hit + zero traces)."""
    from spark_rapids_tpu.dispatch import COMPILE_SCOPE

    # faults key present-but-empty from the start so flipping it later
    # and back yields the IDENTICAL conf (same executable fingerprint)
    s = TpuSession({"spark.rapids.test.faults": ""})
    df = s.create_dataframe(_data())
    _agg(df).collect_table()
    _agg(df).collect_table()
    assert s.last_executable_cache_hit  # warm: cached tree checked out
    health_before = HEALTH.snapshot()

    s.set_conf("spark.rapids.test.faults", "device.lost:device_lost:1")
    with pytest.raises(DeviceLostError):
        _agg(df).collect_table()
    assert HEALTH.snapshot()["deviceReinits"] == \
        health_before["deviceReinits"] + 1
    assert HEALTH.state() == "DEGRADED"  # loss streak open

    # back to the ORIGINAL conf: same fingerprint as the warm entries —
    # only the recovery's invalidation can explain a miss now
    s.set_conf("spark.rapids.test.faults", "")
    scope_before = dict(COMPILE_SCOPE)
    t = _agg(df).collect_table()
    _check_result(t)
    retraced = COMPILE_SCOPE.get("kernelTraces", 0) \
        - scope_before.get("kernelTraces", 0)
    assert not s.last_executable_cache_hit  # executable cache emptied
    assert retraced > 0  # kernel-trace caches emptied: re-traced
    assert HEALTH.state() == "HEALTHY"  # success closed the streak

    scope_before = dict(COMPILE_SCOPE)
    t = _agg(df).collect_table()
    _check_result(t)
    assert s.last_executable_cache_hit  # re-warmed
    assert COMPILE_SCOPE.get("kernelTraces", 0) \
        == scope_before.get("kernelTraces", 0)


def test_device_loss_requeues_in_service():
    """The service's in-process 'rescheduler': a DeviceLostError is
    retryable, so the handle goes BACK in its queue and completes
    against the recovered backend."""
    with QueryService({"spark.rapids.test.faults":
                       "device.lost:device_lost:1"}) as svc:
        df = svc.session.create_dataframe(_data())
        h = svc.submit(_agg(df), tenant="a")
        assert h.wait(timeout=60)
        assert h.state == "FINISHED"
        assert h.requeues == 1
        _check_result(h.result_table)
        st = svc.stats()
        assert st["requeued"] == 1
        assert QUARANTINE.snapshot()["strikes"] == 1  # loss = a strike
        # completions clear DEGRADED
        h2 = svc.submit(_agg(df), tenant="a")
        h3 = svc.submit(_agg(df), tenant="a")
        assert h2.wait(60) and h3.wait(60)
        assert svc.health()["state"] == "HEALTHY"


def test_max_reinits_exhaustion_latches_cpu_only(tmp_path):
    """deviceLoss.maxReinits consecutive losses latch CPU-only mode:
    the latch reason lands in explain() and the event log, and the
    query then COMPLETES on the CPU path with the faults still armed
    (no device dispatch = no injected loss = survival)."""
    s = TpuSession({
        "spark.rapids.test.faults": "device.lost:device_lost:99",
        "spark.rapids.service.deviceLoss.maxReinits": "2",
        "spark.rapids.sql.eventLog.enabled": "true",
        "spark.rapids.sql.eventLog.dir": str(tmp_path),
    })
    df = s.create_dataframe(_data())
    for _ in range(2):
        with pytest.raises(DeviceLostError):
            _agg(df).collect_table()
    assert HEALTH.state() == "CPU_ONLY"
    t = _agg(df).collect_table()  # CPU-only: completes despite faults
    _check_result(t)
    reason = HEALTH.cpu_only_reason()
    assert "CPU-only mode latched" in reason
    assert reason in s.explain(_agg(df).plan)
    rec = s.last_event_record
    assert rec["healthState"] == "CPU_ONLY"
    assert any(reason in r for fb in rec["fallbacks"]
               for r in fb["reasons"])


# ---------------------------------------------------------------------------
# watchdog + self-healing pool
# ---------------------------------------------------------------------------


def test_watchdog_abandons_wedged_worker(monkeypatch):
    """A worker stuck INSIDE one dispatch never reaches the cooperative
    cancel boundary — the watchdog's hard wall limit fails the handle
    with the typed error, and a replacement worker keeps the pool at
    capacity."""
    monkeypatch.setattr(FMOD, "_WEDGE_SLEEP_S", 1.2)
    # warm the kernels through a plain session first so the service
    # query's RUNNING wall is dispatch-bound, not compile-bound
    warm = TpuSession()
    _agg(warm.create_dataframe(_data())).collect_table()
    with QueryService({"spark.rapids.test.faults":
                       "dispatch.wedge:wedge:1",
                       "spark.rapids.service.hardTimeoutMs": "300"}) as svc:
        df = svc.session.create_dataframe(_data())
        t0 = time.monotonic()
        h = svc.submit(_agg(df), tenant="a")
        assert h.wait(timeout=30)
        assert h.state == "TIMED_OUT"
        assert isinstance(h.error, HardTimeoutError)
        # the verdict came from the watchdog, not the 1.2s wedge end
        assert time.monotonic() - t0 < 1.0
        st = svc.stats()
        assert st["hardTimeouts"] == 1
        assert st["workersLost"] == 1 and st["workersRespawned"] == 1
        assert st["healthState"] == "DEGRADED"
        assert len(svc._workers) == svc.max_concurrent  # capacity holds
        # the pool still serves (wedge schedule is exhausted)
        h2 = svc.submit(_agg(df), tenant="a")
        assert h2.wait(timeout=60) and h2.state == "FINISHED"
        _check_result(h2.result_table)
        # let the abandoned thread wake, notice it is lost, and exit —
        # it must not poison the next test's semaphore accounting
        time.sleep(max(0.0, 1.3 - (time.monotonic() - t0)))


def test_worker_crash_respawns_and_requeues():
    """A dying worker (runner machinery raises outside the query) is
    replaced and its query replays on the new worker."""
    with QueryService({"spark.rapids.test.faults":
                       "service.worker_crash:crash:1"}) as svc:
        df = svc.session.create_dataframe(_data())
        h = svc.submit(_agg(df), tenant="a")
        assert h.wait(timeout=60)
        assert h.state == "FINISHED"
        assert h.requeues == 1
        _check_result(h.result_table)
        st = svc.stats()
        assert st["workersLost"] == 1 and st["workersRespawned"] == 1
        assert st["requeued"] == 1
        assert len(svc._workers) == svc.max_concurrent


# ---------------------------------------------------------------------------
# poison-query quarantine
# ---------------------------------------------------------------------------


def test_poison_query_quarantined():
    """A template that keeps killing workers is quarantined: the
    in-flight handle fails typed with the strike history, resubmission
    is refused at admission, and explain() flags the template."""
    # count 2 EXACTLY: both kills land on the poison template's two
    # runs; the schedule is then spent, so the innocent template below
    # runs clean (the point fires per worker run, not per template)
    with QueryService({"spark.rapids.test.faults":
                       "service.worker_crash:crash:2",
                       "spark.rapids.service.quarantine.maxStrikes":
                       "2"}) as svc:
        df = svc.session.create_dataframe(_data())
        h = svc.submit(_agg(df), tenant="a")
        assert h.wait(timeout=60)
        assert h.state == "FAILED"
        assert isinstance(h.error, QueryQuarantinedError)
        assert len(h.error.strikes) == 2
        assert h.requeues == 1  # strike 1 -> requeue, strike 2 -> latch
        with pytest.raises(QueryQuarantinedError) as ei:
            svc.submit(_agg(df), tenant="b")
        assert len(ei.value.strikes) == 2
        assert svc.stats()["quarantineRejected"] == 1
        assert svc.health()["quarantine"]["quarantined"] == 1
        assert "QUARANTINED" in svc.session.explain(_agg(df).plan)
        # a DIFFERENT template is unaffected
        other = svc.submit(df.group_by("k").agg(F.count("v").alias("c")),
                           tenant="b")
        assert other.wait(timeout=60) and other.state == "FINISHED"


def test_quarantine_surfaces_in_event_log(tmp_path):
    """The v4 ``quarantined`` field: a template with strikes carries
    true on its (executed or cache-served) records."""
    with QueryService({"spark.rapids.sql.eventLog.enabled": "true",
                       "spark.rapids.sql.eventLog.dir":
                       str(tmp_path)}) as svc:
        from spark_rapids_tpu.plan.fingerprint import template_fingerprint

        df = svc.session.create_dataframe(_data())
        h1 = svc.submit(_agg(df), tenant="a")
        assert h1.wait(60) and h1.state == "FINISHED"
        assert h1.event_record["quarantined"] is False
        # the fingerprint is computed lazily (clean submissions never
        # pay the walk) — derive the strike key the way a kill would
        fp = template_fingerprint(h1.plan, svc.conf)
        QUARANTINE.strike(fp, "test strike", max_strikes=99)
        h2 = svc.submit(_agg(df), tenant="b")
        assert h2.wait(60) and h2.state == "FINISHED"
        assert h2.event_record["quarantined"] is True


# ---------------------------------------------------------------------------
# health states + degraded-mode shedding
# ---------------------------------------------------------------------------


def test_degraded_sheds_lowest_weight_pool():
    """DEGRADED admission: while higher-weight work is in flight, the
    lowest-weight pool is shed; completions clear the state and lift
    the shed. An IDLE degraded service admits the shed pool instead
    (forward progress — only completions pay the latch down, so
    shedding the sole traffic source would wedge DEGRADED forever)."""
    # one slow worker: the gold query provably stays RUNNING while the
    # bronze submission is evaluated (50ms sleep per dispatch)
    with QueryService({"spark.rapids.service.pools":
                       "gold:weight=2;bronze:weight=1",
                       "spark.rapids.test.faults":
                       "dispatch.kernel:slow:1.0"},
                      max_concurrent=1) as svc:
        df = svc.session.create_dataframe(_data())
        assert svc.health()["state"] == "HEALTHY"
        with svc._cond:
            svc._degraded_pending = svc._DEGRADE_CLEAR_SUCCESSES
        assert svc.health()["state"] == "DEGRADED"
        h1 = svc.submit(_agg(df), tenant="t", pool="gold")
        h2 = svc.submit(_agg(df), tenant="t", pool="gold")
        with pytest.raises(QueryRejectedError) as ei:
            svc.submit(_agg(df), tenant="t", pool="bronze")
        assert "DEGRADED" in str(ei.value)
        assert ei.value.retry_after_ms >= 50
        assert h1.wait(60) and h2.wait(60)
        assert svc.health()["state"] == "HEALTHY"
        # shedding lifted with the state
        h3 = svc.submit(_agg(df), tenant="t", pool="bronze")
        assert h3.wait(60) and h3.state == "FINISHED"

    # the forward-progress escape: degraded but IDLE -> bronze admitted
    with QueryService({"spark.rapids.service.pools":
                       "gold:weight=2;bronze:weight=1"}) as svc:
        df = svc.session.create_dataframe(_data())
        with svc._cond:
            svc._degraded_pending = svc._DEGRADE_CLEAR_SUCCESSES
        h = svc.submit(_agg(df), tenant="t", pool="bronze")
        assert h.wait(60) and h.state == "FINISHED"


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------


def test_semaphore_timeout_mid_query_leaves_no_stale_state(monkeypatch):
    """SemaphoreTimeoutError inside execute: the per-thread query state
    unwinds, no cancel scope leaks, and no executable-cache tree stays
    checked out (busy count back to zero); the pool then serves the
    same query normally."""
    from spark_rapids_tpu.plan.executable_cache import EXEC_CACHE
    from spark_rapids_tpu.runtime import TpuSemaphore
    from spark_rapids_tpu.service.query import current_cancel_scope

    def _timeout(self, timeout=None):
        raise SemaphoreTimeoutError("injected semaphore timeout")

    with QueryService({}) as svc:
        df = svc.session.create_dataframe(_data())
        monkeypatch.setattr(TpuSemaphore, "acquire_if_necessary",
                            _timeout)
        h = svc.submit(_agg(df), tenant="a")
        assert h.wait(timeout=60)
        assert h.state == "FAILED"
        assert isinstance(h.error, SemaphoreTimeoutError)
        monkeypatch.undo()
        # no tree stuck checked out, no residual device holders
        assert EXEC_CACHE.stats()["busyTrees"] == 0
        sem = TpuSemaphore.current()
        assert sem is None or sem.holders == 0
        # the worker thread's scope contextvar was reset by the
        # cancel_scope CM (same thread serves the next query)
        h2 = svc.submit(_agg(df), tenant="a")
        assert h2.wait(timeout=60) and h2.state == "FINISHED"
        _check_result(h2.result_table)
        # direct (unscoped) caller: per-thread state unwinds too
        with pytest.raises(SemaphoreTimeoutError):
            monkeypatch.setattr(TpuSemaphore, "acquire_if_necessary",
                                _timeout)
            _agg(df).collect_table()
        monkeypatch.undo()
        assert svc.session._q.exec_depth == 0
        assert current_cancel_scope() is None
        assert EXEC_CACHE.stats()["busyTrees"] == 0


def test_spill_disk_files_removed_on_shutdown(tmp_path):
    """Disk-tier spill files no longer outlive the catalog: release()
    unlinks, shutdown() sweeps the rest, and the atexit sweep covers
    hard-teardown leftovers."""
    import os

    from spark_rapids_tpu.columnar import DeviceTable, HostTable
    from spark_rapids_tpu.runtime.spill import (
        BufferCatalog,
        SpillableBatch,
        _atexit_spill_sweep,
    )

    disk_dir = str(tmp_path)
    cat = BufferCatalog(host_limit_bytes=1, disk_dir=disk_dir)
    host = HostTable.from_pydict({"v": np.arange(64, dtype=np.int64)})
    sb = SpillableBatch(DeviceTable.from_host(host), cat)
    sb.spill_to_host()
    sb.spill_to_disk()
    files = os.listdir(disk_dir)
    assert len(files) == 1 and files[0].startswith("rapids_spill_")
    # release() path unlinks its own file
    sb2 = SpillableBatch(DeviceTable.from_host(host), cat)
    sb2.spill_to_host()
    sb2.spill_to_disk()
    sb2.release()
    assert len(os.listdir(disk_dir)) == 1
    # shutdown releases every registered spillable (their release()
    # unlinks) then sweeps whatever remains — nothing survives
    cat.shutdown()
    assert os.listdir(disk_dir) == []
    # atexit sweep: a file that escaped release/shutdown still goes
    sb3 = SpillableBatch(DeviceTable.from_host(host), cat)
    sb3.spill_to_host()
    sb3.spill_to_disk()
    assert len(os.listdir(disk_dir)) == 1
    _atexit_spill_sweep()
    assert os.listdir(disk_dir) == []


def test_buffer_catalog_counter_bumps_are_locked():
    """The spill counters are bumped from concurrent retry/service
    paths; the read-modify-write must hold the catalog lock (it did
    not — increments were lost under contention)."""
    from spark_rapids_tpu.runtime.spill import BufferCatalog

    cat = BufferCatalog()
    n, threads = 500, []
    for _ in range(4):
        t = threading.Thread(
            target=lambda: [cat._bump("spill_device_count", 1)
                            for _ in range(n)])
        threads.append(t)
        t.start()
    for t in threads:
        t.join()
    assert cat.spill_device_count == 4 * n


def test_stats_snapshot_consistent_under_concurrency():
    """QueryService.stats() takes the scheduler lock for the whole
    snapshot while workers mutate counters; lifecycle counters must
    add up exactly afterwards and every interim snapshot must be
    internally sane (never more running than workers)."""
    with QueryService({}, max_concurrent=3) as svc:
        df = svc.session.create_dataframe(_data())
        stop = threading.Event()
        bad = []

        def hammer():
            while not stop.is_set():
                st = svc.stats()
                if st["running"] > svc.max_concurrent or \
                        st["running"] < 0:
                    bad.append(st)
                svc.health()

        reader = threading.Thread(target=hammer)
        reader.start()
        handles = [svc.submit(_agg(df), tenant=f"t{i % 3}")
                   for i in range(12)]
        for h in handles:
            assert h.wait(timeout=120)
        stop.set()
        reader.join(timeout=10)
        assert not bad
        st = svc.stats()
        assert st["submitted"] == 12
        assert (st["finished"] + st["failed"] + st["cancelled"]
                + st["timed_out"]) == 12
        assert st["finished"] == 12


# ---------------------------------------------------------------------------
# fault-spec plumbing for the new kinds/points
# ---------------------------------------------------------------------------


def test_new_fault_kinds_parse_and_fire():
    from spark_rapids_tpu.runtime.faults import parse_fault_spec

    armed = parse_fault_spec(
        "device.lost:device_lost:1;dispatch.wedge:wedge:2:9;"
        "service.worker_crash:crash:0.5:3")
    assert [a.kind for a in armed] == ["device_lost", "wedge", "crash"]
    with pytest.raises(Exception):
        parse_fault_spec("device.lost:nosuchkind:1")
    with pytest.raises(Exception):
        parse_fault_spec("service.nosuchpoint:crash:1")
