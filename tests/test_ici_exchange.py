"""Plan-integrated ICI all-to-all exchange: the ENGINE's Exchange exec runs
the collective path over the virtual 8-device CPU mesh — not a bespoke
kernel (VERDICT r1: 'mesh_hash_exchange is an island')."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit

from tests.asserts import assert_tpu_and_cpu_are_equal
from tests.data_gen import DoubleGen, IntGen, LongGen, StringGen, gen_table


@pytest.fixture(scope="module")
def ici_session():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.shuffle.mode": "ICI"})


def _df(sess, gens, n=800, seed=71, nb=1):
    from spark_rapids_tpu.plan import from_host_table
    return from_host_table(gen_table(gens, n, seed), sess, nb)


GENS = {"k": IntGen(min_val=0, max_val=40), "s": StringGen(cardinality=9),
        "v": LongGen(min_val=-500, max_val=500),
        "d": DoubleGen(corner_prob=0.0)}


def test_ici_exchange_engages(ici_session):
    """repartition(8) by hash must take the collective path (metric)."""
    from spark_rapids_tpu.overrides import apply_overrides
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec

    df = _df(ici_session, GENS).repartition(8, "k")
    executable, _ = apply_overrides(df.plan, ici_session.conf)

    exchanges = []

    def walk(e):
        if isinstance(e, TpuShuffleExchangeExec):
            exchanges.append(e)
        for c in getattr(e, "children", ()):
            walk(c)
        for attr in ("source", "tpu_exec", "cpu_node"):
            nxt = getattr(e, attr, None)
            if nxt is not None:
                walk(nxt)

    walk(executable)
    assert len(exchanges) == 1
    batches = list(executable.execute_cpu())
    assert exchanges[0].metrics.get("iciPartitions") == 8
    total = sum(b.num_rows for b in batches)
    assert total == 800


def test_ici_exchange_int_keys_correct(ici_session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).repartition(8, "k")
        .group_by("k").agg(F.count().alias("c"), F.sum(col("v")).alias("sv")),
        ici_session, cpu_session)


def test_ici_exchange_string_keys_correct(ici_session, cpu_session):
    """String keys hash via the replicated dictionary byte matrix."""
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).repartition(8, "s")
        .group_by("s").agg(F.count().alias("c"), F.avg(col("d")).alias("ad")),
        ici_session, cpu_session, approximate_float=True)


def test_ici_q1_over_8_shards(ici_session, cpu_session):
    """Full q1-shaped pipeline THROUGH THE ENGINE with an 8-way collective
    exchange in the middle (VERDICT r1 item 9's done-criterion)."""
    def build(s):
        return (_df(s, GENS, n=2000, nb=3)
                .filter(col("v") > lit(-400))
                .repartition(8, "s")
                .group_by("s")
                .agg(F.count().alias("n"), F.sum(col("d")).alias("sd"),
                     F.avg(col("v")).alias("av"))
                .sort("s"))
    assert_tpu_and_cpu_are_equal(build, ici_session, cpu_session,
                                 ignore_order=False,
                                 approximate_float=True)


def test_ici_non_pow2_partitions_run_the_collective(ici_session,
                                                    cpu_session):
    """7 partitions on the 8-device mesh: the pow2 row capacity pads up
    to a multiple of 7 and the COLLECTIVE still runs (round-4 verdict:
    the non-pow2 case used to silently fall back to the host shuffle)."""
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).repartition(7, "k")
        .group_by("k").agg(F.count().alias("c")),
        ici_session, cpu_session)
    df = _df(ici_session, GENS).repartition(7, "k").group_by("k").agg(
        F.count().alias("c"))
    df.collect_table()
    m = ici_session.last_metrics()
    assert "iciPartitions=7" in m, m


def test_ici_preserves_rows_with_nulls(ici_session, cpu_session):
    gens = {"k": IntGen(min_val=0, max_val=10, null_prob=0.3),
            "v": IntGen()}
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, gens).repartition(4, "k")
        .group_by("k").agg(F.count().alias("c")),
        ici_session, cpu_session)
