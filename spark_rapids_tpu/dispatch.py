"""Device-constant interning + dispatch accounting.

Measured on the tunneled TPU (PERF.md): kernel dispatches PIPELINE — eight
chained dispatches plus one result fetch cost the same ~0.09s as one — but
every host->device transfer in the warm path is a fresh ~0.1-3s stall (a
tiny 4-byte scalar upload costs ~0.15s, and an upload interleaved between
dispatches forces a pipeline flush costing seconds). The reference never
faces this: cudaMemcpyAsync on PCIe is microseconds, so it re-uploads
per-kernel scratch freely (e.g. JCudfSerialization headers).

The TPU-first rule is therefore: NOTHING transfers host->device on a warm
query. Every per-query host-side constant — expression aux arrays
(dictionary codes, literal tables, remap vectors), aggregate size/stride
vectors, row-count scalars — is interned here by CONTENT, so a repeated
query shape reuses the device-resident copy and the warm path performs
zero uploads.

``count_dispatch`` feeds the per-query ``dispatches`` metric (VERDICT r3:
the dispatch count must be observable)."""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.obs.metrics import metric_scope, register_metric

_LOCK = threading.Lock()

#: spark.sql.ansi.enabled, set per-query by the session (same pattern as
#: the masked-batch and retry contextvars)
import contextvars
ANSI_MODE = contextvars.ContextVar("rapids_ansi_mode", default=False)

#: content-keyed device copies of host constant arrays (LRU order)
_CONST_CACHE: "OrderedDict[tuple, jax.Array]" = OrderedDict()
#: interned device scalars keyed by (dtype, value) (LRU order)
_SCALAR_CACHE: "OrderedDict[tuple, jax.Array]" = OrderedDict()

#: evict the const cache above this many entries (scans are cached on their
#: host tables, not here; these are small aux/remap arrays). Eviction is
#: LRU one-at-a-time — a wholesale clear() at the cap silently dropped
#: every WARM scan constant and re-triggered the catastrophic
#: mid-pipeline uploads PERF.md measured (~0.15s per tiny array on the
#: tunneled TPU); a hot key must survive cap pressure.
_CONST_CACHE_CAP = 8192


def _content_key(arr: np.ndarray) -> tuple:
    if arr.dtype == object:
        # object arrays (string dictionaries) hash by element repr
        h = hashlib.sha1("\x00".join(map(repr, arr.ravel().tolist()))
                         .encode()).digest()
        return (str(arr.dtype), arr.shape, h)
    b = np.ascontiguousarray(arr).tobytes()
    if len(b) <= 128:
        return (str(arr.dtype), arr.shape, b)
    return (str(arr.dtype), arr.shape, hashlib.sha1(b).digest())


def device_const(arr) -> jax.Array:
    """Device copy of a host constant array, interned by content. Safe to
    call inside a jit trace (the cached concrete array is captured as a
    trace constant — uploaded once at compile, never per call)."""
    if isinstance(arr, jax.Array):
        return arr
    arr = np.asarray(arr)
    key = _content_key(arr)
    with _LOCK:
        d = _CONST_CACHE.get(key)
        if d is not None:
            _CONST_CACHE.move_to_end(key)
    if d is None:
        d = jnp.asarray(arr)
        with _LOCK:
            while len(_CONST_CACHE) >= _CONST_CACHE_CAP:
                _CONST_CACHE.popitem(last=False)
            _CONST_CACHE[key] = d
    return d


def device_scalar(value, dtype=np.int32) -> jax.Array:
    """Interned 0-d device scalar (the DeviceTable row-count pattern:
    ``jnp.asarray(np.int32(n))`` per table was a ~0.15s upload EACH)."""
    dt = np.dtype(dtype)
    key = (dt.str, value)
    with _LOCK:
        d = _SCALAR_CACHE.get(key)
        if d is not None:
            _SCALAR_CACHE.move_to_end(key)
    if d is None:
        d = jnp.asarray(np.asarray(value, dtype=dt))
        with _LOCK:
            while len(_SCALAR_CACHE) >= _CONST_CACHE_CAP:
                _SCALAR_CACHE.popitem(last=False)
            _SCALAR_CACHE[key] = d
    return d


def prep_aux(pctx) -> tuple:
    """Upload a PrepCtx's aux arrays: content-interned for deterministic
    slots, plain per-call upload for nondeterministic ones (rand streams —
    interning those would pin every batch's values on device forever)."""
    intern = getattr(pctx, "aux_intern", None) or [True] * len(pctx.aux_arrays)
    return tuple(device_const(a) if keep else jnp.asarray(a)
                 for a, keep in zip(pctx.aux_arrays, intern))


def clear_device_constants() -> int:
    """Drop interned device constants (device OOM recovery hook)."""
    with _LOCK:
        n = len(_CONST_CACHE) + len(_SCALAR_CACHE)
        _CONST_CACHE.clear()
        _SCALAR_CACHE.clear()
    return n


# -- sanctioned host synchronization ----------------------------------------


class _ThreadCounter(threading.local):
    """Per-thread counter: queries execute whole on one thread (direct
    calls on the caller's thread, service queries on their worker), so
    thread-locality makes the per-query dispatch/sync counts correct
    under CONCURRENT queries — a shared slot would cross-contaminate
    every in-flight query's count on reset."""

    def __init__(self):
        self.n = 0


_HOST_FETCHES = _ThreadCounter()


def host_fetch(value):
    """THE sanctioned device->host synchronization point for exec/op hot
    paths (the repo lint's RL-HOST-SYNC rule rejects raw
    ``jax.device_get`` / ``block_until_ready`` in execs/ and ops/).

    Every call is a deliberate ~0.1s pipeline stall on the tunneled TPU,
    so funneling them here keeps them countable (``host_fetch_count``)
    and greppable in review. Returns the fetched value as host data
    (numpy array or python scalar for 0-d inputs)."""
    _HOST_FETCHES.n += 1
    fetched = jax.device_get(value)
    return fetched


def host_fetch_count() -> int:
    return _HOST_FETCHES.n


# -- compile accounting ------------------------------------------------------

register_metric("kernelTraces", "count", "ESSENTIAL",
                "XLA traces (new jit-cache entries): each is a fresh "
                "trace + lowering + compile — the ~1-2 min cold-shape "
                "cliff on the TPU backend")
register_metric("kernelTraceCacheHits", "count", "MODERATE",
                "dispatches served by an existing jit-cache entry "
                "(no trace, no compile)")
register_metric("kernelCompileTime", "timing", "ESSENTIAL",
                "wall time of dispatches that triggered a new trace "
                "(trace + lowering + backend compile)")
register_metric("padWasteRows", "count", "MODERATE",
                "dead tail rows uploaded to pad batches up to their "
                "capacity bucket (the price of the bounded kernel set)")
register_metric("pallasKernels", "count", "MODERATE",
                "primitive dispatch sites that resolved to a Pallas "
                "kernel at trace time (kernels/); warm dispatches "
                "replay the traced choice without re-counting")
register_metric("hloFallbacks", "count", "MODERATE",
                "primitive dispatch sites that took the HLO path at "
                "trace time — disabled by conf, shape outside the "
                "kernel's envelope, or a demoted primitive")

#: the process-wide `compile` scope: serving-latency observability for
#: shape bucketing + the executable cache (which adds its own counters)
COMPILE_SCOPE = metric_scope("compile")


class _ThreadFloat(threading.local):
    def __init__(self):
        self.v = 0.0


#: per-thread per-query accumulators (the _ThreadCounter rationale:
#: queries execute whole on one thread, so per-query deltas stay
#: correct under concurrent service workers)
_COMPILE_S = _ThreadFloat()
_TRACES = _ThreadCounter()
_PAD_WASTE = _ThreadCounter()
#: warm-dispatch trace-cache hits accumulate PER THREAD and flush to
#: the scope once per query (flush_trace_cache_hits) — taking the
#: process-wide scope lock on every warm dispatch would serialize
#: concurrent service workers on the hottest path
_TRACE_HITS = _ThreadCounter()


def flush_trace_cache_hits() -> int:
    """Move this thread's accumulated warm-dispatch counts into the
    ``compile`` scope (called at query end by the session)."""
    n = _TRACE_HITS.n
    _TRACE_HITS.n = 0
    if n:
        COMPILE_SCOPE.add("kernelTraceCacheHits", n)
    return n


def count_pad_waste(n: int) -> None:
    """Record ``n`` dead tail rows padded onto an uploaded batch."""
    if n <= 0:
        return
    _PAD_WASTE.n += n
    COMPILE_SCOPE.add("padWasteRows", n)


def compile_stats() -> Tuple[int, float, int]:
    """(traces, compile seconds, pad-waste rows) on THIS thread since
    the last reset — the session snapshots these per query."""
    return _TRACES.n, _COMPILE_S.v, _PAD_WASTE.n


def reset_compile_stats() -> None:
    _TRACES.n = 0
    _COMPILE_S.v = 0.0
    _PAD_WASTE.n = 0


def _jit_cache_size(jf) -> Optional[int]:
    """The jit function's trace-cache entry count, or None when this
    jax build does not expose it (trace accounting then reports 0).
    Callers probe capability ONCE per jitted function — raising and
    swallowing an AttributeError on every dispatch would put exception
    overhead on the hot path."""
    try:
        return jf._cache_size()
    except Exception:
        return None


# -- Pallas program interning ------------------------------------------------

#: built pallas_call callables keyed by their static shape signature —
#: the kernels/ layer's analog of the shared_traces jit pools: a
#: primitive's program is constructed once per shape and every trace
#: that embeds it (across queries and sessions) reuses the object
_PALLAS_CACHE: Dict[tuple, object] = {}


def pallas_program(key: tuple, builder):
    """Process-wide interning of built Pallas programs. ``key`` must
    capture every static parameter of the program (shape, dtypes,
    grid/block choices); ``builder`` constructs it on first use."""
    with _LOCK:
        got = _PALLAS_CACHE.get(key)
    if got is None:
        built = builder()
        with _LOCK:
            # build-race loser adopts the winner's interned program —
            # returning its own duplicate would pay a second compile
            got = _PALLAS_CACHE.setdefault(key, built)
    return got


def clear_pallas_programs() -> int:
    """Drop interned Pallas programs (device-loss recovery rides along
    with ops/expr.clear_kernel_caches: a program object is cheap to
    rebuild and must not outlive a reinitialized backend)."""
    with _LOCK:
        n = len(_PALLAS_CACHE)
        _PALLAS_CACHE.clear()
    return n


# -- dispatch accounting ----------------------------------------------------

_DISPATCHES = _ThreadCounter()


def count_dispatch(n: int = 1) -> None:
    """Record ``n`` device kernel dispatches (on this thread — see
    _ThreadCounter). No-op inside a jit trace (an inlined sub-kernel is
    not a dispatch)."""
    _DISPATCHES.n += n


def dispatch_count() -> int:
    return _DISPATCHES.n


def reset_dispatch_count() -> int:
    old = _DISPATCHES.n
    _DISPATCHES.n = 0
    return old


try:
    from jax._src.core import trace_state_clean as _trace_state_clean
except ImportError:  # pragma: no cover - jax internals moved
    def _trace_state_clean() -> bool:
        return True


def tracing() -> bool:
    """Are we inside a jax trace right now?"""
    return not _trace_state_clean()


#: per-kernel wall timings when SRT_PROFILE_DISPATCH=1 (each dispatch is
#: force-synced via a scalar fetch, so entries ~= kernel compute + one RTT)
DISPATCH_PROFILE: list = []

#: (kernel name, thread name) per counted NEW trace when SRT_TRACE_LOG=1
#: — identifies which kernel shapes missed the jit caches (e.g. hunting
#: a cold-compile cliff the executable cache should have absorbed)
TRACE_LOG: list = []


def _sync_result(res):
    from spark_rapids_tpu.shims import get_shim
    leaves = get_shim().tree_leaves(res)
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            jax.device_get(jnp.ravel(leaf)[:1])
            return


def tpu_jit(fn, **kwargs):
    """jax.jit that records a dispatch per (non-traced) call — when an
    exec kernel runs inside a whole-plan fused trace (execs/fused.py) it
    inlines into the outer program and is NOT a dispatch. Also feeds the
    ``compile`` metric scope: a call that grows the jit's trace cache is
    a new XLA trace (counted, with its wall as kernelCompileTime — the
    dispatch itself is async, so a cache-hit call returns in
    microseconds while a tracing call blocks for trace + lowering +
    backend compile); everything else is a trace-cache hit."""
    import os
    import time
    jf = jax.jit(fn, **kwargs)
    name = getattr(fn, "__qualname__", getattr(fn, "__name__", "kernel"))
    profile = bool(os.environ.get("SRT_PROFILE_DISPATCH"))
    trace_log = bool(os.environ.get("SRT_TRACE_LOG"))

    from spark_rapids_tpu.obs.spans import TRACER
    from spark_rapids_tpu.runtime.faults import fault_point

    # cache sizes already credited as a trace: two threads dispatching
    # the same COLD kernel concurrently both observe the cache growing
    # (one traces, the other blocks on it) — only the first claimant of
    # a given size counts it, the other records a trace-cache hit.
    # Attribution is APPROXIMATE under that race: a warm concurrent
    # dispatch can claim the size first and book a near-zero
    # kernelCompileTime while the tracing thread books a hit —
    # process-wide totals stay right, per-query/thread splits may skew.
    # Exact attribution needs compiler hooks jax does not expose.
    counted_sizes: set = set()
    counted_lock = threading.Lock()
    has_cache_size = _jit_cache_size(jf) is not None

    def call(*args, **kw):
        if not _trace_state_clean():
            return jf(*args, **kw)
        fault_point("dispatch.kernel", op=name)
        # survivability injection (runtime/health.py consumers): a wedge
        # stalls INSIDE this dispatch (the between-batch cancel check
        # never runs — watchdog territory); a device loss raises the
        # fatal error the health monitor recovers from
        fault_point("dispatch.wedge", op=name)
        fault_point("device.lost", op=name)
        count_dispatch()
        # host span per dispatch (async: covers enqueue, not device
        # compute — Xprof owns the device timeline); one attribute read
        # when the tracer is idle
        sp = TRACER.begin(name, "dispatch") if TRACER.enabled else None
        try:
            before = _jit_cache_size(jf) if has_cache_size else None
            t0 = time.perf_counter()
            # Pallas primitives embedded while TRACING this call record
            # themselves in the capture frame (kernels._note_used). A
            # kernel that traces fine but dies at backend compile /
            # first execution (Mosaic lowering happens HERE, inside
            # jf(...), not at trace time) raises outside the kernels
            # layer's guarded() wrapper — the frame tells us which
            # primitives to demote so the session's replay re-traces on
            # the HLO path instead of the exec circuit breaker dropping
            # the whole operator to CPU.
            from spark_rapids_tpu import kernels as _kernels
            frame = _kernels.begin_trace_capture()
            try:
                res = jf(*args, **kw)
            except Exception as exc:
                from spark_rapids_tpu.errors import (
                    ColumnarProcessingError,
                    KernelCrashError,
                )
                from spark_rapids_tpu.runtime.crash_handler import (
                    is_fatal_device_error,
                )
                from spark_rapids_tpu.runtime.retry import is_device_oom
                if (not frame or is_device_oom(exc)
                        or is_fatal_device_error(exc)
                        or isinstance(exc, _kernels.KernelIneligible)):
                    # OOMs belong to the retry framework, fatal errors
                    # to the health monitor, and KernelIneligible is a
                    # structured fallback signal for the dispatch site
                    # (the join memoizes it) — none are kernel crashes
                    raise
                if isinstance(exc, KernelCrashError):
                    # already replayable (e.g. an injected crash that
                    # crossed this frame): demote what was embedded,
                    # keep the type
                    for kname in sorted(frame):
                        _kernels.demote(kname, exc)
                    raise
                if isinstance(exc, ColumnarProcessingError):
                    # engine-typed trace failure (expression/plan bug
                    # that happens to share a trace with a kernel):
                    # not the kernel's fault — surface it untouched
                    raise
                # everything else (XlaRuntimeError, Mosaic lowering
                # NotImplementedError, raw jnp errors) demotes the
                # embedded primitives. Deliberately CONSERVATIVE: an
                # unrelated raw trace bug sharing the program costs the
                # kernels their fast path process-wide and surfaces the
                # real error on the replayed HLO trace — the priced-in
                # alternative (trying to classify compiler errors by
                # message) silently misses real lowering failures.
                for kname in sorted(frame):
                    _kernels.demote(kname, exc)
                raise KernelCrashError(
                    f"pallas-embedding program {name} failed at "
                    f"compile/execute; demoted "
                    f"{sorted(frame)} to HLO: {exc}") from exc
            finally:
                _kernels.end_trace_capture(frame)
            if before is not None:
                after = _jit_cache_size(jf)
                grew = after is not None and after > before
                if grew:
                    with counted_lock:
                        grew = after not in counted_sizes
                        counted_sizes.add(after)
                if grew:
                    dt = time.perf_counter() - t0
                    _TRACES.n += 1
                    _COMPILE_S.v += dt
                    COMPILE_SCOPE.add("kernelTraces", 1)
                    COMPILE_SCOPE.add("kernelCompileTime", dt)
                    if trace_log:
                        TRACE_LOG.append(
                            (name, threading.current_thread().name))
                else:
                    _TRACE_HITS.n += 1  # lock-free; flushed per query
            if profile:
                _sync_result(res)
                DISPATCH_PROFILE.append((name, time.perf_counter() - t0))
            return res
        finally:
            TRACER.end(sp)

    call.__wrapped__ = jf
    return call
