"""Shuffle layer (reference: SURVEY.md §2.6 — RapidsShuffleManager
MULTITHREADED mode, GpuPartitioning device split, JCudfSerialization wire
format, shuffle coalesce; the ICI collective path lives in parallel/)."""

from spark_rapids_tpu.shuffle.hashing import murmur3_hash_device, murmur3_hash_host
from spark_rapids_tpu.shuffle.partitioning import (
    HashPartitioner,
    RangePartitioner,
    RoundRobinPartitioner,
    SinglePartitioner,
    split_by_partition,
)
from spark_rapids_tpu.shuffle.serializer import pack_table, unpack_table
from spark_rapids_tpu.shuffle.manager import ShuffleManager, get_shuffle_manager

__all__ = [
    "murmur3_hash_device",
    "murmur3_hash_host",
    "HashPartitioner",
    "RangePartitioner",
    "RoundRobinPartitioner",
    "SinglePartitioner",
    "split_by_partition",
    "pack_table",
    "unpack_table",
    "ShuffleManager",
    "get_shuffle_manager",
]
