"""TPU hash aggregate (reference: GpuHashAggregateExec / GpuMergeAggregate-
Iterator, GpuAggregateExec.scala — SURVEY.md §2.3).

TPU-first design: instead of a hash table (pointer-chasing is hostile to the
VPU), grouping is SORT-SEGMENT based — the XLA-friendly classic:

  1. evaluate key/value expressions (fused, ops/expr.py);
  2. lexicographic multi-operand ``lax.sort`` over (live, key-validity,
     key-data...) with a row-index payload;
  3. segment boundaries -> dense group ids via cumsum;
  4. ``jax.ops.segment_*`` reductions with static num_segments=capacity;
  5. scatter per-group results to [0, ngroups) positions.

Everything is static-shaped; the live group count rides out as a device
scalar. String keys group by dictionary code (order-preserving per batch).
Requires a single coalesced input batch (RequireSingleBatch goal) in v1;
partial-per-batch + merge is the planned widening."""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceColumn, DeviceTable
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops.expr import (
    DevVal,
    EvalCtx,
    Expression,
    NodePrep,
    PrepCtx,
    _prep_trace_key,
    _walk_eval,
    _walk_prep,
)

DEVICE_SUPPORTED_AGGS = (agg.Sum, agg.Min, agg.Max, agg.Count, agg.Average,
                         agg.First, agg.Last, agg.StddevPop, agg.StddevSamp,
                         agg.VariancePop, agg.VarianceSamp)


def _sortable(data, validity):
    """Transform (data, validity) into sort operands grouping nulls
    together: (invalid_first_flag, data_with_nulls_zeroed). Floats are
    normalized so -0.0 groups with 0.0 (Spark NormalizeFloatingNumbers)."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        data = jnp.where(data == 0.0, jnp.zeros_like(data), data)
    return [(~validity).astype(jnp.int32), jnp.where(validity, data, jnp.zeros_like(data))]


class TpuHashAggregateExec(TpuExec):
    def __init__(self, child: TpuExec, grouping: Sequence[Expression],
                 agg_specs: Sequence[Tuple[str, agg.AggregateFunction]],
                 grouping_names: Sequence[str]):
        super().__init__()
        self.children = (child,)
        self.grouping = list(grouping)
        self.agg_specs = list(agg_specs)
        self.grouping_names = list(grouping_names)

    def output_schema(self):
        out = [(n, g.data_type) for n, g in zip(self.grouping_names, self.grouping)]
        out += [(n, fn.data_type) for n, fn in self.agg_specs]
        return out

    def execute(self):
        from spark_rapids_tpu.runtime.retry import retry_block
        batches = list(self.children[0].execute())
        if len(batches) != 1:
            raise ColumnarProcessingError(
                "TpuHashAggregateExec requires a single coalesced batch")
        # spill-and-replay on OOM; split is unsound for a single-pass agg
        # (reference escalates to sort-fallback merge — planned widening)
        yield retry_block(lambda: self._aggregate(batches[0]))

    # -- core ---------------------------------------------------------------
    def _aggregate(self, table: DeviceTable) -> DeviceTable:
        value_exprs: List[Expression] = []
        for _, fn in self.agg_specs:
            value_exprs.append(fn.child if fn.child is not None else None)

        pctx = PrepCtx(table)
        key_preps: List[List[NodePrep]] = []
        for g in self.grouping:
            preps: List[NodePrep] = []
            _walk_prep(g, pctx, preps)
            key_preps.append(preps)
        val_preps: List[List[NodePrep]] = []
        for ve in value_exprs:
            if ve is None:
                val_preps.append([])
            else:
                preps = []
                _walk_prep(ve, pctx, preps)
                val_preps.append(preps)

        cols = tuple(DevVal(c.data, c.validity) for c in table.columns)
        aux = tuple(jnp.asarray(a) for a in pctx.aux_arrays)
        capacity = table.capacity

        from spark_rapids_tpu.ops.expr import shared_traces
        self._traces = shared_traces(
            ("agg",
             tuple(g.key() for g in self.grouping),
             tuple(fn.key() for _, fn in self.agg_specs),
             table.schema_key()[0]))
        tkey = (capacity,
                tuple(_prep_trace_key(p) for p in key_preps),
                tuple(_prep_trace_key(p) for p in val_preps))
        fn = self._traces.get(tkey)
        if fn is None:
            fn = jax.jit(self._build_kernel(capacity, key_preps, val_preps))
            self._traces[tkey] = fn

        out_arrays, ngroups = fn(cols, aux, table.nrows_dev)

        out_cols: List[DeviceColumn] = []
        names: List[str] = []
        for i, (g, name) in enumerate(zip(self.grouping, self.grouping_names)):
            data, validity = out_arrays[i]
            root = key_preps[i][-1]
            out_cols.append(DeviceColumn(g.data_type, data, validity,
                                         dictionary=root.out_dict,
                                         dict_sorted=root.dict_sorted))
            names.append(name)
        for j, (name, fnagg) in enumerate(self.agg_specs):
            data, validity = out_arrays[len(self.grouping) + j]
            dictionary = None
            dict_sorted = True
            if isinstance(fnagg.data_type, T.StringType) and val_preps[j]:
                dictionary = val_preps[j][-1].out_dict
                dict_sorted = val_preps[j][-1].dict_sorted
            out_cols.append(DeviceColumn(fnagg.data_type, data, validity,
                                         dictionary=dictionary, dict_sorted=dict_sorted))
            names.append(name)
        # group counts are usually tiny vs the input bucket; re-bucket so
        # downstream sorts/transfers don't run at input capacity
        return DeviceTable(names, out_cols, ngroups, capacity).shrink()

    def _build_kernel(self, capacity: int, key_preps, val_preps):
        grouping = self.grouping
        agg_specs = self.agg_specs
        value_exprs = [fn.child for _, fn in agg_specs]

        def kernel(cols, aux, nrows):
            live = jnp.arange(capacity, dtype=jnp.int32) < nrows

            key_vals: List[DevVal] = []
            for g, preps in zip(grouping, key_preps):
                ctx = EvalCtx(cols, aux, nrows, capacity)
                ctx._prep_iter = iter(preps)
                key_vals.append(_walk_eval(g, ctx))
            val_vals: List[DevVal] = []
            for ve, preps in zip(value_exprs, val_preps):
                if ve is None:
                    val_vals.append(None)
                else:
                    ctx = EvalCtx(cols, aux, nrows, capacity)
                    ctx._prep_iter = iter(preps)
                    val_vals.append(_walk_eval(ve, ctx))

            # normalize float keys so grouping matches the CPU oracle
            norm = []
            for kv in key_vals:
                d = kv.data
                if jnp.issubdtype(d.dtype, jnp.floating):
                    d = jnp.where(d == 0.0, jnp.zeros_like(d), d)
                norm.append(DevVal(d, kv.validity))
            key_vals = norm

            if grouping:
                operands = [(~live).astype(jnp.int32)]  # dead rows last
                for kv in key_vals:
                    operands.extend(_sortable(kv.data, kv.validity))
                payload = jnp.arange(capacity, dtype=jnp.int32)
                sorted_all = jax.lax.sort(operands + [payload],
                                          num_keys=len(operands))
                perm = sorted_all[-1]
                s_live = live[perm]
                s_keys = [DevVal(kv.data[perm], kv.validity[perm]) for kv in key_vals]

                # group boundaries among live rows
                first = jnp.arange(capacity) == 0
                changed = jnp.zeros(capacity, dtype=jnp.bool_)
                for kv in s_keys:
                    d, v = kv.data, kv.validity
                    dprev = jnp.roll(d, 1)
                    vprev = jnp.roll(v, 1)
                    diff = (jnp.where(v & vprev, d != dprev, v != vprev))
                    changed = changed | diff
                new_group = (first | changed) & s_live
                gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
                gid = jnp.where(s_live, gid, capacity - 1)  # park dead rows
                ngroups = jnp.sum(new_group.astype(jnp.int32))
            else:
                perm = jnp.arange(capacity, dtype=jnp.int32)
                s_live = live
                s_keys = []
                gid = jnp.zeros(capacity, dtype=jnp.int32)
                ngroups = jnp.asarray(1, dtype=jnp.int32)

            group_live = jnp.arange(capacity, dtype=jnp.int32) < ngroups

            outs = []
            # key columns: scatter first-occurrence values to gid slots
            for kv in s_keys:
                tgt = jnp.where(s_live, gid, capacity)
                kd = jnp.zeros_like(kv.data).at[tgt].set(kv.data, mode="drop")
                kvv = jnp.zeros_like(kv.validity).at[tgt].set(kv.validity, mode="drop")
                outs.append((kd, kvv & group_live))

            for (name, fnagg), vv in zip(agg_specs, val_vals):
                outs.append(self._agg_device(fnagg, vv, perm, gid, s_live,
                                             group_live, ngroups, capacity))
            return outs, ngroups

        return kernel

    @staticmethod
    def _agg_device(fnagg, vv, perm, gid, s_live, group_live, ngroups, capacity):
        seg = jax.ops
        if isinstance(fnagg, agg.Count):
            if fnagg.child is None:
                w = s_live.astype(jnp.int64)
            else:
                w = (vv.validity[perm] & s_live).astype(jnp.int64)
            cnt = seg.segment_sum(w, gid, num_segments=capacity)
            return (cnt, group_live)

        sd = vv.data[perm]
        sv = vv.validity[perm] & s_live
        nonnull = seg.segment_sum(sv.astype(jnp.int64), gid, num_segments=capacity)
        has_any = (nonnull > 0) & group_live

        if isinstance(fnagg, agg.Sum):
            if isinstance(fnagg.data_type, T.LongType):
                v = jnp.where(sv, sd.astype(jnp.int64), 0)
                s = seg.segment_sum(v, gid, num_segments=capacity)
                return (s, has_any)
            v = jnp.where(sv, sd.astype(jnp.float64), 0.0)
            s = seg.segment_sum(v, gid, num_segments=capacity)
            return (jnp.where(has_any, s, 0.0), has_any)

        if isinstance(fnagg, agg.Average):
            v = jnp.where(sv, sd.astype(jnp.float64), 0.0)
            s = seg.segment_sum(v, gid, num_segments=capacity)
            return (jnp.where(has_any, s / jnp.maximum(nonnull, 1), 0.0), has_any)

        if isinstance(fnagg, (agg.StddevPop, agg.StddevSamp, agg.VariancePop, agg.VarianceSamp)):
            v = jnp.where(sv, sd.astype(jnp.float64), 0.0)
            s = seg.segment_sum(v, gid, num_segments=capacity)
            mean = s / jnp.maximum(nonnull, 1)
            centered = jnp.where(sv, (sd.astype(jnp.float64) - mean[gid]) ** 2, 0.0)
            m2 = seg.segment_sum(centered, gid, num_segments=capacity)
            if isinstance(fnagg, (agg.StddevPop, agg.VariancePop)):
                denom = jnp.maximum(nonnull, 1)
                validity = has_any
            else:
                denom = jnp.maximum(nonnull - 1, 1)
                validity = (nonnull > 1) & group_live
            var = m2 / denom
            out = jnp.sqrt(var) if isinstance(fnagg, (agg.StddevPop, agg.StddevSamp)) else var
            return (jnp.where(validity, out, 0.0), validity)

        if isinstance(fnagg, (agg.Min, agg.Max)):
            dt = sd.dtype
            if jnp.issubdtype(dt, jnp.floating):
                ident = jnp.asarray(jnp.inf if isinstance(fnagg, agg.Min) else -jnp.inf, dtype=dt)
            elif dt == jnp.bool_:
                sd = sd.astype(jnp.int32)
                dt = jnp.int32
                ident = jnp.asarray(1 if isinstance(fnagg, agg.Min) else 0, dtype=dt)
            else:
                info = jnp.iinfo(dt)
                ident = jnp.asarray(info.max if isinstance(fnagg, agg.Min) else info.min, dtype=dt)
            v = jnp.where(sv, sd, ident)
            if isinstance(fnagg, agg.Min):
                r = seg.segment_min(v, gid, num_segments=capacity)
            else:
                r = seg.segment_max(v, gid, num_segments=capacity)
            if isinstance(fnagg.data_type, T.BooleanType):
                r = r.astype(jnp.bool_)
            zero = jnp.zeros_like(r)
            return (jnp.where(has_any, r, zero), has_any)

        if isinstance(fnagg, (agg.First, agg.Last)):
            idx = jnp.arange(capacity, dtype=jnp.int64)
            pick_mask = sv if fnagg.ignore_nulls else s_live
            sentinel = capacity if isinstance(fnagg, agg.First) else -1
            pos = jnp.where(pick_mask, idx, sentinel)
            if isinstance(fnagg, agg.First):
                chosen = seg.segment_min(pos, gid, num_segments=capacity)
            else:
                chosen = seg.segment_max(pos, gid, num_segments=capacity)
            got = (chosen >= 0) & (chosen < capacity) & group_live
            safe = jnp.clip(chosen, 0, capacity - 1)
            data = sd[safe]
            validity = got & sv[safe] if fnagg.ignore_nulls else got & vv.validity[perm][safe]
            return (jnp.where(validity, data, jnp.zeros_like(data)), validity)

        raise ColumnarProcessingError(f"device aggregate {type(fnagg).__name__}")

    def describe(self):
        return (f"TpuHashAggregate[keys={self.grouping_names}, "
                f"aggs={[n for n, _ in self.agg_specs]}]")
