"""Sample / TakeOrderedAndProject / CollectLimit / df.cache
(reference analogs: GpuSampleExec, GpuTakeOrderedAndProjectExec,
GpuCollectLimitExec, GpuInMemoryTableScanExec)."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.plan import from_host_table
from spark_rapids_tpu.plan.nodes import SortOrder

from tests.asserts import assert_runs_on_tpu, assert_tpu_and_cpu_are_equal
from tests.data_gen import DoubleGen, IntGen, StringGen, gen_table


def _df(sess, n=600, nb=1, seed=77):
    gens = {"k": IntGen(min_val=0, max_val=100), "s": StringGen(cardinality=7),
            "d": DoubleGen(corner_prob=0.0)}
    return from_host_table(gen_table(gens, n, seed), sess, nb)


def test_sample_deterministic_and_matches_oracle(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).sample(0.3, seed=42),
        session, cpu_session, ignore_order=False)


def test_sample_runs_on_device(session):
    assert_runs_on_tpu(lambda s: _df(s).sample(0.5, seed=1), session)


def test_take_ordered(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).sort("k", "d").limit(17),
        session, cpu_session, ignore_order=False)


def test_take_ordered_desc_multi_batch(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, nb=4).sort(
            SortOrder(col("d"), ascending=False)).limit(9),
        session, cpu_session, ignore_order=False)


def test_take_ordered_plans_as_topk(session):
    from spark_rapids_tpu.plan.nodes import TakeOrderedAndProject
    df = _df(session).sort("k").limit(5)
    assert isinstance(df.plan, TakeOrderedAndProject)


def test_take_ordered_limit_larger_than_input(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, n=30).sort("k", "d").limit(100),
        session, cpu_session, ignore_order=False)


def test_cache_materializes_once(session):
    base = _df(session).filter(col("k") > lit(50))
    cached = base.cache()
    r1 = sorted(cached.collect(), key=str)
    from spark_rapids_tpu.plan.nodes import CachedRelation
    assert isinstance(cached.plan, CachedRelation)
    assert cached.plan._table is not None  # materialized on first action
    table_obj = cached.plan._table
    r2 = sorted(cached.group_by("s").agg(F.count().alias("c"))
                .collect(), key=str)
    assert cached.plan._table is table_obj  # not re-executed
    r3 = sorted(cached.collect(), key=str)
    assert r1 == r3


def test_cache_results_match_uncached(session, cpu_session):
    uncached = sorted(
        _df(cpu_session).filter(col("k") > lit(30))
        .group_by("s").agg(F.sum(col("k")).alias("sk")).collect(), key=str)
    cached = sorted(
        _df(session).filter(col("k") > lit(30)).cache()
        .group_by("s").agg(F.sum(col("k")).alias("sk")).collect(), key=str)
    assert cached == uncached
