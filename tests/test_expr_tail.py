"""Expression tail: from_json/to_json, sequence, stack, replicate_rows,
approx_percentile, pivot (reference: GpuJsonToStructs.scala,
GpuGenerateExec Sequence/Stack/ReplicateRows, GpuApproximatePercentile,
GpuPivotFirst — VERDICT r3 missing #6-#8)."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def tpu():
    return TpuSession()


@pytest.fixture(scope="module")
def cpu():
    return TpuSession({"spark.rapids.sql.enabled": "false"})


# -- from_json / to_json -----------------------------------------------------

def test_from_json_device_and_oracle(tpu, cpu):
    st = T.StructType([T.StructField("a", T.LONG),
                       T.StructField("b", T.DOUBLE)])
    docs = ['{"a": 1, "b": 2.5}', '{"a": 7}', "not json", None,
            '{"a": "wrongtype", "b": 3}', '[1,2]']
    for s in (tpu, cpu):
        got = [r[0] for r in s.create_dataframe(
            {"j": docs}, dtypes={"j": T.STRING}).select(
            F.from_json(col("j"), st).alias("s")).collect()]
        # PERMISSIVE: malformed/non-object -> all-null-fields row;
        # only null INPUT -> null struct
        assert got == [(1, 2.5), (7, None), (None, None), None,
                       (None, 3.0), (None, None)]


def test_from_json_then_get_field(tpu, cpu):
    st = T.StructType([T.StructField("x", T.LONG)])
    docs = ['{"x": %d}' % i for i in range(50)] + [None, "oops"]
    q = lambda s: [r[0] for r in s.create_dataframe(
        {"j": docs}, dtypes={"j": T.STRING}).select(
        F.get_field(F.from_json(col("j"), st), "x").alias("v")).collect()]
    assert q(tpu) == q(cpu) == list(range(50)) + [None, None]


def test_to_json_roundtrip(tpu, cpu):
    q = lambda s: [r[0] for r in s.create_dataframe(
        {"a": np.arange(3, dtype=np.int64),
         "b": np.asarray([1.5, 2.0, -3.25])}).select(
        F.to_json(F.struct(col("a"), col("b"), names=["a", "b"]))
        .alias("j")).collect()]
    got = q(tpu)
    assert got == q(cpu)
    assert got[0] == '{"a":0,"b":1.5}'


# -- sequence ----------------------------------------------------------------

def test_sequence_basic(tpu, cpu):
    data = {"a": np.asarray([1, 5, 3], dtype=np.int64),
            "b": np.asarray([4, 1, 3], dtype=np.int64)}
    q = lambda s: [r[0] for r in s.create_dataframe(data).select(
        F.sequence(col("a"), col("b")).alias("s")).collect()]
    assert q(tpu) == q(cpu) == [[1, 2, 3, 4], [5, 4, 3, 2, 1], [3]]


def test_sequence_with_step_and_nulls(tpu, cpu):
    data = {"a": [0, None, 10], "b": [10, 5, 0], "st": [3, 1, -5]}
    dt = {"a": T.LONG, "b": T.LONG, "st": T.LONG}
    q = lambda s: [r[0] for r in s.create_dataframe(data, dtypes=dt).select(
        F.sequence(col("a"), col("b"), col("st")).alias("s")).collect()]
    assert q(tpu) == q(cpu) == [[0, 3, 6, 9], None, [10, 5, 0]]


def test_sequence_zero_step_raises(tpu, cpu):
    data = {"a": np.asarray([1], dtype=np.int64),
            "b": np.asarray([5], dtype=np.int64),
            "st": np.asarray([0], dtype=np.int64)}
    for s in (tpu, cpu):
        with pytest.raises(Exception):
            s.create_dataframe(data).select(
                F.sequence(col("a"), col("b"), col("st")).alias("s")
            ).collect()


def test_explode_sequence(tpu, cpu):
    data = {"a": np.asarray([1, 3], dtype=np.int64)}
    q = lambda s: sorted(s.create_dataframe(data).select(
        col("a"), F.explode(F.sequence(lit(1), col("a"))).alias("e"))
        .collect())
    assert q(tpu) == q(cpu) == [(1, 1), (3, 1), (3, 2), (3, 3)]


# -- stack / replicate_rows --------------------------------------------------

def test_stack(tpu, cpu):
    data = {"a": np.asarray([1, 2], dtype=np.int64),
            "b": np.asarray([10, 20], dtype=np.int64)}
    q = lambda s: sorted(s.create_dataframe(data)
                         .stack(2, col("a"), col("b"),
                                col("a") + lit(100), col("b") + lit(100),
                                names=["x", "y"]).collect())
    assert q(tpu) == q(cpu) == [(1, 10), (2, 20), (101, 110), (102, 120)]


def test_replicate_rows(tpu, cpu):
    data = {"a": np.asarray([7, 8, 9], dtype=np.int64),
            "n": np.asarray([3, 1, 0], dtype=np.int64)}
    q = lambda s: sorted(s.create_dataframe(data)
                         .replicate_rows("n").collect())
    # n <= 0 rows are DROPPED (GpuReplicateRows semantics)
    assert q(tpu) == q(cpu) == [(7, 3), (7, 3), (7, 3), (8, 1)]


# -- approx_percentile / pivot ----------------------------------------------

def test_approx_percentile(tpu, cpu):
    rng = np.random.default_rng(0)
    data = {"k": rng.integers(0, 4, 4000).astype(np.int64),
            "v": rng.random(4000)}
    q = lambda s: sorted(s.create_dataframe(data).group_by("k").agg(
        F.approx_percentile(col("v"), 0.5).alias("med")).collect())
    got, want = q(tpu), q(cpu)
    for g, w in zip(got, want):
        assert g[0] == w[0] and abs(g[1] - w[1]) <= 1e-9


def test_pivot(tpu, cpu):
    rng = np.random.default_rng(1)
    n = 2000
    data = {"k": rng.integers(0, 5, n).astype(np.int64),
            "p": np.array(["x", "y", "z"], dtype=object)[
                rng.integers(0, 3, n)],
            "v": rng.random(n)}
    q = lambda s: sorted(s.create_dataframe(data)
                         .group_by("k").pivot("p", ["x", "y"])
                         .agg(F.sum(col("v"))).collect())
    got, want = q(tpu), q(cpu)
    assert len(got) == len(want) == 5
    for g, w in zip(got, want):
        assert g[0] == w[0]
        for a, b in zip(g[1:], w[1:]):
            assert abs(a - b) <= 1e-6 * max(1.0, abs(b))


def test_pivot_multiple_aggs(tpu, cpu):
    data = {"k": np.asarray([0, 0, 1], dtype=np.int64),
            "p": np.array(["x", "y", "x"], dtype=object),
            "v": np.asarray([1.0, 2.0, 3.0])}
    q = lambda s: sorted(s.create_dataframe(data)
                         .group_by("k").pivot("p", ["x", "y"])
                         .agg(F.sum(col("v")).alias("s"),
                              F.count(col("v")).alias("c")).collect())
    assert q(tpu) == q(cpu)


def test_explode_alone_no_passthrough(tpu, cpu):
    """explode() as the ONLY select expression (regression: the CPU
    Generate iterated the zero-column pruned table as zero rows)."""
    data = {"a": np.asarray([2, 1], dtype=np.int64)}
    q = lambda s: sorted(s.create_dataframe(data).select(
        F.explode(F.sequence(lit(1), col("a"))).alias("e")).collect())
    assert q(tpu) == q(cpu) == [(1,), (1,), (2,)]
