"""Misc expression batch: nondeterministic ids/rand, normalization markers,
timezone shifts (fixed-offset device subset), md5, concat_ws."""

import datetime as dt

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.plan import from_host_table

from tests.asserts import (
    assert_falls_back,
    assert_runs_on_tpu,
    assert_tpu_and_cpu_are_equal,
)
from tests.data_gen import DoubleGen, IntGen, StringGen, gen_table


def _df(sess, n=300, seed=8):
    gens = {"x": IntGen(min_val=-50, max_val=50),
            "d": DoubleGen(), "s": StringGen(cardinality=6)}
    return from_host_table(gen_table(gens, n, seed), sess)


def test_monotonic_id_and_partition_id(session):
    out = _df(session).select(
        F.monotonically_increasing_id().alias("id"),
        F.spark_partition_id().alias("p"), "x").collect()
    ids = [r[0] for r in out]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)
    assert all(r[1] == 0 for r in out)


def test_rand_bit_identical_to_cpu(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select("x", F.rand(seed=7).alias("r")),
        session, cpu_session, ignore_order=False)


def test_normalize_nan_and_zero(session, cpu_session):
    from spark_rapids_tpu.ops.misc import NormalizeNaNAndZero
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select(
            NormalizeNaNAndZero(col("d")).alias("n")),
        session, cpu_session)


def test_at_least_n_non_nulls(session, cpu_session):
    from spark_rapids_tpu.ops.misc import AtLeastNNonNulls
    gens = {"a": IntGen(null_prob=0.4), "b": IntGen(null_prob=0.4)}
    assert_tpu_and_cpu_are_equal(
        lambda s: from_host_table(gen_table(gens, 200, 2), s).select(
            AtLeastNNonNulls(2, col("a"), col("b")).alias("ok")),
        session, cpu_session)


def test_md5(session, cpu_session):
    import hashlib
    build = lambda s: _df(s).select("s", F.md5(col("s")).alias("h"))  # noqa: E731
    assert_runs_on_tpu(build, session)
    out = build(session).collect()
    for s, h in out:
        if s is not None:
            assert h == hashlib.md5(s.encode()).hexdigest()


def test_concat_ws_skips_nulls(session, cpu_session):
    gens = {"s": StringGen(cardinality=5, nullable=True)}
    build = lambda s: from_host_table(gen_table(gens, 150, 3), s).select(  # noqa: E731
        F.concat_ws("-", lit("a"), col("s"), lit("z")).alias("c"))
    assert_runs_on_tpu(build, session)
    out = build(session).collect()
    ref = [(f"a-{s}-z" if s is not None else "a-z",)
           for (s,) in from_host_table(
               gen_table(gens, 150, 3), session).collect()]
    assert out == ref


def test_timezone_fixed_offset_on_device(session, cpu_session):
    base = dt.datetime(2024, 3, 1, 12, 0, 0)
    table = {"t": [base + dt.timedelta(hours=i) for i in range(48)]}
    def build(s):
        df = s.create_dataframe(table, {"t": T.TIMESTAMP})
        return df.select(
            F.from_utc_timestamp(col("t"), lit("+05:30")).alias("ist"),
            F.to_utc_timestamp(col("t"), lit("GMT-8")).alias("utc8"))
    assert_runs_on_tpu(build, session)
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)
    out = build(session).collect()
    assert out[0][0] == base + dt.timedelta(hours=5, minutes=30)
    assert out[0][1] == base + dt.timedelta(hours=8)


def test_timezone_named_zone_on_device(session):
    """Named/DST zones now run on DEVICE via the transition-table DB
    (GpuTimeZoneDB analog — ops/tzdb.py); only unknown zones fall back."""
    from tests.asserts import assert_runs_on_tpu
    base = dt.datetime(2024, 7, 1, 12, 0, 0)
    table = {"t": [base]}
    def build(s):
        df = s.create_dataframe(table, {"t": T.TIMESTAMP})
        return df.select(
            F.from_utc_timestamp(col("t"),
                                 lit("America/New_York")).alias("et"))
    assert_runs_on_tpu(build, session)
    out = build(session).collect()
    # EDT in July: UTC-4
    assert out[0][0] == base - dt.timedelta(hours=4)

    def bogus(s):
        df = s.create_dataframe(table, {"t": T.TIMESTAMP})
        return df.select(
            F.from_utc_timestamp(col("t"), lit("Not/AZone")).alias("x"))
    assert_falls_back(bogus, session, "Project")
