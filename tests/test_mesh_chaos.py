"""Tier-1 mesh-chaos slice: the distributed path under seeded faults.

The full closure is ``python scale_test.py --mesh 8 --chaos`` (q1-q22
mesh-native under the seeded mesh-fault schedule — MULTICHIP_r07); this
marker-gated slice keeps every mesh recovery mechanism exercised in the
tier-1 gate without the corpus cost:

* ``mesh.shard.put`` crash -> query replay, bit-identical;
* ``mesh.ici.exchange`` corrupt -> the checksummed live-count fetch
  trips and REFETCHES the intact device value;
* ``mesh.gather`` corrupt -> the MeshReland row-count/checksum
  validation trips and re-lands from the still-sharded source;
* partial device loss (``device_lost`` at a ``mesh.*`` point) walks the
  degradation ladder retry -> single-device -> SHRINK onto surviving
  devices — visible in MESH.health_snapshot(), HEALTH.mesh_snapshot(),
  explain() and the event log — not straight to CPU-only;
* ladder exhaustion (shrink budget 0, reinit budget 1) latches CPU-only
  mode and the query still completes;
* the digest-kernel cache rejects late publishes after
  clear_mesh_caches (the PR-9 epoch contract, two-thread pin).
"""

import threading

import numpy as np
import pytest

from spark_rapids_tpu.runtime.faults import CIRCUIT_BREAKER, FAULTS

pytestmark = [pytest.mark.multichip, pytest.mark.chaos]


@pytest.fixture(autouse=True)
def _clean_mesh_fault_state():
    """Mesh chaos mutates PROCESS state (fault registry, breaker,
    health ladder, mesh exclusions, quarantine strikes) — restore all
    of it so the rest of the suite sees a healthy full-strength
    process."""
    from spark_rapids_tpu.parallel.mesh import MESH
    from spark_rapids_tpu.runtime.health import HEALTH, QUARANTINE
    from spark_rapids_tpu.session import TpuSession
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()
    HEALTH.reset()
    QUARANTINE.reset()
    MESH.restore("test setup")
    yield
    FAULTS.disarm()
    CIRCUIT_BREAKER.reset()
    HEALTH.reset()
    QUARANTINE.reset()
    MESH.restore("test teardown")
    # leave the process-wide mesh OFF for the rest of the suite
    TpuSession().placement.prepare()


def _data(n=600):
    return {"k": [f"k{i % 7}" for i in range(n)],
            "v": np.arange(n, dtype=np.int64),
            "x": (np.arange(n, dtype=np.float64) * 0.5)}


def _agg(s):
    from spark_rapids_tpu import functions as F
    return (s.create_dataframe(_data())
            .group_by("k")
            .agg(F.sum("x").alias("sx"), F.count("v").alias("c")))


def _exchange(s):
    """A string-keyed 8-way repartition (the q7 shape): lowers to the
    ICI all-to-all on the 8-device mesh."""
    from spark_rapids_tpu import functions as F
    return (s.create_dataframe(_data())
            .repartition(8, "k")
            .group_by("k")
            .agg(F.sum("v").alias("s")))


def _mesh_scope():
    from spark_rapids_tpu.obs.metrics import scopes_snapshot
    return dict(scopes_snapshot().get("mesh", {}))


def _delta(before, after):
    return {k: after.get(k, 0) - before.get(k, 0)
            for k in set(after) | set(before)
            if after.get(k, 0) != before.get(k, 0)}


def _identical(expected, got):
    import scale_test as ST
    return ST.tables_differ(expected, got)


def test_shard_put_crash_replays_bit_identical():
    from spark_rapids_tpu.session import TpuSession
    expected = _agg(TpuSession()).collect_table()
    s = TpuSession({"spark.rapids.mesh.enabled": "true",
                    "spark.rapids.test.faults":
                        "mesh.shard.put:crash:1:11"})
    got = _agg(s).collect_table()
    assert _identical(expected, got) is None
    assert s.last_fault_replays >= 1
    assert FAULTS.counters().get("mesh.shard.put", 0) == 1


def test_ici_exchange_corrupt_refetches_counts():
    from spark_rapids_tpu.session import TpuSession
    expected = _exchange(TpuSession()).collect_table()
    s = TpuSession({"spark.rapids.mesh.enabled": "true",
                    "spark.rapids.test.faults":
                        "mesh.ici.exchange:corrupt:1:12"})
    before = _mesh_scope()
    got = _exchange(s).collect_table()
    d = _delta(before, _mesh_scope())
    assert _identical(expected, got) is None
    assert d.get("iciExchanges", 0) >= 1, d
    # the corrupted fetch was CAUGHT by the digest and refetched
    assert d.get("gatherChecksFailed", 0) >= 1, d
    assert d.get("shardRetries", 0) >= 1, d


def test_gather_checksum_trip_relands_from_source():
    from spark_rapids_tpu.session import TpuSession
    expected = _agg(TpuSession()).collect_table()
    s = TpuSession({"spark.rapids.mesh.enabled": "true",
                    "spark.rapids.test.faults":
                        "mesh.gather:corrupt:1:13"})
    before = _mesh_scope()
    got = _agg(s).collect_table()
    d = _delta(before, _mesh_scope())
    assert _identical(expected, got) is None
    assert d.get("gatherChecksFailed", 0) >= 1, d
    assert d.get("shardRetries", 0) >= 1, d
    # zero replays: the re-land converged LOCALLY from the intact
    # sharded source, no query re-execution needed
    assert not s.last_fault_replays


def test_gather_check_exhaustion_raises_typed():
    """Every re-gather corrupted (count exceeds the retry budget):
    the boundary raises typed MeshGatherError — which IS a
    KernelCrashError, so with the runtime fallback disabled it
    surfaces instead of silently wrong results."""
    from spark_rapids_tpu.errors import MeshGatherError
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({"spark.rapids.mesh.enabled": "true",
                    "spark.rapids.mesh.maxShardRetries": "1",
                    "spark.rapids.sql.runtimeFallback.enabled": "false",
                    "spark.rapids.test.faults":
                        "mesh.gather:corrupt:99:14"})
    with pytest.raises(MeshGatherError):
        _agg(s).collect_table()


def test_partial_device_loss_walks_ladder_to_shrink(tmp_path):
    """device_lost x3 at a mesh point: retry (1), single-device
    re-land with the demotion reason surfaced (2), then a mesh SHRINK
    onto the 7 surviving devices (3) — results bit-identical
    throughout, shrink visible in health snapshots, explain() and the
    event log. NOT straight to CPU-only: the device stays trusted,
    only the mesh shrank."""
    from spark_rapids_tpu.parallel.mesh import MESH
    from spark_rapids_tpu.runtime.health import HEALTH
    from spark_rapids_tpu.session import TpuSession
    expected = _agg(TpuSession()).collect_table()
    s = TpuSession({"spark.rapids.mesh.enabled": "true",
                    "spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": str(tmp_path),
                    "spark.rapids.test.faults":
                        "mesh.gather:device_lost:3:15"})
    # run 1: loss -> retry -> loss -> single-device re-land (converges
    # suppressed; the suppressed success does NOT reset the ladder)
    got = _agg(s).collect_table()
    assert _identical(expected, got) is None
    assert HEALTH.mesh_snapshot()["meshDegradations"] >= 1
    assert MESH.health_snapshot()["excludedDeviceIds"] == []
    # run 2: the third loss walks the ladder to the SHRINK rung
    got = _agg(s).collect_table()
    assert _identical(expected, got) is None
    snap = MESH.health_snapshot()
    assert snap["excludedDeviceIds"], snap
    assert snap["shape"] == "7", snap
    assert "mesh degraded" in (snap["degradedReason"] or "")
    assert HEALTH.mesh_snapshot()["meshShrinks"] == 1
    assert HEALTH.state() == "HEALTHY", \
        "a partial loss must not degrade whole-device health"
    # the shrink is visible in the event log (meshShape of the landed
    # run) and in explain()
    assert s.last_event_record["meshShape"] == "7"
    explain = s.explain(_agg(s).plan)
    assert "mesh degraded" in explain and "7-device" in explain
    # ...and keeps serving bit-identically on the smaller mesh
    got = _agg(s).collect_table()
    assert _identical(expected, got) is None
    # quarantine strikes recorded against the template that kept
    # killing mesh execution (below the quarantine threshold here)
    from spark_rapids_tpu.runtime.health import QUARANTINE
    assert QUARANTINE.snapshot()["strikes"] >= 1


def test_ladder_exhaustion_latches_cpu_only():
    """Shrink budget 0 + reinit budget 1: repeated partial losses
    escalate through the whole-backend rungs to the CPU-only latch —
    and the query STILL completes (on the CPU path, with the latch
    reason in explain())."""
    from spark_rapids_tpu.runtime.health import HEALTH
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({"spark.rapids.mesh.enabled": "true",
                    "spark.rapids.mesh.degrade.maxShrinks": "0",
                    "spark.rapids.service.deviceLoss.maxReinits": "1",
                    "spark.rapids.test.faults":
                        "mesh.gather:device_lost:6:16"})
    got1 = _agg(s).collect_table()  # retry -> single-device, converges
    assert HEALTH.state() == "HEALTHY"
    got2 = _agg(s).collect_table()  # third loss: no shrink budget ->
    assert HEALTH.state() == "CPU_ONLY"  # reinit budget 1 -> latch
    # the latched process serves the SAME results through the CPU path
    # (baseline re-collected post-latch, like the chaos harness does:
    # the latch is process-wide, so the fresh session is latched too)
    expected = _agg(TpuSession()).collect_table()
    assert _identical(expected, got2) is None
    assert sorted(got1.to_pydict()["k"]) == sorted(
        expected.to_pydict()["k"])
    explain = s.explain(_agg(s).plan)
    assert "CPU-only mode latched" in explain


def test_digest_cache_rejects_late_publish():
    """The gather-digest kernel cache closes its check-then-build
    window the way PR 9 closed MeshExchange._cache: a builder that
    started BEFORE clear_mesh_caches ran (a device-loss reinit racing
    an in-flight gather) serves its kernel to that caller only and
    never re-seeds the cleared cache (two-thread pin)."""
    from spark_rapids_tpu.parallel import exchange as EX

    EX.clear_mesh_caches()
    built = threading.Event()
    proceed = threading.Event()
    results = []

    def build():
        built.set()
        proceed.wait(timeout=5)
        return "stale-kernel"

    t = threading.Thread(target=lambda: results.append(
        EX.digest_kernel(("pin", "late"), build)))
    t.start()
    assert built.wait(timeout=5)
    # the invalidation lands MID-BUILD (device-loss reinit)
    EX.clear_mesh_caches()
    proceed.set()
    t.join(timeout=5)
    assert results == ["stale-kernel"]  # served to its caller only...
    with EX._DICT_INTERN_LOCK:
        assert ("pin", "late") not in EX._DIGEST_CACHE, \
            "a pre-invalidation builder re-seeded the cleared cache"
    # a fresh builder AFTER the clear publishes normally
    assert EX.digest_kernel(("pin", "late"), lambda: "fresh") == "fresh"
    with EX._DICT_INTERN_LOCK:
        assert EX._DIGEST_CACHE.get(("pin", "late")) == "fresh"
    EX.clear_mesh_caches()


def test_scale_test_flag_validation():
    """Unsupported mode combinations fail fast with the supported
    combinations named — never a silently-ignored flag."""
    import scale_test as ST

    class A:
        mesh = 8
        hosts = 0
        streaming = False
        chaos = False
        concurrency = 0
        service_faults = False
        cpu_baseline = False
        require_tpu = False
        device_budget = 0

    ST.validate_flags(A())  # plain --mesh: fine
    A.chaos = True
    ST.validate_flags(A())  # --mesh --chaos: the composed harness
    for attr, val in (("concurrency", 4), ("service_faults", True),
                      ("cpu_baseline", True)):
        bad = A()
        setattr(bad, attr, val)
        with pytest.raises(SystemExit) as ei:
            ST.validate_flags(bad)
        assert "supported modes" in str(ei.value)
    lone = A()
    lone.mesh = 0
    lone.chaos = False
    lone.service_faults = True
    with pytest.raises(SystemExit) as ei:
        ST.validate_flags(lone)
    assert "--service-faults" in str(ei.value)
    one_dev = A()
    one_dev.mesh = 1
    with pytest.raises(SystemExit):
        ST.validate_flags(one_dev)
