"""Shared expression machinery: unary/binary bases, null propagation,
numeric coercion, and string-dictionary alignment for comparisons."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import (
    DevVal,
    EvalCtx,
    Expression,
    NodePrep,
    PrepCtx,
)


class UnaryExpression(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def child(self) -> Expression:
        return self.children[0]

    def with_children(self, children):
        return type(self)(children[0])


class BinaryExpression(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def with_children(self, children):
        return type(self)(children[0], children[1])


def coerce_numeric_pair(left: Expression, right: Expression) -> Tuple[Expression, Expression, T.DataType]:
    """Insert casts so both sides share the promoted numeric type (Spark
    TypeCoercion tightest-common-type subset)."""
    from spark_rapids_tpu.ops.cast import Cast

    lt, rt = left.data_type, right.data_type
    out = T.promote(lt, rt)
    if lt != out:
        left = Cast(left, out)
    if rt != out:
        right = Cast(right, out)
    return left, right, out


def null_and(*validities):
    """Combined validity: all inputs valid (default null propagation)."""
    out = validities[0]
    for v in validities[1:]:
        out = out & v
    return out


def cpu_null_and(*validities):
    out = validities[0]
    for v in validities[1:]:
        out = out & v
    return out


# ---------------------------------------------------------------------------
# String dictionary alignment
# ---------------------------------------------------------------------------

def align_string_dicts(pctx: PrepCtx, left_prep: NodePrep, right_prep: NodePrep) -> NodePrep:
    """Host-side: merge the two child dictionaries into one sorted-unique
    dictionary and register per-child remap tables as aux inputs. On device,
    remap[codes] yields codes into the merged dictionary, so ordinary integer
    comparisons implement Spark UTF-8 byte-order string comparisons.

    Returns a NodePrep whose aux_slots are (left_remap, right_remap) and
    whose out_dict is the merged dictionary (for operators like If/Coalesce
    that produce strings)."""
    ld = left_prep.out_dict
    rd = right_prep.out_dict
    if ld is None or rd is None:
        raise ValueError("align_string_dicts on non-string children")
    merged = np.unique(np.concatenate([ld.astype(object), rd.astype(object)]))
    lmap = np.searchsorted(merged, ld).astype(np.int32)
    rmap = np.searchsorted(merged, rd).astype(np.int32)
    ls = pctx.add_aux(lmap)
    rs = pctx.add_aux(rmap)
    return NodePrep(out_dict=merged, dict_sorted=True, aux_slots=(ls, rs))


def dev_aligned_codes(ctx: EvalCtx, prep: NodePrep, lval: DevVal, rval: DevVal):
    """Traced-side companion of align_string_dicts: gather through the remap
    tables. Codes are clipped into the padded remap range so garbage codes in
    invalid rows cannot fault the gather."""
    lmap = ctx.aux[prep.aux_slots[0]]
    rmap = ctx.aux[prep.aux_slots[1]]
    lcap = lmap.shape[0] - 1
    rcap = rmap.shape[0] - 1
    lc = lmap[jnp.clip(lval.data, 0, lcap)]
    rc = rmap[jnp.clip(rval.data, 0, rcap)]
    return lc, rc


def align_string_dicts_many(pctx: PrepCtx, preps: Sequence[NodePrep]) -> NodePrep:
    """N-ary version of align_string_dicts: one merged dictionary, one remap
    aux slot per child (in order)."""
    dicts = [p.out_dict for p in preps]
    if any(d is None for d in dicts):
        raise ValueError("align_string_dicts_many on non-string child")
    merged = np.unique(np.concatenate([d.astype(object) for d in dicts]))
    slots = tuple(pctx.add_aux(np.searchsorted(merged, d).astype(np.int32)) for d in dicts)
    return NodePrep(out_dict=merged, dict_sorted=True, aux_slots=slots)


def dev_remap_codes(ctx: EvalCtx, slot: int, codes):
    remap = ctx.aux[slot]
    return remap[jnp.clip(codes, 0, remap.shape[0] - 1)]


def is_string_pair(left: Expression, right: Expression) -> bool:
    return isinstance(left.data_type, T.StringType) and isinstance(right.data_type, T.StringType)
