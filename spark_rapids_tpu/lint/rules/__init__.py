"""The shared rule registry driving ``lint_repo()``.

Each rule family lives in its own module under ``lint/rules/``; this
package assembles them into one ordered registry so the driver
(``repo_lint.lint_repo``) is pure orchestration: parse each source
file once, hand the tree to every per-file check, then run the
cross-file finalizers (registry audits that need the whole repo seen
— fault points, the concurrency lock graph).

A registry entry is ``(rule_ids, file_check, finalizer)``:

* ``file_check(ctx, rel, tree, diags)`` — called once per source file
  with the shared :class:`LintContext`;
* ``finalizer(ctx, diags)`` — called once after every file was
  walked.

Rule IDs, the diagnostics format and the per-checker signatures are
pinned by tests/test_lint.py — the split moved code, not behavior.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.lint.diagnostics import Diagnostic
from spark_rapids_tpu.lint.rules import (conf_keys, determinism,
                                         device_residency, fault_points,
                                         io_write, obs_passive,
                                         streaming_epoch, thread_shared)


@dataclass
class LintContext:
    """Per-run shared state: what the cross-file halves need."""

    #: declared conf keys (RL-CONF-KEY)
    declared: Set[str] = field(default_factory=set)
    #: fault_point name -> ["rel:line", ...] (RL-FAULT-POINT)
    fault_calls: Dict[str, List[str]] = field(default_factory=dict)
    #: every parsed tree, rel -> ast (the concurrency pass's whole-repo
    #: call graph needs all of them)
    trees: Dict[str, ast.AST] = field(default_factory=dict)


@dataclass(frozen=True)
class LintRule:
    rule_ids: Tuple[str, ...]
    file_check: Optional[Callable[..., None]] = None
    finalizer: Optional[Callable[..., None]] = None


def _concurrency_finalizer(ctx: LintContext, diags: List[Diagnostic]):
    from spark_rapids_tpu.lint.concurrency import check_concurrency
    check_concurrency(ctx.trees, diags)


#: ordered registry — per-file checks run in this order for each file
#: (matching the pre-split lint_repo order), then finalizers run in
#: this order
REGISTRY: Tuple[LintRule, ...] = (
    LintRule(("RL-HOST-SYNC",),
             lambda ctx, rel, tree, diags:
             device_residency._check_host_sync(rel, tree, diags)),
    LintRule(("RL-JNP-SCOPE",),
             lambda ctx, rel, tree, diags:
             device_residency._check_jnp_scope(rel, tree, diags)),
    LintRule(("RL-CONF-KEY",),
             lambda ctx, rel, tree, diags:
             conf_keys._check_conf_keys(rel, tree, ctx.declared, diags)),
    LintRule(("RL-NONDETERMINISM",),
             lambda ctx, rel, tree, diags:
             determinism._check_nondeterminism(rel, tree, diags)),
    LintRule(("RL-DEAD-LAMBDA",),
             lambda ctx, rel, tree, diags:
             determinism._check_dead_lambdas(rel, tree, diags)),
    LintRule(("RL-THREAD-SHARED",),
             lambda ctx, rel, tree, diags:
             thread_shared._check_thread_shared(rel, tree, diags)),
    LintRule(("RL-WRITE-COMMIT",),
             lambda ctx, rel, tree, diags:
             io_write._check_write_commit(rel, tree, diags)),
    LintRule(("RL-MESH-HOST",),
             lambda ctx, rel, tree, diags:
             device_residency._check_mesh_host(rel, tree, diags)),
    LintRule(("RL-KERNEL-HOST",),
             lambda ctx, rel, tree, diags:
             device_residency._check_kernel_host(rel, tree, diags)),
    LintRule(("RL-OBS-PASSIVE",),
             lambda ctx, rel, tree, diags:
             obs_passive._check_obs_passive(rel, tree, diags)),
    LintRule(("RL-MEM-ACCOUNT",),
             lambda ctx, rel, tree, diags:
             device_residency._check_mem_account(rel, tree, diags)),
    LintRule(("RL-MV-EPOCH",),
             lambda ctx, rel, tree, diags:
             streaming_epoch._check_mv_epoch(rel, tree, diags)),
    LintRule(("RL-FAULT-POINT",),
             lambda ctx, rel, tree, diags:
             fault_points._check_fault_sites(rel, tree, ctx.fault_calls,
                                             diags),
             lambda ctx, diags:
             fault_points._check_fault_registry(ctx.fault_calls, diags)),
    LintRule(("RL-LOCK-DECL", "RL-LOCK-ORDER", "RL-LOCK-EFFECT"),
             None, _concurrency_finalizer),
)
