"""Dynamic-partitioning columnar writer.

Reference: GpuFileFormatDataWriter.scala — the dynamic partition writer splits
each batch by the partition-key tuple and routes rows to per-partition files
under Hive-style key=value/ directories; single-partition writes emit
part-00000 files. SURVEY.md §2.3 (DataWritingCommandExec row).

Every file is written to a :mod:`~spark_rapids_tpu.io.committer`
staging path, never to its final destination — a crash mid-write can
only leave debris under ``_temporary/`` (which scans prune), never a
torn ``part-*`` file. With an external ``committer`` (WriteFiles owns
the job lifecycle) this function only STAGES; standalone calls run the
whole task-commit/job-commit protocol themselves and return final
paths."""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError


def _escape_partition_value(v) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    s = str(v)
    out = []
    for ch in s:
        if ch in '\\/:*?"<>|\x7f' or ord(ch) < 32 or ch in "%=":
            out.append("%{:02X}".format(ord(ch)))
        else:
            out.append(ch)
    return "".join(out)


def write_partitioned(table: HostTable, path: str,
                      write_one: Callable[[HostTable, str], None],
                      extension: str,
                      partition_by: Optional[Sequence[str]] = None,
                      committer=None,
                      ) -> List[str]:
    """Route rows to files through the transactional committer; returns
    the list of files written (final paths when this call owns the job,
    staged paths when the caller passed its own ``committer`` and will
    commit the task/job itself)."""
    from spark_rapids_tpu.io.committer import WriteJob
    from spark_rapids_tpu.runtime.faults import fault_point
    os.makedirs(path, exist_ok=True)
    job = committer if committer is not None else WriteJob(path)
    own_job = committer is None

    def _finish(staged: List[str]) -> List[str]:
        if not own_job:
            return staged
        final = job.commit_task()
        job.commit_job(num_rows=table.num_rows)
        return final

    try:
        if not partition_by:
            rel = f"part-00000.{extension}"
            fault_point("io.write.file")
            staged_path = job.stage_path(rel)
            write_one(table, staged_path)
            return _finish([staged_path])

        for k in partition_by:
            if k not in table.names:
                raise ColumnarProcessingError(
                    f"partition column {k!r} not in table")
        data_names = [n for n in table.names if n not in partition_by]
        key_cols = [table.column(k) for k in partition_by]
        n = table.num_rows

        # group rows by partition tuple (host-side; the device path
        # partitions on device then routes per-partition slices here)
        keys = []
        for i in range(n):
            keys.append(tuple(
                None if not c.validity[i] else
                (c.data[i].item() if isinstance(c.data[i], np.generic)
                 else c.data[i])
                for c in key_cols))
        order = {}
        for i, k in enumerate(keys):
            order.setdefault(k, []).append(i)

        staged: List[str] = []
        file_idx = 0
        for key_tuple, rows in order.items():
            idx = np.asarray(rows, dtype=np.int64)
            sub_cols = []
            for name in data_names:
                c = table.column(name)
                sub_cols.append(HostColumn(c.dtype, c.data[idx],
                                           c.validity[idx]))
            sub = HostTable(data_names, sub_cols)
            rel = os.path.join(*[
                f"{k}={_escape_partition_value(v)}"
                for k, v in zip(partition_by, key_tuple)],
                f"part-{file_idx:05d}.{extension}")
            # the fault point fires on EVERY file, partitioned writes
            # included — they were invisible to the chaos harness when
            # only the single-file branch carried it
            fault_point("io.write.file")
            staged_path = job.stage_path(rel)
            write_one(sub, staged_path)
            staged.append(staged_path)
            file_idx += 1
        return _finish(staged)
    except BaseException:
        if own_job:
            job.abort()
        raise
