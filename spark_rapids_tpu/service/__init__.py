"""Multi-tenant concurrent query service.

Reference: the serving layer the plugin assumes Spark provides —
concurrent tasks sharing one device through ``GpuSemaphore``
(``spark.rapids.sql.concurrentGpuTasks``), scheduler pools, and the
driver's kill/timeout plumbing. This engine owns its sessions, so it
owns the serving layer too:

* :mod:`spark_rapids_tpu.service.scheduler` — ``QueryService``: a
  worker pool in front of one ``TpuSession``, with named scheduling
  pools, per-tenant weighted fair queueing, bounded queue depth with
  typed rejection (``QueryRejectedError`` + retry-after), per-query
  deadlines, and memory-pressure-aware admission consulting the spill
  catalog. Knobs under ``spark.rapids.service.*``.
* :mod:`spark_rapids_tpu.service.query` — ``QueryHandle``: the
  QUEUED -> ADMITTED -> RUNNING -> {FINISHED, FAILED, CANCELLED,
  TIMED_OUT} state machine, plus the cooperative-cancellation exec
  boundary (third per-query wrapper in the
  ``install_fault_boundaries`` / ``install_observation`` family).
* :mod:`spark_rapids_tpu.service.result_cache` — plan-fingerprint LRU
  result cache over ``HostTable`` results, invalidated on catalog
  mutation and table writes.
* :mod:`spark_rapids_tpu.service.watchdog` — ``WorkerWatchdog``: hard
  wall limits on RUNNING queries (a worker wedged inside one dispatch
  never reaches the cooperative deadline's batch boundary), abandoned
  workers replaced so pool capacity holds, dead-worker liveness
  backstop. Pairs with :mod:`spark_rapids_tpu.runtime.health` (device
  loss recovery, CPU-only latch, poison-query quarantine).
"""

from spark_rapids_tpu.service.query import (  # noqa: F401
    QueryHandle,
    QueryState,
    install_cancellation,
)
from spark_rapids_tpu.service.result_cache import ResultCache  # noqa: F401
from spark_rapids_tpu.service.scheduler import QueryService  # noqa: F401
from spark_rapids_tpu.service.watchdog import WorkerWatchdog  # noqa: F401
