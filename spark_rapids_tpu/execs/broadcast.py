"""Broadcast exchange + nested-loop join.

Reference: GpuBroadcastExchangeExec.scala (build-side materialization shared
by consumers), GpuBroadcastHashJoinExecBase, GpuBroadcastNestedLoopJoinExec
(conditioned joins without equi keys) — SURVEY.md §2.3.

TPU mapping: a broadcast in the single-controller JAX world is a table that
is materialized once, kept spillable, and (in the sharded plan) replicated
to every device of the mesh rather than partitioned. The nested-loop join
evaluates the join condition over probe-tile x build cross products with a
STATIC pair budget — each tile is one jitted kernel evaluating the bound
condition on gathered pair columns, so memory is bounded regardless of
input sizes."""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
from spark_rapids_tpu.dispatch import tpu_jit
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceColumn, DeviceTable, bucket_for
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.ops.expr import (
    DevVal,
    EvalCtx,
    Expression,
    NodePrep,
    PrepCtx,
    _prep_trace_key,
    _walk_eval,
    _walk_prep,
    shared_traces,
)

#: max probe_tile * build_rows pairs materialized per nested-loop tile
PAIR_BUDGET = 1 << 20


def _materialize_single(child: TpuExec, schema):
    """Materialize a child into ONE device table with spill protection:
    every buffered batch registers as a SpillableBatch so the OOM-retry
    catalog can demote it during the concat (the coalesce path's
    invariant — TpuJoinExec requires a spillable-protected build).
    Returns (table, n_input_batches)."""
    from spark_rapids_tpu.columnar.table import concat_device
    from spark_rapids_tpu.plan.nodes import _empty_table
    from spark_rapids_tpu.runtime.retry import retry_block
    from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch

    catalog = BufferCatalog.get()
    spills = []
    try:
        for b in child.execute():
            spills.append(SpillableBatch(b, catalog))
        if not spills:
            return DeviceTable.from_host(_empty_table(schema)), 0
        if len(spills) == 1:
            return spills[0].get(), 1
        table = retry_block(
            lambda: concat_device([sb.get() for sb in spills]))
        return table, len(spills)
    finally:
        for sb in spills:
            sb.release()


class TpuBroadcastExchangeExec(TpuExec):
    """Materializes the child ONCE into a single spillable table, reused
    across re-executions (multiple consumers / replays). The multi-chip
    plan replicates this table across the mesh instead of partitioning it
    (reference: GpuBroadcastExchangeExec builds the batch on the driver and
    ships it to every executor)."""

    def __init__(self, child: TpuExec):
        super().__init__()
        self.children = (child,)
        self._cached = None

    def execute(self):
        from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch

        if self._cached is None:
            table, n = _materialize_single(self.children[0],
                                           self.output_schema())
            self._cached = SpillableBatch(table, BufferCatalog.get())
            self.add_metric("broadcastBatches", n)
            self.add_metric("broadcastBytes", table.device_nbytes())
        yield self._cached.get()

    def output_schema(self):
        return self.children[0].output_schema()

    def describe(self):
        return "TpuBroadcastExchange"


class TpuNestedLoopJoinExec(TpuExec):
    """Conditioned nested-loop join (no equi keys): inner, left, right,
    full, leftsemi, leftanti, cross — the condition is evaluated on device
    over tiled cross products. The probe side streams; the build side is a
    broadcast table. Full outer tracks build-row matches across all tiles
    and batches and emits unmatched build rows last."""

    def __init__(self, left: TpuExec, right: TpuExec, join_type: str,
                 condition: Optional[Expression],
                 left_schema, right_schema):
        super().__init__()
        self.children = (left, right)
        self.join_type = join_type.lower().replace("_", "")
        self.condition = condition
        self._left_schema = list(left_schema)
        self._right_schema = list(right_schema)
        self.left_names = [n for n, _ in left_schema]
        self.right_names = [n for n, _ in right_schema]

    def output_schema(self):
        if self.join_type in ("leftsemi", "leftanti"):
            return list(self._left_schema)
        return list(self._left_schema) + list(self._right_schema)

    def describe(self):
        c = "cond" if self.condition is not None else "nocond"
        return f"TpuNestedLoopJoin[{self.join_type}, {c}]"

    # ------------------------------------------------------------------
    def execute(self):
        from spark_rapids_tpu.runtime.retry import retry_block

        jt = self.join_type
        swapped = jt in ("right", "rightouter")
        build_child = self.children[0] if swapped else self.children[1]
        probe_child = self.children[1] if swapped else self.children[0]

        build_batches = list(build_child.execute())
        if len(build_batches) != 1:
            from spark_rapids_tpu.columnar.table import concat_device
            build = retry_block(lambda: concat_device(build_batches))
        else:
            build = build_batches[0]

        full_outer = jt in ("full", "fullouter", "outer")
        b_matched = None

        for pb in probe_child.execute():
            tile = self._tile_rows(pb.capacity, build.capacity)
            for start in range(0, pb.capacity, tile):
                pt = self._slice(pb, start, tile)
                outs, bm = retry_block(
                    lambda p=pt: self._join_tile(p, build, swapped))
                if full_outer and bm is not None:
                    b_matched = bm if b_matched is None else (b_matched | bm)
                for out in outs:
                    yield out
            self.add_metric("probeBatches", 1)

        if full_outer:
            if b_matched is None:
                b_matched = jnp.zeros(build.capacity, jnp.bool_)
            yield self._unmatched_build(build, b_matched, swapped)

    @staticmethod
    def _tile_rows(cap_p: int, cap_b: int) -> int:
        # round DOWN to a power of two so tile * cap_b never exceeds the
        # pair budget (huge build sides get 1-row tiles — an O(n*m) nested
        # loop over a big build is slow however it is tiled, but it must
        # not OOM)
        t = max(PAIR_BUDGET // max(cap_b, 1), 1)
        b = 1 << (t.bit_length() - 1)
        return min(b, cap_p)

    @staticmethod
    def _slice(table: DeviceTable, start: int, tile: int) -> DeviceTable:
        cols = [c.with_arrays(
            jax.lax.dynamic_slice_in_dim(c.data, start, tile),
            jax.lax.dynamic_slice_in_dim(c.validity, start, tile))
            for c in table.columns]
        nrows = jnp.clip(table.nrows_dev - jnp.int32(start), 0, tile)
        return DeviceTable(table.names, cols, nrows, tile)

    # ------------------------------------------------------------------
    def _join_tile(self, pt: DeviceTable, bt: DeviceTable, swapped: bool):
        """Join one probe tile against the whole build table. Returns
        (list of output DeviceTables, build-match bool array or None)."""
        jt = self.join_type
        cap_p, cap_b = pt.capacity, bt.capacity

        # left/right logical tables in plan order for condition + output
        lt, rt = (bt, pt) if swapped else (pt, bt)

        # condition preps walk over a PAIR context; aux arrays ride as usual
        preps: List[NodePrep] = []
        pair_pctx = _PairPrepCtx(lt, rt)
        if self.condition is not None:
            _walk_prep(self.condition, pair_pctx, preps)

        tkey = ("nlj", jt, swapped, cap_p, cap_b,
                self.condition.key() if self.condition is not None else None,
                tuple((str(c.dtype), c.dictionary is not None)
                      for c in lt.columns),
                tuple((str(c.dtype), c.dictionary is not None)
                      for c in rt.columns),
                _prep_trace_key(preps))
        traces = shared_traces(("nlj-traces",))
        fn = traces.get(tkey)
        if fn is None:
            fn = tpu_jit(self._build_tile_kernel(
                jt, swapped, cap_p, cap_b, preps))
            traces[tkey] = fn

        lcols = tuple((c.data, c.validity) for c in lt.columns)
        rcols = tuple((c.data, c.validity) for c in rt.columns)
        from spark_rapids_tpu.dispatch import prep_aux
        aux = prep_aux(pair_pctx)
        res = fn(lcols, rcols, aux, pt.nrows_dev, bt.nrows_dev)

        outs = []
        if jt in ("leftsemi", "leftanti"):
            cols_arrays, nout = res[0]
            cols = [c.with_arrays(d, v)
                    for c, (d, v) in zip(pt.columns, cols_arrays)]
            outs.append(DeviceTable(pt.names, cols, nout, cap_p))
            return outs, None

        (pair_arrays, n_pairs), (un_arrays, n_un), b_match = res
        names = self.left_names + self.right_names
        all_cols = list(lt.columns) + list(rt.columns)
        pair_cols = [DeviceColumn(c.dtype, d, v, dictionary=c.dictionary,
                                  dict_sorted=c.dict_sorted, domain=c.domain)
                     for c, (d, v) in zip(all_cols, pair_arrays)]
        outs.append(DeviceTable(names, pair_cols, n_pairs,
                                pair_cols[0].capacity))
        if un_arrays is not None:
            un_cols = [DeviceColumn(c.dtype, d, v, dictionary=c.dictionary,
                                    dict_sorted=c.dict_sorted, domain=c.domain)
                       for c, (d, v) in zip(all_cols, un_arrays)]
            outs.append(DeviceTable(names, un_cols, n_un, cap_p))
        return outs, (b_match if jt in ("full", "fullouter", "outer") else None)

    def _build_tile_kernel(self, jt: str, swapped: bool, cap_p: int,
                           cap_b: int, preps):
        condition = self.condition
        npairs = cap_p * cap_b
        out_cap = bucket_for(npairs)

        def kernel(lcols, rcols, aux, n_p, n_b):
            j = jnp.arange(out_cap, dtype=jnp.int32)
            p_idx = jnp.clip(j // cap_b, 0, cap_p - 1)
            b_idx = jnp.clip(j % cap_b, 0, cap_b - 1)
            in_range = j < npairs
            live_pair = in_range & (p_idx < n_p) & (b_idx < n_b)

            l_idx = b_idx if swapped else p_idx
            r_idx = p_idx if swapped else b_idx
            pair_cols = tuple(
                DevVal(d[l_idx], v[l_idx]) for d, v in lcols) + tuple(
                DevVal(d[r_idx], v[r_idx]) for d, v in rcols)

            if condition is not None:
                ctx = EvalCtx(pair_cols, aux, jnp.int32(npairs), out_cap)
                ctx._prep_iter = iter(preps)
                pred = _walk_eval(condition, ctx)
                match = live_pair & pred.data & pred.validity
            else:
                match = live_pair

            # per-probe-row any-match (for outer/semi/anti)
            mk = jnp.zeros(cap_p, jnp.bool_).at[
                jnp.where(match, p_idx, cap_p)].set(True, mode="drop")
            row_any = mk

            if jt in ("leftsemi", "leftanti"):
                keep = (row_any if jt == "leftsemi" else ~row_any)
                keep = keep & (jnp.arange(cap_p, dtype=jnp.int32) < n_p)
                pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
                tgt = jnp.where(keep, pos, cap_p)
                nout = jnp.sum(keep.astype(jnp.int32))
                # probe table IS the left side for semi/anti (never swapped)
                from spark_rapids_tpu.ops.scatter32 import scatter_pair
                outs = []
                for d, v in (lcols if not swapped else rcols):
                    outs.append(scatter_pair(cap_p, tgt, d, v))
                return ((tuple(outs), nout),)

            # matched pairs -> compact to the front
            pos = jnp.cumsum(match.astype(jnp.int32)) - 1
            tgt = jnp.where(match, pos, out_cap)
            n_pairs = jnp.sum(match.astype(jnp.int32))
            from spark_rapids_tpu.ops.scatter32 import scatter_pair
            pair_out = []
            for pv in pair_cols:
                pair_out.append(
                    scatter_pair(out_cap, tgt, pv.data, pv.validity))

            b_match = jnp.zeros(cap_b, jnp.bool_).at[
                jnp.where(match, b_idx, cap_b)].set(True, mode="drop")

            if jt == "inner" or jt == "cross":
                return ((tuple(pair_out), n_pairs),
                        (None, jnp.int32(0)), b_match)

            # outer: unmatched live probe rows emit one null-build row each
            p_live = jnp.arange(cap_p, dtype=jnp.int32) < n_p
            un = p_live & ~row_any
            upos = jnp.cumsum(un.astype(jnp.int32)) - 1
            utgt = jnp.where(un, upos, cap_p)
            n_un = jnp.sum(un.astype(jnp.int32))
            probe_cols = rcols if swapped else lcols
            probe_out = []
            for d, v in probe_cols:
                probe_out.append(scatter_pair(cap_p, utgt, d, v))
            null_build = []
            for d, v in (lcols if swapped else rcols):
                zd = jnp.zeros((cap_p,) + d.shape[1:], dtype=d.dtype)
                null_build.append((zd, jnp.zeros(cap_p, jnp.bool_)))
            if swapped:
                un_out = tuple(null_build) + tuple(probe_out)
            else:
                un_out = tuple(probe_out) + tuple(null_build)
            return ((tuple(pair_out), n_pairs), (un_out, n_un), b_match)

        return kernel

    def _unmatched_build(self, bt: DeviceTable, b_matched, swapped: bool):
        """Full-outer tail: build rows never matched, null probe side."""
        live = bt.row_mask()
        keep = live & ~b_matched
        pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        tgt = jnp.where(keep, pos, bt.capacity)
        nout = jnp.sum(keep.astype(jnp.int32))
        from spark_rapids_tpu.ops.scatter32 import scatter_pair
        build_cols = []
        for c in bt.columns:
            od, ov = scatter_pair(bt.capacity, tgt, c.data, c.validity)
            build_cols.append(c.with_arrays(od, ov))
        probe_schema = self._right_schema if swapped else self._left_schema
        null_cols = []
        for _, dt in probe_schema:
            if isinstance(dt, T.StringType):
                data = jnp.zeros(bt.capacity, dtype=jnp.int32)
                null_cols.append(DeviceColumn(
                    dt, data, jnp.zeros(bt.capacity, jnp.bool_),
                    dictionary=np.array([], dtype=object)))
            else:
                from spark_rapids_tpu.columnar.column import null_data_array
                null_cols.append(DeviceColumn(
                    dt, null_data_array(dt, bt.capacity),
                    jnp.zeros(bt.capacity, jnp.bool_)))
        names = self.left_names + self.right_names
        cols = (build_cols + null_cols) if swapped else (null_cols + build_cols)
        return DeviceTable(names, cols, nout, bt.capacity)


class _PairPrepCtx(PrepCtx):
    """PrepCtx whose table view is the concatenated (left, right) pair
    schema — BoundReference.prep reads dictionaries by ordinal."""

    def __init__(self, lt: DeviceTable, rt: DeviceTable):
        self.table = _PairTableView(lt, rt)
        self.aux_arrays = []
        self.aux_intern = []


class _PairTableView:
    def __init__(self, lt: DeviceTable, rt: DeviceTable):
        self.columns = list(lt.columns) + list(rt.columns)


class TpuAdaptiveBuildExec(TpuExec):
    """AQE runtime join-strategy conversion (reference: AQE's
    DynamicJoinSelection + GpuOverrides AQE integration,
    GpuOverrides.scala:4577-4638): when the STATIC size estimate could
    not prove the build side small, the decision is deferred to RUNTIME —
    the build materializes, its ACTUAL bytes are measured, and a build
    under the broadcast threshold is cached as a broadcast table (reused
    across replays/consumers exactly like TpuBroadcastExchangeExec);
    otherwise it flows on as the ordinary single-batch build feeding the
    sub-partitioned join path."""

    def __init__(self, child: TpuExec, threshold_bytes: int):
        super().__init__()
        self.children = (child,)
        self.threshold_bytes = threshold_bytes
        self._cached = None
        self.converted: Optional[bool] = None

    def output_schema(self):
        return self.children[0].output_schema()

    def execute(self):
        from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch

        if self._cached is not None:
            yield self._cached.get()
            return
        table, _n = _materialize_single(self.children[0],
                                        self.output_schema())
        measured = table.device_nbytes()
        if self.converted is None:  # record the decision metrics ONCE
            self.add_metric("aqeMeasuredBuildBytes", measured)
            if measured <= self.threshold_bytes:
                self.add_metric("aqeBroadcastConverted", 1)
        if measured <= self.threshold_bytes:
            # runtime conversion to broadcast: cache for reuse
            self.converted = True
            self._cached = SpillableBatch(table, BufferCatalog.get())
            yield self._cached.get()
        else:
            self.converted = False
            yield table

    def describe(self):
        state = {None: "undecided", True: "->broadcast",
                 False: "->shuffle"}[self.converted]
        return f"TpuAdaptiveBuild[{state}]"
