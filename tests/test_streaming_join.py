"""Probe-side streaming joins: the probe side flows through in several
batches while the build side is one coalesced table (reference analog:
GpuShuffledHashJoinExec streamed-side iterator). A 1-byte batch target
forces real streaming."""

import pytest

from spark_rapids_tpu.ops.expr import col, lit

from tests.asserts import assert_tpu_and_cpu_are_equal
from tests.data_gen import DoubleGen, IntGen, StringGen, gen_table


@pytest.fixture(scope="module")
def stream_session():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.sql.batchSizeBytes": 1})


def _dfs(sess, n_left=600, n_right=200, nb=4, seed=31):
    from spark_rapids_tpu.plan import from_host_table
    lg = {"k": IntGen(min_val=0, max_val=50), "s": StringGen(cardinality=8),
          "lv": DoubleGen(corner_prob=0.0)}
    rg = {"k": IntGen(min_val=0, max_val=50), "rv": IntGen()}
    left = from_host_table(gen_table(lg, n_left, seed), sess, nb)
    right = from_host_table(gen_table(rg, n_right, seed + 1), sess, 2)
    return left, right


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "leftsemi", "leftanti"])
def test_streaming_join_types(stream_session, cpu_session, how):
    def build(s):
        left, right = _dfs(s)
        return left.join(right, on="k", how=how)
    assert_tpu_and_cpu_are_equal(build, stream_session, cpu_session)


def test_streaming_inner_with_condition(stream_session, cpu_session):
    def build(s):
        left, right = _dfs(s)
        return left.join(right, on="k", how="inner").filter(
            col("rv") > col("lv"))
    assert_tpu_and_cpu_are_equal(build, stream_session, cpu_session)


def test_streaming_cross_join(stream_session, cpu_session):
    def build(s):
        from spark_rapids_tpu.plan import from_host_table
        left = from_host_table(
            gen_table({"a": IntGen(min_val=0, max_val=9)}, 40, 7), s, 4)
        right = from_host_table(
            gen_table({"b": IntGen(min_val=0, max_val=9)}, 15, 8), s, 1)
        return left.join(right)
    assert_tpu_and_cpu_are_equal(build, stream_session, cpu_session)


def test_streaming_full_outer_no_probe_matches(stream_session, cpu_session):
    """Disjoint key ranges: every build row lands in the unmatched tail."""
    def build(s):
        from spark_rapids_tpu.plan import from_host_table
        left = from_host_table(
            gen_table({"k": IntGen(min_val=0, max_val=10)}, 60, 3), s, 3)
        right = from_host_table(
            gen_table({"k": IntGen(min_val=100, max_val=110),
                       "rv": IntGen()}, 30, 4), s, 1)
        return left.join(right, on="k", how="full")
    assert_tpu_and_cpu_are_equal(build, stream_session, cpu_session)


def test_streaming_join_with_injected_oom(cpu_session):
    from spark_rapids_tpu.session import TpuSession
    inj = TpuSession({"spark.rapids.sql.batchSizeBytes": 1,
                      "spark.rapids.sql.test.injectRetryOOM": "retry:2"})

    def build(s):
        left, right = _dfs(s)
        return left.join(right, on="k", how="left")
    assert_tpu_and_cpu_are_equal(build, inj, cpu_session)


@pytest.fixture(scope="module")
def subpart_session():
    """Tiny sub-partition target forces the bucketed join escalation."""
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.sql.batchSizeBytes": 1,
                       "spark.rapids.sql.join.subPartition.targetBytes": 512})


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "leftsemi", "leftanti"])
def test_subpartitioned_join_types(subpart_session, cpu_session, how):
    def build(s):
        left, right = _dfs(s, n_left=500, n_right=300, nb=3)
        return left.join(right, on="k", how=how)
    assert_tpu_and_cpu_are_equal(build, subpart_session, cpu_session)


def test_subpartitioned_join_string_key(subpart_session, cpu_session):
    def build(s):
        from spark_rapids_tpu.plan import from_host_table
        lg = {"s": StringGen(cardinality=20), "lv": IntGen()}
        rg = {"s": StringGen(cardinality=20), "rv": IntGen()}
        left = from_host_table(gen_table(lg, 400, 41), s, 3)
        right = from_host_table(gen_table(rg, 250, 42), s, 2)
        return left.join(right, on="s", how="full")
    assert_tpu_and_cpu_are_equal(build, subpart_session, cpu_session)


def test_subpartitioned_join_actually_partitions(subpart_session):
    """The escalation must really engage (metric check)."""
    from spark_rapids_tpu.overrides import apply_overrides
    from spark_rapids_tpu.execs.join import TpuJoinExec
    left, right = _dfs(subpart_session, n_left=500, n_right=300, nb=3)
    df = left.join(right, on="k", how="inner")
    executable, _ = apply_overrides(df.plan, subpart_session.conf)

    joins = []

    def walk(e):
        if isinstance(e, TpuJoinExec):
            joins.append(e)
        for c in getattr(e, "children", ()):
            walk(c)
        for attr in ("source", "tpu_exec", "cpu_node"):
            nxt = getattr(e, attr, None)
            if nxt is not None:
                walk(nxt)

    walk(executable)
    assert len(joins) == 1
    list(executable.execute_cpu())
    assert joins[0].metrics.get("subPartitions", 0) > 1
