"""Hive text (LazySimpleSerDe delimited) scan + writer.

Reference: org.apache.spark.sql.hive.rapids (GpuHiveTextFileFormat /
GpuHiveTableScanExec) — Hive's default text layout: \\x01 field delimiter,
no header, '\\N' as the null marker, no quoting/escaping of delimiters.
Rides the CSV machinery with Hive defaults pinned (the reference routes it
through the same text-reader base)."""

from __future__ import annotations

from typing import List, Optional, Sequence

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import RapidsConf, str_conf
from spark_rapids_tpu.io.csv import CsvScanNode
from spark_rapids_tpu.io.writer import write_partitioned
from spark_rapids_tpu.plan.nodes import Schema

HIVE_TEXT_READER_TYPE = str_conf(
    "spark.rapids.sql.format.hiveText.reader.type", "AUTO",
    "PERFILE, COALESCING, MULTITHREADED or AUTO.")

HIVE_DELIM = "\x01"
HIVE_NULL = "\\N"


class HiveTextScanNode(CsvScanNode):
    format_name = "hiveText"

    def __init__(self, paths, conf: RapidsConf, schema: Schema,
                 columns=None, reader_type=None,
                 delimiter: str = HIVE_DELIM, null_value: str = HIVE_NULL,
                 **options):
        if schema is None:
            raise ValueError("Hive text tables require an explicit schema "
                             "(the format carries no header)")
        super().__init__(paths, conf, columns=columns,
                         reader_type=reader_type, schema=schema,
                         header=False, sep=delimiter, null_value=null_value,
                         quote="", escape=None, **options)

    def _conf_reader_type(self) -> str:
        return self.conf.get_entry(HIVE_TEXT_READER_TYPE)


def write_hive_text(table: HostTable, path: str,
                    partition_by: Optional[Sequence[str]] = None,
                    delimiter: str = HIVE_DELIM,
                    null_value: str = HIVE_NULL) -> List[str]:
    def _write_one(tbl: HostTable, file_path: str):
        cols = [c.to_pylist() for c in tbl.columns]
        with open(file_path, "w") as f:
            for i in range(tbl.num_rows):
                f.write(delimiter.join(
                    null_value if cols[j][i] is None else str(cols[j][i])
                    for j in range(len(cols))) + "\n")

    return write_partitioned(table, path, _write_one, "txt", partition_by)
