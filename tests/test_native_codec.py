"""Native string dictionary codec (native/strcodec.cpp via ctypes) and its
pure-Python fallback (reference analog: cuDF strings columns — the hot
host-side string path is native)."""

import numpy as np
import pytest

from spark_rapids_tpu import native


def _check(vals):
    codes, d = native.encode_sorted_dict(np.asarray(vals, dtype=object))
    d2, c2 = np.unique(np.asarray(vals, dtype=object), return_inverse=True)
    assert list(d) == list(d2)
    assert (codes == c2.astype(np.int32)).all()


def test_matches_numpy_unique_basic():
    _check(["b", "a", "c", "a", "", "b"])


def test_unicode_and_empty():
    _check(["", "é", "中文", "a", "", "", "zzé", "中"])


def test_high_cardinality_native_sort():
    rng = np.random.default_rng(1)
    vals = [f"k{rng.integers(0, 10**9):09d}_{i}" for i in range(6000)]
    _check(vals)  # above _NATIVE_SORT_MIN_KEYS -> native index sort


def test_fallback_without_library(monkeypatch):
    monkeypatch.setattr(native, "_libs", {"strcodec": None})
    _check(["x", "a", "x", "b"] * 50)
    rng = np.random.default_rng(2)
    vals = [f"v{rng.integers(0, 10**6)}" for i in range(5000)]
    _check(vals)  # high-card path falls back to numpy argsort


def test_engine_string_upload_uses_codec(session):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.plan import from_host_table
    from tests.data_gen import StringGen, gen_table
    t = gen_table({"s": StringGen(cardinality=50)}, 500, 9)
    out = from_host_table(t, session).group_by("s").agg(
        F.count().alias("c")).collect()
    assert sum(r[1] for r in out) == 500
