"""Host and device tables (batches of columns).

Reference surface: ai.rapids.cudf Table + Spark ColumnarBatch. A DeviceTable
is the unit that flows between TPU execs; a HostTable is the CPU-fallback /
transition representation (GpuRowToColumnarExec / GpuColumnarToRowExec analog
lives in overrides/transitions.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
from spark_rapids_tpu.dispatch import count_pad_waste, tpu_jit
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import (
    DeviceColumn,
    HostColumn,
    bucket_for,
    stage_upload,
)
from spark_rapids_tpu.errors import ColumnarProcessingError

#: jitted per-(recipe, capacity) H2D assemble kernels (see stage_upload):
#: one device program rebuilds every column's logical dtype + validity from
#: the fast-transferring staged arrays in a single dispatch.
_ASSEMBLE_CACHE: Dict[tuple, object] = {}


def _get_assemble(recipes: tuple, cap: int):
    key = (recipes, cap)
    fn = _ASSEMBLE_CACHE.get(key)
    if fn is None:
        def assemble(arrays, nrows):
            row_mask = jnp.arange(cap, dtype=jnp.int32) < nrows
            outs = []
            i = 0
            for kind, vkind, _ in recipes:
                if kind == "f64split":
                    h64 = arrays[i].astype(jnp.float64)
                    l64 = arrays[i + 1].astype(jnp.float64)
                    # emulated f64 add flushes -0.0 + -0.0 to +0.0; take hi
                    # directly for zeros so the signed zero survives
                    data = jnp.where((h64 == 0.0) & (l64 == 0.0), h64,
                                     h64 + l64)
                    i += 2
                elif kind == "dec128":
                    data = jnp.stack([arrays[i], arrays[i + 1]], axis=1)
                    i += 2
                elif kind in ("u32", "u8codes", "u16codes"):
                    data = arrays[i].astype(jnp.int32)
                    i += 1
                elif kind == "bool8":
                    data = arrays[i] != 0
                    i += 1
                else:
                    data = arrays[i]
                    i += 1
                if vkind == "ones":
                    validity = row_mask
                else:
                    validity = arrays[i] != 0
                    i += 1
                outs.append((data, validity))
            return outs

        fn = tpu_jit(assemble)
        _ASSEMBLE_CACHE[key] = fn
    return fn


#: jitted pack kernels for DeviceTable.to_host, keyed by (kinds, k, cap)
_PACK_CACHE: Dict[tuple, object] = {}

#: host tables holding a device-resident cache (weak: dropping the table
#: drops its device image); evicted under memory pressure (runtime/retry.py)
_CACHED_TABLES = None  # lazy weakref.WeakSet


#: tables that are capacity-sharing VIEWS of one source (a local shuffle
#: split's per-partition masks): concatenating them only multiplies
#: capacity, so coalesce streams them (weak: dropping the table drops it)
_SHARED_VIEWS = None


def mark_shared_view(table: "DeviceTable", group=None) -> None:
    """``group`` identifies ONE split execution: views carrying the same
    non-None group have DISJOINT masks by construction and may merge."""
    global _SHARED_VIEWS
    if _SHARED_VIEWS is None:
        import weakref
        _SHARED_VIEWS = weakref.WeakKeyDictionary()
    _SHARED_VIEWS[table] = group


def is_shared_view(table: "DeviceTable") -> bool:
    return _SHARED_VIEWS is not None and table in _SHARED_VIEWS


def view_group(table: "DeviceTable"):
    return _SHARED_VIEWS.get(table) if _SHARED_VIEWS is not None else None


def mergeable_views(a: "DeviceTable", b: "DeviceTable") -> bool:
    """May two masked views merge by mask union? Requires the SAME device
    buffers AND the same split-execution group — same buffers alone is
    not enough (two filters of one scan share buffers with OVERLAPPING
    masks; OR-ing those would dedupe rows)."""
    ga = view_group(a)
    return (ga is not None and ga is view_group(b)
            and a.live is not None and b.live is not None
            and a.capacity == b.capacity
            and len(a.columns) == len(b.columns)
            and all(x.data is y.data and x.validity is y.validity
                    for x, y in zip(a.columns, b.columns)))


def union_views(a: "DeviceTable", b: "DeviceTable") -> "DeviceTable":
    """Merge two same-split masked views by OR-ing liveness — zero data
    movement, one downstream kernel instead of two. Masks are disjoint
    (split partitions), so row counts add."""
    out = DeviceTable(a.names, a.columns, a.nrows_dev + b.nrows_dev,
                      a.capacity, live=a.live | b.live)
    mark_shared_view(out, view_group(a))
    return out


def merge_split_views(batches):
    """Generator: mask-union consecutive same-split views. For consumers
    that are partition-structure-blind (aggregate re-groups everything
    anyway), a repartition's k per-partition views collapse back into ONE
    masked batch — one downstream kernel instead of k full-capacity ones
    (q7-style repartition->agg was paying 8x)."""
    cur = None
    for b in batches:
        if cur is not None and mergeable_views(cur, b):
            cur = union_views(cur, b)
        else:
            if cur is not None:
                yield cur
            cur = b
    if cur is not None:
        yield cur


def register_device_cache(host: "HostTable") -> None:
    global _CACHED_TABLES
    if _CACHED_TABLES is None:
        import weakref
        _CACHED_TABLES = weakref.WeakSet()
    _CACHED_TABLES.add(host)


def evict_device_caches() -> int:
    """Drop every cached device image (called on device OOM before spill
    replay — cached scans are the lowest-priority device residents)."""
    if _CACHED_TABLES is None:
        return 0
    n = 0
    for t in list(_CACHED_TABLES):
        if t._cache.pop("device", None) is not None:
            n += 1
    return n


def _pack_kind(c: DeviceColumn) -> str:
    dt = c.data.dtype
    if getattr(c.data, "ndim", 1) == 2:
        if dt == jnp.int64:
            return "dec128"
        raise ColumnarProcessingError(f"unpackable 2-D device dtype {dt}")
    for kind, want in (("f64", jnp.float64), ("i64", jnp.int64),
                       ("i32", jnp.int32), ("f32", jnp.float32),
                       ("i16", jnp.int16), ("i8", jnp.int8),
                       ("bool", jnp.bool_)):
        if dt == want:
            return kind
    raise ColumnarProcessingError(f"unpackable device dtype {dt}")


def _u32_units(kind: str) -> int:
    return {"f64": 2, "i64": 2, "dec128": 4, "i32": 1, "f32": 1}.get(kind, 0)


def _get_pack(kinds: tuple, k: int, cap: int, n_extra: int = 0):
    """One jitted program bitcasting every column (data + validity) into a
    single u32 buffer: f64 as an exact hi/lo f32 split on TPU (f64 storage
    IS an f32 pair there; CPU bitcasts natively), i64 as hi/lo words, small
    ints and validities byte-packed 4-per-u32 at the tail.

    ``n_extra`` i32 scalars (the live row count + pending speculation
    flags — runtime/speculation.py) prepend as a header so the whole
    result, its size, and its validity arrive in ONE device fetch."""
    cpu = jax.default_backend() == "cpu"
    key = (kinds, k, cap, cpu, n_extra)
    fn = _PACK_CACHE.get(key)
    if fn is None:
        def pack(cols, extras):
            u32s, u8s = [], []
            for (data, _), kind in zip(cols, kinds):
                d = data[:k]
                if kind == "f64":
                    if cpu:
                        u32s.append(jax.lax.bitcast_convert_type(
                            d, jnp.uint32).reshape(-1))
                    else:
                        from spark_rapids_tpu.ops.segsum import split_f64_hi_lo
                        hi, lo = split_f64_hi_lo(d)
                        u32s.append(jax.lax.bitcast_convert_type(hi, jnp.uint32))
                        u32s.append(jax.lax.bitcast_convert_type(lo, jnp.uint32))
                elif kind == "i64":
                    hi = (d >> 32).astype(jnp.int32)
                    lo = (d & 0xFFFFFFFF).astype(jnp.uint32)
                    u32s.append(jax.lax.bitcast_convert_type(hi, jnp.uint32))
                    u32s.append(lo)
                elif kind == "dec128":
                    for limb in (d[:, 0], d[:, 1]):
                        u32s.append(jax.lax.bitcast_convert_type(
                            (limb >> 32).astype(jnp.int32), jnp.uint32))
                        u32s.append((limb & 0xFFFFFFFF).astype(jnp.uint32))
                elif kind in ("i32", "f32"):
                    u32s.append(jax.lax.bitcast_convert_type(d, jnp.uint32))
                elif kind == "i16":
                    u8s.append(jax.lax.bitcast_convert_type(
                        d, jnp.uint8).reshape(-1))
                elif kind == "i8":
                    u8s.append(jax.lax.bitcast_convert_type(d, jnp.uint8))
                else:  # bool
                    u8s.append(d.astype(jnp.uint8))
            for (_, validity), _kind in zip(cols, kinds):
                u8s.append(validity[:k].astype(jnp.uint8))
            u8cat = jnp.concatenate(u8s)
            padlen = (-u8cat.shape[0]) % 4
            if padlen:
                u8cat = jnp.concatenate(
                    [u8cat, jnp.zeros(padlen, dtype=jnp.uint8)])
            tail = jax.lax.bitcast_convert_type(
                u8cat.reshape(-1, 4), jnp.uint32)
            parts = [a for a in u32s] + [tail]
            if n_extra:
                head = jax.lax.bitcast_convert_type(
                    extras.astype(jnp.int32), jnp.uint32)
                parts = [head] + parts
            return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

        fn = tpu_jit(pack)
        _PACK_CACHE[key] = fn
    return fn


def _unpack_host(buf: np.ndarray, kinds: tuple, k: int, n_extra: int = 0):
    cpu = jax.default_backend() == "cpu"
    extras = buf[:n_extra].view(np.int32)
    buf = buf[n_extra:]
    nu32 = sum(_u32_units(kd) for kd in kinds) * k
    u32part = buf[:nu32]
    bytes_part = buf.view(np.uint8)[4 * nu32:]
    datas = []
    o32 = 0
    o8 = 0
    for kind in kinds:
        if kind == "f64":
            if cpu:
                data = u32part[o32:o32 + 2 * k].view(np.float64)
                o32 += 2 * k
            else:
                hi = u32part[o32:o32 + k].view(np.float32).astype(np.float64)
                o32 += k
                lo = u32part[o32:o32 + k].view(np.float32).astype(np.float64)
                o32 += k
                data = hi + lo
        elif kind == "i64":
            hi = u32part[o32:o32 + k].view(np.int32).astype(np.int64)
            o32 += k
            lo = u32part[o32:o32 + k].astype(np.int64)
            o32 += k
            data = (hi << 32) | lo
        elif kind == "dec128":
            limbs = []
            for _limb in range(2):
                hi = u32part[o32:o32 + k].view(np.int32).astype(np.int64)
                o32 += k
                lo = u32part[o32:o32 + k].astype(np.int64)
                o32 += k
                limbs.append((hi << 32) | lo)
            data = np.stack(limbs, axis=1)
        elif kind == "i32":
            data = u32part[o32:o32 + k].view(np.int32)
            o32 += k
        elif kind == "f32":
            data = u32part[o32:o32 + k].view(np.float32)
            o32 += k
        elif kind == "i16":
            data = bytes_part[o8:o8 + 2 * k].view(np.int16)
            o8 += 2 * k
        elif kind == "i8":
            data = bytes_part[o8:o8 + k].view(np.int8)
            o8 += k
        else:  # bool
            data = bytes_part[o8:o8 + k] != 0
            o8 += k
        datas.append(data)
    valids = []
    for _ in kinds:
        valids.append(bytes_part[o8:o8 + k] != 0)
        o8 += k
    return extras, datas, valids


def _multi_device(a) -> bool:
    """True when ``a`` is a jax.Array physically laid out across more
    than one device — the buffer-level predicate behind
    ``DeviceTable.physically_sharded``/``unsharded``. Non-arrays (None,
    host scalars) and single-device arrays are False."""
    return isinstance(a, jax.Array) and len(a.sharding.device_set) > 1


#: jitted concat kernels keyed by (schema kinds, input caps, out cap)
_CONCAT_CACHE: Dict[tuple, object] = {}


def concat_device(tables: Sequence["DeviceTable"]) -> "DeviceTable":
    """Concatenate device tables ON DEVICE (no host round trip).

    Row counts stay device scalars: each table's rows scatter at the
    running dynamic offset (sum of predecessors' nrows_dev), so no host
    sync happens. String columns are remapped into the union dictionary
    first (host work is O(dict size), device work one gather per column).
    Output capacity is the bucket of the capacity sum — a static upper
    bound that avoids syncing the live counts."""
    if not tables:
        raise ColumnarProcessingError("concat of zero tables")
    if len(tables) == 1:
        return tables[0]
    names = tables[0].names
    ncols = len(tables[0].columns)
    caps = tuple(t.capacity for t in tables)
    out_cap = bucket_for(sum(caps))

    # unify string dictionaries; build per-(table, col) remap aux arrays
    out_dicts: List[Optional[np.ndarray]] = []
    out_sorted: dict = {}  # ci -> dict_sorted of a reused shared dict
    remaps: List[List[Optional[np.ndarray]]] = [[None] * ncols
                                                for _ in tables]
    for ci in range(ncols):
        col0 = tables[0].columns[ci]
        if not isinstance(col0.dtype, T.StringType):
            out_dicts.append(None)
            continue
        if all(t.columns[ci].dictionary is col0.dictionary
               for t in tables):
            # identical dictionary OBJECT on every input (masked splits of
            # one table, re-coalesced scan batches): codes already agree —
            # skip the O(dict log dict) union entirely (a 1M-entry object
            # dict costs ~seconds to re-sort). The shared dictionary may
            # be UNSORTED (concat_ws outputs); record its real flag
            out_sorted[ci] = col0.dict_sorted
            out_dicts.append(col0.dictionary)
            continue
        dicts = [(t.columns[ci].dictionary if t.columns[ci].dictionary
                  is not None else np.array([], dtype=object))
                 for t in tables]
        union = np.unique(np.concatenate([d.astype(object) for d in dicts])) \
            if any(len(d) for d in dicts) else np.array([], dtype=object)
        for ti, d in enumerate(dicts):
            m = np.searchsorted(union, d).astype(np.int32) if len(d) else \
                np.zeros(1, np.int32)
            remaps[ti][ci] = m
        out_dicts.append(union)

    kinds = tuple((str(c.dtype), c.dictionary is not None)
                  for c in tables[0].columns)
    masked = tuple(t.live is not None for t in tables)
    key = (kinds, caps, out_cap, masked)
    fn = _CONCAT_CACHE.get(key)
    if fn is None:
        def concat(cols_per_table, remap_per_table, nrows_list, lives):
            from spark_rapids_tpu.ops.scatter32 import scatter_pair
            outs = []
            for ci in range(ncols):
                od = None
                ov = jnp.zeros(out_cap, dtype=jnp.bool_)
                offset = jnp.asarray(0, dtype=jnp.int32)
                for ti in range(len(cols_per_table)):
                    data, valid = cols_per_table[ti][ci]
                    rm = remap_per_table[ti][ci]
                    if rm is not None:
                        data = rm[jnp.clip(data, 0, rm.shape[0] - 1)]
                    if od is None:
                        od = jnp.zeros((out_cap,) + data.shape[1:],
                                       dtype=data.dtype)
                    n = nrows_list[ti]
                    if lives[ti] is not None:
                        # masked input: its deferred compaction fuses into
                        # this scatter (slot -> rank among live rows)
                        lv = lives[ti]
                        pos = jnp.cumsum(lv.astype(jnp.int32)) - 1
                        tgt = jnp.where(lv, pos + offset, out_cap)
                    else:
                        idx = jnp.arange(data.shape[0], dtype=jnp.int32)
                        tgt = jnp.where(idx < n, idx + offset, out_cap)
                    pd, pv = scatter_pair(out_cap, tgt, data, valid)
                    od = od + pd if jnp.issubdtype(od.dtype, jnp.number) \
                        else od | pd
                    ov = ov | pv
                    offset = offset + n
                outs.append((od, ov))
            total = jnp.asarray(0, dtype=jnp.int32)
            for n in nrows_list:
                total = total + n
            return outs, total

        fn = tpu_jit(concat)
        _CONCAT_CACHE[key] = fn

    cols_per_table = tuple(
        tuple((c.data, c.validity) for c in t.columns) for t in tables)
    from spark_rapids_tpu.dispatch import device_const
    remap_per_table = tuple(
        tuple(device_const(m) if m is not None else None for m in row)
        for row in remaps)
    nrows_list = tuple(t.nrows_dev for t in tables)
    lives = tuple(t.live for t in tables)
    outs, total = fn(cols_per_table, remap_per_table, nrows_list, lives)
    def _union_domain(ci):
        doms = [t.columns[ci].domain for t in tables]
        if any(d is None for d in doms):
            return None
        return (min(d[0] for d in doms), max(d[1] for d in doms))

    out_cols = [
        DeviceColumn(c.dtype, d, v, dictionary=out_dicts[ci],
                     dict_sorted=out_sorted.get(
                         ci, True if out_dicts[ci] is not None
                         else c.dict_sorted),
                     domain=_union_domain(ci))
        for ci, (c, (d, v)) in enumerate(zip(tables[0].columns, outs))]
    return DeviceTable(names, out_cols, total, out_cap)


class HostTable:
    """Named host columns with a shared row count.

    ``_cache`` holds derived artifacts — notably the device-resident image
    of the table (see DeviceTable.from_host cache wiring in
    execs/basic.TpuScanExec), the GpuInMemoryTableScanExec analog."""

    __slots__ = ("names", "columns", "_cache", "__weakref__")

    def __init__(self, names: Sequence[str], columns: Sequence[HostColumn]):
        self.names: Tuple[str, ...] = tuple(names)
        self.columns: Tuple[HostColumn, ...] = tuple(columns)
        self._cache = {}
        if len(self.names) != len(self.columns):
            raise ColumnarProcessingError("names/columns mismatch")
        lens = {len(c) for c in self.columns}
        if len(lens) > 1:
            raise ColumnarProcessingError(f"ragged columns: {lens}")

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def schema(self) -> List[Tuple[str, T.DataType]]:
        return [(n, c.dtype) for n, c in zip(self.names, self.columns)]

    def column(self, name: str) -> HostColumn:
        return self.columns[self.names.index(name)]

    @staticmethod
    def from_pydict(data: Dict[str, list], dtypes: Optional[Dict[str, T.DataType]] = None) -> "HostTable":
        names, cols = [], []
        for name, values in data.items():
            dt = (dtypes or {}).get(name)
            names.append(name)
            cols.append(HostColumn.from_pylist(values, dt))
        return HostTable(names, cols)

    def to_pydict(self) -> Dict[str, list]:
        return {n: c.to_pylist() for n, c in zip(self.names, self.columns)}

    @staticmethod
    def from_pandas(df) -> "HostTable":
        names, cols = [], []
        for name in df.columns:
            s = df[name]
            if s.dtype == object or str(s.dtype) in ("string", "str"):
                cols.append(HostColumn.from_pylist(
                    [None if v is None or (isinstance(v, float) and np.isnan(v)) else str(v)
                     for v in s.tolist()], T.STRING))
            else:
                validity = ~s.isna().to_numpy()
                vals = s.to_numpy()
                if vals.dtype == np.float64 and not validity.all():
                    vals = np.where(validity, vals, 0.0)
                cols.append(HostColumn.from_numpy(np.ascontiguousarray(vals), validity))
            names.append(name)
        return HostTable(names, cols)

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame({n: c.to_pylist() for n, c in zip(self.names, self.columns)})

    def slice(self, start: int, length: int) -> "HostTable":
        return HostTable(self.names, [c.slice(start, length) for c in self.columns])

    @staticmethod
    def concat(tables: Sequence["HostTable"]) -> "HostTable":
        if not tables:
            raise ColumnarProcessingError("concat of zero tables")
        names = tables[0].names
        cols = []
        for i in range(len(names)):
            dtype = tables[0].columns[i].dtype
            datas = [t.columns[i].data for t in tables]
            vals = [t.columns[i].validity for t in tables]
            cols.append(HostColumn(dtype, np.concatenate(datas), np.concatenate(vals)))
        return HostTable(names, cols)

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.columns)


class PendingHostTable:
    """An ENQUEUED packed download: the d2h kernel is already in flight
    (enqueued under the device semaphore), ``resolve()`` blocks for the
    buffer, validates any speculation flags riding the header, and
    decodes the HostTable. Splitting enqueue from fetch lets the
    session release the device semaphore before paying the ~0.1s
    tunnel round trip (async result fetch) — the next admitted query's
    kernels dispatch while this one's bytes cross the wire.

    ``resolve()`` may raise SpeculationFailed exactly like the
    synchronous path; callers must therefore resolve INSIDE the
    speculation attempt that produced the batch."""

    __slots__ = ("_table", "_buf", "_kinds", "_k", "_n_extra", "_pend")

    def __init__(self, table: "DeviceTable", buf_dev, kinds: tuple,
                 k: int, n_extra: int, pend):
        self._table = table
        self._buf = buf_dev
        self._kinds = kinds
        self._k = k
        self._n_extra = n_extra
        self._pend = pend

    def resolve(self) -> HostTable:
        from spark_rapids_tpu.runtime import speculation as spec
        buf = np.asarray(self._buf)  # blocks: the one d2h round trip
        extras, datas, valids = _unpack_host(buf, self._kinds, self._k,
                                             self._n_extra)
        if self._pend:
            spec.check_flag_values([s for s, _ in self._pend], extras[1:])
        t = self._table
        n = int(extras[0])
        if t._nrows_host is None:
            t._nrows_host = n
        n = min(n, self._k)
        cols = []
        for c, data, validity in zip(t.columns, datas, valids):
            cols.append(c.decode_host(
                data[:n], np.ascontiguousarray(validity[:n])))
        return HostTable(t.names, cols)


class DeviceTable:
    """Named device columns padded to a common capacity bucket.

    ``num_rows`` is tracked both as a device int32 scalar (``nrows_dev``,
    usable inside jitted kernels without host sync) and, lazily, as a host
    int (``num_rows`` property — blocks on the device the first time it is
    read after a data-dependent op such as filter).

    ``live`` (optional device bool[capacity]) marks MASKED tables: live rows
    sit at their original slots instead of a compacted prefix. Row
    compaction is a scatter per column word — 64-bit columns split into
    2-3 scatters plus emulated recombine chains, the single most expensive
    per-row operation on TPU (PERF.md: ~0.15-0.25s per 8-column 1M-row
    compaction). Filters and dense-key joins therefore emit masked tables
    and downstream mask-aware execs (filter, project, join probe,
    aggregate, sort) consume liveness from ``row_mask()`` — the scatter is
    paid only at a boundary that truly needs the prefix invariant
    (``compacted()``: collects, spill demotion, splits, unlearned execs).
    The reference has no analog: cuDF compaction is bandwidth-priced, so
    GpuFilterExec compacts eagerly (basicPhysicalOperators.scala)."""

    __slots__ = ("names", "columns", "nrows_dev", "_nrows_host", "capacity",
                 "live", "shard_spec", "__weakref__")

    def __init__(self, names: Sequence[str], columns: Sequence[DeviceColumn],
                 nrows, capacity: Optional[int] = None, live=None,
                 shard_spec=None):
        self.names: Tuple[str, ...] = tuple(names)
        self.columns: Tuple[DeviceColumn, ...] = tuple(columns)
        self.live = live
        #: plan-carried sharding descriptor (jax.sharding.NamedSharding
        #: over the row axis, or None for single-device tables): set
        #: when a mesh-native scan lands shards per device; narrow
        #: kernels preserve the layout through GSPMD propagation and
        #: exchanges re-shard explicitly (parallel/mesh.py)
        self.shard_spec = shard_spec
        if self.columns:
            caps = {c.capacity for c in self.columns}
            if len(caps) != 1:
                raise ColumnarProcessingError(f"ragged capacities {caps}")
            self.capacity = caps.pop()
        else:
            self.capacity = int(capacity or 0)
        if isinstance(nrows, (int, np.integer)):
            from spark_rapids_tpu.dispatch import device_scalar
            self._nrows_host: Optional[int] = int(nrows)
            self.nrows_dev = device_scalar(int(nrows))
        else:
            self._nrows_host = None
            self.nrows_dev = nrows

    @property
    def num_rows(self) -> int:
        if self._nrows_host is None:
            self._nrows_host = int(jax.device_get(self.nrows_dev))
        return self._nrows_host

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def schema(self) -> List[Tuple[str, T.DataType]]:
        return [(n, c.dtype) for n, c in zip(self.names, self.columns)]

    def schema_key(self) -> tuple:
        """Structural key for the compile cache: column dtypes + capacity +
        which columns are dictionary-encoded."""
        return (
            tuple((str(c.dtype), c.dictionary is not None) for c in self.columns),
            self.capacity,
        )

    def column(self, name: str) -> DeviceColumn:
        return self.columns[self.names.index(name)]

    def device_nbytes(self) -> int:
        return sum(c.device_nbytes() for c in self.columns)

    @staticmethod
    def from_host(host: HostTable, capacity: Optional[int] = None,
                  sharding=None) -> "DeviceTable":
        """Upload ``host`` as one staged transfer. With ``sharding`` (a
        NamedSharding over the row axis — mesh-native scans), every
        staged column lands SPLIT across the mesh devices by
        ``jax.device_put``: each device receives only its row shard, no
        single-device concat ever materializes, and the assemble
        kernel's outputs inherit the sharded layout (GSPMD)."""
        cap = capacity or bucket_for(host.num_rows)
        if sharding is not None:
            # even per-device shards: round the capacity up to a mesh
            # multiple (pow2 buckets >= 128 already divide pow2 meshes)
            ndev = len(sharding.mesh.devices.flat)
            cap = -(-cap // ndev) * ndev
        # bucket pad waste: dead tail rows this upload carries so the
        # kernel set stays bounded (`compile` scope, padWasteRows)
        count_pad_waste(cap - host.num_rows)
        # the device memory arbiter (runtime/memory.py): every landing
        # reserves its estimated device bytes against the hard budget
        # FIRST — an over-budget reservation spills idle spillables and,
        # when spilling cannot make room, raises RetryOOM into the
        # retry framework — then accounts the landed table at its
        # actual bytes for as long as the object lives
        from spark_rapids_tpu.runtime.memory import (
            MEMORY,
            estimate_device_nbytes,
        )
        reservation = MEMORY.reserve(
            estimate_device_nbytes(host, cap), label="from_host")
        try:
            if not host.columns:
                return MEMORY.account(
                    DeviceTable(host.names, [], host.num_rows, cap),
                    reservation)
            if any(isinstance(c.dtype,
                              (T.ArrayType, T.StructType, T.MapType))
                   for c in host.columns):
                # nested columns bypass the staged fast path (per-column
                # upload) and stay single-device — the exchange layer
                # excludes them from collectives for the same reason
                cols = [DeviceColumn.from_host(c, cap)
                        for c in host.columns]
                return MEMORY.account(
                    DeviceTable(host.names, cols, host.num_rows, cap),
                    reservation)
            return MEMORY.account(
                DeviceTable._from_host_staged(host, cap, sharding),
                reservation)
        finally:
            # a failed upload returns the grant; a successful account()
            # already consumed it (release is idempotent)
            reservation.release()

    @staticmethod
    def _from_host_staged(host: HostTable, cap: int,
                          sharding) -> "DeviceTable":
        """The staged fast-path upload body of :meth:`from_host` (all
        budget accounting happens in the caller)."""
        split_f64 = jax.default_backend() != "cpu"
        recipes, staged, dicts = [], [], []
        for c in host.columns:
            recipe, arrays, dictionary = stage_upload(c, cap, split_f64)
            recipes.append(recipe)
            staged.extend(arrays)
            dicts.append(dictionary)
        if sharding is None:
            dev_arrays = tuple(jnp.asarray(a) for a in staged)
        else:
            # the shard-landing fault site (the second registered
            # mesh.shard.put call site — parallel/mesh.shard_put covers
            # the exchange reshards): one evaluation per sharded batch,
            # before any per-device transfer starts
            from spark_rapids_tpu.runtime.faults import fault_point
            fault_point("mesh.shard.put")
            dev_arrays = tuple(jax.device_put(a, sharding) for a in staged)
        fn = _get_assemble(tuple(recipes), cap)
        outs = fn(dev_arrays, jnp.asarray(np.int32(host.num_rows)))
        cols = [
            DeviceColumn(c.dtype, data, validity, dictionary=d,
                         domain=c.int_domain())
            for c, (data, validity), d in zip(host.columns, outs, dicts)
        ]
        return DeviceTable(host.names, cols, host.num_rows, cap,
                           shard_spec=sharding)

    #: capacity up to which an unknown row count is fetched by embedding it
    #: in the packed buffer (fetching the padded bucket) instead of paying a
    #: separate ~0.1s row-count sync first
    EMBED_NROWS_CAP = 1 << 16

    #: ...but only while the padded transfer stays under this many bytes —
    #: a wide schema at 64k rows can be tens of MB over the ~30MB/s tunnel,
    #: costing more than the row-count sync it avoids (ADVICE r3)
    EMBED_MAX_BYTES = 4 << 20

    def _packed_row_bytes(self) -> int:
        """Bytes per row of the packed d2h buffer (data words + validity)."""
        total = 0
        for c in self.columns:
            total += 4 * _u32_units(_pack_kind(c)) or 2  # small ints ~1-2B
            total += 1  # validity byte
        return max(total, 1)

    def to_host(self) -> HostTable:
        """Download as one packed transfer.

        The tunneled TPU pays ~0.1s latency PER d2h fetch, so per-column
        (data + validity) fetches are ruinous. A jitted pack kernel bitcasts
        every column into one u32 buffer (f64/i64 as exact hi/lo splits —
        TPU f64 storage is an f32 pair; small ints byte-packed 4-per-u32)
        sliced to the live bucket, fetched with ONE device_get, and the host
        unpacks by numpy views.

        The packed buffer carries an i32 header: the live row count plus any
        pending speculation flags (runtime/speculation.py), so a warm query
        whose output bucket is small performs exactly ONE round trip —
        no separate row-count sync, no separate flag validation fetch."""
        out = self.to_host_pending()
        return out.resolve() if isinstance(out, PendingHostTable) else out

    def to_host_pending(self):
        """ENQUEUE the packed-download kernel and return a
        :class:`PendingHostTable` whose ``resolve()`` completes the d2h
        round trip — the async-result-fetch split: kernels are enqueued
        while the caller still holds the device semaphore, the ~0.1s
        tunnel fetch happens after it is released. Paths that cannot
        defer (no columns, nested columns) return a plain HostTable."""
        if not self.columns:
            return HostTable(self.names, [])
        if self.live is not None:
            return self.compacted().to_host_pending()
        if any(c.is_nested for c in self.columns):
            return self.to_host_per_column()
        from spark_rapids_tpu.runtime import speculation as spec
        ctx = spec.current()
        if (self._nrows_host is None
                and self.capacity <= self.EMBED_NROWS_CAP
                and self.capacity * self._packed_row_bytes()
                <= self.EMBED_MAX_BYTES):
            k = self.capacity  # fetch the padded bucket; n rides the header
        else:
            k = min(bucket_for(max(self.num_rows, 1)), self.capacity)
        pend = ctx.take_pending() if ctx is not None else []
        n_extra = 1 + len(pend)
        kinds = tuple(_pack_kind(c) for c in self.columns)
        fn = _get_pack(kinds, k, self.capacity, n_extra)
        extras_dev = jnp.concatenate(
            [jnp.reshape(self.nrows_dev.astype(jnp.int32), (1,))]
            + [jnp.reshape(f.astype(jnp.int32), (1,)) for _, f in pend])
        buf_dev = fn(
            tuple((c.data, c.validity) for c in self.columns), extras_dev)
        return PendingHostTable(self, buf_dev, kinds, k, n_extra, pend)

    def to_host_per_column(self) -> HostTable:
        """Low-allocation download: transfer each column's existing buffers
        (no pack kernel, no table-sized staging allocation). Used by spill
        demotion during OOM recovery, where allocating on the exhausted
        device would fail (the packed path is for collects)."""
        if self.live is not None:
            # OOM demotion path: the device is exhausted, so the deferred
            # compaction must NOT allocate there — fetch the padded
            # columns plus the mask and compact with numpy on host
            mask = np.asarray(jax.device_get(self.live))
            idx = np.nonzero(mask)[0]
            cols = []
            for c in self.columns:
                full = c.to_host(self.capacity)
                cols.append(type(full)(full.dtype, full.data[idx],
                                       full.validity[idx]))
            if self._nrows_host is None:
                self._nrows_host = int(len(idx))
            return HostTable(self.names, cols)
        n = self.num_rows
        return HostTable(self.names, [c.to_host(n) for c in self.columns])

    def row_mask(self):
        """Bool mask of live rows — usable inside jit (no host sync)."""
        if self.live is not None:
            return self.live
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.nrows_dev

    def compacted(self) -> "DeviceTable":
        """Prefix form: live rows scattered to [0, nrows) in original
        order. No-op for prefix tables; masked tables pay the one scatter
        per column word this representation exists to defer."""
        if self.live is None:
            return self
        from spark_rapids_tpu import kernels
        key = ("tablecompact", self.capacity, self.schema_key()[0],
               kernels.trace_token())
        fn = _PACK_CACHE.get(key)
        if fn is None:
            cap = self.capacity

            def compact(datas, valids, keep):
                from spark_rapids_tpu.ops.scatter32 import compact_pairs
                outs, _ = compact_pairs(datas, valids, keep, cap)
                return outs

            fn = tpu_jit(compact)
            _PACK_CACHE[key] = fn
        outs = fn(tuple(c.data for c in self.columns),
                  tuple(c.validity for c in self.columns), self.live)
        cols = [c.with_arrays(d, v) for c, (d, v) in zip(self.columns, outs)]
        out = DeviceTable(self.names, cols, self.nrows_dev, self.capacity,
                          shard_spec=self.shard_spec)
        out._nrows_host = self._nrows_host
        return out

    def physically_sharded(self) -> bool:
        """True when any buffer is laid out across more than one device
        — the predicate ``unsharded()`` gathers on. A bare shard_spec
        descriptor over single-device buffers (e.g. a 1-device mesh)
        does not count: dropping it moves no data."""
        return bool(_multi_device(self.live)
                    or _multi_device(self.nrows_dev)
                    or any(_multi_device(c.data)
                           or _multi_device(c.validity)
                           for c in self.columns))

    def unsharded(self) -> "DeviceTable":
        """Re-land a row-sharded table into the single-device layout —
        the merge-boundary gather of mesh-native execution. Wide kernels
        (aggregate/sort/join/window) must see exactly the layout the
        single-chip path computes on: a GSPMD-partitioned reduction over
        mesh shards changes float accumulation order, breaking the
        bit-identity contract. The move is DEVICE-to-device (ICI on a
        real pod) — data never round-trips through the host, so the
        RL-MESH-HOST zero-host-transfer invariant holds; no-op for
        tables that are not physically sharded."""
        # one traversal: per-buffer verdicts drive both the early-out
        # and the selective re-land below
        live_m = _multi_device(self.live)
        nrows_m = _multi_device(self.nrows_dev)
        col_m = [(_multi_device(c.data), _multi_device(c.validity))
                 for c in self.columns]
        if not (live_m or nrows_m or any(d or v for d, v in col_m)):
            if self.shard_spec is None:
                return self
            out = DeviceTable(self.names, self.columns, self.nrows_dev,
                              self.capacity, live=self.live)
            out._nrows_host = self._nrows_host
            return out
        dev = jax.devices()[0]

        def _land(a, multi):
            return jax.device_put(a, dev) if multi else a

        cols = [c.with_arrays(_land(c.data, d), _land(c.validity, v))
                for c, (d, v) in zip(self.columns, col_m)]
        # the row-count scalar rides replicated across the mesh on
        # sharded tables — re-land it with the columns or a downstream
        # jit sees mixed committed devices
        out = DeviceTable(self.names, cols, _land(self.nrows_dev, nrows_m),
                          self.capacity, live=_land(self.live, live_m))
        out._nrows_host = self._nrows_host
        return out

    def shrink(self) -> "DeviceTable":
        """Re-bucket to the smallest capacity holding the live rows. Syncs
        the row count (host round-trip) — worth it after cardinality-
        collapsing ops (aggregate output of a few groups must not drag the
        input's multi-million-row bucket through downstream sorts/uploads)."""
        if self.live is not None:
            return self.compacted().shrink()
        n = self.num_rows
        k = bucket_for(max(n, 1))
        if k >= self.capacity:
            return self
        cols = [c.sliced_rows(k) for c in self.columns]
        return DeviceTable(self.names, cols, n, k)
