"""RL-CONF-KEY — every ``spark.*`` conf key referenced as a string
literal must be declared in the conf registry (a typo'd key string
silently falls back to the default at runtime)."""

from __future__ import annotations

import ast
import re
from typing import List

from spark_rapids_tpu.lint.diagnostics import Diagnostic, make

_CONF_KEY_RE = re.compile(r"^spark\.(rapids|sql)\.[A-Za-z0-9_]"
                          r"[A-Za-z0-9_.]*[A-Za-z0-9_]$")


def _check_conf_keys(rel: str, tree: ast.AST, declared,
                     diags: List[Diagnostic]):
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Constant)
                and isinstance(node.value, str)):
            continue
        v = node.value
        if not _CONF_KEY_RE.match(v):
            continue
        if v in declared:
            continue
        diags.append(make(
            "RL-CONF-KEY", f"{rel}:{node.lineno}",
            f"conf key {v!r} is not declared in the conf registry — "
            "typo, or a key removed without cleaning its references"))
