"""StreamingQuery: a micro-batch stream scheduled through the query
service as a recurring tenant.

Each trigger plans one micro-batch (offsets logged first — the
write-ahead half of exactly-once), submits the batch plan through
``QueryService.submit`` so it rides the normal session path (plan cache,
memory arbiter, retry framework, SLO accounting under the stream's
tenant/pool), commits the result through the transactional sink, then
writes the commit marker. A stream that dies at ANY point resumes from
its checkpoint: a pending batch re-runs over the same recorded offsets
and the sink's txn watermark swallows the duplicate if the data already
landed.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from spark_rapids_tpu.conf import STREAMING_POOL, STREAMING_TRIGGER_INTERVAL_MS
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.runtime.faults import fault_point
from spark_rapids_tpu.streaming.metrics import STREAM_METRICS
from spark_rapids_tpu.streaming.offsets import OffsetLog
from spark_rapids_tpu.streaming.sink import DeltaStreamSink
from spark_rapids_tpu.streaming.source import StreamingSource
from spark_rapids_tpu.lockorder import ordered_lock

__all__ = ["StreamingQuery"]


class StreamingQuery:
    """One named stream: source -> optional transform -> sink."""

    def __init__(self, service, source: StreamingSource,
                 sink: DeltaStreamSink, checkpoint_dir: str, *,
                 name: str,
                 transform: Optional[Callable] = None,
                 pool: Optional[str] = None,
                 tenant: Optional[str] = None,
                 trigger_interval_ms: Optional[int] = None):
        if not name:
            raise ColumnarProcessingError("stream needs a non-empty name")
        self.service = service
        self.source = source
        self.sink = sink
        self.transform = transform
        self.name = name
        self.tenant = tenant or name
        conf = service.session.conf
        pool = pool or STREAMING_POOL.get(conf)
        # a stream outlives any one pool spec; fall back to the
        # service's first pool rather than failing every trigger
        self.pool = pool if pool in service.pools \
            else next(iter(service.pools))
        self.trigger_interval_s = (
            trigger_interval_ms if trigger_interval_ms is not None
            else STREAMING_TRIGGER_INTERVAL_MS.get(conf)) / 1000.0
        self.offsets = OffsetLog(checkpoint_dir)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = ordered_lock("streaming.query")
        self._state = "INITIALIZED"
        self._error: Optional[BaseException] = None
        self._batches_run = 0
        self._rows_sunk = 0

    # -- one trigger ---------------------------------------------------------
    def run_one_batch(self) -> bool:
        """Plan/resume and execute one micro-batch. Returns False when the
        source has nothing new (no batch ran)."""
        session = self.service.session
        pending = self.offsets.pending_batch()
        if pending is not None:
            batch_id, off = pending
            start, end = off["start"], off["end"]
        else:
            start = self.offsets.last_end_offset()
            if start is None:
                start = self.source.initial_offset()
            end = self.source.latest_offset(start)
            if end == start:
                return False
            batch_id = self.offsets.latest_batch_id() + 1
            self.offsets.write_offsets(batch_id,
                                       {"start": start, "end": end})
        fault_point("stream.batch", op=self.name)
        plan = self.source.read_batch(session, start, end)
        if self.transform is not None:
            from spark_rapids_tpu.plan.dataframe import DataFrame
            out = self.transform(DataFrame(plan, session))
            plan = out.plan if hasattr(out, "plan") else out
        handle = self.service.submit(plan, tenant=self.tenant,
                                     pool=self.pool,
                                     tag=f"stream:{self.name}:b{batch_id}")
        table = handle.result()
        session.stage_stream_delta("microBatches")
        outcome = self.sink.commit_batch(session, batch_id, table)
        self.offsets.write_commit(
            batch_id, {"outcome": outcome, "rows": table.num_rows})
        with self._lock:
            self._batches_run += 1
            if outcome == "committed":
                self._rows_sunk += table.num_rows
        STREAM_METRICS.add("microBatches", 1)
        return True

    def process_available(self, max_batches: int = 1000) -> int:
        """Synchronously drain everything the source has right now (plus
        any pending batch). Returns the number of batches run."""
        n = 0
        while n < max_batches and self.run_one_batch():
            n += 1
        return n

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "StreamingQuery":
        with self._lock:
            if self._thread is not None:
                raise ColumnarProcessingError(
                    f"stream {self.name!r} already started")
            self._state = "RUNNING"
            self._thread = threading.Thread(
                target=self._drive, name=f"stream-{self.name}", daemon=True)
        self.service.register_stream(self)
        self._thread.start()
        return self

    def _drive(self) -> None:
        while not self._stop.is_set():
            try:
                ran = self.run_one_batch()
            except Exception as e:  # noqa: BLE001 - fault surface
                with self._lock:
                    self._error = e
                    self._state = "FAILED"
                return
            if not ran:
                self._stop.wait(self.trigger_interval_s)
        with self._lock:
            if self._state == "RUNNING":
                self._state = "STOPPED"

    def stop(self, wait: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if wait and t is not None and t is not threading.current_thread():
            t.join(timeout=60)
        self.service.unregister_stream(self.name)
        with self._lock:
            if self._state == "RUNNING":
                self._state = "STOPPED"

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def error(self) -> Optional[BaseException]:
        with self._lock:
            return self._error

    def describe(self) -> dict:
        with self._lock:
            state, batches, rows = (self._state, self._batches_run,
                                    self._rows_sunk)
        return {
            "name": self.name,
            "tenant": self.tenant,
            "pool": self.pool,
            "state": state,
            "batchesRun": batches,
            "rowsSunk": rows,
            "lastBatchId": self.offsets.latest_batch_id(),
            "lastCommittedId": self.offsets.latest_committed_id(),
            "source": self.source.describe(),
            "sink": self.sink.describe(),
        }
