"""Oracle assertions (reference: integration_tests asserts.py —
assert_gpu_and_cpu_are_equal_collect / assert_gpu_fallback_collect,
SURVEY.md §4). Every test builds a DataFrame pipeline, runs it through the
TPU overrides engine AND the pure-CPU path, and compares results."""

from __future__ import annotations

import math

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.overrides import wrap_plan
from spark_rapids_tpu.overrides.rules import _EXEC_RULES


def _canon_row(row, approx):
    out = []
    for v in row:
        if v is None:
            out.append(("n",))
        elif isinstance(v, float):
            if math.isnan(v):
                out.append(("nan",))
            elif approx:
                out.append(("f", round(v, 9) if abs(v) < 1e15 else v))
            else:
                out.append(("f", v))
        else:
            out.append((type(v).__name__, v))
    return tuple(out)


def _sort_key(row):
    return tuple((x is None, str(type(x)), str(x)) for x in row)


def assert_tpu_and_cpu_are_equal(build_df, session, cpu_session,
                                 ignore_order: bool = True,
                                 approximate_float: bool = False):
    """build_df: fn(session) -> DataFrame. Runs on both paths, asserts
    equality (bit-for-bit unless approximate_float)."""
    tpu_df = build_df(session)
    cpu_df = build_df(cpu_session)

    tpu_rows = tpu_df.collect()
    cpu_rows = cpu_df.collect()

    assert len(tpu_rows) == len(cpu_rows), \
        f"row count: tpu={len(tpu_rows)} cpu={len(cpu_rows)}"
    if ignore_order:
        tpu_rows = sorted(tpu_rows, key=_sort_key)
        cpu_rows = sorted(cpu_rows, key=_sort_key)
    for i, (t, c) in enumerate(zip(tpu_rows, cpu_rows)):
        tc = _canon_row(t, approximate_float)
        cc = _canon_row(c, approximate_float)
        if approximate_float:
            assert len(t) == len(c), f"row {i} arity"
            for j, (tv, cv) in enumerate(zip(t, c)):
                if isinstance(tv, float) and isinstance(cv, float) \
                        and not (math.isnan(tv) or math.isnan(cv)):
                    assert tv == cv or abs(tv - cv) <= 1e-6 * max(1.0, abs(cv)), \
                        f"row {i} col {j}: tpu={tv!r} cpu={cv!r}"
                else:
                    assert _canon_row([tv], False) == _canon_row([cv], False), \
                        f"row {i} col {j}: tpu={tv!r} cpu={cv!r}"
        else:
            assert tc == cc, f"row {i}: tpu={t!r} cpu={c!r}"


def assert_runs_on_tpu(build_df, session):
    """Asserts the WHOLE plan converts (no fallback) — the plan-capture
    analog of the reference's fallback assertions."""
    df = build_df(session)
    meta = wrap_plan(df.plan, session.conf)

    def walk(m):
        assert m.can_run_on_tpu, \
            f"{m.node.describe()} fell back: {m.reasons}\n{meta.explain(only_fallback=False)}"
        for c in m.children:
            walk(c)

    walk(meta)


def assert_falls_back(build_df, session, node_name: str):
    df = build_df(session)
    meta = wrap_plan(df.plan, session.conf)
    found = []

    def walk(m):
        if m.node.name == node_name:
            found.append(m)
        for c in m.children:
            walk(c)

    walk(meta)
    assert found, f"no node {node_name} in plan"
    assert any(not m.can_run_on_tpu for m in found), \
        f"{node_name} unexpectedly supported on TPU"
