"""Datagen DSL tests (reference: datagen/bigDataGen.scala properties —
determinism, chunking invariance, column stability, distributions, key
groups)."""

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.datagen import (
    DecimalRange,
    DoubleRange,
    Exponential,
    Flat,
    ForeignKey,
    LongRange,
    MultiModal,
    Normal,
    RandomString,
    SequentialKey,
    TableSpec,
    Word,
    scale_test_specs,
)


def _spec():
    return (TableSpec("t", rows_per_sf=1000)
            .col("k", SequentialKey())
            .col("v", LongRange(lo=0, hi=100))
            .col("s", Word(cardinality=10))
            .col("d", DoubleRange(lo=-1.0, hi=1.0, null_prob=0.1)))


def test_deterministic_across_runs():
    a = _spec().generate_table(1.0, seed=7)
    b = _spec().generate_table(1.0, seed=7)
    for ca, cb in zip(a.columns, b.columns):
        assert np.array_equal(ca.validity, cb.validity)
        assert list(ca.data) == list(cb.data)
    c = _spec().generate_table(1.0, seed=8)
    assert list(a.columns[1].data) != list(c.columns[1].data)


def test_chunking_invariance():
    """The same rows come out regardless of chunk size (row_offset
    re-seeding — the reference's scalable-generation property)."""
    whole = _spec().generate_table(1.0, seed=3)
    chunked = _spec().generate(1.0, seed=3, chunk_rows=137)
    merged = HostTable.concat(chunked)
    assert merged.num_rows == whole.num_rows
    for cw, cm in zip(whole.columns, merged.columns):
        assert np.array_equal(cw.validity, cm.validity)
        assert list(cw.data) == list(cm.data)


def test_column_stability_under_schema_changes():
    """Adding another column must not change an existing column's
    values (per-column seed streams)."""
    base = (TableSpec("t", 500).col("v", LongRange(lo=0, hi=1 << 30)))
    wide = (TableSpec("t", 500)
            .col("extra", RandomString())
            .col("v", LongRange(lo=0, hi=1 << 30)))
    a = base.generate_table(1.0, seed=1)
    b = wide.generate_table(1.0, seed=1)
    va = a.columns[0].data
    vb = b.columns[list(b.names).index("v")].data
    assert list(va) == list(vb)


def test_sequential_key_unique_and_chunk_consistent():
    chunks = (TableSpec("t", 1000).col("k", SequentialKey())
              .generate(1.0, seed=0, chunk_rows=333))
    ks = np.concatenate([c.columns[0].data for c in chunks])
    assert list(ks) == list(range(1000))


def test_foreign_key_domain_and_skew():
    fk = ForeignKey(parent_rows=100, distribution=Exponential(rate=6.0))
    col = fk.generate(20000, seed=0, table="t", column="f")
    assert col.data.min() >= 0 and col.data.max() < 100
    # exponential skew: the hottest key much hotter than the median
    counts = np.bincount(col.data, minlength=100)
    assert counts.max() > 5 * np.median(counts[counts > 0])


def test_distributions_shape():
    rng = np.random.default_rng(0)
    flat = Flat().sample(20000, rng)
    norm = Normal(center=0.5, stddev=0.1).sample(20000, rng)
    mm = MultiModal(centers=(0.2, 0.8), stddev=0.02).sample(20000, rng)
    assert 0.45 < flat.mean() < 0.55 and flat.std() > 0.25
    assert norm.std() < 0.12
    hist, _ = np.histogram(mm, bins=10, range=(0, 1))
    assert hist[2] > hist[5] * 3 and hist[7] > hist[5] * 3  # two modes
    assert all(0 <= x < 1 for x in (flat.min(), norm.min(), mm.min()))


def test_decimal_gen_scale():
    g = DecimalRange(dtype=T.DecimalType(10, 2), lo=0.0, hi=10.0)
    col = g.generate(1000, seed=0, table="t", column="d")
    assert col.dtype == T.DecimalType(10, 2)
    assert col.data.min() >= 0 and col.data.max() <= 1000  # unscaled


def test_scale_test_specs_join_consistent(session, cpu_session):
    specs = scale_test_specs(0.01)
    tables = {k: s.generate_table(0.01, seed=0) for k, s in specs.items()}
    assert tables["lineitem"].num_rows == 10000
    # every l_orderkey exists in orders (FK domain)
    li_keys = tables["lineitem"].columns[0].data
    assert li_keys.max() < tables["orders"].num_rows

    # run one end-to-end query over generated data, TPU vs CPU oracle
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.plan import from_host_table

    def q(s):
        return (from_host_table(tables["lineitem"], s)
                .group_by("l_returnflag")
                .agg(F.count("l_quantity").alias("c"),
                     F.sum("l_quantity").alias("sq")))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert got == want


def test_scale_harness_queries_build(cpu_session):
    """scale_test.py's query set builds and runs on the CPU session at a
    tiny SF (harness smoke; TPU timing is the driver's job)."""
    import scale_test as st
    specs = scale_test_specs(0.005)
    tables = {k: s.generate_table(0.005, seed=0) for k, s in specs.items()}
    queries = st.build_queries(cpu_session, tables)
    for name, fn in queries.items():
        t = fn().collect_table()
        assert t.num_rows >= 0, name
