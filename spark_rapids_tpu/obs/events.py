"""Per-query structured event log (JSONL).

Reference: the Spark event log that spark-rapids-tools' qualification /
profiling analyzers consume — the machine-readable record every perf PR
diffs instead of hand-timing (PERF.md's essay form). One JSON object per
completed query, written by ``TpuSession.execute`` when
``spark.rapids.sql.eventLog.enabled`` is set:

* the executed plan tree with per-operator typed metrics and lore ids;
* fallback reasons (overrides tagging) and circuit-breaker demotions;
* AQE runtime conversions, spill / retry / fault-recovery counter
  deltas, per-exchange shuffle bytes;
* query wall / phase times and the span summary (category totals,
  attribution of wall time to named spans).

``python -m spark_rapids_tpu.tools`` analyzes these offline; the record
schema is versioned and pinned by a golden test so drift breaks a test,
not the tools.
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from collections import deque
from typing import Dict, List, Optional

from spark_rapids_tpu.conf import bool_conf, str_conf
from spark_rapids_tpu.lockorder import ordered_lock

EVENT_LOG_ENABLED = bool_conf(
    "spark.rapids.sql.eventLog.enabled", False,
    "Write one structured JSONL record per executed query (plan tree "
    "with per-op metrics, fallback/demotion reasons, recovery counters, "
    "span attribution) under spark.rapids.sql.eventLog.dir — the input "
    "to `python -m spark_rapids_tpu.tools`.", commonly_used=True)

EVENT_LOG_DIR = str_conf(
    "spark.rapids.sql.eventLog.dir", "/tmp/rapids_tpu_eventlog",
    "Directory for query event logs (one events-<session>.jsonl per "
    "session).")

#: bump on ANY record shape change and update the golden test — the
#: offline tools key off this.
#: v2 (query service PR): + tenant, pool, queueWaitS, cacheHit fields
#: (null/false for queries executed outside the service).
#: v3 (serving-latency PR): + compileMs (wall spent on new XLA traces:
#: trace + lowering + backend compile; 0.0 on fully warm queries),
#: executableCacheHit (the query checked out a cached converted
#: executable — false outside the cache paths / when disabled), and
#: padWasteRows (dead tail rows uploaded to pad batches to their
#: capacity buckets; 0 when every batch landed exactly on a bucket).
#: Result-cache-served replays carry compileMs=0.0,
#: executableCacheHit=false, padWasteRows=0 (nothing executed).
#: v4 (survivability PR): + healthState (process health at record
#: time: HEALTHY / DEGRADED / CPU_ONLY — runtime/health.py),
#: quarantined (the query's template carries poison strikes; false
#: outside the service), workerRestarts (service workers respawned
#: during this query's wall) and deviceReinits (backend
#: reinitializations after device loss during this query's wall) —
#: the last two are per-record DELTAS of the ``health`` scope, 0 on a
#: quiet process. Result-cache serves carry 0/0 and the serve-time
#: healthState.
#: v5 (transactional-write PR): + filesWritten (data files committed
#: into place by the transactional output committer during this
#: query's wall), bytesWritten (their bytes), and commitRetries
#: (Delta optimistic commits rebased and retried after losing the
#: version race) — per-record DELTAS of the ``write`` scope, all 0
#: for read-only queries and result-cache serves.
#: v6 (mesh-native execution PR): + meshShape (the active device-mesh
#: topology — '8' / '2x4' — null when mesh-native execution is off),
#: iciBytes (payload bytes this query moved through ICI all-to-all
#: collectives; per-record DELTA of the ``mesh`` scope, 0 off-mesh)
#: and shardSkew (max over the query's ICI exchanges of per-shard
#: map-output max/median bytes — the AQE skew signal measured from
#: REAL shard distributions; 0.0 when no collective exchange ran).
#: Result-cache serves carry the serve-time meshShape and 0/0.0.
#: v7 (mesh fault-domain PR): + meshDegradations (degradation-ladder
#: demotions — single-device re-lands and mesh shrinks — during this
#: query's wall; per-record DELTA of the ``health`` scope),
#: shardRetries (local re-gathers paid at mesh gather boundaries after
#: failed row-count/checksum validations) and gatherChecksFailed
#: (validations that TRIPPED — corrupted shards caught instead of
#: served) — the latter two per-record DELTAS of the ``mesh`` scope.
#: All 0 on a healthy mesh (and off-mesh); result-cache serves carry
#: 0/0/0 (nothing gathered).
#: v8 (multi-host fault-domain PR): + hostTopology (the active cluster
#: host topology at record time — '2' at full strength, '1/2' with a
#: host lost/excluded, '0/2' under the single-process latch; null when
#: cluster execution is off), hostsLost (executor hosts declared lost
#: during this query's wall — missed-beat sweep, dead dispatch socket,
#: or the host ladder's re-land rung), hostRelands (scans that
#: re-assigned a lost host's source files onto survivors) and
#: dcnExchanges (shuffle collectives whose mesh spanned more than one
#: cluster host group — the all-to-all crossed the DCN axis) — the
#: last three per-record DELTAS of the new ``cluster`` scope
#: (runtime/cluster.py). All 0/null off-cluster; result-cache serves
#: carry the serve-time hostTopology and 0/0/0.
#: v9 (flight-recorder PR): + hostScans — per-executor-host scan
#: attribution merged from cluster scan replies ({host: {scans, files,
#: bytes, wallS, execWallS, crcRetries}}: dispatch round trips, TPAK
#: frames landed and their bytes, driver-side round-trip wall,
#: executor-reported scan wall, CRC-caught re-lands). {} off-cluster,
#: for local-fallback scans, and for result-cache serves (nothing
#: dispatched).
#: v10 (out-of-core PR): + oomRetries (spill-and-replay retries the
#: OOM retry framework performed during this query's wall),
#: splitRetries (split-and-retry escalations — an input halved by rows
#: and both halves replayed), spillBytes (device bytes freed by spill
#: demotions) and unspills (spilled batches re-landed on device) —
#: per-record DELTAS of the new ``memory`` scope (runtime/memory.py);
#: plus budgetPeak (the memory arbiter's PEAK accounted device bytes
#: at record time — absolute, process-wide, not a delta). All deltas 0
#: on an unbudgeted quiet process and for result-cache serves.
#: v11 (streaming PR): + microBatches (streaming micro-batches whose
#: execution rode this query's wall), mvRefreshes (materialized-view
#: refreshes taken), mvIncrementalRefreshes (refreshes satisfied from
#: the CDF delta instead of a full recompute), mvFullRecomputes
#: (refreshes that fell back to recomputing the whole plan),
#: sinkCommits (transactional micro-batch sink commits) and
#: sinkReplays (micro-batches skipped at the sink because their txn
#: watermark was already committed — the exactly-once dedupe firing) —
#: per-record DELTAS of the new ``streaming`` scope (streaming/), all
#: 0 for non-streaming queries and result-cache serves; plus mvEpoch
#: (the maintained table's Delta version when this query was served
#: FROM a materialized view; null for every other query).
EVENT_SCHEMA_VERSION = 11


def plan_tree(executable) -> dict:
    """The executed tree as nested dicts: operator name, lore id,
    describe() and TYPED metrics per node (children include transition/
    adapter links, matching lore's tree walk)."""
    from spark_rapids_tpu.obs.metrics import MetricSet

    def node(e) -> dict:
        m = getattr(e, "metrics", None)
        if isinstance(m, MetricSet):
            metrics = m.typed()
        elif m:
            metrics = {k: {"value": v, "kind": "count",
                           "level": "MODERATE"}
                       for k, v in sorted(m.items())}
        else:
            metrics = {}
        d = {
            "op": type(e).__name__,
            "describe": e.describe() if hasattr(e, "describe")
            else type(e).__name__,
            "loreId": getattr(e, "_lore_id", None),
            "metrics": metrics,
            "children": [],
        }
        for c in getattr(e, "children", ()):
            d["children"].append(node(c))
        for attr in ("source", "tpu_exec", "cpu_node", "scan_node"):
            nxt = getattr(e, attr, None)
            if nxt is not None:
                d["children"].append(node(nxt))
        return d

    return node(executable)


def collect_fallbacks(meta) -> List[dict]:
    """Flatten the overrides meta tree into [{op, reasons}] for every
    node tagged with fallback reasons."""
    out: List[dict] = []

    def walk(m):
        if m is None:
            return
        reasons = list(getattr(m, "reasons", ()) or ())
        if reasons:
            out.append({"op": type(getattr(m, "node", m)).__name__,
                        "reasons": reasons})
        for c in getattr(m, "children", ()) or ():
            walk(c)

    walk(meta)
    return out


def _walk_exec_tree(executable):
    from spark_rapids_tpu.lore import _iter_tree
    return _iter_tree(executable)


def collect_exchanges(executable) -> List[dict]:
    """Per-exchange shuffle summary from the executed tree's metrics —
    bytes, times, skew and AQE coalescing per exchange node."""
    keys = ("shuffleBytesWritten", "shuffleBytesRead", "shuffleWriteTime",
            "shuffleReadTime", "mapOutputBytesMax", "mapOutputBytesMedian",
            "skewedPartitions", "aqeCoalescedPartitions",
            "recomputedMapOutputs", "iciExchangeTime", "iciPartitions",
            "iciBytes", "hostShuffleFallbacks",
            "localSplitParts", "localSplitTime")
    out = []
    for e in _walk_exec_tree(executable):
        m = getattr(e, "metrics", None)
        if not m or not any(k in m for k in keys):
            continue
        entry = {"op": type(e).__name__,
                 "loreId": getattr(e, "_lore_id", None)}
        entry.update({k: m[k] for k in keys if k in m})
        out.append(entry)
    return out


def collect_aqe(executable) -> Dict[str, int]:
    """AQE runtime re-plan summary (measured broadcast conversions,
    coalesced partitions) aggregated over the tree."""
    totals = {"broadcastConversions": 0, "coalescedPartitions": 0}
    for e in _walk_exec_tree(executable):
        m = getattr(e, "metrics", None)
        if not m:
            continue
        totals["broadcastConversions"] += int(m.get("aqeBroadcastConverted",
                                                    0))
        totals["coalescedPartitions"] += int(m.get("aqeCoalescedPartitions",
                                                   0))
    return totals


def build_query_record(*, query_index: int, wall_s: float,
                       phases: Dict[str, float], executable, meta,
                       sql_text: Optional[str], query_tag: Optional[str],
                       dispatches: int, recovery_delta: Dict[str, int],
                       scope_deltas: Dict[str, dict],
                       fault_fires: Dict[str, int],
                       demotions: Dict[str, str],
                       spans_summary: Optional[dict],
                       fault_replays: int,
                       service: Optional[dict] = None,
                       compile_ms: float = 0.0,
                       executable_cache_hit: bool = False,
                       pad_waste_rows: int = 0,
                       health_state: str = "HEALTHY",
                       device_reinits: int = 0,
                       worker_restarts: int = 0,
                       files_written: int = 0,
                       bytes_written: int = 0,
                       commit_retries: int = 0,
                       mesh_shape: Optional[str] = None,
                       ici_bytes: int = 0,
                       mesh_degradations: int = 0,
                       shard_retries: int = 0,
                       gather_checks_failed: int = 0,
                       host_topology: Optional[str] = None,
                       hosts_lost: int = 0,
                       host_relands: int = 0,
                       dcn_exchanges: int = 0,
                       host_scans: Optional[Dict[str, dict]] = None,
                       oom_retries: int = 0,
                       split_retries: int = 0,
                       spill_bytes: int = 0,
                       unspills: int = 0,
                       budget_peak: int = 0,
                       micro_batches: int = 0,
                       mv_refreshes: int = 0,
                       mv_incremental_refreshes: int = 0,
                       mv_full_recomputes: int = 0,
                       sink_commits: int = 0,
                       sink_replays: int = 0,
                       mv_epoch: Optional[int] = None) -> dict:
    """Assemble one event-log record. Every field is JSON-native; the
    golden schema test normalizes timings and pins the shape.
    ``service`` is the query-service envelope (tenant, pool, queueWaitS,
    cacheHit) — None for queries executed outside the service, which
    still record the fields as null/false so the schema is stable."""
    service = service or {}
    exchanges = collect_exchanges(executable)
    # per-shard skew of this query's ICI exchanges (measured from the
    # collective's live counts, not file sizes): max over exchanges of
    # max/median per-shard map-output bytes
    shard_skew = 0.0
    for e in exchanges:
        if "iciBytes" in e and e.get("mapOutputBytesMedian"):
            shard_skew = max(shard_skew, e["mapOutputBytesMax"]
                             / max(e["mapOutputBytesMedian"], 1))
    return {
        "schema": EVENT_SCHEMA_VERSION,
        "event": "queryCompleted",
        "queryIndex": query_index,
        "queryTag": query_tag,
        "sqlText": sql_text,
        "tenant": service.get("tenant"),
        "pool": service.get("pool"),
        "queueWaitS": service.get("queueWaitS"),
        "cacheHit": bool(service.get("cacheHit", False)),
        "wallS": round(wall_s, 6),
        "phasesS": {k: round(v, 6) for k, v in sorted(phases.items())},
        "dispatches": dispatches,
        "compileMs": round(float(compile_ms), 3),
        "executableCacheHit": bool(executable_cache_hit),
        "padWasteRows": int(pad_waste_rows),
        "healthState": str(health_state),
        "quarantined": bool(service.get("quarantined", False)),
        "deviceReinits": int(device_reinits),
        "workerRestarts": int(worker_restarts),
        "filesWritten": int(files_written),
        "bytesWritten": int(bytes_written),
        "commitRetries": int(commit_retries),
        "meshShape": mesh_shape,
        "iciBytes": int(ici_bytes),
        "shardSkew": round(float(shard_skew), 4),
        "meshDegradations": int(mesh_degradations),
        "shardRetries": int(shard_retries),
        "gatherChecksFailed": int(gather_checks_failed),
        "hostTopology": host_topology,
        "hostsLost": int(hosts_lost),
        "hostRelands": int(host_relands),
        "dcnExchanges": int(dcn_exchanges),
        "hostScans": {h: dict(v)
                      for h, v in sorted((host_scans or {}).items())},
        "oomRetries": int(oom_retries),
        "splitRetries": int(split_retries),
        "spillBytes": int(spill_bytes),
        "unspills": int(unspills),
        "budgetPeak": int(budget_peak),
        "microBatches": int(micro_batches),
        "mvRefreshes": int(mv_refreshes),
        "mvIncrementalRefreshes": int(mv_incremental_refreshes),
        "mvFullRecomputes": int(mv_full_recomputes),
        "sinkCommits": int(sink_commits),
        "sinkReplays": int(sink_replays),
        "mvEpoch": mv_epoch if mv_epoch is None else int(mv_epoch),
        "faultReplays": fault_replays,
        "plan": plan_tree(executable),
        "fallbacks": collect_fallbacks(meta),
        "demotions": dict(demotions),
        "aqe": collect_aqe(executable),
        "exchanges": exchanges,
        "recovery": dict(recovery_delta),
        "scopes": scope_deltas,
        "faultFires": dict(fault_fires),
        "spans": spans_summary,
    }


class QueryEventWriter:
    """Appends one JSON line per query to a per-session file under the
    configured directory. Lazy: the file is created at the first
    record, so enabling the conf on an idle session writes nothing."""

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(
            directory, f"events-{uuid.uuid4().hex[:12]}.jsonl")
        self._lock = ordered_lock("obs.events.writer")
        self.records_written = 0

    def write(self, record: dict) -> str:
        line = json.dumps(record, sort_keys=True)
        with self._lock:
            os.makedirs(self.directory, exist_ok=True)
            with open(self.path, "a") as f:
                f.write(line + "\n")
            self.records_written += 1
        return self.path


# ---------------------------------------------------------------------------
# Recent-record ring (the flight recorder's "what was the engine doing
# just before the incident" context — obs/telemetry.py embeds it in
# every incident bundle)
# ---------------------------------------------------------------------------

#: slimmed summaries of the most recent event records, process-wide
#: (full records carry whole plan trees — the bundle only needs the
#: headline facts)
_RECENT_KEEP = 32
_RECENT_LOCK = ordered_lock("obs.events.recent")
_RECENT = deque(maxlen=_RECENT_KEEP)
_RECENT_FIELDS = ("queryIndex", "queryTag", "wallS", "healthState",
                  "hostTopology", "meshShape", "dispatches",
                  "faultReplays", "hostsLost", "hostRelands",
                  "meshDegradations", "deviceReinits", "cacheHit")


def note_recent_record(record: dict) -> None:
    """Remember a slim summary of one written event record (called by
    the session's event-log append path)."""
    slim = {k: record.get(k) for k in _RECENT_FIELDS}
    slim["demotions"] = sorted(record.get("demotions") or {})
    slim["faultFires"] = dict(record.get("faultFires") or {})
    with _RECENT_LOCK:
        _RECENT.append(slim)


def recent_records(n: int = _RECENT_KEEP) -> List[dict]:
    if n <= 0:
        return []  # [-0:] would return ALL
    with _RECENT_LOCK:
        return list(_RECENT)[-int(n):]


def scope_delta(before: Dict[str, dict],
                after: Dict[str, dict]) -> Dict[str, dict]:
    """Per-scope numeric deltas between two scopes_snapshot() calls —
    only keys that moved, so idle subsystems stay out of the record."""
    out: Dict[str, dict] = {}
    for scope, vals in after.items():
        prev = before.get(scope, {})
        moved = {}
        for k, v in vals.items():
            d = v - prev.get(k, 0)
            if d:
                moved[k] = round(d, 6) if isinstance(d, float) else d
        if moved:
            out[scope] = moved
    return out
