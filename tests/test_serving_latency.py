"""Serving-latency subsystem: shape bucketing, the plan->executable
cache, async result fetch, the dispatch-cache LRU, and AOT warmup."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from spark_rapids_tpu.functions import col, lit
from spark_rapids_tpu.functions import sum as fsum
from spark_rapids_tpu.session import TpuSession


def _df(s, n=50):
    return s.create_dataframe({
        "a": list(range(n)),
        "b": [float(i) * 0.5 for i in range(n)],
    })


# ---------------------------------------------------------------------------
# shared fingerprint module (satellite: one implementation, two keys)
# ---------------------------------------------------------------------------


def test_fingerprints_diverge_exactly_on_literal_values():
    from spark_rapids_tpu.plan.fingerprint import plan_fingerprints
    s = TpuSession()
    df = _df(s)
    p5 = df.filter(col("a") > lit(5)).plan
    p6 = df.filter(col("a") > lit(6)).plan
    p6b = df.filter(col("a") >= lit(6)).plan
    t5, f5 = plan_fingerprints(p5, s.conf)
    t6, f6 = plan_fingerprints(p6, s.conf)
    t6b, f6b = plan_fingerprints(p6b, s.conf)
    # literal-only difference: templates COLLIDE, full keys DIVERGE
    assert t5 == t6
    assert f5 != f6
    # structural difference (>= vs >): BOTH diverge
    assert t6 != t6b and f6 != f6b
    # same plan twice: both stable
    t5x, f5x = plan_fingerprints(
        df.filter(col("a") > lit(5)).plan, s.conf)
    assert (t5x, f5x) == (t5, f5)


def test_result_cache_still_separates_literal_variants():
    """The result cache keys on the FULL fingerprint — literal variants
    must never share a cached result."""
    from spark_rapids_tpu.service.result_cache import fingerprint
    s = TpuSession()
    df = _df(s)
    assert fingerprint(df.filter(col("a") > lit(5)).plan, s.conf) != \
        fingerprint(df.filter(col("a") > lit(6)).plan, s.conf)


# ---------------------------------------------------------------------------
# shape bucketing policy
# ---------------------------------------------------------------------------


def test_bucket_policy_shapes():
    from spark_rapids_tpu.columnar.column import BucketPolicy
    p2 = BucketPolicy("pow2", 128)
    assert [p2.bucket_for(n) for n in (1, 128, 129, 1000)] == \
        [128, 128, 256, 1024]
    p4 = BucketPolicy("pow4", 128)
    assert [p4.bucket_for(n) for n in (1, 129, 600, 3000)] == \
        [128, 512, 2048, 8192]
    ex = BucketPolicy("1024,16384", 128)
    assert ex.bucket_for(5) == 1024
    assert ex.bucket_for(2000) == 16384
    # above the declared maximum: pow2 growth, capacity always exists
    assert ex.bucket_for(20000) == 32768


def test_bucket_capacities_drawn_only_from_declared_set():
    from spark_rapids_tpu.columnar.column import BucketPolicy
    for spec in ("pow2", "pow4", "512,4096,65536"):
        p = BucketPolicy(spec, 128)
        declared = set(p.buckets_up_to(1 << 20))
        for n in (1, 7, 128, 129, 500, 5000, 70000, 1 << 20):
            assert p.bucket_for(n) in declared, (spec, n)
        # the set is BOUNDED: log-many buckets, not one per row count
        assert len(declared) <= 21


def test_bucket_policy_validation():
    from spark_rapids_tpu.columnar.column import BucketPolicy
    from spark_rapids_tpu.errors import ColumnarProcessingError
    for bad in ("100,200", "1024,512", "pow3x", "0"):
        with pytest.raises(ColumnarProcessingError):
            BucketPolicy(bad, 128)
    with pytest.raises(ColumnarProcessingError):
        BucketPolicy("pow2", 100)  # not a lane-width multiple


def test_bucketing_bit_identity_on_scale_corpus_slice():
    """A coarser bucket policy changes kernel shapes, never results:
    scale_test slice runs bit-identical under pow2 (default), pow4 and
    an explicit bucket set."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import scale_test as st
    from spark_rapids_tpu.datagen import scale_test_specs
    sf = 0.003
    specs = scale_test_specs(sf)
    tables = {name: spec.generate_table(sf, seed=3)
              for name, spec in specs.items()}
    wanted = ["q1", "q3", "q6"]
    results = {}
    for policy in ("pow2", "pow4", "256,2048,16384"):
        s = TpuSession({"spark.rapids.sql.shapeBuckets": policy})
        qs = st.build_queries(s, tables)
        results[policy] = {name: qs[name]().collect_table()
                           for name in wanted}
    for policy in ("pow4", "256,2048,16384"):
        for name in wanted:
            diff = st.tables_differ(results["pow2"][name],
                                    results[policy][name])
            assert diff is None, f"{policy}/{name}: {diff}"


def test_pad_waste_metric_counted():
    from spark_rapids_tpu.dispatch import COMPILE_SCOPE
    s = TpuSession()
    before = COMPILE_SCOPE.get("padWasteRows", 0)
    _df(s, n=50).filter(col("a") > lit(10)).collect_table()
    assert COMPILE_SCOPE.get("padWasteRows", 0) > before
    # per-query view: 50 rows pad to the 128 bucket somewhere in the plan
    assert (s.last_pad_waste_rows or 0) >= 78


# ---------------------------------------------------------------------------
# plan -> executable cache
# ---------------------------------------------------------------------------


def test_executable_cache_hit_skips_tracing_bit_identical():
    from spark_rapids_tpu.dispatch import COMPILE_SCOPE
    s = TpuSession()
    df = _df(s, n=100)

    def q(v):
        return (df.filter(col("a") > lit(v)).group_by("a")
                .agg(fsum(col("b")).alias("sb")))

    r1 = q(5).collect_table()
    assert s.last_executable_cache_hit is False
    traces_after_cold = COMPILE_SCOPE.get("kernelTraces", 0)
    r2 = q(5).collect_table()
    assert s.last_executable_cache_hit is True
    assert s.last_compile_ms == 0.0
    # the repeat performed ZERO new XLA traces
    assert COMPILE_SCOPE.get("kernelTraces", 0) == traces_after_cold
    assert r1.to_pydict() == r2.to_pydict()


def test_executable_cache_literal_variant_is_template_hit():
    from spark_rapids_tpu.plan.executable_cache import EXEC_CACHE
    s = TpuSession()
    df = _df(s, n=100)

    def q(v):
        return df.filter(col("a") > lit(v))

    q(5).collect_table()
    before = EXEC_CACHE.stats()
    r = q(6).collect_table()
    after = EXEC_CACHE.stats()
    assert s.last_executable_cache_hit is False
    assert after["templateHits"] == before["templateHits"] + 1
    # and the variant computed its OWN (correct) result
    assert r.to_pydict()["a"] == list(range(7, 100))


def test_executable_cache_invalidated_by_catalog_mutation():
    s = TpuSession()
    df = _df(s, n=40)
    q = df.group_by("a").agg(fsum(col("b")).alias("sb"))
    q.collect_table()
    q.collect_table()
    assert s.last_executable_cache_hit is True
    # any warehouse mutation bumps the epoch -> cached executables stale
    _df(s, n=4).create_or_replace_temp_view("serving_latency_inval_v")
    q.collect_table()
    assert s.last_executable_cache_hit is False
    q.collect_table()
    assert s.last_executable_cache_hit is True


def test_executable_cache_disabled_by_conf():
    s = TpuSession({"spark.rapids.sql.executableCache.enabled": "false"})
    df = _df(s)
    df.filter(col("a") > lit(1)).collect_table()
    df.filter(col("a") > lit(1)).collect_table()
    assert s.last_executable_cache_hit is False


def test_executable_cache_metrics_reset_per_run():
    """A reused tree must report the SECOND query's metrics, not the
    accumulated pair (the event record depends on it)."""
    s = TpuSession()
    df = _df(s, n=64)
    q = df.filter(col("a") > lit(2))
    q.collect_table()
    first = s.last_dispatches
    q.collect_table()
    assert s.last_executable_cache_hit is True
    ex = s._last_executable
    # numOutputRows on the root covers ONE run's 61 rows, not 122
    assert ex.metrics.get("numOutputRows", 0) <= 61 + 3
    assert s.last_dispatches <= first


def test_cached_tree_does_not_inherit_stale_cancel_scope():
    """The cancellation boundary resolves the ACTIVE scope per pull: a
    tree first run under a (later-cancelled) service scope must not
    raise for a plain session re-run."""
    from spark_rapids_tpu.service.query import CancelScope, cancel_scope
    s = TpuSession()
    df = _df(s, n=30)
    q = df.filter(col("a") > lit(3))
    scope = CancelScope()
    with cancel_scope(scope):
        q.collect_table()
    scope.cancel()  # late cancel on a finished query's scope
    out = q.collect_table()  # reuses the cached tree: must NOT raise
    assert s.last_executable_cache_hit is True
    assert out.num_rows == 26


def test_executable_cache_mid_run_write_stales_the_fill():
    """Entries are stamped with the CHECKOUT-time epoch: a write that
    lands while the filling query runs must stale the entry on its
    first lookup, and a pre-write tree must never re-park into a
    post-write pool (review-round coherence fix)."""
    from spark_rapids_tpu.plan.executable_cache import ExecutableCache
    from spark_rapids_tpu.plan.fingerprint import bump_invalidation_epoch
    s = TpuSession()
    plan = _df(s).filter(col("a") > lit(1)).plan
    cache = ExecutableCache()
    tok = cache.checkout(plan, s.conf)
    assert not tok.hit
    bump_invalidation_epoch("test: write lands mid-run")
    tok.fill(object(), None)
    tok.release()
    # the filled entry belongs to the PRE-write generation: the
    # post-write lookup must not serve it
    tok2 = cache.checkout(plan, s.conf)
    assert not tok2.hit
    assert cache.stats()["invalidations"] >= 1 or \
        cache.stats()["idleTrees"] == 0
    tok2.release()


# ---------------------------------------------------------------------------
# dispatch const/scalar cache LRU (satellite)
# ---------------------------------------------------------------------------


def test_const_cache_lru_keeps_hot_key_under_cap_pressure(monkeypatch):
    from spark_rapids_tpu import dispatch as D
    monkeypatch.setattr(D, "_CONST_CACHE_CAP", 8)
    hot = np.arange(7, dtype=np.int32)
    hot_dev = D.device_const(hot)
    for i in range(64):  # 8x the cap of distinct cold keys
        D.device_const(np.arange(8 + i, dtype=np.int32))
        # touch the hot key so LRU keeps it
        assert D.device_const(hot) is hot_dev, \
            "hot constant evicted under cap pressure (wholesale clear?)"
    with D._LOCK:
        assert len(D._CONST_CACHE) <= 8


def test_scalar_cache_lru_keeps_hot_key_under_cap_pressure(monkeypatch):
    from spark_rapids_tpu import dispatch as D
    monkeypatch.setattr(D, "_CONST_CACHE_CAP", 8)
    hot_dev = D.device_scalar(424241)
    for i in range(32):
        D.device_scalar(900000 + i)
        assert D.device_scalar(424241) is hot_dev


# ---------------------------------------------------------------------------
# async result fetch
# ---------------------------------------------------------------------------


def test_async_fetch_bit_identical_and_metered():
    base = {"spark.rapids.sql.executableCache.enabled": "false"}
    s_on = TpuSession(base)
    s_off = TpuSession({**base, "spark.rapids.sql.asyncResultFetch":
                        "false"})
    data = {"a": list(range(300)), "b": [float(i) for i in range(300)]}
    got_on = (s_on.create_dataframe(data).filter(col("a") > lit(3))
              .group_by("a").agg(fsum(col("b")).alias("sb"))
              .collect_table())
    got_off = (s_off.create_dataframe(data).filter(col("a") > lit(3))
               .group_by("a").agg(fsum(col("b")).alias("sb"))
               .collect_table())
    assert got_on.to_pydict() == got_off.to_pydict()
    # the root transition recorded the post-semaphore fetch
    ex = s_on._last_executable
    assert "resultFetchTime" in ex.metrics
    assert ex.metrics.get("asyncFetchBatches", 0) >= 1
    assert "resultFetchTime" not in s_off._last_executable.metrics


def test_pending_host_table_resolve_matches_sync():
    from spark_rapids_tpu.columnar import DeviceTable, HostTable
    from spark_rapids_tpu.columnar.table import PendingHostTable
    host = HostTable.from_pydict({
        "x": [1, 2, None, 4], "y": [1.5, None, 3.5, 4.5]})
    dt = DeviceTable.from_host(host)
    pending = dt.to_host_pending()
    assert isinstance(pending, PendingHostTable)
    assert pending.resolve().to_pydict() == dt.to_host().to_pydict()


# ---------------------------------------------------------------------------
# event-log v3 fields
# ---------------------------------------------------------------------------


def test_event_log_carries_compile_fields(tmp_path):
    s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": str(tmp_path)})
    df = _df(s)
    q = df.group_by("a").agg(fsum(col("b")).alias("sb"))
    q.collect_table()
    cold = s.last_event_record
    q.collect_table()
    warm = s.last_event_record
    assert cold["schema"] == 11
    assert cold["executableCacheHit"] is False
    assert warm["executableCacheHit"] is True
    assert warm["compileMs"] == 0.0
    assert cold["compileMs"] >= warm["compileMs"]
    assert cold["padWasteRows"] > 0


# ---------------------------------------------------------------------------
# AOT warmup (subprocess smoke: the tier-1 CLI contract)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # fresh-process jax import + compile; the warmup
# logic (trace/skip/compile accounting) is covered in-process by
# test_warmup_in_process_skips_warm_templates
def test_warmup_cli_subprocess_smoke(tmp_path):
    """End-to-end: write a tiny tagged event log, then `python -m
    spark_rapids_tpu.tools warmup` replays it in a FRESH process and
    reports compiled programs (tiny corpus; tier-1 time budget)."""
    eld = tmp_path / "el"
    s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": str(eld)})
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import scale_test as st
    from spark_rapids_tpu.datagen import scale_test_specs
    sf = 0.002
    tables = {name: spec.generate_table(sf, seed=0)
              for name, spec in scale_test_specs(sf).items()}
    qs = st.build_queries(s, tables)
    s.next_query_tag = "q6@smoke"
    qs["q6"]().collect_table()

    out = tmp_path / "warmup.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.tools", "warmup",
         "--eventlog-dir", str(eld), "--sf", str(sf), "--json",
         "--out", str(out)],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["ok"] is True
    assert report["distinctUnits"] == 1
    assert report["programsCompiled"] == 1  # fresh process: q6 compiles
    assert report["newTraces"] > 0
    assert report["queries"][0]["query"] == "q6"


def test_warmup_in_process_skips_warm_templates(tmp_path):
    """Second warmup over the same corpus in one process: everything is
    already traced -> skipped, zero new traces."""
    from spark_rapids_tpu.tools.warmup import run_warmup
    eld = tmp_path / "el"
    s = TpuSession({"spark.rapids.sql.eventLog.enabled": "true",
                    "spark.rapids.sql.eventLog.dir": str(eld)})
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import scale_test as st
    from spark_rapids_tpu.datagen import scale_test_specs
    sf = 0.002
    tables = {name: spec.generate_table(sf, seed=0)
              for name, spec in scale_test_specs(sf).items()}
    qs = st.build_queries(s, tables)
    s.next_query_tag = "q6"
    qs["q6"]().collect_table()
    first = run_warmup(str(eld), sf=sf, tables=tables, session=s)
    assert first["ok"] and first["distinctUnits"] == 1
    second = run_warmup(str(eld), sf=sf, tables=tables, session=s)
    assert second["newTraces"] == 0
    assert second["programsCompiled"] == 0
    assert second["programsSkipped"] == 1
