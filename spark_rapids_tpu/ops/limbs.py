"""Two-limb 64-bit layout — the single source of truth.

TPU ALUs are 32-bit: f64 storage IS an (f32, f32) pair and i64 compute
emulates through 32-bit word sequences, so every hot path in the engine
represents a 64-bit value as TWO native 32-bit limbs:

  f64 -> (hi = f32(x), lo = f32(x - hi)) — EXACT on TPU because the
         storage itself is the pair; hi rounds monotonically, so
         (hi, lo) also orders lexicographically like the value.
  i64 -> (hi = x >> 32 as i32, lo = x & 0xffffffff as u32) — the
         (signed high word, unsigned low word) pair orders
         lexicographically like the value.

Before this module the split/recombine recipes were hand-rolled in
three places (ops/scatter32.py, ops/segsum.py, segment_minmax_64) and
had started to drift; now kernels/ (the Pallas layer), the HLO scatter/
sort/segment paths, and the d2h pack all import the one definition
here. The numpy staging variant (host-side upload split) remains in
columnar/column.py stage_upload — it runs on host buffers before any
device array exists.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: low-word mask, usable against i64 without promotion surprises
M32 = 0xFFFFFFFF


def split_f64_hi_lo(x):
    """EXACT hi/lo f32 decomposition of a device f64 array (TPU f64
    storage is an (f32, f32) pair, so x == hi + lo exactly). Non-finite
    hi (inf from overflow, NaN) gets lo=0 so hi+lo reproduces the
    special value instead of inf-inf=NaN. Signed zero: -0.0 - (-0.0) =
    +0.0 and -0.0 + 0.0 = +0.0 would lose the sign on reconstruction,
    so the signed zero is carried in lo too."""
    hi = x.astype(jnp.float32)
    lo = jnp.where(jnp.isfinite(hi),
                   (x - hi.astype(jnp.float64)).astype(jnp.float32), 0.0)
    lo = jnp.where(x == 0.0, hi, lo)
    return hi, lo


def combine_f64(hi, lo):
    """Reassemble a split f64: exact for every value split_f64_hi_lo
    produced on a backend where the split round-trips (TPU always; CPU
    backends with the split forced on can lose values outside f32
    range — callers there guard with a reconstruction check)."""
    return hi.astype(jnp.float64) + lo.astype(jnp.float64)


def split_i64_hi_lo(x):
    """(hi i32, lo u32) two-limb decomposition of an integer array.
    value == (hi << 32) | lo, and (signed hi, unsigned lo) orders
    lexicographically like the i64 value."""
    d = x.astype(jnp.int64)
    return ((d >> 32).astype(jnp.int32),
            (d & jnp.int64(M32)).astype(jnp.uint32))


def combine_i64(hi, lo):
    """Reassemble a split i64 from its (i32 hi, u32 lo) limbs."""
    return (hi.astype(jnp.int64) << 32) | lo.astype(jnp.int64)


def f32_sortable_u32(x) -> jax.Array:
    """Monotone map f32 -> u32 (IEEE sortable-bits trick): negatives
    complement, non-negatives set the top bit, so unsigned order equals
    the float total order with NaN (canonicalized positive pattern)
    greatest — Spark's NaN-last ordering."""
    b = jax.lax.bitcast_convert_type(x, jnp.int32)
    return jnp.where(b < 0,
                     (~b).astype(jnp.uint32),
                     b.astype(jnp.uint32) | jnp.uint32(0x80000000))
