"""TPU window exec.

Reference: GpuWindowExec + its specialized iterators (SURVEY.md §2.3,
window/ — running window, batched bounded, unbounded-to-unbounded).

TPU-first design — everything is ONE jitted kernel over a sorted batch:
  1. lax.sort by (live, partition keys, order keys) with a row payload;
  2. partition boundaries -> segment starts via an associative max-scan;
     peer boundaries (order-key ties) -> peer-group ids;
  3. per function:
     row_number   = idx - seg_start + 1
     rank         = peer_start - seg_start + 1 (propagated over peers)
     dense_rank   = segmented cumsum of peer boundaries
     lag/lead     = shifted gather masked to the segment
     whole-part.  = jax.ops.segment_* + gather by segment id
     running      = segmented inclusive prefix (cumsum / scan-min / scan-max),
                    RANGE frames read the value at the LAST PEER row
     bounded rows = prefix-sum differences against clamped segment bounds
                    (sum/count/avg; bounded min/max falls back)
  4. results ride out positionally with the sorted child columns.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
from spark_rapids_tpu.dispatch import tpu_jit
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceColumn, DeviceTable
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops.window import (
    NthValue,
    PercentRank,
    DenseRank,
    Lag,
    Lead,
    Rank,
    RowNumber,
    WindowExpression,
)
from spark_rapids_tpu.ops.expr import (
    DevVal,
    EvalCtx,
    NodePrep,
    PrepCtx,
    _prep_trace_key,
    _walk_eval,
    _walk_prep,
)

#: window aggregates with device support
DEVICE_WINDOW_AGGS = (agg.Sum, agg.Count, agg.Min, agg.Max, agg.Average)


def device_window_supported(w: WindowExpression,
                            variable_float_agg: bool = True,
                            rows_frame_max_bound: int = 1 << 16
                            ) -> Tuple[bool, str]:
    fn = w.function
    frame = w.spec.resolved_frame()
    if isinstance(fn, (RowNumber, Rank, DenseRank, PercentRank)):
        if not w.spec.orders:
            return False, "ranking window function requires an ORDER BY"
        return True, ""
    if isinstance(fn, NthValue):
        if fn.ignore_nulls:
            return False, "nth_value IGNORE NULLS is not supported on TPU"
        if frame != ("range", None, 0):
            return False, ("nth_value supports only the default running "
                           "frame on TPU")
        return True, ""
    if isinstance(fn, (Lag, Lead)):
        if fn.default is not None and isinstance(fn.data_type, T.StringType):
            return False, "lag/lead string default value is not supported on TPU"
        return True, ""
    if isinstance(fn, DEVICE_WINDOW_AGGS):
        kind, lo, hi = frame
        if kind == "range" and not (lo is None and (hi in (0, None))):
            return False, "only UNBOUNDED..CURRENT/UNBOUNDED range frames"
        if kind == "rows":
            # sparse-table / unroll widths are bounded by the frame's
            # FINITE endpoints; gate them so table levels can't exhaust HBM
            for bound in (lo, hi):
                if bound is not None and abs(bound) > rows_frame_max_bound:
                    return False, (
                        f"rows frame bound beyond {rows_frame_max_bound} "
                        "is not supported on TPU (spark.rapids.sql."
                        "window.rowsFrameMaxBound)")
            if (lo is not None and hi is not None and (hi - lo + 1) > 512
                    and isinstance(fn, (agg.Sum, agg.Average))
                    and isinstance(fn.data_type, (T.FloatType, T.DoubleType))
                    and not variable_float_agg):
                return False, ("wide float rows frame uses prefix-difference "
                               "sums (reduction-order variance); enable "
                               "spark.rapids.sql.variableFloatAgg.enabled")
        return True, ""
    return False, f"window function {type(fn).__name__} is not supported on TPU"


class _TableExec(TpuExec):
    """Fixed device tables as an exec (two-pass composition plumbing)."""

    def __init__(self, tables, schema):
        super().__init__()
        self.children = ()
        self._tables = list(tables)
        self._schema = list(schema)

    def output_schema(self):
        return self._schema

    def execute(self):
        yield from self._tables


class _ReplayExec(TpuExec):
    """Replays SpillableBatches, pinning each while downstream consumes
    it (the cached-batch source of the double-pass window)."""

    def __init__(self, spills, schema):
        super().__init__()
        self.children = ()
        self._spills = list(spills)
        self._schema = list(schema)

    def output_schema(self):
        return self._schema

    def execute(self):
        for sb in self._spills:
            with sb.pinned_batch() as dt:
                yield dt


def _slice_rows(table: DeviceTable, a: int, b: int) -> DeviceTable:
    """Rows [a, b) of a compacted flat-column table as a fresh
    bucket-capacity table (the bounded-window streaming emit/carry cut)."""
    from spark_rapids_tpu.columnar import bucket_for

    n = b - a
    cap = bucket_for(max(n, 1))

    def cut(arr):
        s = arr[a:b]
        if cap > n:
            pad = jnp.zeros((cap - n,) + s.shape[1:], dtype=s.dtype)
            s = jnp.concatenate([s, pad])
        return s

    cols = [c.with_arrays(cut(c.data), cut(c.validity))
            for c in table.columns]
    return DeviceTable(table.names, cols, n, cap)


def _seg_scan_max(flags_idx):
    return jax.lax.associative_scan(jnp.maximum, flags_idx)


def _segmented_cumsum(v, seg_start_idx):
    """Inclusive prefix sum restarting at each segment: cumsum(v) minus the
    exclusive total at the segment start."""
    c = jnp.cumsum(v, dtype=v.dtype)
    base = c[seg_start_idx] - v[seg_start_idx]
    return c - base


def _segmented_scan(op, v, new_seg):
    """Generic segmented inclusive scan via flagged associative combine."""
    def combine(a, b):
        af, av = a
        bf, bv = b
        return af | bf, jnp.where(bf, bv, op(av, bv))
    _, out = jax.lax.associative_scan(combine, (new_seg, v))
    return out


class TpuWindowExec(TpuExec):
    def __init__(self, child: TpuExec, window_cols: Sequence[Tuple[str, WindowExpression]],
                 per_batch: bool = False, use_split: bool = False,
                 stream_target_rows: int = 0):
        super().__init__()
        self.children = (child,)
        self.window_cols = list(window_cols)
        self.per_batch = per_batch
        self.use_split = use_split
        self.stream_target_rows = stream_target_rows

    def output_schema(self):
        return (self.children[0].output_schema()
                + [(n, w.data_type) for n, w in self.window_cols])

    def describe(self):
        return f"TpuWindow[{[n for n, _ in self.window_cols]}]"

    def execute(self):
        from spark_rapids_tpu.runtime.retry import retry_block
        if self.per_batch:
            # each incoming batch holds COMPLETE partition groups
            # (TpuKeyedBatchExec contract) and windows independently
            for batch in self.children[0].execute():
                yield retry_block(lambda b=batch: self._window(b))
            return
        it = self.children[0].execute()
        if self._streamable():
            # consume ONE batch at a time: each sorts on device and
            # demotes to a host run before the next loads (bounded HBM)
            yield from self._stream_running(it)
            return
        bctx = self._bounded_ctx()
        two_pass = bctx is None and self._two_pass_able()
        if bctx is not None or two_pass:
            first = next(it, None)
            if first is None:
                return
            second = next(it, None)
            if second is None:
                yield retry_block(lambda: self._window(first))
                return
            from itertools import chain
            rest = chain([first, second], it)
            if two_pass:
                yield from self._stream_two_pass(rest)
            else:
                yield from self._stream_bounded(rest, *bctx)
            return
        batches = list(it)
        if not batches:
            return
        if len(batches) == 1:
            yield retry_block(lambda: self._window(batches[0]))
            return
        # multi-batch fallback (whole-partition frames with rank mixes,
        # RANGE frames, lag/lead): device concat (bounded by HBM) + one
        # kernel — the pre-round-4 "requires a single batch" raise is
        # gone; running and finite-rows frames stream above
        from spark_rapids_tpu.columnar.table import concat_device
        from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch
        catalog = BufferCatalog.get()
        spills = [SpillableBatch(b, catalog) for b in batches]
        try:
            merged = retry_block(
                lambda: concat_device([sb.get() for sb in spills]))
        finally:
            for sb in spills:
                sb.release()
        yield retry_block(lambda: self._window(merged))

    # -- partition-less running-window streaming ----------------------------
    # (reference: GpuRunningWindowExec — per-batch evaluation with carried
    # scalar state; window/GpuWindowExec.scala)

    _RUNNING_FRAMES = (("range", None, 0), ("rows", None, 0))

    def _streamable(self) -> bool:
        """True when every window column is a partition-less running
        window over ONE shared ORDER BY — these stream with cross-batch
        carried state instead of materializing the whole input."""
        first_orders = None
        for _, w in self.window_cols:
            if w.spec.partition_exprs:
                return False
            if not w.spec.orders:
                return False
            okey = tuple((o.expr.key(), o.ascending,
                          o.resolved_nulls_first()) for o in w.spec.orders)
            if first_orders is None:
                first_orders = okey
            elif okey != first_orders:
                return False
            fn = w.function
            if isinstance(fn, (RowNumber, Rank, DenseRank)):
                continue
            if isinstance(fn, DEVICE_WINDOW_AGGS) and \
                    w.spec.resolved_frame() in self._RUNNING_FRAMES:
                continue
            return False
        return True

    def _stream_running(self, batches):
        """Sort the input ONCE into globally ordered range batches (host
        runs + quantile range merge — execs/sort.sorted_run_stream; its
        equal-first-key invariant keeps RANGE-frame peers within one
        batch), then evaluate each batch with carried running state."""
        from spark_rapids_tpu.execs.sort import TpuSortExec, sorted_run_stream
        from spark_rapids_tpu.runtime.retry import retry_block

        orders = self.window_cols[0][1].spec.orders
        sorter = TpuSortExec.for_orders(orders)
        runs = []
        for b in batches:
            runs.append(retry_block(lambda bb=b: sorter._sort(bb)).to_host())
        if not runs:
            return
        state = None
        self.add_metric("runningWindowBatches", len(runs))
        for dt in sorted_run_stream(
                runs, orders,
                target_rows=getattr(self, "stream_target_rows", 0) or None):
            out, state = retry_block(
                lambda d=dt, st=state: self._stream_batch(d, st))
            yield out

    # -- batched bounded-frame streaming ------------------------------------
    # (reference: window/GpuBatchedBoundedWindowExec.scala:1-255 — batches
    # stream with a small carried context instead of materializing the
    # whole input; the TPU shape: globally sort into spill-backed runs,
    # then window each run EXTENDED by `lookback` rows of kept context
    # before it, withholding the last `lookahead` rows until the next run
    # supplies their forward frame.)

    def _bounded_ctx(self, child_schema=None):
        """(lookback, lookahead) when every window column is a device agg
        over a FINITE rows frame sharing one (partition, order) and all
        child columns are flat; None otherwise (-> other paths)."""
        from spark_rapids_tpu import types as T

        if child_schema is None:
            child_schema = self.children[0].output_schema()
        for _, dt in child_schema:
            if isinstance(dt, (T.ArrayType, T.StructType, T.MapType)):
                return None  # row-slicing nested buffers is not supported
        shared = None
        lookback = lookahead = 0
        for _, w in self.window_cols:
            if not isinstance(w.function, DEVICE_WINDOW_AGGS):
                return None
            kind, lo, hi = w.spec.resolved_frame()
            if kind != "rows" or lo is None or hi is None:
                return None
            if not w.spec.partition_exprs and not w.spec.orders:
                return None  # nothing to sort runs by -> concat fallback
            skey = (tuple(e.key() for e in w.spec.partition_exprs),
                    tuple((o.expr.key(), o.ascending,
                           o.resolved_nulls_first()) for o in w.spec.orders))
            if shared is None:
                shared = skey
            elif skey != shared:
                return None
            lookback = max(lookback, -min(lo, 0))
            lookahead = max(lookahead, max(hi, 0))
        if shared is None:
            return None
        return lookback, lookahead

    # -- cached double-pass: whole-partition aggregate windows ---------------
    # (reference: window/GpuCachedDoublePassWindowExec.scala — one pass
    # computes per-partition results while batches cache spillably, a
    # second pass stitches results onto every cached batch. TPU shape:
    # COMPOSE the existing streaming aggregate (pass 1) with a hash join
    # back by partition key (pass 2) — no bespoke caching machinery.)

    def _two_pass_able(self) -> bool:
        """True when every window column is a device agg over the whole
        partition (UNBOUNDED..UNBOUNDED) sharing one non-empty
        partition_by, over flat child columns."""
        from spark_rapids_tpu import types as T

        for _, dt in self.children[0].output_schema():
            if isinstance(dt, (T.ArrayType, T.StructType, T.MapType)):
                return False
        shared = None
        for _, w in self.window_cols:
            if not isinstance(w.function, DEVICE_WINDOW_AGGS):
                return False
            kind, lo, hi = w.spec.resolved_frame()
            if not (lo is None and hi is None):
                return False
            if not w.spec.partition_exprs:
                return False
            skey = tuple(e.key() for e in w.spec.partition_exprs)
            if shared is None:
                shared = skey
            elif skey != shared:
                return False
        return shared is not None

    @staticmethod
    def _null_sentinel(dt):
        from spark_rapids_tpu.ops.expr import Literal
        if isinstance(dt, T.StringType):
            return Literal("", dt)
        if isinstance(dt, T.BooleanType):
            return Literal(False, dt)
        if isinstance(dt, (T.FloatType, T.DoubleType)):
            return Literal(0.0, dt)
        return Literal(0, dt)

    @classmethod
    def _null_safe_keys(cls, exprs):
        """(coalesce(k, sentinel), isnull(k)) pairs — the join kernel has
        Spark null!=null key semantics, but window partitions group nulls
        together; the flag key restores null-safe matching."""
        from spark_rapids_tpu.ops.conditional import Coalesce
        from spark_rapids_tpu.ops.predicates import IsNull
        keys = []
        for k in exprs:
            keys.append(Coalesce(k, cls._null_sentinel(k.data_type)))
            keys.append(IsNull(k))
        return keys

    def _stream_two_pass(self, batches):
        from spark_rapids_tpu.columnar.table import concat_device
        from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
        from spark_rapids_tpu.execs.join import TpuJoinExec
        from spark_rapids_tpu.ops.expr import BoundReference
        from spark_rapids_tpu.runtime.retry import retry_block
        from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch

        catalog = BufferCatalog.get()
        child_schema = self.children[0].output_schema()
        grouping = list(self.window_cols[0][1].spec.partition_exprs)
        gnames = [f"__wp{i}" for i in range(len(grouping))]
        wnames = [n for n, _ in self.window_cols]
        agg_specs = [(f"__wa{i}", w.function)
                     for i, (_, w) in enumerate(self.window_cols)]

        spills = [SpillableBatch(b, catalog) for b in batches]
        try:
            # pass 1: streaming partial/merge aggregate over the cached
            # batches (bounded HBM — one pinned batch at a time)
            agg_exec = TpuHashAggregateExec(
                _ReplayExec(spills, child_schema), grouping, agg_specs,
                gnames, use_split=self.use_split)
            agg_batches = list(agg_exec.execute())
            self.add_metric("twoPassPartitions", len(agg_batches))
            agg_table = (agg_batches[0] if len(agg_batches) == 1 else
                         retry_block(lambda: concat_device(agg_batches)))
            agg_schema = agg_exec.output_schema()
            right_refs = [BoundReference(i, dt, name_hint=n)
                          for i, (n, dt) in enumerate(agg_schema)]

            # pass 2: ONE probe-streaming join stitches every cached
            # batch to its partition's results by null-safe key, then the
            # key duplicates drop
            join = TpuJoinExec(
                _ReplayExec(spills, child_schema),
                _TableExec([agg_table], agg_schema),
                "inner",
                self._null_safe_keys(grouping),
                self._null_safe_keys(right_refs[:len(grouping)]),
                None, child_schema, agg_schema)
            keep_child = len(child_schema)
            names = [n for n, _ in child_schema] + wnames
            for out in join.execute():
                cols = (list(out.columns[:keep_child])
                        + list(out.columns[keep_child + len(grouping):]))
                yield DeviceTable(names, cols, out.nrows_dev,
                                  out.capacity, live=out.live)
        finally:
            for sb in spills:
                sb.release()

    def _stream_bounded(self, batches, lookback: int, lookahead: int):
        """Sort ONCE into host runs, stream globally ordered ranges, and
        window each range over [kept context ++ range], emitting only the
        rows whose frame is complete: a row emits when `lookahead` rows
        exist after it; `lookback` already-emitted rows stay as context.
        Peak HBM = one range + (lookback+lookahead) rows."""
        from spark_rapids_tpu.columnar.table import concat_device
        from spark_rapids_tpu.execs.sort import TpuSortExec, sorted_run_stream
        from spark_rapids_tpu.plan.nodes import SortOrder
        from spark_rapids_tpu.runtime.retry import retry_block

        spec = self.window_cols[0][1].spec
        all_orders = ([SortOrder(e, True) for e in spec.partition_exprs]
                      + list(spec.orders))
        sorter = TpuSortExec.for_orders(all_orders)
        from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch
        catalog = BufferCatalog.get()
        # queued inputs stay SPILLABLE while each sorts (the sort exec's
        # _ooc_stream pattern): an OOM mid-sort demotes a queued batch
        spillables = [SpillableBatch(b, catalog) for b in batches]
        runs = []
        try:
            while spillables:
                sb = spillables.pop(0)
                try:
                    with sb.pinned_batch() as dt:
                        runs.append(retry_block(
                            lambda d=dt: sorter._sort(d)).to_host())
                finally:
                    sb.release()
        finally:
            for sb in spillables:
                sb.release()
        if not runs:
            return
        keep = lookback + lookahead
        carry_sb = None    # last `keep`+ rows (SPILLABLE context — an OOM
        # mid-stream can demote it and replay)
        c_n = 0
        unemitted = 0      # trailing carry rows still awaiting lookahead
        try:
            for dt in sorted_run_stream(
                    runs, all_orders,
                    target_rows=self.stream_target_rows or None):
                self.add_metric("boundedWindowBatches", 1)
                b_n = dt.num_rows
                if carry_sb is not None:
                    ext = retry_block(lambda d=dt: concat_device(
                        [carry_sb.get(), d]))
                    ext = DeviceTable(ext.names, ext.columns, c_n + b_n,
                                      ext.capacity)
                else:
                    ext = dt
                ext_n = c_n + b_n
                emit_start = c_n - unemitted
                emit_end = max(ext_n - lookahead, emit_start)
                if emit_end > emit_start:
                    out = retry_block(lambda e=ext: self._window(e))
                    yield _slice_rows(out, emit_start, emit_end)
                unemitted = ext_n - emit_end
                cstart = max(0, ext_n - max(keep, unemitted))
                new_carry = retry_block(
                    lambda e=ext, a=cstart, b=ext_n: _slice_rows(e, a, b))
                if carry_sb is not None:
                    carry_sb.release()
                carry_sb = SpillableBatch(new_carry, catalog)
                c_n = ext_n - cstart
            if unemitted:
                # final rows: no further input, frames clamp at the end
                out = retry_block(
                    lambda: self._window(carry_sb.get()))
                yield _slice_rows(out, c_n - unemitted, c_n)
        finally:
            if carry_sb is not None:
                carry_sb.release()

    def _stream_batch(self, table: DeviceTable, state):
        """One sorted batch through the running-window kernel with carried
        state (tuple of device scalars; None = initial)."""
        from spark_rapids_tpu.dispatch import prep_aux
        from spark_rapids_tpu.ops.expr import shared_traces

        pctx = PrepCtx(table)
        specs = []
        for _, w in self.window_cols:
            op = [self._prep_tree(o.expr, pctx) for o in w.spec.orders]
            vp = self._prep_value(w, pctx)
            specs.append((op, vp))
        cols = tuple(DevVal(c.data, c.validity) for c in table.columns)
        aux = prep_aux(pctx)
        capacity = table.capacity

        self._traces = shared_traces(
            ("runwin", tuple(w.key() for _, w in self.window_cols),
             table.schema_key()[0]))
        tkey = ("stream", capacity, tuple(
            (tuple(_prep_trace_key(p) for p in op),
             tuple(_prep_trace_key(p) for p in vp) if vp else None)
            for op, vp in specs))
        fn = self._traces.get(tkey)
        if fn is None:
            fn = tpu_jit(self._build_stream_kernel(capacity, specs))
            self._traces[tkey] = fn
        if state is None:
            state = self._initial_state()
        outs, new_state = fn(cols, aux, table.nrows_dev, state)
        out_cols = list(table.columns)
        names = list(table.names)
        for (name, w), (d, v) in zip(self.window_cols, outs):
            out_cols.append(DeviceColumn(w.data_type, d, v))
            names.append(name)
        return (DeviceTable(names, out_cols, table.nrows_dev, capacity),
                new_state)

    def _initial_state(self):
        parts = []
        for _, w in self.window_cols:
            fn = w.function
            if isinstance(fn, (RowNumber, Rank)):
                parts.append((jnp.asarray(0, jnp.int64),))
            elif isinstance(fn, DenseRank):
                parts.append((jnp.asarray(0, jnp.int64),))
            elif isinstance(fn, agg.Count):
                parts.append((jnp.asarray(0, jnp.int64),))
            elif isinstance(fn, (agg.Sum, agg.Average)):
                is_long = (isinstance(fn, agg.Sum)
                           and isinstance(fn.data_type, T.LongType))
                parts.append((jnp.asarray(0, jnp.int64) if is_long
                              else jnp.asarray(0.0, jnp.float64),
                              jnp.asarray(0, jnp.int64)))
            elif isinstance(fn, (agg.Min, agg.Max)):
                dt = fn.children[0].data_type.np_dtype
                ident = self._ident(jnp.dtype(dt), isinstance(fn, agg.Min))
                parts.append((ident, jnp.asarray(0, jnp.int64)))
        return tuple(parts)

    def _build_stream_kernel(self, capacity: int, specs):
        window_cols = self.window_cols

        def kernel(cols, aux, nrows, state):
            live = jnp.arange(capacity, dtype=jnp.int32) < nrows

            def eval_tree(e, preps):
                ctx = EvalCtx(cols, aux, nrows, capacity)
                ctx._prep_iter = iter(preps)
                return _walk_eval(e, ctx)

            # shared ORDER key peer structure (all specs share orders)
            op0 = specs[0][0]
            orders0 = window_cols[0][1].spec.orders
            from spark_rapids_tpu.ops.ordering import comparable_operands
            peer_ops = []
            for o, preps in zip(orders0, op0):
                kv = eval_tree(o.expr, preps)
                # canonical operands: NaNs are peers, -0.0 == 0.0 (the
                # batch kernel's _peer_eq_break invariant)
                from spark_rapids_tpu.ops.ordering import zero_invalid
                peer_ops.append((~kv.validity).astype(jnp.int32))
                peer_ops.extend(comparable_operands(
                    zero_invalid(kv.data, kv.validity)))
            first = jnp.arange(capacity) == 0
            new_peer = first
            for o in peer_ops:
                new_peer = new_peer | (o != jnp.roll(o, 1))
            new_peer = new_peer & live
            peer_id = jnp.cumsum(new_peer.astype(jnp.int32)) - 1
            peer_id = jnp.where(live, peer_id, capacity - 1)
            rows_before = jnp.cumsum(live.astype(jnp.int64)) - 1  # 0-based
            batch_rows = jnp.sum(live.astype(jnp.int64))
            peer_start = _seg_scan_max(
                jnp.where(new_peer, jnp.arange(capacity, dtype=jnp.int32),
                          0))

            outs = []
            new_state = []
            for ((op, vp), (name, w), st) in zip(specs, window_cols, state):
                fn = w.function
                if isinstance(fn, RowNumber):
                    (prev_rows,) = st
                    d = (prev_rows + rows_before + 1).astype(jnp.int64)
                    outs.append((jnp.where(live, d, 0), live))
                    new_state.append((prev_rows + batch_rows,))
                elif isinstance(fn, Rank):
                    (prev_rows,) = st
                    start_rows = rows_before[peer_start]
                    d = (prev_rows + start_rows + 1).astype(jnp.int64)
                    outs.append((jnp.where(live, d, 0), live))
                    new_state.append((prev_rows + batch_rows,))
                elif isinstance(fn, DenseRank):
                    (prev_dense,) = st
                    local = jnp.cumsum(new_peer.astype(jnp.int64))
                    d = prev_dense + local
                    outs.append((jnp.where(live, d, 0), live))
                    new_state.append((prev_dense + local[capacity - 1]
                                      if capacity else prev_dense,))
                else:
                    outs_st = self._stream_agg(
                        fn, vp, eval_tree, w, live, peer_id, capacity, st)
                    outs.append(outs_st[0])
                    new_state.append(outs_st[1])
            return outs, tuple(new_state)

        return kernel

    def _stream_agg(self, fn, vp, eval_tree, w, live, peer_id, capacity,
                    st):
        """Running aggregate over one sorted batch with carry. RANGE
        frames read the running value at the END of the row's peer group
        (per-peer totals + prefix over peers); ROWS frames are plain
        prefixes."""
        frame = w.spec.resolved_frame()
        rows_frame = frame[0] == "rows"
        v = eval_tree(fn.children[0], vp[0]) if fn.children else None
        if isinstance(fn, agg.Count):
            (prev_cnt,) = st
            w_valid = (live if fn.child is None
                       else (live & v.validity)).astype(jnp.int64)
            if rows_frame:
                run = jnp.cumsum(w_valid)
            else:
                per_peer = jax.ops.segment_sum(w_valid, peer_id,
                                               num_segments=capacity)
                run = jnp.cumsum(per_peer)[peer_id]
            d = prev_cnt + run
            return ((jnp.where(live, d, 0), live),
                    (prev_cnt + jnp.sum(w_valid),))
        if isinstance(fn, (agg.Sum, agg.Average)):
            prev_sum, prev_cnt = st
            sv = live & v.validity
            # LongType sums stay exact in int64 (the batch kernel's
            # invariant — f64 emulation would round beyond 2^53)
            int_exact = (isinstance(fn, agg.Sum)
                         and isinstance(fn.data_type, T.LongType))
            if int_exact:
                vv = jnp.where(sv, v.data.astype(jnp.int64), 0)
                prev_sum = prev_sum.astype(jnp.int64)
            else:
                vv = jnp.where(sv, v.data.astype(jnp.float64), 0.0)
            cnt1 = sv.astype(jnp.int64)
            if rows_frame:
                rsum = jnp.cumsum(vv)
                rcnt = jnp.cumsum(cnt1)
            else:
                rsum = jnp.cumsum(jax.ops.segment_sum(
                    vv, peer_id, num_segments=capacity))[peer_id]
                rcnt = jnp.cumsum(jax.ops.segment_sum(
                    cnt1, peer_id, num_segments=capacity))[peer_id]
            tsum = prev_sum + rsum
            tcnt = prev_cnt + rcnt
            has = tcnt > 0
            if isinstance(fn, agg.Average):
                d = tsum / jnp.maximum(tcnt, 1).astype(jnp.float64)
            else:
                d = tsum
            zero = jnp.zeros_like(d)
            return ((jnp.where(has & live, d, zero), has & live),
                    (prev_sum + jnp.sum(vv), prev_cnt + jnp.sum(cnt1)))
        # Min / Max
        prev_m, prev_cnt = st
        is_min = isinstance(fn, agg.Min)
        dt = jnp.dtype(v.data.dtype)
        ident = self._ident(dt, is_min)
        sv = live & v.validity
        vd = jnp.where(sv, v.data, ident)
        op = jnp.minimum if is_min else jnp.maximum
        if frame[0] == "rows":
            run = jax.lax.associative_scan(op, vd)
        else:
            per_peer = (jax.ops.segment_min if is_min
                        else jax.ops.segment_max)(
                vd, peer_id, num_segments=capacity)
            run = jax.lax.associative_scan(op, per_peer)[peer_id]
        cnt1 = sv.astype(jnp.int64)
        if frame[0] == "rows":
            rcnt = jnp.cumsum(cnt1)
        else:
            rcnt = jnp.cumsum(jax.ops.segment_sum(
                cnt1, peer_id, num_segments=capacity))[peer_id]
        total = op(run, prev_m.astype(run.dtype))
        tcnt = prev_cnt + rcnt
        has = tcnt > 0
        zero = jnp.zeros_like(total)
        return ((jnp.where(has & live, total, zero), has & live),
                (op(prev_m.astype(run.dtype),
                    jnp.where(jnp.sum(cnt1) > 0, run[capacity - 1],
                              prev_m.astype(run.dtype))),
                 prev_cnt + jnp.sum(cnt1)))

    # -----------------------------------------------------------------------
    def _window(self, table: DeviceTable) -> DeviceTable:
        # all window exprs share ONE spec sort per distinct spec; v1 sorts
        # once per expr group with identical (partition, order) — common case
        # is a single spec.
        pctx = PrepCtx(table)
        expr_preps = []
        for _, w in self.window_cols:
            pp = [self._prep_tree(e, pctx) for e in w.spec.partition_exprs]
            op = [self._prep_tree(o.expr, pctx) for o in w.spec.orders]
            vp = self._prep_value(w, pctx)
            expr_preps.append((pp, op, vp))

        from spark_rapids_tpu.dispatch import prep_aux
        cols = tuple(DevVal(c.data, c.validity) for c in table.columns)
        aux = prep_aux(pctx)
        capacity = table.capacity

        from spark_rapids_tpu.ops.expr import shared_traces
        self._traces = shared_traces(
            ("window", tuple(w.key() for _, w in self.window_cols),
             table.schema_key()[0]))
        tkey = (capacity, tuple(
            (tuple(_prep_trace_key(p) for p in pp),
             tuple(_prep_trace_key(p) for p in op),
             tuple(_prep_trace_key(p) for p in vp))
            for pp, op, vp in expr_preps))
        fn = self._traces.get(tkey)
        if fn is None:
            fn = tpu_jit(self._build_kernel(capacity, expr_preps))
            self._traces[tkey] = fn
        col_outs, win_outs = fn(cols, aux, table.nrows_dev)

        out_cols = [c.with_arrays(d, v) for c, (d, v) in zip(table.columns, col_outs)]
        names = list(table.names)
        for (name, w), (d, v), (pp, op, vp) in zip(self.window_cols, win_outs,
                                                   expr_preps):
            dictionary = None
            dict_sorted = True
            if isinstance(w.data_type, T.StringType) and vp:
                # lag/lead of a string expr: the value prep's root carries
                # the output dictionary (same as aggregate outputs)
                dictionary = vp[0][-1].out_dict
                dict_sorted = vp[0][-1].dict_sorted
            out_cols.append(DeviceColumn(w.data_type, d, v,
                                         dictionary=dictionary,
                                         dict_sorted=dict_sorted))
            names.append(name)
        return DeviceTable(names, out_cols, table.nrows_dev, capacity)

    @staticmethod
    def _prep_tree(e, pctx):
        preps: List[NodePrep] = []
        _walk_prep(e, pctx, preps)
        return preps

    def _prep_value(self, w: WindowExpression, pctx):
        fn = w.function
        if isinstance(fn, (Lag, Lead, NthValue)):
            return [self._prep_tree(fn.children[0], pctx)]
        if isinstance(fn, agg.AggregateFunction) and fn.child is not None:
            return [self._prep_tree(fn.child, pctx)]
        return []

    # -----------------------------------------------------------------------
    def _build_kernel(self, capacity: int, expr_preps):
        window_cols = self.window_cols

        def kernel(cols, aux, nrows):
            idx = jnp.arange(capacity, dtype=jnp.int32)
            live = idx < nrows

            def eval_tree(e, preps):
                ctx = EvalCtx(cols, aux, nrows, capacity)
                ctx._prep_iter = iter(preps)
                return _walk_eval(e, ctx)

            outs = []
            for (name, w), (pp, op, vp) in zip(window_cols, expr_preps):
                spec = w.spec
                pvals = [eval_tree(e, p) for e, p in zip(spec.partition_exprs, pp)]
                ovals = [eval_tree(o.expr, p) for o, p in zip(spec.orders, op)]

                # ---- sort by (dead-last, partition, order) ----------------
                operands = [(~live).astype(jnp.int32)]
                for kv in pvals:
                    operands.extend(self._sortable(kv))
                from spark_rapids_tpu.execs.sort import _directional
                for o, kv in zip(spec.orders, ovals):
                    operands.extend(_directional(
                        kv.data, kv.validity, o.ascending,
                        o.resolved_nulls_first(), capacity))
                res = jax.lax.sort(operands + [idx], num_keys=len(operands),
                                   is_stable=True)
                perm = res[-1]
                s_live = live[perm]

                # ---- segment & peer structure -----------------------------
                first = idx == 0
                def _peer_eq_break(kv):
                    """rows[i] != rows[i-1] with Spark peer semantics:
                    -0.0 == 0.0 and NaN == NaN (canonicalize before the
                    compare — raw float != would split NaN ties into
                    singleton peer groups; ADVICE r1)."""
                    d, v = kv.data[perm], kv.validity[perm]
                    if jnp.issubdtype(d.dtype, jnp.floating):
                        d = jnp.where(d == 0.0, jnp.zeros_like(d), d)
                        nan_mask = jnp.isnan(d)
                        d = jnp.where(nan_mask, jnp.zeros_like(d), d)
                        dp, vpv = jnp.roll(d, 1), jnp.roll(v, 1)
                        np_mask = jnp.roll(nan_mask, 1)
                        diff = (d != dp) | (nan_mask != np_mask)
                    elif getattr(d, "ndim", 1) == 2:  # dec128 limbs
                        dp, vpv = jnp.roll(d, 1, axis=0), jnp.roll(v, 1)
                        diff = jnp.any(d != dp, axis=1)
                    else:
                        dp, vpv = jnp.roll(d, 1), jnp.roll(v, 1)
                        diff = d != dp
                    return jnp.where(v & vpv, diff, v != vpv)

                new_seg = first
                for kv in pvals:
                    new_seg = new_seg | _peer_eq_break(kv)
                new_seg = new_seg & s_live | first
                gid = jnp.cumsum(new_seg.astype(jnp.int32)) - 1
                seg_start = _seg_scan_max(jnp.where(new_seg, idx, 0))

                new_peer = new_seg
                for kv in ovals:
                    new_peer = new_peer | _peer_eq_break(kv)
                peer_id = jnp.cumsum(new_peer.astype(jnp.int32)) - 1
                peer_start = _seg_scan_max(jnp.where(new_peer, idx, 0))
                # last row index of each peer group
                peer_last = jax.ops.segment_max(
                    jnp.where(s_live, idx, -1), peer_id,
                    num_segments=capacity)[peer_id]

                d, v = self._eval_window_fn(
                    w, vp, eval_tree, perm, idx, s_live, gid, seg_start,
                    peer_start, peer_last, nrows, capacity)
                # scatter back to INPUT row order so multiple window exprs
                # with different specs stay positionally aligned with the
                # child columns
                from spark_rapids_tpu.ops.scatter32 import scatter_pair
                outs.append(scatter_pair(capacity, perm, d, v))

            col_outs = [(d, v) for d, v in cols]  # original order
            return col_outs, outs

        return kernel

    @staticmethod
    def _sortable(kv):
        d = kv.data
        if getattr(d, "ndim", 1) == 2:
            # dec128 limb keys MUST decompose (no 2-D sort operand);
            # 1-D keys stay whole — extra sort operands cost real wall
            # time in the per-batch window kernel (measured 0.42s ->
            # 1.8s+ on q6 when every key decomposed)
            from spark_rapids_tpu.ops.ordering import (
                comparable_operands,
                zero_invalid,
            )
            return ([(~kv.validity).astype(jnp.int32)]
                    + comparable_operands(zero_invalid(d, kv.validity)))
        if jnp.issubdtype(d.dtype, jnp.floating):
            d = jnp.where(d == 0.0, jnp.zeros_like(d), d)
        if d.dtype == jnp.bool_:
            d = d.astype(jnp.int32)
        return [(~kv.validity).astype(jnp.int32),
                jnp.where(kv.validity, d, jnp.zeros_like(d))]

    @staticmethod
    def _rmq(op, ident, vv, a, b, width: int, capacity: int):
        """Range min/max over [a, b] per row via a doubling sparse table of
        ceil(log2(width))+1 levels. Queries satisfy b - a + 1 <= width and
        stay inside one partition, so table entries crossing partition
        boundaries are never read by a query that could be contaminated."""
        levels = [vv]
        span = 1
        while span < width:
            prev = levels[-1]
            shifted = jnp.concatenate(
                [prev[span:], jnp.full(span, ident, dtype=prev.dtype)])
            levels.append(op(prev, shifted))
            span <<= 1
        table = jnp.stack(levels)  # (L, capacity)
        length = jnp.maximum(b - a + 1, 1)
        k = jnp.floor(jnp.log2(length.astype(jnp.float32))).astype(jnp.int32)
        k = jnp.clip(k, 0, len(levels) - 1)
        pow_k = (jnp.int32(1) << k)
        left = table[k, a]
        right = table[k, jnp.clip(b - pow_k + 1, 0, capacity - 1)]
        return op(left, right)

    def _eval_window_fn(self, w, vp, eval_tree, perm, idx, s_live, gid,
                        seg_start, peer_start, peer_last, nrows, capacity):
        fn = w.function
        kind, lo, hi = w.spec.resolved_frame()

        if isinstance(fn, RowNumber):
            return ((idx - seg_start + 1).astype(jnp.int32), s_live)
        if isinstance(fn, Rank):
            return ((peer_start - seg_start + 1).astype(jnp.int32), s_live)
        if isinstance(fn, DenseRank):
            # segmented count of peer-group starts
            new_peer_int = (peer_start == idx).astype(jnp.int32)
            dense = _segmented_cumsum(new_peer_int, seg_start)
            return (dense.astype(jnp.int32), s_live)
        if isinstance(fn, PercentRank):
            seg_end_pr = jax.ops.segment_max(
                jnp.where(s_live, idx, -1), gid, num_segments=capacity)[gid]
            m = (seg_end_pr - seg_start + 1).astype(jnp.float64)
            rank = (peer_start - seg_start + 1).astype(jnp.float64)
            pr = jnp.where(m > 1, (rank - 1.0) / jnp.maximum(m - 1.0, 1.0),
                           0.0)
            return (pr, s_live)
        if isinstance(fn, NthValue):
            src = eval_tree(fn.children[0], vp[0])
            sd_n, sv_n = src.data[perm], src.validity[perm]
            pos = seg_start + (fn.n - 1)
            safe = jnp.clip(pos, 0, capacity - 1)
            seg_end_nv = jax.ops.segment_max(
                jnp.where(s_live, idx, -1), gid, num_segments=capacity)[gid]
            avail = (pos <= peer_last) & (pos <= seg_end_nv) & s_live
            data = jnp.where(avail, sd_n[safe], jnp.zeros_like(sd_n))
            return (data, avail & sv_n[safe])

        if isinstance(fn, (Lag, Lead)):
            src = eval_tree(fn.children[0], vp[0])
            sd, sv = src.data[perm], src.validity[perm]
            off = fn.offset if isinstance(fn, Lead) else -fn.offset
            j = idx + off
            safe = jnp.clip(j, 0, capacity - 1)
            in_seg = (j >= 0) & (j < capacity) & (gid[safe] == gid) & s_live
            in_seg = in_seg & (safe < nrows)
            data = jnp.where(in_seg, sd[safe], jnp.zeros_like(sd))
            valid = in_seg & sv[safe]
            if fn.default is not None:
                dflt = jnp.asarray(fn.default, dtype=sd.dtype)
                data = jnp.where(~in_seg & s_live, dflt, data)
                valid = valid | (~in_seg & s_live)
            return (data, valid)

        # aggregates
        if isinstance(fn, agg.Count) and fn.child is None:
            v = s_live.astype(jnp.int64)
            sv = s_live
        else:
            src = eval_tree(fn.child, vp[0])
            sd, sv = src.data[perm], src.validity[perm] & s_live
            if isinstance(fn, agg.Count):
                v = sv.astype(jnp.int64)
            elif isinstance(fn.data_type, T.LongType) and isinstance(fn, agg.Sum):
                v = jnp.where(sv, sd.astype(jnp.int64), 0)
            elif isinstance(fn, (agg.Sum, agg.Average)):
                v = jnp.where(sv, sd.astype(jnp.float64), 0.0)
            else:  # min/max keep dtype
                v = sd

        whole = (lo is None and hi is None)
        running = (lo is None and hi == 0)
        new_seg = seg_start == idx

        def seg_prefix(x):
            """Inclusive prefix restarting at each segment — never crosses
            partitions, so float sums cannot catastrophically cancel against
            other partitions' values (int stays exact too)."""
            return _segmented_scan(jnp.add, x, new_seg)

        if isinstance(fn, (agg.Min, agg.Max)):
            op = jnp.minimum if isinstance(fn, agg.Min) else jnp.maximum
            ident = self._ident(v.dtype, isinstance(fn, agg.Min))
            vv = jnp.where(sv, v, ident)
            if whole:
                seg_fn = jax.ops.segment_min if isinstance(fn, agg.Min) else jax.ops.segment_max
                r = seg_fn(vv, gid, num_segments=capacity)[gid]
                nn = jax.ops.segment_sum(sv.astype(jnp.int32), gid,
                                         num_segments=capacity)[gid]
                valid = (nn > 0) & s_live
            elif running:
                new_seg = seg_start == idx
                r = _segmented_scan(op, vv, new_seg)
                cnt = _segmented_scan(jnp.add, sv.astype(jnp.int32), new_seg)
                if kind == "range":
                    r = r[peer_last]
                    cnt = cnt[peer_last]
                valid = (cnt > 0) & s_live
            else:
                # bounded rows min/max (GpuBatchedBoundedWindowExec analog):
                # clip the frame to the partition, then
                #   hi unbounded  -> reverse segmented running scan read at a
                #   lo unbounded  -> forward scan at idx combined with a
                #                    sparse-table query over (idx, b]
                #   both bounded  -> classic RMQ sparse-table query on [a, b]
                seg_end = jax.ops.segment_max(
                    jnp.where(s_live, idx, -1), gid,
                    num_segments=capacity)[gid]
                a = seg_start if lo is None else jnp.maximum(seg_start, idx + lo)
                b = seg_end if hi is None else jnp.minimum(seg_end, idx + hi)
                # emptiness must be judged BEFORE clipping into the index
                # range (clipping turns an empty edge frame into a 1-row one)
                nonempty = (b >= a) & s_live
                a = jnp.clip(a, 0, capacity - 1)
                b = jnp.clip(b, 0, capacity - 1)
                new_seg = seg_start == idx

                prefc = _segmented_scan(jnp.add, sv.astype(jnp.int32), new_seg)
                lo_exclc = jnp.where(a > seg_start,
                                     prefc[jnp.maximum(a - 1, 0)], 0)
                nn = jnp.where(nonempty, prefc[b] - lo_exclc, 0)

                if hi is None:
                    rscan = jnp.flip(_segmented_scan(
                        op, jnp.flip(vv), jnp.flip(idx == seg_end)))
                    r = rscan[a]
                else:
                    width = (hi - (lo if lo is not None else 0)) + 1 \
                        if lo is not None else hi + 1
                    width = max(int(width), 1)
                    qa = a if lo is not None else jnp.minimum(idx + 1, b)
                    r_tab = self._rmq(op, ident, vv, qa, b, width, capacity)
                    if lo is None:
                        fwd = _segmented_scan(op, vv, new_seg)
                        head = fwd[jnp.minimum(idx, b)]
                        tail = jnp.where(b > idx, r_tab, ident)
                        r = op(head, tail)
                    else:
                        r = r_tab
                valid = (nn > 0) & nonempty
            r = jnp.where(valid, r, jnp.zeros_like(r))
            if isinstance(fn.data_type, T.BooleanType):
                r = r.astype(jnp.bool_)
            return (r, valid)

        # sum / count / average via prefix sums
        if isinstance(fn, agg.Count) and fn.child is None:
            cnt_all = s_live.astype(jnp.int64)
        else:
            cnt_all = sv.astype(jnp.int64)
        if whole:
            total = jax.ops.segment_sum(v, gid, num_segments=capacity)[gid]
            nn = jax.ops.segment_sum(cnt_all, gid, num_segments=capacity)[gid]
        elif running:
            total = seg_prefix(v)
            nn = seg_prefix(cnt_all)
            if kind == "range":
                total = total[peer_last]
                nn = nn[peer_last]
        else:
            # bounded rows frame [lo, hi] relative to current row
            seg_end = jax.ops.segment_max(jnp.where(s_live, idx, -1), gid,
                                          num_segments=capacity)[gid]
            a = seg_start if lo is None else jnp.maximum(seg_start, idx + lo)
            b = seg_end if hi is None else jnp.minimum(seg_end, idx + hi)
            # emptiness judged BEFORE clipping (empty edge frames must
            # stay empty)
            nonempty = b >= a
            a = jnp.clip(a, 0, capacity - 1)
            b = jnp.clip(b, 0, capacity - 1)
            is_float = jnp.issubdtype(v.dtype, jnp.floating)

            # counts (int, exact) always go prefix-diff
            prefc = seg_prefix(cnt_all)
            past_start = a > seg_start
            lo_exclc = jnp.where(past_start, prefc[jnp.maximum(a - 1, 0)], 0)
            nn = jnp.where(nonempty, prefc[b] - lo_exclc, 0)

            if not is_float:
                pref = seg_prefix(v)
                lo_excl = jnp.where(past_start, pref[jnp.maximum(a - 1, 0)], 0)
                total = jnp.where(nonempty, pref[b] - lo_excl, 0)
            elif lo is None:
                # frame starts at segment start: prefix read, NO subtraction
                # (prefix-diff on floats can catastrophically cancel)
                total = jnp.where(nonempty, seg_prefix(v)[b], 0.0)
            elif hi is None:
                # frame ends at segment end: reverse segmented prefix
                seg_last = idx == seg_end
                rpref = jnp.flip(_segmented_scan(
                    jnp.add, jnp.flip(v), jnp.flip(seg_last)))
                total = jnp.where(nonempty, rpref[a], 0.0)
            elif (hi - lo + 1) <= 512:
                # both-bounded small frame: exact per-frame unrolled sum
                total = jnp.zeros_like(v)
                for k in range(lo, hi + 1):
                    j = idx + k
                    safe = jnp.clip(j, 0, capacity - 1)
                    inside = (j >= seg_start) & (j <= seg_end) & s_live
                    total = total + jnp.where(inside, v[safe], 0.0)
            else:
                # wide float frame: segmented-prefix DIFFERENCE — same
                # reduction-order float variance class the reference gates
                # with variableFloatAgg (ulp-level, partition-local)
                pref = seg_prefix(v)
                lo_excl = jnp.where(past_start,
                                    pref[jnp.maximum(a - 1, 0)], 0.0)
                total = jnp.where(nonempty, pref[b] - lo_excl, 0.0)

        if isinstance(fn, agg.Count):
            return (nn.astype(jnp.int64), s_live)
        valid = (nn > 0) & s_live
        if isinstance(fn, agg.Average):
            r = total / jnp.maximum(nn, 1).astype(jnp.float64)
        else:
            r = total
        return (jnp.where(valid, r, jnp.zeros_like(r)), valid)

    @staticmethod
    def _ident(dtype, is_min: bool):
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(jnp.inf if is_min else -jnp.inf, dtype=dtype)
        if dtype == jnp.bool_:
            return jnp.asarray(True if is_min else False, dtype=dtype)
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.max if is_min else info.min, dtype=dtype)


class TpuKeyedBatchExec(TpuExec):
    """Partition-complete batching for windows (GpuKeyBatchingIterator /
    batched-window analog — reference window/ iterators process bounded
    batches instead of the whole input): a single-batch child passes
    through untouched; a multi-batch child hash-exchanges on the window
    PARTITION keys so every partition group lands whole inside exactly
    one output batch — the window then processes each batch independently
    and peak memory is bounded by the largest reduce partition, not the
    whole input."""

    def __init__(self, child: TpuExec, keys, conf, num_partitions: int = 8):
        super().__init__()
        self.children = (child,)
        self.keys = list(keys)
        self.conf = conf
        self.num_partitions = num_partitions

    def output_schema(self):
        return self.children[0].output_schema()

    def describe(self):
        return f"TpuKeyedBatch[n={self.num_partitions}]"

    def execute(self):
        it = self.children[0].execute()
        first = next(it, None)
        if first is None:
            return
        second = next(it, None)
        if second is None:
            yield first  # common fast path: already one batch, no shuffle
            return
        from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec

        prefix = [first, second]

        class _Replay(TpuExec):
            def __init__(self, schema):
                super().__init__()
                self._schema = schema

            def output_schema(self):
                return self._schema

            def execute(self):
                yield from prefix
                yield from it

        # partition-ALIGNED batches are the contract: no AQE partition
        # coalescing, and one batch per reduce partition (huge target)
        conf = self.conf.set(
            "spark.rapids.sql.adaptive.coalescePartitions.enabled", "false")
        ex = TpuShuffleExchangeExec(
            _Replay(self.output_schema()), "hash", self.num_partitions,
            self.keys, conf, target_batch_bytes=1 << 62)
        self.add_metric("keyBatchedPartitions", self.num_partitions)
        yield from ex.execute()
        self.metrics.update(ex.metrics)


class TpuWindowGroupLimitExec(TpuExec):
    """Pre-window group limit (GpuWindowGroupLimitExec analog): one sort
    kernel ranks rows within their partition and emits a MASKED batch
    keeping rank <= limit — at most limit(+ties) rows per partition reach
    the window/shuffle above. Purely an optimization; the exact rank
    filter above still applies."""

    produces_masked = True

    def __init__(self, child: TpuExec, partition_exprs, orders,
                 rank_kind: str, limit: int):
        super().__init__()
        self.children = (child,)
        self.partition_exprs = list(partition_exprs)
        self.orders = list(orders)
        self.rank_kind = rank_kind
        self.limit = int(limit)

    def output_schema(self):
        return self.children[0].output_schema()

    def describe(self):
        return f"TpuWindowGroupLimit[{self.rank_kind} <= {self.limit}]"

    def execute_masked(self):
        from spark_rapids_tpu.runtime.retry import with_retry
        for batch in self.children[0].execute_masked():
            yield from with_retry(batch, self._limit_batch)

    def _limit_batch(self, table: DeviceTable) -> DeviceTable:
        from spark_rapids_tpu.dispatch import prep_aux, tpu_jit
        from spark_rapids_tpu.ops.expr import shared_traces
        pctx = PrepCtx(table)
        pp = [TpuWindowExec._prep_tree(e, pctx)
              for e in self.partition_exprs]
        op = [TpuWindowExec._prep_tree(o.expr, pctx) for o in self.orders]
        cols = tuple(DevVal(c.data, c.validity) for c in table.columns)
        aux = prep_aux(pctx)
        capacity = table.capacity
        self._traces = shared_traces(
            ("wingrouplimit", self.rank_kind, self.limit,
             tuple(e.key() for e in self.partition_exprs),
             tuple((o.expr.key(), o.ascending, o.resolved_nulls_first())
                   for o in self.orders),
             table.schema_key()[0]))
        has_mask = table.live is not None
        tkey = (capacity, has_mask,
                tuple(_prep_trace_key(x) for x in pp),
                tuple(_prep_trace_key(x) for x in op))
        fn = self._traces.get(tkey)
        if fn is None:
            fn = tpu_jit(self._build_kernel(capacity, pp, op))
            self._traces[tkey] = fn
        keep, nkeep = fn(cols, aux, table.nrows_dev, table.live)
        self.add_metric("groupLimitBatches", 1)
        return DeviceTable(table.names, table.columns, nkeep, capacity,
                           live=keep)

    def _build_kernel(self, capacity: int, pp, op):
        part_exprs = self.partition_exprs
        orders = self.orders
        rank_kind = self.rank_kind
        limit = self.limit

        def kernel(cols, aux, nrows, live_in):
            def eval_tree(e, preps):
                ctx = EvalCtx(cols, aux, nrows, capacity, live=live_in)
                ctx._prep_iter = iter(preps)
                return _walk_eval(e, ctx)

            if live_in is not None:
                live = live_in
            else:
                live = jnp.arange(capacity, dtype=jnp.int32) < nrows
            from spark_rapids_tpu.execs.sort import _directional
            from spark_rapids_tpu.ops.ordering import comparable_operands
            operands = [(~live).astype(jnp.int32)]
            part_ops = []
            for e, preps in zip(part_exprs, pp):
                kv = eval_tree(e, preps)
                from spark_rapids_tpu.ops.ordering import zero_invalid
                part_ops.append((~kv.validity).astype(jnp.int32))
                part_ops.extend(comparable_operands(
                    zero_invalid(kv.data, kv.validity)))
            operands.extend(part_ops)
            n_part_ops = len(part_ops)
            order_ops = []
            for o, preps in zip(orders, op):
                kv = eval_tree(o.expr, preps)
                order_ops.extend(_directional(
                    kv.data, kv.validity, o.ascending,
                    o.resolved_nulls_first(), capacity))
            operands.extend(order_ops)
            payload = jnp.arange(capacity, dtype=jnp.int32)
            res = jax.lax.sort(operands + [payload],
                               num_keys=len(operands))
            perm = res[-1]
            s_live = live[perm]
            first = jnp.arange(capacity) == 0
            new_part = first
            for so in res[1:1 + n_part_ops]:
                new_part = new_part | (so != jnp.roll(so, 1))
            new_peer = new_part
            for so in res[1 + n_part_ops:-1]:
                new_peer = new_peer | (so != jnp.roll(so, 1))
            idx = jnp.arange(capacity, dtype=jnp.int32)
            part_start = _seg_scan_max(jnp.where(new_part, idx, 0))
            if rank_kind == "rownumber":
                rank = idx - part_start + 1
            elif rank_kind == "rank":
                peer_start = _seg_scan_max(jnp.where(new_peer, idx, 0))
                rank = peer_start - part_start + 1
            else:  # denserank
                rank = _segmented_cumsum(
                    new_peer.astype(jnp.int32), part_start)
            keep_sorted = s_live & (rank <= limit)
            keep = jnp.zeros(capacity, jnp.bool_).at[perm].set(keep_sorted)
            return keep, jnp.sum(keep.astype(jnp.int32))

        return kernel
