"""Window functions and specs.

Reference: the window/ package (SURVEY.md §2.3 — GpuWindowExec + specialized
iterators: running window, batched bounded, unbounded-to-unbounded) and the
WindowExpression/WindowSpecDefinition expressions (Appendix A).

Frames: ("rows" | "range", lo, hi) with None = unbounded, 0 = current row,
negative = preceding, positive = following. Spark defaults: with an ORDER BY
the frame is RANGE UNBOUNDED PRECEDING..CURRENT ROW; without it the frame is
the whole partition."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops.expr import Expression
from spark_rapids_tpu.plan.nodes import SortOrder


class WindowSpec:
    """Builder: Window.partition_by(...).order_by(...).rows_between(a, b)."""

    def __init__(self, partition_by: Sequence[Expression] = (),
                 order_by: Sequence[SortOrder] = (),
                 frame: Optional[Tuple[str, Optional[int], Optional[int]]] = None):
        self.partition_exprs = list(partition_by)
        self.orders = list(order_by)
        self.frame = frame

    def partition_by(self, *cols) -> "WindowSpec":
        from spark_rapids_tpu.ops.expr import col
        exprs = [col(c) if isinstance(c, str) else c for c in cols]
        return WindowSpec(exprs, self.orders, self.frame)

    def order_by(self, *cols, ascending: bool = True) -> "WindowSpec":
        from spark_rapids_tpu.ops.expr import col
        orders = []
        for c in cols:
            if isinstance(c, SortOrder):
                orders.append(c)
            else:
                e = col(c) if isinstance(c, str) else c
                orders.append(SortOrder(e, ascending))
        return WindowSpec(self.partition_exprs, orders, self.frame)

    def rows_between(self, lo: Optional[int], hi: Optional[int]) -> "WindowSpec":
        return WindowSpec(self.partition_exprs, self.orders, ("rows", lo, hi))

    def range_between(self, lo: Optional[int], hi: Optional[int]) -> "WindowSpec":
        return WindowSpec(self.partition_exprs, self.orders, ("range", lo, hi))

    def resolved_frame(self) -> Tuple[str, Optional[int], Optional[int]]:
        if self.frame is not None:
            return self.frame
        if self.orders:
            return ("range", None, 0)  # Spark default with ORDER BY
        return ("rows", None, None)


#: Spark-style entry: Window.partition_by(...)
class Window:
    unbounded_preceding = None
    unbounded_following = None
    current_row = 0

    @staticmethod
    def partition_by(*cols) -> WindowSpec:
        return WindowSpec().partition_by(*cols)

    @staticmethod
    def order_by(*cols, **kw) -> WindowSpec:
        return WindowSpec().order_by(*cols, **kw)


class WindowFunction(Expression):
    """Base of rank/offset window functions (not evaluable standalone)."""

    needs_order = True

    def over(self, spec: WindowSpec) -> "WindowExpression":
        return WindowExpression(self, spec)


class RowNumber(WindowFunction):
    children = ()

    @property
    def data_type(self):
        return T.INT

    def key(self):
        return ("row_number",)

    def with_children(self, children):
        return self


class Rank(WindowFunction):
    children = ()

    @property
    def data_type(self):
        return T.INT

    def key(self):
        return ("rank",)

    def with_children(self, children):
        return self


class DenseRank(WindowFunction):
    children = ()

    @property
    def data_type(self):
        return T.INT

    def key(self):
        return ("dense_rank",)

    def with_children(self, children):
        return self


class PercentRank(WindowFunction):
    """percent_rank() = (rank - 1) / (partition rows - 1); 0 for single-row
    partitions (reference: GpuWindowExpression rank family)."""

    children = ()

    @property
    def data_type(self):
        return T.DOUBLE

    def key(self):
        return ("percentrank",)

    def with_children(self, children):
        return self


class NthValue(WindowFunction):
    """nth_value(e, n) over the default running frame: the n-th row's value
    of the partition, visible once the frame reaches it (reference:
    GpuNthValue)."""

    def __init__(self, child: Expression, n: int, ignore_nulls: bool = False):
        self.children = (child,)
        self.n = int(n)
        self.ignore_nulls = bool(ignore_nulls)
        if self.n < 1:
            raise ValueError("nth_value n must be >= 1")

    @property
    def data_type(self):
        return self.children[0].data_type

    def key(self):
        return ("nthvalue", self.n, self.ignore_nulls,
                self.children[0].key())

    def with_children(self, children):
        return NthValue(children[0], self.n, self.ignore_nulls)


class Lag(WindowFunction):
    def __init__(self, child: Expression, offset: int = 1, default=None):
        self.children = (child,)
        self.offset = offset
        self.default = default

    @property
    def data_type(self):
        return self.children[0].data_type

    def key(self):
        return ("lag", self.children[0].key(), self.offset, self.default)

    def with_children(self, children):
        return Lag(children[0], self.offset, self.default)


class Lead(WindowFunction):
    def __init__(self, child: Expression, offset: int = 1, default=None):
        self.children = (child,)
        self.offset = offset
        self.default = default

    @property
    def data_type(self):
        return self.children[0].data_type

    def key(self):
        return ("lead", self.children[0].key(), self.offset, self.default)

    def with_children(self, children):
        return Lead(children[0], self.offset, self.default)


class WindowExpression(Expression):
    """function OVER spec. Carries the bound spec; binding descends into the
    function child, partition exprs and order exprs."""

    def __init__(self, function: Expression, spec: WindowSpec):
        self.function = function
        self.spec = spec
        self.children = tuple(function.children)

    @property
    def data_type(self):
        return self.function.data_type

    def key(self):
        # Must capture EVERYTHING that shapes the traced kernel — function
        # (incl. the aggregate's child ordinals), partition exprs, and
        # orders — because window traces are shared process-wide
        # (shared_traces); a weak key silently reuses another query's
        # compiled kernel.
        frame = self.spec.resolved_frame()
        return ("winexpr", self.function.key(),
                tuple(p.key() for p in self.spec.partition_exprs),
                tuple((o.expr.key(), o.ascending, o.resolved_nulls_first())
                      for o in self.spec.orders),
                frame)

    def bind(self, schema):
        if isinstance(self.function, agg.AggregateFunction):
            fn = type(self.function)(self.function.child.bind(schema)) \
                if self.function.child is not None else self.function
            if isinstance(fn, (agg.Average, agg.StddevPop, agg.StddevSamp,
                               agg.VariancePop, agg.VarianceSamp)) and \
                    fn.child is not None and \
                    isinstance(fn.child.data_type, T.DecimalType):
                # DOUBLE-typed moments over UNSCALED decimal buffers would
                # come out in unscaled units (the lint-era probe caught
                # window avg(decimal(4,2)) of [1,2] = 150.0); one Cast at
                # the bind chokepoint fixes every frame mode on both the
                # CPU and device paths
                from spark_rapids_tpu.ops.cast import Cast
                fn = type(fn)(Cast(fn.child, T.DOUBLE))
        else:
            bound_children = [c.bind(schema) for c in self.function.children]
            fn = self.function.with_children(bound_children) \
                if bound_children else self.function
        spec = WindowSpec(
            [p.bind(schema) for p in self.spec.partition_exprs],
            [SortOrder(o.expr.bind(schema), o.ascending, o.nulls_first)
             for o in self.spec.orders],
            self.spec.frame)
        return WindowExpression(fn, spec)


# -- CPU oracle -------------------------------------------------------------

def eval_window_cpu(table: HostTable, wexpr: WindowExpression) -> HostColumn:
    """Numpy reference for every supported window function (the fallback
    path and the test oracle). Rows are processed in (partition, order)
    sorted position but results return in the INPUT row order, matching
    Spark's WindowExec + downstream ordering behavior."""
    n = table.num_rows
    spec = wexpr.spec
    fn = wexpr.function

    # partition codes
    if spec.partition_exprs:
        pcols = [p.eval_cpu(table) for p in spec.partition_exprs]
        pkeys = []
        for c in pcols:
            vals = np.where(c.validity, c.data, None if c.data.dtype == object else 0)
            pkeys.append([(bool(c.validity[i]), vals[i]) for i in range(n)])
        part_of = {}
        pid = np.zeros(n, dtype=np.int64)
        for i in range(n):
            key = tuple(pk[i] for pk in pkeys)
            pid[i] = part_of.setdefault(key, len(part_of))
    else:
        pid = np.zeros(n, dtype=np.int64)

    # sorted order within partitions
    from spark_rapids_tpu.plan.nodes import _stable_sort_indices
    if spec.orders:
        ocols = [o.expr.eval_cpu(table) for o in spec.orders]
        order_idx = _stable_sort_indices(
            [HostColumn(T.LONG, pid, np.ones(n, dtype=np.bool_))] + ocols,
            [SortOrder(None, True)] + list(spec.orders), n)
    else:
        ocols = []
        order_idx = np.argsort(pid, kind="stable")

    frame = spec.resolved_frame()

    # peer flags (for rank/range frames): equal order-key values
    def order_tuple(i):
        return tuple(
            (bool(c.validity[i]), None if not c.validity[i] else c.data[i])
            for c in ocols) if spec.orders else ()

    result = np.empty(n, dtype=object)
    valid = np.ones(n, dtype=np.bool_)

    pos = 0
    while pos < n:
        # find partition run in sorted order
        p = pid[order_idx[pos]]
        end = pos
        while end < n and pid[order_idx[end]] == p:
            end += 1
        rows = order_idx[pos:end]
        m = len(rows)

        if isinstance(fn, RowNumber):
            for j, r in enumerate(rows):
                result[r] = j + 1
        elif isinstance(fn, (Rank, DenseRank)):
            rank = 0
            dense = 0
            prev = object()
            for j, r in enumerate(rows):
                cur = order_tuple(r)
                if cur != prev:
                    rank = j + 1
                    dense += 1
                    prev = cur
                result[r] = rank if isinstance(fn, Rank) else dense
        elif isinstance(fn, PercentRank):
            rank = 0
            prev = object()
            for j, r in enumerate(rows):
                cur = order_tuple(r)
                if cur != prev:
                    rank = j + 1
                    prev = cur
                result[r] = 0.0 if m == 1 else (rank - 1) / (m - 1)
        elif isinstance(fn, NthValue):
            if frame != ("range", None, 0):
                raise ColumnarProcessingError(
                    "nth_value supports only the default running frame")
            src = fn.children[0].eval_cpu(table)
            # default running frame (range unbounded preceding..current):
            # the nth partition row becomes visible at its peer group
            pos = fn.n - 1
            for j, r in enumerate(rows):
                # frame end = last peer of r
                e = j
                while e + 1 < m and order_tuple(rows[e + 1]) == order_tuple(r):
                    e += 1
                if pos <= e:
                    rr = rows[pos]
                    result[r] = src.data[rr] if src.validity[rr] else None
                    valid[r] = bool(src.validity[rr])
                else:
                    result[r] = None
                    valid[r] = False
        elif isinstance(fn, (Lag, Lead)):
            src = fn.children[0].eval_cpu(table)
            off = fn.offset if isinstance(fn, Lead) else -fn.offset
            for j, r in enumerate(rows):
                k = j + off
                if 0 <= k < m:
                    rr = rows[k]
                    result[r] = src.data[rr] if src.validity[rr] else None
                    valid[r] = bool(src.validity[rr])
                else:
                    result[r] = fn.default
                    valid[r] = fn.default is not None
        elif isinstance(fn, agg.AggregateFunction):
            src = fn.child.eval_cpu(table) if fn.child is not None else None
            kind, lo, hi = frame
            # per-row frame bounds in sorted positions
            if kind == "range":
                if not ((lo is None and (hi == 0 or hi is None))):
                    raise ColumnarProcessingError(
                        "only UNBOUNDED..CURRENT/UNBOUNDED range frames supported")
            for j, r in enumerate(rows):
                if kind == "rows":
                    a = 0 if lo is None else max(0, j + lo)
                    b = m - 1 if hi is None else min(m - 1, j + hi)
                else:  # range: unbounded preceding .. current-row peers / unbounded
                    a = 0
                    if hi is None:
                        b = m - 1
                    else:  # current row incl peers
                        b = j
                        while b + 1 < m and order_tuple(rows[b + 1]) == order_tuple(r):
                            b += 1
                window_rows = rows[a:b + 1] if b >= a else rows[0:0]
                result[r], valid[r] = _agg_window_cpu(fn, src, window_rows)
        else:
            raise ColumnarProcessingError(
                f"window function {type(fn).__name__} unsupported")
        pos = end

    dt = wexpr.data_type
    if isinstance(dt, T.StringType):
        data = np.array([result[i] if valid[i] else None for i in range(n)],
                        dtype=object)
        return HostColumn(dt, data, valid)
    np_dt = dt.np_dtype
    data = np.array([result[i] if valid[i] and result[i] is not None else 0
                     for i in range(n)], dtype=np_dt)
    valid = valid & np.array([result[i] is not None for i in range(n)])
    return HostColumn(dt, data, valid)


def _agg_window_cpu(fn, src, rows):
    if isinstance(fn, agg.Count):
        if fn.child is None:
            return len(rows), True
        return int(np.sum(src.validity[rows])), True
    vals = [src.data[r] for r in rows if src.validity[r]]
    if not vals:
        return None, False
    if isinstance(fn, agg.Sum):
        if isinstance(fn.data_type, T.LongType):
            # exact python sum, wrapped to int64 like Spark non-ANSI overflow
            total = sum(int(v) for v in vals)
            return ((total + (1 << 63)) % (1 << 64)) - (1 << 63), True
        return float(sum(float(v) for v in vals)), True
    if isinstance(fn, agg.Min):
        return min(vals), True
    if isinstance(fn, agg.Max):
        return max(vals), True
    if isinstance(fn, agg.Average):
        return float(sum(float(v) for v in vals)) / len(vals), True
    raise ColumnarProcessingError(f"window agg {type(fn).__name__}")


def row_number() -> RowNumber:
    return RowNumber()


def rank() -> Rank:
    return Rank()


def dense_rank() -> DenseRank:
    return DenseRank()


def lag(e, offset: int = 1, default=None) -> Lag:
    from spark_rapids_tpu.ops.expr import col
    return Lag(col(e) if isinstance(e, str) else e, offset, default)


def lead(e, offset: int = 1, default=None) -> Lead:
    from spark_rapids_tpu.ops.expr import col
    return Lead(col(e) if isinstance(e, str) else e, offset, default)
