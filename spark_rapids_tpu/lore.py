"""LORE — Local Operator Replay (reference: lore/GpuLore.scala,
GpuLoreDumpExec / GpuLoreReplayExec; SURVEY.md §2.10).

The reference assigns every GPU operator a LORE id, dumps a tagged
operator's input batches + plan meta to a directory during a real run, and
can later re-execute JUST that operator from the dump. Same shape here:

* every converted exec gets a ``lore_id`` (pre-order over the exec tree),
  shown in ``session.explain`` output;
* ``spark.rapids.sql.lore.idsToDump`` = comma-separated ids;
  ``spark.rapids.sql.lore.dumpPath`` = target directory. During execution
  each tagged exec's child batches are tee'd to
  ``<path>/lore-<id>/input-<child>/batch-<n>.pkl`` (host-side pickles) and
  the exec itself is pickled (jitted kernel caches stripped — they rebuild
  lazily) with a meta.json describing the operator;
* ``replay(dump_dir)`` reloads the exec, replaces its children with scans
  over the dumped batches, re-runs it, and returns the result HostTable —
  in a fresh process if desired.
"""

from __future__ import annotations

import json
import os
import pickle
from typing import List, Optional

from spark_rapids_tpu.columnar import HostTable

#: attributes holding per-process jit/kernel caches — stripped before
#: pickling, rebuilt lazily on first execute after unpickle
_STRIP_ATTRS = ("_traces", "_filter_kernel", "_kernel", "metrics", "_cached")


def assign_lore_ids(executable) -> None:
    """Pre-order numbering over the converted tree (TpuExec and
    transition/adapter wrappers all get ids so explain can show them)."""
    counter = [0]

    def walk(e):
        counter[0] += 1
        e._lore_id = counter[0]
        for c in getattr(e, "children", ()):
            walk(c)
        for attr in ("source", "tpu_exec", "cpu_node", "scan_node"):
            nxt = getattr(e, attr, None)
            if nxt is not None:
                walk(nxt)

    walk(executable)


def _iter_tree(e):
    yield e
    for c in getattr(e, "children", ()):
        yield from _iter_tree(c)
    for attr in ("source", "tpu_exec", "cpu_node", "scan_node"):
        nxt = getattr(e, attr, None)
        if nxt is not None:
            yield from _iter_tree(nxt)


class _TeeChild:
    """Wraps a child exec: passes batches through while dumping each one
    (host-side) to the lore directory."""

    def __init__(self, inner, outdir: str):
        self.inner = inner
        self.outdir = outdir
        self.children = getattr(inner, "children", ())

    def output_schema(self):
        return self.inner.output_schema()

    def execute(self):
        os.makedirs(self.outdir, exist_ok=True)
        for i, batch in enumerate(self.inner.execute()):
            host = batch.to_host_per_column() if hasattr(
                batch, "to_host_per_column") else batch
            with open(os.path.join(self.outdir, f"batch-{i}.pkl"), "wb") as f:
                pickle.dump(host, f)
            yield batch

    def execute_masked(self):
        # the dump must record the batch as consumers would see it, so the
        # tee compacts (execute); masked passthrough would skip the dump
        return self.execute()

    def describe(self):
        return f"LoreDump[{self.inner.describe()}]"

    def tree_string(self, indent=0):
        return self.inner.tree_string(indent)


def _strip_for_pickle(exec_obj):
    import copy
    clone = copy.copy(exec_obj)
    from spark_rapids_tpu.obs.metrics import MetricSet
    for a in _STRIP_ATTRS:
        if hasattr(clone, a):
            try:
                # metrics must stay a MetricSet: add_metric routes
                # through MetricSet.add, so a plain {} would crash the
                # replayed exec's first metric record
                setattr(clone, a, None if a != "metrics" else MetricSet())
            except AttributeError:
                pass
    # fault-boundary wrappers (runtime/faults.install_fault_boundaries),
    # observation wrappers (obs/spans.install_observation) and
    # cancellation wrappers (service/query.install_cancellation) are
    # instance-attribute closures: unpicklable, and a replayed exec
    # wants the plain class methods anyway. DELETE (not None) so the
    # class methods resurface.
    for a in ("execute", "execute_masked", "execute_cpu",
              "_fault_guarded", "_obs_installed", "_obs_depth",
              "_obs_pending_rows", "_cancel_installed"):
        clone.__dict__.pop(a, None)
    # children are replaced by scans at replay; drop them from the pickle
    if hasattr(clone, "children"):
        clone.children = ()
    return clone


def install_dumpers(executable, conf) -> List[int]:
    """Wrap children of every exec whose lore id is in
    spark.rapids.sql.lore.idsToDump; returns the ids that were armed."""
    from spark_rapids_tpu.conf import LORE_DUMP_IDS, LORE_DUMP_PATH

    raw = str(conf.get_entry(LORE_DUMP_IDS) or "").strip()
    if not raw:
        return []
    path = str(conf.get_entry(LORE_DUMP_PATH) or "").strip()
    if not path:
        raise ValueError(
            "spark.rapids.sql.lore.idsToDump is set but "
            "spark.rapids.sql.lore.dumpPath is empty")
    want = {int(x) for x in raw.split(",") if x.strip()}
    armed = []
    # snapshot BEFORE arming: wrapping children mid-walk would hide a
    # tagged exec nested under another tagged exec
    for e in list(_iter_tree(executable)):
        lid = getattr(e, "_lore_id", None)
        if lid not in want or not hasattr(e, "execute"):
            continue
        outdir = os.path.join(path, f"lore-{lid}")
        os.makedirs(outdir, exist_ok=True)
        kids = list(getattr(e, "children", ()))
        e.children = tuple(
            _TeeChild(c, os.path.join(outdir, f"input-{ci}"))
            for ci, c in enumerate(kids))
        meta = {
            "lore_id": lid,
            "exec_class": type(e).__name__,
            "describe": e.describe(),
            "num_children": len(kids),
            "output_schema": [(n, str(dt)) for n, dt in e.output_schema()],
        }
        with open(os.path.join(outdir, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2)
        with open(os.path.join(outdir, "exec.pkl"), "wb") as f:
            # schema dtype OBJECTS ride along so empty-result replay can
            # build a typed empty table (meta.json only has display strings)
            pickle.dump({"exec": _strip_for_pickle(e),
                         "schema": list(e.output_schema())}, f)
        armed.append(lid)
    return armed


def replay(dump_dir: str) -> HostTable:
    """Re-execute ONE dumped operator from its lore directory (works in a
    fresh process): loads the pickled exec, replaces its children with
    scans over the dumped input batches, runs, and returns the collected
    HostTable."""
    from spark_rapids_tpu.execs.basic import TpuScanExec

    with open(os.path.join(dump_dir, "meta.json")) as f:
        meta = json.load(f)
    with open(os.path.join(dump_dir, "exec.pkl"), "rb") as f:
        payload = pickle.load(f)
    exec_obj = payload["exec"]
    schema = payload["schema"]

    kids = []
    for ci in range(meta["num_children"]):
        indir = os.path.join(dump_dir, f"input-{ci}")
        batches = []
        i = 0
        while os.path.exists(os.path.join(indir, f"batch-{i}.pkl")):
            with open(os.path.join(indir, f"batch-{i}.pkl"), "rb") as f:
                batches.append(pickle.load(f))
            i += 1
        kids.append(TpuScanExec(batches, device_cache=False))
    exec_obj.children = tuple(kids)
    from spark_rapids_tpu.obs.metrics import MetricSet
    if not isinstance(getattr(exec_obj, "metrics", None), MetricSet):
        exec_obj.metrics = MetricSet()
    # per-process kernel caches rebuild lazily; joins re-pool their kernel
    if hasattr(exec_obj, "left_keys") and getattr(exec_obj, "_kernel", 1) is None:
        from spark_rapids_tpu.execs.join import JoinKernel
        exec_obj._kernel = JoinKernel.get(len(exec_obj.left_keys))

    out = [b.to_host_per_column() if hasattr(b, "to_host_per_column") else b
           for b in exec_obj.execute()]
    if not out:
        from spark_rapids_tpu.plan.nodes import _empty_table
        return _empty_table(schema)
    return HostTable.concat(out)
