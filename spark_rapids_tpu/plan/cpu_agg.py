"""CPU (oracle/fallback) group-by aggregation with Spark-exact semantics.

Used by the Aggregate plan node's CPU path. Vectorized numpy implementation:
keys are factorized per column, combined into dense group ids, and
aggregations run via np.*.at segment updates — integer sums stay in int64
(wrapping, like Java), nulls are ignored by sum/min/max/avg, and an all-null
group yields NULL (count yields 0)."""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops.expr import Alias, Expression


def _factorize_column(col: HostColumn) -> Tuple[np.ndarray, int]:
    """Dense codes for one key column; nulls get code 0 (their own group)."""
    if isinstance(col.dtype, T.StringType):
        vals = np.where(col.validity, col.data, "")
    else:
        vals = np.where(col.validity, col.data, np.zeros((), dtype=col.data.dtype))
    uniq, codes = np.unique(vals, return_inverse=True)
    codes = codes.astype(np.int64) + 1
    codes[~col.validity] = 0
    return codes, len(uniq) + 1


def group_ids(key_cols: Sequence[HostColumn], n: int) -> Tuple[np.ndarray, np.ndarray, int]:
    """Returns (gid per row, representative row index per group in
    first-occurrence order, number of groups)."""
    if not key_cols:
        return np.zeros(n, dtype=np.int64), np.zeros(1 if n else 1, dtype=np.int64), 1
    combined = None
    for col in key_cols:
        codes, card = _factorize_column(col)
        if combined is None:
            combined = codes
        else:
            combined = combined * card + codes
            # re-densify to keep the mixed-radix product bounded
            _, combined = np.unique(combined, return_inverse=True)
            combined = combined.astype(np.int64)
    uniq, first_idx, inverse = np.unique(combined, return_index=True, return_inverse=True)
    # re-number groups by first occurrence so output order is deterministic
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty_like(order)
    rank[order] = np.arange(len(order))
    gid = rank[inverse].astype(np.int64)
    reps = first_idx[order]
    return gid, reps, len(uniq)


def _agg_one(fn: agg.AggregateFunction, value: HostColumn, gid: np.ndarray,
             ngroups: int, n: int) -> HostColumn:
    out_type = fn.data_type
    if isinstance(fn, agg.Count):
        if fn.child is None:
            cnt = np.bincount(gid, minlength=ngroups).astype(np.int64)
        else:
            cnt = np.bincount(gid[value.validity], minlength=ngroups).astype(np.int64)
        return HostColumn(T.LONG, cnt, np.ones(ngroups, dtype=np.bool_))

    valid = value.validity
    vgid = gid[valid]
    nonnull = np.bincount(vgid, minlength=ngroups).astype(np.int64)
    has_any = nonnull > 0

    if isinstance(fn, (agg.Sum, agg.Average)) or isinstance(fn, agg._CentralMoment):
        if isinstance(value.dtype, T.IntegralType) and isinstance(fn, agg.Sum):
            acc = np.zeros(ngroups, dtype=np.int64)
            with np.errstate(over="ignore"):
                np.add.at(acc, vgid, value.data[valid].astype(np.int64))
            return HostColumn(T.LONG, acc, has_any)
        if isinstance(value.dtype, T.DecimalType) and isinstance(fn, agg.Sum):
            # EXACT decimal sum (Spark semantics); overflow beyond the
            # p+10 result precision -> NULL (non-ANSI CheckOverflow)
            acc = np.zeros(ngroups, dtype=object)
            np.add.at(acc, vgid, value.data[valid].astype(object))
            bound = 10 ** out_type.precision
            fits = np.array([abs(int(x)) < bound for x in acc], dtype=bool)
            validity = has_any & fits
            if out_type.precision <= T.DecimalType.MAX_LONG_DIGITS:
                data = np.array([int(x) if ok else 0
                                 for x, ok in zip(acc, validity)],
                                dtype=np.int64)
            else:
                data = np.array([int(x) if ok else 0
                                 for x, ok in zip(acc, validity)],
                                dtype=object)
            return HostColumn(out_type, data, validity)
        data = value.data[valid].astype(np.float64)
        if isinstance(value.dtype, T.DecimalType):
            # decimal buffers hold UNSCALED ints; Average/stddev/variance
            # results are doubles in VALUE units (Spark semantics — the
            # lint-era probe caught avg(decimal(4,2)) of [1,2,3] = 200.0)
            data = data / float(10 ** value.dtype.scale)
        s = np.zeros(ngroups, dtype=np.float64)
        np.add.at(s, vgid, data)
        if isinstance(fn, agg.Sum):
            return HostColumn(T.DOUBLE, np.where(has_any, s, 0.0), has_any)
        if isinstance(fn, agg.Average):
            cnt = np.maximum(nonnull, 1)
            return HostColumn(T.DOUBLE, np.where(has_any, s / cnt, 0.0), has_any)
        # central moments
        mean = s / np.maximum(nonnull, 1)
        sq = np.zeros(ngroups, dtype=np.float64)
        np.add.at(sq, vgid, (data - mean[vgid]) ** 2)
        if isinstance(fn, (agg.VariancePop, agg.StddevPop)):
            denom = np.maximum(nonnull, 1)
            validity = has_any
        else:
            denom = np.maximum(nonnull - 1, 1)
            validity = nonnull > 1
        var = sq / denom
        out = np.sqrt(var) if isinstance(fn, (agg.StddevPop, agg.StddevSamp)) else var
        return HostColumn(T.DOUBLE, np.where(validity, out, 0.0), validity)

    if isinstance(fn, (agg.Min, agg.Max)):
        if isinstance(value.dtype, T.StringType):
            vals = np.where(valid, value.data, "")
            uniq, codes = np.unique(vals.astype(object), return_inverse=True)
            codes = codes.astype(np.int64)
            sentinel = len(uniq) if isinstance(fn, agg.Min) else -1
            acc = np.full(ngroups, sentinel, dtype=np.int64)
            if isinstance(fn, agg.Min):
                np.minimum.at(acc, vgid, codes[valid])
            else:
                np.maximum.at(acc, vgid, codes[valid])
            out = np.empty(ngroups, dtype=object)
            safe = np.clip(acc, 0, max(len(uniq) - 1, 0))
            if len(uniq):
                out[:] = uniq[safe]
            out[~has_any] = None
            return HostColumn(T.STRING, out, has_any)
        if T.is_dec128(value.dtype):
            # python-int object storage: bound sentinels beyond any p<=38
            sentinel = 10 ** 39 if isinstance(fn, agg.Min) else -(10 ** 39)
            acc = np.full(ngroups, sentinel, dtype=object)
            red = np.minimum if isinstance(fn, agg.Min) else np.maximum
            red.at(acc, vgid, value.data[valid].astype(object))
            data = np.array([int(x) if ok else 0
                             for x, ok in zip(acc, has_any)], dtype=object)
            return HostColumn(value.dtype, data, has_any)
        dt = value.dtype.np_dtype
        if np.issubdtype(dt, np.floating):
            sentinel = np.inf if isinstance(fn, agg.Min) else -np.inf
        elif dt == np.bool_:
            sentinel = True if isinstance(fn, agg.Min) else False
        else:
            info = np.iinfo(dt)
            sentinel = info.max if isinstance(fn, agg.Min) else info.min
        acc = np.full(ngroups, sentinel, dtype=dt)
        if isinstance(fn, agg.Min):
            np.minimum.at(acc, vgid, value.data[valid])
        else:
            np.maximum.at(acc, vgid, value.data[valid])
        zero = np.zeros((), dtype=dt).item()
        return HostColumn(value.dtype, np.where(has_any, acc, zero).astype(dt), has_any)

    if isinstance(fn, (agg.First, agg.Last)):
        idx = np.arange(n)
        if fn.ignore_nulls:
            pick_idx = idx[valid]
            pick_gid = vgid
        else:
            pick_idx = idx
            pick_gid = gid
        acc = np.full(ngroups, n if isinstance(fn, agg.First) else -1, dtype=np.int64)
        if isinstance(fn, agg.First):
            np.minimum.at(acc, pick_gid, pick_idx)
        else:
            np.maximum.at(acc, pick_gid, pick_idx)
        got = (acc >= 0) & (acc < n)
        safe = np.clip(acc, 0, max(n - 1, 0))
        data = value.data[safe] if n else value.data
        validity = got & value.validity[safe] if n else got
        if isinstance(value.dtype, T.StringType):
            out = np.empty(ngroups, dtype=object)
            out[:] = data
            out[~validity] = None
            return HostColumn(value.dtype, out, validity)
        zero = np.zeros((), dtype=value.dtype.np_dtype).item()
        return HostColumn(value.dtype, np.where(validity, data, zero).astype(value.dtype.np_dtype), validity)

    if isinstance(fn, (agg.CollectList, agg.CollectSet)):
        out = np.empty(ngroups, dtype=object)
        for g in range(ngroups):
            out[g] = []
        valid = value.validity
        for i in range(n):
            if valid[i]:
                v = value.data[i]
                out[gid[i]].append(v.item() if hasattr(v, "item") else v)
        if isinstance(fn, agg.CollectSet):
            for g in range(ngroups):
                out[g] = sorted(set(out[g]))
        return HostColumn(fn.data_type, out, np.ones(ngroups, dtype=np.bool_))

    if isinstance(fn, agg.Percentile):
        outv = np.zeros(ngroups)
        validity = np.zeros(ngroups, dtype=np.bool_)
        for g in range(ngroups):
            vals = np.sort(value.data[(gid == g) & value.validity].astype(np.float64))
            if len(vals):
                k = (len(vals) - 1) * fn.percentage
                lo, hi = int(np.floor(k)), int(np.ceil(k))
                outv[g] = vals[lo] + (vals[hi] - vals[lo]) * (k - lo)
                validity[g] = True
        return HostColumn(T.DOUBLE, outv, validity)

    raise NotImplementedError(f"cpu aggregate {type(fn).__name__}")


def aggregate_cpu(table: HostTable, grouping: Sequence[Expression],
                  aggs: Sequence[Tuple[str, agg.AggregateFunction]]) -> HostTable:
    """Group ``table`` by the (bound) grouping expressions, compute the named
    aggregate functions. Returns one row per group (first-occurrence order);
    with no grouping, exactly one row (global aggregate)."""
    n = table.num_rows
    key_cols = [g.eval_cpu(table) for g in grouping]
    gid, reps, ngroups = group_ids(key_cols, n)
    if not grouping:
        reps = np.zeros(1, dtype=np.int64) if n else np.array([], dtype=np.int64)

    names: List[str] = []
    cols: List[HostColumn] = []
    from spark_rapids_tpu.ops.expr import output_name
    for i, g in enumerate(grouping):
        kc = key_cols[i]
        if n:
            if isinstance(kc.dtype, T.StringType):
                data = kc.data[reps]
            else:
                data = kc.data[reps].copy()
            cols.append(HostColumn(kc.dtype, data, kc.validity[reps].copy()))
        else:
            cols.append(HostColumn(kc.dtype, kc.data[:0], kc.validity[:0]))
        names.append(output_name(g, f"k{i}"))

    for out_name, fn in aggs:
        if fn.child is not None:
            value = fn.child.eval_cpu(table)
        else:
            value = HostColumn(T.LONG, np.zeros(n, dtype=np.int64), np.ones(n, dtype=np.bool_))
        if not grouping and n == 0:
            # global aggregate over empty input: one row, null (count: 0)
            if isinstance(fn, agg.Count):
                cols.append(HostColumn(T.LONG, np.zeros(1, dtype=np.int64), np.ones(1, dtype=np.bool_)))
            else:
                dt = fn.data_type
                if isinstance(dt, T.StringType):
                    cols.append(HostColumn(dt, np.array([None], dtype=object), np.zeros(1, dtype=np.bool_)))
                else:
                    cols.append(HostColumn(dt, np.zeros(1, dtype=dt.np_dtype), np.zeros(1, dtype=np.bool_)))
            names.append(out_name)
            continue
        ng = ngroups if (grouping or n) else 1
        res = _agg_one(fn, value, gid, ng, n)
        if not grouping and n == 0:
            res = res.slice(0, 1)
        cols.append(res)
        names.append(out_name)

    return HostTable(names, cols)
