"""Driver-mediated peer discovery for the p2p shuffle.

Reference (SURVEY.md §2.6): ``RapidsShuffleHeartbeatManager.scala`` (driver:
executors register and periodically heartbeat; each reply carries the peers
registered since the executor's last call) and
``RapidsShuffleHeartbeatEndpoint`` (executor: background heartbeat thread
that hands new peers to the transport), wired in ``Plugin.scala:436-447,
552-556``. Dead peers are evicted after missing heartbeats so fetches stop
targeting them.

TPU mapping: identical design — the pattern is transport-agnostic. The
"driver" is whatever process coordinates executors (in tests, an object;
multi-host, an RPC endpoint)."""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.shuffle.transport import PeerInfo


class ShuffleHeartbeatManager:
    """Driver side: registration order is the peer log; each executor
    remembers the log index it has seen (RapidsShuffleHeartbeatManager)."""

    def __init__(self, heartbeat_timeout_s: float = 30.0):
        self._lock = threading.Lock()
        # append-only registration log; re-registration appends a new entry
        # and supersedes the old one (indices into the log are what each
        # executor's "seen" cursor points at, so entries never move)
        self._log: List[PeerInfo] = []
        self._current: Dict[str, PeerInfo] = {}
        self._seen_index: Dict[str, int] = {}
        self._last_beat: Dict[str, float] = {}
        self.heartbeat_timeout_s = heartbeat_timeout_s

    def register_executor(self, peer: PeerInfo) -> List[PeerInfo]:
        """New executor joins; returns every OTHER live peer known so far."""
        with self._lock:
            self._log.append(peer)
            self._current[peer.executor_id] = peer
            self._seen_index[peer.executor_id] = len(self._log)
            self._last_beat[peer.executor_id] = time.monotonic()
            return [p for ex, p in self._current.items()
                    if ex != peer.executor_id and self._alive_locked(ex)]

    def heartbeat(self, executor_id: str) -> List[PeerInfo]:
        """Returns peers registered since this executor's last call."""
        with self._lock:
            if executor_id not in self._seen_index:
                raise ColumnarProcessingError(
                    f"executor {executor_id} never registered")
            self._last_beat[executor_id] = time.monotonic()
            start = self._seen_index[executor_id]
            # deliver only entries that are still the executor's CURRENT
            # registration (a superseded entry's replacement appears later
            # in the log slice anyway)
            fresh = [p for p in self._log[start:]
                     if p.executor_id != executor_id
                     and self._current.get(p.executor_id) is p]
            self._seen_index[executor_id] = len(self._log)
            return fresh

    def _alive_locked(self, executor_id: str) -> bool:
        last = self._last_beat.get(executor_id)
        return last is not None and (
            time.monotonic() - last) < self.heartbeat_timeout_s

    def live_executors(self) -> List[str]:
        with self._lock:
            return [ex for ex in self._current if self._alive_locked(ex)]

    def evict_dead(self) -> List[str]:
        """Drop executors that missed the heartbeat window; returns their
        ids (the UCX path evicts dead peers the same way). The log keeps
        their entries (cursors point into it) but they stop being current,
        so they are never handed out again."""
        with self._lock:
            dead = [ex for ex in self._current
                    if not self._alive_locked(ex)]
            for ex in dead:
                self._current.pop(ex, None)
                self._seen_index.pop(ex, None)
                self._last_beat.pop(ex, None)
            return dead


class ShuffleHeartbeatEndpoint:
    """Executor side: registers, then heartbeats on a background thread,
    handing freshly discovered peers to ``on_new_peer`` (which typically
    pre-connects the transport).

    A beat rejected because the driver evicted us (a paused-then-resumed
    executor misses its heartbeat window) invokes ``on_evicted``; the
    default re-registers so the executor REJOINS the mesh instead of
    going permanently deaf with its heartbeat thread dead."""

    def __init__(self, manager: ShuffleHeartbeatManager, me: PeerInfo,
                 on_new_peer: Callable[[PeerInfo], None],
                 interval_s: float = 5.0,
                 on_evicted: Optional[Callable[[], None]] = None):
        self.manager = manager
        self.me = me
        self.on_new_peer = on_new_peer
        self.on_evicted = on_evicted if on_evicted is not None else self.rejoin
        self.interval_s = interval_s
        self.evicted_count = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        for peer in manager.register_executor(me):
            on_new_peer(peer)

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=f"shuffle-heartbeat-{self.me.executor_id}",
            daemon=True)
        self._thread.start()

    def beat_once(self):
        for peer in self.manager.heartbeat(self.me.executor_id):
            self.on_new_peer(peer)

    def rejoin(self):
        """Default eviction response: re-register with the driver (the
        existing peers come back in the reply) and keep beating."""
        for peer in self.manager.register_executor(self.me):
            self.on_new_peer(peer)

    def beat_or_recover(self):
        """One heartbeat; a driver-forgot-us rejection triggers the
        eviction callback instead of being swallowed."""
        try:
            self.beat_once()
        except ColumnarProcessingError:
            self.evicted_count += 1
            self.on_evicted()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.beat_or_recover()

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
