"""Test fixtures. Runs JAX on a virtual 8-device CPU mesh so multi-chip
sharding logic is exercised without TPU hardware (the driver dry-runs the
real multi-chip path separately)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# the axon site package pins JAX_PLATFORMS=axon at interpreter start; the
# config update below overrides it reliably.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def session():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession()


@pytest.fixture(scope="session")
def cpu_session():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.sql.enabled": "false"})
