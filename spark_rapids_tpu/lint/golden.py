"""Golden-suite plan verification: the TPC-H q1-q22 corpus (DSL and SQL
forms, with AQE on and off) tagged, converted and verified — the lint
CLI's `--plans` stage and tier-1's test_lint coverage.

The corpus lives in scale_test.py (the ScaleTest harness); this module
only builds the plans, never executes them, so verification stays fast
enough to run on every PR."""

from __future__ import annotations

import os
import sys
from typing import Iterable, List, Tuple

from spark_rapids_tpu.lint.diagnostics import Diagnostic


def _load_scale_test():
    try:
        import scale_test
    except ImportError:
        import spark_rapids_tpu
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(spark_rapids_tpu.__file__)))
        if root not in sys.path:
            sys.path.insert(0, root)
        import scale_test
    return scale_test


def golden_tables(scale_factor: float = 0.01, seed: int = 0):
    from spark_rapids_tpu.datagen import scale_test_specs
    specs = scale_test_specs(scale_factor)
    return {name: spec.generate_table(scale_factor, seed=seed)
            for name, spec in specs.items()}


def iter_golden_plans(scale_factor: float = 0.01,
                      tables=None) -> Iterable[Tuple[str, object, object]]:
    """Yield (query_id, logical_plan, conf) for every corpus query in
    both DSL and SQL form, pre- and post-AQE conversion settings."""
    from spark_rapids_tpu.session import TpuSession
    st = _load_scale_test()
    tables = tables if tables is not None else golden_tables(scale_factor)
    for mode, build in (("dsl", st.build_queries),
                        ("sql", st.build_sql_queries)):
        for aqe in (True, False):
            session = TpuSession({
                "spark.rapids.sql.adaptive.enabled": str(aqe).lower(),
            })
            queries = build(session, tables)
            for name, fn in queries.items():
                qid = f"{name}[{mode},aqe={'on' if aqe else 'off'}]"
                yield qid, fn().plan, session.conf


def verify_golden_plans(scale_factor: float = 0.01,
                        tables=None) -> List[Diagnostic]:
    from spark_rapids_tpu.lint.plan_verifier import verify_plan
    diags: List[Diagnostic] = []
    for qid, plan, conf in iter_golden_plans(scale_factor, tables):
        for d in verify_plan(plan, conf):
            diags.append(Diagnostic(d.rule_id, f"{qid}:{d.path}",
                                    d.message, d.severity))
    return diags
