"""Device representation of STRUCT and MAP columns.

Reference surface: cuDF STRUCT/LIST columns consumed by the plugin's
complexTypeCreator.scala / collectionOperations.scala expression families
(SURVEY.md §2.3 #26). The TPU mapping keeps everything as flat padded
buffers XLA can fuse over:

* STRUCT — a bundle of per-field (data, validity) pairs sharing the parent
  row capacity, plus a struct-level validity. No row data moves to form or
  project a struct: creation bundles existing arrays, field access is a
  tuple pick (both free under XLA).
* MAP — the array layout with TWO element streams: row offsets[cap+1] into
  parallel key/value buffers (keys non-null by construction, values carry
  their own validity). Spark's map<k,v> IS array<struct<k,v>> semantically;
  splitting the streams keeps every buffer fixed-width so lookups and
  lambda transforms are plain gathers/segment ops.

Host form: structs are python tuples (collect() rows are tuples), maps are
python dicts.

Device maps/structs restrict element/field types to the fixed-width set
(is_fixed_array's element rule); anything else tags the op for CPU
fallback through the TypeSig layer (overrides/typesig.py) — the same
per-op nested-type gating the reference encodes in TypeChecks.scala."""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.errors import ColumnarProcessingError


class StructData:
    """Device payload of a struct column/value: one (data, validity) pair
    per field. Field data may itself be nested."""

    __slots__ = ("fields",)

    def __init__(self, fields: Tuple[tuple, ...]):
        self.fields = tuple(fields)


class MapData:
    """Device payload of a map column/value."""

    __slots__ = ("offsets", "kdata", "kvalid", "vdata", "vvalid")

    def __init__(self, offsets, kdata, kvalid, vdata, vvalid):
        self.offsets = offsets
        self.kdata = kdata
        self.kvalid = kvalid
        self.vdata = vdata
        self.vvalid = vvalid


# nested payloads cross jit boundaries as ordinary pytrees (registration
# rides the shim layer — the pytree API has moved across JAX releases)
from spark_rapids_tpu.shims import get_shim as _get_shim  # noqa: E402

_get_shim().register_pytree_node(
    StructData,
    lambda sd: (sd.fields, None),
    lambda _, fields: StructData(tuple(fields)))
_get_shim().register_pytree_node(
    MapData,
    lambda md: ((md.offsets, md.kdata, md.kvalid, md.vdata, md.vvalid),
                None),
    lambda _, ch: MapData(*ch))


def fixed_np_dtype(dt: T.DataType):
    """np dtype for a device-supported nested element/field type, or None."""
    if isinstance(dt, (T.StringType, T.ArrayType, T.StructType, T.MapType,
                       T.NullType)):
        return None
    try:
        return dt.np_dtype
    except Exception:
        return None


def struct_device_supported(dt: T.StructType) -> bool:
    return all(fixed_np_dtype(f.data_type) is not None for f in dt.fields)


def map_device_supported(dt: T.MapType) -> bool:
    return (fixed_np_dtype(dt.key_type) is not None
            and fixed_np_dtype(dt.value_type) is not None)


def struct_from_host(host, cap: int):
    """(StructData, validity) from a host object-array of tuples/dicts."""
    dt: T.StructType = host.dtype
    n = len(host)
    validity = np.zeros(cap, dtype=np.bool_)
    validity[:n] = host.validity
    fields = []
    for fi, f in enumerate(dt.fields):
        npdt = fixed_np_dtype(f.data_type)
        if npdt is None:
            raise ColumnarProcessingError(
                f"struct field {f.name} type {f.data_type.simple_string()} "
                "not device-representable")
        fd = np.zeros(cap, dtype=npdt)
        fv = np.zeros(cap, dtype=np.bool_)
        for i in range(n):
            if not host.validity[i]:
                continue
            row = host.data[i]
            v = row.get(f.name) if isinstance(row, dict) else row[fi]
            if v is not None:
                fd[i] = v
                fv[i] = True
        fields.append((jnp.asarray(fd), jnp.asarray(fv)))
    return StructData(tuple(fields)), jnp.asarray(validity)


def struct_to_host(dtype: T.StructType, sd: StructData, validity,
                   num_rows: int):
    from spark_rapids_tpu.columnar.column import HostColumn
    validity = np.ascontiguousarray(np.asarray(validity)[:num_rows])
    fds = [np.asarray(d)[:num_rows] for d, _ in sd.fields]
    fvs = [np.asarray(v)[:num_rows] for _, v in sd.fields]
    out = np.empty(num_rows, dtype=object)
    for i in range(num_rows):
        if validity[i]:
            out[i] = tuple(
                fds[fi][i].item() if fvs[fi][i] else None
                for fi in range(len(sd.fields)))
    return HostColumn(dtype, out, validity)


def map_from_host(host, cap: int):
    """(MapData, validity) from a host object-array of dicts (or
    (key, value) pair lists)."""
    dt: T.MapType = host.dtype
    kdt, vdt = fixed_np_dtype(dt.key_type), fixed_np_dtype(dt.value_type)
    if kdt is None or vdt is None:
        raise ColumnarProcessingError(
            f"map type {dt.simple_string()} not device-representable")
    from spark_rapids_tpu.columnar.column import bucket_for
    n = len(host)
    lengths = np.zeros(cap + 1, dtype=np.int64)
    for i in range(n):
        if host.validity[i]:
            lengths[i + 1] = len(host.data[i])
    offsets = np.cumsum(lengths).astype(np.int32)
    ecap = bucket_for(max(int(offsets[cap]), 1))
    kd = np.zeros(ecap, dtype=kdt)
    kv = np.zeros(ecap, dtype=np.bool_)
    vd = np.zeros(ecap, dtype=vdt)
    vv = np.zeros(ecap, dtype=np.bool_)
    pos = 0
    for i in range(n):
        if not host.validity[i]:
            continue
        items = (host.data[i].items() if isinstance(host.data[i], dict)
                 else host.data[i])
        for k, v in items:
            kd[pos] = k
            kv[pos] = True
            if v is not None:
                vd[pos] = v
                vv[pos] = True
            pos += 1
    validity = np.zeros(cap, dtype=np.bool_)
    validity[:n] = host.validity
    return (MapData(jnp.asarray(offsets), jnp.asarray(kd), jnp.asarray(kv),
                    jnp.asarray(vd), jnp.asarray(vv)),
            jnp.asarray(validity))


def map_to_host(dtype: T.MapType, md: MapData, validity, num_rows: int):
    from spark_rapids_tpu.columnar.column import HostColumn
    validity = np.ascontiguousarray(np.asarray(validity)[:num_rows])
    off = np.asarray(md.offsets)
    kd, kv = np.asarray(md.kdata), np.asarray(md.kvalid)
    vd, vv = np.asarray(md.vdata), np.asarray(md.vvalid)
    out = np.empty(num_rows, dtype=object)
    for i in range(num_rows):
        if validity[i]:
            s, e = int(off[i]), int(off[i + 1])
            if not kv[s:e].all():
                # a null key expression result reached a map entry — Spark
                # raises at evaluation; the device kernel cannot, so the
                # error surfaces at collect instead of as a bogus zero key
                raise ColumnarProcessingError("Cannot use null as map key")
            out[i] = {kd[j].item(): (vd[j].item() if vv[j] else None)
                      for j in range(s, e)}
    return HostColumn(dtype, out, validity)


def nested_nbytes(data) -> int:
    if isinstance(data, StructData):
        # fields are fixed-width by construction (struct_device_supported)
        return int(sum(d.size * d.dtype.itemsize + v.size
                       for d, v in data.fields))
    if isinstance(data, MapData):
        return int(data.offsets.size * 4
                   + data.kdata.size * data.kdata.dtype.itemsize
                   + data.kvalid.size
                   + data.vdata.size * data.vdata.dtype.itemsize
                   + data.vvalid.size)
    return 0
