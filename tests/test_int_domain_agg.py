"""Int-domain no-sort aggregation fast path + 32-bit limb segment min/max.

The reference aggregates through cudf hash aggregation
(GpuHashAggregateExec, aggregate.scala); the TPU engine's analog for
bounded-domain integer keys is a direct segment reduction over the value
domain, driven by upload-time column statistics (DeviceColumn.domain).
These tests pin:
  - fast-path vs sort-path result parity (nulls, negatives, multi-key)
  - domain propagation: upload -> filter -> project -> join -> concat
  - the 64-bit min/max two-pass limb reduction (NaN/inf/-0.0, i64 extremes)
  - cap fallback to the sorted path when the domain is too large
"""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.column import HostColumn
from spark_rapids_tpu.columnar.table import HostTable
from spark_rapids_tpu.execs import aggregate as agg_mod
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.plan import from_host_table
from spark_rapids_tpu.session import TpuSession


def _nullsafe_key(r):
    return tuple((x is None, x) for x in r)


def _sorted_rows(df):
    return sorted(df.collect(), key=_nullsafe_key)


def _mktable(n=8000, seed=0, kmax=700):
    rng = np.random.default_rng(seed)
    keys = rng.integers(-40, kmax, n).astype(np.int64)
    kvalid = rng.random(n) > 0.05
    vals = rng.normal(size=n) * 100
    vvalid = rng.random(n) > 0.1
    small = rng.integers(0, 5, n).astype(np.int32)
    return HostTable(
        ["k", "v", "i"],
        [HostColumn(T.LongType(), keys, kvalid),
         HostColumn(T.DoubleType(), vals, vvalid),
         HostColumn(T.IntegerType(), small)])


def _slow_session():
    return TpuSession({"spark.rapids.tpu.agg.maxKeyDomainGroups": 0,
                       "spark.rapids.tpu.agg.maxDictGroups": 0})


def _assert_rows_close(fast, slow):
    assert len(fast) == len(slow)
    for a, b in zip(fast, slow):
        assert len(a) == len(b)
        for ca, cb in zip(a, b):
            if isinstance(ca, float) and isinstance(cb, float):
                if np.isnan(cb):
                    assert np.isnan(ca)
                else:
                    assert ca == pytest.approx(cb, rel=1e-9, abs=1e-9)
            else:
                assert ca == cb


class SpyLayout:
    """Asserts the int fast layout fired (or not) during a collect."""

    def __init__(self, monkeypatch):
        self.layouts = []
        orig = agg_mod.TpuHashAggregateExec._fast_layout

        def spy(slf, grouping, key_preps, capacity):
            r = orig(slf, grouping, key_preps, capacity)
            if grouping:
                self.layouts.append(None if r is None else r[0])
            return r

        monkeypatch.setattr(agg_mod.TpuHashAggregateExec, "_fast_layout", spy)

    @property
    def int_fired(self):
        return any(l is not None and "int" in l for l in self.layouts)


def test_int_key_fast_vs_sorted_parity(monkeypatch):
    ht = _mktable()
    spy = SpyLayout(monkeypatch)
    q = lambda s: (from_host_table(ht, s).group_by("k")
                   .agg(F.sum("v").alias("s"), F.count("v").alias("c"),
                        F.min("v").alias("mn"), F.max("v").alias("mx"),
                        F.avg("v").alias("av")))
    fast = _sorted_rows(q(TpuSession()))
    assert spy.int_fired
    slow = _sorted_rows(q(_slow_session()))
    _assert_rows_close(fast, slow)
    # null-key group present (Spark groups null keys)
    assert any(r[0] is None for r in fast)


def test_multi_int_key_and_mixed_string(monkeypatch):
    rng = np.random.default_rng(3)
    n = 4000
    ht = HostTable(
        ["a", "b", "s", "v"],
        [HostColumn(T.IntegerType(), rng.integers(0, 9, n).astype(np.int32)),
         HostColumn(T.LongType(), rng.integers(-5, 60, n).astype(np.int64),
                    rng.random(n) > 0.1),
         HostColumn.from_pylist(
             [str(x) for x in rng.integers(0, 4, n)], T.StringType()),
         HostColumn(T.DoubleType(), rng.normal(size=n))])
    spy = SpyLayout(monkeypatch)
    q = lambda s: (from_host_table(ht, s).group_by("a", "b", "s")
                   .agg(F.count("v").alias("c"), F.sum("v").alias("sv")))
    fast = _sorted_rows(q(TpuSession()))
    assert spy.int_fired  # int keys compose with the string-dict kind
    slow = _sorted_rows(q(_slow_session()))
    _assert_rows_close(fast, slow)


def test_domain_survives_filter_project_join_concat(monkeypatch):
    ht = _mktable(n=3000, seed=5, kmax=300)
    dims = HostTable(
        ["k2", "name"],
        [HostColumn(T.LongType(), np.arange(-40, 300).astype(np.int64)),
         HostColumn.from_pylist(
             ["n%d" % i for i in range(340)], T.StringType())])
    spy = SpyLayout(monkeypatch)
    s = TpuSession()
    df = (from_host_table(ht, s)
          .filter(col("v") > lit(-1000.0))           # filter keeps domain
          .with_column("k2", col("k"))               # project keeps domain
          .join(from_host_table(dims, s), on=["k2"], how="inner")
          .group_by("k2").agg(F.count("v").alias("c")))
    fast = _sorted_rows(df)
    assert spy.int_fired
    df_slow = (from_host_table(ht, _slow_session())
               .filter(col("v") > lit(-1000.0))
               .with_column("k2", col("k"))
               .join(from_host_table(dims, _slow_session()), on=["k2"],
                     how="inner")
               .group_by("k2").agg(F.count("v").alias("c")))
    _assert_rows_close(fast, _sorted_rows(df_slow))


def test_large_domain_falls_back_to_sort(monkeypatch):
    rng = np.random.default_rng(11)
    n = 2000
    # domain ~2^40 >> maxKeyDomainGroups -> sorted path, still correct
    keys = rng.integers(-(2 ** 40), 2 ** 40, n).astype(np.int64)
    ht = HostTable(["k", "v"],
                   [HostColumn(T.LongType(), keys),
                    HostColumn(T.DoubleType(), rng.normal(size=n))])
    spy = SpyLayout(monkeypatch)
    out = _sorted_rows(from_host_table(ht, TpuSession()).group_by("k")
                       .agg(F.count("v").alias("c")))
    assert not spy.int_fired
    assert len(out) == len(set(keys.tolist()))


def test_segment_minmax_64_f64_special_values():
    import jax.numpy as jnp

    from spark_rapids_tpu.ops.segsum import segment_minmax_64
    sd = jnp.asarray(np.array(
        [1.5, np.nan, -np.inf, np.inf, -0.0, 0.0,
         1e300, 1e300 * (1 + 1e-15), -3.25, np.nan],
        dtype=np.float64))
    sv = jnp.asarray(np.array(
        [True, True, True, True, True, True, True, True, True, True]))
    gid = jnp.asarray(np.array([0, 0, 1, 1, 2, 2, 3, 3, 4, 4], np.int32))
    mx = np.asarray(segment_minmax_64(False, sd, sv, gid, 8))
    mn = np.asarray(segment_minmax_64(True, sd, sv, gid, 8))
    assert np.isnan(mx[0]) and mn[0] == 1.5          # NaN greatest
    assert mx[1] == np.inf and mn[1] == -np.inf
    assert mx[2] == 0.0 and mn[2] == 0.0
    # hi limbs tie at f32(1e300); the lo pass must break the tie
    assert mx[3] == 1e300 * (1 + 1e-15) and mn[3] == 1e300
    assert np.isnan(mx[4]) and mn[4] == -3.25


def test_segment_minmax_64_i64_extremes():
    import jax.numpy as jnp

    from spark_rapids_tpu.ops.segsum import segment_minmax_64
    lo, hi = np.iinfo(np.int64).min, np.iinfo(np.int64).max
    # values straddling the 32-bit limb boundary exercise the tie-break
    sd = jnp.asarray(np.array(
        [lo, hi, -1, 0, (5 << 32) | 7, (5 << 32) | 9, -(3 << 32) - 1,
         -(3 << 32) - 2], dtype=np.int64))
    sv = jnp.ones(8, dtype=bool)
    gid = jnp.asarray(np.array([0, 0, 1, 1, 2, 2, 3, 3], np.int32))
    mx = np.asarray(segment_minmax_64(False, sd, sv, gid, 8))
    mn = np.asarray(segment_minmax_64(True, sd, sv, gid, 8))
    assert mx[0] == hi and mn[0] == lo
    assert mx[1] == 0 and mn[1] == -1
    assert mx[2] == (5 << 32) | 9 and mn[2] == (5 << 32) | 7
    assert mx[3] == -(3 << 32) - 1 and mn[3] == -(3 << 32) - 2


def test_minmax_64_through_engine_split_mode():
    """Engine-level: splitF64 forced on (the TPU default) must agree with
    the exact emulated path on i64/f64 min/max + f64 sums at a large
    segment count (the batched unblocked split)."""
    ht = _mktable(n=20000, seed=9, kmax=5000)
    split = TpuSession({"spark.rapids.tpu.sum.splitF64": "true"})
    exact = TpuSession({"spark.rapids.tpu.sum.splitF64": "false"})
    q = lambda s: (from_host_table(ht, s).group_by("k")
                   .agg(F.min("v").alias("mn"), F.max("v").alias("mx"),
                        F.sum("v").alias("sv")))
    _assert_rows_close(_sorted_rows(q(split)), _sorted_rows(q(exact)))


def test_upload_sets_domain_and_structural_ops_keep_it():
    from spark_rapids_tpu.columnar.table import DeviceTable
    ht = _mktable(n=512, seed=1)
    dt = DeviceTable.from_host(ht)
    k = dt.columns[0]
    assert k.domain is not None
    lo, hi = k.domain
    vals = ht.columns[0].data[ht.columns[0].validity]
    assert lo == vals.min() and hi == vals.max()
    assert k.with_arrays(k.data, k.validity).domain == k.domain
    assert k.sliced_rows(16).domain == k.domain
    # doubles have no int domain
    assert dt.columns[1].domain is None


def test_subnormal_f64_minmax_reroutes_exact():
    """Code-review r5: values below f32 range must not collapse to 0.0 in
    the limb split — the lossy guard reroutes to the emulated reduction."""
    import jax.numpy as jnp

    from spark_rapids_tpu.ops.segsum import segment_minmax_64
    sd = jnp.asarray(np.array([1e-50, 2e-50, -1e-44, 3e-44],
                              dtype=np.float64))
    sv = jnp.ones(4, dtype=bool)
    gid = jnp.asarray(np.array([0, 0, 1, 1], np.int32))
    mn = np.asarray(segment_minmax_64(True, sd, sv, gid, 2))
    mx = np.asarray(segment_minmax_64(False, sd, sv, gid, 2))
    assert mn[0] == 1e-50 and mx[0] == 2e-50
    assert mn[1] == -1e-44 and mx[1] == 3e-44


def test_decimal_avg_sums_exactly():
    """Code-review r5: avg over decimal must not ride the lossy f64 split
    pass — the unscaled sum is exact (128-bit word sums) with one
    rounding at the final divide, on BOTH agg paths and at any sign.
    (lint-era fix: the result is in VALUE units — unscaled/10^s —
    matching Cast(decimal->double); exactness is unchanged.)"""
    n = 2000
    big = 10 ** 16 + 300
    for sign in (1, -1):
        unscaled = np.full(n, sign * big, dtype=np.int64)
        ht = HostTable(
            ["k", "d"],
            [HostColumn(T.IntegerType(), (np.arange(n) % 4).astype(np.int32)),
             HostColumn(T.DecimalType(17, 2), unscaled)])
        s = TpuSession({"spark.rapids.tpu.sum.splitF64": "true"})
        grouped = sorted(from_host_table(ht, s).group_by("k")
                         .agg(F.avg("d").alias("a")).collect())
        ungrouped = from_host_table(ht, s).agg(F.avg("d").alias("a")).collect()
        for got in [grouped[0][1], ungrouped[0][0]]:
            assert got == pytest.approx(float(sign * big) / 100.0,
                                        rel=1e-13)


def test_dec128_twos_complement_boundary_bytes():
    """Code-review r5: BigInteger.toByteArray parity at -2^(8n-1)
    boundaries (minimal two's-complement length for negatives)."""
    from spark_rapids_tpu.shuffle.hashing import (
        _dec128_twos_complement_bytes as tb)
    cases = [-128, -129, -(2 ** 15), -(2 ** 31), -(2 ** 63), -1, 127, 128,
             255, 2 ** 63 - 1, 0]
    for v in cases:
        got = tb(v)
        # independent oracle: minimal signed big-endian encoding
        length = 1
        while True:
            try:
                want = v.to_bytes(length, "big", signed=True)
                break
            except OverflowError:
                length += 1
        assert got == want, (v, got, want)
