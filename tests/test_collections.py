"""Array types on device + GenerateExec + collection expressions
(reference analog: array_test.py / generate_expr_test.py;
GpuGenerateExec.scala, collectionOperations.scala)."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.plan import from_host_table

from tests.asserts import assert_runs_on_tpu, assert_tpu_and_cpu_are_equal


def _arr_table():
    arrays = [[1, 2, 3], None, [], [4, None, 6], [7], [None], [8, 9],
              [10, 2, 10], [3], None, [5, 5, 5, 5], [11, -2]]
    ids = list(range(len(arrays)))
    return HostTable(
        ["id", "a"],
        [HostColumn.from_pylist(ids, T.INT),
         HostColumn.from_pylist(arrays, T.ArrayType(T.INT))])


def _df(sess, nb=1):
    return from_host_table(_arr_table(), sess, nb)


def test_array_scan_roundtrip(session):
    out = _df(session).collect_table()
    assert out.columns[1].to_pylist() == _arr_table().columns[1].to_pylist()


def test_explode(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select("id", F.explode(col("a")).alias("e")),
        session, cpu_session)


def test_explode_runs_on_device(session):
    assert_runs_on_tpu(
        lambda s: _df(s).select("id", F.explode(col("a")).alias("e")),
        session)


def test_posexplode(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select("id", F.posexplode(col("a")).alias("e")),
        session, cpu_session)


def test_explode_outer(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select("id", F.explode_outer(col("a")).alias("e")),
        session, cpu_session)


def test_posexplode_outer(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select("id", F.posexplode_outer(col("a")).alias("e")),
        session, cpu_session)


def test_explode_then_aggregate(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s)
        .select("id", F.explode(col("a")).alias("e"))
        .group_by("id")
        .agg(F.count().alias("n"), F.sum(col("e")).alias("se")),
        session, cpu_session)


def test_size_and_minmax(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select(
            "id", F.size(col("a")).alias("sz"),
            F.array_min(col("a")).alias("mn"),
            F.array_max(col("a")).alias("mx")),
        session, cpu_session)


def test_array_contains_and_get_item(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select(
            "id", F.array_contains(col("a"), lit(2)).alias("has2"),
            F.get_item(col("a"), lit(0)).alias("first"),
            F.get_item(col("a"), lit(5)).alias("oob")),
        session, cpu_session)


def test_sort_array(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s).select(
            "id", F.sort_array(col("a")).alias("asc"),
            F.sort_array(col("a"), asc=False).alias("desc")),
        session, cpu_session)


def test_create_array_and_explode(session, cpu_session):
    def build(s):
        from tests.data_gen import IntGen, gen_table
        df = from_host_table(gen_table(
            {"x": IntGen(min_val=0, max_val=50),
             "y": IntGen(min_val=0, max_val=50)}, 100, 13), s)
        return df.select(
            "x", F.explode(F.array(col("x"), col("y"), lit(7))).alias("e"))
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_array_through_generator_falls_back(session):
    """Selecting the array column itself past a generator is unsupported
    on device: the plan must fall back, results still correct."""
    from tests.asserts import assert_falls_back
    assert_falls_back(
        lambda s: _df(s).select("a", F.explode(col("a")).alias("e")),
        session, "Generate")


def test_array_multi_batch(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, nb=3).select("id", F.explode(col("a")).alias("e")),
        session, cpu_session)


def test_array_grouping_key_falls_back(session):
    """Grouping BY an array column is unsupported on device; results come
    from the CPU path (code-review r2: loosened schema check leak)."""
    from tests.asserts import assert_falls_back
    assert_falls_back(
        lambda s: _df(s).group_by("a").agg(F.count().alias("c")),
        session, "Aggregate")


def test_first_over_array_input_falls_back(session):
    from tests.asserts import assert_falls_back
    assert_falls_back(
        lambda s: _df(s).group_by("id").agg(F.first(col("a")).alias("f")),
        session, "Aggregate")
