"""Misc expressions: nondeterministic ids/random, float normalization
markers, null guards, timezone shifts, string hashes, concat_ws.

Reference: GpuMonotonicallyIncreasingID / GpuSparkPartitionID /
GpuRand (nondeterministicExpressions.scala), NormalizeNaNAndZero /
KnownFloatingPointNormalized (GpuNormalizeNanAndZero), AtLeastNNonNulls,
GpuFromUTCTimestamp/GpuToUTCTimestamp (+ GpuTimeZoneDB — the UTC-offset
subset runs on device, DST zones tag fallback exactly like the reference's
carve-out), Md5 (HashFunctions), ConcatWs (stringFunctions.scala).
"""

from __future__ import annotations

import datetime as _dt
import hashlib
from typing import Optional

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.common import UnaryExpression
from spark_rapids_tpu.ops.expr import (
    DevVal,
    Expression,
    Literal,
    NodePrep,
    PrepCtx,
    lit,
)

# ---------------------------------------------------------------------------
# float normalization / null guards
# ---------------------------------------------------------------------------


class NormalizeNaNAndZero(UnaryExpression):
    """-0.0 -> 0.0 and all NaNs -> one canonical NaN (Spark inserts this
    before grouping/joining on floats)."""

    @property
    def data_type(self):
        return self.children[0].data_type

    def key(self):
        return ("normnanzero", self.children[0].key())

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        d = np.where(c.data == 0.0, 0.0, c.data)
        d = np.where(np.isnan(c.data), np.nan, d)
        return HostColumn(c.dtype, d.astype(c.data.dtype), c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        d = jnp.where(c.data == 0.0, jnp.zeros_like(c.data), c.data)
        d = jnp.where(jnp.isnan(c.data), jnp.full_like(d, jnp.nan), d)
        return DevVal(d, c.validity)


class KnownFloatingPointNormalized(UnaryExpression):
    """Planner marker: input is already normalized — identity."""

    @property
    def data_type(self):
        return self.children[0].data_type

    def key(self):
        return ("knownnormalized", self.children[0].key())

    def eval_cpu(self, table):
        return self.children[0].eval_cpu(table)

    def eval_dev(self, ctx, child_vals, prep):
        return child_vals[0]


class KnownNotNull(UnaryExpression):
    """Planner marker: input is known non-null — identity with
    non-nullable typing."""

    @property
    def data_type(self):
        return self.children[0].data_type

    @property
    def nullable(self):
        return False

    def key(self):
        return ("knownnotnull", self.children[0].key())

    def eval_cpu(self, table):
        return self.children[0].eval_cpu(table)

    def eval_dev(self, ctx, child_vals, prep):
        return child_vals[0]


class AtLeastNNonNulls(Expression):
    """True when at least n of the children are non-null (Spark uses it
    for DataFrame.dropna)."""

    def __init__(self, n: int, *children: Expression):
        self.n = int(n)
        self.children = tuple(children)

    @property
    def data_type(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def key(self):
        return ("atleastnnonnulls", self.n,
                tuple(c.key() for c in self.children))

    def with_children(self, children):
        return AtLeastNNonNulls(self.n, *children)

    def eval_cpu(self, table):
        kids = [c.eval_cpu(table) for c in self.children]
        cnt = np.zeros(table.num_rows, dtype=np.int32)
        for k in kids:
            cnt += k.validity
        return HostColumn(T.BOOLEAN, cnt >= self.n,
                          np.ones(table.num_rows, dtype=np.bool_))

    def eval_dev(self, ctx, child_vals, prep):
        cnt = jnp.zeros(ctx.capacity, dtype=jnp.int32)
        for cv in child_vals:
            cnt = cnt + cv.validity.astype(jnp.int32)
        return DevVal(cnt >= self.n, jnp.ones(ctx.capacity, dtype=jnp.bool_))


# ---------------------------------------------------------------------------
# nondeterministic
# ---------------------------------------------------------------------------


#: live nondeterministic expression instances; session.execute resets them
#: so re-collecting a DataFrame reproduces the same stream (Spark rand(seed)
#: is per-query deterministic)
_NONDETERMINISTIC = None


def _register_nondeterministic(e):
    global _NONDETERMINISTIC
    if _NONDETERMINISTIC is None:
        import weakref
        _NONDETERMINISTIC = weakref.WeakSet()
    _NONDETERMINISTIC.add(e)


def reset_nondeterministic_streams() -> None:
    if _NONDETERMINISTIC is None:
        return
    for e in list(_NONDETERMINISTIC):
        e.reset_stream()


class MonotonicallyIncreasingID(Expression):
    """Per-batch monotonically increasing ids: (partition << 33) + row
    offset, continuing across batches (the engine is single-partition per
    stream, so the running row offset carries the Spark shape)."""

    position_dependent = True

    children = ()

    def __init__(self):
        self._offset = {"n": 0}
        _register_nondeterministic(self)

    def reset_stream(self):
        self._offset["n"] = 0

    @property
    def data_type(self):
        return T.LONG

    @property
    def nullable(self):
        return False

    def key(self):
        return ("monotonicid", id(self._offset))

    def with_children(self, children):
        return self

    def eval_cpu(self, table):
        n = table.num_rows
        base = self._offset["n"]
        self._offset["n"] += n
        return HostColumn(T.LONG, base + np.arange(n, dtype=np.int64))

    def prep(self, pctx: PrepCtx, child_preps):
        base = self._offset["n"]
        self._offset["n"] += pctx.table.num_rows
        slot = pctx.add_aux(np.asarray([base], dtype=np.int64),
                            intern=False)
        return NodePrep(aux_slots=(slot,))

    def eval_dev(self, ctx, child_vals, prep):
        base = ctx.aux[prep.aux_slots[0]][0]
        data = base + jnp.arange(ctx.capacity, dtype=jnp.int64)
        return DevVal(data, jnp.ones(ctx.capacity, dtype=jnp.bool_))


class SparkPartitionID(Expression):
    """Partition id of the executing task (0 in the single-stream engine;
    exchanges renumber per output partition)."""

    children = ()

    def __init__(self, pid: int = 0):
        self.pid = pid

    @property
    def data_type(self):
        return T.INT

    @property
    def nullable(self):
        return False

    def key(self):
        return ("sparkpartitionid", self.pid)

    def with_children(self, children):
        return self

    def eval_cpu(self, table):
        return HostColumn(
            T.INT, np.full(table.num_rows, self.pid, dtype=np.int32))

    def eval_dev(self, ctx, child_vals, prep):
        return DevVal(jnp.full(ctx.capacity, self.pid, dtype=jnp.int32),
                      jnp.ones(ctx.capacity, dtype=jnp.bool_))


class Rand(Expression):
    """rand([seed]) — uniform [0, 1). The stream draws ON HOST from the
    seeded generator (like GpuSampleExec's mask) so the device result is
    bit-identical to the CPU path; values ride as an aux array."""

    position_dependent = True

    children = ()

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed)
        _register_nondeterministic(self)

    def reset_stream(self):
        self._rng = np.random.default_rng(self.seed)

    @property
    def data_type(self):
        return T.DOUBLE

    @property
    def nullable(self):
        return False

    def key(self):
        # id(self): unique per instance but STABLE across reset_stream()
        # (an id on the rng object would re-trace every query)
        return ("rand", self.seed, id(self))

    def with_children(self, children):
        return self

    def eval_cpu(self, table):
        return HostColumn(T.DOUBLE, self._rng.random(table.num_rows))

    def prep(self, pctx: PrepCtx, child_preps):
        vals = np.zeros(pctx.table.capacity)
        vals[:pctx.table.num_rows] = self._rng.random(pctx.table.num_rows)
        # per-batch nondeterministic stream: interning would pin every
        # batch's values on device forever (and never hit)
        slot = pctx.add_aux(vals, intern=False)
        return NodePrep(aux_slots=(slot,))

    def eval_dev(self, ctx, child_vals, prep):
        vals = ctx.aux[prep.aux_slots[0]][:ctx.capacity]
        return DevVal(vals, jnp.ones(ctx.capacity, dtype=jnp.bool_))


# ---------------------------------------------------------------------------
# timezone shifts (UTC-offset subset on device; DST zones fall back —
# the reference's GpuTimeZoneDB carve-out pattern)
# ---------------------------------------------------------------------------


def _fixed_offset_micros(tz: str) -> Optional[int]:
    """Micros offset for fixed-offset zone spellings (UTC, GMT, +hh:mm,
    UTC+h, GMT-hh:mm); None for named/DST zones."""
    t = tz.strip()
    up = t.upper()
    if up in ("UTC", "GMT", "Z"):
        return 0
    for prefix in ("UTC", "GMT"):
        if up.startswith(prefix):
            t = t[len(prefix):]
            break
    if not t:
        return 0
    sign = 1
    if t[0] == "+":
        t = t[1:]
    elif t[0] == "-":
        sign = -1
        t = t[1:]
    else:
        return None
    parts = t.split(":")
    try:
        hh = int(parts[0])
        mm = int(parts[1]) if len(parts) > 1 else 0
        ss = int(parts[2]) if len(parts) > 2 else 0
    except ValueError:
        return None
    if hh > 18 or mm > 59 or ss > 59:
        return None
    return sign * ((hh * 3600 + mm * 60 + ss) * 1_000_000)


class _TzShift(Expression):
    to_utc = False

    def __init__(self, child: Expression, tz: Expression):
        self.children = (child, tz)

    @property
    def data_type(self):
        return T.TIMESTAMP

    def key(self):
        name = "toutc" if self.to_utc else "fromutc"
        # the zone NAME must be part of the compile key: string literals
        # key only by null-ness, but each zone bakes different transition
        # tables into the traced kernel
        tz = self.children[1]
        zone = str(tz.value) if isinstance(tz, Literal) else None
        return (name, zone, tuple(c.key() for c in self.children))

    def with_children(self, children):
        return type(self)(children[0], children[1])

    @property
    def device_supported(self):
        tz = self.children[1]
        if not isinstance(tz, Literal) or tz.value is None:
            return False
        name = str(tz.value)
        if _fixed_offset_micros(name) is not None:
            return True
        # named/DST zones: device transition tables (GpuTimeZoneDB analog)
        from spark_rapids_tpu.ops.tzdb import TimeZoneDB
        return TimeZoneDB.supported(name)

    def _offset(self) -> Optional[int]:
        tz = self.children[1]
        if not isinstance(tz, Literal) or tz.value is None:
            return None
        return _fixed_offset_micros(str(tz.value))

    def eval_cpu(self, table):
        from spark_rapids_tpu.ops import tzdb
        c = self.children[0].eval_cpu(table)
        off = self._offset()
        if off is None:
            name = str(self.children[1].value)
            data = np.asarray(c.data, dtype=np.int64)
            out = (tzdb.to_utc_micros_host(data, name) if self.to_utc
                   else tzdb.from_utc_micros_host(data, name))
            return HostColumn(T.TIMESTAMP, out, c.validity.copy())
        delta = -off if self.to_utc else off
        return HostColumn(T.TIMESTAMP, c.data + delta, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        from spark_rapids_tpu.ops import tzdb
        c, _tz = child_vals
        off = self._offset()
        if off is None:
            name = str(self.children[1].value)
            out = (tzdb.to_utc_micros_dev(c.data, name) if self.to_utc
                   else tzdb.from_utc_micros_dev(c.data, name))
            return DevVal(out, c.validity)
        delta = -off if self.to_utc else off
        return DevVal(c.data + jnp.int64(delta), c.validity)


class FromUTCTimestamp(_TzShift):
    to_utc = False


class ToUTCTimestamp(_TzShift):
    to_utc = True


# ---------------------------------------------------------------------------
# md5 / concat_ws
# ---------------------------------------------------------------------------


from spark_rapids_tpu.ops.strings import DictStringToString  # noqa: E402


class Md5(DictStringToString, UnaryExpression):
    """md5(string) -> lowercase hex digest (dictionary transform)."""

    def transform(self, s):
        return hashlib.md5(s.encode("utf-8")).hexdigest()


class ConcatWs(Expression):
    """concat_ws(sep, e1, e2, ...) — null children SKIP (unlike Concat);
    never returns null when sep is non-null. Device path: dictionary
    transform when at most one child is a non-literal string column."""

    def __init__(self, sep: Expression, *children: Expression):
        self.children = (sep,) + tuple(children)

    @property
    def data_type(self):
        return T.STRING

    @property
    def nullable(self):
        return self.children[0].nullable

    def key(self):
        return ("concatws", tuple(c.key() for c in self.children))

    def with_children(self, children):
        return ConcatWs(children[0], *children[1:])

    @property
    def device_supported(self):
        sep = self.children[0]
        if not isinstance(sep, Literal) or sep.value is None:
            return False  # null separator: CPU path returns all-null
        non_lit = [c for c in self.children[1:]
                   if not isinstance(c, Literal)]
        # the dictionary transform only applies to STRING columns
        if any(not isinstance(c.data_type, T.StringType) for c in non_lit):
            return False
        return len(non_lit) <= 1

    def eval_cpu(self, table):
        kids = [c.eval_cpu(table) for c in self.children]
        sep, vals = kids[0], kids[1:]
        n = table.num_rows
        out = np.empty(n, dtype=object)
        validity = sep.validity.copy()
        for i in range(n):
            if validity[i]:
                parts = [str(k.data[i]) for k in vals if k.validity[i]]
                out[i] = str(sep.data[i]).join(parts)
        return HostColumn(T.STRING, out, validity)

    def prep(self, pctx: PrepCtx, child_preps):
        sep = self.children[0].value
        if sep is None:
            return NodePrep(out_dict=np.array([], dtype=object))
        col_idx = None
        for j, c in enumerate(self.children[1:]):
            if not isinstance(c, Literal):
                col_idx = j
        lits = [(j, c.value) for j, c in enumerate(self.children[1:])
                if isinstance(c, Literal)]
        if col_idx is None:
            parts = [v for _, v in sorted(lits) if v is not None]
            return NodePrep(out_dict=np.array([sep.join(map(str, parts))],
                                              dtype=object),
                            extra={"constant": True})
        d = child_preps[col_idx + 1].out_dict
        if d is None:
            d = np.array([], dtype=object)
        out = np.empty(max(len(d), 1), dtype=object)
        with_col = [(j, v) for j, v in lits] + [(col_idx, None)]
        order = sorted(with_col)
        for i in range(max(len(d), 1)):
            parts = []
            for j, v in order:
                if j == col_idx:
                    parts.append(str(d[i]) if len(d) else "")
                elif v is not None:
                    parts.append(str(v))
            out[i] = sep.join(parts)
        # the version where the column value is null: skip it entirely
        no_col = sep.join(str(v) for _, v in sorted(lits) if v is not None)
        # null_code rides as aux so the trace is shared across dict sizes
        slot = pctx.add_aux(np.asarray([len(out)], dtype=np.int32))
        return NodePrep(out_dict=np.append(out, no_col), dict_sorted=False,
                        aux_slots=(slot,), extra={"col_idx": col_idx})

    def eval_dev(self, ctx, child_vals, prep):
        if prep.extra.get("constant"):
            cap = ctx.capacity
            sep_valid = self.children[0].value is not None
            return DevVal(jnp.zeros(cap, dtype=jnp.int32),
                          jnp.full(cap, sep_valid, dtype=jnp.bool_))
        col_idx = prep.extra["col_idx"]
        cv = child_vals[col_idx + 1]
        null_code = ctx.aux[prep.aux_slots[0]][0]
        codes = jnp.where(cv.validity, cv.data, null_code)
        return DevVal(codes, jnp.ones(ctx.capacity, dtype=jnp.bool_))
