"""Column pruning (reference: Spark's ColumnPruning logical rule, which the
reference plugin inherits for free by overriding PHYSICAL plans —
GpuOverrides.scala consumes already-pruned plans. This engine builds its own
logical plans, so it needs the rule itself).

On TPU the payoff is direct: every column that survives to a join is a
1M-row gather (and, on the sort path, a scatter) of emulated-64-bit halves
— measured ~10-30ms per column per operator at 1M rows (PERF.md). A q3-
style plan carries 4 dead columns through two joins; pruning removes every
gather for them.

``prune_plan(root)`` returns an equivalent plan in which each Join input
carries only the columns referenced above it (plus its own keys/condition).
The pass rewrites BOUND expressions (BoundReference ordinals), preserving
output names exactly — the root's schema is unchanged.
"""

from __future__ import annotations

from typing import FrozenSet, List, Sequence

from spark_rapids_tpu.ops.expr import Alias, BoundReference, Expression
from spark_rapids_tpu.plan import nodes as P


def _collect_refs(e: Expression, acc: set) -> None:
    if isinstance(e, BoundReference):
        acc.add(e.ordinal)
    for c in e.children:
        _collect_refs(c, acc)


def _remap(e: Expression, mapping: dict) -> Expression:
    if isinstance(e, BoundReference):
        return BoundReference(mapping[e.ordinal], e.data_type, e.nullable,
                              name_hint=e.name_hint)
    if not e.children:
        return e
    return e.with_children([_remap(c, mapping) for c in e.children])


def _keep_project(node: P.PlanNode, keep: List[int]) -> P.PlanNode:
    """Wrap ``node`` in a Project keeping columns ``keep`` (ordinal order),
    preserving names."""
    schema = node.output_schema()
    exprs = [Alias(BoundReference(i, schema[i][1], name_hint=schema[i][0]),
                   schema[i][0]) for i in keep]
    return P.Project(node, exprs)


def _visit(node: P.PlanNode, required: FrozenSet[int]):
    """Rewrite ``node`` so its output is exactly
    ``[schema[i] for i in sorted(required)]``. Returns the new node; the
    caller remaps its ordinals via ``sorted(required).index(old)``."""
    schema = node.output_schema()
    nall = len(schema)
    required = frozenset(i for i in required if i < nall)
    if not required and nall:
        required = frozenset([0])  # keep one column (row counts need one)
    kept = sorted(required)
    mapping = {o: i for i, o in enumerate(kept)}

    if isinstance(node, P.Project):
        exprs = [node.exprs[i] for i in kept]
        names = [node.names[i] for i in kept]
        creq: set = set()
        for e in exprs:
            _collect_refs(e, creq)
        child = _visit(node.children[0], frozenset(creq))
        cmap = {o: i for i, o in enumerate(sorted(
            o for o in creq if o < len(node.children[0].output_schema())))}
        new = P.Project(child, [Alias(_remap_strip(e, cmap), n)
                                for e, n in zip(exprs, names)])
        return new

    if isinstance(node, P.Filter):
        creq: set = set(kept)
        _collect_refs(node.condition, creq)
        child = _visit(node.children[0], frozenset(creq))
        ckept = sorted(frozenset(i for i in creq if i < nall) or {0})
        cmap = {o: i for i, o in enumerate(ckept)}
        new = P.Filter(child, _remap(node.condition, cmap))
        if ckept != kept:
            new = _keep_project(new, [cmap[o] for o in kept])
        return new

    if isinstance(node, P.Join):
        nl = len(node.children[0].output_schema())
        semi = node.join_type in ("leftsemi", "leftanti")
        lreq: set = set(o for o in kept if o < nl)
        rreq: set = set(o - nl for o in kept if o >= nl)
        for k in node.left_keys:
            _collect_refs(k, lreq)
        for k in node.right_keys:
            _collect_refs(k, rreq)
        if node.condition is not None:
            cond_refs: set = set()
            _collect_refs(node.condition, cond_refs)
            lreq |= {o for o in cond_refs if o < nl}
            rreq |= {o - nl for o in cond_refs if o >= nl}
        left = _visit(node.children[0], frozenset(lreq))
        right = _visit(node.children[1], frozenset(rreq))
        lkept = sorted(frozenset(
            o for o in lreq if o < nl) or {0})
        rkept = sorted(frozenset(
            o for o in rreq
            if o < len(node.children[1].output_schema())) or {0})
        lmap = {o: i for i, o in enumerate(lkept)}
        rmap = {o: i for i, o in enumerate(rkept)}
        jmap = dict(lmap)
        for o, i in rmap.items():
            jmap[o + nl] = len(lkept) + i
        cond = (_remap(node.condition, jmap)
                if node.condition is not None else None)
        new = P.Join(left, right, node.join_type,
                     [_remap(k, lmap) for k in node.left_keys],
                     [_remap(k, rmap) for k in node.right_keys], cond)
        out_idx = [jmap[o] for o in kept]
        out_all = list(range(len(lkept) + (0 if semi else len(rkept))))
        if out_idx != out_all:
            new = _keep_project(new, out_idx)
        return new

    if isinstance(node, P.Aggregate):
        creq: set = set()
        for g in node.grouping:
            _collect_refs(g, creq)
        for _, fn in node.agg_specs:
            _collect_refs(fn, creq)
        child = _visit(node.children[0], frozenset(creq))
        ckept = sorted(frozenset(
            o for o in creq
            if o < len(node.children[0].output_schema())) or {0})
        cmap = {o: i for i, o in enumerate(ckept)}
        new = P.Aggregate.__new__(P.Aggregate)
        new.children = (child,)
        new.grouping = [_remap(g, cmap) for g in node.grouping]
        new.agg_specs = [(n, _remap(fn, cmap)) for n, fn in node.agg_specs]
        new.grouping_names = list(node.grouping_names)
        if kept != list(range(nall)):
            new = _keep_project(new, kept)
        return new

    if isinstance(node, (P.Sort, P.TakeOrderedAndProject)):
        is_topk = isinstance(node, P.TakeOrderedAndProject)
        creq: set = set()
        for o in node.orders:
            _collect_refs(o.expr, creq)
        if is_topk and node.project is not None:
            proj = [node.project[i] for i in kept]
            names = [node.project_names[i] for i in kept]
            for e in proj:
                _collect_refs(e, creq)
        else:
            creq |= set(kept)
        child = _visit(node.children[0], frozenset(creq))
        ckept = sorted(frozenset(
            o for o in creq
            if o < len(node.children[0].output_schema())) or {0})
        cmap = {o: i for i, o in enumerate(ckept)}
        orders = [P.SortOrder(_remap(o.expr, cmap), o.ascending,
                              o.nulls_first) for o in node.orders]
        if is_topk:
            new = P.TakeOrderedAndProject.__new__(P.TakeOrderedAndProject)
            new.children = (child,)
            new.orders = orders
            new.limit = node.limit
            if node.project is not None:
                new.project = [_remap_strip(e, cmap) for e in proj]
                new.project_names = names
                return new
            new.project = None
            new.project_names = None
            if ckept != kept:
                new = _keep_project(new, [cmap[o] for o in kept])
            return new
        new = P.Sort.__new__(P.Sort)
        new.children = (child,)
        new.orders = orders
        new.global_sort = node.global_sort
        if ckept != kept:
            new = _keep_project(new, [cmap[o] for o in kept])
        return new

    if isinstance(node, (P.Limit, P.CollectLimit)):
        child = _visit(node.children[0], required)
        new = type(node)(child, node.limit)
        return new

    if isinstance(node, P.Union):
        kids = [_visit(c, required) for c in node.children]
        # each child now outputs exactly sorted(required) — schemas align
        return P.Union(kids)

    # conservative default: keep the node whole, prune nothing below it
    if kept == list(range(nall)):
        return node
    return _keep_project(node, kept)


def _remap_strip(e: Expression, cmap: dict) -> Expression:
    """Remap refs; tolerate an outer Alias (rebuild preserves out_name)."""
    if isinstance(e, Alias):
        return Alias(_remap(e.children[0], cmap), e.out_name)
    return _remap(e, cmap)


def prune_plan(root: P.PlanNode) -> P.PlanNode:
    """Apply column pruning below the root; the root's schema is unchanged
    (names, order, types)."""
    try:
        n = len(root.output_schema())
        return _visit(root, frozenset(range(n)))
    except Exception:
        # pruning is an optimization — never fail a query over it
        return root
