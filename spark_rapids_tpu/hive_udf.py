"""Hive UDF support.

Reference: ``org/apache/spark/sql/hive/rapids/hiveUDFs.scala:44,60``
(GpuHiveSimpleUDF / GpuHiveGenericUDF) — the plugin wraps a Hive
``UDF``/``GenericUDF`` object and either runs its RapidsUDF columnar
path on device or evaluates the original row-wise function with
columnar transport around it.

TPU mapping: there is no JVM, so the "Hive function class" is a Python
callable registered under a function name (the ``CREATE TEMPORARY
FUNCTION name AS 'class'`` analog). A *simple* UDF is row-at-a-time —
``fn(*scalar args) -> scalar`` (Hive UDF.evaluate contract); a *generic*
UDF receives whole columns as pandas Series (the batch-level
ObjectInspector analog) and returns an aligned Series. Both evaluate on
HOST between device columnar batches via the ArrowEvalPython transport
(device → Arrow → fn → Arrow → device), exactly the reference's
fallback evaluation shape, and each carries its own expression
kill-switch so disabling it reports a per-op fallback reason."""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import register_op_kill_switch
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.plan.pandas_udf import PandasUDFExpr, _normalize_schema

for _cls, _doc in (("HiveSimpleUDF", "row-at-a-time Hive UDFs"),
                   ("HiveGenericUDF", "batch-level Hive GenericUDFs")):
    register_op_kill_switch(
        "expression", _cls, True,
        f"Enable {_doc} (host-evaluated with device columnar transport).")

#: name -> (callable, return type, generic?)
_HIVE_FUNCTIONS: Dict[str, Tuple[Callable, T.DataType, bool]] = {}


def register_hive_udf(name: str, fn: Callable, return_type,
                      generic: bool = False) -> None:
    """CREATE TEMPORARY FUNCTION name AS 'class' analog: make ``fn``
    callable from queries as ``hive_udf(name)(cols...)``."""
    rt = (_normalize_schema(f"x {return_type}")[0][1]
          if isinstance(return_type, str) else return_type)
    _HIVE_FUNCTIONS[name.lower()] = (fn, rt, bool(generic))


def unregister_hive_udf(name: str) -> None:
    _HIVE_FUNCTIONS.pop(name.lower(), None)


class HiveUDFExpr(PandasUDFExpr):
    """Shared base — rides the scalar-UDF extraction + ArrowEvalPython
    columnar transport; ``series_fn`` adapts the Hive contract to the
    series-level boundary."""

    hive_kind = "HiveUDF"

    def __init__(self, func_name: str, fn: Callable, return_type,
                 children: Sequence):
        series_fn = self._wrap(fn)
        # the transport consults this to apply the right kill switch /
        # fallback reason (GpuOverrides checks the wrapped class the
        # same way)
        series_fn._hive_udf_class = self.hive_kind
        super().__init__(series_fn, return_type, children, "scalar",
                         udf_name=f"{self.hive_kind}#{func_name}")
        self.func_name = func_name

    def _wrap(self, fn: Callable) -> Callable:
        raise NotImplementedError


class HiveSimpleUDF(HiveUDFExpr):
    """Row-at-a-time: fn(*scalars) -> scalar, nulls pass through as None
    (Hive UDF.evaluate semantics)."""

    hive_kind = "HiveSimpleUDF"

    def _wrap(self, fn):
        def series_fn(*cols):
            import pandas as pd
            vals = [fn(*[None if pd.isna(v) else v for v in row])
                    for row in zip(*cols)]
            return pd.Series(vals, index=cols[0].index if cols else None)
        return series_fn


class HiveGenericUDF(HiveUDFExpr):
    """Batch-level: fn(*pandas Series) -> aligned Series (the
    GenericUDF/ObjectInspector batch analog)."""

    hive_kind = "HiveGenericUDF"

    def _wrap(self, fn):
        def series_fn(*cols):
            import pandas as pd
            out = fn(*cols)
            return out if isinstance(out, pd.Series) else pd.Series(out)
        return series_fn


def hive_udf(name: str):
    """Query-side lookup: F-style factory producing the UDF expression.

    >>> register_hive_udf("my_upper", str.upper, "string")
    >>> df.select(hive_udf("my_upper")(col("s")).alias("u"))
    """
    entry = _HIVE_FUNCTIONS.get(name.lower())
    if entry is None:
        raise ColumnarProcessingError(
            f"hive function {name!r} is not registered "
            f"(known: {sorted(_HIVE_FUNCTIONS)})")
    fn, rt, generic = entry
    cls = HiveGenericUDF if generic else HiveSimpleUDF

    def call(*args):
        from spark_rapids_tpu.ops.expr import col
        exprs = [col(a) if isinstance(a, str) else a for a in args]
        return cls(name, fn, rt, exprs)
    call.__name__ = name
    return call
