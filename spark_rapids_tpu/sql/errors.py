"""SQL front-end errors with position annotation.

Reference: Spark's ParseException / AnalysisException carry the failing
line/column plus a caret snippet of the query text; the overrides layer
here reports per-construct fallback reasons the same way GpuOverrides
tags unsupported nodes. Both error classes derive from
ColumnarProcessingError so existing callers that catch engine errors
keep working.
"""

from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.errors import ColumnarProcessingError


def annotate(sql: str, line: int, col: int, msg: str) -> str:
    """Message + the offending line with a caret under (line, col).
    Positions are 1-based (the lexer's convention)."""
    lines = sql.splitlines() or [""]
    out = [msg, f"(line {line}, pos {col})"]
    if 1 <= line <= len(lines):
        out.append(lines[line - 1])
        out.append(" " * (col - 1) + "^")
    return "\n".join(out)


class SqlError(ColumnarProcessingError):
    """Base for parse/analysis errors; carries the 1-based position."""

    def __init__(self, msg: str, sql: str = "", line: int = 0, col: int = 0):
        self.raw_msg = msg
        self.line = line
        self.col = col
        super().__init__(annotate(sql, line, col, msg) if sql else msg)


class SqlParseError(SqlError):
    """Lexer/parser rejection (ParseException analog)."""


class SqlAnalysisError(SqlError):
    """Binder/lowering rejection (AnalysisException analog): unresolved
    identifiers, bad function arity, constructs outside the supported
    subset. Unsupported constructs name themselves the way overrides
    fallback reasons do ("<construct> is not supported ...")."""


def unsupported(construct: str, reason: str, sql: str = "",
                line: int = 0, col: int = 0) -> SqlAnalysisError:
    """Per-construct fallback reason, mirroring the overrides explain
    style (`! <node>  <-- <reason>`)."""
    return SqlAnalysisError(
        f"{construct} is not supported by the SQL front end: {reason}",
        sql, line, col)
