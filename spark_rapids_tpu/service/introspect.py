"""Live introspection: a loopback HTTP JSON endpoint on QueryService.

The reference plugin surfaces its metrics into the Spark UI; this
engine has no UI process, so the serving layer exposes the same live
surface as machine-readable JSON on a 127.0.0.1-only socket
(``spark.rapids.service.introspect.enabled`` / ``.port`` — port 0
binds an ephemeral port, reported as ``QueryService.introspect_port``).
``python -m spark_rapids_tpu.tools top`` polls and renders it.

Routes (all GET, all JSON):

* ``/health``     — ``QueryService.health()`` (device/mesh/cluster
  topology, ladder counters, quarantine);
* ``/topology``   — the consistent fleet-topology snapshot (hosts +
  mesh + memory + ladders under every owning lock at once — the
  shared-topology path in runtime/health.py);
* ``/stats``      — ``QueryService.stats()`` (lifecycle counters, WFQ
  clocks, result-cache stats);
* ``/slo``        — rolling per-pool / per-tenant p50/p95 latency and
  run-time percentiles over recently FINISHED handles;
* ``/queries``    — the live query table (running + queued handles);
* ``/telemetry``  — the telemetry ring tail (``?n=`` bounds it);
* ``/top``        — all of the above in one document (what the CLI
  polls — one round trip per refresh).

Every handler reads a snapshot surface that bounds its own lock hold;
the server thread can therefore never wedge a query. Loopback-only by
construction (the bind address is hardcoded): this is an operator
surface, not a network service."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


def _routes(service, path: str, query: dict) -> Optional[dict]:
    """Resolve one GET path to its JSON document (None = 404)."""
    from spark_rapids_tpu.obs.telemetry import TELEMETRY
    if path in ("/", "/top"):
        return {
            "health": service.health(),
            "stats": service.stats(),
            "slo": service.slo_snapshot(),
            "queries": service.query_table(),
            "streams": service.streams(),
            "telemetry": {
                "sampler": TELEMETRY.stats(),
                "tail": TELEMETRY.tail(
                    int(query.get("n", ["5"])[0])),
            },
        }
    if path == "/health":
        return service.health()
    if path == "/topology":
        return service.topology_snapshot()
    if path == "/stats":
        return service.stats()
    if path == "/slo":
        return service.slo_snapshot()
    if path == "/queries":
        return {"queries": service.query_table()}
    if path == "/streams":
        return {"streams": service.streams()}
    if path == "/telemetry":
        n = query.get("n")
        return {
            "sampler": TELEMETRY.stats(),
            "tail": TELEMETRY.tail(int(n[0]) if n else None),
        }
    return None


class IntrospectionServer:
    """Daemon HTTP server bound to 127.0.0.1 serving one
    QueryService's live surface. Constructed by the service when
    ``spark.rapids.service.introspect.enabled`` is set; ``port`` is
    the bound port (useful with the ephemeral default of 0)."""

    def __init__(self, service, port: int = 0):
        svc = service

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silence per-request stderr
                pass

            def do_GET(self):
                parsed = urlparse(self.path)
                try:
                    doc = _routes(svc, parsed.path,
                                  parse_qs(parsed.query))
                    status = 200 if doc is not None else 404
                    if doc is None:
                        doc = {"error": f"no route {parsed.path!r}",
                               "routes": ["/top", "/health",
                                          "/topology", "/stats",
                                          "/slo", "/queries",
                                          "/streams", "/telemetry"]}
                except Exception as exc:  # surface, never crash the srv
                    status, doc = 500, {
                        "error": f"{type(exc).__name__}: {exc}"}
                body = json.dumps(doc, sort_keys=True).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)),
                                          _Handler)
        self._httpd.daemon_threads = True
        self.port: int = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="rapids-svc-introspect", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def shutdown(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        self._thread.join(timeout=5)
