"""Load driver: the TPC-H corpus through the concurrent query service.

``python -m spark_rapids_tpu.tools loadtest`` (and
``scale_test.py --concurrency N``) fire q1-q22 across simulated tenants
at a configured worker concurrency and report the serving story the
serial harnesses cannot: aggregate wall clock vs the serial sum,
p50/p95 submit-to-finish latency, queue wait, and result-cache hit
rate — while asserting every concurrent result BIT-IDENTICAL to its
fault-free serial execution (the correctness bar every other harness in
this repo holds).

Workload shape: every tenant submits every selected query, so with T
tenants the service sees T x Q submissions. The serial comparator
models exactly what a one-at-a-time server would do with the same
T x Q request stream: the FIRST submission of each query pays the cold
(compile-inclusive) wall, the remaining T-1 pay the warm wall —
serialSumS = sum(cold) + (T-1) * sum(warm). The concurrent side pays
the same per-query compiles (on misses), so the speedup and the
below-serial-sum acceptance gate compare like for like; both
components are reported separately.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, List, Optional

#: recovery-work ceilings a service-chaos run asserts (whole-run; the
#: fault schedule is COUNT-based, so recovery work is bounded by
#: construction — a violation means a retry loop ran away)
SERVICE_CHAOS_BOUNDS = {
    "deviceReinits": 8,
    "workersLost": 8,
    "workersRespawned": 8,
    "requeued": 24,
    "hardTimeouts": 8,
}

#: handle errors a chaos run accepts as TYPED survivability outcomes —
#: anything else failing a submission fails the run
_CHAOS_TYPED_ERRORS = ("HardTimeoutError", "WorkerLostError",
                       "DeviceLostError", "QueryQuarantinedError")


def service_chaos_spec(seed: int) -> str:
    """The seeded SERVICE-level fault schedule: worker deaths, device
    losses and one wedged dispatch — count-based so total disruption is
    deterministic regardless of corpus size (probabilities would scale
    chaos with load and unbound the recovery counters)."""
    return ";".join([
        f"service.worker_crash:crash:2:{seed * 100 + 1}",
        f"device.lost:device_lost:2:{seed * 100 + 2}",
        f"dispatch.wedge:wedge:1:{seed * 100 + 3}",
    ])


#: how long the injected wedge stalls its dispatch during a chaos
#: loadtest (SRT_WEDGE_SLEEP_S) — must exceed hardTimeoutMs so the
#: watchdog provably fires, with margin so the abandoned (still
#: sleeping, semaphore-holding) thread outlives the verdict
_CHAOS_WEDGE_SLEEP_S = 45.0


def service_chaos_settings(concurrency: int) -> dict:
    """The service conf a chaos run needs BESIDES the fault schedule —
    shared with ``scale_test.py --service-faults`` so the two harnesses
    cannot drift apart on the survivability contract."""
    return {
        # hard limit well under the wedge stall so the watchdog
        # provably fires, but FAR above the worst legitimate run: a
        # device loss mid-run clears every kernel cache, so
        # post-recovery queries pay cold re-traces CONCURRENTLY (every
        # worker compiling at once multiplies the ~3s p95 serial cold
        # wall several-fold) — a tight limit here reads honest
        # recovery work as a wedge and cascades worker loss
        "spark.rapids.service.hardTimeoutMs": "25000",
        # one semaphore slot per worker: the abandoned wedged thread
        # keeps sleeping INSIDE its dispatch holding a slot — with
        # slots == workers the remaining workers keep flowing (a fixed
        # slot count below the worker count would stack semaphore wait
        # into RUNNING wall and cascade hard timeouts)
        "spark.rapids.sql.concurrentGpuTasks": str(max(1, concurrency)),
        # injected faults are NOT the query's fault — a strike budget
        # above the schedule's kill count keeps an innocent template
        # out of quarantine (quarantine is pinned by its own tier-1
        # tests, which inject repeat kills into ONE template)
        "spark.rapids.service.quarantine.maxStrikes": "8",
    }


def _chaos_conf(seed: int, concurrency: int) -> dict:
    conf = {"spark.rapids.test.faults": service_chaos_spec(seed)}
    conf.update(service_chaos_settings(concurrency))
    return conf


@contextmanager
def wedge_stall_env():
    """Arm the chaos wedge stall (SRT_WEDGE_SLEEP_S) for the scope of
    one chaos run, restoring whatever was there before — shared by both
    harnesses so the stall/hard-limit relationship cannot drift."""
    import os
    before = os.environ.get("SRT_WEDGE_SLEEP_S")
    os.environ["SRT_WEDGE_SLEEP_S"] = str(_CHAOS_WEDGE_SLEEP_S)
    try:
        yield
    finally:
        if before is None:
            os.environ.pop("SRT_WEDGE_SLEEP_S", None)
        else:
            os.environ["SRT_WEDGE_SLEEP_S"] = before


def drive_health_probes(svc, make_query, *, timeout_s: float,
                        max_probes: int = 4) -> int:
    """Prove return-to-HEALTHY after a chaos run: the DEGRADED latch
    pays down on COMPLETED queries, so a loss landing on the corpus
    tail leaves nothing to pay it — drive a few probe queries, exactly
    what live traffic would do. Returns probes driven. Callers skip
    this when submissions HUNG (the run already failed; waiting out
    probe timeouts would only delay the verdict)."""
    probes = 0
    while svc.health()["state"] == "DEGRADED" and probes < max_probes:
        try:
            hp = svc.submit(make_query(), tenant="health-probe",
                            tag=f"probe{probes}")
        except Exception:
            break  # probe template quarantined/shed: report as-is
        hp.wait(timeout=timeout_s)
        probes += 1
    return probes


def _percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 1]) of a non-empty list."""
    vals = sorted(values)
    idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
    return vals[idx]


def _scope_delta(before: dict, after: dict) -> dict:
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = round(d, 4) if isinstance(d, float) else d
    return out


def run_loadtest(sf: float = 0.05, seed: int = 0, queries=None,
                 use_sql: bool = False, concurrency: int = 4,
                 tenants: int = 2, eventlog_dir: Optional[str] = None,
                 timeout_s: float = 600.0,
                 warmup_from: Optional[str] = None,
                 chaos: bool = False) -> dict:
    """Run the loadtest and return the JSON-ready report dict.
    ``report["ok"]`` is False when any result diverged from serial or
    any submission failed — callers exit non-zero on it.

    ``warmup_from``: an event-log dir to AOT-warm from first
    (``tools warmup`` in-process, sharing this run's tables/session so
    the executable cache warms by table identity) — the serial "cold"
    pass then measures warmed-cold latency; compare coldP95S against a
    run without warmup to price the warmup.

    ``chaos``: arm the seeded SERVICE-level fault schedule
    (:func:`service_chaos_spec` — worker crashes, device losses, a
    wedged dispatch) on the service session only. The run then asserts
    the survivability contract instead of all-finished: every
    submission reaches a TERMINAL state (zero hangs), every FINISHED
    result is bit-identical to the fault-free serial baseline, any
    failure carries a typed survivability error, recovery counters stay
    within SERVICE_CHAOS_BOUNDS, and the service health returns to
    HEALTHY. The report gains a ``chaos`` section with the schedule,
    fire counts, recovery counters and terminal-state census."""
    from spark_rapids_tpu.dispatch import COMPILE_SCOPE
    from spark_rapids_tpu.lint.golden import _load_scale_test
    from spark_rapids_tpu.datagen import scale_test_specs
    from spark_rapids_tpu.plan.executable_cache import EXEC_CACHE
    from spark_rapids_tpu.runtime.faults import FAULTS
    from spark_rapids_tpu.runtime.health import HEALTH, QUARANTINE
    from spark_rapids_tpu.service import QueryService
    from spark_rapids_tpu.session import TpuSession

    st = _load_scale_test()
    specs = scale_test_specs(sf)
    tables = {name: spec.generate_table(sf, seed=seed)
              for name, spec in specs.items()}

    def _conf(extra=None):
        conf = dict(extra or {})
        if eventlog_dir:
            conf["spark.rapids.sql.eventLog.enabled"] = "true"
            conf["spark.rapids.sql.eventLog.dir"] = eventlog_dir
        return conf

    build = st.build_sql_queries if use_sql else st.build_queries

    # -- serial baseline: cold once + warm for the repeat submissions -------
    serial_session = TpuSession(_conf())

    warmup_report = None
    if warmup_from:
        from spark_rapids_tpu.tools.warmup import run_warmup
        warmup_report = run_warmup(
            warmup_from, sf=sf, seed=seed, use_sql=use_sql,
            tables=tables, session=TpuSession())

    scope_t0 = dict(COMPILE_SCOPE)
    serial_queries = build(serial_session, tables)
    wanted = [q for q in (queries or list(serial_queries))]
    expected: Dict[str, object] = {}
    serial_cold: Dict[str, float] = {}
    serial_warm: Dict[str, float] = {}
    for name in wanted:
        serial_session.next_query_tag = f"{name}_serial_cold"
        t0 = time.perf_counter()
        expected[name] = serial_queries[name]().collect_table()
        serial_cold[name] = time.perf_counter() - t0
        serial_session.next_query_tag = f"{name}_serial"
        t0 = time.perf_counter()
        serial_queries[name]().collect_table()
        serial_warm[name] = time.perf_counter() - t0
    serial_sum = (sum(serial_cold.values())
                  + (tenants - 1) * sum(serial_warm.values()))
    scope_serial = dict(COMPILE_SCOPE)

    # -- concurrent run through the service ---------------------------------
    n_submissions = len(wanted) * tenants
    svc_conf = {
        "spark.rapids.service.maxConcurrentQueries": str(concurrency),
        "spark.rapids.service.queueDepth": str(max(n_submissions, 64)),
    }
    from contextlib import ExitStack
    health_before = HEALTH.snapshot()
    chaos_env = ExitStack()
    if chaos:
        svc_conf.update(_chaos_conf(seed, concurrency))
        chaos_env.enter_context(wedge_stall_env())
    svc = QueryService(_conf(svc_conf))
    svc_queries = build(svc.session, tables)
    mismatches: List[str] = []
    failures: List[str] = []
    rejected: List[str] = []
    handles = []
    hung: List[str] = []
    svc_health_live = None
    health_probes = 0
    t0 = time.perf_counter()
    try:
        with svc:
            for t in range(tenants):
                for name in wanted:
                    label = f"{name}@tenant{t}"
                    try:
                        handles.append((name, f"tenant{t}", svc.submit(
                            svc_queries[name](), tenant=f"tenant{t}",
                            tag=label)))
                    except Exception as exc:
                        # under chaos a DEGRADED shed / quarantine can
                        # refuse admission — a typed rejection IS a
                        # terminal outcome, not a hang
                        if not chaos:
                            raise
                        rejected.append(
                            f"{label}: {type(exc).__name__}: {exc}")
            for name, tenant, h in handles:
                if not h.wait(timeout=timeout_s):
                    hung.append(
                        f"{name}@{tenant}: still {h.state} after "
                        f"{timeout_s}s")
                    failures.append(hung[-1])
            if chaos and not hung:
                health_probes = drive_health_probes(
                    svc, svc_queries[wanted[0]], timeout_s=timeout_s)
            # capture health while the pool is still up (post-shutdown
            # the workers have deregistered and workerCount reads 0)
            svc_health_live = svc.health()
    finally:
        chaos_fires = FAULTS.counters() if chaos else {}
        if chaos:
            FAULTS.disarm()
        chaos_env.close()
    wall = time.perf_counter() - t0
    scope_conc = dict(COMPILE_SCOPE)

    latencies, queue_waits, per_query = [], [], {}
    cache_hits = 0
    chaos_outcomes: List[dict] = []
    for name, tenant, h in handles:
        if h.state != "FINISHED":
            typed = type(h.error).__name__ in _CHAOS_TYPED_ERRORS
            if chaos and typed:
                # survivable terminal outcome: reported, not a failure
                chaos_outcomes.append({
                    "query": f"{name}@{tenant}", "state": h.state,
                    "error": f"{type(h.error).__name__}: {h.error}",
                    "requeues": h.requeues})
                continue
            failures.append(f"{name}@{tenant}: {h.state} ({h.error})")
            continue
        diff = st.tables_differ(expected[name], h.result_table)
        if diff is not None:
            mismatches.append(f"{name}@{tenant}: {diff}")
        latencies.append(h.latency_s)
        queue_waits.append(h.queue_wait_s or 0.0)
        cache_hits += 1 if h.cache_hit else 0
        entry = per_query.setdefault(name, {
            "serialColdS": round(serial_cold[name], 4),
            "serialWarmS": round(serial_warm[name], 4), "runs": []})
        entry["runs"].append({
            "tenant": tenant, "latencyS": round(h.latency_s, 4),
            "queueWaitS": round(h.queue_wait_s or 0.0, 4),
            "cacheHit": h.cache_hit, "identical": diff is None,
            "requeues": h.requeues})

    # compile-breakdown per phase: the serial pass traces every cold
    # shape (unless warmed); the concurrent pass repeats templates and
    # must trace NOTHING new — executable-cache hit rate 1.0 on the
    # queries it executed (result-cache serves never look up)
    serial_phase = _scope_delta(scope_t0, scope_serial)
    conc_phase = _scope_delta(scope_serial, scope_conc)
    conc_lookups = (conc_phase.get("executableCacheHits", 0)
                    + conc_phase.get("executableCacheMisses", 0))
    compile_report = {
        "serialPhase": serial_phase,
        "concurrentPhase": conc_phase,
        "repeatPassNewTraces": int(conc_phase.get("kernelTraces", 0)),
        # exact-tree checkouts / lookups: a burst of one query wider
        # than the variant's tree pool converts fresh (counted a miss)
        # but still shares every compiled kernel via its template
        "executableCacheHitRate": (
            round(conc_phase.get("executableCacheHits", 0)
                  / conc_lookups, 4) if conc_lookups else None),
        # template-known / lookups: the rate that governs TRACING —
        # 1.0 means no executed query saw an unknown template, so the
        # repeat pass compiles nothing (repeatPassNewTraces 0)
        "templateHitRate": (
            round((conc_phase.get("executableCacheHits", 0)
                   + conc_phase.get("executableCacheTemplateHits", 0))
                  / conc_lookups, 4) if conc_lookups else None),
        "executableCache": EXEC_CACHE.stats(),
    }
    cold_vals = list(serial_cold.values())
    warm_vals = list(serial_warm.values())

    # -- chaos verdicts ------------------------------------------------------
    chaos_report = None
    if chaos:
        health_after = HEALTH.snapshot()
        svc_stats = svc.stats()
        svc_health = svc_health_live or svc.health()
        recovery = {
            "deviceReinits": health_after["deviceReinits"]
            - health_before["deviceReinits"],
            "deviceLost": health_after["deviceLost"]
            - health_before["deviceLost"],
            "workersLost": svc_stats["workersLost"],
            "workersRespawned": svc_stats["workersRespawned"],
            "requeued": svc_stats["requeued"],
            "hardTimeouts": svc_stats["hardTimeouts"],
        }
        bounds_violations = [
            f"{k}={recovery[k]} exceeds the chaos bound {bound}"
            for k, bound in SERVICE_CHAOS_BOUNDS.items()
            if recovery.get(k, 0) > bound]
        returned_healthy = svc_health["state"] == "HEALTHY"
        chaos_report = {
            "faultSpec": service_chaos_spec(seed),
            "faultFires": chaos_fires,
            "recovery": recovery,
            "bounds": dict(SERVICE_CHAOS_BOUNDS),
            "boundsViolations": bounds_violations,
            "typedOutcomes": chaos_outcomes,
            "rejectedSubmissions": rejected,
            "hungSubmissions": hung,
            "quarantine": QUARANTINE.snapshot(),
            "healthAtEnd": svc_health,
            "healthProbes": health_probes,
            "returnedToHealthy": returned_healthy,
        }
        if bounds_violations:
            failures.extend(bounds_violations)
        if not returned_healthy:
            failures.append(
                f"service did not return to HEALTHY: {svc_health}")

    import jax
    report = {
        "mode": "loadtest",
        "scaleFactor": sf,
        "seed": seed,
        # which backend these numbers measured (the BENCH_r06 lesson:
        # a CPU-backend artifact must say so in-band, not in prose)
        "backend": jax.default_backend(),
        "form": "sql" if use_sql else "dsl",
        "concurrency": concurrency,
        "tenants": tenants,
        "submissions": n_submissions,
        "wallClockS": round(wall, 4),
        "serialSumS": round(serial_sum, 4),
        "serialColdSumS": round(sum(serial_cold.values()), 4),
        "serialWarmSumS": round(sum(serial_warm.values()), 4),
        "coldP50S": round(_percentile(cold_vals, 0.50), 4)
        if cold_vals else None,
        "coldP95S": round(_percentile(cold_vals, 0.95), 4)
        if cold_vals else None,
        "warmP50S": round(_percentile(warm_vals, 0.50), 4)
        if warm_vals else None,
        "warmP95S": round(_percentile(warm_vals, 0.95), 4)
        if warm_vals else None,
        "warmup": warmup_report,
        "compile": compile_report,
        "chaos": chaos_report,
        "speedupVsSerial": round(serial_sum / wall, 3) if wall else None,
        "throughputQps": round(n_submissions / wall, 3) if wall else None,
        "latencyP50S": round(_percentile(latencies, 0.50), 4)
        if latencies else None,
        "latencyP95S": round(_percentile(latencies, 0.95), 4)
        if latencies else None,
        "queueWaitP50S": round(_percentile(queue_waits, 0.50), 4)
        if queue_waits else None,
        "queueWaitP95S": round(_percentile(queue_waits, 0.95), 4)
        if queue_waits else None,
        # over FINISHED submissions (the population hits can occur in),
        # matching the latency/queue-wait percentile population
        "cacheHitRate": round(cache_hits / len(latencies), 4)
        if latencies else None,
        "resultCache": (svc.result_cache.stats()
                        if svc.result_cache is not None else None),
        "service": svc.stats(),
        "allIdentical": not mismatches and not failures,
        "belowSerialSum": wall < serial_sum,
        "mismatches": mismatches,
        "failures": failures,
        "queries": per_query,
        # chaos mode: typed survivable outcomes and bounded recovery
        # are the CONTRACT, not failures — ok still requires zero
        # hangs, zero mismatches, zero untyped failures, bounds held,
        # and the service back at HEALTHY (folded into failures above)
        "ok": not mismatches and not failures,
    }
    return report


def render_loadtest(report: dict) -> str:
    lines = [
        f"Loadtest: {report['submissions']} submissions "
        f"({report['tenants']} tenants x "
        f"{len(report['queries'])} queries, {report['form']}) "
        f"at concurrency {report['concurrency']}",
        f"  wall clock      {report['wallClockS']:.3f}s  "
        f"(serial sum {report['serialSumS']:.3f}s, "
        f"speedup {report['speedupVsSerial']}x)",
        f"  throughput      {report['throughputQps']} q/s",
        f"  latency p50/p95 {report['latencyP50S']}s / "
        f"{report['latencyP95S']}s",
        f"  queue p50/p95   {report['queueWaitP50S']}s / "
        f"{report['queueWaitP95S']}s",
        f"  cache hit rate  {report['cacheHitRate']}",
        f"  cold p50/p95    {report['coldP50S']}s / {report['coldP95S']}s"
        + ("  (AOT-warmed)" if report.get("warmup") else ""),
        f"  repeat pass     {report['compile']['repeatPassNewTraces']} "
        f"new traces, executable-cache hit rate "
        f"{report['compile']['executableCacheHitRate']} "
        f"(template {report['compile']['templateHitRate']})",
        f"  all identical   {report['allIdentical']}",
    ]
    if report.get("warmup"):
        w = report["warmup"]
        lines.append(
            f"  warmup          {w['programsCompiled']} compiled / "
            f"{w['programsSkipped']} skipped in {w['wallS']:.2f}s "
            f"({w['newTraces']} traces)")
    if report.get("chaos"):
        c = report["chaos"]
        r = c["recovery"]
        lines.append(
            f"  chaos           fires {sum(c['faultFires'].values())} "
            f"({', '.join(f'{k}={v}' for k, v in sorted(c['faultFires'].items()))})")
        lines.append(
            f"    recovery      deviceReinits={r['deviceReinits']} "
            f"workersLost={r['workersLost']} respawned="
            f"{r['workersRespawned']} requeued={r['requeued']} "
            f"hardTimeouts={r['hardTimeouts']}")
        lines.append(
            f"    outcomes      {len(c['typedOutcomes'])} typed "
            f"non-finished, {len(c['rejectedSubmissions'])} rejected, "
            f"{len(c['hungSubmissions'])} hung; health at end: "
            f"{c['healthAtEnd']['state']}")
    if report["mismatches"]:
        lines.append("  MISMATCHES:")
        lines += [f"    {m}" for m in report["mismatches"]]
    if report["failures"]:
        lines.append("  FAILURES:")
        lines += [f"    {f}" for f in report["failures"]]
    return "\n".join(lines)
