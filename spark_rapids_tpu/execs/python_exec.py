"""Pandas/Arrow Python UDF execs.

Reference (SURVEY.md §2.3/§3.5): ``GpuArrowEvalPythonExec.scala`` and the
``execution/python/`` family — device batch → Arrow → Python worker →
Arrow → device, gated by ``PythonWorkerSemaphore.scala`` (limits how many
Python workers hold device resources concurrently).

TPU mapping: the device batch round-trips through pyarrow exactly as the
reference's Arrow IPC boundary does (device columnar → host Arrow →
pandas → user fn → pandas → Arrow → device upload); the semaphore analog
bounds concurrent UDF evaluations per process. The user function runs
in-process (the engine IS Python), which removes the worker-daemon
plumbing but keeps every data-movement boundary the reference models."""

from __future__ import annotations

import threading
import time
from typing import Iterator, List, Optional

from spark_rapids_tpu.columnar import DeviceTable, HostTable
from spark_rapids_tpu.conf import int_conf
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.plan.pandas_udf import (
    AggregateInPandas,
    ArrowEvalPython,
    FlatMapCoGroupsInPandas,
    FlatMapGroupsInPandas,
    MapInArrow,
    MapInPandas,
    WindowInPandas,
    _pandas_to_host,
    align_cogroups,
    arrow_batch_to_host,
    eval_window_udf,
)

CONCURRENT_PYTHON_WORKERS = int_conf(
    "spark.rapids.python.concurrentPythonWorkers", 0,
    "Max concurrent Python UDF evaluations holding device data "
    "(0 = unlimited; PythonWorkerSemaphore analog).")


class PythonWorkerSemaphore:
    """Process-wide gate on concurrent Python UDF work
    (PythonWorkerSemaphore.scala analog). One persistent semaphore per
    configured permit count — never rebuilt while permits are held, so a
    config's cap always holds and releases always reach the semaphore
    they were acquired from."""

    _lock = threading.Lock()
    _sems: dict = {}
    _held = threading.local()  # re-entrancy: nested UDF execs on one
    # thread (map_in_pandas pulling a child UDF exec) must not self-deadlock

    @classmethod
    def acquire_if_necessary(cls, permits: int):
        if permits <= 0:
            return None
        held = getattr(cls._held, "sems", None)
        if held is None:
            held = cls._held.sems = set()
        with cls._lock:
            sem = cls._sems.get(permits)
            if sem is None:
                sem = cls._sems[permits] = threading.Semaphore(permits)
        if sem in held:
            return None  # this thread already owns a permit
        sem.acquire()
        held.add(sem)
        return sem

    @classmethod
    def release(cls, sem):
        if sem is not None:
            cls._held.sems.discard(sem)
            sem.release()


def _arrow_roundtrip_to_pandas(table: HostTable):
    """Host columnar → Arrow → pandas (the GpuArrowWriter direction)."""
    from spark_rapids_tpu.io.arrow_convert import host_table_to_arrow
    return host_table_to_arrow(table).to_pandas()


class _PythonExecBase(TpuExec):
    def __init__(self, child: TpuExec, node, conf):
        super().__init__()
        self.children = (child,)
        self.node = node
        self.permits = int(conf.get_entry(CONCURRENT_PYTHON_WORKERS))

    def output_schema(self):
        return self.node.output_schema()

    def _run_udf(self, fn, *args):
        sem = PythonWorkerSemaphore.acquire_if_necessary(self.permits)
        t0 = time.perf_counter()
        try:
            return fn(*args)
        finally:
            PythonWorkerSemaphore.release(sem)
            self.add_metric("pythonUdfTime", time.perf_counter() - t0)

    def _download(self, batch: DeviceTable):
        t0 = time.perf_counter()
        pdf = _arrow_roundtrip_to_pandas(batch.to_host())
        self.add_metric("d2hArrowTime", time.perf_counter() - t0)
        return pdf

    def _upload(self, host: HostTable) -> DeviceTable:
        from spark_rapids_tpu.runtime.retry import retry_block
        t0 = time.perf_counter()
        # UDF result re-landings are device landings like scans: a
        # budget squeeze spills and replays instead of failing
        dt = retry_block(lambda: DeviceTable.from_host(host))
        self.add_metric("h2dArrowTime", time.perf_counter() - t0)
        return dt

    def _download_all(self, child, schema):
        """Drain a child exec into ONE pandas frame (empty frame keeps
        the schema's column names)."""
        import pandas as pd
        batches = [self._download(b) for b in child.execute()]
        if not batches:
            return pd.DataFrame(columns=[n for n, _ in schema])
        return pd.concat(batches, ignore_index=True) if len(batches) > 1 \
            else batches[0]

    def describe(self):
        return f"Tpu{type(self.node).__name__}Exec"


class TpuMapInPandasExec(_PythonExecBase):
    def execute(self) -> Iterator[DeviceTable]:
        node: MapInPandas = self.node

        def pdfs():
            for batch in self.children[0].execute():
                yield self._download(batch)

        # the user generator holds the worker slot for its whole stream
        # (the reference's python worker owns its task for the task's life)
        sem = PythonWorkerSemaphore.acquire_if_necessary(self.permits)
        t0 = time.perf_counter()
        try:
            for out in node.fn(pdfs()):
                host = _pandas_to_host(out, node.schema)
                if host.num_rows:
                    yield self._upload(host)
        finally:
            PythonWorkerSemaphore.release(sem)
            self.add_metric("pythonUdfTime", time.perf_counter() - t0)


class TpuFlatMapGroupsInPandasExec(_PythonExecBase):
    def execute(self) -> Iterator[DeviceTable]:
        node: FlatMapGroupsInPandas = self.node
        pdf = self._download_all(self.children[0],
                                 node.children[0].output_schema())
        if len(pdf) == 0:
            return
        for _key, group in pdf.groupby(node.keys, dropna=False, sort=True):
            out = self._run_udf(node.fn, group.reset_index(drop=True))
            if len(out):
                yield self._upload(_pandas_to_host(out, node.schema))


class TpuAggregateInPandasExec(_PythonExecBase):
    def execute(self) -> Iterator[DeviceTable]:
        node: AggregateInPandas = self.node
        import pandas as pd
        schema = node.output_schema()
        pdf = self._download_all(self.children[0],
                                 node.children[0].output_schema())
        rows = []
        if len(pdf):
            for key, group in pdf.groupby(node.keys, dropna=False,
                                          sort=True):
                if not isinstance(key, tuple):
                    key = (key,)
                row = dict(zip(node.keys, key))
                for name, fn, _rt, args in node.aggs:
                    row[name] = self._run_udf(
                        fn, *[group[a] for a in args])
                rows.append(row)
        out = pd.DataFrame(rows, columns=[n for n, _ in schema])
        yield self._upload(_pandas_to_host(out, schema))


class TpuMapInArrowExec(_PythonExecBase):
    """Device batch → host Arrow RecordBatches → user fn → Arrow →
    device (GpuMapInArrowExec analog; the Arrow boundary is the real
    contract, no pandas materialization)."""

    def execute(self) -> Iterator[DeviceTable]:
        from spark_rapids_tpu.io.arrow_convert import host_table_to_arrow
        node: MapInArrow = self.node

        def rbs():
            for batch in self.children[0].execute():
                t0 = time.perf_counter()
                at = host_table_to_arrow(batch.to_host())
                self.add_metric("d2hArrowTime", time.perf_counter() - t0)
                for rb in at.to_batches():
                    yield rb

        sem = PythonWorkerSemaphore.acquire_if_necessary(self.permits)
        t0 = time.perf_counter()
        try:
            for out in node.fn(rbs()):
                host = arrow_batch_to_host(out, node.schema)
                if host.num_rows:
                    yield self._upload(host)
        finally:
            PythonWorkerSemaphore.release(sem)
            self.add_metric("pythonUdfTime", time.perf_counter() - t0)


class TpuFlatMapCoGroupsInPandasExec(_PythonExecBase):
    """Two device children download once each; groups align by key with
    empty-side frames (GpuFlatMapCoGroupsInPandasExec analog)."""

    def __init__(self, children, node, conf):
        super().__init__(children[0], node, conf)
        self.children = tuple(children)

    def execute(self) -> Iterator[DeviceTable]:
        node: FlatMapCoGroupsInPandas = self.node
        left = self._download_all(self.children[0],
                                  node.children[0].output_schema())
        right = self._download_all(self.children[1],
                                   node.children[1].output_schema())
        for lg, rg in align_cogroups(left, right, node.left_keys,
                                     node.right_keys):
            out = self._run_udf(node.fn, lg, rg)
            if len(out):
                yield self._upload(_pandas_to_host(out, node.schema))


class TpuWindowInPandasExec(_PythonExecBase):
    """Whole input downloads once (window UDFs need full partitions, the
    same all-batches materialization the reference's exec performs),
    UDF columns append, result re-uploads (GpuWindowInPandasExec)."""

    def execute(self) -> Iterator[DeviceTable]:
        node: WindowInPandas = self.node
        pdf = self._download_all(self.children[0],
                                 node.children[0].output_schema())
        if len(pdf) == 0:
            return
        for name, fn, rt, args, spec in node.udfs:
            pdf[name] = self._run_udf(eval_window_udf, pdf, fn, args, spec)
        yield self._upload(_pandas_to_host(pdf, node.output_schema()))


class TpuArrowEvalPythonExec(_PythonExecBase):
    """Child columns pass through ON DEVICE; only UDF argument columns
    round-trip through Arrow, results upload and append — the reference's
    batch-queue + zip design (GpuArrowEvalPythonExec BatchQueue)."""

    def execute(self) -> Iterator[DeviceTable]:
        import pandas as pd
        node: ArrowEvalPython = self.node
        from spark_rapids_tpu.ops.expr import compile_project
        for batch in self.children[0].execute():
            extra_schema = [(name, rt) for name, _f, rt, _a in node.udfs]
            frames = {}
            for name, fn, rt, args in node.udfs:
                # evaluate arg exprs on DEVICE, download just those columns
                arg_cols = compile_project(list(args), batch)
                arg_table = DeviceTable(
                    [f"a{i}" for i in range(len(arg_cols))], arg_cols,
                    batch.num_rows, batch.capacity)
                arg_pdf = self._download(arg_table)
                result = self._run_udf(
                    fn, *[arg_pdf[c] for c in arg_pdf.columns])
                if len(result) != len(arg_pdf):
                    raise ColumnarProcessingError(
                        f"scalar pandas UDF {name} returned {len(result)} "
                        f"rows for a {len(arg_pdf)}-row batch")
                frames[name] = (result if hasattr(result, "reset_index")
                                else pd.Series(result))
            extra = _pandas_to_host(pd.DataFrame(frames), extra_schema)
            from spark_rapids_tpu.columnar import bucket_for
            if bucket_for(max(extra.num_rows, 1)) == batch.capacity:
                # common case: zip on device, pass-through columns never
                # leave HBM
                extra_dev = self._upload(extra)
                yield DeviceTable(
                    list(batch.names) + list(extra_dev.names),
                    list(batch.columns) + list(extra_dev.columns),
                    batch.num_rows, batch.capacity)
            else:
                # capacity buckets differ (batch padded past num_rows):
                # align on host, one upload
                host = batch.to_host()
                yield self._upload(HostTable(
                    list(host.names) + list(extra.names),
                    list(host.columns) + list(extra.columns)))
