"""Iceberg snapshot scan with delete-file application.

Reference: ``GpuIcebergReader.java`` (applies the delete filter then hands
batches to the engine), ``GpuDeleteFilter.java`` (positional + equality
deletes), ``GpuMultiFileBatchReader.java`` (reader-mode integration).
Positional deletes are parquet files of (file_path, pos); equality
deletes are parquet files whose rows name deleted keys over the columns
given by ``equality_ids``, applied to data files with a SMALLER sequence
number (v2 sequence-number semantics)."""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.iceberg.metadata import (
    EQUALITY_DELETES,
    POSITION_DELETES,
    IcebergSnapshot,
    IcebergTableMetadata,
    load_snapshot,
    load_table_metadata,
)
from spark_rapids_tpu.io.common import FileScanNode
from spark_rapids_tpu.plan.nodes import Schema


class IcebergScanNode(FileScanNode):
    format_name = "iceberg"

    def __init__(self, table_path: str, conf: RapidsConf,
                 snapshot_id: Optional[int] = None,
                 columns: Optional[Sequence[str]] = None, **options):
        self.table_path = table_path
        self.meta: IcebergTableMetadata = load_table_metadata(table_path)
        self.snap: IcebergSnapshot = load_snapshot(table_path, self.meta,
                                                   snapshot_id)
        self._seq_by_path = {d.file_path: d.sequence_number
                             for d in self.snap.data_files}
        self._pos_deletes: Optional[Dict[str, np.ndarray]] = None
        self._eq_deletes: Optional[List[Tuple[int, List[str], Set[tuple]]]] \
            = None
        paths = [d.file_path for d in self.snap.data_files]
        self._empty = not paths
        super().__init__(paths or ["<empty>"], conf, columns=columns,
                         **options)

    def output_schema(self) -> Schema:
        full = list(self.meta.schema)
        if self.columns is not None:
            by_name = dict(full)
            for c in self.columns:
                if c not in by_name:
                    raise ColumnarProcessingError(
                        f"column {c!r} not in {[n for n, _ in full]}")
            full = [(c, by_name[c]) for c in self.columns]
        return full

    def file_schema(self, path: str) -> Schema:
        return list(self.meta.schema)

    def _resolve_schemas(self):
        if self._schema is not None:
            return
        self._schema = self.output_schema()
        self._data_schema = self._schema
        self._partition_schema = []

    def _cache_key_extra(self) -> tuple:
        return (self.snap.snapshot_id,)

    # -- delete files --------------------------------------------------------
    def _load_deletes(self):
        if self._pos_deletes is not None:
            return
        import pyarrow.parquet as pq
        pos: Dict[str, List[np.ndarray]] = {}
        eqs: List[Tuple[int, List[str], Set[tuple]]] = []
        for d in self.snap.delete_files:
            t = pq.read_table(d.file_path)
            if d.content == POSITION_DELETES:
                paths = t.column("file_path").to_pylist()
                positions = np.asarray(t.column("pos").to_pylist(),
                                       dtype=np.int64)
                for p in set(paths):
                    mask = np.array([x == p for x in paths])
                    pos.setdefault(self._norm(p), []).append(
                        positions[mask])
            elif d.content == EQUALITY_DELETES:
                cols = [self.meta.field_ids[i] for i in d.equality_ids]
                if not cols:
                    cols = t.column_names
                keys = set()
                data = [t.column(c).to_pylist() for c in cols]
                for row in zip(*data):
                    keys.add(row)
                eqs.append((d.sequence_number, cols, keys))
        self._pos_deletes = {p: np.unique(np.concatenate(v))
                             for p, v in pos.items()}
        self._eq_deletes = eqs

    def _norm(self, p: str) -> str:
        if p.startswith("file://"):
            p = p[len("file://"):]
        return os.path.normpath(p)

    def read_file(self, path: str) -> HostTable:
        import pyarrow.parquet as pq

        from spark_rapids_tpu.io.arrow_convert import decode_to_schema
        self._resolve_schemas()
        self._load_deletes()
        # equality deletes may need columns beyond the projection
        eq_cols = {c for seq, cols, _k in self._eq_deletes for c in cols
                   if seq > self._seq_by_path.get(path, 0)}
        proj = [n for n, _ in self._data_schema]
        read_cols = list(dict.fromkeys(proj + sorted(eq_cols)))
        t = pq.read_table(path, columns=read_cols)
        all_schema = dict(self.meta.schema)
        table = decode_to_schema(t, [(n, all_schema[n]) for n in read_cols])

        keep = np.ones(table.num_rows, dtype=bool)
        dv = self._pos_deletes.get(self._norm(path))
        if dv is not None:
            keep[dv[dv < table.num_rows]] = False
        my_seq = self._seq_by_path.get(path, 0)
        for seq, cols, keys in self._eq_deletes:
            if seq <= my_seq:
                continue  # deletes only apply to OLDER data
            idx = [list(table.names).index(c) for c in cols]
            for r in range(table.num_rows):
                if keep[r] and tuple(table.columns[i].data[r]
                                     for i in idx) in keys:
                    keep[r] = False
        cols_out = []
        names_out = []
        for n in proj:
            i = list(table.names).index(n)
            c = table.columns[i]
            cols_out.append(HostColumn(c.dtype, c.data[keep],
                                       c.validity[keep]))
            names_out.append(n)
        return HostTable(names_out, cols_out)

    def execute_cpu(self, dynamic_prunes=None, metrics=None):
        if self._empty:
            from spark_rapids_tpu.plan.nodes import _empty_table
            yield _empty_table(self.output_schema())
            return
        yield from super().execute_cpu(dynamic_prunes=dynamic_prunes,
                                       metrics=metrics)

    def estimate_bytes(self):
        try:
            return sum(os.path.getsize(d.file_path)
                       for d in self.snap.data_files)
        except OSError:
            return None

    def describe(self):
        return (f"IcebergScan[snap={self.snap.snapshot_id}, "
                f"{len(self.snap.data_files)} data files, "
                f"{len(self.snap.delete_files)} delete files]")
