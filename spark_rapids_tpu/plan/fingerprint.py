"""Canonical structural plan fingerprinting (shared by both caches).

One implementation, two variants:

* **full** (``strip_literals=False``) — every non-child attribute of
  every plan node and expression folds in, INCLUDING literal values.
  This is the result-cache key (service/result_cache.py): two plans
  differing in any literal compute different tables and must never
  collide.
* **template** (``strip_literals=True``) — ``Literal`` expression
  nodes contribute only their dtype and null-ness, so distinct-literal
  variants of one query template (``price > 5`` vs ``price > 6``)
  share a fingerprint. This is the executable-cache grouping key
  (plan/executable_cache.py): kernels are keyed structurally
  (``Expression.key``), so template-mates share every compiled program
  whose key is literal-value-free (string-literal predicates, joins,
  aggregates, all shape-dependent kernels); numeric literal values
  trace as XLA constants and keep per-value programs for the
  expressions that contain them.

The two keys diverge EXACTLY on literal values (pinned by
tests/test_serving_latency.py): any other difference changes both.

Correctness over hit rate, everywhere: anything the walk cannot PROVE
structurally stable (a UDF closure, an unknown object with an
address-y repr) raises :class:`Unfingerprintable` and the caller
treats the plan as uncacheable — a miss, never a wrong hit.

The warehouse invalidation epoch lives here too (it versions the
state BOTH caches key against): every catalog mutation, WriteFiles
execution, or Delta/Iceberg commit bumps it; cache entries remember
the epoch they were filled under and stale entries drop on lookup.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from typing import Optional, Tuple

# ---------------------------------------------------------------------------
# Invalidation epoch
# ---------------------------------------------------------------------------

_EPOCH_LOCK = threading.Lock()
_EPOCH = [0]
_EPOCH_REASON = [""]


def invalidation_epoch() -> int:
    with _EPOCH_LOCK:
        return _EPOCH[0]


def bump_invalidation_epoch(reason: str = "") -> int:
    """Storage/catalog state changed (temp-view or table registration,
    WriteFiles, Delta/Iceberg commit): every currently cached result —
    and every cached executable whose scans may now read different
    bytes — is stale. Called by the session's write detection, the SQL
    catalog's mutators, and the Delta log's commit path."""
    with _EPOCH_LOCK:
        _EPOCH[0] += 1
        _EPOCH_REASON[0] = reason
        return _EPOCH[0]


# ---------------------------------------------------------------------------
# Plan fingerprinting
# ---------------------------------------------------------------------------


class Unfingerprintable(Exception):
    """Internal: the plan holds state the fingerprinter cannot prove
    structurally stable. The query runs uncached."""


#: lazily resolved (datetime, np, T, HostTable, Expression, PlanNode,
#: Literal) — module-level import would pull the whole plan layer at
#: package import; resolving on first fingerprint keeps the module
#: importable standalone while the hot path pays one tuple unpack
_FP_TYPES = None


#: conf key prefixes that cannot change a query's RESULT — observability
#: and service knobs are excluded from the result-cache fingerprint so
#: flipping the event log on does not cold the cache. Everything else
#: folds in.
RESULT_NEUTRAL_PREFIXES = (
    "spark.rapids.sql.eventLog.",
    "spark.rapids.trace.",
    "spark.rapids.profile.",
    "spark.rapids.sql.metrics.level",
    "spark.rapids.sql.lore.",
    "spark.rapids.sql.explain",
    "spark.rapids.sql.planVerify.mode",
    "spark.rapids.service.",
    # fetch mechanics only — the root transition's flag is re-set per
    # query, results and the converted tree are byte-identical
    "spark.rapids.sql.asyncResultFetch",
    "spark.rapids.sql.executableCache.",
)

#: conf key prefixes that cannot change the CONVERTED EXECUTABLE. A
#: strict subset of the result-neutral set: lore dump ids rewrite the
#: tree (_TeeChild wrappers) and planVerify.mode decides whether the
#: tree was proven, so both fold into the executable-cache key even
#: though they cannot change results.
EXECUTABLE_NEUTRAL_PREFIXES = (
    "spark.rapids.sql.eventLog.",
    "spark.rapids.trace.",
    "spark.rapids.profile.",
    "spark.rapids.sql.metrics.level",
    "spark.rapids.sql.explain",
    "spark.rapids.service.",
    "spark.rapids.sql.asyncResultFetch",
    "spark.rapids.sql.executableCache.",
)

#: identity tokens for in-memory source tables: a HostTable object IS
#: its data (tables are immutable after construction), so identity is a
#: sound cache key — and the weak keying means a collected table can
#: never alias a new one's token
_TABLE_TOKENS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_TABLE_TOKEN_LOCK = threading.Lock()
_TABLE_TOKEN_SEQ = [0]


def _table_token(table) -> str:
    with _TABLE_TOKEN_LOCK:
        tok = _TABLE_TOKENS.get(table)
        if tok is None:
            _TABLE_TOKEN_SEQ[0] += 1
            tok = f"tbl#{_TABLE_TOKEN_SEQ[0]}"
            _TABLE_TOKENS[table] = tok
        return tok


def _resolve_types():
    global _FP_TYPES
    if _FP_TYPES is None:
        import datetime

        import numpy as np

        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.columnar import HostTable
        from spark_rapids_tpu.ops.expr import Expression, Literal
        from spark_rapids_tpu.plan.nodes import PlanNode
        _FP_TYPES = (datetime, np, T, HostTable, Expression, PlanNode,
                     Literal)
    return _FP_TYPES


def _fp_value(obj, depth: int = 0, strip_literals: bool = False) -> str:
    """One value's canonical token. Raises Unfingerprintable for
    anything that cannot be proven stable."""
    # deferred-but-cached: fingerprinting runs on the service's submit
    # hot path, once per attribute of every plan node — resolve the
    # type anchors once per process, not per call
    datetime, np, T, HostTable, Expression, PlanNode, Literal = \
        _resolve_types()

    if depth > 64:
        raise Unfingerprintable("plan too deep to fingerprint")
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return f"{type(obj).__name__}:{obj!r}"
    if isinstance(obj, (datetime.date, datetime.datetime)):
        return f"dt:{obj.isoformat()}"
    if isinstance(obj, T.DataType):
        return f"type:{obj}"
    if isinstance(obj, HostTable):
        return _fp_value_table(obj)
    if isinstance(obj, (Expression, PlanNode)) or \
            type(obj).__module__.startswith("spark_rapids_tpu."):
        # generic structural walk over instance state — plan nodes,
        # expressions, and plain engine data holders (SortOrder,
        # WindowSpec, ...). Unlike .key() (which drops string literal
        # VALUES because the compile cache doesn't need them) or
        # __repr__ (which some subclasses leave at the children-only
        # default), this captures EVERY non-child attribute, so two
        # nodes differing in any parameter can never collide; state the
        # walk cannot prove stable (closures, device arrays) raises
        # Unfingerprintable and the plan just never caches
        return _fp_node(obj, depth + 1, strip_literals)
    if isinstance(obj, np.generic):
        return f"np:{obj.dtype}:{obj!r}"
    if isinstance(obj, np.ndarray):
        if obj.dtype == object:
            raise Unfingerprintable("object ndarray in plan state")
        return (f"nd:{obj.dtype}:{obj.shape}:"
                f"{hashlib.sha1(np.ascontiguousarray(obj).tobytes()).hexdigest()}")
    if isinstance(obj, dict):
        items = sorted((str(k), _fp_value(v, depth + 1, strip_literals))
                       for k, v in obj.items())
        return "dict{" + ",".join(f"{k}={v}" for k, v in items) + "}"
    if isinstance(obj, (list, tuple)):
        return ("seq[" +
                ",".join(_fp_value(v, depth + 1, strip_literals)
                         for v in obj) + "]")
    if isinstance(obj, (set, frozenset)):
        return ("set{" +
                ",".join(sorted(_fp_value(v, depth + 1, strip_literals)
                                for v in obj)) +
                "}")
    raise Unfingerprintable(
        f"{type(obj).__name__} in plan state is not fingerprintable")


def _fp_value_table(table) -> str:
    return f"table:{_table_token(table)}"


#: per-node attributes that never affect results (caches, back-refs;
#: the session conf folds into the fingerprint separately)
_SKIP_ATTRS = {"_session", "_table", "conf", "_conf"}


def _fp_node(node, depth: int = 0, strip_literals: bool = False) -> str:
    """Canonical token of one plan node or expression: class name +
    every non-child attribute's token (sorted by name) + children in
    order. With ``strip_literals``, a ``Literal`` contributes only its
    dtype and null-ness — the one place the template and full
    fingerprints are allowed to differ."""
    Literal = _resolve_types()[6]
    if strip_literals and isinstance(node, Literal):
        return (f"(Literal;dtype=type:{node.data_type};"
                f"null={node.value is None})[]")
    parts = [type(node).__name__]
    try:
        state = vars(node)
    except TypeError:  # __slots__ object; nothing generic to prove
        raise Unfingerprintable(
            f"{type(node).__name__} has no inspectable state")
    for name in sorted(state):
        if name in _SKIP_ATTRS or name == "children":
            continue
        value = state[name]
        if callable(value) and not isinstance(value, type):
            raise Unfingerprintable(
                f"{type(node).__name__}.{name} holds a callable")
        parts.append(
            f"{name}={_fp_value(value, depth + 1, strip_literals)}")
    kids = ",".join(_fp_node(c, depth + 1, strip_literals)
                    for c in getattr(node, "children", ()))
    return "(" + ";".join(parts) + ")[" + kids + "]"


def fingerprint(plan, conf, *, strip_literals: bool = False,
                neutral_prefixes: Tuple[str, ...] = RESULT_NEUTRAL_PREFIXES,
                ) -> Optional[str]:
    """Canonical fingerprint of (bound plan, result-affecting conf), or
    None when the plan is uncacheable (side-effecting WriteFiles nodes,
    UDF closures, unfingerprintable state)."""
    from spark_rapids_tpu.plan.nodes import WriteFiles

    stack = [plan]
    while stack:
        n = stack.pop()
        if isinstance(n, WriteFiles):
            return None  # side effects never cache
        stack.extend(getattr(n, "children", ()))
    try:
        plan_tok = _fp_node(plan, 0, strip_literals)
    except Unfingerprintable:
        return None
    conf_items = sorted(
        (k, str(v)) for k, v in conf.to_dict().items()
        if not any(k.startswith(p) or k == p.rstrip(".")
                   for p in neutral_prefixes))
    h = hashlib.sha1()
    h.update(plan_tok.encode())
    h.update(repr(conf_items).encode())
    # mesh identity (parallel/mesh.py): shape/axes/device ids of the
    # ACTIVE mesh fold in beyond the spark.rapids.mesh.* conf keys
    # above — a backend whose device set changed (reinit after device
    # loss) must not serve plans cached against the old placement
    from spark_rapids_tpu.parallel.mesh import MESH
    h.update(MESH.identity_token().encode())
    # host topology token (runtime/cluster.py): the cluster's declared/
    # lost/excluded host set folds in beyond the spark.rapids.cluster.*
    # conf keys — a plan cached while host h1 was lost (its scans
    # re-landed on survivors) must not serve the full-strength topology
    from spark_rapids_tpu.runtime.cluster import CLUSTER
    h.update(CLUSTER.identity_token().encode())
    # Pallas kernel demotions are runtime state the conf cannot see
    # (the kernels.* conf keys fold in above): a cached tree traced
    # with a kernel embedded must never serve a query after that
    # primitive demoted to HLO, and vice versa
    from spark_rapids_tpu import kernels
    h.update(kernels.demotion_token().encode())
    return h.hexdigest()


def template_fingerprint(plan, conf) -> Optional[str]:
    """THE template key: literal-stripped, executable-neutral-conf
    fingerprint — what the executable cache groups by and the poison
    quarantine strikes against. One definition so the scheduler's
    strike ledger and explain()'s quarantine flag can never key on
    different fingerprints."""
    return fingerprint(plan, conf, strip_literals=True,
                       neutral_prefixes=EXECUTABLE_NEUTRAL_PREFIXES)


def plan_fingerprints(plan, conf) -> Tuple[Optional[str], Optional[str]]:
    """(template_fp, full_fp) for the executable cache: the template is
    literal-stripped and conf-reduced to executable-affecting keys; the
    full print distinguishes literal variants within the template.
    (None, None) for uncacheable plans."""
    template = template_fingerprint(plan, conf)
    if template is None:
        return None, None
    full = fingerprint(plan, conf, strip_literals=False,
                       neutral_prefixes=EXECUTABLE_NEUTRAL_PREFIXES)
    return template, full
