"""Delta table read/write through the engine.

Reference (SURVEY.md §2.8): delta-lake module scan + write path —
``GpuDelta*Scan`` reads the snapshot's parquet files with deletion-vector
filtering; ``GpuOptimisticTransaction`` stages parquet writes and commits
add/remove actions with per-file column stats
(``GpuStatisticsCollection``). Same architecture here: the scan node
feeds the engine's standard overrides/exec machinery, writes go through
the parquet writer, and commits are optimistic with retry."""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.conf import RapidsConf, int_conf
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.delta.log import (
    AddFile,
    DeltaConcurrentModificationException,
    DeltaConcurrentWriteException,
    DeltaLog,
    DeltaMetadataChangedException,
    Metadata,
    PROTOCOL_ACTION,
    RemoveFile,
    Snapshot,
    schema_to_json,
)
from spark_rapids_tpu.delta.roaring import deserialize_dv, serialize_dv
from spark_rapids_tpu.io.common import FileScanNode
from spark_rapids_tpu.plan.nodes import PlanNode, Schema

DELTA_CHECKPOINT_INTERVAL = int_conf(
    "spark.rapids.delta.checkpointInterval", 10,
    "Write a delta checkpoint every N commits.")


# -- deletion vectors --------------------------------------------------------
#
# Spec framing (Delta PROTOCOL.md "Deletion Vector Format", ADVICE r2):
# a DV FILE starts with a 1-byte format version (1); each stored vector is
# a 4-byte big-endian size, the serialized RoaringBitmapArray blob, and a
# 4-byte big-endian CRC-32 of the blob. The descriptor's ``offset`` points
# at the size prefix; ``sizeInBytes`` is the blob length (without
# prefix/checksum). Storage types: 'u' = path derived from a base85 uuid
# relative to the table (written here), 'p' = absolute path, 'i' = inline
# base85 blob.

import base64
import zlib


def _dv_relative_path(path_or_inline: str) -> str:
    """'u' storage: the LAST 20 chars are the base85 (RFC 1924) uuid; any
    leading chars are a directory prefix."""
    enc = path_or_inline[-20:]
    prefix = path_or_inline[:-20]
    u = uuid.UUID(bytes=base64.b85decode(enc))
    name = f"deletion_vector_{u}.bin"
    return os.path.join(prefix, name) if prefix else name


def write_dv_file(table_path: str, row_indexes: np.ndarray) -> dict:
    """Persist a deletion vector with spec framing; returns the
    deletionVector descriptor for the add action ('u' storage)."""
    blob = serialize_dv(row_indexes)
    u = uuid.uuid4()
    enc = base64.b85encode(u.bytes).decode()
    name = f"deletion_vector_{u}.bin"
    dv_path = os.path.join(table_path, name)
    with open(dv_path, "wb") as f:
        f.write(b"\x01")  # format version
        f.write(len(blob).to_bytes(4, "big"))
        f.write(blob)
        f.write(zlib.crc32(blob).to_bytes(4, "big"))
    return {"storageType": "u", "pathOrInlineDv": enc, "offset": 1,
            "sizeInBytes": len(blob), "cardinality": int(len(row_indexes))}


def read_dv(table_path: str, descriptor: dict) -> np.ndarray:
    st = descriptor["storageType"]
    if st == "i":
        return deserialize_dv(base64.b85decode(descriptor["pathOrInlineDv"]))
    if st == "u":
        p = os.path.join(table_path,
                         _dv_relative_path(descriptor["pathOrInlineDv"]))
    elif st == "p":
        p = descriptor["pathOrInlineDv"]
        if not os.path.isabs(p):  # tolerate our pre-spec relative form
            p = os.path.join(table_path, p)
    else:
        raise ColumnarProcessingError(
            f"deletion-vector storage {st!r} not supported")
    with open(p, "rb") as f:
        off = descriptor.get("offset", 0)
        if off == 0:
            # pre-framing files stored the bare blob at offset 0
            buf = f.read()
            return deserialize_dv(buf)
        f.seek(off)
        size = int.from_bytes(f.read(4), "big")
        blob = f.read(size)
        crc = int.from_bytes(f.read(4), "big")
    if len(blob) != size or zlib.crc32(blob) != crc:
        raise ColumnarProcessingError(
            f"deletion vector at {p}:{off} failed checksum")
    return deserialize_dv(blob)


# -- scan --------------------------------------------------------------------

def attach_partition_columns(table: HostTable, add: AddFile,
                             part_schema) -> HostTable:
    """Append typed partition-value columns from an add action's
    partitionValues (one shared implementation for the scan and the DML
    commands)."""
    if not part_schema:
        return table
    n = table.num_rows
    names = list(table.names)
    cols = list(table.columns)
    for name, dt in part_schema:
        raw = add.partition_values.get(name)
        if raw is None:
            validity = np.zeros(n, dtype=np.bool_)
            data = (np.full(n, None, dtype=object)
                    if isinstance(dt, T.StringType)
                    else np.zeros(n, dtype=dt.np_dtype))
        else:
            validity = np.ones(n, dtype=np.bool_)
            if isinstance(dt, T.StringType):
                data = np.full(n, raw, dtype=object)
            elif isinstance(dt, (T.FloatType, T.DoubleType)):
                data = np.full(n, float(raw), dtype=dt.np_dtype)
            elif isinstance(dt, T.BooleanType):
                data = np.full(n, raw == "true", dtype=np.bool_)
            else:
                data = np.full(n, int(raw), dtype=dt.np_dtype)
        names.append(name)
        cols.append(HostColumn(dt, data, validity))
    return HostTable(names, cols)


class DeltaScanNode(FileScanNode):
    """Snapshot scan: file list + partition values + deletion vectors come
    from the LOG, not from directory structure."""

    format_name = "delta"

    def __init__(self, table_path: str, conf: RapidsConf,
                 version_as_of: Optional[int] = None,
                 columns: Optional[Sequence[str]] = None,
                 snapshot: Optional[Snapshot] = None, **options):
        self.table_path = table_path
        self.delta_log = DeltaLog(table_path)
        self.snap = snapshot if snapshot is not None \
            else self.delta_log.snapshot(version_as_of)
        self._adds = {os.path.join(table_path, a.path): a
                      for a in self.snap.files}
        if not self._adds:
            # empty table: synthesize an empty scan over the schema
            paths = []
        else:
            paths = sorted(self._adds)
        self._empty = not paths
        super().__init__(paths or ["<empty>"], conf, columns=columns,
                         **options)

    # expand_paths would reject []; bypass for the empty-table case
    def output_schema(self) -> Schema:
        full = list(self.snap.schema)
        if self.columns is not None:
            by_name = dict(full)
            for c in self.columns:
                if c not in by_name:
                    raise ColumnarProcessingError(
                        f"column {c!r} not in {[n for n, _ in full]}")
            full = [(c, by_name[c]) for c in self.columns]
        return full

    def file_schema(self, path: str) -> Schema:
        # data columns = schema minus partition columns
        parts = set(self.snap.metadata.partition_columns)
        return [(n, dt) for n, dt in self.snap.schema if n not in parts]

    def _cache_key_extra(self) -> tuple:
        # deletion vectors change what a FILE decodes to between versions
        return (self.snap.version,)

    def _resolve_schemas(self):
        if self._schema is not None:
            return
        parts = set(self.snap.metadata.partition_columns)
        full = self.output_schema()
        self._schema = full
        self._data_schema = [(n, dt) for n, dt in full if n not in parts]
        self._partition_schema = [(n, dt) for n, dt in full if n in parts]

    def read_file(self, path: str) -> HostTable:
        import pyarrow.parquet as pq

        from spark_rapids_tpu.io.arrow_convert import decode_to_schema
        self._resolve_schemas()
        if not self._data_schema:
            # projection touches only partition columns: the row COUNT
            # still comes from the file (partition columns replicate per
            # row), carried by a placeholder column
            n = pq.ParquetFile(path).metadata.num_rows
            table = HostTable(["__rows__"], [HostColumn(
                T.LONG, np.zeros(n, dtype=np.int64))])
        else:
            # column mapping: files store PHYSICAL names; the engine reads
            # by physical name and surfaces logical (Delta columnMapping
            # mode=name/id; identity map when off)
            phys = None
            if self.snap.metadata is not None \
                    and self.snap.metadata.column_mapping_mode() != "none":
                phys = self.snap.metadata.physical_names()
            table = read_physical_parquet(path, self._data_schema, phys)
        add = self._adds[path]
        if add.deletion_vector:
            deleted = read_dv(self.table_path, add.deletion_vector)
            keep = np.ones(table.num_rows, dtype=bool)
            keep[deleted[deleted < table.num_rows]] = False
            table = table.filter_rows(keep) if hasattr(table, "filter_rows") \
                else _mask_table(table, keep)
        return table

    def _with_partition_columns(self, table: HostTable, path: str) -> HostTable:
        """Partition values come from the add action, typed per schema."""
        self._resolve_schemas()
        if not self._partition_schema:
            return table
        full = attach_partition_columns(table, self._adds[path],
                                        self._partition_schema)
        by_name = dict(zip(full.names, full.columns))
        out = [n2 for n2, _ in self._schema]
        return HostTable(out, [by_name[n2] for n2 in out])

    def execute_cpu(self, dynamic_prunes=None,
                    metrics=None) -> Iterator[HostTable]:
        if self._empty:
            from spark_rapids_tpu.plan.nodes import _empty_table
            yield _empty_table(self.output_schema())
            return
        yield from super().execute_cpu(dynamic_prunes=dynamic_prunes,
                                       metrics=metrics)

    def estimate_bytes(self):
        return sum(a.size for a in self.snap.files)

    def describe(self):
        return (f"DeltaScan[v{self.snap.version}, "
                f"{len(self.snap.files)} files]")


def _mask_table(table: HostTable, keep: np.ndarray) -> HostTable:
    cols = [HostColumn(c.dtype, c.data[keep], c.validity[keep])
            for c in table.columns]
    return HostTable(list(table.names), cols)


# -- write transaction -------------------------------------------------------

def _column_stats(table: HostTable) -> str:
    """Per-file stats JSON (numRecords + min/max per leaf column) — the
    GpuStatisticsCollection analog used for data skipping."""
    stats = {"numRecords": int(table.num_rows), "minValues": {},
             "maxValues": {}, "nullCount": {}}
    for name, col in zip(table.names, table.columns):
        valid = col.validity
        stats["nullCount"][name] = int((~valid).sum())
        if not valid.any():
            continue
        vals = col.data[valid]
        if isinstance(col.dtype, T.StringType):
            svals = [v for v in vals if v is not None]
            if svals:
                stats["minValues"][name] = min(svals)
                stats["maxValues"][name] = max(svals)
        elif isinstance(col.dtype, (T.FloatType, T.DoubleType)):
            finite = vals[np.isfinite(vals)]
            if len(finite):
                stats["minValues"][name] = float(finite.min())
                stats["maxValues"][name] = float(finite.max())
        else:
            stats["minValues"][name] = int(vals.min())
            stats["maxValues"][name] = int(vals.max())
    return json.dumps(stats)


def read_physical_parquet(full_path: str, schema,
                          phys_map: Optional[Dict[str, str]]) -> HostTable:
    """ONE data/cdc parquet as the given LOGICAL schema: read by physical
    column name (column mapping; identity when None), decode, rename to
    logical, null-fill columns the file predates (mergeSchema evolution).
    The single implementation behind the scan node, the DML readers and
    the cdc reader (code-review r5: three hand-rolled copies)."""
    import pyarrow.parquet as pq

    from spark_rapids_tpu.io.arrow_convert import decode_to_schema
    pf = pq.ParquetFile(full_path)
    have = set(pf.schema_arrow.names)
    pn = (lambda n: phys_map.get(n, n)) if phys_map else (lambda n: n)
    present = [(n, dt) for n, dt in schema if pn(n) in have]
    missing = [(n, dt) for n, dt in schema if pn(n) not in have]
    t = pf.read(columns=[pn(n) for n, _ in present])
    table = decode_to_schema(t, [(pn(n), dt) for n, dt in present])
    table = HostTable([n for n, _ in present], list(table.columns))
    if not missing:
        return table
    by_name = dict(zip(table.names, table.columns))
    for n, dt in missing:
        by_name[n] = _null_column(dt, table.num_rows)
    return HostTable([n for n, _ in schema],
                     [by_name[n] for n, _ in schema])


def _evolved_metadata(old_meta: Metadata, evolved_schema,
                      partition_by) -> Metadata:
    """Metadata action for a schema evolution that PRESERVES table
    configuration and per-field metadata (column-mapping physical names,
    ids). A bare schema_to_json would wipe delta.columnMapping state and
    delta.enableChangeDataFeed (code-review r5).

    On a mapped table (columnMapping.mode != none) every NEW field must
    get its own physicalName/id and maxColumnId must advance, or the
    committed metadata violates the column-mapping protocol for external
    readers (ADVICE r5)."""
    from spark_rapids_tpu.delta.log import schema_fields_from_json
    old_fields = {f["name"]: f
                  for f in schema_fields_from_json(old_meta.schema_json)}
    new_json = json.loads(schema_to_json(evolved_schema))
    cfg = dict(old_meta.configuration)
    mapped = old_meta.column_mapping_mode() != "none"
    max_id = int(cfg.get("delta.columnMapping.maxColumnId", "0") or 0)
    for f in old_fields.values():
        fid = (f.get("metadata") or {}).get("delta.columnMapping.id", 0)
        max_id = max(max_id, int(fid or 0))
    merged = []
    for f in new_json["fields"]:
        have = old_fields.get(f["name"])
        if have is not None:
            merged.append(have)
            continue
        if mapped:
            md = dict(f.get("metadata") or {})
            max_id += 1
            # new physical names are UUID-based so a later rename/re-add
            # of the same logical name can never collide with this file
            # column (Delta's DeltaColumnMapping convention)
            md.setdefault("delta.columnMapping.physicalName",
                          f"col-{uuid.uuid4()}")
            md.setdefault("delta.columnMapping.id", max_id)
            f = dict(f, metadata=md)
        merged.append(f)
    if mapped:
        cfg["delta.columnMapping.maxColumnId"] = str(max_id)
    return Metadata(json.dumps({"type": "struct", "fields": merged}),
                    list(partition_by), table_id=old_meta.table_id,
                    name=old_meta.name, configuration=cfg)


def _write_data_file(table_path: str, table: HostTable,
                     partition_values: Dict[str, str],
                     subdir: str = "",
                     physical: Optional[Dict[str, str]] = None) -> AddFile:
    from spark_rapids_tpu.io.arrow_convert import host_table_to_arrow
    import pyarrow.parquet as pq
    rel_dir = subdir
    os.makedirs(os.path.join(table_path, rel_dir) if rel_dir else table_path,
                exist_ok=True)
    rel = os.path.join(rel_dir, f"part-{uuid.uuid4().hex}.parquet") \
        if rel_dir else f"part-{uuid.uuid4().hex}.parquet"
    full = os.path.join(table_path, rel)
    if physical:
        # column mapping: data files carry PHYSICAL column names
        table = HostTable([physical.get(n, n) for n in table.names],
                          list(table.columns))
    pq.write_table(host_table_to_arrow(table), full)
    return AddFile(path=rel, partition_values=dict(partition_values),
                   size=os.path.getsize(full),
                   modification_time=int(time.time() * 1000),
                   stats=_column_stats(table))


class OptimisticTransaction:
    """Stage file writes, then commit with conflict classification and
    bounded rebase-and-retry (GpuOptimisticTransaction analog). A
    transaction that ultimately FAILS sweeps the data files it staged
    into the table directory — they are unreferenced by any committed
    version and would otherwise sit as orphans until vacuum."""

    def __init__(self, log: DeltaLog, conf: RapidsConf,
                 read_version: Optional[int] = None):
        self.log = log
        self.conf = conf
        self.read_version = read_version
        self.actions: List[dict] = []
        #: full paths of files this txn wrote into the table dir —
        #: shielded from concurrent vacuum until commit resolves
        self._created: set = set()

    def stage(self, *actions):
        from spark_rapids_tpu.io.committer import protect_files
        for a in actions:
            act = a.to_action() if hasattr(a, "to_action") else a
            self.actions.append(act)
            rel = None
            if "add" in act:
                rel = act["add"].get("path")
            elif "cdc" in act:
                rel = act["cdc"].get("path")
            if rel:
                self._created.add(
                    os.path.join(self.log.table_path, rel))
        if self._created:
            protect_files(self, self.log.table_path, self._created)

    # -- conflict handling ---------------------------------------------------
    def _classify_conflict(self, attempt: int):
        """Examine the winners' commits in [attempt, latest]; raise the
        typed conflict when this transaction cannot safely rebase, else
        return (no raise) meaning a blind-append rebase is legal.

        Rebase is legal exactly when this transaction is a PURE APPEND
        (no removes, no metadata — unique new files never invalidate a
        reader) AND no winner changed metadata/protocol AND no winner's
        add collides with ours on path. Everything staging removes
        (DELETE/UPDATE/MERGE/overwrite) read table state the winner may
        have changed — retrying those stale actions would silently lose
        the winner's commit."""
        try:
            latest = self.log.latest_version()
        except ColumnarProcessingError:
            return  # injected race on a log with no winner: plain retry
        pure_append = all("remove" not in a and "metaData" not in a
                          and "protocol" not in a for a in self.actions)
        my_adds = {a["add"]["path"] for a in self.actions if "add" in a}
        for v in range(attempt, latest + 1):
            try:
                winner = self.log.read_actions(v)
            except FileNotFoundError:
                continue  # gap in the log: nothing to conflict with
            except (OSError, ValueError) as exc:
                # commit files publish atomically (content-complete at
                # first visibility), so an unreadable/unparseable
                # winner is durable corruption or an access failure —
                # safety is unprovable; surface typed, never
                # blind-rebase over a winner we could not inspect
                raise DeltaConcurrentWriteException(
                    f"cannot verify concurrent commit v{v} of "
                    f"{self.log.table_path} ({exc}); not rebasing "
                    "over an unreadable winner") from exc
            for wa in winner:
                if "metaData" in wa or "protocol" in wa:
                    raise DeltaMetadataChangedException(
                        f"concurrent commit v{v} of "
                        f"{self.log.table_path} changed table "
                        "metadata/protocol; re-read the table and "
                        "re-derive the write")
                if not pure_append and ("add" in wa or "remove" in wa):
                    raise DeltaConcurrentWriteException(
                        f"concurrent commit v{v} of "
                        f"{self.log.table_path} wrote files this "
                        "transaction's removes/rewrites were derived "
                        "without; re-read the table and retry the "
                        "command")
                if "add" in wa and wa["add"].get("path") in my_adds:
                    raise DeltaConcurrentWriteException(
                        f"concurrent commit v{v} of "
                        f"{self.log.table_path} added the same file "
                        f"path {wa['add'].get('path')!r}")

    def _sweep_staged_files(self) -> int:
        """Delete the DATA files this failed transaction wrote into the
        table directory. Only files this transaction CREATED are swept:
        an add that re-stages an existing path with a deletion vector
        (DELETE/MERGE DV path) or that was live at the read snapshot is
        someone's committed data and stays."""
        pre_existing: set = set()
        if self.read_version is not None and self.read_version >= 0:
            try:
                pre_existing = {
                    a.path
                    for a in self.log.snapshot(self.read_version).files}
            except ColumnarProcessingError:
                pre_existing = set()
        swept = 0
        for act in self.actions:
            if "add" in act:
                a = act["add"]
                if a.get("deletionVector") or a["path"] in pre_existing:
                    continue
                rel = a["path"]
            elif "cdc" in act:
                rel = act["cdc"]["path"]
            else:
                continue
            full = os.path.join(self.log.table_path, rel)
            try:
                os.unlink(full)
                swept += 1
            except OSError:
                pass
        if swept:
            from spark_rapids_tpu.io.committer import WRITE_METRICS
            WRITE_METRICS.add("stagingFilesSwept", swept)
        return swept

    def commit(self, op_name: str, max_retries: Optional[int] = None) -> int:
        from spark_rapids_tpu.io.committer import unprotect_files
        try:
            return self._commit(op_name, max_retries)
        finally:
            # the txn lifecycle ends either way: committed files are in
            # the log (vacuum's live set), failed ones were swept —
            # drop the concurrent-vacuum shield
            unprotect_files(self)

    def _commit(self, op_name: str, max_retries: Optional[int]) -> int:
        from spark_rapids_tpu.io.committer import (
            WRITE_COMMIT_RETRY_WAIT_MS,
            WRITE_MAX_COMMIT_RETRIES,
            WRITE_METRICS,
        )
        if max_retries is None:
            max_retries = int(self.conf.get_entry(WRITE_MAX_COMMIT_RETRIES))
        wait_s = int(
            self.conf.get_entry(WRITE_COMMIT_RETRY_WAIT_MS)) / 1000.0
        base = self.read_version
        if base is None:
            try:
                base = self.log.latest_version()
            except ColumnarProcessingError:
                base = -1
        attempt = base + 1
        for retry in range(max_retries + 1):
            try:
                v = self.log.commit(self.actions, attempt, op_name)
                self._maybe_checkpoint(v)
                return v
            except DeltaConcurrentModificationException:
                WRITE_METRICS.add("commitConflicts", 1)
                try:
                    # typed metadata/overlap conflicts raise from here;
                    # a clean blind-append race falls through to rebase
                    self._classify_conflict(attempt)
                except DeltaConcurrentModificationException:
                    self._sweep_staged_files()
                    raise
                try:
                    attempt = self.log.latest_version() + 1
                except ColumnarProcessingError:
                    pass  # injected race before any commit exists
                if retry < max_retries:
                    WRITE_METRICS.add("commitRetries", 1)
                    if wait_s > 0:
                        time.sleep(wait_s)
        self._sweep_staged_files()
        raise DeltaConcurrentModificationException(
            f"gave up committing to {self.log.table_path} after "
            f"{max_retries} retries")

    def _maybe_checkpoint(self, version: int):
        interval = int(self.conf.get_entry(DELTA_CHECKPOINT_INTERVAL))
        if interval > 0 and version > 0 and version % interval == 0:
            self.log.write_checkpoint(self.log.snapshot(version))


def _split_partitions(table: HostTable, partition_by: List[str]):
    """Yield (partition_values dict, subdir, subtable-without-partition-
    columns)."""
    if not partition_by:
        yield {}, "", table
        return
    pdf_cols = {n: c for n, c in zip(table.names, table.columns)}
    keys = [pdf_cols[k] for k in partition_by]
    n = table.num_rows
    tags = np.zeros(n, dtype=object)
    for i in range(n):
        tags[i] = tuple(
            None if not k.validity[i] else k.data[i] for k in keys)
    data_names = [nm for nm in table.names if nm not in set(partition_by)]
    for tag in sorted(set(tags.tolist()), key=repr):
        mask = np.array([t == tag for t in tags.tolist()])
        vals = {k: (None if v is None else str(v))
                for k, v in zip(partition_by, tag)}
        subdir = "/".join(
            f"{k}={'__HIVE_DEFAULT_PARTITION__' if v is None else v}"
            for k, v in vals.items())
        sub = _mask_table(table, mask)
        idx = {nm: i for i, nm in enumerate(sub.names)}
        sub = HostTable(data_names,
                        [sub.columns[idx[nm]] for nm in data_names])
        yield vals, subdir, sub


def _null_column(dt, n: int) -> HostColumn:
    """All-null host column of ``dt`` (mergeSchema: files written before
    the evolution lack the added columns)."""
    if isinstance(dt, T.StringType) or T.is_dec128(dt):
        data = np.empty(n, dtype=object)
        data[:] = [None if isinstance(dt, T.StringType) else 0] * n
    else:
        data = np.zeros(n, dtype=dt.np_dtype)
    return HostColumn(dt, data, np.zeros(n, dtype=np.bool_))


def _check_write_compat(snap: Snapshot, schema, partition_by,
                        table_path: str, verb: str,
                        merge_schema: bool = False):
    """Returns the EFFECTIVE table schema: unchanged normally; with
    ``merge_schema`` (Spark's mergeSchema option), the union of the table
    schema and any NEW trailing columns the write adds — overlapping
    columns must still type-match (reference: delta-lake schema
    evolution support the round-4 verdict flagged as rejected here)."""
    existing = [(n, dt.simple_string()) for n, dt in snap.schema]
    incoming = [(n, dt.simple_string()) for n, dt in schema]
    if merge_schema:
        have = dict(existing)
        for n, t in incoming:
            if n in have and have[n] != t:
                raise ColumnarProcessingError(
                    f"schema mismatch {verb} {table_path}: column {n!r} "
                    f"is {have[n]} in the table but {t} in the write "
                    "(mergeSchema cannot change column types)")
        evolved = list(snap.schema) + [
            (n, dt) for n, dt in schema if n not in have]
    else:
        if existing != incoming:
            raise ColumnarProcessingError(
                f"schema mismatch {verb} {table_path}: table has "
                f"{existing}, write has {incoming} (pass "
                "merge_schema=True to evolve the schema)")
        evolved = list(snap.schema)
    table_parts = list(snap.metadata.partition_columns)
    if list(partition_by) != table_parts:
        raise ColumnarProcessingError(
            f"partitioning mismatch {verb} {table_path}: table is "
            f"partitioned by {table_parts}, write specified "
            f"{list(partition_by)}")
    return evolved


def write_delta(df_plan: PlanNode, session, table_path: str,
                mode: str = "error",
                partition_by: Optional[List[str]] = None,
                merge_schema: bool = False,
                txn_action=None) -> int:
    """modes: error | append | overwrite (Spark writer semantics).
    ``merge_schema`` allows the write to ADD columns; the widened schema
    commits as a Metadata action (Spark mergeSchema). ``txn_action``
    (a SetTransaction) commits a streaming watermark atomically with the
    data — the exactly-once sink contract rides on it."""
    if mode not in ("error", "append", "overwrite", "ignore"):
        raise ColumnarProcessingError(
            f"unknown write mode {mode!r} (error|append|overwrite|ignore)")
    partition_by = list(partition_by or [])
    log = DeltaLog(table_path)
    schema = df_plan.output_schema()
    for k in partition_by:
        if k not in [n for n, _ in schema]:
            raise ColumnarProcessingError(
                f"partition column {k!r} not in output {schema}")
    exists = log.exists()
    if exists and mode == "error":
        raise ColumnarProcessingError(
            f"delta table already exists at {table_path} (mode=error)")
    if exists and mode == "ignore":
        return log.latest_version()
    new_meta: Optional[Metadata] = None

    os.makedirs(table_path, exist_ok=True)
    table = session.execute(df_plan) if session is not None \
        else df_plan.collect_cpu()

    txn = OptimisticTransaction(log, session.conf if session else
                                RapidsConf())
    if not exists:
        txn.stage(PROTOCOL_ACTION,
                  Metadata(schema_to_json(schema), partition_by,
                           table_id=uuid.uuid4().hex))
        op = "CREATE TABLE AS SELECT"
    elif mode == "overwrite":
        snap = log.snapshot()
        evolved = _check_write_compat(snap, schema, partition_by,
                                      table_path, "overwriting",
                                      merge_schema)
        if [n for n, _ in evolved] != [n for n, _ in snap.schema]:
            new_meta = _evolved_metadata(snap.metadata, evolved,
                                         partition_by)
            txn.stage(new_meta)
        # conflict detection: the removes below are vs THIS snapshot; a
        # concurrent commit must surface, not silently survive the
        # overwrite (commit() refuses blind retry when removes are staged)
        txn.read_version = snap.version
        now = int(time.time() * 1000)
        for a in snap.files:
            txn.stage(RemoveFile(a.path, now))
        op = "WRITE (overwrite)"
    else:
        op = "WRITE (append)"
        snap = log.snapshot()
        evolved = _check_write_compat(snap, schema, partition_by,
                                      table_path, "appending to",
                                      merge_schema)
        if [n for n, _ in evolved] != [n for n, _ in snap.schema]:
            # log-recorded schema change: subsequent snapshots read the
            # widened schema; old files null-fill the new columns
            txn.read_version = snap.version
            new_meta = _evolved_metadata(snap.metadata, evolved,
                                         partition_by)
            txn.stage(new_meta)

    phys = None
    if exists:
        # an evolving write must use the EVOLVED mapping so data files
        # carry the new fields' physical names, not their logical ones
        m = new_meta if new_meta is not None else log.snapshot().metadata
        if m is not None and m.column_mapping_mode() != "none":
            phys = m.physical_names()
    for vals, subdir, sub in _split_partitions(table, partition_by):
        if sub.num_rows == 0:
            continue
        txn.stage(_write_data_file(table_path, sub, vals, subdir,
                                   physical=phys))
    if txn_action is not None:
        txn.stage(txn_action)
    return txn.commit(op)
