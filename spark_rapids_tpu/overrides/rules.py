"""Meta/tag/convert rules (reference: GpuOverrides exec/expr registries +
RapidsMeta hierarchy + GpuTransitionOverrides)."""

from __future__ import annotations

import copy
from typing import Callable, Dict, List, Optional, Sequence, Type

from spark_rapids_tpu import conf as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import RapidsConf, register_op_kill_switch
from spark_rapids_tpu.execs import (
    DeviceToHost,
    HostToDevice,
    InputAdapter,
    TpuCoalesceExec,
    TpuExec,
    TpuExpandExec,
    TpuFileScanExec,
    TpuFilterExec,
    TpuHashAggregateExec,
    TpuLimitExec,
    TpuProjectExec,
    TpuRangeExec,
    TpuScanExec,
    TpuSortExec,
    TpuUnionExec,
)
from spark_rapids_tpu.execs.aggregate import DEVICE_SUPPORTED_AGGS
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops.expr import Expression
from spark_rapids_tpu.overrides.typesig import (
    COMMON,
    COMMON_128,
    COMMON_PLUS_ARRAYS,
    COMMON_PLUS_NESTED,
    DEC128,
    INTEGRAL,
    NESTED_128,
    ORDERABLE,
    AnyOfSig,
    TypeSig,
)
from spark_rapids_tpu.plan import nodes as P

# ---------------------------------------------------------------------------
# Expression support checking
# ---------------------------------------------------------------------------

#: expression classes with device implementations; populated lazily from the
#: ops modules. Each entry maps class -> TypeSig for its OUTPUT type.
_EXPR_SIGS: Dict[type, TypeSig] = {}

#: per-parameter input checks (ExprChecks analog). Classes absent here
#: check only their output sig (legacy behavior).
_EXPR_CHECKS: Dict[type, "ExprChecks"] = {}


def _build_expr_sigs():
    if _EXPR_SIGS:
        return
    from spark_rapids_tpu.ops import (
        arithmetic,
        cast,
        conditional,
        datetime as datetime_ops,
        hashfns,
        math,
        predicates,
        strings,
    )
    from spark_rapids_tpu.ops import expr as expr_mod

    def reg(cls, sig=COMMON):
        _EXPR_SIGS[cls] = sig
        register_op_kill_switch("expression", cls.__name__, True,
                               f"Enable {cls.__name__} on the accelerator.")

    for mod in (arithmetic, conditional, math, predicates, strings,
                datetime_ops, hashfns):
        for name in dir(mod):
            obj = getattr(mod, name)
            if (isinstance(obj, type) and issubclass(obj, Expression)
                    and not name.startswith("_")
                    and obj.__module__ == mod.__name__
                    and "_is_expr_base" not in vars(obj)  # skip abstract bases
                    and "eval_dev" in {m for kls in obj.__mro__ for m in vars(kls)}
                    and getattr(obj, "eval_dev", None) is not Expression.eval_dev):
                reg(obj)
    reg(expr_mod.BoundReference, NESTED_128)
    reg(expr_mod.Literal)
    reg(expr_mod.Alias, NESTED_128)
    reg(cast.Cast)
    from spark_rapids_tpu.ops import json_fns
    reg(json_fns.GetJsonObject)
    from spark_rapids_tpu import udf as udf_mod
    reg(udf_mod.ColumnarDeviceUDF)
    from spark_rapids_tpu.ops import decimal as decimal_ops
    for name in ("DecimalAdd", "DecimalSubtract", "DecimalMultiply",
                 "DecimalDivide", "DecimalRemainder", "DecimalPmod",
                 "UnscaledValue", "MakeDecimal", "CheckOverflow"):
        # DecimalRemainder/DecimalPmod were shipped with device kernels
        # but never registered — the registry auditor (RA-UNREGISTERED)
        # caught decimal % silently falling back to CPU
        reg(getattr(decimal_ops, name))
    from spark_rapids_tpu.ops import misc as misc_ops
    for name in ("NormalizeNaNAndZero", "KnownFloatingPointNormalized",
                 "KnownNotNull", "AtLeastNNonNulls",
                 "MonotonicallyIncreasingID", "SparkPartitionID", "Rand",
                 "FromUTCTimestamp", "ToUTCTimestamp", "Md5", "ConcatWs"):
        reg(getattr(misc_ops, name))
    from spark_rapids_tpu.ops import collections as coll
    reg(coll.Size)
    reg(coll.GetArrayItem)
    reg(coll.ArrayContains)
    reg(coll.ArrayMin)
    reg(coll.ArrayMax)
    reg(coll.SortArray, COMMON_PLUS_ARRAYS)
    reg(coll.CreateArray, COMMON_PLUS_ARRAYS)
    from spark_rapids_tpu.ops import nested as nested_ops
    for name in ("CreateNamedStruct", "GetStructField", "CreateMap",
                 "GetMapValue", "MapKeys", "MapValues", "MapEntries",
                 "MapConcat", "MapFilter", "TransformKeys",
                 "TransformValues", "ArrayTransform", "ArrayFilter",
                 "ArrayExists", "ArrayForAll", "ArraysZip"):
        reg(getattr(nested_ops, name), COMMON_PLUS_NESTED)
    from spark_rapids_tpu.ops.bloom import BloomFilterMightContain
    reg(BloomFilterMightContain)
    from spark_rapids_tpu.ops import inputfile as if_ops
    for name in ("InputFileName", "InputFileBlockStart",
                 "InputFileBlockLength"):
        reg(getattr(if_ops, name))
    reg(coll.Sequence, COMMON_PLUS_ARRAYS)
    from spark_rapids_tpu.ops import json_structs as js
    reg(js.JsonToStructs, COMMON_PLUS_NESTED)
    reg(js.StructsToJson, COMMON_PLUS_NESTED)
    for fn in DEVICE_SUPPORTED_AGGS:
        reg(fn)
    _register_param_checks(arithmetic, math, predicates, strings,
                           datetime_ops)


def _register_param_checks(arithmetic, math, predicates, strings,
                           datetime_ops):
    """Per-parameter input signatures (reference: ExprChecks — the
    per-param half of TypeChecks.scala). Base classes cover whole
    families through the MRO walk; irregular operators get explicit
    entries. Without these, only OUTPUT types gate fallback, so
    ``Acos(string_col)`` would claim device support (its output is
    always DOUBLE) — the round-4 matrix-honesty finding."""
    from spark_rapids_tpu.overrides.typesig import ExprChecks

    STR = TypeSig(T.StringType)
    BOOL = TypeSig(T.BooleanType)
    NUM_DEC = TypeSig(T.ByteType, T.ShortType, T.IntegerType, T.LongType,
                      T.FloatType, T.DoubleType, T.DecimalType)
    NUMERIC = TypeSig(T.ByteType, T.ShortType, T.IntegerType, T.LongType,
                      T.FloatType, T.DoubleType)
    DT_IN = TypeSig(T.DateType, T.TimestampType)

    def chk(cls, *params, rest=None):
        _EXPR_CHECKS[cls] = ExprChecks(params, rest=rest)

    # family bases (MRO lookup extends them to every subclass)
    chk(arithmetic.BinaryArithmetic, NUM_DEC, NUM_DEC)
    chk(math.UnaryMath, NUMERIC)
    chk(predicates.BinaryComparison, COMMON_128, COMMON_128)

    # arithmetic irregulars
    chk(arithmetic.Abs, NUM_DEC)
    chk(arithmetic.UnaryMinus, NUM_DEC)
    chk(arithmetic.UnaryPositive, NUM_DEC)
    # math irregulars (binary / integer-domain)
    for cls in (math.Pow, math.Hypot, math.Logarithm):
        chk(cls, NUMERIC, NUMERIC)
    for cls in (math.BitwiseAnd, math.BitwiseOr, math.BitwiseXor):
        chk(cls, INTEGRAL, INTEGRAL)
    chk(math.BitwiseNot, INTEGRAL)
    for cls in (math.ShiftLeft, math.ShiftRight, math.ShiftRightUnsigned):
        chk(cls, INTEGRAL, INTEGRAL)
    for cls in (math.Round, math.BRound, math.RoundCeil, math.RoundFloor):
        chk(cls, NUM_DEC, INTEGRAL)
    for cls in (math.Ceil, math.Floor):
        chk(cls, NUM_DEC)
    # predicates
    chk(predicates.And, BOOL, BOOL)
    chk(predicates.Or, BOOL, BOOL)
    chk(predicates.Not, BOOL)
    chk(predicates.IsNaN, NUMERIC)
    chk(predicates.IsNull, NESTED_128)
    chk(predicates.IsNotNull, NESTED_128)
    # strings: data params are STRING; positions/lengths are integral
    for name in ("Upper", "Lower", "Length", "InitCap", "Reverse",
                 "Ascii", "BitLength", "OctetLength", "StringTrim",
                 "StringTrimLeft", "StringTrimRight"):
        chk(getattr(strings, name), STR)
    for name in ("Contains", "StartsWith", "EndsWith", "Like", "RLike",
                 "StringInstr"):
        chk(getattr(strings, name), STR, STR)
    chk(strings.Substring, STR, INTEGRAL, INTEGRAL)
    chk(strings.SubstringIndex, STR, STR, INTEGRAL)
    chk(strings.StringRepeat, STR, INTEGRAL)
    chk(strings.StringReplace, STR, STR, STR)
    chk(strings.StringTranslate, STR, STR, STR)
    chk(strings.StringLocate, STR, STR, INTEGRAL)
    chk(strings.StringLPad, STR, INTEGRAL, STR)
    chk(strings.StringRPad, STR, INTEGRAL, STR)
    chk(strings.Concat, rest=STR)
    chk(strings.RegExpExtract, STR, STR, INTEGRAL)
    chk(strings.RegExpReplace, STR, STR, STR)
    chk(strings.Conv, STR, INTEGRAL, INTEGRAL)
    # datetime: field extraction takes DATE/TIMESTAMP; arithmetic mixes
    for name in ("Year", "Month", "DayOfMonth", "DayOfWeek", "DayOfYear",
                 "Quarter", "WeekDay", "LastDay", "Hour", "Minute",
                 "Second", "TsToDate"):
        chk(getattr(datetime_ops, name), DT_IN)
    # hash EXPRESSIONS over p>18 decimals fall back: their user-visible
    # value must be Spark's byte-array murmur3/xxhash (the CPU path is
    # Spark-exact); the device limb-pair hash serves only partitioning
    from spark_rapids_tpu.ops import hashfns
    chk(hashfns.Murmur3Hash, rest=COMMON)
    chk(hashfns.XxHash64, rest=COMMON)
    chk(datetime_ops.DateAdd, TypeSig(T.DateType), INTEGRAL)
    chk(datetime_ops.DateSub, TypeSig(T.DateType), INTEGRAL)
    chk(datetime_ops.AddMonths, TypeSig(T.DateType), INTEGRAL)
    chk(datetime_ops.DateDiff, TypeSig(T.DateType), TypeSig(T.DateType))


def check_expr(e: Expression, conf: RapidsConf, reasons: List[str], context: str = ""):
    """Recursively verify a bound expression tree can run on device."""
    _build_expr_sigs()
    from spark_rapids_tpu.overrides.typesig import lookup_mro
    cls = type(e)
    where = f"{context}{cls.__name__}"
    sig = lookup_mro(_EXPR_SIGS, cls)
    if sig is None:
        reasons.append(f"expression {where} is not supported on TPU")
        return
    if not conf.is_op_enabled("expression", cls.__name__):
        reasons.append(f"expression {where} is disabled by conf")
        return
    try:
        dt = e.data_type
    except Exception:
        dt = None
    if dt is not None and not sig.supports(dt):
        reasons.append(f"expression {where} produces unsupported type {dt.simple_string()}")
    if not e.device_supported:
        reasons.append(f"expression {where} configuration is not supported on TPU")
    # per-PARAMETER input checks (ExprChecks analog): the output type of
    # e.g. Acos is DOUBLE no matter what, so only input-position sigs can
    # reject Acos(string_col)
    checks = lookup_mro(_EXPR_CHECKS, cls)
    if checks is not None:
        for i, c in enumerate(e.children):
            psig = checks.param_sig(i)
            if psig is None:
                continue
            try:
                cdt = c.data_type
            except Exception:
                cdt = None
            if cdt is not None and not psig.supports(cdt):
                reasons.append(
                    f"expression {where} input {i} has unsupported type "
                    f"{cdt.simple_string()}")
    for c in e.children:
        check_expr(c, conf, reasons, context)
    # higher-order functions carry their rebound lambda body OUTSIDE
    # children (ops/nested.py); its expressions face the same sig/conf
    # gating as everything else
    body = getattr(e, "_rebound", None)
    if body is not None:
        check_expr(body, conf, reasons, context + "lambda body ")


# ---------------------------------------------------------------------------
# Exec rules
# ---------------------------------------------------------------------------

class ExecRule:
    def __init__(self, node_cls: Type[P.PlanNode],
                 tag_fn: Callable[["PlanMeta", RapidsConf], None],
                 convert_fn: Callable[[P.PlanNode, List[TpuExec]], TpuExec],
                 doc: str = ""):
        self.node_cls = node_cls
        self.tag_fn = tag_fn
        self.convert_fn = convert_fn
        register_op_kill_switch("exec", node_cls.__name__, True,
                               doc or f"Enable {node_cls.__name__} on the accelerator.")


_EXEC_RULES: Dict[type, ExecRule] = {}


def exec_rule(node_cls, tag_fn, convert_fn, doc=""):
    _EXEC_RULES[node_cls] = ExecRule(node_cls, tag_fn, convert_fn, doc)


def _check_output_schema(meta: "PlanMeta", conf: RapidsConf,
                         sig=COMMON_128):
    for name, dt in meta.node.output_schema():
        r = sig.reason_if_unsupported(dt, f"output column {name}")
        if r:
            meta.reasons.append(r)


def _tag_scan(meta, conf):
    # scans may carry fixed-element arrays, fixed-field structs and
    # fixed-width maps (device representations in columnar/)
    _check_output_schema(meta, conf, NESTED_128)


def _tag_project(meta, conf):
    _check_output_schema(meta, conf, NESTED_128)
    for e in meta.node.exprs:
        check_expr(e, conf, meta.reasons)


def _tag_generate(meta, conf):
    from spark_rapids_tpu.ops.collections import is_fixed_array
    node = meta.node
    _check_output_schema(meta, conf, COMMON_PLUS_ARRAYS)
    check_expr(node.gen_child, conf, meta.reasons, "generator input ")
    if not is_fixed_array(node.gen_child.data_type):
        meta.reasons.append(
            f"generator over {node.gen_child.data_type.simple_string()} "
            "requires fixed-width array elements on TPU")
    child_schema = dict(node.children[0].output_schema())
    for n in node.required:
        if isinstance(child_schema[n], T.ArrayType):
            meta.reasons.append(
                f"array column {n} passing THROUGH a generator is not "
                "supported on TPU (prune it or explode it)")


def _tag_filter(meta, conf):
    _check_output_schema(meta, conf)
    check_expr(meta.node.condition, conf, meta.reasons)


def _tag_aggregate(meta, conf):
    # collect_list/set OUTPUT fixed-element arrays; array-typed grouping
    # keys / other agg inputs stay CPU (flat-buffer kernels)
    _check_output_schema(meta, conf, AnyOfSig(COMMON_PLUS_ARRAYS, DEC128))
    node: P.Aggregate = meta.node
    for g in node.grouping:
        check_expr(g, conf, meta.reasons, "grouping key ")
        if isinstance(g.data_type, T.ArrayType):
            meta.reasons.append("array-typed grouping keys are not "
                                "supported on TPU")
    from spark_rapids_tpu.execs.aggregate import SORT_ONLY_AGGS
    for name, fn in node.agg_specs:
        if not isinstance(fn, DEVICE_SUPPORTED_AGGS):
            meta.reasons.append(f"aggregate {type(fn).__name__} is not supported on TPU")
            continue
        if fn.child is not None:
            check_expr(fn.child, conf, meta.reasons, f"aggregate {name} input ")
            if isinstance(fn.child.data_type, T.ArrayType) and not isinstance(
                    fn, (agg.CollectList, agg.CollectSet)):
                meta.reasons.append(
                    f"aggregate {name} over an array input is not "
                    "supported on TPU")
            if T.is_dec128(fn.child.data_type) and not isinstance(
                    fn, (agg.Count, agg.Sum, agg.Min, agg.Max)):
                # count/sum/min/max run as two-limb device kernels
                # (exact limb sums, lexicographic min/max); the rest
                # (avg, collect, percentile, moments) fall back
                meta.reasons.append(
                    f"aggregate {name} over a decimal(>18) input is not "
                    "supported on TPU")


def _tag_sort(meta, conf):
    _check_output_schema(meta, conf)
    for o in meta.node.orders:
        check_expr(o.expr, conf, meta.reasons, "sort key ")
        dt = o.expr.data_type
        if not COMMON_128.supports(dt):
            meta.reasons.append(f"sort key type {dt.simple_string()} not orderable on TPU")


def _tag_simple(meta, conf):
    _check_output_schema(meta, conf)


def _tag_expand(meta, conf):
    _check_output_schema(meta, conf)
    for proj in meta.node.projections:
        for e in proj:
            check_expr(e, conf, meta.reasons)


_SUPPORTED_JOIN_TYPES = {"inner", "cross", "left", "leftouter", "right",
                         "rightouter", "full", "fullouter", "outer",
                         "leftsemi", "leftanti"}


def _tag_join(meta, conf):
    _check_output_schema(meta, conf)
    node: P.Join = meta.node
    jt = node.join_type.lower().replace("_", "")
    if jt not in _SUPPORTED_JOIN_TYPES:
        meta.reasons.append(f"join type {node.join_type} is not supported on TPU")
        return
    if len(node.left_keys) != len(node.right_keys):
        meta.reasons.append(
            f"join key count mismatch: {len(node.left_keys)} vs {len(node.right_keys)}")
        return
    for k in list(node.left_keys) + list(node.right_keys):
        check_expr(k, conf, meta.reasons, "join key ")
        dt = k.data_type
        if not ORDERABLE.supports(dt):
            meta.reasons.append(f"join key type {dt.simple_string()} not supported on TPU")
    for lk, rk in zip(node.left_keys, node.right_keys):
        try:
            if lk.data_type != rk.data_type:
                T.promote(lk.data_type, rk.data_type)
        except TypeError:
            meta.reasons.append(
                f"join key types {lk.data_type} vs {rk.data_type} incompatible")
    if node.condition is not None and not node.left_keys:
        # keyless nested-loop join: the build side broadcasts whole; a
        # KNOWN-oversized build must not OOM the device (unknown estimates
        # proceed — Spark also runs BNLJ as a last resort)
        from spark_rapids_tpu.conf import BROADCAST_SIZE_BYTES
        swapped_nlj = jt in ("right", "rightouter")
        build = node.children[0] if swapped_nlj else node.children[1]
        est = build.estimate_bytes()
        limit = 8 * conf.get_entry(BROADCAST_SIZE_BYTES)
        if est is not None and est > limit:
            meta.reasons.append(
                f"nested-loop build side estimate {est}B exceeds "
                f"8x broadcastSizeBytes ({limit}B)")
    if node.condition is not None:
        if node.left_keys and jt not in ("inner", "cross"):
            # equi keys + residual non-equi condition on outer/semi/anti:
            # post-filtering changes match semantics (reference: AstUtil
            # splits AST-able conditions; this engine runs KEYLESS
            # conditioned joins on the nested-loop exec instead)
            meta.reasons.append(
                f"non-equi condition on equi {jt} join is not supported on TPU")
        else:
            check_expr(node.condition, conf, meta.reasons, "join condition ")


def _convert_generate(node: P.Generate, children, conf):
    from spark_rapids_tpu.execs.generate import TpuGenerateExec
    return TpuGenerateExec(children[0], node.gen_child, node.pos,
                           node.outer, node.out_names, node.required)


def _convert_sample(node: P.Sample, children, conf):
    from spark_rapids_tpu.execs.basic import TpuSampleExec
    return TpuSampleExec(children[0], node.fraction, node.seed)


def _convert_take_ordered(node: P.TakeOrderedAndProject, children, conf):
    from spark_rapids_tpu.execs.sort import TpuTakeOrderedAndProjectExec
    return TpuTakeOrderedAndProjectExec(children[0], node.orders, node.limit,
                                        node.project, node.project_names)


def _convert_cached(node: P.CachedRelation, children, conf):
    from spark_rapids_tpu.conf import SCAN_DEVICE_CACHE
    return TpuScanExec([node.materialize()],
                       device_cache=conf.get_entry(SCAN_DEVICE_CACHE))


def _tag_take_ordered(meta, conf):
    _tag_sort(meta, conf)  # same output-schema + sort-key rules
    if meta.node.project is not None:
        for e in meta.node.project:
            check_expr(e, conf, meta.reasons)


def _convert_scan(node: P.LocalScan, children, conf):
    from spark_rapids_tpu.conf import SCAN_DEVICE_CACHE
    return TpuScanExec(node.batches,
                       device_cache=conf.get_entry(SCAN_DEVICE_CACHE))


def _convert_range(node: P.RangeNode, children, conf):
    return TpuRangeExec(node.start, node.end, node.step, node.batch_rows, node.col_name)


def _convert_project(node: P.Project, children, conf):
    return TpuProjectExec(children[0], node.exprs, node.names)


def _convert_filter(node: P.Filter, children, conf):
    return TpuFilterExec(children[0], node.condition)


def _convert_aggregate(node: P.Aggregate, children, conf):
    from spark_rapids_tpu.conf import (AGG_FUSE_INPUT, AGG_MAX_DICT_GROUPS,
                                       AGG_MAX_KEY_DOMAIN_GROUPS)
    from spark_rapids_tpu.execs.fuse import peel_input_chain
    from spark_rapids_tpu.ops.segsum import resolve_split_mode

    child = children[0]
    grouping = list(node.grouping)
    agg_specs = list(node.agg_specs)
    filters = []
    if conf.get_entry(AGG_FUSE_INPUT):
        exprs = grouping + [fn for _, fn in agg_specs]
        child, exprs, filters = peel_input_chain(child, exprs)
        grouping = exprs[:len(grouping)]
        agg_specs = [(n, fn) for (n, _), fn in
                     zip(agg_specs, exprs[len(grouping):])]
    # target-size coalesce (NOT RequireSingleBatch): inputs above the batch
    # target stream through the partial-per-batch merge path. Collect/
    # percentile have no merge decomposition yet -> one coalesced batch.
    from spark_rapids_tpu.execs.aggregate import SORT_ONLY_AGGS
    if any(isinstance(fn, SORT_ONLY_AGGS) for _, fn in agg_specs):
        coalesced = TpuCoalesceExec(child, require_single=True)
    else:
        coalesced = TpuCoalesceExec(child, target_bytes=conf.batch_size_bytes)
    return TpuHashAggregateExec(coalesced, grouping, agg_specs,
                                node.grouping_names,
                                filters=filters,
                                use_split=resolve_split_mode(conf),
                                max_dict_groups=conf.get_entry(AGG_MAX_DICT_GROUPS),
                                max_domain_groups=conf.get_entry(
                                    AGG_MAX_KEY_DOMAIN_GROUPS))


def _convert_sort(node: P.Sort, children, conf):
    from spark_rapids_tpu.conf import SORT_OOC_THRESHOLD
    ooc = conf.get_entry(SORT_OOC_THRESHOLD)
    # the pre-sort coalesce must not merge past the out-of-core threshold,
    # or the sort would never see separable runs to spill
    coalesced = TpuCoalesceExec(
        children[0], target_bytes=min(conf.batch_size_bytes, ooc))
    ex = TpuSortExec(coalesced, node.orders)
    ex.ooc_threshold_bytes = ooc
    return ex


def _convert_limit(node: P.Limit, children, conf):
    return TpuLimitExec(children[0], node.limit)


def _convert_union(node: P.Union, children, conf):
    return TpuUnionExec(children)


def _convert_expand(node: P.Expand, children, conf):
    return TpuExpandExec(children[0], node.projections, node.names)


def _tag_exchange(meta, conf):
    _check_output_schema(meta, conf)
    node: P.Exchange = meta.node
    if node.partitioning not in ("hash", "range", "roundrobin", "single"):
        meta.reasons.append(
            f"partitioning {node.partitioning} is not supported on TPU")
        return
    if node.partitioning == "hash" and not node.keys:
        meta.reasons.append("hash partitioning requires keys")
    for k in node.keys:
        check_expr(k, conf, meta.reasons, "partition key ")
    if not meta.reasons:
        # mesh/ICI demotion note: the exchange still runs on device, but
        # an ICI-requested collective that must take the host-file
        # shuffle surfaces WHY here — the exec acts on the same static
        # reason at execution (hostShuffleFallbacks metric)
        from spark_rapids_tpu.execs.exchange import (
            collective_applicable,
            ici_demotion_reason,
            ici_requested,
        )
        if ici_requested(conf) and collective_applicable(
                node.partitioning, node.num_partitions):
            reason = ici_demotion_reason(
                conf, node.partitioning, node.num_partitions,
                node.children[0].output_schema())
            if reason is not None:
                meta.notes.append(f"host-shuffle fallback: {reason}")


def _convert_exchange(node: P.Exchange, children, conf):
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    return TpuShuffleExchangeExec(children[0], node.partitioning,
                                  node.num_partitions, node.keys, conf,
                                  target_batch_bytes=conf.batch_size_bytes)


def _convert_join(node: P.Join, children, conf):
    from spark_rapids_tpu.execs.join import TpuJoinExec
    from spark_rapids_tpu.ops.cast import Cast

    lkeys = list(node.left_keys)
    rkeys = list(node.right_keys)
    for i, (lk, rk) in enumerate(zip(lkeys, rkeys)):
        if lk.data_type != rk.data_type:
            target = T.promote(lk.data_type, rk.data_type)
            if lk.data_type != target:
                lkeys[i] = Cast(lk, target)
            if rk.data_type != target:
                rkeys[i] = Cast(rk, target)
    from spark_rapids_tpu.conf import (
        BROADCAST_SIZE_BYTES,
        JOIN_SUBPARTITION_BYTES,
    )
    from spark_rapids_tpu.execs.broadcast import (
        TpuBroadcastExchangeExec,
        TpuNestedLoopJoinExec,
    )

    jt = node.join_type.lower().replace("_", "")
    swapped = jt in ("right", "rightouter")
    target = conf.batch_size_bytes

    if not lkeys and (node.condition is not None or jt != "cross"):
        # keyless conditioned join -> broadcast nested-loop
        if swapped:
            left = TpuBroadcastExchangeExec(children[0])
            right = TpuCoalesceExec(children[1], target_bytes=target)
        else:
            left = TpuCoalesceExec(children[0], target_bytes=target)
            right = TpuBroadcastExchangeExec(children[1])
        return TpuNestedLoopJoinExec(left, right, node.join_type,
                                     node.condition,
                                     node.children[0].output_schema(),
                                     node.children[1].output_schema())

    # equi join (and pure cross): the BUILD side is a single table — a
    # BROADCAST exchange when its size estimate is under the threshold
    # (GpuBroadcastHashJoinExec planning), else a coalesce with
    # sub-partition escalation; the PROBE side streams target-sized batches
    build_node = node.children[0] if swapped else node.children[1]
    est = build_node.estimate_bytes()
    threshold = conf.get_entry(BROADCAST_SIZE_BYTES)
    broadcast = est is not None and est <= threshold

    def wrap_build(child):
        if broadcast:
            return TpuBroadcastExchangeExec(child)
        from spark_rapids_tpu.conf import ADAPTIVE_ENABLED
        if conf.get_entry(ADAPTIVE_ENABLED):
            # AQE: the static estimate couldn't prove broadcast; defer the
            # strategy to runtime-measured build size
            from spark_rapids_tpu.execs.broadcast import TpuAdaptiveBuildExec
            return TpuAdaptiveBuildExec(child, threshold)
        return TpuCoalesceExec(child, require_single=True)

    if swapped:
        left = wrap_build(children[0])
        right = TpuCoalesceExec(children[1], target_bytes=target)
    else:
        left = TpuCoalesceExec(children[0], target_bytes=target)
        right = wrap_build(children[1])
    from spark_rapids_tpu.conf import JOIN_MAX_SUBPARTITIONS
    join = TpuJoinExec(left, right, node.join_type, lkeys, rkeys,
                       node.condition,
                       node.children[0].output_schema(),
                       node.children[1].output_schema(),
                       subpartition_bytes=conf.get_entry(JOIN_SUBPARTITION_BYTES),
                       max_subpartitions=conf.get_entry(JOIN_MAX_SUBPARTITIONS))
    from spark_rapids_tpu.conf import DPP_ENABLED
    if broadcast and conf.get_entry(DPP_ENABLED) and not swapped:
        # only inner/leftsemi qualify (checked inside), so the probe is
        # always the LEFT side here
        _maybe_install_dpp(jt, left, right, lkeys, rkeys)
    return join


def _maybe_install_dpp(jt: str, probe_exec, build_exec, probe_keys,
                       build_keys) -> None:
    """Dynamic partition pruning (reference: DynamicPruningExpression /
    SubqueryBroadcast planned into GpuFileSourceScanExec partitionFilters;
    dpp_test.py): when the probe side of a BROADCAST join scans a
    Hive-partitioned source and a join key resolves to a partition column,
    install a pruning filter on the scan that reads the build side's
    distinct key values from the (already materialized, cached) broadcast
    — probe file IO then skips partitions that cannot match. Only join
    types that DROP unmatched probe rows qualify."""
    from spark_rapids_tpu.execs.basic import (
        TpuCoalesceExec,
        TpuFileScanExec,
        TpuFilterExec,
        TpuProjectExec,
    )
    from spark_rapids_tpu.ops.expr import Alias, BoundReference

    # inner/semi drop unmatched probe rows -> pruning is sound; outer
    # joins keep them (right-outer keeps the PROBE side) -> never prune
    if jt not in ("inner", "leftsemi"):
        return
    for pk, bk in zip(probe_keys, build_keys):
        e = pk
        while isinstance(e, Alias):
            e = e.children[0]
        if not isinstance(e, BoundReference):
            continue
        ordinal = e.ordinal
        cur = probe_exec
        scan_exec = None
        while True:
            if isinstance(cur, (TpuCoalesceExec, TpuFilterExec)):
                cur = cur.children[0]
            elif isinstance(cur, TpuProjectExec):
                pe = cur.exprs[ordinal]
                while isinstance(pe, Alias):
                    pe = pe.children[0]
                if not isinstance(pe, BoundReference):
                    break
                ordinal = pe.ordinal
                cur = cur.children[0]
            elif isinstance(cur, TpuFileScanExec):
                scan_exec = cur
                break
            else:
                break
        if scan_exec is None:
            continue
        scan_node = scan_exec.scan_node
        schema = scan_node.output_schema()
        if ordinal >= len(schema):
            continue
        col_name = schema[ordinal][0]
        scan_node._resolve_schemas()
        part_names = {n for n, _ in (scan_node._partition_schema or [])}
        if col_name not in part_names:
            continue

        def provider(build_exec=build_exec, bk=bk):
            from spark_rapids_tpu.ops.expr import compile_project
            batches = list(build_exec.execute())
            allowed = set()
            for bt in batches:
                kcol = compile_project([bk], bt)[0]
                host = kcol.to_host(bt.num_rows)
                for v, ok in zip(host.data, host.validity):
                    if ok:
                        allowed.add(v.item() if hasattr(v, "item") else v)
            return allowed

        scan_exec.install_dynamic_pruning(col_name, provider)


def _convert_file_scan(node, children, conf):
    return TpuFileScanExec(node)


def register_file_scan(cls):
    """Register a FileScanNode subclass with a kill switch. Called from
    spark_rapids_tpu.io at ITS import time so the core engine never
    hard-requires pyarrow (reference: per-format
    spark.rapids.sql.format.<fmt>.* keys)."""
    exec_rule(cls, _tag_scan, _convert_file_scan,
              f"Enable {cls.format_name} scans on the accelerator.")


from spark_rapids_tpu.overrides.docs import register_exec_sig

# doc sigs mirror the _check_output_schema call each _tag_* makes, so
# the generated matrix states what tagging actually falls back on —
# notably DECIMAL128 is S wherever storage-level machinery carries it
# (VERDICT r5 weak #3: exec rows said NS while test_decimal128.py proves
# device group-by/join/sort on p38 keys). Execs not registered here doc
# as COMMON_128, the _check_output_schema default.
for _cls in (P.LocalScan, P.Project, P.CachedRelation):
    register_exec_sig(_cls, NESTED_128)
register_exec_sig(P.Generate, COMMON_PLUS_ARRAYS)
register_exec_sig(P.Aggregate, AnyOfSig(COMMON_PLUS_ARRAYS, DEC128))

exec_rule(P.LocalScan, _tag_scan, _convert_scan)
exec_rule(P.RangeNode, _tag_simple, _convert_range)
exec_rule(P.Project, _tag_project, _convert_project)
exec_rule(P.Filter, _tag_filter, _convert_filter)
exec_rule(P.Aggregate, _tag_aggregate, _convert_aggregate)
exec_rule(P.Sort, _tag_sort, _convert_sort)
exec_rule(P.Limit, _tag_simple, _convert_limit)
exec_rule(P.Union, _tag_simple, _convert_union)
exec_rule(P.Expand, _tag_expand, _convert_expand)
def _tag_window(meta, conf):
    from spark_rapids_tpu.execs.window import device_window_supported
    _check_output_schema(meta, conf)
    node: P.WindowNode = meta.node
    from spark_rapids_tpu.conf import IMPROVED_FLOAT_OPS
    vfa = bool(conf.get_entry(IMPROVED_FLOAT_OPS))
    for name, w in node.window_cols:
        from spark_rapids_tpu.conf import WINDOW_ROWS_FRAME_MAX_BOUND
        ok, reason = device_window_supported(
            w, variable_float_agg=vfa,
            rows_frame_max_bound=conf.get_entry(WINDOW_ROWS_FRAME_MAX_BOUND))
        if not ok:
            meta.reasons.append(f"window {name}: {reason}")
            continue
        fn_child = getattr(w.function, "children", ())
        for cexp in fn_child:
            if T.is_dec128(cexp.data_type):
                meta.reasons.append(
                    f"window {name} over a decimal(>18) input is not "
                    "supported on TPU")
        for p in w.spec.partition_exprs:
            check_expr(p, conf, meta.reasons, f"window {name} partition key ")
        for o in w.spec.orders:
            check_expr(o.expr, conf, meta.reasons, f"window {name} order key ")
        for c in w.function.children:  # covers aggregate inputs too
            check_expr(c, conf, meta.reasons, f"window {name} input ")


def _convert_window(node: P.WindowNode, children, conf):
    from spark_rapids_tpu.execs.window import TpuKeyedBatchExec, TpuWindowExec

    # batched windows (GpuKeyBatchingIterator analog): when every window
    # spec shares the SAME partition keys, batches can split at partition
    # boundaries and window independently — out-of-core instead of
    # require-single. Global (unpartitioned) or mixed-key windows keep the
    # single-batch path.
    specs = [w.spec for _, w in node.window_cols]
    probe = TpuWindowExec.__new__(TpuWindowExec)
    probe.window_cols = list(node.window_cols)
    bounded = probe._bounded_ctx(children[0].output_schema())
    if bounded is not None:
        # finite-rows frames stream range by range with carried context
        # (GpuBatchedBoundedWindowExec analog) — scales past both the
        # whole-input concat AND a single giant partition; no coalesce:
        # each input batch becomes a sorted host run directly
        return TpuWindowExec(
            children[0], node.window_cols,
            stream_target_rows=int(conf.get_entry(
                C.WINDOW_STREAM_TARGET_ROWS)))
    probe.children = (children[0],)
    if probe._two_pass_able():
        # whole-partition agg windows: cached double-pass (streaming
        # aggregate + join-back) — GpuCachedDoublePassWindowExec analog
        from spark_rapids_tpu.ops.segsum import resolve_split_mode
        return TpuWindowExec(children[0], node.window_cols,
                             use_split=resolve_split_mode(conf),
                             stream_target_rows=int(conf.get_entry(
                                 C.WINDOW_STREAM_TARGET_ROWS)))
    keys0 = [p.key() for p in specs[0].partition_exprs] if specs else []
    same_keys = keys0 and all(
        [p.key() for p in s.partition_exprs] == keys0 for s in specs)
    if same_keys:
        batched = TpuKeyedBatchExec(children[0],
                                    specs[0].partition_exprs, conf)
        return TpuWindowExec(batched, node.window_cols, per_batch=True)
    if probe._streamable():
        # partition-less running windows STREAM with carried state
        # (GpuRunningWindowExec analog) — no require-single concat
        coalesced = TpuCoalesceExec(children[0],
                                    target_bytes=conf.batch_size_bytes)
    else:
        coalesced = TpuCoalesceExec(children[0], require_single=True)
    return TpuWindowExec(coalesced, node.window_cols)


exec_rule(P.Join, _tag_join, _convert_join)
exec_rule(P.Generate, _tag_generate, _convert_generate)
exec_rule(P.Sample, _tag_simple, _convert_sample)
exec_rule(P.TakeOrderedAndProject, _tag_take_ordered, _convert_take_ordered)
exec_rule(P.CollectLimit, _tag_simple,
          lambda node, children, conf: TpuLimitExec(children[0], node.limit))
exec_rule(P.CachedRelation, _tag_scan, _convert_cached)
def _tag_window_group_limit(meta, conf):
    _check_output_schema(meta, conf)
    node: P.WindowGroupLimit = meta.node
    for e in node.partition_exprs:
        check_expr(e, conf, meta.reasons, "group-limit partition key ")
    for o in node.orders:
        check_expr(o.expr, conf, meta.reasons, "group-limit order key ")


def _convert_window_group_limit(node: P.WindowGroupLimit, children, conf):
    from spark_rapids_tpu.execs.window import TpuWindowGroupLimitExec
    return TpuWindowGroupLimitExec(children[0], node.partition_exprs,
                                   node.orders, node.rank_kind, node.limit)


exec_rule(P.WindowGroupLimit, _tag_window_group_limit,
          _convert_window_group_limit)
exec_rule(P.WindowNode, _tag_window, _convert_window)
exec_rule(P.Exchange, _tag_exchange, _convert_exchange)


# -- pandas/Arrow Python UDF execs (execution/python/ analogs) ---------------

def _tag_python_udf(meta, conf):
    _check_output_schema(meta, conf)
    # ArrowEvalPython evaluates its UDF ARGUMENT expressions on device
    # (compile_project); they must pass the same expression checks as a
    # project, or the whole node falls back
    udfs = getattr(meta.node, "udfs", None)
    if udfs:
        for name, fn, _rt, args, *_spec in udfs:
            # hive UDFs carry their wrapped class; its expression
            # kill-switch reports a per-op fallback (hiveUDFs.scala rules)
            hive_cls = getattr(fn, "_hive_udf_class", None)
            if hive_cls and not conf.is_op_enabled("expression", hive_cls):
                meta.reasons.append(
                    f"expression {hive_cls} ({name}) is disabled by conf")
            for a in args:
                if isinstance(a, str):  # WindowInPandas carries col names
                    continue
                check_expr(a, conf, meta.reasons, f"pandas UDF {name} arg ")


def _convert_python_exec(cls):
    def convert(node, children, conf):
        return cls(children[0], node, conf)
    return convert


def _register_pandas_udf_rules():
    from spark_rapids_tpu.execs.python_exec import (
        TpuAggregateInPandasExec,
        TpuArrowEvalPythonExec,
        TpuFlatMapGroupsInPandasExec,
        TpuMapInPandasExec,
    )
    from spark_rapids_tpu.plan import pandas_udf as PU
    exec_rule(PU.MapInPandas, _tag_python_udf,
              _convert_python_exec(TpuMapInPandasExec),
              "Enable MapInPandas on the accelerator.")
    exec_rule(PU.FlatMapGroupsInPandas, _tag_python_udf,
              _convert_python_exec(TpuFlatMapGroupsInPandasExec),
              "Enable FlatMapGroupsInPandas on the accelerator.")
    exec_rule(PU.AggregateInPandas, _tag_python_udf,
              _convert_python_exec(TpuAggregateInPandasExec),
              "Enable AggregateInPandas on the accelerator.")
    exec_rule(PU.ArrowEvalPython, _tag_python_udf,
              _convert_python_exec(TpuArrowEvalPythonExec),
              "Enable scalar pandas UDF eval on the accelerator.")
    from spark_rapids_tpu.execs.python_exec import (
        TpuFlatMapCoGroupsInPandasExec,
        TpuMapInArrowExec,
        TpuWindowInPandasExec,
    )
    exec_rule(PU.MapInArrow, _tag_python_udf,
              _convert_python_exec(TpuMapInArrowExec),
              "Enable MapInArrow on the accelerator.")
    exec_rule(PU.FlatMapCoGroupsInPandas, _tag_python_udf,
              lambda node, children, conf:
                  TpuFlatMapCoGroupsInPandasExec(children, node, conf),
              "Enable FlatMapCoGroupsInPandas on the accelerator.")
    exec_rule(PU.WindowInPandas, _tag_python_udf,
              _convert_python_exec(TpuWindowInPandasExec),
              "Enable WindowInPandas on the accelerator.")


_register_pandas_udf_rules()


# ---------------------------------------------------------------------------
# Meta + conversion
# ---------------------------------------------------------------------------

class PlanMeta:
    """RapidsMeta analog for plan nodes."""

    def __init__(self, node: P.PlanNode, conf: RapidsConf, parent: Optional["PlanMeta"] = None):
        self.node = node
        self.conf = conf
        self.parent = parent
        self.reasons: List[str] = []
        #: advisory demotion notes: the op still runs ON DEVICE but a
        #: requested fast path demoted (e.g. an ICI-requested exchange
        #: taking the host-file shuffle). Rendered by explain() like
        #: fallback reasons but never forcing CPU conversion.
        self.notes: List[str] = []
        # CachedRelation is a planning LEAF: its child executes through its
        # own session at materialize() time; tagging/converting the subtree
        # here would duplicate planning and (on fallback) re-point the
        # memoized table at a throwaway copy of the node
        if isinstance(node, P.CachedRelation):
            self.children = []
        else:
            self.children = [PlanMeta(c, conf, self) for c in node.children]

    def tag(self):
        rule = _EXEC_RULES.get(type(self.node))
        # runtime circuit breaker (runtime/faults.py): an op demoted after
        # repeated non-OOM device failures falls back like any other
        # tagged reason, so explain()/planVerify surface WHY it's on CPU
        from spark_rapids_tpu.conf import RUNTIME_FALLBACK_ENABLED
        from spark_rapids_tpu.runtime.faults import CIRCUIT_BREAKER
        # device health latch (runtime/health.py): after repeated device
        # losses the WHOLE device is demoted — every op falls back with
        # the latch reason, the whole-device analog of the breaker.
        # Ungated by runtimeFallback.enabled: the latch only forms via
        # deviceLoss.maxReinits, and once it has, dispatching to the
        # dead device cannot be the answer.
        from spark_rapids_tpu.runtime.health import HEALTH
        cpu_only = HEALTH.cpu_only_reason()
        if self.parent is None:
            # mesh fault domain (ROOT note, advisory): a mesh running
            # below declared strength after partial device losses, or
            # an attempt the degradation ladder suppressed to single-
            # device landing, is visible in explain() like every other
            # demotion — the query still runs on device
            from spark_rapids_tpu.parallel.mesh import (
                MESH,
                MESH_ENABLED,
                suppression_reason,
            )
            if bool(self.conf.get_entry(MESH_ENABLED)):
                sup = suppression_reason()
                degraded = MESH.degraded_reason()
                if sup is not None:
                    self.notes.append(f"mesh demoted: {sup}")
                elif degraded is not None:
                    snap = MESH.health_snapshot()
                    self.notes.append(
                        f"mesh degraded: running on the "
                        f"{snap['shape']}-device surviving mesh "
                        f"(excluded device ids "
                        f"{snap['excludedDeviceIds']}): {degraded}")
            # Pallas kernel demotions (ROOT note, advisory — the op
            # still runs on device, on its HLO path): surfaced in
            # explain() exactly like the ICI/mesh demotion reasons
            from spark_rapids_tpu import kernels as _K
            for _reason in sorted(_K.demoted_ops().values()):
                self.notes.append(_reason)
        demoted = CIRCUIT_BREAKER.demotion_reason(type(self.node).__name__)
        if rule is None:
            self.reasons.append(f"exec {self.node.name} is not supported on TPU")
        elif cpu_only is not None:
            self.reasons.append(cpu_only)
        elif demoted and self.conf.get_entry(RUNTIME_FALLBACK_ENABLED):
            self.reasons.append(demoted)
        elif not self.conf.is_op_enabled("exec", type(self.node).__name__):
            self.reasons.append(f"exec {self.node.name} is disabled by conf")
        else:
            rule.tag_fn(self, self.conf)
        for c in self.children:
            c.tag()

    @property
    def can_run_on_tpu(self) -> bool:
        return not self.reasons

    def explain(self, indent: int = 0, only_fallback: bool = True) -> str:
        mark = "*" if self.can_run_on_tpu else "!"
        line = "  " * indent + f"{mark} {self.node.describe()}"
        if self.reasons:
            line += "  <-- " + "; ".join(self.reasons)
        if self.notes:
            line += "  (" + "; ".join(self.notes) + ")"
        out = [line] if (not only_fallback or self.reasons or self.notes
                         or indent == 0) else [
            "  " * indent + f"{mark} {self.node.describe()}"]
        for c in self.children:
            out.append(c.explain(indent + 1, only_fallback))
        return "\n".join(out)


def wrap_plan(plan: P.PlanNode, conf: RapidsConf) -> PlanMeta:
    meta = PlanMeta(plan, conf)
    meta.tag()
    return meta


def _convert(meta: PlanMeta):
    """Returns either a TpuExec (device) or a P.PlanNode (host)."""
    converted_children = [_convert(c) for c in meta.children]
    if meta.can_run_on_tpu:
        rule = _EXEC_RULES[type(meta.node)]
        dev_children = []
        for cc in converted_children:
            if isinstance(cc, TpuExec):
                dev_children.append(cc)
            else:
                dev_children.append(HostToDevice(cc))
        out = rule.convert_fn(meta.node, dev_children, meta.conf)
        # runtime-failure attribution unit (runtime/faults.py): the
        # plan-node class this exec tree was converted from — what the
        # circuit breaker demotes and PlanMeta.tag re-checks
        out._plan_origin = type(meta.node).__name__
        return out
    # CPU node: children must be host-side
    host_children = []
    for cc, cm in zip(converted_children, meta.children):
        if isinstance(cc, TpuExec):
            host_children.append(InputAdapter(DeviceToHost(cc), cm.node.output_schema()))
        else:
            host_children.append(cc)
    if host_children:
        node = copy.copy(meta.node)
        node.children = tuple(host_children)
        return node
    return meta.node


def convert_plan(meta: PlanMeta):
    """Convert a tagged plan; result always exposes execute_cpu (top-level
    DeviceToHost transition added when the root runs on device)."""
    out = _convert(meta)
    if isinstance(out, TpuExec):
        return DeviceToHost(out)
    return out


def _insert_window_group_limits(node: P.PlanNode) -> P.PlanNode:
    """WindowGroupLimit rewrite (reference: GpuWindowGroupLimitExec /
    Spark 3.5 InsertWindowGroupLimit): Filter(rank_col <= k) directly
    above a WindowNode whose rank_col is row_number/rank/dense_rank
    admits a pre-window group limit — at most k(+ties) rows per
    partition need to enter the window. Builds a NEW tree (plan nodes
    are shared across collects; never mutate)."""
    import copy as _copy

    from spark_rapids_tpu.ops.expr import BoundReference, Literal
    from spark_rapids_tpu.ops.predicates import (
        EqualTo,
        LessThan,
        LessThanOrEqual,
    )
    from spark_rapids_tpu.ops.window import DenseRank, Rank, RowNumber

    new_children = [_insert_window_group_limits(c) for c in node.children]
    if any(a is not b for a, b in zip(new_children, node.children)):
        node = _copy.copy(node)
        node.children = tuple(new_children)

    if not isinstance(node, P.Filter) or not isinstance(
            node.children[0], P.WindowNode):
        return node
    cond = node.condition
    if not isinstance(cond, (LessThan, LessThanOrEqual, EqualTo)):
        return node
    lhs, rhs = cond.children
    if not (isinstance(lhs, BoundReference) and isinstance(rhs, Literal)):
        return node
    win: P.WindowNode = node.children[0]
    n_child = len(win.children[0].output_schema())
    wi = lhs.ordinal - n_child
    if wi < 0 or wi >= len(win.window_cols):
        return node
    w = win.window_cols[wi][1]
    fn = w.function
    kinds = {RowNumber: "rownumber", Rank: "rank", DenseRank: "denserank"}
    kind = kinds.get(type(fn))
    if kind is None or not w.spec.orders:
        return node
    # EVERY window column in the node must be safe under pruning: a
    # sibling computed over a different spec (or a non-ranking function)
    # would see only the surviving rows and produce wrong values
    # (Spark's InferWindowGroupLimit applies the same gate)
    spec_key = (tuple(e.key() for e in w.spec.partition_exprs),
                tuple((o.expr.key(), o.ascending,
                       o.resolved_nulls_first()) for o in w.spec.orders))
    for _, other in win.window_cols:
        if type(other.function) not in kinds:
            return node
        ok = (tuple(e.key() for e in other.spec.partition_exprs),
              tuple((o.expr.key(), o.ascending, o.resolved_nulls_first())
                    for o in other.spec.orders))
        if ok != spec_key:
            return node
    try:
        k = int(rhs.value)
    except (TypeError, ValueError):
        return node
    if isinstance(cond, LessThan):
        k -= 1
    elif isinstance(cond, EqualTo):
        pass  # rank == k admits keeping rank <= k
    if k < 1:
        return node
    wgl = P.WindowGroupLimit(win.children[0], w.spec.partition_exprs,
                             w.spec.orders, kind, k)
    new_win = _copy.copy(win)
    new_win.children = (wgl,)
    new_filter = _copy.copy(node)
    new_filter.children = (new_win,)
    return new_filter


def apply_overrides(plan: P.PlanNode, conf: RapidsConf):
    """GpuOverrides.apply analog: tag + CBO + convert (or explain-only)."""
    if not conf.sql_enabled:
        return plan, None
    # the mesh runtime must reflect THIS conf before tagging: the
    # exchange demotion notes and the reland pass below both read it
    # (idempotent when the session's placement layer already prepared)
    from spark_rapids_tpu.parallel.mesh import MESH
    MESH.configure(conf)
    from spark_rapids_tpu.conf import COLUMN_PRUNING
    if conf.get_entry(COLUMN_PRUNING):
        from spark_rapids_tpu.overrides.pruning import prune_plan
        plan = prune_plan(plan)
    plan = _insert_window_group_limits(plan)
    meta = wrap_plan(plan, conf)
    from spark_rapids_tpu.overrides.optimizer import apply_cbo
    apply_cbo(meta, conf)
    if conf.is_explain_only:
        return plan, meta
    executable = convert_plan(meta)
    if MESH.enabled:
        # mesh-native execution: bound sharded residency at wide-kernel
        # boundaries (execs/mesh.py) — part of the converted tree, so
        # the executable cache parks the boundaries with it (and its
        # mesh-generation stamp keeps them coherent)
        from spark_rapids_tpu.execs.mesh import insert_mesh_relands
        executable = insert_mesh_relands(executable)
    return executable, meta


def explain_plan(plan: P.PlanNode, conf: RapidsConf) -> str:
    # same mesh realization as apply_overrides: an explain() before the
    # first execute must report the demotion reasons the exec will act
    # on, not a stale (or never-configured) mesh
    from spark_rapids_tpu.parallel.mesh import MESH
    MESH.configure(conf)
    meta = wrap_plan(plan, conf)
    out = meta.explain(only_fallback=conf.explain_mode != "ALL")
    # poison-query quarantine (runtime/health.py): a template with a
    # strike history is flagged up front. The fingerprint walk only
    # runs when strikes exist at all — the common (clean) process pays
    # one snapshot call
    from spark_rapids_tpu.runtime.health import QUARANTINE
    if QUARANTINE.snapshot()["strikes"]:
        from spark_rapids_tpu.plan.fingerprint import template_fingerprint
        fp = template_fingerprint(plan, conf)
        quarantined = QUARANTINE.is_quarantined(fp)
        if quarantined is not None:
            out = ("!! QUARANTINED template: submissions are rejected "
                   f"({len(quarantined)} strikes: "
                   f"{'; '.join(quarantined)})\n" + out)
        elif QUARANTINE.strike_count(fp):
            out = (f"! poison suspect: {QUARANTINE.strike_count(fp)} "
                   "worker/device kill strike(s) recorded against this "
                   "template\n" + out)
    return out


# Register every expression rule (and its kill switch) at import: the
# conf registry must list the full per-op switch surface without waiting
# for a first query (RapidsConf.scala registers everything at class init)
_build_expr_sigs()
