"""TPU hash aggregate (reference: GpuHashAggregateExec / GpuMergeAggregate-
Iterator, GpuAggregateExec.scala — SURVEY.md §2.3).

TPU-first design, two device strategies (neither is a hash table —
pointer-chasing is hostile to the VPU):

FAST PATH (dictionary-code grouping, no sort): when every grouping key is a
dictionary-encoded string or a boolean, the key domain is known on the host
(dict sizes), so each row's group id is a mixed-radix combination of its
codes — ``gid = sum(code_i * stride_i)`` with one extra slot per key for
null. Aggregation is then direct ``segment_*`` reductions with
``num_segments = padded domain product`` (small!), group compaction is a
cumsum scatter, and the live group count stays on device — no sort, no
host sync, no capacity-sized outputs. f64 sums run through the exact-
decomposition blocked f32 path (ops/segsum.py).

SORT-SEGMENT PATH (general keys): lexicographic multi-operand ``lax.sort``
over (live, key-validity, key-data...) with a row-index payload; segment
boundaries -> dense group ids via cumsum; ``jax.ops.segment_*`` reductions.

Input fusion: Project/Filter chains feeding the aggregate are substituted
into the kernel (execs/fuse.py) — predicates become weight masks evaluated
in the same XLA program, so a filter+project+aggregate pipeline is ONE
device dispatch with no intermediate materialization.

Requires a single coalesced input batch (RequireSingleBatch goal) in v1;
partial-per-batch + merge is the planned widening."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceColumn, DeviceTable
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.ops import aggregates as agg
from spark_rapids_tpu.ops.expr import (
    DevVal,
    EvalCtx,
    Expression,
    NodePrep,
    PrepCtx,
    _prep_trace_key,
    _walk_eval,
    _walk_prep,
)
from spark_rapids_tpu.ops.segsum import batched_segment_sum_f64, segment_sum_f64

DEVICE_SUPPORTED_AGGS = (agg.Sum, agg.Min, agg.Max, agg.Count, agg.Average,
                         agg.First, agg.Last, agg.StddevPop, agg.StddevSamp,
                         agg.VariancePop, agg.VarianceSamp)


def _sortable(data, validity):
    """Transform (data, validity) into sort operands grouping nulls
    together: (invalid_first_flag, data_with_nulls_zeroed). Floats are
    normalized so -0.0 groups with 0.0 (Spark NormalizeFloatingNumbers)."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        data = jnp.where(data == 0.0, jnp.zeros_like(data), data)
    return [(~validity).astype(jnp.int32), jnp.where(validity, data, jnp.zeros_like(data))]


class TpuHashAggregateExec(TpuExec):
    def __init__(self, child: TpuExec, grouping: Sequence[Expression],
                 agg_specs: Sequence[Tuple[str, agg.AggregateFunction]],
                 grouping_names: Sequence[str],
                 filters: Sequence[Expression] = (),
                 use_split: bool = False,
                 max_dict_groups: int = 1 << 16):
        super().__init__()
        self.children = (child,)
        self.grouping = list(grouping)
        self.agg_specs = list(agg_specs)
        self.grouping_names = list(grouping_names)
        self.filters = list(filters)
        self.use_split = use_split
        self.max_dict_groups = max_dict_groups

    def output_schema(self):
        out = [(n, g.data_type) for n, g in zip(self.grouping_names, self.grouping)]
        out += [(n, fn.data_type) for n, fn in self.agg_specs]
        return out

    def execute(self):
        from spark_rapids_tpu.runtime.retry import retry_block
        batches = list(self.children[0].execute())
        if len(batches) != 1:
            raise ColumnarProcessingError(
                "TpuHashAggregateExec requires a single coalesced batch")
        # spill-and-replay on OOM; split is unsound for a single-pass agg
        # (reference escalates to sort-fallback merge — planned widening)
        yield retry_block(lambda: self._aggregate(batches[0]))

    # -- core ---------------------------------------------------------------
    def _prep_all(self, table: DeviceTable):
        pctx = PrepCtx(table)
        filter_preps: List[List[NodePrep]] = []
        for f in self.filters:
            preps: List[NodePrep] = []
            _walk_prep(f, pctx, preps)
            filter_preps.append(preps)
        key_preps: List[List[NodePrep]] = []
        for g in self.grouping:
            preps = []
            _walk_prep(g, pctx, preps)
            key_preps.append(preps)
        val_preps: List[List[NodePrep]] = []
        for _, fn in self.agg_specs:
            if fn.child is None:
                val_preps.append([])
            else:
                preps = []
                _walk_prep(fn.child, pctx, preps)
                val_preps.append(preps)
        return pctx, filter_preps, key_preps, val_preps

    def _fast_layout(self, key_preps) -> Optional[tuple]:
        """Dictionary-code layout if every key has a small known domain:
        (kinds, sizes, strides, padded_num_segments)."""
        if not self.grouping or self.max_dict_groups <= 0:
            return None
        kinds: List[str] = []
        sizes: List[int] = []
        for g, preps in zip(self.grouping, key_preps):
            dt = g.data_type
            root = preps[-1]
            if isinstance(dt, T.StringType) and root.out_dict is not None:
                kinds.append("str")
                sizes.append(len(root.out_dict) + 1)  # +1: null slot
            elif isinstance(dt, T.BooleanType):
                kinds.append("bool")
                sizes.append(3)  # False, True, null
            else:
                return None
        total = 1
        for s in sizes:
            total *= max(s, 1)
        if total > self.max_dict_groups:
            return None
        strides = [1] * len(sizes)
        for i in range(len(sizes) - 2, -1, -1):
            strides[i] = strides[i + 1] * sizes[i + 1]
        # tight power-of-two segment count (NOT the 128-row table bucket):
        # one-hot einsum traffic scales with it, and a q1-style 12-slot
        # domain must pad to 16, not 128
        gpad = max(8, 1 << (max(total - 1, 1)).bit_length())
        return tuple(kinds), sizes, strides, gpad

    def _aggregate(self, table: DeviceTable) -> DeviceTable:
        pctx, filter_preps, key_preps, val_preps = self._prep_all(table)
        cols = tuple(DevVal(c.data, c.validity) for c in table.columns)
        aux = tuple(jnp.asarray(a) for a in pctx.aux_arrays)
        capacity = table.capacity

        fast = self._fast_layout(key_preps)

        from spark_rapids_tpu.ops.expr import shared_traces
        self._traces = shared_traces(
            ("agg",
             tuple(g.key() for g in self.grouping),
             tuple(fn.key() for _, fn in self.agg_specs),
             tuple(f.key() for f in self.filters),
             table.schema_key()[0]))
        mode_key = ("fast", fast[0], fast[3]) if fast else ("sorted",)
        tkey = (capacity, self.use_split, mode_key,
                tuple(_prep_trace_key(p) for p in filter_preps),
                tuple(_prep_trace_key(p) for p in key_preps),
                tuple(_prep_trace_key(p) for p in val_preps))
        fn = self._traces.get(tkey)
        if fn is None:
            if fast:
                fn = jax.jit(self._build_fast_kernel(
                    capacity, fast[0], fast[3], filter_preps, key_preps, val_preps))
            else:
                fn = jax.jit(self._build_kernel(
                    capacity, filter_preps, key_preps, val_preps))
            self._traces[tkey] = fn

        if fast:
            _, sizes, strides, gpad = fast
            out_arrays, ngroups = fn(
                cols, aux, table.nrows_dev,
                jnp.asarray(np.asarray(sizes, dtype=np.int32)),
                jnp.asarray(np.asarray(strides, dtype=np.int32)))
            out_capacity = gpad
        else:
            out_arrays, ngroups = fn(cols, aux, table.nrows_dev)
            out_capacity = capacity

        out_cols: List[DeviceColumn] = []
        names: List[str] = []
        for i, (g, name) in enumerate(zip(self.grouping, self.grouping_names)):
            data, validity = out_arrays[i]
            root = key_preps[i][-1]
            out_cols.append(DeviceColumn(g.data_type, data, validity,
                                         dictionary=root.out_dict,
                                         dict_sorted=root.dict_sorted))
            names.append(name)
        for j, (name, fnagg) in enumerate(self.agg_specs):
            data, validity = out_arrays[len(self.grouping) + j]
            dictionary = None
            dict_sorted = True
            if isinstance(fnagg.data_type, T.StringType) and val_preps[j]:
                dictionary = val_preps[j][-1].out_dict
                dict_sorted = val_preps[j][-1].dict_sorted
            out_cols.append(DeviceColumn(fnagg.data_type, data, validity,
                                         dictionary=dictionary, dict_sorted=dict_sorted))
            names.append(name)
        out = DeviceTable(names, out_cols, ngroups, out_capacity)
        if fast:
            # outputs are already domain-sized; the group count stays a
            # device scalar (no host sync on the hot path)
            return out
        # sorted path emits capacity-sized outputs; re-bucket so downstream
        # sorts/transfers don't run at input capacity
        return out.shrink()

    def _eval_live(self, capacity, cols, aux, nrows, filter_preps):
        """Row-liveness mask: in-bounds AND every fused predicate true."""
        live = jnp.arange(capacity, dtype=jnp.int32) < nrows
        for f, preps in zip(self.filters, filter_preps):
            ctx = EvalCtx(cols, aux, nrows, capacity)
            ctx._prep_iter = iter(preps)
            pred = _walk_eval(f, ctx)
            live = live & pred.data & pred.validity
        return live

    # -- fast path: dictionary-code grouping, no sort -----------------------
    def _build_fast_kernel(self, capacity: int, kinds, gpad: int,
                           filter_preps, key_preps, val_preps):
        grouping = self.grouping
        agg_specs = self.agg_specs
        value_exprs = [fn.child for _, fn in agg_specs]
        use_split = self.use_split

        def kernel(cols, aux, nrows, sizes, strides):
            live = self._eval_live(capacity, cols, aux, nrows, filter_preps)

            gid = jnp.zeros(capacity, dtype=jnp.int32)
            for i, (g, preps, kind) in enumerate(zip(grouping, key_preps, kinds)):
                ctx = EvalCtx(cols, aux, nrows, capacity)
                ctx._prep_iter = iter(preps)
                kv = _walk_eval(g, ctx)
                code = kv.data.astype(jnp.int32) if kind == "bool" else kv.data
                code = jnp.where(kv.validity, code, sizes[i] - 1)
                gid = gid + code * strides[i]

            # ---- batched value aggregation ------------------------------
            # All sum-class f64 reductions (Sum/Average/Stddev/Variance)
            # ride ONE batched device pass (ops/segsum.py); validity counts
            # for every spec plus group existence ride one 2-D i32
            # segment_sum. Min/Max/First/Last and i64 sums stay per-spec
            # (_agg_one).
            vvs = []
            for ve, preps in zip(value_exprs, val_preps):
                if ve is None:
                    vvs.append(None)
                else:
                    ctx = EvalCtx(cols, aux, nrows, capacity)
                    ctx._prep_iter = iter(preps)
                    vvs.append(_walk_eval(ve, ctx))
            svs = [(vv.validity & live) if vv is not None else None
                   for vv in vvs]

            # one scatter for live-count + every spec's nonnull count
            masks = [live] + [sv for sv in svs if sv is not None]
            mix = {}
            k = 1
            for j, sv in enumerate(svs):
                if sv is not None:
                    mix[j] = k
                    k += 1
            mcnt = jax.ops.segment_sum(
                jnp.stack(masks, axis=1).astype(jnp.int32), gid,
                num_segments=gpad)
            nonnulls = {j: mcnt[:, i] for j, i in mix.items()}

            exists = mcnt[:, 0] > 0
            ngroups = jnp.sum(exists.astype(jnp.int32))
            pos = jnp.cumsum(exists.astype(jnp.int32)) - 1
            tgt = jnp.where(exists, pos, gpad)  # compact: slot -> dense rank
            out_live = jnp.arange(gpad, dtype=jnp.int32) < ngroups

            def compact(data, validity):
                cd = jnp.zeros_like(data).at[tgt].set(data, mode="drop")
                cv = jnp.zeros_like(validity).at[tgt].set(validity, mode="drop")
                return cd, cv & out_live

            outs = []
            slot_ix = jnp.arange(gpad, dtype=jnp.int32)
            for i, kind in enumerate(kinds):
                slot = (slot_ix // strides[i]) % sizes[i]
                kvalid = slot != (sizes[i] - 1)
                kdata = (slot == 1) if kind == "bool" else slot
                outs.append(compact(kdata, kvalid))

            fplan = []  # (spec index, kind) riding the batched f64 pass
            for j, (_, fnagg) in enumerate(agg_specs):
                if isinstance(fnagg, (agg.StddevPop, agg.StddevSamp,
                                      agg.VariancePop, agg.VarianceSamp)):
                    fplan.append((j, "var"))
                elif isinstance(fnagg, agg.Average):
                    fplan.append((j, "avg"))
                elif isinstance(fnagg, agg.Sum) and not isinstance(
                        fnagg.data_type, T.LongType):
                    fplan.append((j, "sum"))
            fcols = [jnp.where(svs[j], vvs[j].data.astype(jnp.float64), 0.0)
                     for j, _ in fplan]
            fsums = batched_segment_sum_f64(fcols, gid, gpad, capacity,
                                            use_split)

            # second batched pass: centered moments for stddev/variance
            vplan = [(i, j) for i, (j, kind) in enumerate(fplan)
                     if kind == "var"]
            ccols = []
            for i, j in vplan:
                mean = fsums[:, i] / jnp.maximum(nonnulls[j], 1)
                ccols.append(jnp.where(
                    svs[j],
                    (vvs[j].data.astype(jnp.float64) - mean[gid]) ** 2, 0.0))
            csums = batched_segment_sum_f64(ccols, gid, gpad, capacity,
                                            use_split)
            m2s = {j: csums[:, i2] for i2, (_, j) in enumerate(vplan)}

            fres = {}
            for i, (j, kind) in enumerate(fplan):
                fnagg = agg_specs[j][1]
                nonnull = nonnulls[j]
                has_any = (nonnull > 0) & exists
                s = fsums[:, i]
                if kind == "sum":
                    fres[j] = (jnp.where(has_any, s, 0.0), has_any)
                elif kind == "avg":
                    fres[j] = (jnp.where(has_any, s / jnp.maximum(nonnull, 1), 0.0),
                               has_any)
                else:
                    if isinstance(fnagg, (agg.StddevPop, agg.VariancePop)):
                        denom = jnp.maximum(nonnull, 1)
                        validity = has_any
                    else:
                        denom = jnp.maximum(nonnull - 1, 1)
                        validity = (nonnull > 1) & exists
                    var = m2s[j] / denom
                    out = jnp.sqrt(var) if isinstance(
                        fnagg, (agg.StddevPop, agg.StddevSamp)) else var
                    fres[j] = (jnp.where(validity, out, 0.0), validity)

            for j, (_, fnagg) in enumerate(agg_specs):
                if j in fres:
                    data, validity = fres[j]
                elif isinstance(fnagg, agg.Count):
                    w = mcnt[:, 0] if fnagg.child is None else nonnulls[j]
                    data, validity = w.astype(jnp.int64), exists
                else:
                    sd = vvs[j].data if vvs[j] is not None else None
                    data, validity = self._agg_one(
                        fnagg, sd, svs[j], live, gid, gpad, exists,
                        capacity, use_split)
                outs.append(compact(data, validity))
            return outs, ngroups

        return kernel

    # -- general path: sort-segment -----------------------------------------
    def _build_kernel(self, capacity: int, filter_preps, key_preps, val_preps):
        grouping = self.grouping
        agg_specs = self.agg_specs
        value_exprs = [fn.child for _, fn in agg_specs]
        use_split = self.use_split

        def kernel(cols, aux, nrows):
            live = self._eval_live(capacity, cols, aux, nrows, filter_preps)

            key_vals: List[DevVal] = []
            for g, preps in zip(grouping, key_preps):
                ctx = EvalCtx(cols, aux, nrows, capacity)
                ctx._prep_iter = iter(preps)
                key_vals.append(_walk_eval(g, ctx))
            val_vals: List[DevVal] = []
            for ve, preps in zip(value_exprs, val_preps):
                if ve is None:
                    val_vals.append(None)
                else:
                    ctx = EvalCtx(cols, aux, nrows, capacity)
                    ctx._prep_iter = iter(preps)
                    val_vals.append(_walk_eval(ve, ctx))

            # normalize float keys so grouping matches the CPU oracle
            norm = []
            for kv in key_vals:
                d = kv.data
                if jnp.issubdtype(d.dtype, jnp.floating):
                    d = jnp.where(d == 0.0, jnp.zeros_like(d), d)
                norm.append(DevVal(d, kv.validity))
            key_vals = norm

            if grouping:
                operands = [(~live).astype(jnp.int32)]  # dead rows last
                for kv in key_vals:
                    operands.extend(_sortable(kv.data, kv.validity))
                payload = jnp.arange(capacity, dtype=jnp.int32)
                sorted_all = jax.lax.sort(operands + [payload],
                                          num_keys=len(operands))
                perm = sorted_all[-1]
                s_live = live[perm]
                s_keys = [DevVal(kv.data[perm], kv.validity[perm]) for kv in key_vals]

                # group boundaries among live rows
                first = jnp.arange(capacity) == 0
                changed = jnp.zeros(capacity, dtype=jnp.bool_)
                for kv in s_keys:
                    d, v = kv.data, kv.validity
                    dprev = jnp.roll(d, 1)
                    vprev = jnp.roll(v, 1)
                    diff = (jnp.where(v & vprev, d != dprev, v != vprev))
                    changed = changed | diff
                new_group = (first | changed) & s_live
                gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
                gid = jnp.where(s_live, gid, capacity - 1)  # park dead rows
                ngroups = jnp.sum(new_group.astype(jnp.int32))
            else:
                perm = jnp.arange(capacity, dtype=jnp.int32)
                s_live = live
                s_keys = []
                gid = jnp.zeros(capacity, dtype=jnp.int32)
                ngroups = jnp.asarray(1, dtype=jnp.int32)

            group_live = jnp.arange(capacity, dtype=jnp.int32) < ngroups

            outs = []
            # key columns: scatter first-occurrence values to gid slots
            for kv in s_keys:
                tgt = jnp.where(s_live, gid, capacity)
                kd = jnp.zeros_like(kv.data).at[tgt].set(kv.data, mode="drop")
                kvv = jnp.zeros_like(kv.validity).at[tgt].set(kv.validity, mode="drop")
                outs.append((kd, kvv & group_live))

            for (name, fnagg), vv in zip(agg_specs, val_vals):
                sd = vv.data[perm] if vv is not None else None
                sv = (vv.validity[perm] & s_live) if vv is not None else None
                outs.append(self._agg_one(fnagg, sd, sv, s_live, gid, capacity,
                                          group_live, capacity, use_split))
            return outs, ngroups

        return kernel

    @staticmethod
    def _agg_one(fnagg, sd, sv, live, gid, nseg, group_live, capacity, use_split):
        """One aggregate over segment ids. ``sd``/``sv``: value data and
        validity aligned with ``gid`` (``sv`` already excludes dead rows);
        ``live``: row liveness (COUNT(*)); ``nseg``: number of segments;
        ``group_live``: which segment slots are real groups."""
        seg = jax.ops
        if isinstance(fnagg, agg.Count):
            w = live if fnagg.child is None else sv
            # capacity < 2^31 always (power-of-two row buckets), so count
            # accumulates natively in i32 and widens to Spark's LONG after
            cnt = seg.segment_sum(w.astype(jnp.int32), gid,
                                  num_segments=nseg).astype(jnp.int64)
            return (cnt, group_live)

        nonnull = seg.segment_sum(sv.astype(jnp.int32), gid, num_segments=nseg)
        has_any = (nonnull > 0) & group_live

        if isinstance(fnagg, agg.Sum):
            if isinstance(fnagg.data_type, T.LongType):
                v = jnp.where(sv, sd.astype(jnp.int64), 0)
                s = seg.segment_sum(v, gid, num_segments=nseg)
                return (s, has_any)
            v = jnp.where(sv, sd.astype(jnp.float64), 0.0)
            s = segment_sum_f64(v, gid, nseg, capacity, use_split)
            return (jnp.where(has_any, s, 0.0), has_any)

        if isinstance(fnagg, agg.Average):
            v = jnp.where(sv, sd.astype(jnp.float64), 0.0)
            s = segment_sum_f64(v, gid, nseg, capacity, use_split)
            return (jnp.where(has_any, s / jnp.maximum(nonnull, 1), 0.0), has_any)

        if isinstance(fnagg, (agg.StddevPop, agg.StddevSamp, agg.VariancePop, agg.VarianceSamp)):
            v = jnp.where(sv, sd.astype(jnp.float64), 0.0)
            s = segment_sum_f64(v, gid, nseg, capacity, use_split)
            mean = s / jnp.maximum(nonnull, 1)
            centered = jnp.where(sv, (sd.astype(jnp.float64) - mean[gid]) ** 2, 0.0)
            m2 = segment_sum_f64(centered, gid, nseg, capacity, use_split)
            if isinstance(fnagg, (agg.StddevPop, agg.VariancePop)):
                denom = jnp.maximum(nonnull, 1)
                validity = has_any
            else:
                denom = jnp.maximum(nonnull - 1, 1)
                validity = (nonnull > 1) & group_live
            var = m2 / denom
            out = jnp.sqrt(var) if isinstance(fnagg, (agg.StddevPop, agg.StddevSamp)) else var
            return (jnp.where(validity, out, 0.0), validity)

        if isinstance(fnagg, (agg.Min, agg.Max)):
            dt = sd.dtype
            if jnp.issubdtype(dt, jnp.floating):
                ident = jnp.asarray(jnp.inf if isinstance(fnagg, agg.Min) else -jnp.inf, dtype=dt)
            elif dt == jnp.bool_:
                sd = sd.astype(jnp.int32)
                dt = jnp.int32
                ident = jnp.asarray(1 if isinstance(fnagg, agg.Min) else 0, dtype=dt)
            else:
                info = jnp.iinfo(dt)
                ident = jnp.asarray(info.max if isinstance(fnagg, agg.Min) else info.min, dtype=dt)
            v = jnp.where(sv, sd, ident)
            if isinstance(fnagg, agg.Min):
                r = seg.segment_min(v, gid, num_segments=nseg)
            else:
                r = seg.segment_max(v, gid, num_segments=nseg)
            if isinstance(fnagg.data_type, T.BooleanType):
                r = r.astype(jnp.bool_)
            zero = jnp.zeros_like(r)
            return (jnp.where(has_any, r, zero), has_any)

        if isinstance(fnagg, (agg.First, agg.Last)):
            idx = jnp.arange(capacity, dtype=jnp.int32)
            pick_mask = sv if fnagg.ignore_nulls else live
            sentinel = capacity if isinstance(fnagg, agg.First) else -1
            pos = jnp.where(pick_mask, idx, sentinel)
            if isinstance(fnagg, agg.First):
                chosen = seg.segment_min(pos, gid, num_segments=nseg)
            else:
                chosen = seg.segment_max(pos, gid, num_segments=nseg)
            got = (chosen >= 0) & (chosen < capacity) & group_live
            safe = jnp.clip(chosen, 0, capacity - 1)
            data = sd[safe]
            # chosen rows are live by construction, so sv at them equals the
            # raw value validity — right for both ignore_nulls modes
            validity = got & sv[safe]
            return (jnp.where(validity, data, jnp.zeros_like(data)), validity)

        raise ColumnarProcessingError(f"device aggregate {type(fnagg).__name__}")

    def describe(self):
        fused = f", fusedFilters={len(self.filters)}" if self.filters else ""
        return (f"TpuHashAggregate[keys={self.grouping_names}, "
                f"aggs={[n for n, _ in self.agg_specs]}{fused}]")
