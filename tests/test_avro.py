"""Avro scan tests (reference: avro_test.py in the integration suite +
GpuAvroScan.scala reader modes — SURVEY.md §2.4)."""

import datetime

import numpy as np
import pytest

from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.io.avro import decode_file, read_header
from tests.avro_util import write_avro

SCHEMA = {
    "type": "record", "name": "t", "fields": [
        {"name": "i", "type": "int"},
        {"name": "l", "type": ["null", "long"]},
        {"name": "d", "type": "double"},
        {"name": "f", "type": "float"},
        {"name": "b", "type": "boolean"},
        {"name": "s", "type": ["null", "string"]},
        {"name": "dt", "type": {"type": "int", "logicalType": "date"}},
        {"name": "ts", "type": {"type": "long",
                                "logicalType": "timestamp-micros"}},
    ]}


def _rows(n, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for k in range(n):
        rows.append({
            "i": int(rng.integers(-1000, 1000)),
            "l": None if k % 7 == 0 else int(rng.integers(-10**12, 10**12)),
            "d": float(rng.standard_normal()),
            "f": float(np.float32(rng.standard_normal())),
            "b": bool(k % 3 == 0),
            "s": None if k % 5 == 0 else f"row-{k}-{rng.integers(0, 99)}",
            "dt": int(rng.integers(0, 20000)),
            "ts": int(rng.integers(0, 10**15)),
        })
    return rows


@pytest.mark.parametrize("codec", ["null", "deflate", "zstandard"])
def test_decode_roundtrip(tmp_path, codec):
    rows = _rows(500, seed=1)
    path = str(tmp_path / "a.avro")
    write_avro(path, SCHEMA, rows, codec=codec, rows_per_block=128)
    with open(path, "rb") as f:
        table = decode_file(f.read())
    assert table.num_rows == 500
    assert list(table.names) == ["i", "l", "d", "f", "b", "s", "dt", "ts"]
    col = dict(zip(table.names, table.columns))
    for k, row in enumerate(rows):
        assert col["i"].data[k] == row["i"]
        if row["l"] is None:
            assert not col["l"].validity[k]
        else:
            assert col["l"].data[k] == row["l"]
        assert col["d"].data[k] == row["d"]
        assert np.float32(col["f"].data[k]) == np.float32(row["f"])
        assert col["b"].data[k] == row["b"]
        if row["s"] is None:
            assert not col["s"].validity[k]
        else:
            assert col["s"].data[k] == row["s"]
        assert col["dt"].data[k] == row["dt"]
        assert col["ts"].data[k] == row["ts"]


def test_timestamp_millis_scaled(tmp_path):
    schema = {"type": "record", "name": "t", "fields": [
        {"name": "ts", "type": {"type": "long",
                                "logicalType": "timestamp-millis"}}]}
    path = str(tmp_path / "m.avro")
    write_avro(path, schema, [{"ts": 1234}])
    with open(path, "rb") as f:
        table = decode_file(f.read())
    assert table.columns[0].data[0] == 1234 * 1000  # micros internally


def test_header_parse(tmp_path):
    path = str(tmp_path / "h.avro")
    write_avro(path, SCHEMA, _rows(3), codec="deflate")
    with open(path, "rb") as f:
        info = read_header(f.read())
    assert info.codec == "deflate"
    assert [f["name"] for f in info.schema_json["fields"]][0] == "i"


def test_corrupt_sync_rejected(tmp_path):
    path = str(tmp_path / "c.avro")
    write_avro(path, SCHEMA, _rows(10))
    buf = bytearray(open(path, "rb").read())
    buf[-1] ^= 0xFF  # clobber final sync marker
    with pytest.raises(ColumnarProcessingError, match="sync"):
        decode_file(bytes(buf))


def test_unsupported_types_rejected(tmp_path):
    schema = {"type": "record", "name": "t", "fields": [
        {"name": "x", "type": "bytes"}]}
    path = str(tmp_path / "u.avro")
    with open(path, "wb") as fh:
        # header only is enough: schema mapping happens before decode
        import json as _json
        from tests.avro_util import _zigzag
        fh.write(b"Obj\x01" + _zigzag(1))
        for k, v in {"avro.schema": _json.dumps(schema).encode()}.items():
            kb = k.encode()
            fh.write(_zigzag(len(kb)) + kb + _zigzag(len(v)) + v)
        fh.write(_zigzag(0) + b"0123456789abcdef")
    with pytest.raises(ColumnarProcessingError, match="unsupported avro"):
        decode_file(open(path, "rb").read())


def test_engine_scan_modes_and_pruning(tmp_path, session, cpu_session):
    rows = _rows(700, seed=2)
    for part in range(3):
        sub = tmp_path / f"p={part}"
        sub.mkdir()
        write_avro(str(sub / "part.avro"), SCHEMA,
                   rows[part * 200:(part + 1) * 200], codec="deflate")

    def read(s, **kw):
        return s.read_avro(str(tmp_path)).collect()

    base = None
    for mode in ("PERFILE", "COALESCING", "MULTITHREADED"):
        tpu = session.read_avro(str(tmp_path), reader_type=mode)
        got = sorted(tpu.collect(), key=repr)
        if base is None:
            base = got
            assert len(got) == 600
        else:
            assert got == base

    # partition column recovered + column pruning
    df = session.read_avro(str(tmp_path), columns=["i", "p"])
    t = df.collect_table()
    assert list(t.names) == ["i", "p"]
    assert sorted(set(t.columns[1].data.tolist())) == [0, 1, 2]

    # oracle: TPU path vs CPU path agree
    tpu_rows = sorted(session.read_avro(str(tmp_path)).collect(), key=repr)
    cpu_rows = sorted(cpu_session.read_avro(str(tmp_path)).collect(), key=repr)
    assert tpu_rows == cpu_rows


def test_engine_filter_aggregate_over_avro(tmp_path, session, cpu_session):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col

    write_avro(str(tmp_path / "x.avro"), SCHEMA, _rows(1000, seed=3))

    def q(s):
        return (s.read_avro(str(tmp_path / "x.avro"))
                .filter(col("i") > 0)
                .group_by("b").agg(F.count("i").alias("c"),
                                   F.sum("d").alias("sd")))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[1] == w[1]
        assert abs(g[2] - w[2]) <= 1e-6 * max(1.0, abs(w[2]))
