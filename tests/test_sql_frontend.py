"""SQL front end golden suite (reference: qa_nightly_select_test.py —
the reference's test corpus IS SQL text; ISSUE 1 tentpole).

Three layers:
  * construct-by-construct SQL-vs-DSL equivalence: every supported
    grammar feature collected through session.sql() must equal the
    same query built through the DataFrame DSL;
  * error surfaces: parse errors carry (line, col) + caret; analysis
    errors name the construct with an overrides-style reason;
  * the ScaleTest q1-q22 corpus: SQL text and DSL forms produce
    identical results AND identical device dispatch counts (the SQL
    path lowers onto the same plan layer — no parallel engine).
"""

import datetime

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.sql.errors import SqlAnalysisError, SqlParseError


@pytest.fixture(scope="module")
def s():
    sess = TpuSession()
    sess.create_dataframe({
        "id": np.arange(1, 9, dtype=np.int64),
        "k": np.array(["a", "b", "a", "c", "b", "a", None, "c"],
                      dtype=object),
        "v": np.array([10.0, 20.0, 30.0, 40.0, None, 60.0, 70.0, 80.0],
                      dtype=object),
        "d": np.array([0, 100, 200, 300, 400, 500, 600, 700],
                      dtype=np.int32),
    }, dtypes={"id": T.LONG, "k": T.STRING, "v": T.DOUBLE, "d": T.DATE}) \
        .create_or_replace_temp_view("t")
    sess.create_dataframe({
        "k": np.array(["a", "b", "d"], dtype=object),
        "w": np.array([1.0, 2.0, 3.0]),
    }).create_or_replace_temp_view("u")
    return sess


def _canon(rows):
    out = []
    for r in rows:
        out.append(tuple(round(x, 9) if isinstance(x, float) else x
                         for x in r))
    return sorted(out, key=lambda r: tuple(
        (x is None, str(type(x)), x) for x in r))


def check(s, sql, build_dsl):
    got = _canon(s.sql(sql).collect())
    want = _canon(build_dsl(s).collect())
    assert got == want, f"{sql}\n  sql: {got}\n  dsl: {want}"


def t(s):
    return s.table("t")


def u(s):
    return s.table("u")


# -- projection / expressions ------------------------------------------------

def test_select_star(s):
    check(s, "SELECT * FROM t", t)


def test_projection_arithmetic_alias(s):
    check(s, "SELECT id, v * 2 + 1 AS dv, -id AS neg, v / 4, v % 3 FROM t",
          lambda s: t(s).select(
              col("id"), (col("v") * lit(2) + lit(1)).alias("dv"),
              (-col("id")).alias("neg"), col("v") / lit(4),
              col("v") % lit(3)))


def test_comparisons_and_logic(s):
    check(s, "SELECT id FROM t WHERE (v > 15 AND v <= 60) "
             "OR NOT (id < 5) OR v <> 30",
          lambda s: t(s).filter(
              ((col("v") > lit(15)) & (col("v") <= lit(60)))
              | ~(col("id") < lit(5)) | (col("v") != lit(30)))
          .select(col("id")))


def test_null_predicates(s):
    check(s, "SELECT id FROM t WHERE v IS NULL",
          lambda s: t(s).filter(col("v").isnull()).select(col("id")))
    check(s, "SELECT id FROM t WHERE k IS NOT NULL",
          lambda s: t(s).filter(col("k").isnotnull()).select(col("id")))


def test_null_safe_equal(s):
    check(s, "SELECT id FROM t WHERE k <=> NULL",
          lambda s: t(s).filter(
              (col("k").isnull() & lit(None).isnull())
              | (col("k") == lit(None))).select(col("id")))


def test_null_safe_equal_never_null(s):
    """<=> is NEVER null (code-review fix: the old lowering returned
    NULL when exactly one side was null, so NOT(a <=> b) dropped rows)."""
    rows = s.sql("SELECT k <=> NULL FROM t ORDER BY id").collect()
    assert all(v in (True, False) for (v,) in rows), rows
    # row 7 has k NULL -> true; every other row false
    assert [v for (v,) in rows] == [False] * 6 + [True, False]
    n = s.sql("SELECT COUNT(*) AS n FROM t "
              "WHERE NOT (k <=> NULL)").collect()
    assert n == [(7,)]


def test_decimal_literal_positive_exponent(s):
    """1E2BD is 100 = decimal(3,0) (code-review fix: precision ignored a
    positive exponent, so CheckOverflow nulled 1E2BD + 1BD)."""
    assert s.sql("SELECT 1E2BD + 1BD AS v").collect() == [(101,)]


def test_backwards_unbounded_frames_rejected(s):
    """Spark rejects UNBOUNDED FOLLOWING as a frame START (and PRECEDING
    as an END) at parse time; the old parser collapsed both directions
    to None and silently computed a running aggregate."""
    with pytest.raises(SqlParseError, match="frame START"):
        s.sql("SELECT SUM(v) OVER (ORDER BY id ROWS UNBOUNDED FOLLOWING) "
              "FROM t")
    with pytest.raises(SqlParseError, match="frame END"):
        s.sql("SELECT SUM(v) OVER (ORDER BY id ROWS BETWEEN CURRENT ROW "
              "AND UNBOUNDED PRECEDING) FROM t")
    # the legal directions still parse and run
    rows = s.sql("SELECT SUM(v) OVER (ORDER BY id ROWS BETWEEN UNBOUNDED "
                 "PRECEDING AND UNBOUNDED FOLLOWING) AS sv FROM t").collect()
    assert len(rows) == 8


def test_hint_rejects_unsupported_argument(s):
    with pytest.raises(SqlParseError, match="hint argument"):
        s.sql("SELECT /*+ REPARTITION('8', k) */ k FROM t")


def test_between_in_like(s):
    check(s, "SELECT id FROM t WHERE id BETWEEN 2 AND 5",
          lambda s: t(s).filter((col("id") >= lit(2))
                                & (col("id") <= lit(5)))
          .select(col("id")))
    check(s, "SELECT id FROM t WHERE id NOT BETWEEN 2 AND 5",
          lambda s: t(s).filter(~((col("id") >= lit(2))
                                  & (col("id") <= lit(5))))
          .select(col("id")))
    from spark_rapids_tpu.ops.predicates import In
    check(s, "SELECT id FROM t WHERE k IN ('a', 'c')",
          lambda s: t(s).filter(In(col("k"), [lit("a"), lit("c")]))
          .select(col("id")))
    from spark_rapids_tpu.ops.strings import Like, RLike
    check(s, "SELECT id FROM t WHERE k LIKE 'a%'",
          lambda s: t(s).filter(Like(col("k"), lit("a%")))
          .select(col("id")))
    check(s, "SELECT id FROM t WHERE k RLIKE '[ab]'",
          lambda s: t(s).filter(RLike(col("k"), lit("[ab]")))
          .select(col("id")))


def test_concat_operator(s):
    from spark_rapids_tpu.ops.strings import Concat
    check(s, "SELECT k || '_x' AS kk FROM t",
          lambda s: t(s).select(Concat(col("k"), lit("_x")).alias("kk")))


def test_case_when(s):
    from spark_rapids_tpu.ops.conditional import CaseWhen
    check(s, "SELECT id, CASE WHEN v > 50 THEN 'hi' WHEN v > 20 "
             "THEN 'mid' ELSE 'lo' END AS b FROM t",
          lambda s: t(s).select(col("id"), CaseWhen(
              col("v") > lit(50), lit("hi"),
              col("v") > lit(20), lit("mid"), lit("lo")).alias("b")))
    # simple CASE (operand form)
    check(s, "SELECT id, CASE k WHEN 'a' THEN 1 WHEN 'b' THEN 2 END AS c "
             "FROM t",
          lambda s: t(s).select(col("id"), CaseWhen(
              col("k") == lit("a"), lit(1),
              col("k") == lit("b"), lit(2)).alias("c")))


def test_cast(s):
    check(s, "SELECT CAST(v AS INT) AS iv, CAST(id AS STRING) AS sid, "
             "CAST(v AS DECIMAL(10, 2)) AS dv FROM t",
          lambda s: t(s).select(
              col("v").cast(T.INT).alias("iv"),
              col("id").cast(T.STRING).alias("sid"),
              col("v").cast(T.DecimalType(10, 2)).alias("dv")))


def test_literals(s):
    df = s.sql("SELECT 1 AS a, 1.5 AS b, '[x]' AS c, TRUE AS d, "
               "NULL AS e, 2.5BD AS f, 3L AS g, 4D AS h "
               "FROM t LIMIT 1")
    # decimals collect as unscaled ints (engine convention, see
    # test_decimal128: "decimals are BIT-exact"); 2.5BD is dec(2,1) = 25
    assert dict(df.schema)["f"] == T.DecimalType(2, 1)
    assert df.collect() == [(1, 1.5, "[x]", True, None, 25, 3, 4.0)]


def test_date_literal_and_interval(s):
    from spark_rapids_tpu.ops.datetime import AddMonths, DateAdd, DateSub
    check(s, "SELECT id FROM t WHERE d <= DATE '1970-07-20'",
          lambda s: t(s).filter(
              col("d") <= lit(datetime.date(1970, 7, 20)))
          .select(col("id")))
    check(s, "SELECT d + INTERVAL 3 DAYS AS d2, d - INTERVAL 1 WEEK AS "
             "d3, d + INTERVAL 2 MONTHS AS d4 FROM t",
          lambda s: t(s).select(
              DateAdd(col("d"), lit(3)).alias("d2"),
              DateSub(col("d"), lit(7)).alias("d3"),
              AddMonths(col("d"), lit(2)).alias("d4")))


def test_functions_resolve_to_dsl_builders(s):
    check(s, "SELECT upper(k) AS uk, length(k) AS lk, abs(v - 50) AS av, "
             "coalesce(v, 0.0) AS cv, year(d) AS y, round(v / 7, 1) AS r "
             "FROM t",
          lambda s: t(s).select(
              F.upper(col("k")).alias("uk"),
              F.length(col("k")).alias("lk"),
              F.abs(col("v") - lit(50)).alias("av"),
              F.coalesce(col("v"), lit(0.0)).alias("cv"),
              F.year(col("d")).alias("y"),
              F.round(col("v") / lit(7), 1).alias("r")))


# -- aggregates --------------------------------------------------------------

def test_group_by_aggs(s):
    check(s, "SELECT k, SUM(v) AS sv, COUNT(v) AS cv, COUNT(*) AS c, "
             "AVG(v) AS av, MIN(v) AS mn, MAX(v) AS mx FROM t GROUP BY k",
          lambda s: t(s).group_by("k").agg(
              F.sum("v").alias("sv"), F.count(col("v")).alias("cv"),
              F.count().alias("c"), F.avg("v").alias("av"),
              F.min("v").alias("mn"), F.max("v").alias("mx")))


def test_global_agg(s):
    check(s, "SELECT SUM(v) AS sv FROM t",
          lambda s: t(s).agg(F.sum("v").alias("sv")))


def test_group_by_ordinal_and_alias(s):
    check(s, "SELECT k AS grp, SUM(v) AS sv FROM t GROUP BY 1",
          lambda s: t(s).group_by("k").agg(F.sum("v").alias("sv"))
          .select(col("k").alias("grp"), col("sv")))
    check(s, "SELECT k AS grp, SUM(v) AS sv FROM t GROUP BY grp",
          lambda s: t(s).group_by("k").agg(F.sum("v").alias("sv"))
          .select(col("k").alias("grp"), col("sv")))


def test_expression_over_aggregates(s):
    check(s, "SELECT k, SUM(v) / COUNT(v) + 1 AS m FROM t GROUP BY k",
          lambda s: t(s).group_by("k")
          .agg(F.sum("v").alias("__a1"), F.count(col("v")).alias("__a2"))
          .select(col("k"),
                  (col("__a1") / col("__a2") + lit(1)).alias("m")))


def test_having(s):
    check(s, "SELECT k, SUM(v) AS sv FROM t GROUP BY k HAVING SUM(v) > 40",
          lambda s: t(s).group_by("k").agg(F.sum("v").alias("sv"))
          .filter(col("sv") > lit(40)))
    # HAVING over an alias and over a hidden aggregate
    check(s, "SELECT k, SUM(v) AS sv FROM t GROUP BY k HAVING sv > 40",
          lambda s: t(s).group_by("k").agg(F.sum("v").alias("sv"))
          .filter(col("sv") > lit(40)))
    check(s, "SELECT k FROM t GROUP BY k HAVING COUNT(*) >= 2",
          lambda s: t(s).group_by("k").agg(F.count().alias("__c"))
          .filter(col("__c") >= lit(2)).select(col("k")))


def test_distinct(s):
    check(s, "SELECT DISTINCT k FROM t",
          lambda s: t(s).select(col("k")).group_by(col("k")).agg())


def test_count_distinct_unsupported(s):
    with pytest.raises(SqlAnalysisError, match="DISTINCT"):
        s.sql("SELECT COUNT(DISTINCT k) FROM t")


# -- set ops -----------------------------------------------------------------

def test_union_all_and_distinct(s):
    check(s, "SELECT k FROM t UNION ALL SELECT k FROM u",
          lambda s: t(s).select(col("k")).union(u(s).select(col("k"))))
    check(s, "SELECT k FROM t UNION SELECT k FROM u",
          lambda s: t(s).select(col("k")).union(u(s).select(col("k")))
          .group_by(col("k")).agg())


# -- joins -------------------------------------------------------------------

def test_join_on_equi(s):
    check(s, "SELECT id, v, w FROM t JOIN u ON t.k = u.k",
          lambda s: t(s).join(
              u(s).select(col("k").alias("k2"), col("w")),
              on=col("k") == col("k2"), how="inner")
          .select(col("id"), col("v"), col("w")))


def test_join_using_all_types(s):
    for how in ("inner", "left", "right", "full"):
        kw = {"inner": "JOIN", "left": "LEFT JOIN",
              "right": "RIGHT JOIN", "full": "FULL JOIN"}[how]
        check(s, f"SELECT id, v, w FROM t {kw} u USING (k)",
              lambda s, how=how: t(s).join(u(s), on=["k"], how=how)
              .select(col("id"), col("v"), col("w")))


def test_cross_join(s):
    check(s, "SELECT id, w FROM t CROSS JOIN u",
          lambda s: t(s).join(u(s)).select(col("id"), col("w")))


def test_semi_anti_join(s):
    check(s, "SELECT id FROM t LEFT SEMI JOIN u USING (k)",
          lambda s: t(s).join(u(s), on=["k"], how="leftsemi")
          .select(col("id")))
    check(s, "SELECT id FROM t LEFT ANTI JOIN u USING (k)",
          lambda s: t(s).join(u(s), on=["k"], how="leftanti")
          .select(col("id")))


def test_join_residual_condition(s):
    # equi conjunct rides the hash join; the rest stays a condition
    check(s, "SELECT id, w FROM t JOIN u ON t.k = u.k AND v > w * 5",
          lambda s: t(s).join(
              u(s).select(col("k").alias("k2"), col("w")),
              on=(col("k") == col("k2")) & (col("v") > col("w") * lit(5)),
              how="inner").select(col("id"), col("w")))


# -- ordering / limit --------------------------------------------------------

def test_order_by_variants(s):
    from spark_rapids_tpu.plan.nodes import SortOrder
    q = "SELECT id, v FROM t ORDER BY v DESC NULLS LAST, id"
    got = s.sql(q).collect()
    want = t(s).select(col("id"), col("v")).sort(
        SortOrder(col("v"), ascending=False, nulls_first=False),
        SortOrder(col("id"), ascending=True)).collect()
    assert got == want
    # ordinal
    assert s.sql("SELECT id, v FROM t ORDER BY 2 DESC NULLS LAST"
                 ).collect()[0][0] == 8


def test_order_by_hidden_input_column(s):
    # SQL: sort keys may reference input columns the projection drops
    got = s.sql("SELECT k FROM t WHERE v IS NOT NULL ORDER BY v DESC"
                ).collect()
    want = [(r[0],) for r in sorted(
        t(s).filter(col("v").isnotnull()).select(col("k"), col("v"))
        .collect(), key=lambda r: -r[1])]
    assert got == want


def test_limit(s):
    assert s.sql("SELECT id FROM t ORDER BY id LIMIT 3").collect() == \
        [(1,), (2,), (3,)]
    assert len(s.sql("SELECT id FROM t LIMIT 2").collect()) == 2


# -- windows -----------------------------------------------------------------

def test_window_functions(s):
    from spark_rapids_tpu.ops.window import Window as W
    check(s, "SELECT id, ROW_NUMBER() OVER (PARTITION BY k ORDER BY v) "
             "AS rn FROM t",
          lambda s: t(s).with_windows(
              rn=F.row_number().over(
                  W.partition_by("k").order_by("v")))
          .select(col("id"), col("rn")))
    check(s, "SELECT id, SUM(v) OVER (PARTITION BY k ORDER BY id) AS rs "
             "FROM t",
          lambda s: t(s).with_windows(
              rs=F.sum("v").over(W.partition_by("k").order_by("id")))
          .select(col("id"), col("rs")))


def test_window_frame(s):
    from spark_rapids_tpu.ops.window import Window as W
    check(s, "SELECT id, SUM(v) OVER (ORDER BY id ROWS BETWEEN 1 "
             "PRECEDING AND CURRENT ROW) AS rs FROM t",
          lambda s: t(s).with_windows(
              rs=F.sum("v").over(
                  W.order_by("id").rows_between(-1, 0)))
          .select(col("id"), col("rs")))


def test_window_lag_lead(s):
    from spark_rapids_tpu.ops.window import Window as W
    check(s, "SELECT id, LAG(v, 1) OVER (ORDER BY id) AS pv, "
             "LEAD(v, 2) OVER (ORDER BY id) AS nv FROM t",
          lambda s: t(s).with_windows(
              pv=F.lag("v", 1).over(W.order_by("id")),
              nv=F.lead("v", 2).over(W.order_by("id")))
          .select(col("id"), col("pv"), col("nv")))


# -- CTEs / subqueries -------------------------------------------------------

def test_cte(s):
    check(s, "WITH big AS (SELECT * FROM t WHERE v > 25), "
             "two AS (SELECT k FROM big) "
             "SELECT k, COUNT(*) AS c FROM two GROUP BY k",
          lambda s: t(s).filter(col("v") > lit(25)).select(col("k"))
          .group_by("k").agg(F.count().alias("c")))


def test_from_subquery(s):
    check(s, "SELECT kk FROM (SELECT k AS kk, v FROM t) WHERE v > 25",
          lambda s: t(s).select(col("k").alias("kk"), col("v"))
          .filter(col("v") > lit(25)).select(col("kk")))


def test_in_subquery_rewrites_to_semi_join(s):
    check(s, "SELECT id FROM t WHERE k IN (SELECT k FROM u)",
          lambda s: s.__class__ and __import__(
              "spark_rapids_tpu.plan", fromlist=["DataFrame"]).DataFrame(
              __import__("spark_rapids_tpu.plan",
                         fromlist=["nodes"]).nodes.Join(
                  t(s).plan, u(s).select(col("k")).plan, "leftsemi",
                  [col("k")], [col("k")]), s).select(col("id")))
    # NOT IN is null-aware (ANSI three-valued logic, Spark's
    # NullAwareAntiJoin): t's NULL-k row is UNKNOWN -> dropped, and only
    # k='c' rows fall outside u's {a, b, d}
    got = s.sql("SELECT id FROM t WHERE k NOT IN "
                "(SELECT k FROM u)").collect()
    assert sorted(r[0] for r in got) == [4, 8]
    # any NULL in the subquery empties the result (t.k has a NULL row)
    got = s.sql("SELECT id FROM t WHERE k NOT IN "
                "(SELECT k FROM t)").collect()
    assert got == []


def test_qualified_refs_across_same_named_join_columns(s):
    """a.x / b.x across a join where BOTH sides have x must bind their
    own side (the analyzer renames right-side duplicates; plan-layer
    name binding would otherwise silently pick the left copy)."""
    s2 = TpuSession()
    s2.create_dataframe({"id": np.array([1, 2], dtype=np.int64),
                         "x": np.array([1.0, 2.0])}) \
        .create_or_replace_temp_view("ta")
    s2.create_dataframe({"id": np.array([1, 2], dtype=np.int64),
                         "x": np.array([10.0, 20.0])}) \
        .create_or_replace_temp_view("tb")
    got = s2.sql("SELECT a.x, b.x FROM ta a JOIN tb b ON a.id = b.id "
                 "ORDER BY a.id").collect()
    assert got == [(1.0, 10.0), (2.0, 20.0)]
    # residual (non-equi) condition across the same-named columns
    got = s2.sql("SELECT a.id FROM ta a JOIN tb b "
                 "ON a.id = b.id AND a.x < b.x").collect()
    assert sorted(got) == [(1,), (2,)]
    # star expansion shows both copies under their SQL-level names
    df = s2.sql("SELECT * FROM ta a JOIN tb b ON a.id = b.id")
    assert [n for n, _ in df.schema] == ["id", "x", "id", "x"]
    row = sorted(df.collect())[0]
    assert row == (1, 1.0, 1, 10.0)


def test_right_full_using_coalesces_key(s):
    """RIGHT/FULL ... USING output the right/merged key, not NULL, for
    unmatched right rows (SQL USING = COALESCE(l.k, r.k))."""
    s2 = TpuSession()
    s2.create_dataframe({"k": np.array([1, 2], dtype=np.int64),
                         "va": np.array([10.0, 20.0])}) \
        .create_or_replace_temp_view("ja")
    s2.create_dataframe({"k": np.array([2, 3], dtype=np.int64),
                         "vb": np.array([200.0, 300.0])}) \
        .create_or_replace_temp_view("jb")
    got = sorted(s2.sql("SELECT k, vb FROM ja RIGHT JOIN jb USING (k)")
                 .collect())
    assert got == [(2, 200.0), (3, 300.0)]
    got = sorted(r[0] for r in s2.sql(
        "SELECT k FROM ja FULL JOIN jb USING (k)").collect())
    assert got == [1, 2, 3]


def test_create_or_replace_view_with_using_table(s, tmp_path):
    """CREATE OR REPLACE ... USING must replace an existing plan view of
    the same name (one namespace), and DROP VIEW must clear both."""
    s2 = TpuSession()
    s2.create_dataframe({"x": np.arange(3, dtype=np.int64)}) \
        .create_or_replace_temp_view("vv")
    p = str(tmp_path / "pq8")
    s2.create_dataframe({"x": np.arange(8, dtype=np.int64)}) \
        .write_parquet(p)
    s2.sql(f"CREATE OR REPLACE TEMP VIEW vv USING parquet "
           f"OPTIONS (path '{p}')")
    assert s2.sql("SELECT COUNT(*) FROM vv").collect()[0][0] == 8
    s2.sql("DROP VIEW vv")
    with pytest.raises(SqlAnalysisError, match="not found"):
        s2.sql("SELECT * FROM vv")


def test_quoted_identifiers_escape_keywords(s):
    """Backtick/double-quoted identifiers are never keywords — columns
    named `order`, `from`, `null` stay reachable."""
    s2 = TpuSession()
    s2.create_dataframe({
        "order": np.arange(3, dtype=np.int64),
        "from": np.array(["x", "y", "z"], dtype=object),
    }).create_or_replace_temp_view("kw")
    got = s2.sql('SELECT `order`, "from" FROM kw WHERE `order` > 0 '
                 "ORDER BY `order` DESC").collect()
    assert got == [(2, "z"), (1, "y")]
    # quoted alias that collides with a keyword
    got = s2.sql("SELECT `order` AS `select` FROM kw "
                 "ORDER BY `select`").collect()
    assert got == [(0,), (1,), (2,)]


def test_scalar_subquery(s):
    got = s.sql("SELECT id FROM t WHERE v > (SELECT AVG(v) FROM t) "
                "ORDER BY id").collect()
    avg = s.sql("SELECT AVG(v) FROM t").collect()[0][0]
    want = [(r[0],) for r in t(s).filter(col("v") > lit(avg))
            .select(col("id")).sort("id").collect()]
    assert got == want


# -- hints -------------------------------------------------------------------

def test_repartition_hint(s):
    check(s, "SELECT /*+ REPARTITION(4, k) */ k, COUNT(*) AS c FROM t "
             "GROUP BY k",
          lambda s: t(s).repartition(4, "k").group_by("k")
          .agg(F.count().alias("c")))


# -- temp views / catalog ----------------------------------------------------

def test_create_drop_temp_view(s):
    s.sql("CREATE TEMP VIEW big AS SELECT * FROM t WHERE v > 25")
    assert s.sql("SELECT COUNT(*) FROM big").collect()[0][0] == 5
    assert "big" in s.catalog.list_tables()
    # resolvable through session.table too
    assert s.table("big").count() == 5
    s.sql("CREATE OR REPLACE TEMP VIEW big AS SELECT * FROM t "
          "WHERE v > 55")
    assert s.sql("SELECT COUNT(*) FROM big").collect()[0][0] == 3
    with pytest.raises(SqlAnalysisError, match="already exists"):
        s.sql("CREATE TEMP VIEW big AS SELECT * FROM t")
    s.sql("DROP VIEW big")
    assert "big" not in s.catalog.list_tables()
    with pytest.raises(SqlAnalysisError, match="not found"):
        s.sql("DROP VIEW big")
    s.sql("DROP VIEW IF EXISTS big")  # no raise


def test_create_view_using_format(s, tmp_path):
    p = str(tmp_path / "pq")
    t(s).select(col("id"), col("v")).write_parquet(p)
    s.sql(f"CREATE TEMP VIEW pq_tbl USING parquet OPTIONS (path '{p}')")
    assert s.sql("SELECT COUNT(*) FROM pq_tbl").collect()[0][0] == 8
    got = _canon(s.sql("SELECT id, v FROM pq_tbl").collect())
    assert got == _canon(t(s).select(col("id"), col("v")).collect())
    s.sql("DROP VIEW pq_tbl")


def test_view_sees_plan_not_name(s):
    """Temp views capture the PLAN: re-registering t does not change an
    existing view built over the old t."""
    s2 = TpuSession()
    s2.create_dataframe({"x": np.arange(3, dtype=np.int64)}) \
        .create_or_replace_temp_view("src")
    s2.sql("CREATE TEMP VIEW snap AS SELECT * FROM src")
    s2.create_dataframe({"x": np.arange(10, dtype=np.int64)}) \
        .create_or_replace_temp_view("src")
    assert s2.sql("SELECT COUNT(*) FROM snap").collect()[0][0] == 3
    assert s2.sql("SELECT COUNT(*) FROM src").collect()[0][0] == 10


# -- function registration ---------------------------------------------------

def test_session_registered_udf(s):
    from spark_rapids_tpu.udf import udf
    s.catalog.register_function("plus_one", udf(lambda x: x + 1))
    try:
        check(s, "SELECT plus_one(id) AS p FROM t",
              lambda s: t(s).select((col("id") + lit(1)).alias("p")))
    finally:
        s.catalog.unregister_function("plus_one")


def test_global_registered_function(s):
    F.register_sql_function("twice", lambda e: e * lit(2))
    try:
        check(s, "SELECT twice(v) AS p FROM t",
              lambda s: t(s).select((col("v") * lit(2)).alias("p")))
    finally:
        F.unregister_sql_function("twice")


def test_hive_udf_resolves(s):
    from spark_rapids_tpu.hive_udf import (
        register_hive_udf,
        unregister_hive_udf,
    )
    register_hive_udf("sql_t_upper",
                      lambda x: x.upper() if x is not None else None,
                      "string")
    try:
        got = _canon(s.sql("SELECT sql_t_upper(k) AS ku FROM t").collect())
        want = _canon([(k.upper() if k else None,)
                       for (k,) in t(s).select(col("k")).collect()])
        assert got == want
    finally:
        unregister_hive_udf("sql_t_upper")


def test_f_expr(s):
    got = _canon(t(s).select(F.expr("v * 2 + id").alias("e")).collect())
    want = _canon(t(s).select(
        (col("v") * lit(2) + col("id")).alias("e")).collect())
    assert got == want


# -- error surfaces ----------------------------------------------------------

def test_parse_error_positions(s):
    with pytest.raises(SqlParseError) as ei:
        s.sql("SELECT id FROM t WHERE")
    assert ei.value.line == 1 and ei.value.col >= 23
    with pytest.raises(SqlParseError) as ei:
        s.sql("SELECT id,\nFROM t")
    assert ei.value.line == 2
    assert "^" in str(ei.value)  # caret snippet
    with pytest.raises(SqlParseError, match="expected BY"):
        s.sql("SELECT id FROM t ORDER id")
    with pytest.raises(SqlParseError, match="after statement"):
        s.sql("SELECT id FROM t garbage extra")
    with pytest.raises(SqlParseError, match="unterminated string"):
        s.sql("SELECT 'oops FROM t")


def test_analysis_error_positions(s):
    with pytest.raises(SqlAnalysisError) as ei:
        s.sql("SELECT nope FROM t")
    assert "cannot resolve column 'nope'" in str(ei.value)
    assert ei.value.line == 1 and ei.value.col == 8
    with pytest.raises(SqlAnalysisError, match="not found"):
        s.sql("SELECT * FROM no_such_table")
    with pytest.raises(SqlAnalysisError, match="undefined function"):
        s.sql("SELECT frobnicate(id) FROM t")
    with pytest.raises(SqlAnalysisError, match="argument"):
        s.sql("SELECT upper(k, v) FROM t")
    with pytest.raises(SqlAnalysisError, match="GROUP BY"):
        s.sql("SELECT k, v FROM t GROUP BY k")


def test_unsupported_constructs_report_reasons(s):
    # overrides-style per-construct reasons
    with pytest.raises(SqlParseError, match="EXISTS subqueries are not "
                                            "supported"):
        s.sql("SELECT id FROM t WHERE EXISTS (SELECT 1 FROM u)")
    with pytest.raises(SqlAnalysisError,
                       match="is not supported by the SQL front end"):
        s.sql("SELECT id FROM t WHERE v > ALL_ROWS(u)" if False else
              "SELECT INTERVAL 3 DAYS FROM t")
    with pytest.raises(SqlAnalysisError,
                       match="window functions must be top-level"):
        s.sql("SELECT ROW_NUMBER() OVER (ORDER BY id) + 1 FROM t")
    with pytest.raises(SqlAnalysisError, match="semi join"):
        s.sql("SELECT id FROM t WHERE k IN (SELECT k FROM u) OR v > 5")
    with pytest.raises(SqlAnalysisError, match="hint"):
        s.sql("SELECT /*+ BROADCAST(u) */ id FROM t")


def test_explain_carries_sql_text(s):
    out = s.sql("SELECT id FROM t WHERE v > 5").explain()
    assert out.startswith("-- SQL: SELECT id FROM t WHERE v > 5")


# -- ScaleTest q1-q10: SQL text == DSL, results AND dispatch counts ----------

@pytest.fixture(scope="module")
def scale_setup():
    from spark_rapids_tpu.datagen import scale_test_specs
    from scale_test import build_queries, build_sql_queries
    sf = 0.002
    specs = scale_test_specs(sf)
    tables = {n: sp.generate_table(sf, seed=0) for n, sp in specs.items()}
    s_dsl, s_sql = TpuSession(), TpuSession()
    return (build_queries(s_dsl, tables),
            build_sql_queries(s_sql, tables), s_dsl, s_sql)


@pytest.mark.parametrize("name", [f"q{i}" for i in range(1, 23)])
def test_scale_query_sql_equals_dsl(scale_setup, name):
    dsl_q, sql_q, s_dsl, s_sql = scale_setup
    a = _canon(dsl_q[name]().collect())
    b = _canon(sql_q[name]().collect())
    assert a == b, f"{name}: SQL and DSL results differ"
    # warm runs: device dispatch counts must match exactly (the SQL path
    # lowers onto the same plan layer — no parallel execution engine)
    dsl_q[name]().collect_table()
    da = s_dsl.last_dispatches
    sql_q[name]().collect_table()
    db = s_sql.last_dispatches
    assert da == db, f"{name}: dispatches dsl={da} sql={db}"
