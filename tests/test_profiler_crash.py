"""Profiler + crash-handler tests (reference: profiler.scala,
GpuCoreDumpHandler.scala, DumpUtils.scala, RangeConfMatcher — SURVEY §5)."""

import json
import os

import numpy as np
import pytest

from spark_rapids_tpu.runtime.profiler import TpuProfiler, parse_ranges


def test_parse_ranges():
    assert parse_ranges("1-3,8") == {1, 2, 3, 8}
    assert parse_ranges("") is None
    assert parse_ranges("5") == {5}
    assert parse_ranges(" 0-1 , 4 ") == {0, 1, 4}


@pytest.mark.parametrize("bad", ["5-", "-3", "1-x", "x", "7-3", "-2"])
def test_parse_ranges_malformed_named_error(bad):
    """Malformed specs raise a clear error NAMING the conf key, at
    conf-read time — not a raw int() ValueError at the first profiled
    query."""
    with pytest.raises(ValueError,
                       match="spark.rapids.profile.queryRanges"):
        parse_ranges(bad)


def test_profiler_validates_ranges_at_conf_read():
    from spark_rapids_tpu.conf import RapidsConf
    conf = RapidsConf({"spark.rapids.profile.queryRanges": "1-x"})
    with pytest.raises(ValueError,
                       match="spark.rapids.profile.queryRanges"):
        TpuProfiler(conf)


def test_nested_query_does_not_burn_query_index():
    """Nested/cached-relation materialization queries ride the outer
    trace session and must NOT claim a _query_index slot — otherwise
    queryRanges indices drift off the user's spec."""
    from spark_rapids_tpu.conf import RapidsConf
    p = TpuProfiler(RapidsConf({}))  # profiling disabled; indexing still runs
    with p.profile_query() as outer:
        assert outer is None
        with p.profile_query() as inner:  # nested: no index
            assert inner is None
    with p.profile_query():
        pass
    assert p._query_index == 2  # two TOP-LEVEL queries, one nested


def test_query_ranges_alignment_with_nested_queries(tmp_path):
    """With queryRanges=1, a nested query inside query 0 must not shift
    profiling onto the wrong top-level query: the SECOND top-level
    query is the one traced."""
    from spark_rapids_tpu.conf import RapidsConf
    conf = RapidsConf({
        "spark.rapids.profile.enabled": "true",
        "spark.rapids.profile.pathPrefix": str(tmp_path),
        "spark.rapids.profile.queryRanges": "1"})
    p = TpuProfiler(conf)
    with p.profile_query() as q0:      # index 0: not in ranges
        assert q0 is None
        with p.profile_query() as nested:
            assert nested is None
    with p.profile_query() as q1:      # index 1: profiled
        assert q1 is not None and q1.endswith("query_1")
    assert p.sessions_written == 1


def test_profiler_query_ranges(tmp_path):
    from spark_rapids_tpu.conf import RapidsConf
    conf = RapidsConf({
        "spark.rapids.profile.enabled": "true",
        "spark.rapids.profile.pathPrefix": str(tmp_path),
        "spark.rapids.profile.queryRanges": "1"})
    p = TpuProfiler(conf)
    assert not p.should_profile(0)
    assert p.should_profile(1)
    assert not p.should_profile(2)


def test_profiler_collects_trace(tmp_path):
    """An enabled profiler writes an Xprof trace dir for the profiled
    query (CPU-mesh jax works with the profiler too)."""
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({
        "spark.rapids.profile.enabled": "true",
        "spark.rapids.profile.pathPrefix": str(tmp_path),
        "spark.rapids.profile.queryRanges": "0"})
    df = s.create_dataframe({"x": np.arange(100, dtype=np.int64)})
    assert df.select("x").count() == 100
    qdir = tmp_path / "query_0"
    assert qdir.is_dir()
    # jax writes plugins/profile/<ts>/ under the trace dir
    found = list(qdir.rglob("*.xplane.pb")) + list(qdir.rglob("*.json.gz")) \
        + list(qdir.rglob("*.trace*"))
    assert s.profiler.sessions_written == 1
    assert found, f"no trace artifacts under {qdir}"


def test_fatal_classification():
    from spark_rapids_tpu.runtime.crash_handler import is_fatal_device_error

    class XlaRuntimeError(Exception):
        pass

    assert is_fatal_device_error(XlaRuntimeError("INTERNAL: device halted"))
    assert not is_fatal_device_error(
        XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory"))
    assert not is_fatal_device_error(ValueError("INTERNAL"))


def test_crash_report_written(tmp_path):
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.runtime.crash_handler import write_crash_report
    conf = RapidsConf({"spark.rapids.memory.crashDump.dir": str(tmp_path)})
    try:
        raise RuntimeError("XlaRuntimeError: INTERNAL: boom")
    except RuntimeError as e:
        path = write_crash_report(e, conf, plan_description="* Scan")
    assert path and os.path.exists(path)
    report = json.load(open(path))
    assert "boom" in report["exception"]
    assert report["plan"] == "* Scan"
    assert "thread_dump" in report
    assert "buffer_catalog" in report


def test_dump_table(tmp_path):
    import pyarrow.parquet as pq
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.runtime.crash_handler import dump_table
    t = HostTable.from_pydict({"x": np.arange(10, dtype=np.int64)})
    p = dump_table(t, str(tmp_path / "d.parquet"))
    assert pq.read_table(p).num_rows == 10
