"""CLI: ``python -m spark_rapids_tpu.lint``.

Runs the repo lint, the registry auditor and the golden-suite plan
verification (TPC-H q1-q22, DSL + SQL, AQE on/off) and exits non-zero on
any diagnostic — the correctness gate every PR runs under."""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.lint",
        description="plan verifier + registry auditor + repo lint")
    ap.add_argument("--skip-repo", action="store_true",
                    help="skip the Python-AST repo lint")
    ap.add_argument("--skip-registry", action="store_true",
                    help="skip the registry/doc-drift audit")
    ap.add_argument("--skip-plans", action="store_true",
                    help="skip golden-suite (TPC-H q1-q22) plan "
                         "verification")
    ap.add_argument("--skip-exec-metrics", action="store_true",
                    help="skip the RA-ESSENTIAL-METRICS executed-corpus "
                         "audit (runs a golden-corpus slice)")
    ap.add_argument("--sf", type=float, default=0.01,
                    help="scale factor for golden-suite table generation")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every rule id and exit")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate SUPPORTED_OPS.md and CONFIGS.md "
                         "from the registries, then exit")
    args = ap.parse_args(argv)

    from spark_rapids_tpu.lint.diagnostics import RULES
    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid:22s} {RULES[rid]}")
        return 0
    if args.write_docs:
        from spark_rapids_tpu.lint.registry_audit import regenerate_docs
        for path in regenerate_docs():
            print(f"wrote {path}")
        return 0

    diags = []
    ran = []
    if not args.skip_repo:
        from spark_rapids_tpu.lint.repo_lint import lint_repo
        repo = lint_repo()
        print(f"repo lint: {len(repo)} diagnostic(s)")
        diags += repo
        ran.append("repo")
    if not args.skip_registry:
        from spark_rapids_tpu.lint.registry_audit import audit_registry
        reg = audit_registry()
        print(f"registry audit: {len(reg)} diagnostic(s)")
        diags += reg
        ran.append("registries")
    if not args.skip_plans:
        from spark_rapids_tpu.lint.golden import verify_golden_plans
        plans = verify_golden_plans(scale_factor=args.sf)
        print(f"golden-suite plan verify: {len(plans)} diagnostic(s)")
        diags += plans
        ran.append("golden-suite plans")
    if not args.skip_exec_metrics:
        from spark_rapids_tpu.lint.registry_audit import audit_exec_metrics
        em = audit_exec_metrics()
        print(f"exec-metrics audit: {len(em)} diagnostic(s)")
        diags += em
        ran.append("exec metrics")

    for d in diags:
        print(str(d))
    if diags:
        print(f"FAILED: {len(diags)} diagnostic(s)")
        return 1
    print(f"OK: {', '.join(ran) if ran else 'nothing checked'} clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
