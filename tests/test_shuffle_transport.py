"""P2P shuffle transport tests.

Mirrors the reference's mocked-transport protocol suites
(RapidsShuffleClientSuite / RapidsShuffleServerSuite /
RapidsShuffleIteratorSuite, run against mocked jucx —
tests/.../RapidsShuffleTestHelper.scala:45-84): windowed transfer
correctness, bounce-buffer bounding, heartbeat peer discovery/eviction,
fault propagation, catalog spill, plus a real TCP two-executor fetch and
the engine-level P2P exchange vs the CPU oracle."""

import threading

import numpy as np
import pytest

from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.shuffle.catalogs import (
    ShuffleBufferCatalog,
    ShuffleReceivedBufferCatalog,
)
from spark_rapids_tpu.shuffle.client_server import (
    ShuffleClient,
    ShuffleServer,
    decode_block_list,
    decode_metadata_request,
    decode_transfer_request,
    encode_block_list,
    encode_metadata_request,
    encode_transfer_request,
)
from spark_rapids_tpu.shuffle.heartbeat import (
    ShuffleHeartbeatEndpoint,
    ShuffleHeartbeatManager,
)
from spark_rapids_tpu.shuffle.transport import (
    BlockRange,
    BounceBufferManager,
    InProcessTransport,
    PeerInfo,
    TcpShuffleServerListener,
    TcpTransport,
    windowed_slices,
)


def _blob(i, n):
    rng = np.random.default_rng(i)
    return rng.integers(0, 256, n, dtype=np.uint8).tobytes()


# -- windowed block iterator -------------------------------------------------

def test_windowed_slices_small_blocks_share_window():
    blocks = [BlockRange((0, m, 0), 100) for m in range(5)]
    windows = windowed_slices(blocks, 1000)
    assert len(windows) == 1
    assert sum(s.length for s in windows[0]) == 500


def test_windowed_slices_large_block_spans_windows():
    windows = windowed_slices([BlockRange((0, 0, 0), 2500)], 1000)
    assert [sum(s.length for s in w) for w in windows] == [1000, 1000, 500]
    # offsets must chain
    offs = [(s.block_offset, s.length) for w in windows for s in w]
    assert offs == [(0, 1000), (1000, 1000), (2000, 500)]


def test_windowed_slices_mixed_packing():
    blocks = [BlockRange((0, 0, 0), 700), BlockRange((0, 1, 0), 700)]
    windows = windowed_slices(blocks, 1000)
    # second window starts mid-second-block
    assert len(windows) == 2
    assert sum(s.length for w in windows for s in w) == 1400


# -- bounce buffers ----------------------------------------------------------

def test_bounce_pool_blocks_until_release():
    pool = BounceBufferManager(64, 1)
    buf = pool.acquire()
    with pytest.raises(ColumnarProcessingError):
        pool.acquire(timeout=0.05)
    pool.release(buf)
    buf2 = pool.acquire(timeout=1)
    assert buf2 is buf
    pool.release(buf2)
    with pytest.raises(ColumnarProcessingError):
        pool.release(buf2)  # double release


# -- message encodings -------------------------------------------------------

def test_message_roundtrips():
    assert decode_metadata_request(
        encode_metadata_request(7, 3, [1, 2, 9])) == (7, 3, [1, 2, 9])
    assert decode_metadata_request(
        encode_metadata_request(7, 3, None)) == (7, 3, None)
    blocks = [((1, 2, 3), 4096), ((1, 5, 3), 123)]
    assert decode_block_list(encode_block_list(blocks)) == blocks
    assert decode_transfer_request(
        encode_transfer_request(1 << 20, [(1, 2, 3)])) == \
        (1 << 20, [(1, 2, 3)])


# -- in-process client/server (mocked-jucx analog) ---------------------------

def _make_env(bounce=256, nbuf=2, host_limit=1 << 30):
    catalog = ShuffleBufferCatalog(host_limit_bytes=host_limit)
    server = ShuffleServer(catalog, BounceBufferManager(bounce, nbuf))
    return catalog, server


def test_client_fetch_multiwindow_inprocess():
    catalog, server = _make_env(bounce=256)
    blobs = {m: _blob(m, 300 + 100 * m) for m in range(4)}
    for m, b in blobs.items():
        catalog.add_block((0, m, 2), b)
    # another partition's block must not appear
    catalog.add_block((0, 0, 1), _blob(99, 50))

    InProcessTransport.register_server("A", server)
    try:
        transport = InProcessTransport(BounceBufferManager(256, 2))
        client = ShuffleClient(transport.connect(PeerInfo("A")),
                               window_size=256)
        received = ShuffleReceivedBufferCatalog()
        blocks = client.fetch_partition(0, 2, received)
        assert [bid for bid, _ in blocks] == [(0, m, 2) for m in range(4)]
        got = dict(received.drain())
        assert {bid: b for bid, b in got.items()} == {
            (0, m, 2): blobs[m] for m in range(4)}
        assert server.windows_sent > 1  # small windows forced chunking
    finally:
        InProcessTransport.unregister_server("A")


def test_fetch_unknown_block_surfaces_error():
    _catalog, server = _make_env()
    InProcessTransport.register_server("B", server)
    try:
        transport = InProcessTransport(BounceBufferManager(256, 2))
        client = ShuffleClient(transport.connect(PeerInfo("B")),
                               window_size=128)
        received = ShuffleReceivedBufferCatalog()
        with pytest.raises(ColumnarProcessingError, match="transfer failed"):
            client.fetch_blocks([((9, 9, 9), 10)], received)
        with pytest.raises(ColumnarProcessingError, match="fetch failed"):
            list(received.drain(timeout=1))
    finally:
        InProcessTransport.unregister_server("B")


def test_fetch_metadata_empty_for_unknown_shuffle():
    _catalog, server = _make_env()
    InProcessTransport.register_server("C", server)
    try:
        transport = InProcessTransport(BounceBufferManager(64, 1))
        client = ShuffleClient(transport.connect(PeerInfo("C")))
        assert client.fetch_metadata(42, 0) == []
    finally:
        InProcessTransport.unregister_server("C")


def test_oversized_window_rejected_by_server():
    catalog, server = _make_env(bounce=128)
    catalog.add_block((0, 0, 0), _blob(1, 64))
    InProcessTransport.register_server("D", server)
    try:
        transport = InProcessTransport(BounceBufferManager(1 << 20, 1))
        client = ShuffleClient(transport.connect(PeerInfo("D")),
                               window_size=1 << 20)  # > server bounce size
        received = ShuffleReceivedBufferCatalog()
        with pytest.raises(ColumnarProcessingError, match="bounce"):
            client.fetch_blocks([((0, 0, 0), 64)], received)
    finally:
        InProcessTransport.unregister_server("D")


# -- catalog spill -----------------------------------------------------------

def test_shuffle_catalog_spills_and_serves_from_disk():
    catalog = ShuffleBufferCatalog(host_limit_bytes=1000)
    blobs = {m: _blob(m, 400) for m in range(5)}
    for m, b in blobs.items():
        catalog.add_block((3, m, 0), b)
    assert catalog.spill_count >= 2  # 2000 bytes over a 1000-byte limit
    assert catalog.host_bytes <= 1000
    for m, b in blobs.items():
        assert catalog.get_block((3, m, 0)) == b  # spilled ones fault back
    catalog.remove_shuffle(3)
    with pytest.raises(ColumnarProcessingError):
        catalog.get_block((3, 0, 0))


def test_duplicate_block_rejected():
    catalog = ShuffleBufferCatalog()
    catalog.add_block((0, 0, 0), b"x")
    with pytest.raises(ColumnarProcessingError):
        catalog.add_block((0, 0, 0), b"y")


# -- heartbeat ---------------------------------------------------------------

def test_heartbeat_discovery_and_eviction():
    mgr = ShuffleHeartbeatManager(heartbeat_timeout_s=0.2)
    seen_a, seen_b = [], []
    a = ShuffleHeartbeatEndpoint(mgr, PeerInfo("A"), seen_a.append,
                                 interval_s=100)
    assert seen_a == []  # first in, nobody to see
    b = ShuffleHeartbeatEndpoint(mgr, PeerInfo("B"), seen_b.append,
                                 interval_s=100)
    assert [p.executor_id for p in seen_b] == ["A"]
    a.beat_once()
    assert [p.executor_id for p in seen_a] == ["B"]
    # B goes silent; after the timeout the driver evicts it
    import time
    time.sleep(0.25)
    a.beat_once()  # keeps A alive... but A also timed out in between
    dead = mgr.evict_dead()
    assert dead == ["B"]
    assert mgr.live_executors() == ["A"]
    a.close()
    b.close()


def test_heartbeat_unregistered_executor_rejected():
    mgr = ShuffleHeartbeatManager()
    with pytest.raises(ColumnarProcessingError):
        mgr.heartbeat("ghost")


def test_evicted_endpoint_rejoins_by_default():
    """Satellite pin: a paused-then-resumed executor whose heartbeat the
    driver rejected (evicted) must RE-REGISTER and keep beating instead
    of its heartbeat loop dying silently — otherwise it goes permanently
    deaf to new peers."""
    import time
    mgr = ShuffleHeartbeatManager(heartbeat_timeout_s=0.1)
    seen_a = []
    a = ShuffleHeartbeatEndpoint(mgr, PeerInfo("A"), seen_a.append,
                                 interval_s=100)
    # A pauses past the heartbeat window; the driver forgets it
    time.sleep(0.15)
    assert mgr.evict_dead() == ["A"]
    # the loop's next beat hits "never registered" -> default on_evicted
    # re-registers instead of killing the loop
    a.beat_or_recover()
    assert a.evicted_count == 1
    assert "A" in mgr.live_executors()
    # ...and the rejoined endpoint still discovers new peers
    b = ShuffleHeartbeatEndpoint(mgr, PeerInfo("B"), lambda p: None,
                                 interval_s=100)
    a.beat_or_recover()
    assert [p.executor_id for p in seen_a] == ["B"]
    a.close()
    b.close()


def test_evicted_endpoint_custom_callback():
    import time
    mgr = ShuffleHeartbeatManager(heartbeat_timeout_s=0.1)
    evictions = []
    a = ShuffleHeartbeatEndpoint(mgr, PeerInfo("A"), lambda p: None,
                                 interval_s=100,
                                 on_evicted=lambda: evictions.append(1))
    time.sleep(0.15)
    mgr.evict_dead()
    a.beat_or_recover()
    assert evictions == [1]
    # the custom callback chose NOT to re-register: still forgotten
    assert "A" not in mgr.live_executors()
    a.close()


def test_reregistration_replaces_stale_endpoint():
    mgr = ShuffleHeartbeatManager()
    mgr.register_executor(PeerInfo("A", "h1", 1))
    mgr.register_executor(PeerInfo("B", "h2", 2))
    peers = mgr.register_executor(PeerInfo("A", "h1b", 99))  # A restarts
    assert [p.executor_id for p in peers] == ["B"]
    fresh = mgr.heartbeat("B")
    assert [(p.executor_id, p.port) for p in fresh] == [("A", 99)]


# -- TCP two-executor fetch --------------------------------------------------

def test_tcp_fetch_between_executors():
    catalog_a, server_a = _make_env(bounce=512)
    blobs = {m: _blob(10 + m, 2000) for m in range(3)}
    for m, b in blobs.items():
        catalog_a.add_block((1, m, 0), b)
    listener = TcpShuffleServerListener(server_a)
    try:
        mgr = ShuffleHeartbeatManager()
        mgr.register_executor(
            PeerInfo("A", listener.host, listener.port))
        peers = mgr.register_executor(PeerInfo("B"))
        assert peers[0].port == listener.port

        transport = TcpTransport(BounceBufferManager(512, 2))
        client = ShuffleClient(transport.connect(peers[0]), window_size=512)
        received = ShuffleReceivedBufferCatalog()
        blocks = client.fetch_partition(1, 0, received)
        assert len(blocks) == 3
        got = dict(received.drain())
        assert got == {(1, m, 0): blobs[m] for m in range(3)}
        assert server_a.windows_sent >= 12  # 6000B through 512B windows
    finally:
        listener.close()


def test_tcp_concurrent_fetchers():
    """Two clients fetch different partitions concurrently through the same
    server; the send bounce pool (2 buffers) bounds server-side memory."""
    catalog, server = _make_env(bounce=256, nbuf=2)
    data = {p: {m: _blob(100 * p + m, 1500) for m in range(2)}
            for p in range(2)}
    for p, by_map in data.items():
        for m, b in by_map.items():
            catalog.add_block((0, m, p), b)
    listener = TcpShuffleServerListener(server)
    results = {}
    errors = []

    def fetch(p):
        try:
            transport = TcpTransport(BounceBufferManager(256, 2))
            client = ShuffleClient(
                transport.connect(PeerInfo("A", listener.host,
                                           listener.port)),
                window_size=256)
            received = ShuffleReceivedBufferCatalog()
            client.fetch_partition(0, p, received)
            results[p] = dict(received.drain())
        except Exception as e:  # pragma: no cover
            errors.append(e)

    try:
        threads = [threading.Thread(target=fetch, args=(p,))
                   for p in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        for p in range(2):
            assert results[p] == {(0, m, p): data[p][m] for m in range(2)}
        assert server.send_pool.high_water <= 2
    finally:
        listener.close()


# -- engine-level P2P exchange ----------------------------------------------

def _kv_table(n, seed):
    rng = np.random.default_rng(seed)
    return {"k": rng.integers(0, 40, n).astype(np.int64),
            "v": rng.standard_normal(n)}


@pytest.mark.parametrize("transport", ["inprocess", "tcp"])
def test_engine_repartition_p2p_matches_cpu(transport):
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.session import TpuSession

    data = _kv_table(3000, seed=5)
    tpu = TpuSession({"spark.rapids.shuffle.mode": "P2P",
                      "spark.rapids.shuffle.p2p.transport": transport,
                      "spark.rapids.shuffle.compression.codec": "lz4"})
    cpu = TpuSession({"spark.rapids.sql.enabled": "false"})

    def q(s):
        return (s.create_dataframe(dict(data), num_batches=3)
                .repartition(4, "k")
                .group_by("k").agg(F.sum("v").alias("sv"),
                                   F.count("v").alias("c")))

    got = sorted(q(tpu).collect())
    want = sorted(q(cpu).collect())
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[2] == w[2]
        assert abs(g[1] - w[1]) <= 1e-6 * max(1.0, abs(w[1]))


def test_tcp_two_process_shuffle_fetch(tmp_path):
    """TWO PROCESSES (simulated two hosts over the DCN wire): a child
    process serves map-output blocks through TcpShuffleServerListener;
    this process fetches them with the TcpTransport client — the
    multi-host half of SURVEY §2.6's shuffle contract."""
    import subprocess
    import sys
    import time

    port_file = tmp_path / "port"
    code = f"""
import sys, time
sys.path.insert(0, {repr(str(__import__('pathlib').Path(__file__).resolve().parents[1]))})
from spark_rapids_tpu.shuffle.catalogs import ShuffleBufferCatalog
from spark_rapids_tpu.shuffle.client_server import ShuffleServer
from spark_rapids_tpu.shuffle.transport import BounceBufferManager
from spark_rapids_tpu.shuffle.p2p import TcpShuffleServerListener
catalog = ShuffleBufferCatalog()
for m in range(3):
    catalog.add_block((7, m, 0), bytes([m]) * 2000)
server = ShuffleServer(catalog, BounceBufferManager(512, 2))
listener = TcpShuffleServerListener(server)
open({repr(str(port_file))}, "w").write(f"{{listener.host}}:{{listener.port}}")
time.sleep(30)
"""
    child = subprocess.Popen([sys.executable, "-c", code],
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE)
    try:
        for _ in range(100):
            if port_file.exists() and port_file.read_text():
                break
            if child.poll() is not None:
                raise AssertionError(
                    f"server process died: {child.stderr.read().decode()}")
            time.sleep(0.1)
        host, port = port_file.read_text().split(":")

        from spark_rapids_tpu.shuffle.catalogs import (
            ShuffleReceivedBufferCatalog,
        )
        from spark_rapids_tpu.shuffle.client_server import ShuffleClient
        from spark_rapids_tpu.shuffle.transport import (
            BounceBufferManager,
            PeerInfo,
            TcpTransport,
        )
        transport = TcpTransport(BounceBufferManager(512, 2))
        client = ShuffleClient(
            transport.connect(PeerInfo("remote", host, int(port))),
            window_size=512)
        received = ShuffleReceivedBufferCatalog()
        blocks = client.fetch_partition(7, 0, received)
        assert len(blocks) == 3
        got = dict(received.drain())
        assert got == {(7, m, 0): bytes([m]) * 2000 for m in range(3)}
    finally:
        child.terminate()
        child.wait(timeout=10)
