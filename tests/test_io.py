"""File IO tests: scans in all three reader modes + writers round-trip
(reference: integration_tests parquet/orc/csv/json test files — SURVEY.md §4)."""

import datetime

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostTable
from tests.asserts import assert_runs_on_tpu, assert_tpu_and_cpu_are_equal
from tests.data_gen import table_gen


def _sample_table(n=1000, seed=7):
    return table_gen({
        "i": T.INT, "l": T.LONG, "d": T.DOUBLE, "f": T.FLOAT,
        "b": T.BOOLEAN, "s": T.STRING,
    }, n, seed=seed)


def _write_sample_parquet(tmp_path, num_files=3, rows=400):
    from spark_rapids_tpu.io.parquet import write_parquet
    paths = []
    for k in range(num_files):
        t = _sample_table(rows, seed=k)
        paths.extend(write_parquet(t, str(tmp_path / f"f{k}"),
                                   row_group_rows=150))
    return paths


@pytest.mark.parametrize("mode", ["PERFILE", "COALESCING", "MULTITHREADED"])
def test_parquet_read_modes(tmp_path, session, cpu_session, mode):
    paths = _write_sample_parquet(tmp_path)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.read_parquet(*paths, reader_type=mode),
        session, cpu_session)


def test_parquet_scan_on_device(tmp_path, session):
    paths = _write_sample_parquet(tmp_path, num_files=1)
    assert_runs_on_tpu(lambda s: s.read_parquet(*paths), session)


def test_parquet_column_pruning(tmp_path, session):
    paths = _write_sample_parquet(tmp_path, num_files=1)
    df = session.read_parquet(*paths, columns=["l", "s"])
    assert df.columns == ["l", "s"]
    assert df.count() == 400


def test_parquet_predicate_pushdown(tmp_path, session):
    paths = _write_sample_parquet(tmp_path, num_files=2)
    df = session.read_parquet(*paths, filters=[("b", "=", True)])
    rows = df.collect()
    assert all(r[4] for r in rows)


def test_parquet_pipeline_over_scan(tmp_path, session, cpu_session):
    paths = _write_sample_parquet(tmp_path)
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col
    assert_tpu_and_cpu_are_equal(
        lambda s: (s.read_parquet(*paths)
                   .filter(col("i").isnotnull())
                   .group_by("b")
                   .agg(F.sum("l").alias("sl"), F.count("i").alias("c"))),
        session, cpu_session)


def test_parquet_partitioned_write(tmp_path, session):
    t = HostTable.from_pydict({
        "k": ["a", "b", "a", "c", None],
        "v": [1, 2, 3, 4, 5],
    })
    df = session.create_dataframe(t)
    from spark_rapids_tpu.io.parquet import write_parquet
    files = write_parquet(df.collect_table(), str(tmp_path / "out"),
                          partition_by=["k"])
    assert len(files) == 4  # a, b, c, null
    assert any("k=a" in f for f in files)
    assert any("__HIVE_DEFAULT_PARTITION__" in f for f in files)
    # partition column recovered from key=value dirs on read-back
    back = session.read_parquet(str(tmp_path / "out"))
    assert dict(back.schema)["k"] == T.STRING
    rows = sorted(back.collect())
    assert rows == sorted([(1, "a"), (3, "a"), (2, "b"), (4, "c"), (5, None)])


def test_partition_column_type_inference(tmp_path, session):
    t = HostTable.from_pydict({"year": [2023, 2023, 2024], "v": [1.0, 2.0, 3.0]})
    from spark_rapids_tpu.io.parquet import write_parquet
    write_parquet(t, str(tmp_path / "y"), partition_by=["year"])
    back = session.read_parquet(str(tmp_path / "y"))
    assert dict(back.schema)["year"] == T.LONG
    assert sorted(back.collect()) == [(1.0, 2023), (2.0, 2023), (3.0, 2024)]


def test_coalescing_respects_filters(tmp_path, session):
    paths = _write_sample_parquet(tmp_path, num_files=2)
    a = session.read_parquet(*paths, filters=[("b", "=", True)],
                             reader_type="COALESCING").count()
    b = session.read_parquet(*paths, filters=[("b", "=", True)],
                             reader_type="PERFILE").count()
    assert a == b


def test_multifile_schema_divergence_raises(tmp_path, session):
    """File 2's inferred double column must not silently truncate to the
    scan schema's int — safe cast raises instead."""
    (tmp_path / "a.json").write_text('{"x": 1}\n{"x": 2}\n')
    (tmp_path / "b.json").write_text('{"x": 1.5}\n')
    with pytest.raises(Exception):
        session.read_json(str(tmp_path / "a.json"), str(tmp_path / "b.json"),
                          reader_type="PERFILE").collect()


def test_parquet_types_roundtrip(tmp_path, session):
    t = HostTable.from_pydict({
        "dt": [datetime.date(2024, 1, 1), datetime.date(1969, 12, 31), None],
        "ts": [datetime.datetime(2024, 6, 1, 12, 30, 45, 123456),
               datetime.datetime(1969, 12, 31, 23, 59, 59), None],
        "x": [1, 2, 3],
    }, dtypes={"dt": T.DATE, "ts": T.TIMESTAMP, "x": T.INT})
    from spark_rapids_tpu.io.parquet import write_parquet
    write_parquet(t, str(tmp_path / "t"))
    back = session.read_parquet(str(tmp_path / "t"))
    schema = dict(back.schema)
    assert schema["dt"] == T.DATE and schema["ts"] == T.TIMESTAMP
    rows = back.collect()
    assert rows[0][0] == datetime.date(2024, 1, 1)
    assert rows[0][1] == datetime.datetime(2024, 6, 1, 12, 30, 45, 123456)
    assert rows[2][0] is None and rows[2][1] is None


@pytest.mark.parametrize("mode", ["PERFILE", "COALESCING", "MULTITHREADED"])
def test_orc_read_modes(tmp_path, session, cpu_session, mode):
    from spark_rapids_tpu.io.orc import write_orc
    paths = []
    for k in range(2):
        paths.extend(write_orc(_sample_table(300, seed=k), str(tmp_path / f"o{k}")))
    assert_tpu_and_cpu_are_equal(
        lambda s: s.read_orc(*paths, reader_type=mode), session, cpu_session)


def test_csv_roundtrip(tmp_path, session, cpu_session):
    from spark_rapids_tpu.io.csv import write_csv
    t = table_gen({"i": T.INT, "d": T.DOUBLE, "s": T.STRING}, 500, seed=3)
    paths = write_csv(t, str(tmp_path / "c"))
    schema = [("i", T.INT), ("d", T.DOUBLE), ("s", T.STRING)]
    assert_tpu_and_cpu_are_equal(
        lambda s: s.read_csv(*paths, schema=schema),
        session, cpu_session, approximate_float=True)


def test_csv_headerless_with_schema(tmp_path, session):
    p = tmp_path / "raw.csv"
    p.write_text("1,a\n2,b\n3,\n")
    schema = [("n", T.INT), ("s", T.STRING)]
    rows = session.read_csv(str(p), schema=schema, header=False).collect()
    assert rows[0] == (1, "a") and rows[2][0] == 3


def test_json_roundtrip(tmp_path, session):
    from spark_rapids_tpu.io.json import write_json
    t = HostTable.from_pydict({"a": [1, 2, None], "s": ["x", None, "z"]})
    write_json(t, str(tmp_path / "j"))
    back = session.read_json(str(tmp_path / "j")).collect()
    assert back[0] == (1, "x")
    assert back[1][1] is None
    assert back[2][0] is None and back[2][1] == "z"


def test_glob_and_dir_expansion(tmp_path, session):
    _write_sample_parquet(tmp_path, num_files=3, rows=100)
    via_glob = session.read_parquet(str(tmp_path / "f*" / "*.parquet")).count()
    assert via_glob == 300


def test_multithreaded_read_order_stable(tmp_path, session):
    """MULTITHREADED must preserve file order (reference keeps ordered
    results despite parallel decode)."""
    from spark_rapids_tpu.io.parquet import write_parquet
    paths = []
    for k in range(6):
        t = HostTable.from_pydict({"v": [k * 10 + i for i in range(10)]})
        paths.extend(write_parquet(t, str(tmp_path / f"ord{k}")))
    rows = session.read_parquet(*paths, reader_type="MULTITHREADED").collect()
    vals = [v for (v,) in rows]
    assert vals == sorted(vals)
