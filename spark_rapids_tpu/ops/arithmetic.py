"""Arithmetic expressions with Spark (non-ANSI, Java) semantics.

Reference rules: Add Subtract Multiply Divide IntegralDivide Remainder Pmod
UnaryMinus UnaryPositive Abs (GpuOverrides + shim registry, SURVEY.md
Appendix A). Spark-exact corners implemented here:

* integer overflow wraps (two's complement, like Java);
* Divide coerces to double and returns NULL on a zero divisor (Spark
  deviates from IEEE here);
* Remainder/Pmod use Java % (sign of the dividend) and NULL on zero;
* IntegralDivide truncates toward zero and yields LongType.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import AnsiViolation, ColumnarProcessingError
from spark_rapids_tpu.ops.common import (
    BinaryExpression,
    UnaryExpression,
    coerce_numeric_pair,
    null_and,
)
from spark_rapids_tpu.ops.expr import DevVal


class BinaryArithmetic(BinaryExpression):
    #: decimal-specific expression this op rewrites to when either
    #: operand is a DecimalType (DecimalArithmeticOverrides analog)
    decimal_impl: type = None

    @property
    def data_type(self):
        return self.left.data_type

    def resolve(self, bound):
        from spark_rapids_tpu.ops.cast import Cast
        lt, rt = bound[0].data_type, bound[1].data_type
        if isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType):
            # Spark coercion: decimal mixed with float/double promotes the
            # DECIMAL side to double and runs float arithmetic
            if isinstance(lt, (T.FloatType, T.DoubleType)) or \
                    isinstance(rt, (T.FloatType, T.DoubleType)):
                bound = [Cast(e, T.DOUBLE) if e.data_type != T.DOUBLE
                         else e for e in bound]
                return type(self)(bound[0], bound[1])
            from spark_rapids_tpu.ops import decimal as dec
            impl = self.decimal_impl
            if impl is None:
                if isinstance(self, Pmod):
                    impl = dec.DecimalPmod
                elif isinstance(self, Remainder):
                    impl = dec.DecimalRemainder
                else:
                    raise ColumnarProcessingError(
                        f"{type(self).__name__} does not support decimal "
                        "operands")
            out = []
            for e, dt in zip(bound, (lt, rt)):
                d = dec.decimal_for(dt)
                if d is None:
                    raise ColumnarProcessingError(
                        f"cannot mix {dt.simple_string()} with decimal "
                        "arithmetic (cast explicitly)")
                out.append(e if d == dt else Cast(e, d))
            return impl(out[0], out[1])
        left, right, _ = coerce_numeric_pair(*bound)
        return type(self)(left, right)

    def _cpu_op(self, ld, rd):
        raise NotImplementedError

    def _dev_op(self, ld, rd):
        raise NotImplementedError

    #: ANSI overflow check on integral operands ("+"/"-"/"*" labels)
    _ansi_symbol = None

    def eval_cpu(self, table: HostTable) -> HostColumn:
        from spark_rapids_tpu.dispatch import ANSI_MODE
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        with np.errstate(over="ignore", invalid="ignore", divide="ignore"):
            data = self._cpu_op(l.data, r.data)
        validity = l.validity & r.validity
        if (ANSI_MODE.get() and self._ansi_symbol
                and isinstance(self.data_type, T.IntegralType)):
            wide = self._cpu_op(l.data.astype(object), r.data.astype(object))
            info = np.iinfo(self.data_type.np_dtype)
            bad = validity & np.fromiter(
                (not (info.min <= w <= info.max) for w in wide),
                dtype=np.bool_, count=len(wide))
            if bad.any():
                raise AnsiViolation(
                    f"integer overflow in {self._ansi_symbol} "
                    "(spark.sql.ansi.enabled)")
        zero = np.zeros((), dtype=data.dtype).item()
        return HostColumn(self.data_type, np.where(validity, data, zero).astype(data.dtype), validity)

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        validity = null_and(lval.validity, rval.validity)
        data = self._dev_op(lval.data, rval.data)
        if (ctx.ansi and self._ansi_symbol
                and isinstance(self.data_type, T.IntegralType)):
            bad = self._dev_overflow(lval.data, rval.data, data) & validity
            ctx.ansi_check(f"integer overflow in {self._ansi_symbol}", bad)
        return DevVal(jnp.where(validity, data, jnp.zeros_like(data)), validity)

    def _dev_overflow(self, ld, rd, res):
        raise NotImplementedError


class Add(BinaryArithmetic):
    _ansi_symbol = "+"

    def _cpu_op(self, ld, rd):
        return ld + rd

    def _dev_op(self, ld, rd):
        return ld + rd

    def _dev_overflow(self, ld, rd, res):
        # sign trick: overflow iff operands share a sign and the result
        # flips it (exact for two's-complement wrap)
        return ((ld >= 0) == (rd >= 0)) & ((res >= 0) != (ld >= 0))


class Subtract(BinaryArithmetic):
    _ansi_symbol = "-"

    def _cpu_op(self, ld, rd):
        return ld - rd

    def _dev_op(self, ld, rd):
        return ld - rd

    def _dev_overflow(self, ld, rd, res):
        return ((ld >= 0) != (rd >= 0)) & ((res >= 0) != (ld >= 0))


class Multiply(BinaryArithmetic):
    _ansi_symbol = "*"

    def _cpu_op(self, ld, rd):
        return ld * rd

    def _dev_op(self, ld, rd):
        return ld * rd

    def _dev_overflow(self, ld, rd, res):
        # divide-back check (integer division is exact on device)
        dtmin = jnp.asarray(np.iinfo(np.dtype(res.dtype)).min, res.dtype)
        safe_r = jnp.where(rd == 0, 1, rd)
        divback_bad = (rd != 0) & (res // safe_r != ld)
        min_neg = (ld == dtmin) & (rd == -1) | (rd == dtmin) & (ld == -1)
        return divback_bad | min_neg


class Divide(BinaryArithmetic):
    """Double division; NULL on zero divisor (Spark non-ANSI)."""

    @property
    def data_type(self):
        return T.DOUBLE

    def resolve(self, bound):
        from spark_rapids_tpu.ops.cast import Cast
        if any(isinstance(e.data_type, T.DecimalType) for e in bound):
            # decimal mixed with float/double promotes to double (Spark
            # coercion), matching BinaryArithmetic.resolve
            if any(isinstance(e.data_type, (T.FloatType, T.DoubleType))
                   for e in bound):
                bound = [Cast(e, T.DOUBLE) if e.data_type != T.DOUBLE
                         else e for e in bound]
                return Divide(bound[0], bound[1])
            from spark_rapids_tpu.ops import decimal as dec
            out = []
            for e in bound:
                d = dec.decimal_for(e.data_type)
                if d is None:
                    raise ColumnarProcessingError(
                        f"cannot mix {e.data_type.simple_string()} with "
                        "decimal division (cast explicitly)")
                out.append(e if d == e.data_type else Cast(e, d))
            return dec.DecimalDivide(out[0], out[1])
        left, right = bound
        if left.data_type != T.DOUBLE:
            left = Cast(left, T.DOUBLE)
        if right.data_type != T.DOUBLE:
            right = Cast(right, T.DOUBLE)
        return Divide(left, right)

    def eval_cpu(self, table):
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        _ansi_div_zero_cpu(l, r)
        validity = l.validity & r.validity & (r.data != 0.0)
        with np.errstate(divide="ignore", invalid="ignore"):
            data = np.where(validity, l.data / np.where(r.data != 0.0, r.data, 1.0), 0.0)
        return HostColumn(T.DOUBLE, data, validity)

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        _ansi_div_zero_dev(ctx, lval, rval)
        validity = lval.validity & rval.validity & (rval.data != 0.0)
        safe = jnp.where(rval.data != 0.0, rval.data, 1.0)
        return DevVal(jnp.where(validity, lval.data / safe, 0.0), validity)


def _ansi_div_zero_cpu(l, r):
    from spark_rapids_tpu.dispatch import ANSI_MODE
    if ANSI_MODE.get():
        bad = l.validity & r.validity & (r.data == 0)
        if bad.any():
            raise AnsiViolation("divide by zero (spark.sql.ansi.enabled)")


def _ansi_div_zero_dev(ctx, lval, rval):
    if ctx.ansi:
        ctx.ansi_check("divide by zero",
                       lval.validity & rval.validity & (rval.data == 0))


def _trunc_div_int(a, b, xp):
    """C/Java truncation division on integers given a floor-dividing xp."""
    q = xp.floor_divide(a, xp.where(b != 0, b, 1))
    r = a - q * xp.where(b != 0, b, 1)
    adjust = (r != 0) & ((a < 0) != (b < 0))
    return q + adjust.astype(q.dtype)


class IntegralDivide(BinaryArithmetic):
    """`div` operator: operands cast to long, truncating division, NULL on
    zero divisor."""

    @property
    def data_type(self):
        return T.LONG

    def resolve(self, bound):
        from spark_rapids_tpu.ops.cast import Cast
        if any(isinstance(e.data_type, T.DecimalType) for e in bound):
            # Spark `div` over decimals: exact decimal division truncated
            # to long (casting operands to LONG first would destroy the
            # fractional part — 7.5 div 0.5 is 15, not 7 div 0)
            from spark_rapids_tpu.ops import decimal as dec
            out = []
            for e in bound:
                d = dec.decimal_for(e.data_type)
                if d is None:
                    e = Cast(e, T.LONG)
                    d = dec.decimal_for(T.LONG)
                out.append(e if e.data_type == d else Cast(e, d))
            return Cast(dec.DecimalDivide(out[0], out[1]), T.LONG)
        left, right = bound
        if left.data_type != T.LONG:
            left = Cast(left, T.LONG)
        if right.data_type != T.LONG:
            right = Cast(right, T.LONG)
        return IntegralDivide(left, right)

    def eval_cpu(self, table):
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        _ansi_div_zero_cpu(l, r)
        validity = l.validity & r.validity & (r.data != 0)
        with np.errstate(over="ignore"):
            data = _trunc_div_int(l.data, r.data, np)
        return HostColumn(T.LONG, np.where(validity, data, 0), validity)

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        _ansi_div_zero_dev(ctx, lval, rval)
        validity = lval.validity & rval.validity & (rval.data != 0)
        data = _trunc_div_int(lval.data, rval.data, jnp)
        return DevVal(jnp.where(validity, data, 0), validity)


def _java_mod(a, b, xp):
    """Java % — sign of the dividend. fmod matches for both ints and floats."""
    if xp is np:
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.fmod(a, np.where(b != 0, b, 1))
    return jnp.fmod(a, jnp.where(b != 0, b, 1))


class Remainder(BinaryArithmetic):
    """% with Java semantics (sign of dividend), NULL on zero divisor."""

    def eval_cpu(self, table):
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        _ansi_div_zero_cpu(l, r)
        validity = l.validity & r.validity & (r.data != 0)
        data = _java_mod(l.data, r.data, np)
        zero = np.zeros((), dtype=l.data.dtype).item()
        return HostColumn(self.data_type, np.where(validity, data, zero).astype(l.data.dtype), validity)

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        _ansi_div_zero_dev(ctx, lval, rval)
        validity = lval.validity & rval.validity & (rval.data != 0)
        data = _java_mod(lval.data, rval.data, jnp)
        return DevVal(jnp.where(validity, data, jnp.zeros_like(data)), validity)


class Pmod(BinaryArithmetic):
    """Positive modulus: ((a % b) + b) % b with Java %, NULL on zero."""

    def eval_cpu(self, table):
        l = self.left.eval_cpu(table)
        r = self.right.eval_cpu(table)
        validity = l.validity & r.validity & (r.data != 0)
        safe = np.where(r.data != 0, r.data, 1)
        with np.errstate(over="ignore", invalid="ignore", divide="ignore"):
            m = np.fmod(l.data, safe)
            data = np.fmod(m + safe, safe)
        zero = np.zeros((), dtype=l.data.dtype).item()
        return HostColumn(self.data_type, np.where(validity, data, zero).astype(l.data.dtype), validity)

    def eval_dev(self, ctx, child_vals, prep):
        lval, rval = child_vals
        validity = lval.validity & rval.validity & (rval.data != 0)
        safe = jnp.where(rval.data != 0, rval.data, jnp.ones_like(rval.data))
        m = jnp.fmod(lval.data, safe)
        data = jnp.fmod(m + safe, safe)
        return DevVal(jnp.where(validity, data, jnp.zeros_like(data)), validity)


class UnaryMinus(UnaryExpression):
    @property
    def data_type(self):
        return self.child.data_type

    def eval_cpu(self, table):
        from spark_rapids_tpu.dispatch import ANSI_MODE
        c = self.child.eval_cpu(table)
        if ANSI_MODE.get() and isinstance(self.data_type, T.IntegralType):
            info = np.iinfo(c.data.dtype)
            if (c.validity & (c.data == info.min)).any():
                raise AnsiViolation(
                    "integer overflow in negate (spark.sql.ansi.enabled)")
        with np.errstate(over="ignore"):
            data = -c.data
        zero = np.zeros((), dtype=c.data.dtype).item()
        return HostColumn(self.data_type, np.where(c.validity, data, zero).astype(c.data.dtype), c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        if ctx.ansi and isinstance(self.data_type, T.IntegralType):
            info = np.iinfo(np.dtype(c.data.dtype))
            ctx.ansi_check("integer overflow in negate",
                           c.validity & (c.data == info.min))
        return DevVal(jnp.where(c.validity, -c.data, jnp.zeros_like(c.data)), c.validity)


class UnaryPositive(UnaryExpression):
    @property
    def data_type(self):
        return self.child.data_type

    def eval_cpu(self, table):
        return self.child.eval_cpu(table)

    def eval_dev(self, ctx, child_vals, prep):
        return child_vals[0]


class Abs(UnaryExpression):
    """Java Math.abs: wraps at integer MIN_VALUE (non-ANSI)."""

    @property
    def data_type(self):
        return self.child.data_type

    def eval_cpu(self, table):
        from spark_rapids_tpu.dispatch import ANSI_MODE
        c = self.child.eval_cpu(table)
        if ANSI_MODE.get() and np.issubdtype(c.data.dtype, np.integer):
            info = np.iinfo(c.data.dtype)
            if (c.validity & (c.data == info.min)).any():
                raise AnsiViolation(
                    "integer overflow in abs (spark.sql.ansi.enabled)")
        with np.errstate(over="ignore"):
            data = np.abs(c.data)
        zero = np.zeros((), dtype=c.data.dtype).item()
        return HostColumn(self.data_type, np.where(c.validity, data, zero).astype(c.data.dtype), c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep):
        (c,) = child_vals
        if ctx.ansi and jnp.issubdtype(c.data.dtype, jnp.integer):
            info = np.iinfo(np.dtype(c.data.dtype))
            ctx.ansi_check("integer overflow in abs",
                           c.validity & (c.data == info.min))
        return DevVal(jnp.where(c.validity, jnp.abs(c.data), jnp.zeros_like(c.data)), c.validity)


# decimal rewrites (DecimalArithmeticOverrides analog); Divide keeps its
# own resolve, so its decimal branch is spliced there
from spark_rapids_tpu.ops import decimal as _dec  # noqa: E402

Add.decimal_impl = _dec.DecimalAdd
Subtract.decimal_impl = _dec.DecimalSubtract
Multiply.decimal_impl = _dec.DecimalMultiply
