"""ORC scan + writer (reference: GpuOrcScan.scala, GpuOrcFileFormat.scala —
SURVEY.md §2.4; same three reader modes as parquet, stripe-granular
coalescing)."""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import pyarrow as pa
import pyarrow.orc as po

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import str_conf
from spark_rapids_tpu.io.arrow_convert import arrow_schema_to_spark, decode_to_schema
from spark_rapids_tpu.io.common import FileScanNode
from spark_rapids_tpu.io.writer import write_partitioned
from spark_rapids_tpu.plan.nodes import Schema

ORC_READER_TYPE = str_conf(
    "spark.rapids.sql.format.orc.reader.type", "AUTO",
    "PERFILE, COALESCING, MULTITHREADED or AUTO (reference: GpuOrcScan "
    "reader modes).")


class OrcScanNode(FileScanNode):
    format_name = "orc"

    def _conf_reader_type(self) -> str:
        return self.conf.get_entry(ORC_READER_TYPE)

    def file_schema(self, path: str) -> Schema:
        return arrow_schema_to_spark(po.ORCFile(path).schema)

    def _file_columns(self):
        if self.columns is None:
            return None
        data_names = {n for n, _ in self.data_schema}
        return [c for c in self.columns if c in data_names]

    def read_file(self, path: str) -> HostTable:
        cols = self._file_columns()
        if cols is not None and not cols:
            from spark_rapids_tpu.io.common import row_carrier_table
            return row_carrier_table(po.ORCFile(path).nrows)
        t = po.ORCFile(path).read(columns=cols)
        return decode_to_schema(t, self.data_schema)

    def _coalescing_chunks(self, paths=None) -> Iterator[HostTable]:
        """Stripe-granular chunks (MultiFileOrcPartitionReader analog)."""
        for path in (self.paths if paths is None else paths):
            f = po.ORCFile(path)
            for s in range(f.nstripes):
                batch = f.read_stripe(s, columns=self._file_columns())
                yield self._with_partition_columns(
                    decode_to_schema(pa.Table.from_batches([batch]),
                                     self.data_schema),
                    path)


def write_orc(table: HostTable, path: str,
              partition_by: Optional[Sequence[str]] = None,
              compression: str = "zstd", committer=None) -> List[str]:
    def _write_one(tbl: HostTable, file_path: str):
        from spark_rapids_tpu.io.arrow_convert import host_table_to_arrow
        po.write_table(host_table_to_arrow(tbl), file_path,
                       compression=compression)
    return write_partitioned(table, path, _write_one, "orc", partition_by,
                             committer=committer)
