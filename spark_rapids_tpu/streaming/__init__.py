"""Streaming ingestion + incrementally-maintained materialized views.

Two halves over the query service:

* **Micro-batch streams** (query.StreamingQuery): a source (rate /
  file-watch / Delta CDF tail) drives micro-batches through
  ``QueryService.submit`` as a recurring tenant; offsets are
  write-ahead-logged (offsets.OffsetLog) and the sink commits through
  the Delta transaction protocol with a per-stream ``txn`` watermark
  (sink.DeltaStreamSink) — together: exactly-once across kills.
* **Materialized views** (mv.MaterializedViewRegistry): plans registered
  as views are kept current by delta recomputation off the table-scoped
  invalidation epochs, with a full-recompute fallback outside the
  incremental whitelist.

Observability: the ``streaming`` metric scope (metrics.py) feeds the six
per-record schema-v11 fields (microBatches … sinkReplays, mvEpoch).
"""

from spark_rapids_tpu.streaming.metrics import STREAM_METRICS
from spark_rapids_tpu.streaming.mv import (
    MaterializedView,
    MaterializedViewRegistry,
)
from spark_rapids_tpu.streaming.offsets import OffsetLog
from spark_rapids_tpu.streaming.query import StreamingQuery
from spark_rapids_tpu.streaming.sink import DeltaStreamSink
from spark_rapids_tpu.streaming.source import (
    DeltaCDFSource,
    FileWatchSource,
    RateSource,
    StreamingSource,
)

__all__ = [
    "DeltaCDFSource",
    "DeltaStreamSink",
    "FileWatchSource",
    "MaterializedView",
    "MaterializedViewRegistry",
    "OffsetLog",
    "RateSource",
    "STREAM_METRICS",
    "StreamingQuery",
    "StreamingSource",
]
