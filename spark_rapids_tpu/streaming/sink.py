"""Exactly-once Delta streaming sink.

Every micro-batch append commits a ``SetTransaction(stream_id, batch_id)``
action ATOMICALLY with its data files (through the same optimistic
transaction protocol every Delta write uses). On replay — a batch whose
sink commit landed but whose stream died before the commit marker was
written — ``DeltaLog.last_txn_version`` already carries the batch id, so
the sink skips the append instead of duplicating rows. That watermark,
plus the OffsetLog's re-run-the-same-range rule, is the whole
exactly-once story: no distributed coordination, just one idempotence
check in front of one atomic commit.
"""

from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.runtime.faults import fault_point
from spark_rapids_tpu.streaming.metrics import STREAM_METRICS

__all__ = ["DeltaStreamSink"]


class DeltaStreamSink:
    """Appends each micro-batch to a Delta table with txn dedupe."""

    kind = "delta"

    def __init__(self, table_path: str, stream_id: str):
        import os
        self.table_path = os.path.abspath(table_path)
        self.stream_id = stream_id

    def last_committed_batch(self) -> Optional[int]:
        from spark_rapids_tpu.delta.log import DeltaLog
        log = DeltaLog(self.table_path)
        if not log.exists():
            return None
        return log.last_txn_version(self.stream_id)

    def commit_batch(self, session, batch_id: int, table) -> str:
        """Commit one micro-batch's result table. Returns ``"committed"``
        or ``"replayed"`` (watermark already past this batch)."""
        from spark_rapids_tpu.delta.log import DeltaLog, SetTransaction
        from spark_rapids_tpu.delta.table import write_delta
        from spark_rapids_tpu.plan import nodes as P

        last = self.last_committed_batch()
        if last is not None and last >= batch_id:
            STREAM_METRICS.add("sinkReplays", 1)
            session.stage_stream_delta("sinkReplays")
            return "replayed"
        fault_point("stream.sink.commit", op=self.stream_id)
        mode = "append" if DeltaLog(self.table_path).exists() else "error"
        session.stage_stream_delta("sinkCommits")
        write_delta(P.LocalScan([table]), session, self.table_path,
                    mode=mode,
                    txn_action=SetTransaction(self.stream_id, batch_id))
        STREAM_METRICS.add("sinkCommits", 1)
        return "committed"

    def describe(self) -> dict:
        return {"kind": self.kind, "tablePath": self.table_path}
