"""Shim provider interface: every JAX API the engine uses that has moved
(or may move) between JAX releases, in one place.

Reference: the SparkShims trait (sql-plugin-api) — the reference funnels
every version-variant Spark API through one interface so the rest of the
plugin compiles version-agnostic. Here the variant APIs are JAX's; the
engine calls ``shims.get_shim().<api>()`` instead of importing from a
location that only exists in some JAX versions.
"""

from __future__ import annotations

from typing import Tuple


class BaseShim:
    """Canonical implementations against the CURRENT JAX API surface.
    Version providers subclass and override only what their JAX spells
    differently (the shimplify model: base file + per-shim deltas)."""

    #: half-open [MIN_VERSION, MAX_VERSION) range this provider serves
    MIN_VERSION: Tuple[int, int, int] = (0, 0, 0)
    MAX_VERSION: Tuple[int, int, int] = (99, 0, 0)

    @property
    def name(self) -> str:
        return type(self).__name__

    # -- SPMD ---------------------------------------------------------------
    def shard_map(self):
        """jax.shard_map (top-level since 0.6; jax.experimental before)."""
        import jax
        return jax.shard_map

    # -- pytrees ------------------------------------------------------------
    def tree_map(self, f, tree, *rest):
        import jax
        return jax.tree.map(f, tree, *rest)

    def tree_leaves(self, tree):
        import jax
        return jax.tree.leaves(tree)

    def register_pytree_node(self, cls, flatten, unflatten):
        import jax
        jax.tree_util.register_pytree_node(cls, flatten, unflatten)

    # -- devices / platform -------------------------------------------------
    def default_backend(self) -> str:
        import jax
        return jax.default_backend()

    def local_device_count(self) -> int:
        import jax
        return jax.local_device_count()

    def make_mesh(self, axis_shapes, axis_names):
        """Mesh construction (jax.make_mesh since 0.4.35; explicit Mesh
        over mesh_utils before)."""
        import jax
        return jax.make_mesh(axis_shapes, axis_names)

    # -- compilation --------------------------------------------------------
    def jit(self, fn, **kw):
        import jax
        return jax.jit(fn, **kw)
