"""Window exec tests vs the CPU oracle (reference: window_function_test.py
matrix — SURVEY.md §4)."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.ops.window import Window
from tests.asserts import assert_runs_on_tpu, assert_tpu_and_cpu_are_equal
from tests.data_gen import DoubleGen, IntGen, LongGen, StringGen, gen_table


def _t(n=400, seed=0):
    return gen_table({"k": IntGen(min_val=0, max_val=8, null_prob=0.05),
                      "o": LongGen(min_val=-100, max_val=100),
                      "v": LongGen(),
                      "d": DoubleGen(),
                      "s": StringGen(cardinality=12)}, n, seed=seed)


W_KO = lambda: Window.partition_by("k").order_by("o")  # noqa: E731


@pytest.mark.parametrize("fn", [
    lambda: F.row_number(), lambda: F.rank(), lambda: F.dense_rank(),
], ids=["row_number", "rank", "dense_rank"])
def test_ranking_functions(session, cpu_session, fn):
    host = _t()
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            r=fn().over(W_KO())), session, cpu_session)


def test_rank_with_ties(session, cpu_session):
    host = HostTable.from_pydict({
        "k": [1, 1, 1, 1, 2, 2], "o": [5, 5, 7, 9, 1, 1]})
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            rn=F.row_number().over(W_KO()),
            rk=F.rank().over(W_KO()),
            dr=F.dense_rank().over(W_KO())), session, cpu_session)


@pytest.mark.parametrize("off,default", [(1, None), (2, None), (1, -99)],
                         ids=["lag1", "lag2", "lag1_default"])
def test_lag_lead(session, cpu_session, off, default):
    host = _t(300, seed=2)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            lg=F.lag("v", off, default).over(W_KO()),
            ld=F.lead("v", off, default).over(W_KO())),
        session, cpu_session)


def test_lag_string(session, cpu_session):
    host = _t(200, seed=3)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            p=F.lag("s").over(W_KO())), session, cpu_session)


@pytest.mark.parametrize("make_agg", [
    lambda: F.sum("v"), lambda: F.count("v"), lambda: F.min("v"),
    lambda: F.max("v"), lambda: F.avg("d"),
], ids=["sum", "count", "min", "max", "avg"])
def test_whole_partition_aggs(session, cpu_session, make_agg):
    host = _t(350, seed=4)
    w = Window.partition_by("k")  # no order -> whole partition frame
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            a=make_agg().over(w)), session, cpu_session,
        approximate_float=True)


@pytest.mark.parametrize("make_agg", [
    lambda: F.sum("v"), lambda: F.count("v"), lambda: F.min("v"),
    lambda: F.max("v"), lambda: F.avg("d"),
], ids=["sum", "count", "min", "max", "avg"])
def test_running_aggs_default_range_frame(session, cpu_session, make_agg):
    """ORDER BY default frame = RANGE UNBOUNDED..CURRENT (peers included)."""
    host = _t(300, seed=5)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            a=make_agg().over(W_KO())), session, cpu_session,
        approximate_float=True)


def test_running_rows_frame(session, cpu_session):
    host = _t(300, seed=6)
    w = W_KO().rows_between(None, 0)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            rsum=F.sum("v").over(w), rmin=F.min("v").over(w)),
        session, cpu_session)


@pytest.mark.parametrize("lo,hi", [(-2, 2), (-3, 0), (0, 3), (None, 1)],
                         ids=["pm2", "m3_0", "0_p3", "unb_p1"])
def test_bounded_rows_frames(session, cpu_session, lo, hi):
    host = _t(250, seed=7)
    w = W_KO().rows_between(lo, hi)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            bs=F.sum("v").over(w), bc=F.count("v").over(w),
            ba=F.avg("d").over(w)),
        session, cpu_session, approximate_float=True)


def test_window_runs_on_tpu(session):
    host = _t(100)
    assert_runs_on_tpu(
        lambda s: s.create_dataframe(host).with_windows(
            rn=F.row_number().over(W_KO()),
            sm=F.sum("v").over(W_KO())), session)


def test_bounded_minmax_runs_on_device(session, cpu_session):
    """Bounded rows min/max frames run on device via the sparse-table RMQ
    (GpuBatchedBoundedWindowExec analog; was an r1 fallback carve-out)."""
    from spark_rapids_tpu.overrides import wrap_plan
    host = _t(80)
    df = session.create_dataframe(host).with_windows(
        bm=F.min("v").over(W_KO().rows_between(-2, 2)),
        bx=F.max("v").over(W_KO().rows_between(-3, 1)),
        lead_min=F.min("v").over(W_KO().rows_between(1, 4)),
        tail_max=F.max("v").over(W_KO().rows_between(-1, None)),
        head_min=F.min("v").over(W_KO().rows_between(None, 2)),
    )
    meta = wrap_plan(df.plan, session.conf)
    assert meta.can_run_on_tpu, meta.explain(only_fallback=False)

    def build(s):
        return s.create_dataframe(host).with_windows(
            bm=F.min("v").over(W_KO().rows_between(-2, 2)),
            bx=F.max("v").over(W_KO().rows_between(-3, 1)),
            lead_min=F.min("v").over(W_KO().rows_between(1, 4)),
            tail_max=F.max("v").over(W_KO().rows_between(-1, None)),
            head_min=F.min("v").over(W_KO().rows_between(None, 2)),
        )
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_wide_float_bounded_sum_runs_on_device(session, cpu_session):
    """Float both-bounded frames wider than the exact unroll window use
    segmented-prefix differences (was an r1 fallback carve-out)."""
    # corner-free doubles: +/-1e30 corners make prefix-difference sums
    # diverge from direct per-frame sums by design (variableFloatAgg class)
    host = gen_table({"k": IntGen(min_val=0, max_val=8),
                      "o": LongGen(min_val=-100, max_val=100),
                      "d": DoubleGen(corner_prob=0.0)}, 2000, seed=4)
    def build(s):
        return s.create_dataframe(host).with_windows(
            ws=F.sum("d").over(W_KO().rows_between(-600, 600)),
            wa=F.avg("d").over(W_KO().rows_between(-700, 10)))
    from spark_rapids_tpu.overrides import wrap_plan
    meta = wrap_plan(build(session).plan, session.conf)
    assert meta.can_run_on_tpu, meta.explain(only_fallback=False)
    assert_tpu_and_cpu_are_equal(build, session, cpu_session,
                                 approximate_float=True)


def test_mixed_specs_stay_aligned(session, cpu_session):
    """Two window exprs with DIFFERENT partition/order specs in one node."""
    host = _t(200, seed=9)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            by_k=F.sum("v").over(Window.partition_by("k")),
            by_s=F.count("v").over(Window.partition_by("s"))),
        session, cpu_session)


def test_window_no_partition(session, cpu_session):
    """Global window (single partition)."""
    host = _t(150, seed=10)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).with_windows(
            rn=F.row_number().over(Window.order_by("o")),
            tot=F.sum("v").over(Window.partition_by())),
        session, cpu_session)


def test_window_then_filter_pipeline(session, cpu_session):
    """Classic top-N per group: window + filter + project."""
    from spark_rapids_tpu.ops.expr import col
    host = _t(400, seed=11)

    def build(s):
        return (s.create_dataframe(host)
                .with_windows(rn=F.row_number().over(W_KO()))
                .filter(col("rn") <= 3)
                .select("k", "o", "rn"))
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_percent_rank_and_nth_value(session, cpu_session):
    host = _t(300)
    def build(s):
        return s.create_dataframe(host).with_windows(
            pr=F.percent_rank().over(W_KO()),
            nv=F.nth_value("v", 2).over(W_KO()),
            nv5=F.nth_value("v", 5).over(W_KO()))
    from spark_rapids_tpu.overrides import wrap_plan
    meta = wrap_plan(build(session).plan, session.conf)
    assert meta.can_run_on_tpu, meta.explain(only_fallback=False)
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_empty_edge_frames_are_null(session, cpu_session):
    """Frames that are empty at partition edges must yield NULL, not a
    clipped 1-row frame (code-review r2: clip-before-emptiness bug)."""
    host = _t(120)
    def build(s):
        return s.create_dataframe(host).with_windows(
            trail=F.min("v").over(W_KO().rows_between(None, -2)),
            ahead=F.sum("v").over(W_KO().rows_between(5, 7)),
            tcnt=F.count("v").over(W_KO().rows_between(None, -2)))
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


# -- batched bounded-frame streaming (GpuBatchedBoundedWindowExec analog) ----

@pytest.mark.parametrize("lo,hi", [(-2, 2), (-3, 0), (0, 3), (-1, 1)],
                         ids=["pm2", "m3_0", "0_p3", "pm1"])
def test_bounded_streaming_multibatch(session, cpu_session, lo, hi):
    """Finite rows frames over a MULTI-batch input stream with carried
    context (no whole-input device concat)."""
    host = _t(1200, seed=11)
    w = W_KO().rows_between(lo, hi)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host, num_batches=5).with_windows(
            bs=F.sum("v").over(w), bc=F.count("v").over(w),
            bm=F.max("o").over(w), ba=F.avg("d").over(w)),
        session, cpu_session, approximate_float=True)


def test_bounded_streaming_takes_streaming_path(session):
    """The exec reports per-range streaming batches — the whole-input
    concat path must NOT be taken for finite-rows frames."""
    host = _t(900, seed=3)
    w = W_KO().rows_between(-2, 1)
    df = session.create_dataframe(host, num_batches=4).with_windows(
        bs=F.sum("v").over(w))
    df.collect_table()
    m = session.last_metrics()
    assert "boundedWindowBatches" in m, m


def test_bounded_streaming_partitionless(session, cpu_session):
    """No partition_by: frames cross the whole sorted stream, so carried
    context must span range boundaries correctly."""
    host = _t(800, seed=13)
    w = Window.order_by("o").rows_between(-3, 2)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host, num_batches=4).with_windows(
            bs=F.sum("v").over(w), bc=F.count("v").over(w)),
        session, cpu_session, approximate_float=True)


def test_bounded_streaming_with_injected_oom(cpu_session):
    """Streaming bounded windows survive injected device OOM (retry
    framework) without materializing the whole input."""
    from spark_rapids_tpu.session import TpuSession
    host = _t(600, seed=17)
    w = W_KO().rows_between(-2, 2)
    s = TpuSession({"spark.rapids.sql.test.injectRetryOOM": "retry:2"})
    got = sorted(
        s.create_dataframe(host, num_batches=3).with_windows(
            bs=F.sum("v").over(w)).collect(), key=repr)
    want = sorted(
        cpu_session.create_dataframe(host).with_windows(
            bs=F.sum("v").over(w)).collect(), key=repr)
    assert got == want


# -- cached double-pass (GpuCachedDoublePassWindowExec analog) ---------------

def test_two_pass_whole_partition_aggs_multibatch(session, cpu_session):
    """UNBOUNDED..UNBOUNDED partitioned agg windows over a multi-batch
    input take the double-pass (aggregate + join-back) path. The avg
    column uses BOUNDED doubles: corner-value doubles (±1e30) make float
    sums order-dependent, which is inherent float variance (Spark's
    variableFloatAgg caveat), not a path bug."""
    host = gen_table(
        {"k": IntGen(min_val=0, max_val=8, null_prob=0.05),
         "o": LongGen(min_val=-100, max_val=100),
         "v": LongGen(min_val=-10**6, max_val=10**6),
         "d": DoubleGen(corner_prob=0.0)}, 1000, seed=21)
    w = Window.partition_by("k")
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host, num_batches=4).with_windows(
            ps=F.sum("v").over(w), pc=F.count("v").over(w),
            pm=F.min("o").over(w), px=F.max("o").over(w),
            pa=F.avg("d").over(w)),
        session, cpu_session, approximate_float=True)


def test_two_pass_null_partition_keys(session, cpu_session):
    """Null partition keys form ONE partition (the join-back must be
    null-safe)."""
    import numpy as np
    import pandas as pd
    data = {"k": np.array([1.0, np.nan, 2.0, np.nan, 1.0, np.nan]),
            "v": np.arange(6, dtype=np.int64)}

    def build(s):
        pdf = pd.DataFrame({"k": data["k"], "v": data["v"]})
        return s.create_dataframe(pdf).with_windows(
            ps=F.sum("v").over(Window.partition_by("k")))

    got = sorted(build(session).collect(), key=repr)
    want = sorted(build(cpu_session).collect(), key=repr)
    assert got == want
    # null rows: v = 1+3+5 = 9
    nulls = [r for r in got if r[0] is None]
    assert len(nulls) == 3 and all(r[2] == 9 for r in nulls)


def test_two_pass_null_keys_multibatch(session, cpu_session):
    import numpy as np
    rng = np.random.default_rng(5)
    k = rng.integers(0, 6, 400).astype(np.float64)
    k[rng.random(400) < 0.2] = np.nan
    data = {"k": k, "v": rng.integers(-50, 50, 400)}

    def build(s):
        import pandas as pd
        return s.create_dataframe(
            pd.DataFrame(data), num_batches=3).with_windows(
            ps=F.sum("v").over(Window.partition_by("k")),
            pc=F.count("v").over(Window.partition_by("k")))

    got = sorted(build(session).collect(), key=repr)
    want = sorted(build(cpu_session).collect(), key=repr)
    assert got == want


def test_two_pass_takes_double_pass_path(session):
    host = _t(800, seed=23)
    df = session.create_dataframe(host, num_batches=3).with_windows(
        ps=F.sum("v").over(Window.partition_by("k")))
    df.collect_table()
    m = session.last_metrics()
    assert "twoPassPartitions" in m, m


def test_two_pass_with_injected_oom(cpu_session):
    from spark_rapids_tpu.session import TpuSession
    host = _t(600, seed=29)
    s = TpuSession({"spark.rapids.sql.test.injectRetryOOM": "retry:2"})
    w = Window.partition_by("k")
    got = sorted(
        s.create_dataframe(host, num_batches=3).with_windows(
            ps=F.sum("v").over(w)).collect(), key=repr)
    want = sorted(
        cpu_session.create_dataframe(host).with_windows(
            ps=F.sum("v").over(w)).collect(), key=repr)
    assert got == want


def test_bounded_frame_no_keys_concat_fallback(session, cpu_session):
    """Finite rows frame with NO partition_by and NO order_by must take
    the concat fallback, not crash in run sorting (review fix)."""
    from spark_rapids_tpu.ops.window import WindowSpec
    host = _t(300, seed=31)
    w = WindowSpec().rows_between(-2, 2)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host, num_batches=3).with_windows(
            bs=F.count("v").over(w)),
        session, cpu_session, approximate_float=True)
