"""RL-MV-EPOCH — MV/stream maintenance lives in streaming/ and must
drive cache coherence through the invalidation-epoch API only — a
direct result-cache mutation there would race the scheduler's
epoch-vector staleness checks."""

from __future__ import annotations

import ast
from typing import List

from spark_rapids_tpu.lint.diagnostics import Diagnostic, make
from spark_rapids_tpu.lint.rules.common import _attr_chain

#: the ONLY names streaming/ may import from service/result_cache — the
#: invalidation-epoch API (all re-exported from plan/fingerprint).
#: Anything else (ResultCache itself, its mutators) is a second write
#: path into cache coherence.
_MV_EPOCH_ALLOWED_IMPORTS = frozenset({
    "GLOBAL_EPOCH_KEY",
    "bump_invalidation_epoch",
    "bump_table_epoch",
    "delta_table_id",
    "epoch_snapshot",
    "epochs_current",
    "invalidation_epoch",
    "plan_table_ids",
    "register_epoch_listener",
    "table_epoch",
    "unregister_epoch_listener",
})

_MV_CACHE_MUTATORS = ("put", "clear", "pop", "evict", "invalidate")


def _check_mv_epoch(rel: str, tree: ast.AST, diags: List[Diagnostic]):
    """RL-MV-EPOCH: MV/stream maintenance lives in streaming/ and must
    drive cache coherence through the invalidation-epoch API only —
    a direct result-cache mutation there would race the scheduler's
    epoch-vector staleness checks."""
    if not rel.startswith("spark_rapids_tpu/streaming/"):
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.endswith("service.result_cache"):
            for alias in node.names:
                if alias.name not in _MV_EPOCH_ALLOWED_IMPORTS:
                    diags.append(make(
                        "RL-MV-EPOCH", f"{rel}:{node.lineno}",
                        f"import of {alias.name!r} from service/"
                        "result_cache in streaming/ — only the "
                        "invalidation-epoch API may cross this "
                        "boundary"))
        elif isinstance(node, ast.Attribute) and node.attr == "_entries":
            diags.append(make(
                "RL-MV-EPOCH", f"{rel}:{node.lineno}",
                "direct access to a result cache's _entries from "
                "streaming/ — mark staleness via bump_table_epoch, "
                "never by reaching into the cache"))
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            parts = chain.split(".")
            if len(parts) >= 2 and parts[-1] in _MV_CACHE_MUTATORS \
                    and any("result_cache" in p or p == "cache"
                            for p in parts[:-1]):
                diags.append(make(
                    "RL-MV-EPOCH", f"{rel}:{node.lineno}",
                    f"{chain}() mutates a result cache from "
                    "streaming/ — MV maintenance owns its own "
                    "tables; cache invalidation goes through the "
                    "epoch API"))
