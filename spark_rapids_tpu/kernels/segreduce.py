"""Pallas segmented reductions over two-limb 64-bit values.

Two kernels, replacing the HLO workarounds where the fused form wins:

* ``fused_minmax`` — the hi-limb-native / lo-limb-tiebreak reduction
  (ops/segsum.segment_minmax_64) in ONE two-pass program. The HLO form
  is 4+ separate passes over the input (hi scatter-reduce, a gather of
  the per-segment winner, the candidate mask, the lo scatter-reduce);
  here the input streams through twice (grid phase 0 reduces the high
  limbs into a VMEM accumulator, phase 1 re-reads each block and
  reduces the low limbs among winner ties) and the accumulators never
  leave VMEM. Segment counts are bounded by
  ``spark.rapids.tpu.kernels.segreduce.maxSegments`` (the accumulator
  and the per-block (rows x segments) compare tile are VMEM-resident).

* ``onehot_partials`` — the blocked one-hot matmul of the split-f64
  segment sum (ops/segsum.batched_segment_sum_f64's small-domain
  path). The HLO form MATERIALIZES the (blocks, block, segments)
  one-hot in HBM before the einsum; here each block's one-hot is built
  in VMEM from an iota compare and contracted immediately — the input
  is read once and nothing segment-shaped touches HBM but the partial
  sums themselves. The contraction is the same highest-precision f32
  dot the einsum lowers to, so results are bit-identical.

Reductions here are min/max (exactly associative) and the same-order
blocked f32 dot — NOT reorderings of float addition — so bit-identity
with the HLO path holds on every backend (pinned by
tests/test_kernels.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from spark_rapids_tpu.kernels import KernelIneligible, config, interpret_mode
from spark_rapids_tpu.runtime.faults import fault_point


def _pick_block(capacity: int, nseg: int, budget: int) -> int:
    """Largest row block whose (block x nseg) compare tile fits the
    budget; capacity must tile evenly (capacities are multiples of the
    128-lane minimum bucket)."""
    for blk in (1024, 512, 256, 128):
        if capacity % blk == 0 and blk * nseg * 4 * 3 <= budget:
            return blk
    if capacity < 128 and capacity * nseg * 4 * 3 <= budget:
        return capacity
    raise KernelIneligible(
        f"no block tiling for capacity {capacity} x {nseg} segments "
        "inside the VMEM budget")


def fused_minmax(is_min: bool, hi, lo, valid, gid, nseg: int,
                 hi_ident, lo_ident):
    """(per-segment hi winner, per-segment lo tiebreak) with the exact
    semantics of the two segment_min/segment_max passes in
    ops/segsum.segment_minmax_64: empty segments hold the identity."""
    fault_point("kernels.segreduce")
    cfg = config()
    if nseg > cfg.max_segments:
        raise KernelIneligible(
            f"{nseg} segments > kernels.segreduce.maxSegments "
            f"({cfg.max_segments})")
    capacity = int(hi.shape[0])
    blk = _pick_block(capacity, nseg, cfg.vmem_budget)
    nb = capacity // blk
    hi_dt, lo_dt = hi.dtype, lo.dtype

    from spark_rapids_tpu.dispatch import pallas_program
    key = ("segminmax", bool(is_min), capacity, nseg, blk,
           str(hi_dt), str(lo_dt))

    def build():
        red = jnp.minimum if is_min else jnp.maximum
        axred = jnp.min if is_min else jnp.max

        def kernel(hi_ref, lo_ref, valid_ref, gid_ref, mhi_ref, mlo_ref):
            p = pl.program_id(0)
            b = pl.program_id(1)

            @pl.when((p == 0) & (b == 0))
            def _init():
                mhi_ref[:] = jnp.full((nseg,), hi_ident, hi_dt)
                mlo_ref[:] = jnp.full((nseg,), lo_ident, lo_dt)

            g = gid_ref[:]
            onseg = g[:, None] == jax.lax.broadcasted_iota(
                jnp.int32, (blk, nseg), 1)

            @pl.when(p == 0)
            def _hi_pass():
                contrib = jnp.where(onseg & valid_ref[:][:, None],
                                    hi_ref[:][:, None],
                                    jnp.asarray(hi_ident, hi_dt))
                mhi_ref[:] = red(mhi_ref[:], axred(contrib, axis=0))

            @pl.when(p == 1)
            def _lo_pass():
                win = jnp.take(mhi_ref[:], jnp.clip(g, 0, nseg - 1))
                cand = valid_ref[:] & (hi_ref[:] == win)
                contrib = jnp.where(onseg & cand[:, None],
                                    lo_ref[:][:, None],
                                    jnp.asarray(lo_ident, lo_dt))
                mlo_ref[:] = red(mlo_ref[:], axred(contrib, axis=0))

        return pl.pallas_call(
            kernel,
            grid=(2, nb),
            in_specs=[pl.BlockSpec((blk,), lambda p, b: (b,))] * 4,
            out_specs=[pl.BlockSpec((nseg,), lambda p, b: (0,))] * 2,
            out_shape=[jax.ShapeDtypeStruct((nseg,), hi_dt),
                       jax.ShapeDtypeStruct((nseg,), lo_dt)],
            interpret=interpret_mode())

    fn = pallas_program(key, build)
    return fn(hi, lo, valid, gid)


def onehot_partials(x, gid, nseg: int, nb: int, block: int):
    """Per-(block, segment) f32 partial sums, shape (nb, nseg, c) —
    bit-compatible with ``einsum('nbc,nbg->ngc', x.reshape(nb, block,
    c), one_hot(gid.reshape(nb, block), nseg), precision='highest')``
    but with the one-hot built in VMEM per block."""
    fault_point("kernels.segreduce")
    cfg = config()
    c = int(x.shape[1])
    if (block * nseg + block * c + nseg * c) * 4 * 2 > cfg.vmem_budget:
        raise KernelIneligible("one-hot partial tile exceeds the VMEM "
                               "budget")

    from spark_rapids_tpu.dispatch import pallas_program
    key = ("onehotsum", nb, block, nseg, c, str(x.dtype))

    def build():
        def kernel(x_ref, gid_ref, out_ref):
            oh = (gid_ref[:][:, None] == jax.lax.broadcasted_iota(
                jnp.int32, (block, nseg), 1)).astype(x_ref.dtype)
            # contract the row axis: (block, nseg)^T . (block, c)
            out_ref[0] = jax.lax.dot_general(
                oh, x_ref[:], (((0,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST)

        return pl.pallas_call(
            kernel,
            grid=(nb,),
            in_specs=[pl.BlockSpec((block, c), lambda b: (b, 0)),
                      pl.BlockSpec((block,), lambda b: (b,))],
            out_specs=pl.BlockSpec((1, nseg, c), lambda b: (b, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((nb, nseg, c), x.dtype),
            interpret=interpret_mode())

    fn = pallas_program(key, build)
    return fn(x, gid)
