"""CLI: ``python -m spark_rapids_tpu.tools``.

Subcommands:

* ``profile <eventlog>`` — profiling report over a .jsonl event log (or
  a directory of them): top operators by self time, compute/transfer/
  shuffle/spill breakdown, per-exchange summary, fallback inventory,
  span attribution with the untracked remainder.
* ``compare <A> <B>`` — per-query/per-operator diff of two runs.

``--json`` emits the raw report dict for machines; exit status 2 when a
profile's span coverage falls below ``--coverage-floor`` (default 0.95)
so CI can gate on attribution quality.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools",
        description="offline profiling / qualification tools over query "
                    "event logs (spark.rapids.sql.eventLog.*)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("profile", help="profiling report over one run")
    p.add_argument("eventlog", help=".jsonl event log file or directory")
    p.add_argument("--json", action="store_true",
                   help="emit the raw report JSON")
    p.add_argument("--top", type=int, default=10,
                   help="operators to show per ranking (default 10)")
    p.add_argument("--coverage-floor", type=float, default=0.95,
                   help="minimum span attribution per query; below it "
                        "the command exits 2 (default 0.95)")

    c = sub.add_parser("compare", help="diff two runs per-query/per-op")
    c.add_argument("a", help="baseline event log file or directory")
    c.add_argument("b", help="candidate event log file or directory")
    c.add_argument("--json", action="store_true",
                   help="emit the raw comparison JSON")
    c.add_argument("--top", type=int, default=5,
                   help="op diffs to show per query (default 5)")

    args = ap.parse_args(argv)

    if args.cmd == "profile":
        from spark_rapids_tpu.tools.report import (
            build_profile,
            load_events,
            render_profile,
        )
        report = build_profile(load_events(args.eventlog), top_n=args.top,
                               coverage_floor=args.coverage_floor)
        print(json.dumps(report) if args.json else render_profile(report))
        return 2 if report["queriesBelowCoverageFloor"] else 0

    from spark_rapids_tpu.tools.compare import build_compare, render_compare
    cmp = build_compare(args.a, args.b)
    print(json.dumps(cmp) if args.json
          else render_compare(cmp, top_n=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
